"""qlint pass 7 — DF8xx: whole-program device-dataflow analysis.

The serving stack stands on one contract (ISSUE 16): every hot path is a
params-compiled tensor program whose device<->host traffic is COUNTED
(kernels.h2d / h2d_pad / d2h / d2h_many, PR 11), whose progcache keys
are shape-stable (PR 6), and whose measured device time is truth.  This
pass machine-checks that contract the way CC7xx machine-checked the
threading model — and reuses CC7xx's whole-program machinery
(`concurrency._Program`: per-module indexing, cross-module call
resolution, nested-def reachability) to taint device-array values
interprocedurally from their birth sites:

- ``kernels.h2d`` / ``h2d_pad`` / ``jax.device_put`` / ``_params_dev``
  uploads, and the replica-memoized ``_dev_upload`` idiom (devpipe);
- results of calling a program wrapper (``counted_jit`` /
  ``stacked_variant`` / an entry fetched from ``progcache.get``);
- any jax-namespace constructor (``jn.zeros`` / ``jnp.asarray`` / ...);
- functions/methods RETURNING tainted values (fixed point across the
  whole analysis batch — this is what makes the pass whole-program:
  a helper in module B that returns a device array taints its callers
  in module A only when both files are in the batch);
- instance attributes assigned tainted values anywhere in the batch
  (``self._dev_v`` in chunk/column.py, ``self._fn`` program slots).

Rules:

- **DF801** hidden host sync: ``np.asarray`` / ``.item()`` / ``float()``
  / ``bool()`` / ``.tolist()`` / ``block_until_ready`` on a
  device-tainted value inside a dispatch-hot region — any function
  reachable (whole-program) from an executor ``next``/drain loop, a
  devpipe stage, or a batching dispatch/replay leg — outside the
  sanctioned wrapper modules (ops/kernels.py owns ``d2h``/``d2h_many``
  and the two-phase scalar-sync protocol; ops/profiler.py owns the
  sampled ``block_until_ready``; utils/xferaudit.py IS the interposer).
  A hidden sync stalls the dispatch pipeline for a full link round trip
  AND escapes the transfer counters that EXPLAIN ANALYZE, the bench,
  and the tsring advisor treat as ground truth.
- **DF802** uncounted transfer: a ``jax.device_put`` or implicit-upload
  call site (``jn.asarray`` / ``jnp.array`` over host values) outside
  ops/kernels.py — the invariant PR 11 established by hand sweep.
  Route uploads through ``kernels.h2d`` / ``h2d_pad``.
- **DF803** retrace hazard: a value-derived (non-shape) Python scalar
  flowing into a ``progcache`` key — TS107 generalized from closures to
  the full key-construction dataflow.  ``bucket()`` /
  ``occupancy_bucket()`` / ``len()`` / ``stable_shape_key()`` LAUNDER
  value taint (bucketing is exactly how a data-dependent count becomes
  a shape-stable key; the two-phase ``present_keep`` protocol depends
  on it).
- **DF804** device-buffer escape: a device-tainted value stored into a
  module-level container outside the registered cache owners
  (progcache's ``_REG``, kernels' program/constant tables, batching's
  park sites, exprjit's ParamTable staging, the columnar replica memo).
  Module caches never rotate with replicas, so an escaped device buffer
  pins HBM for the process lifetime — a leak no test notices on the
  8-way virtual CPU mesh but item 1's real mesh multiplies by N chips.

The dynamic twin is ``tools/transfer_audit.py`` + ``utils/xferaudit.py``
(TINYSQL_XFER_AUDIT=1): interpose jax's transfer entry points, replay
the serve/spill/batching subsets, and fail on any observed transfer the
STATS counters cannot explain — proving the static pass and the metrics
tell the same story.

Suppressions follow the tree-wide protocol::

    np.asarray(dev)  # qlint: disable=DF801 -- why this sync is designed

Entry point: :func:`lint_device_flow` over ONE batch of sources (like
``lint_concurrency``, cross-module findings only exist in the union).
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .concurrency import _Func, _Module, _Program, _call_name, _self_attr
from .diag import Diagnostic, SourceFile, register_rules

register_rules({
    "DF801": "hidden host sync on a device value in a dispatch-hot region",
    "DF802": "device upload not routed through counted kernels.h2d/h2d_pad",
    "DF803": "value-derived (non-shape) scalar flows into a progcache key",
    "DF804": "device array stored in a module-level container outside the "
             "registered cache owners",
    "DF805": "raw shard_map construction / collective outside the "
             "dist.shard_map_fn wiring",
    "DF806": "host sync or numpy call inside a shard_map body",
    "DF807": "mesh-shape scalar flows into a progcache key outside the "
             "sanctioned launders (dist.mesh_shards/shard_bucket)",
})

# ---- taint vocabulary ------------------------------------------------------

#: calls whose RESULT is a device array (birth sites)
_DEV_BIRTH = {"h2d", "h2d_pad", "device_put", "_dev_upload", "_params_dev"}
#: calls whose RESULT is a compiled device program (calling it -> device)
_DEVFN_BIRTH = {"counted_jit", "_stackable_jit", "jit", "vmap", "pmap"}
#: calls that LAUNDER device taint back to counted host memory
_LAUNDER = {"d2h", "d2h_many", "unpack_flat", "unpack_host", "_slice_pack",
            "stats_snapshot", "stats_delta"}
#: builtins that pass their operands' taint through (zip(outs, ...) must
#: not launder a device value — the TPUProjectionExec.next find)
_PASSTHROUGH = {"zip", "enumerate", "reversed", "sorted", "list", "tuple",
                "iter", "next", "map", "filter", "min", "max"}
#: receiver names that ARE the jax namespace (tree idiom: jn = jnp())
_JAX_NS = {"jn", "jnp", "jax", "j"}
#: jax-namespace calls that return HOST metadata, not device arrays
_JAX_HOST_CALLS = {"devices", "local_devices", "device_count",
                   "local_device_count", "default_backend",
                   "process_index", "process_count", "make_jaxpr",
                   "tree_flatten", "tree_unflatten", "tree_map"}
#: instance-attribute NAMING convention: `self._dev*` slots hold device
#: arrays (chunk/column.py DeviceColumn) — taints attribute loads even
#: when the assignment flows through an untainted constructor parameter
_DEV_ATTR_PREFIX = "_dev"
#: attribute reads that stay host/shape metadata on a device value
_SHAPE_ATTRS = {"shape", "dtype", "ndim", "size", "nbytes", "sharding",
                "stack_info"}
#: host-sync method names (DF801 sinks when the receiver is tainted)
_SYNC_ATTRS = {"item", "tolist", "block_until_ready"}
#: scalar coercions (DF801 sinks when an argument is tainted)
_SYNC_COERCE = {"float", "int", "bool"}
#: calls that LAUNDER value taint into a shape-stable key component
#: (bucketing data-dependent counts is THE sanctioned retrace bound;
#: mesh_shards/shard_bucket/_shards_tag are the mesh-shape analogues)
_VAL_LAUNDER = {"bucket", "occupancy_bucket", "len", "stable_shape_key",
                "id", "type", "isinstance", "hasattr",
                "mesh_shards", "shard_bucket", "_shards_tag"}

# ---- mesh discipline (DF805/DF806/DF807, ISSUE 17) ------------------------

#: mesh collectives: legal only under a shard_map wired through
#: parallel/dist.py (shard_map_fn / shard_map_unchecked) — a raw
#: collective outside that wiring dodges the version-fallback shim AND
#: the sharded tier's counter discipline
_COLLECTIVES = {"psum", "pmin", "pmax", "all_gather", "all_to_all",
                "ppermute", "psum_scatter", "axis_index", "pbroadcast"}
#: the sanctioned construction entry points (parallel/dist.py owns them)
_SHARD_WIRING = {"shard_map_fn", "shard_map_unchecked"}
#: the one module allowed to touch jax's shard_map entry points raw
_MESH_OWNER = ("parallel.dist",)
#: host-sync / host-compute sinks inside a shard_map body (DF806): a
#: numpy call or transfer wrapper inside the traced SPMD body either
#: fails at trace time or — worse — constant-folds host-side per shard
_BODY_SINK_CALLS = {"d2h", "d2h_many", "h2d", "h2d_pad", "print", "open"}
#: calls whose RESULT is a mesh-shape scalar (DF807 births)
_MESH_BIRTHS = {"devices", "device_count", "local_device_count"}
#: calls that LAUNDER mesh-shape taint into a sanctioned key component
_MESH_LAUNDER = {"mesh_shards", "shard_bucket", "_shards_tag", "bucket"}

#: dispatch-hot roots by protocol name: executor iterators, drain loops,
#: the batching dispatch/replay legs (reachability closes over callees)
_HOT_ROOT_NAMES = {"next", "consume", "replay", "dispatch"}
_HOT_ROOT_PREFIXES = ("drain", "_drain", "_dispatch")
#: dynamic-dispatch hot seeds the call graph cannot see (receiver types
#: are erased at c.values()/take() call sites) — the late-materialization
#: methods run inside executor drain loops by construction
_HOT_SEEDS: List[Tuple[str, str]] = [
    ("chunk.column", "DeviceColumn._ensure_host"),
    ("chunk.column", "DeviceColumn.take"),
    ("chunk.column", "LazyTakeColumn._ensure_host"),
]

#: sanctioned-wrapper modules: DF801 does not fire inside them.
#: ops/kernels.py OWNS d2h/d2h_many and the two-phase protocol's designed
#: scalar syncs; ops/profiler.py owns the sampled block_until_ready;
#: utils/xferaudit.py interposes the raw entry points on purpose.
_SANCTIONED_MODULES = ("ops.kernels", "ops.profiler", "utils.xferaudit")

#: DF802 exemption: the module that IS the counted wrapper layer (plus
#: the runtime interposer, which must reach the raw entry points)
_UPLOAD_OWNERS = ("ops.kernels", "utils.xferaudit")

#: DF804 registered cache owners: progcache's _REG/catalog, kernels'
#: program & constant tables, batching's park sites, exprjit ParamTable
#: staging, the columnar replica memo
_ESCAPE_OWNERS = ("ops.progcache", "ops.kernels", "ops.batching",
                  "ops.exprjit", "columnar.store")


def _mod_endswith(modpath: str, suffixes) -> bool:
    return any(modpath.endswith(s) for s in suffixes)


# ===========================================================================
# whole-program taint state
# ===========================================================================

class _FlowState:
    """Fixed-point facts shared across the batch: which functions return
    device values / program wrappers, and which instance attributes hold
    them (collected from every ``self.x = <tainted>`` in the batch)."""

    def __init__(self, prog: _Program):
        self.prog = prog
        self.dev_returning: Set[str] = set()
        self.devfn_returning: Set[str] = set()
        self.dev_attrs: Set[str] = set()
        self.devfn_attrs: Set[str] = set()

    def solve(self) -> None:
        for _ in range(6):  # taint heights are tiny; 6 >> fixpoint depth
            changed = False
            for f in self.prog.funcs.values():
                fl = _FnFlow(self, f)
                fl.scan()
                if fl.returns_dev and f.qual not in self.dev_returning:
                    self.dev_returning.add(f.qual)
                    changed = True
                if fl.returns_devfn and f.qual not in self.devfn_returning:
                    self.devfn_returning.add(f.qual)
                    changed = True
                for a in fl.attr_dev:
                    if a not in self.dev_attrs:
                        self.dev_attrs.add(a)
                        changed = True
                for a in fl.attr_devfn:
                    if a not in self.devfn_attrs:
                        self.devfn_attrs.add(a)
                        changed = True
            if not changed:
                break


class _FnFlow:
    """One function's local taint environment.  ``scan()`` collects the
    fixed-point facts (returns / attribute assignments); ``check()``
    re-walks with the solved state and emits diagnostics."""

    def __init__(self, state: _FlowState, func: _Func):
        self.state = state
        self.func = func
        self.mod: _Module = next(m for m in state.prog.modules
                                 if m.modpath == func.mod)
        self.env: Dict[str, str] = {}      # name -> "dev" | "devfn"
        self.vals: Set[str] = set()        # value-derived local names
        self.meshv: Set[str] = set()       # mesh-shape-derived names
        self.returns_dev = False
        self.returns_devfn = False
        self.attr_dev: Set[str] = set()
        self.attr_devfn: Set[str] = set()
        self.diags: List[Diagnostic] = []
        self.checking = False

    # ---- cross-module call resolution (CC7xx's scheme) -------------------
    def _resolve(self, fn: ast.expr) -> Optional[str]:
        ref = None
        if isinstance(fn, ast.Name):
            ref = f"{self.mod.modpath}:{fn.id}"
        elif isinstance(fn, ast.Attribute):
            a = _self_attr(fn)
            if a is not None and self.func.cls is not None:
                ref = f"{self.mod.modpath}:{self.func.cls}.{a}"
            elif isinstance(fn.value, ast.Name):
                tgt = self.mod.imports.get(fn.value.id)
                if tgt:
                    ref = f"?{tgt}:{fn.attr}"
        if ref is None:
            return None
        return self.state.prog._find_qual(ref)

    def _is_numpy(self, recv: ast.expr) -> bool:
        return isinstance(recv, ast.Name) and (
            recv.id == "np"
            or self.mod.imports.get(recv.id, "").startswith("numpy"))

    def _is_jaxns(self, recv: ast.expr) -> bool:
        """Receiver is the jax / jax.numpy namespace (imported, aliased,
        or fetched through the kernels.jnp()/jax() lazy accessors)."""
        if isinstance(recv, ast.Name):
            tgt = self.mod.imports.get(recv.id, "")
            return recv.id in _JAX_NS or tgt.startswith("jax")
        if isinstance(recv, ast.Call):
            nm = _call_name(recv.func)
            return nm in ("jnp", "jax")
        return False

    def _is_progcache(self, recv: ast.expr) -> bool:
        if isinstance(recv, ast.Name):
            tgt = self.mod.imports.get(recv.id, "")
            return "progcache" in recv.id or tgt.endswith("progcache")
        if isinstance(recv, ast.Attribute):
            return "progcache" in recv.attr
        return False

    # ---- expression taint -------------------------------------------------
    def _taint(self, e: ast.expr) -> Optional[str]:
        if isinstance(e, ast.Name):
            return self.env.get(e.id)
        if isinstance(e, ast.Attribute):
            if e.attr in _SHAPE_ATTRS:
                return None
            if e.attr.startswith(_DEV_ATTR_PREFIX):
                return "dev"
            if e.attr in self.state.dev_attrs:
                return "dev"
            if e.attr in self.state.devfn_attrs:
                return "devfn"
            return self._taint(e.value)
        if isinstance(e, ast.Call):
            return self._call_taint(e)
        if isinstance(e, (ast.Tuple, ast.List, ast.Set)):
            for x in e.elts:
                t = self._taint(x)
                if t is not None:
                    return t
            return None
        if isinstance(e, ast.Starred):
            return self._taint(e.value)
        if isinstance(e, (ast.ListComp, ast.GeneratorExp, ast.SetComp)):
            return self._comp_taint(e)
        if isinstance(e, ast.Subscript):
            return self._taint(e.value)
        if isinstance(e, ast.BinOp):
            return self._taint(e.left) or self._taint(e.right)
        if isinstance(e, ast.UnaryOp):
            return self._taint(e.operand)
        if isinstance(e, ast.BoolOp):
            for x in e.values:
                t = self._taint(x)
                if t is not None:
                    return t
            return None
        if isinstance(e, ast.IfExp):
            return self._taint(e.body) or self._taint(e.orelse)
        if isinstance(e, ast.Compare):
            t = self._taint(e.left)
            if t is not None:
                return t
            for x in e.comparators:
                t = self._taint(x)
                if t is not None:
                    return t
            return None
        if isinstance(e, ast.NamedExpr):
            return self._taint(e.value)
        return None

    def _comp_taint(self, e) -> Optional[str]:
        bound: List[str] = []
        for gen in e.generators:
            if self._taint(gen.iter) == "dev":
                for nm in _target_names(gen.target):
                    if nm not in self.env:
                        self.env[nm] = "dev"
                        bound.append(nm)
        try:
            return self._taint(e.elt)
        finally:
            for nm in bound:
                del self.env[nm]

    def _call_taint(self, e: ast.Call) -> Optional[str]:
        nm = _call_name(e.func)
        if nm in _LAUNDER:
            return None
        if nm in _DEV_BIRTH:
            return "dev"
        if nm in _DEVFN_BIRTH:
            return "devfn"
        if nm in _PASSTHROUGH:
            for a in e.args:
                t = self._taint(a)
                if t is not None:
                    return t
            return None
        if isinstance(e.func, ast.Attribute):
            recv = e.func.value
            if e.func.attr in _SYNC_ATTRS:
                return None  # result is host (flagged separately if hot)
            if self._is_jaxns(recv):
                # any jax-namespace call yields a device value — except
                # the host-metadata accessors (jax.devices() etc.)
                if e.func.attr in _JAX_HOST_CALLS:
                    return None
                return "dev"
            if e.func.attr == "get" and self._is_progcache(recv):
                return "devfn"  # progcache entries are program wrappers
            if e.func.attr == "memo" and len(e.args) >= 2 \
                    and isinstance(e.args[1], ast.Lambda):
                # replica memo: rep.memo(key, lambda: kernels.h2d(...))
                return self._taint(e.args[1].body)
        # calling a program wrapper dispatches it -> device result
        if self._taint(e.func) == "devfn":
            return "dev"
        q = self._resolve(e.func)
        if q is not None:
            if q in self.state.dev_returning:
                return "dev"
            if q in self.state.devfn_returning:
                return "devfn"
            return None
        if isinstance(e.func, ast.Attribute):
            # unknown method on a device value (dev.sum(), dev.astype())
            # stays on device
            if e.func.attr not in _SYNC_ATTRS \
                    and self._taint(e.func.value) == "dev":
                return "dev"
        return None

    # ---- value-derived (non-shape) scalar taint (DF803) -------------------
    def _val(self, e: ast.expr) -> bool:
        if isinstance(e, ast.Name):
            return e.id in self.vals
        if isinstance(e, ast.Attribute):
            if e.attr in _SHAPE_ATTRS:
                return False
            if e.attr == "value":  # the Expression/Datum literal idiom
                return True
            return self._val(e.value)
        if isinstance(e, ast.Call):
            nm = _call_name(e.func)
            if nm in _VAL_LAUNDER:
                return False
            if nm in _SYNC_ATTRS:  # .item() materializes the value
                return True
            if nm in _SYNC_COERCE:
                return any(self._val(a) or self._taint(a) == "dev"
                           for a in e.args)
            return any(self._val(a) for a in e.args)
        if isinstance(e, (ast.Tuple, ast.List, ast.Set)):
            return any(self._val(x) for x in e.elts)
        if isinstance(e, ast.Starred):
            return self._val(e.value)
        if isinstance(e, ast.BinOp):
            return self._val(e.left) or self._val(e.right)
        if isinstance(e, ast.UnaryOp):
            return self._val(e.operand)
        if isinstance(e, ast.IfExp):
            return self._val(e.body) or self._val(e.orelse)
        if isinstance(e, ast.Subscript):
            return self._val(e.value)
        return False

    # ---- mesh-shape scalar taint (DF807) ----------------------------------
    def _meshval(self, e: ast.expr) -> bool:
        if isinstance(e, ast.Name):
            return e.id in self.meshv
        if isinstance(e, ast.Attribute):
            if e.attr == "devices":
                return True
            return self._meshval(e.value)
        if isinstance(e, ast.Call):
            nm = _call_name(e.func)
            if nm in _MESH_LAUNDER:
                return False
            if nm in _MESH_BIRTHS:
                return True
            return any(self._meshval(a) for a in e.args)
        if isinstance(e, (ast.Tuple, ast.List, ast.Set)):
            return any(self._meshval(x) for x in e.elts)
        if isinstance(e, ast.Starred):
            return self._meshval(e.value)
        if isinstance(e, ast.BinOp):
            return self._meshval(e.left) or self._meshval(e.right)
        if isinstance(e, ast.UnaryOp):
            return self._meshval(e.operand)
        if isinstance(e, ast.IfExp):
            return self._meshval(e.body) or self._meshval(e.orelse)
        if isinstance(e, ast.Subscript):
            return self._meshval(e.value)
        return False

    # ---- statement walk ---------------------------------------------------
    def scan(self) -> None:
        self.checking = False
        # two passes pick up loop-carried and use-before-def-order taint
        for _ in range(2):
            self._walk(self.func.node.body)

    def check(self, hot: bool) -> List[Diagnostic]:
        self.scan()  # environments are cheap; rebuild then emit
        self.checking = True
        self.hot = hot
        self._walk(self.func.node.body)
        return self.diags

    def _walk(self, stmts) -> None:
        for s in stmts:
            self._stmt(s)

    def _stmt(self, s: ast.stmt) -> None:
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef)):
            return  # nested defs are separate _Funcs in the index
        if isinstance(s, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = s.targets if isinstance(s, ast.Assign) else [s.target]
            val = getattr(s, "value", None)
            if val is not None:
                self._visit_expr(val)
                t = self._taint(val)
                v = self._val(val)
                mv = self._meshval(val)
                for tgt in targets:
                    self._bind(tgt, t, v, mv)
                    self._store_check(tgt, val, t)
            return
        if isinstance(s, ast.Return):
            if s.value is not None:
                self._visit_expr(s.value)
                t = self._taint(s.value)
                if t == "dev":
                    self.returns_dev = True
                elif t == "devfn":
                    self.returns_devfn = True
            return
        if isinstance(s, ast.For):
            self._visit_expr(s.iter)
            if self._taint(s.iter) == "dev":
                for nm in _target_names(s.target):
                    self.env[nm] = "dev"
            self._walk(s.body)
            self._walk(s.orelse)
            return
        if isinstance(s, ast.While):
            self._visit_expr(s.test)
            self._walk(s.body)
            self._walk(s.orelse)
            return
        if isinstance(s, ast.If):
            self._visit_expr(s.test)
            # isinstance(x, np.ndarray) narrowing: inside the guarded
            # body x is PROVEN host — drop its device taint there
            narrowed: Dict[str, str] = {}
            for nm in _host_narrowed_names(s.test):
                if nm in self.env:
                    narrowed[nm] = self.env.pop(nm)
            self._walk(s.body)
            self.env.update(narrowed)
            self._walk(s.orelse)
            return
        if isinstance(s, ast.With):
            for item in s.items:
                self._visit_expr(item.context_expr)
            self._walk(s.body)
            return
        if isinstance(s, ast.Try):
            for blk in ([s.body, s.orelse, s.finalbody]
                        + [h.body for h in s.handlers]):
                self._walk(blk)
            return
        if isinstance(s, ast.Expr):
            self._visit_expr(s.value)
            self._mutator_check(s.value)
            return
        for child in ast.iter_child_nodes(s):
            if isinstance(child, ast.expr):
                self._visit_expr(child)

    def _bind(self, tgt: ast.expr, t: Optional[str], val: bool,
              mesh: bool = False) -> None:
        if isinstance(tgt, ast.Name):
            if t is not None:
                self.env[tgt.id] = t
            if val:
                self.vals.add(tgt.id)
            if mesh:
                self.meshv.add(tgt.id)
            return
        a = _self_attr(tgt)
        if a is not None:
            if t == "dev":
                self.attr_dev.add(a)
            elif t == "devfn":
                self.attr_devfn.add(a)
            return
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for x in tgt.elts:
                self._bind(x, t, val, mesh)

    # ---- DF804: stores into module-level containers -----------------------
    def _container_of(self, base: ast.expr) -> Optional[Tuple[str, str]]:
        """(module, name) when ``base`` names a module-level container —
        local (``CACHE[...]``) or through a module alias
        (``mod.CACHE[...]``, resolved against the batch)."""
        if isinstance(base, ast.Name):
            if base.id in self.mod.containers:
                return (self.mod.modpath, base.id)
            return None
        if isinstance(base, ast.Attribute) \
                and isinstance(base.value, ast.Name):
            tgt = self.mod.imports.get(base.value.id)
            if tgt:
                tail = tgt.split(".")
                for m in self.state.prog.modules:
                    mp = m.modpath.split(".")
                    if mp[-len(tail):] == tail or mp[-1] == tail[-1]:
                        if base.attr in m.containers:
                            return (m.modpath, base.attr)
        return None

    def _store_check(self, tgt: ast.expr, val: ast.expr,
                     t: Optional[str]) -> None:
        if not self.checking or t != "dev":
            return
        if isinstance(tgt, ast.Subscript):
            owner = self._container_of(tgt.value)
            if owner is not None and not _mod_endswith(owner[0],
                                                       _ESCAPE_OWNERS):
                self._flag(
                    "DF804", tgt,
                    f"device array stored into module-level container "
                    f"`{owner[1]}` ({owner[0]}) — outside the registered "
                    f"cache owners (progcache/kernels/batching/exprjit/"
                    f"replica memo) nothing ever evicts it: the buffer "
                    f"pins HBM for the process lifetime")

    def _mutator_check(self, e: ast.expr) -> None:
        if not self.checking or not isinstance(e, ast.Call):
            return
        fn = e.func
        if not isinstance(fn, ast.Attribute) \
                or fn.attr not in ("append", "add", "insert", "setdefault",
                                   "update", "extend"):
            return
        owner = self._container_of(fn.value)
        if owner is None or _mod_endswith(owner[0], _ESCAPE_OWNERS):
            return
        for a in list(e.args) + [kw.value for kw in e.keywords]:
            if self._taint(a) == "dev":
                self._flag(
                    "DF804", e,
                    f"device array {fn.attr}()-ed into module-level "
                    f"container `{owner[1]}` ({owner[0]}) — outside the "
                    f"registered cache owners nothing evicts it (device-"
                    f"memory leak)")
                return

    # ---- DF801 / DF802 / DF803 sinks -------------------------------------
    def _visit_expr(self, e: ast.expr) -> None:
        if not self.checking:
            return
        for node in ast.walk(e):
            if isinstance(node, ast.Call):
                self._check_call(node)

    def _check_call(self, node: ast.Call) -> None:
        fn = node.func
        nm = _call_name(fn)
        mod = self.mod.modpath

        # DF802: raw upload entry points outside the wrapper owner
        if not _mod_endswith(mod, _UPLOAD_OWNERS):
            if nm == "device_put":
                self._flag(
                    "DF802", node,
                    "`device_put` upload outside ops/kernels.py — route "
                    "through the counted kernels.h2d/h2d_pad wrappers so "
                    "h2d_transfers/h2d_bytes (EXPLAIN ANALYZE, tsring, "
                    "the bench invariants) stay truthful")
            elif nm in ("asarray", "array") \
                    and isinstance(fn, ast.Attribute) \
                    and self._is_jaxns(fn.value):
                self._flag(
                    "DF802", node,
                    f"implicit device upload `{ast.unparse(fn)}(...)` "
                    "outside ops/kernels.py — an uncounted transfer; "
                    "route through kernels.h2d/h2d_pad")

        # DF803: value-derived scalar into a progcache key
        if nm == "get" and isinstance(fn, ast.Attribute) \
                and self._is_progcache(fn.value) and node.args:
            key = node.args[0]
            if self._val(key):
                self._flag(
                    "DF803", node,
                    "progcache key carries a value-derived (non-shape) "
                    "scalar — every distinct literal mints a new program "
                    "(unbounded retrace/compile); parameterize the value "
                    "(exprjit ParamTable) or bucket it "
                    "(kernels.bucket/occupancy_bucket) into a "
                    "shape-stable key component")
            # DF807: a raw mesh-shape scalar (device count, mesh.devices
            # size) in the key ties the program registry to the physical
            # topology instead of the laundered shard count — prewarm on
            # a different host mesh minted different keys, and a resized
            # mesh silently recompiles everything
            if self._meshval(key):
                self._flag(
                    "DF807", node,
                    "progcache key carries a raw mesh-shape scalar — "
                    "launder it through dist.mesh_shards / "
                    "dist.shard_bucket (the sanctioned bucketed shard "
                    "counts) so keys stay stable across physical device "
                    "topologies")

        # DF801: hidden host syncs in dispatch-hot regions
        if not self.hot or _mod_endswith(mod, _SANCTIONED_MODULES):
            return
        if nm in _SYNC_COERCE and node.args \
                and self._taint(node.args[0]) == "dev":
            self._flag(
                "DF801", node,
                f"`{nm}()` on a device value in a dispatch-hot region — "
                "a hidden blocking sync the transfer counters never see; "
                "use kernels.d2h (counted) or keep the value on device")
        elif isinstance(fn, ast.Attribute) and fn.attr in _SYNC_ATTRS \
                and self._taint(fn.value) == "dev":
            self._flag(
                "DF801", node,
                f"`.{fn.attr}()` on a device value in a dispatch-hot "
                "region — a hidden blocking sync outside the sanctioned "
                "d2h/d2h_many/profiler wrappers")
        elif nm in ("asarray", "array") and isinstance(fn, ast.Attribute) \
                and self._is_numpy(fn.value) and node.args \
                and self._taint(node.args[0]) == "dev":
            self._flag(
                "DF801", node,
                "`np.asarray` on a device value in a dispatch-hot region "
                "— an uncounted blocking download; use kernels.d2h / "
                "d2h_many (counted, span-attributed)")
        elif nm == "block_until_ready" and node.args \
                and self._taint(node.args[0]) == "dev":
            self._flag(
                "DF801", node,
                "`block_until_ready` in a dispatch-hot region outside "
                "the sampling profiler — stalls the dispatch pipeline")

    def _flag(self, rule: str, node, msg: str) -> None:
        self.diags.append(Diagnostic(
            rule, msg + f" (in `{self.func.qual}`)",
            self.mod.sf.path, getattr(node, "lineno", 0),
            getattr(node, "col_offset", 0)))


def _host_narrowed_names(test: ast.expr) -> List[str]:
    """Names a conditional PROVES are host numpy: conjuncts of the form
    ``isinstance(x, np.ndarray)`` (the _semi_next dtype-coercion idiom)."""
    out: List[str] = []
    for node in ast.walk(test):
        if isinstance(node, ast.Call) and _call_name(node.func) == "isinstance" \
                and len(node.args) == 2 \
                and isinstance(node.args[0], ast.Name) \
                and "ndarray" in ast.dump(node.args[1]):
            out.append(node.args[0].id)
    return out


def _target_names(tgt: ast.expr) -> List[str]:
    if isinstance(tgt, ast.Name):
        return [tgt.id]
    if isinstance(tgt, (ast.Tuple, ast.List)):
        out: List[str] = []
        for x in tgt.elts:
            out.extend(_target_names(x))
        return out
    if isinstance(tgt, ast.Starred):
        return _target_names(tgt.value)
    return []


# ===========================================================================
# hot-region computation (CC7xx reachability over the resolved call graph)
# ===========================================================================

def _hot_set(prog: _Program) -> Set[str]:
    roots: Set[str] = set()
    for f in prog.funcs.values():
        if f.name in _HOT_ROOT_NAMES \
                or f.name.startswith(_HOT_ROOT_PREFIXES):
            roots.add(f.qual)
    for msfx, name in _HOT_SEEDS:
        q = None
        for cand, f in prog.funcs.items():
            mod, fname = cand.split(":", 1)
            if fname == name and mod.endswith(msfx):
                q = cand
                break
        if q:
            roots.add(q)
    edges: Dict[str, List[str]] = {}
    for f in prog.funcs.values():
        lst = edges.setdefault(f.qual, [])
        for callee, _h, _ln in f.calls:
            if callee is not None:
                lst.append(callee)
        if f.nested_in is not None:
            # a nested def runs where its enclosing scope wires it
            edges.setdefault(f.nested_in, []).append(f.qual)
    seen = set(roots)
    stack = list(roots)
    while stack:
        cur = stack.pop()
        for nxt in edges.get(cur, ()):
            if nxt not in seen:
                seen.add(nxt)
                stack.append(nxt)
    return seen


# ===========================================================================
# mesh discipline (DF805 / DF806) — raw shard_map wiring + body hygiene
# ===========================================================================

def _mesh_discipline_diags(prog: _Program) -> List[Diagnostic]:
    out: List[Diagnostic] = []
    for m in prog.modules:
        if _mod_endswith(m.modpath, _MESH_OWNER):
            continue  # parallel/dist.py IS the wiring layer
        # DF805a: raw shard_map import — the version-fallback shim and
        # the unchecked-replication variant live in dist.py alone
        for node in ast.walk(m.sf.tree):
            if isinstance(node, ast.ImportFrom):
                modname = node.module or ""
                if "shard_map" in modname or (
                        modname.startswith("jax")
                        and any(a.name == "shard_map"
                                for a in node.names)):
                    out.append(Diagnostic(
                        "DF805",
                        "raw shard_map import outside parallel/dist.py — "
                        "construct through dist.shard_map_fn / "
                        "shard_map_unchecked (one jax-version fallback, "
                        "one replication-check policy)",
                        m.sf.path, node.lineno, node.col_offset))
            elif isinstance(node, ast.Import):
                for a in node.names:
                    if "shard_map" in a.name:
                        out.append(Diagnostic(
                            "DF805",
                            "raw shard_map import outside parallel/"
                            "dist.py — construct through "
                            "dist.shard_map_fn / shard_map_unchecked",
                            m.sf.path, node.lineno, node.col_offset))
        for f in m.funcs:
            if f.nested_in is not None:
                continue  # nested defs ride their top-level scope
            wired = any(
                isinstance(n, ast.Call)
                and _call_name(n.func) in _SHARD_WIRING
                for n in ast.walk(f.node))
            body_names: List[str] = []
            for n in ast.walk(f.node):
                if isinstance(n, ast.Call) \
                        and _call_name(n.func) in ("shard_map",
                                                   "shard_map_fn",
                                                   "shard_map_unchecked") \
                        and n.args and isinstance(n.args[0], ast.Name):
                    body_names.append(n.args[0].id)
                elif isinstance(n, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                    # @partial(shard_map, ...) decorator idiom
                    for d in n.decorator_list:
                        if isinstance(d, ast.Call) \
                                and _call_name(d.func) == "partial" \
                                and d.args \
                                and isinstance(d.args[0], ast.Name) \
                                and d.args[0].id == "shard_map":
                            body_names.append(n.name)
            # DF805b: a collective with no dist wiring in scope runs
            # outside any shard_map this pass can see — it either traces
            # into a single-device program (wrong axis) or was wired raw
            if not wired:
                for n in ast.walk(f.node):
                    if isinstance(n, ast.Call) \
                            and _call_name(n.func) in _COLLECTIVES:
                        out.append(Diagnostic(
                            "DF805",
                            f"collective `{_call_name(n.func)}` outside "
                            "any dist.shard_map_fn/shard_map_unchecked "
                            "wiring in scope — mesh programs construct "
                            f"through parallel/dist.py (in `{f.qual}`)",
                            m.sf.path, n.lineno, n.col_offset))
            # DF806: host syncs / numpy compute inside the traced body
            if not body_names:
                continue
            for n in ast.walk(f.node):
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                        and n.name in body_names:
                    out.extend(_body_sync_diags(m, f, n))
    return out


def _body_sync_diags(m: _Module, f: _Func, body) -> List[Diagnostic]:
    out: List[Diagnostic] = []

    def flag(node, msg):
        out.append(Diagnostic(
            "DF806", msg + f" (shard_map body `{body.name}` in "
            f"`{f.qual}`)", m.sf.path, node.lineno, node.col_offset))

    for n in ast.walk(body):
        if not isinstance(n, ast.Call):
            continue
        fn = n.func
        nm = _call_name(fn)
        if nm in _BODY_SINK_CALLS:
            flag(n, f"`{nm}` inside a shard_map body — the traced SPMD "
                 "program cannot host-sync; move the transfer outside "
                 "the shard_map")
        elif isinstance(fn, ast.Attribute) and fn.attr in _SYNC_ATTRS:
            flag(n, f"`.{fn.attr}()` inside a shard_map body — a host "
                 "sync under trace either fails or constant-folds "
                 "per-shard host work into the program")
        elif isinstance(fn, ast.Attribute) \
                and isinstance(fn.value, ast.Name) \
                and (fn.value.id == "np"
                     or m.imports.get(fn.value.id, "").startswith("numpy")):
            flag(n, f"numpy call `np.{fn.attr}(...)` inside a shard_map "
                 "body — host compute under trace; use the jax "
                 "namespace so the work stays in the SPMD program")
    return out


# ===========================================================================
# module-body escapes (DF804 at import time)
# ===========================================================================

def _module_body_diags(state: _FlowState, m: _Module) -> List[Diagnostic]:
    if _mod_endswith(m.modpath, _ESCAPE_OWNERS):
        return []
    shim = _Func(m.modpath, None, "<module>", ast.Module(body=[], type_ignores=[]))
    fl = _FnFlow(state, shim)
    out: List[Diagnostic] = []
    for node in m.sf.tree.body:
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        val = getattr(node, "value", None)
        if val is None or fl._taint(val) != "dev":
            continue
        out.append(Diagnostic(
            "DF804",
            "module-level binding holds a device array at import time — "
            "outside the registered cache owners nothing ever releases "
            "it (device-memory pin for the process lifetime)",
            m.sf.path, node.lineno, node.col_offset))
    return out


# ===========================================================================
# entry point
# ===========================================================================

def lint_device_flow(sources: List[SourceFile]) -> List[Diagnostic]:
    """Whole-program DF8xx over ONE batch (cross-module taint and hot
    reachability only exist in the union, exactly like CC7xx)."""
    prog = _Program(sources)
    state = _FlowState(prog)
    state.solve()
    hot = _hot_set(prog)
    diags: List[Diagnostic] = []
    for f in prog.funcs.values():
        fl = _FnFlow(state, f)
        diags.extend(fl.check(f.qual in hot))
    for m in prog.modules:
        diags.extend(_module_body_diags(state, m))
    diags.extend(_mesh_discipline_diags(prog))
    out = []
    for d in diags:
        sf = prog.by_path.get(d.path)
        if sf is not None and sf.suppressed(d.rule, d.line):
            continue
        out.append(d)
    out.sort(key=lambda d: (d.path, d.line, d.rule))
    return out


def hot_report(sources: List[SourceFile]) -> List[str]:
    """The computed dispatch-hot set (introspection / docs)."""
    return sorted(_hot_set(_Program(sources)))
