"""Shared diagnostic/reporting core for the qlint passes.

Every pass produces `Diagnostic`s; this module owns the common machinery:
source loading + AST parse, the inline-suppression protocol, rule
registration, and report formatting.  The suppression syntax is

    offending_line()  # qlint: disable=TS101 -- why this is actually fine

- the comment may sit on the flagged line or on the line directly above;
- `disable=` takes a comma-separated rule list or `all`;
- the `-- justification` text is REQUIRED: a disable without it does not
  suppress anything and instead raises its own QL001 violation, so every
  suppression in the tree documents WHY the code is correct.
"""
from __future__ import annotations

import ast
import os
import re
import tokenize
from dataclasses import dataclass, field
from io import StringIO
from typing import Dict, Iterable, List, Optional, Set, Tuple


class Severity:
    ERROR = "error"
    WARNING = "warning"


#: rule code -> one-line description; passes register on import so the CLI
#: can print the catalogue (`tools/lint.py --rules`)
RULES: Dict[str, str] = {
    "QL001": "qlint disable comment without a `-- justification` text",
}


def register_rules(rules: Dict[str, str]) -> None:
    RULES.update(rules)


@dataclass
class Diagnostic:
    rule: str
    message: str
    path: str = "<plan>"
    line: int = 0
    col: int = 0
    severity: str = Severity.ERROR

    def format(self) -> str:
        loc = f"{self.path}:{self.line}" if self.line else self.path
        return f"{loc}: {self.severity}: {self.rule} {self.message}"


_SUPPRESS_RE = re.compile(
    r"#\s*qlint:\s*disable=([A-Za-z0-9_,\s]+?)"
    r"(?:\s*--\s*(.*?))?\s*$")


@dataclass
class _Suppression:
    line: int
    rules: Set[str]
    justification: str


class SourceFile:
    """One parsed source file: text, AST, and its suppression table."""

    def __init__(self, path: str, text: Optional[str] = None):
        self.path = path
        if text is None:
            with open(path, "r", encoding="utf-8") as f:
                text = f.read()
        self.text = text
        self.tree = ast.parse(text, filename=path)
        self.suppressions: List[_Suppression] = []
        self._scan_comments()

    def _scan_comments(self) -> None:
        try:
            toks = tokenize.generate_tokens(StringIO(self.text).readline)
            for tok in toks:
                if tok.type != tokenize.COMMENT:
                    continue
                m = _SUPPRESS_RE.search(tok.string)
                if not m:
                    continue
                rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
                self.suppressions.append(_Suppression(
                    tok.start[0], rules, (m.group(2) or "").strip()))
        except tokenize.TokenError:
            pass

    def suppressed(self, rule: str, line: int) -> bool:
        """Is `rule` disabled at `line`?  Same-line or line-above comments
        apply; a justification-less disable never suppresses (QL001)."""
        for s in self.suppressions:
            if s.line in (line, line - 1) and s.justification \
                    and (rule in s.rules or "all" in s.rules):
                return True
        return False

    def check_suppression_syntax(self) -> List[Diagnostic]:
        out = []
        for s in self.suppressions:
            if not s.justification:
                out.append(Diagnostic(
                    "QL001",
                    "suppression requires a justification: "
                    "`# qlint: disable=RULE -- why this is correct`",
                    self.path, s.line))
            for r in s.rules:
                if r != "all" and r not in RULES:
                    out.append(Diagnostic(
                        "QL001", f"unknown rule {r!r} in disable comment",
                        self.path, s.line))
        return out

    def filter(self, diags: Iterable[Diagnostic]) -> List[Diagnostic]:
        return [d for d in diags if not self.suppressed(d.rule, d.line)]


def gather_sources(root: str,
                   skip_dirs: Tuple[str, ...] = ()) -> List[SourceFile]:
    """All .py files under `root` (a package dir or a single file)."""
    if os.path.isfile(root):
        return [SourceFile(root)]
    out = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in sorted(dirnames)
                       if d not in skip_dirs and not d.startswith(".")]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                out.append(SourceFile(os.path.join(dirpath, fn)))
    return out


def format_diagnostics(diags: List[Diagnostic]) -> str:
    lines = [d.format() for d in diags]
    lines.append(f"{len(diags)} violation" + ("s" if len(diags) != 1 else ""))
    return "\n".join(lines)
