"""qlint pass: failure-injection discipline (FP5xx).

Two invariants keep the resilience surface testable:

- **FP501** — no raw ``time.sleep`` in retry-path modules outside
  ``Backoffer`` (kv/backoff.py owns sleeping: it meters every wait
  against the typed budget, scales under ``SLEEP_SCALE`` so chaos tests
  run the full ladder without wall-clock, wakes on cancel events, and
  checks the statement kill flag).  A raw sleep in a retry loop is
  invisible to all four — a statement stuck in it cannot be killed and
  a chaos run cannot accelerate it.
- **FP502** — every ``failpoint.inject("name")`` / ``eval`` site must
  name a point registered in the ``tinysql_tpu/fail/points.py``
  catalogue.  The chaos suite enumerates that catalogue and proves each
  point degrades cleanly; an unregistered name is a seam no chaos test
  will ever arm.

Scope is set by tools/lint.py (``FAIL_SCOPE``): the kv/distsql/ddl
retry ladders, the device tier, and the executor layer.
"""
from __future__ import annotations

import ast
import os
from typing import List, Optional, Set

from .diag import Diagnostic, SourceFile, register_rules

register_rules({
    "FP501": "raw time.sleep in a retry path — only Backoffer may sleep "
             "(budget metering, SLEEP_SCALE, cancellation, kill checks)",
    "FP502": "failpoint name not registered in tinysql_tpu/fail/points.py "
             "— the chaos suite cannot arm it",
})

#: files that legitimately own sleeping
_SLEEP_OWNERS = ("backoff.py",)

#: module aliases whose .inject/.eval calls are failpoint sites
_FAIL_MODULES = {"failpoint", "fail", "_fail"}
_FAIL_VERBS = {"inject", "eval", "eval_point"}


def _registered_names() -> Set[str]:
    from .. import fail
    return set(fail.catalogue())


def _is_time_sleep(call: ast.Call) -> bool:
    f = call.func
    if isinstance(f, ast.Attribute) and f.attr == "sleep" \
            and isinstance(f.value, ast.Name) \
            and f.value.id in ("time", "time_mod", "_time"):
        return True
    return False


def _failpoint_name(call: ast.Call) -> Optional[str]:
    """The literal name of a failpoint call site, or None when the call
    is not one (or the name is dynamic — out of static scope)."""
    f = call.func
    if isinstance(f, ast.Attribute) and f.attr in _FAIL_VERBS \
            and isinstance(f.value, ast.Name) and f.value.id in _FAIL_MODULES:
        pass
    elif isinstance(f, ast.Name) and f.id in ("inject", "eval_point"):
        pass
    else:
        return None
    if not call.args:
        return None
    a = call.args[0]
    if isinstance(a, ast.Constant) and isinstance(a.value, str):
        return a.value
    return None


def lint_fail_discipline(sf: SourceFile) -> List[Diagnostic]:
    diags: List[Diagnostic] = []
    base = os.path.basename(sf.path)
    sleep_ok = base in _SLEEP_OWNERS
    registered = _registered_names()
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        if not sleep_ok and _is_time_sleep(node):
            diags.append(Diagnostic(
                "FP501",
                "raw time.sleep in a retry path — meter the wait through "
                "Backoffer (or arm a failpoint sleep action) so chaos "
                "tests can scale it and KILL can interrupt it",
                sf.path, node.lineno))
        name = _failpoint_name(node)
        if name is not None and name not in registered:
            diags.append(Diagnostic(
                "FP502",
                f"failpoint {name!r} is not registered in "
                "tinysql_tpu/fail/points.py — register it so the chaos "
                "suite can arm it",
                sf.path, node.lineno))
    return sf.filter(diags)
