"""Pass 1 — trace-safety lint (TS1xx).

Flags host-sync and retrace hazards inside jit-traced regions.  A traced
region is (a) a function decorated with a jit-like wrapper, (b) a
function named ``emit`` (the devpipe convention: the pure traced half of
a prepare/emit node), or (c) a function whose name is passed to a
jit-like call in the same module (``counted_jit(kernel)``,
``shard_map(kernel, ...)``, ``vmap(kernel, ...)``) — including names
reached through simple assignment aliases (``fn = kernel`` then
``vmap(fn)``, the stacked-variant builder idiom) and through a
``functools.partial`` wrapper at the call site.

Inside a traced region the pass taints the function's parameters (they
are tracers at trace time) and propagates:

- bare parameter names carry CONTAINER taint — branching on a pytree's
  truthiness (``if cols``) is host-static and fine;
- subscripts, arithmetic, comparisons, and calls over tainted values
  carry VALUE taint — these are device arrays;
- the static tracer attributes (``.shape``/``.dtype``/``.ndim``/
  ``.size``) and host-structural builtins (``len``/``zip``/...) launder
  taint: their results are host values.

Hazards:

- TS101: ``np.*`` call over a tainted value (host sync mid-trace; on a
  real tracer this either raises or silently forces a device round-trip).
- TS102: ``.item()`` / ``float()`` / ``int()`` / ``bool()`` /
  ``kernels.d2h`` over a tainted value (explicit host sync).
- TS103: ``if`` / ``while`` / ``assert`` / conditional expression whose
  test is VALUE-tainted (data-dependent Python control flow retraces or
  raises; use ``jnp.where``/masking).
- TS104: a jit wrapper created inside a function body whose result is
  neither returned (factory pattern — the caller owns caching) nor
  stored into a module-level ``*CACHE*`` table: a fresh wrapper per call
  defeats jax's dispatch cache and retraces every query.
- TS105: a ``*CACHE*`` table keyed by an expression containing a
  list/set/dict display or an ndarray constructor — unhashable (raises)
  or hash-by-identity (never hits).
- TS106: a host sync inside a PIPELINE STAGE CALLBACK (a function passed
  to ``BlockPipeline`` as its stage_fn).  The stage thread's whole job
  is to prepare the NEXT block while the device computes the current
  one; ``jax.block_until_ready``, ``kernels.d2h``, or ``np.asarray``
  over a device value parks the stage thread on the device and defeats
  the overlap.  Device UPLOADS (``jn.asarray`` over host values) are the
  point of the stage and stay legal.
- TS107: a QUERY CONSTANT baked into a device closure.  A nested
  function that evaluates over device columns (a traced region, or an
  expression closure by the engine's ``cols``-first-parameter
  convention) freely referencing a variable its enclosing builder
  derived from a ``<node>.value`` attribute (the ``Constant.value``
  idiom, tracked transitively through local assignments) closes the
  literal into the traced program: every distinct constant then
  compiles its own XLA program — the 15s-cold-start-per-literal bug
  class.  Route the constant through an ``exprjit.ParamTable`` slot
  (a runtime operand) instead; binding it as a DEFAULT PARAMETER of
  the closure (``slot=slot``) is the sanctioned slot-plumbing form
  and is not flagged.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .diag import Diagnostic, SourceFile, register_rules

register_rules({
    "TS101": "numpy call over a traced value inside a jit-traced region",
    "TS102": "host sync (.item()/float()/int()/bool()/d2h) on a traced value",
    "TS103": "Python control flow on a traced value (use jnp.where/masking)",
    "TS104": "jit wrapper built per call — cache it at module level",
    "TS105": "unhashable jit cache key (list/set/dict/ndarray in key)",
    "TS106": "host sync inside a pipeline stage callback (defeats the "
             "host-staging/device-compute overlap)",
    "TS107": "query constant baked into a device closure — route it "
             "through a ParamTable slot",
})

_JIT_CALL_NAMES = {"jit", "counted_jit", "shard_map", "pmap", "vmap"}
_HOST_SAFE_CALLS = {"len", "isinstance", "enumerate", "zip", "range",
                    "list", "tuple", "getattr", "hasattr", "type", "str",
                    "sorted", "min", "max", "repr", "id"}
_STATIC_ATTRS = {"shape", "dtype", "ndim", "size"}
_SYNC_CASTS = {"float", "int", "bool"}

_NONE = 0
_CONTAINER = 1
_VALUE = 2


def _call_name(func: ast.expr) -> Optional[str]:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _root_name(e: ast.expr) -> Optional[str]:
    while isinstance(e, (ast.Attribute, ast.Subscript, ast.Call)):
        e = e.func if isinstance(e, ast.Call) else e.value
    return e.id if isinstance(e, ast.Name) else None


def _numpy_aliases(tree: ast.Module) -> Set[str]:
    out = {"np", "numpy"}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "numpy":
                    out.add(a.asname or "numpy")
    return out


def _jitted_names(tree: ast.Module) -> Set[str]:
    """Function names passed to jit-like calls anywhere in the module —
    those defs trace when the wrapper runs.  Coverage (ISSUE 14: the
    vmap-batched kernel variants must fire like any jit region):

    - bare names (``counted_jit(kernel)``, ``vmap(kernel)``);
    - names reached through simple ASSIGNMENT ALIASES (``fn = kernel``
      then ``vmap(fn, ...)`` — the stacked-variant builder idiom of
      binding the factory-returned kernel before batching it);
    - names wrapped in ``functools.partial`` at the call site
      (``vmap(partial(kernel, ...))``).
    """
    alias: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) \
                and isinstance(node.value, ast.Name):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    alias[t.id] = node.value.id
    out: Set[str] = set()

    def add(name: str) -> None:
        seen: Set[str] = set()
        while name not in seen:
            out.add(name)
            seen.add(name)
            nxt = alias.get(name)
            if nxt is None:
                break
            name = nxt

    for node in ast.walk(tree):
        if isinstance(node, ast.Call) \
                and _call_name(node.func) in _JIT_CALL_NAMES:
            for a in list(node.args) + [k.value for k in node.keywords]:
                if isinstance(a, ast.Name):
                    add(a.id)
                elif isinstance(a, ast.Call) \
                        and _call_name(a.func) == "partial":
                    for pa in a.args:
                        if isinstance(pa, ast.Name):
                            add(pa.id)
    return out


def _is_jit_decorated(fn: ast.FunctionDef) -> bool:
    for d in fn.decorator_list:
        name = _call_name(d.func) if isinstance(d, ast.Call) else \
            (d.attr if isinstance(d, ast.Attribute)
             else d.id if isinstance(d, ast.Name) else None)
        if name in _JIT_CALL_NAMES:
            return True
        # functools.partial(jax.jit, ...) style
        if isinstance(d, ast.Call) and _call_name(d.func) == "partial":
            for a in d.args:
                if (isinstance(a, ast.Attribute) and a.attr in
                        _JIT_CALL_NAMES) or (isinstance(a, ast.Name)
                                             and a.id in _JIT_CALL_NAMES):
                    return True
    return False


class _TaintScanner(ast.NodeVisitor):
    """Hazard scan of ONE traced function body with taint propagation."""

    def __init__(self, sf: SourceFile, fn: ast.FunctionDef,
                 np_aliases: Set[str]):
        self.sf = sf
        self.fn = fn
        self.np_aliases = np_aliases
        self.taint: Dict[str, int] = {}
        for arg in (list(fn.args.posonlyargs) + list(fn.args.args)
                    + list(fn.args.kwonlyargs)
                    + ([fn.args.vararg] if fn.args.vararg else [])
                    + ([fn.args.kwarg] if fn.args.kwarg else [])):
            self.taint[arg.arg] = _CONTAINER
        self.diags: List[Diagnostic] = []

    # ---- taint algebra --------------------------------------------------
    def taint_of(self, e: ast.expr) -> int:
        if isinstance(e, ast.Name):
            return self.taint.get(e.id, _NONE)
        if isinstance(e, ast.Subscript):
            base = max(self.taint_of(e.value), self.taint_of(e.slice))
            return _VALUE if base else _NONE
        if isinstance(e, ast.Attribute):
            base = self.taint_of(e.value)
            if base and e.attr in _STATIC_ATTRS:
                return _NONE  # host-static tracer metadata
            return base
        if isinstance(e, ast.Call):
            name = _call_name(e.func)
            args = list(e.args) + [k.value for k in e.keywords]
            amax = max((self.taint_of(a) for a in args), default=_NONE)
            if name in _HOST_SAFE_CALLS:
                return _NONE
            recv = (self.taint_of(e.func.value)
                    if isinstance(e.func, ast.Attribute) else _NONE)
            return _VALUE if (amax or recv) else _NONE
        if isinstance(e, (ast.BinOp,)):
            t = max(self.taint_of(e.left), self.taint_of(e.right))
            return _VALUE if t else _NONE
        if isinstance(e, ast.UnaryOp):
            return _VALUE if self.taint_of(e.operand) else _NONE
        if isinstance(e, ast.Compare):
            t = max([self.taint_of(e.left)]
                    + [self.taint_of(c) for c in e.comparators])
            return _VALUE if t else _NONE
        if isinstance(e, ast.BoolOp):
            return max((self.taint_of(v) for v in e.values), default=_NONE)
        if isinstance(e, (ast.Tuple, ast.List)):
            return max((self.taint_of(v) for v in e.elts), default=_NONE)
        if isinstance(e, ast.IfExp):
            return max(self.taint_of(e.body), self.taint_of(e.orelse))
        if isinstance(e, ast.Starred):
            return self.taint_of(e.value)
        return _NONE

    def _mark_targets(self, tgt: ast.expr, t: int) -> None:
        if isinstance(tgt, ast.Name):
            self.taint[tgt.id] = max(self.taint.get(tgt.id, _NONE), t)
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for e in tgt.elts:
                self._mark_targets(e, t)
        elif isinstance(tgt, ast.Starred):
            self._mark_targets(tgt.value, t)

    # ---- statement walk -------------------------------------------------
    def visit_Assign(self, node: ast.Assign) -> None:
        self.generic_visit(node)
        for tgt in node.targets:
            # element-wise unpack when arities line up: `v, m, d =
            # key_vals[i], key_nulls[i], descs[i]` must not smear taint
            # from the traced operands onto the host-static one
            if isinstance(tgt, (ast.Tuple, ast.List)) \
                    and isinstance(node.value, (ast.Tuple, ast.List)) \
                    and len(tgt.elts) == len(node.value.elts) \
                    and not any(isinstance(e, ast.Starred)
                                for e in tgt.elts):
                for t_e, v_e in zip(tgt.elts, node.value.elts):
                    if self.taint_of(v_e):
                        self._mark_targets(t_e, _VALUE)
            elif self.taint_of(node.value):
                self._mark_targets(tgt, _VALUE)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self.generic_visit(node)
        if self.taint_of(node.value):
            self._mark_targets(node.target, _VALUE)

    def _check_test(self, test: ast.expr, node: ast.AST,
                    kind: str) -> None:
        if self.taint_of(test) >= _VALUE:
            self.diags.append(Diagnostic(
                "TS103",
                f"{kind} over a traced value inside "
                f"`{self.fn.name}` — data-dependent Python control flow "
                f"forces a host sync / retrace (use jnp.where or masks)",
                self.sf.path, node.lineno, node.col_offset))

    def visit_If(self, node: ast.If) -> None:
        self._check_test(node.test, node, "`if` branch")
        self.generic_visit(node)

    def visit_While(self, node: ast.While) -> None:
        self._check_test(node.test, node, "`while` loop")
        self.generic_visit(node)

    def visit_Assert(self, node: ast.Assert) -> None:
        self._check_test(node.test, node, "`assert`")
        self.generic_visit(node)

    def visit_IfExp(self, node: ast.IfExp) -> None:
        self._check_test(node.test, node, "conditional expression")
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        self.generic_visit(node)
        name = _call_name(node.func)
        args = list(node.args) + [k.value for k in node.keywords]
        tainted = any(self.taint_of(a) for a in args)
        root = _root_name(node.func) if isinstance(node.func,
                                                   ast.Attribute) else None
        if root in self.np_aliases and tainted:
            self.diags.append(Diagnostic(
                "TS101",
                f"numpy call `{ast.unparse(node.func)}` over a traced "
                f"value inside `{self.fn.name}` — host sync mid-trace "
                f"(use the jnp equivalent)",
                self.sf.path, node.lineno, node.col_offset))
        if name == "item" and isinstance(node.func, ast.Attribute) \
                and self.taint_of(node.func.value):
            self.diags.append(Diagnostic(
                "TS102",
                f".item() on a traced value inside `{self.fn.name}` — "
                "explicit device->host sync",
                self.sf.path, node.lineno, node.col_offset))
        if isinstance(node.func, ast.Name) and name in _SYNC_CASTS \
                and any(self.taint_of(a) >= _VALUE for a in node.args):
            self.diags.append(Diagnostic(
                "TS102",
                f"{name}() scalar coercion of a traced value inside "
                f"`{self.fn.name}` — explicit device->host sync",
                self.sf.path, node.lineno, node.col_offset))
        if name == "d2h" and tainted:
            self.diags.append(Diagnostic(
                "TS102",
                f"kernels.d2h on a traced value inside `{self.fn.name}` "
                "— the packed download belongs OUTSIDE the program",
                self.sf.path, node.lineno, node.col_offset))


# ---- TS106: pipeline stage callbacks ------------------------------------

_PIPELINE_CTORS = {"BlockPipeline"}
# kernels.h2d / h2d_pad are the COUNTED upload wrappers (ISSUE 11 h2d
# accounting) — device-producing exactly like a bare jn.asarray
_DEV_UPLOAD_CALLS = {"asarray", "array", "device_put", "h2d", "h2d_pad"}
_DEV_UPLOAD_ROOTS = {"jn", "jnp", "kernels"}


def _stage_fn_names(tree: ast.Module) -> Set[str]:
    """Function names passed to a BlockPipeline construction as its stage
    callback (first positional argument or ``stage_fn=`` keyword)."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and _call_name(node.func) in _PIPELINE_CTORS):
            continue
        cands = list(node.args[:1]) + [k.value for k in node.keywords
                                       if k.arg == "stage_fn"]
        for a in cands:
            if isinstance(a, ast.Name):
                out.add(a.id)
    return out


class _StageScanner(ast.NodeVisitor):
    """TS106 scan of ONE stage callback.  Device-PRODUCING calls
    (``jn.asarray``/``jnp.asarray``/``device_put``/``_dev_upload``) taint
    the names they assign; a host sync — ``block_until_ready`` or
    ``kernels.d2h`` anywhere, ``np.asarray``/``np.array`` or a
    ``float()``/``int()`` coercion over a device-tainted value — parks
    the stage thread on the device mid-pipeline."""

    def __init__(self, sf: SourceFile, fn: ast.FunctionDef,
                 np_aliases: Set[str]):
        self.sf = sf
        self.fn = fn
        self.np_aliases = np_aliases
        self.dev: Set[str] = set()
        self.diags: List[Diagnostic] = []

    def _devval(self, e: ast.expr) -> bool:
        if isinstance(e, ast.Name):
            return e.id in self.dev
        if isinstance(e, ast.Call):
            name = _call_name(e.func)
            root = _root_name(e.func)
            if name in _DEV_UPLOAD_CALLS and root in _DEV_UPLOAD_ROOTS:
                return True
            if name == "_dev_upload":
                return True
            args = list(e.args) + [k.value for k in e.keywords]
            return any(self._devval(a) for a in args)
        if isinstance(e, (ast.Tuple, ast.List)):
            return any(self._devval(v) for v in e.elts)
        if isinstance(e, ast.Subscript):
            return self._devval(e.value)
        if isinstance(e, ast.Attribute):
            return self._devval(e.value)
        if isinstance(e, ast.BinOp):
            return self._devval(e.left) or self._devval(e.right)
        if isinstance(e, ast.IfExp):
            return self._devval(e.body) or self._devval(e.orelse)
        if isinstance(e, ast.Starred):
            return self._devval(e.value)
        return False

    def _mark(self, tgt: ast.expr) -> None:
        if isinstance(tgt, ast.Name):
            self.dev.add(tgt.id)
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for e in tgt.elts:
                self._mark(e)
        elif isinstance(tgt, ast.Starred):
            self._mark(tgt.value)

    def visit_Assign(self, node: ast.Assign) -> None:
        self.generic_visit(node)
        if self._devval(node.value):
            for tgt in node.targets:
                self._mark(tgt)

    def _flag(self, node: ast.AST, what: str) -> None:
        self.diags.append(Diagnostic(
            "TS106",
            f"{what} inside pipeline stage callback `{self.fn.name}` — "
            "the stage thread must only PREPARE the next block "
            "(host syncs mid-pipeline serialize staging behind the "
            "device and defeat the overlap)",
            self.sf.path, node.lineno, node.col_offset))

    def visit_Call(self, node: ast.Call) -> None:
        self.generic_visit(node)
        name = _call_name(node.func)
        args = list(node.args) + [k.value for k in node.keywords]
        root = _root_name(node.func) if isinstance(node.func,
                                                   ast.Attribute) else None
        if name == "block_until_ready":
            self._flag(node, "`block_until_ready` host sync")
        elif name == "d2h":
            self._flag(node, "`kernels.d2h` download")
        elif root in self.np_aliases and name in ("asarray", "array") \
                and any(self._devval(a) for a in args):
            self._flag(node, f"`np.{name}` over a device value")
        elif isinstance(node.func, ast.Name) and name in _SYNC_CASTS \
                and any(self._devval(a) for a in node.args):
            self._flag(node, f"`{name}()` scalar coercion of a device "
                             "value")


def _lint_stage_callbacks(sf: SourceFile,
                          np_aliases: Set[str]) -> List[Diagnostic]:
    names = _stage_fn_names(sf.tree)
    if not names:
        return []
    out: List[Diagnostic] = []
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.FunctionDef) and node.name in names:
            scanner = _StageScanner(sf, node, np_aliases)
            for stmt in node.body:
                scanner.visit(stmt)
            out.extend(scanner.diags)
    return out


def _returned_by(fn: ast.FunctionDef, name: str) -> bool:
    """Does `fn` return `name` (bare or wrapped in a call, e.g.
    ``return counted_jit(step)``)?  The factory pattern: the caller owns
    caching the wrapper, so building it here is not a per-call retrace."""
    for node in ast.walk(fn):
        if isinstance(node, ast.Return) and node.value is not None:
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Name) and sub.id == name:
                    return True
    return False


def _cache_target(stmt: ast.stmt) -> bool:
    """Does `stmt` store into a module-level *CACHE* table?"""
    if not isinstance(stmt, ast.Assign):
        return False
    for tgt in stmt.targets:
        for sub in ast.walk(tgt):
            if isinstance(sub, ast.Subscript):
                root = _root_name(sub.value)
                if root and "cache" in root.lower():
                    return True
                if isinstance(sub.value, ast.Attribute) \
                        and "cache" in sub.value.attr.lower():
                    return True
    return False


def _lint_retrace(sf: SourceFile) -> List[Diagnostic]:
    """TS104: jit wrappers built per call without a module-level cache."""
    out: List[Diagnostic] = []
    parent: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(sf.tree):
        for child in ast.iter_child_nodes(node):
            parent[child] = node

    def enclosing_stmt_chain(n: ast.AST):
        chain = []
        while n in parent:
            n = parent[n]
            chain.append(n)
        return chain

    for node in ast.walk(sf.tree):
        if not (isinstance(node, ast.Call)
                and _call_name(node.func) in {"jit", "counted_jit"}):
            # @jit-decorated def nested inside a function body
            if isinstance(node, ast.FunctionDef) and _is_jit_decorated(node):
                chain = enclosing_stmt_chain(node)
                encl = next((c for c in chain
                             if isinstance(c, (ast.FunctionDef,
                                               ast.AsyncFunctionDef))),
                            None)
                if encl is not None and not _returned_by(encl, node.name):
                    out.append(Diagnostic(
                        "TS104",
                        f"`@jit` def `{node.name}` inside a function "
                        "body compiles a fresh program per call — hoist "
                        "behind a module-level cache keyed by structure",
                        sf.path, node.lineno, node.col_offset))
            continue
        chain = enclosing_stmt_chain(node)
        in_function = any(isinstance(c, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))
                          for c in chain)
        if not in_function:
            continue  # module-level wrapper: compiled once at import
        ok = False
        for c in chain:
            if isinstance(c, ast.Return):
                ok = True  # factory pattern: the caller owns caching
                break
            if isinstance(c, (ast.FunctionDef, ast.AsyncFunctionDef)):
                break
            if isinstance(c, ast.stmt) and _cache_target(c):
                ok = True
                break
        if not ok:
            out.append(Diagnostic(
                "TS104",
                f"`{ast.unparse(node.func)}(...)` result is neither "
                "returned nor stored in a module-level *CACHE* table — "
                "a fresh jit wrapper per call retraces every query",
                sf.path, node.lineno, node.col_offset))
    return out


def _key_unhashable(key: ast.expr) -> bool:
    for sub in ast.walk(key):
        if isinstance(sub, (ast.List, ast.Set, ast.Dict, ast.ListComp,
                            ast.SetComp, ast.DictComp)):
            return True
        if isinstance(sub, ast.Call):
            name = _call_name(sub.func)
            root = _root_name(sub.func)
            if name in {"array", "asarray"} and root in {"np", "numpy",
                                                         "jnp", "jn"}:
                return True
    return False


def _lint_cache_keys(sf: SourceFile) -> List[Diagnostic]:
    """TS105: unhashable keys into *CACHE* tables."""
    out: List[Diagnostic] = []
    for node in ast.walk(sf.tree):
        key = None
        where = None
        if isinstance(node, ast.Subscript):
            root = _root_name(node.value)
            attr = (node.value.attr if isinstance(node.value, ast.Attribute)
                    else "")
            if (root and "cache" in root.lower()) \
                    or "cache" in attr.lower():
                key, where = node.slice, node
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in {"get", "setdefault"} and node.args:
            root = _root_name(node.func.value)
            if root and "cache" in root.lower():
                key, where = node.args[0], node
        if key is not None and _key_unhashable(key):
            out.append(Diagnostic(
                "TS105",
                "jit cache key contains a list/set/dict/ndarray — "
                "unhashable (or identity-hashed, so it never hits); "
                "use tuples of scalars",
                sf.path, where.lineno, where.col_offset))
    return out


# ---- TS107: query constants baked into device closures --------------------

def _value_derived_names(fn: ast.FunctionDef) -> Dict[str, int]:
    """Names assigned (directly or transitively through local
    assignments) from an expression containing a ``<node>.value``
    attribute read — the ``Constant.value`` extraction idiom — mapped to
    the lineno of the seeding assignment.  Nested function bodies are
    excluded (their assignments are their own scope)."""
    nested: Set[ast.AST] = set()
    for node in ast.walk(fn):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)) and node is not fn:
            for sub in ast.walk(node):
                nested.add(sub)

    def has_value_attr(e: ast.expr, derived: Dict[str, int]) -> bool:
        for sub in ast.walk(e):
            if isinstance(sub, ast.Attribute) and sub.attr == "value":
                return True
            if isinstance(sub, ast.Name) and sub.id in derived:
                return True
        return False

    out: Dict[str, int] = {}
    changed = True
    while changed:  # transitive: cval = wrap_i64(int(val)) follows val
        changed = False
        for node in ast.walk(fn):
            if node in nested or not isinstance(node, ast.Assign):
                continue
            if not has_value_attr(node.value, out):
                continue
            for tgt in node.targets:
                for sub in ast.walk(tgt):
                    if isinstance(sub, ast.Name) and sub.id not in out:
                        out[sub.id] = node.lineno
                        changed = True
    return out


def _closure_bound_names(fn: ast.FunctionDef) -> Set[str]:
    """Names bound INSIDE `fn`: parameters (incl. the `slot=slot`
    default-capture idiom) and local assignment/loop targets."""
    bound: Set[str] = set()
    a = fn.args
    for arg in (list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)
                + ([a.vararg] if a.vararg else [])
                + ([a.kwarg] if a.kwarg else [])):
        bound.add(arg.arg)
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                for sub in ast.walk(tgt):
                    if isinstance(sub, ast.Name):
                        bound.add(sub.id)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign, ast.For)):
            tgt = node.target
            for sub in ast.walk(tgt):
                if isinstance(sub, ast.Name):
                    bound.add(sub.id)
        elif isinstance(node, (ast.comprehension,)):
            for sub in ast.walk(node.target):
                if isinstance(sub, ast.Name):
                    bound.add(sub.id)
    return bound


def _lint_baked_literals(sf: SourceFile,
                         jitted: Set[str]) -> List[Diagnostic]:
    """TS107: device closures freely capturing a value-derived constant.
    A closure qualifies when it is a traced region (jit-passed /
    decorated / ``emit``) or follows the engine's expression-closure
    convention (first parameter named ``cols``)."""
    # map each FunctionDef to its IMMEDIATELY enclosing FunctionDef (the
    # scope whose assignments its free names resolve against first)
    encl_of: Dict[ast.FunctionDef, ast.FunctionDef] = {}

    def walk_scope(owner, node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.FunctionDef):
                if owner is not None:
                    encl_of[child] = owner
                walk_scope(child, child)
            else:
                walk_scope(owner, child)

    walk_scope(None, sf.tree)
    out: List[Diagnostic] = []
    derived_memo: Dict[ast.FunctionDef, Dict[str, int]] = {}
    for inner, encl in encl_of.items():
        args = inner.args.posonlyargs + inner.args.args
        is_device_closure = (
            inner.name == "emit" or inner.name in jitted
            or _is_jit_decorated(inner)
            or (bool(args) and args[0].arg == "cols"))
        if not is_device_closure:
            continue
        if encl not in derived_memo:
            derived_memo[encl] = _value_derived_names(encl)
        derived = derived_memo[encl]
        if not derived:
            continue
        bound = _closure_bound_names(inner)
        flagged: Set[str] = set()
        for sub in ast.walk(inner):
            if not (isinstance(sub, ast.Name)
                    and isinstance(sub.ctx, ast.Load)):
                continue
            name = sub.id
            if name in bound or name in flagged or name not in derived:
                continue
            flagged.add(name)
            out.append(Diagnostic(
                "TS107",
                f"`{name}` (derived from a `.value` constant at "
                f"line {derived[name]}) is baked into device "
                f"closure `{inner.name}` — every distinct literal "
                "compiles its own XLA program; route it through an "
                "exprjit.ParamTable slot (runtime operand) instead",
                sf.path, sub.lineno, sub.col_offset))
    return out


def lint_trace_safety(sf: SourceFile) -> List[Diagnostic]:
    np_aliases = _numpy_aliases(sf.tree)
    jitted = _jitted_names(sf.tree)
    diags: List[Diagnostic] = []
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.FunctionDef):
            continue
        traced = (node.name == "emit" or node.name in jitted
                  or _is_jit_decorated(node))
        if not traced:
            continue
        scanner = _TaintScanner(sf, node, np_aliases)
        for stmt in node.body:
            scanner.visit(stmt)
        diags.extend(scanner.diags)
    diags.extend(_lint_retrace(sf))
    diags.extend(_lint_cache_keys(sf))
    diags.extend(_lint_stage_callbacks(sf, np_aliases))
    diags.extend(_lint_baked_literals(sf, jitted))
    return sf.filter(diags)
