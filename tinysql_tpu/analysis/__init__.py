"""qlint: the static-analysis subsystem.

Three passes over the invariants nothing else checks mechanically:

- **trace-safety** (`trace_safety.py`, TS1xx): AST lint flagging host-sync
  and retrace hazards inside jit-traced regions — np calls / `.item()` /
  scalar coercion on traced values, Python branches on tracers, per-call
  `jax.jit` wrappers that defeat the dispatch cache, unhashable jit-cache
  keys.  A host sync inside a fused program costs a whole extra dispatch
  (~40-70ms on the device link, PROFILE.md §1), which is exactly the bug
  class "Premature Dimensional Collapse" (PAPERS.md) says silently
  destroys tensor-backend wins.
- **plan-device** (`plan_device.py`, PD2xx): walks PHYSICAL plans after
  placement and verifies the device enforcer's invariants (planner/
  device.py admissibility, CPU-fallback edge shape, EXPLAIN annotation
  consistency).  Runs offline over the SQL corpus in tests/ and as an
  opt-in runtime verifier inside the optimizer (`tidb_qlint_verify`).
- **lock-discipline** (`lock_discipline.py`, LD3xx): infers per-class
  lock-to-field guard maps for the threaded subsystems and flags
  shared-state mutations outside declared lock scopes.
- **obs-discipline** (`obs_discipline.py`, OB4xx): flags direct
  ``STATS[...]`` writes outside the owning device-layer modules — only
  the ``kernels.stats_add``/``stats_hwm`` accessors fan increments out
  to per-query observability scopes (obs/context.py).
- **fail-discipline** (`fail_discipline.py`, FP5xx): retry paths may
  only sleep through ``Backoffer`` (FP501), and every failpoint inject
  site must name a point registered in the ``fail/points.py`` catalogue
  (FP502) so the chaos suite can arm it.
- **concurrency** (`concurrency.py`, CC7xx): the WHOLE-PROGRAM pass —
  thread-root discovery + cross-module reachability, shared-state race
  detection with unified guard inference (CC701, subsuming LD3xx's
  per-class maps), lock-order deadlock cycles (CC702),
  blocking-under-lock (CC703), and context-hop discipline for thread
  spawns (CC704).  Its dynamic twin is ``tools/race_stress.py``.
- **device-flow** (`device_flow.py`, DF8xx): the second WHOLE-PROGRAM
  pass — interprocedural device-array taint from the counted-wrapper
  birth sites, enforcing hidden-host-sync (DF801), uncounted-transfer
  (DF802), progcache-key retrace-hazard (DF803), and device-buffer-
  escape (DF804) discipline over the dispatch-hot reachability set.
  Its dynamic twin is ``tools/transfer_audit.py`` (utils/xferaudit.py
  interposes jax's transfer entry points and reconciles observed
  transfers against the kernels.STATS counters).

Every pass honors inline suppressions with REQUIRED justification text:

    something_hazardous()  # qlint: disable=TS101 -- post-download host copy

See docs/LINT.md and tools/lint.py.
"""
from .concurrency import lint_concurrency, thread_roots
from .device_flow import lint_device_flow
from .diag import (Diagnostic, Severity, SourceFile, format_diagnostics,
                   gather_sources)
from .fail_discipline import lint_fail_discipline
from .lock_discipline import lint_lock_discipline
from .obs_discipline import lint_obs_discipline
from .plan_device import PlanDeviceError, check_plan, verify_plan
from .trace_safety import lint_trace_safety

__all__ = [
    "Diagnostic", "Severity", "SourceFile", "format_diagnostics",
    "gather_sources", "lint_trace_safety", "lint_lock_discipline",
    "lint_obs_discipline", "lint_fail_discipline", "lint_concurrency",
    "lint_device_flow", "thread_roots", "check_plan", "verify_plan",
    "PlanDeviceError",
]
