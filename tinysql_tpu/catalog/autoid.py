"""Batched auto-increment allocator.

Capability parity with reference meta/autoid/autoid.go: allocates handle/
auto-increment IDs in steps (one meta txn reserves a batch; subsequent
allocs are in-memory until the batch drains), with Rebase on explicit
user-supplied ids (autoid.go:122-214).
"""
from __future__ import annotations

import threading
from typing import Callable, Optional

DEFAULT_STEP = 4000  # reference: autoid.go step


class Allocator:
    def __init__(self, storage, table_id: int, step: int = DEFAULT_STEP):
        self.storage = storage
        self.table_id = table_id
        self.step = step
        self._base = 0
        self._end = 0
        self._mu = threading.Lock()

    def _reserve(self, at_least: int = 0) -> None:
        from .meta import Meta
        from ..kv.errors import KVError
        # concurrent allocators race on the same meta key; retry the small
        # reservation txn on conflict (reference: autoid.go retries via
        # kv.RunInNewTxn)
        last_err = None
        for _ in range(10):
            txn = self.storage.begin()
            m = Meta(txn)
            if at_least:
                m.rebase_autoid(self.table_id, at_least)
            end = m.advance_autoid(self.table_id, self.step)
            try:
                txn.commit()
            except KVError as e:
                last_err = e
                continue
            self._base = end - self.step
            self._end = end
            return
        raise last_err

    def alloc(self) -> int:
        with self._mu:
            if self._base >= self._end:
                self._reserve()
            self._base += 1
            return self._base

    def rebase(self, v: int) -> None:
        """Ensure future allocs are > v (user wrote an explicit id)."""
        with self._mu:
            if v < self._base:
                return
            if v < self._end:
                self._base = max(self._base, v)
                return
            self._reserve(at_least=v)

    def base(self) -> int:
        with self._mu:
            return self._base
