"""Row-level table abstraction over KV.

Capability parity with reference table/table.go:126 (Table iface:
AddRecord/RemoveRecord/Row/Allocator), table/tables/tables.go (row encode +
per-index maintenance on the write path) and table/tables/index.go:103,194
(index kv create/delete/seek).  Schema-state gating implements the F1
online-DDL write rules: WRITE_ONLY columns/indices are maintained but not
readable; DELETE_ONLY indices only see deletes.
"""
from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Tuple

from ..codec import keycodec, rowcodec, tablecodec
from ..kv.errors import KeyExists, KeyNotFound
from ..mytypes import Datum, cast_datum, FLAG_PRI_KEY
from .autoid import Allocator
from .model import ColumnInfo, IndexInfo, SchemaState, TableInfo


class DuplicateKeyError(Exception):
    def __init__(self, table: str, index: str, values):
        super().__init__(f"Duplicate entry {values!r} for key '{table}.{index}'")
        self.index = index
        self.values = values


class Index:
    """One index's KV encoding (reference: tables/index.go)."""

    def __init__(self, table: "Table", info: IndexInfo):
        self.table = table
        self.info = info

    def _index_values(self, row: List[Datum]) -> List[Datum]:
        vals = []
        for ic in self.info.columns:
            v = row[ic.offset]
            if ic.length >= 0 and isinstance(v, str):
                v = v[:ic.length]
            vals.append(v)
        return vals

    def _unsigned_flags(self) -> List[bool]:
        """Unsigned columns must encode with the UINT key flag or values
        >= 2^63 sort before 0 in the index (and range seeks miss)."""
        out = []
        for ic in self.info.columns:
            ci = self.table.info.find_column(ic.name)
            out.append(bool(ci is not None and ci.ft.is_unsigned))
        return out

    def key(self, row: List[Datum], handle: int) -> Tuple[bytes, bytes]:
        """Returns (key, value).  Unique index: handle in value (unless NULLs
        present); non-unique: handle in key (reference: index.go:103)."""
        vals = self._index_values(row)
        has_null = any(v is None for v in vals)
        tid = self.table.info.id
        uns = self._unsigned_flags()
        if self.info.unique and not has_null:
            k = tablecodec.encode_index_key(tid, self.info.id, vals,
                                            unsigned_flags=uns)
            return k, b"%d" % handle
        k = tablecodec.encode_index_key(tid, self.info.id, vals,
                                        handle=handle, unsigned_flags=uns)
        return k, b"0"

    def create(self, txn, row: List[Datum], handle: int) -> None:
        k, v = self.key(row, handle)
        vals = self._index_values(row)
        if self.info.unique and not any(x is None for x in vals):
            txn.insert(k, v, dup_err=DuplicateKeyError(
                self.table.info.name, self.info.name, vals))
        else:
            txn.set(k, v)

    def delete(self, txn, row: List[Datum], handle: int) -> None:
        k, _ = self.key(row, handle)
        txn.delete(k)

    def exists_conflict(self, txn, row: List[Datum]) -> Optional[int]:
        """Pre-check for REPLACE/dup detection: returns conflicting handle
        (reference: executor/batch_checker.go)."""
        if not self.info.unique:
            return None
        vals = self._index_values(row)
        if any(v is None for v in vals):
            return None
        k = tablecodec.encode_index_key(self.table.info.id, self.info.id,
                                        vals,
                                        unsigned_flags=self._unsigned_flags())
        try:
            return int(txn.get(k))
        except KeyNotFound:
            return None


class Table:
    """reference: table/tables/tables.go tableCommon."""

    def __init__(self, info: TableInfo, allocator: Optional[Allocator] = None):
        self.info = info
        self.allocator = allocator
        self.indices = [Index(self, ii) for ii in info.indices]

    # ---- handle / autoid ------------------------------------------------
    def _alloc_handle(self, txn) -> int:
        assert self.allocator is not None, "table has no allocator bound"
        return self.allocator.alloc()

    def handle_for_row(self, txn, row: List[Datum]) -> int:
        pk = self.info.get_pk_handle_col()
        if pk is not None and row[pk.offset] is not None:
            h = int(row[pk.offset])
            if self.allocator is not None:
                self.allocator.rebase(h)
            return h
        return self._alloc_handle(txn)

    # ---- write path -----------------------------------------------------
    def add_record(self, txn, row: List[Datum],
                   handle: Optional[int] = None) -> int:
        """Insert one row: encode row value, write record key, maintain every
        writable index (reference: tables.go AddRecord)."""
        # `row` is indexed by column offset over ALL of info.columns; values
        # at non-writable offsets are ignored.  Cast writable cells in place
        # (no compaction — offsets must stay valid for index encoding).
        row = list(row)
        for c in self.info.writable_columns():
            if row[c.offset] is not None:
                row[c.offset] = cast_datum(row[c.offset], c.ft)
            else:
                row[c.offset] = None
        if handle is None:
            handle = self.handle_for_row(txn, row)
        rec_key = tablecodec.encode_row_key(self.info.id, handle)
        pk = self.info.get_pk_handle_col()
        if pk is not None:
            # pk-as-handle: uniqueness enforced on the record key itself
            txn.insert(rec_key, self._encode_row(row, handle),
                       dup_err=DuplicateKeyError(self.info.name, "PRIMARY", [handle]))
        else:
            txn.set(rec_key, self._encode_row(row, handle))
        for idx in self.indices:
            if idx.info.state >= SchemaState.WRITE_ONLY:
                idx.create(txn, row, handle)
        self._bump(txn, +1)
        return handle

    def remove_record(self, txn, handle: int, row: List[Datum]) -> None:
        txn.delete(tablecodec.encode_row_key(self.info.id, handle))
        for idx in self.indices:
            if idx.info.state >= SchemaState.DELETE_ONLY:
                idx.delete(txn, row, handle)
        self._bump(txn, -1)

    def _bump(self, txn, d: int) -> None:
        """Net row-count delta, applied to live stats at commit."""
        sd = getattr(txn, "stats_delta", None)
        if sd is not None:
            sd[self.info.id] = sd.get(self.info.id, 0) + d

    def update_record(self, txn, handle: int, old_row: List[Datum],
                      new_row: List[Datum]) -> None:
        """Used by DDL reorg and REPLACE (reference: tables.go UpdateRecord)."""
        self.remove_record(txn, handle, old_row)
        self.add_record(txn, new_row, handle)

    def _encode_row(self, row: List[Datum], handle: int) -> bytes:
        vals: Dict[int, Datum] = {}
        pk = self.info.get_pk_handle_col()
        for c in self.info.writable_columns():
            if pk is not None and c.id == pk.id:
                continue  # pk-as-handle lives in the key, not the value
            vals[c.id] = row[c.offset]
        return rowcodec.encode_row(vals)

    # ---- read path ------------------------------------------------------
    def decode_row(self, value: bytes, handle: int,
                   cols: Optional[List[ColumnInfo]] = None) -> List[Datum]:
        cols = cols if cols is not None else self.info.public_columns()
        pk = self.info.get_pk_handle_col()
        out = rowcodec.decode_row_to_datums(
            value, [c.id for c in cols], [c.ft for c in cols],
            defaults=[c.default for c in cols])
        if pk is not None:
            for i, c in enumerate(cols):
                if c.id == pk.id:
                    out[i] = handle
        return out

    def row(self, txn, handle: int,
            cols: Optional[List[ColumnInfo]] = None) -> List[Datum]:
        v = txn.get(tablecodec.encode_row_key(self.info.id, handle))
        return self.decode_row(v, handle, cols)

    def iter_records(self, txn, start_handle: Optional[int] = None,
                     cols: Optional[List[ColumnInfo]] = None
                     ) -> Iterator[Tuple[int, List[Datum]]]:
        """Scan records in handle order, decoding only `cols` (column
        pruning reaches all the way to the decode loop)."""
        lo, hi = tablecodec.record_range(self.info.id)
        if start_handle is not None:
            lo = tablecodec.encode_row_key(self.info.id, start_handle)
        for k, v in txn.iter_range(lo, hi):
            _, handle = tablecodec.decode_record_key(k)
            yield handle, self.decode_row(v, handle, cols)
