"""Schema catalog: model, meta storage, autoid, table abstraction
(reference: parser/model, meta/, table/)."""
from .model import (SchemaState, JobState, ActionType, ColumnInfo,
                    IndexColumn, IndexInfo, TableInfo, DBInfo, Job)
from .meta import Meta
from .autoid import Allocator
from .table import Table, Index, DuplicateKeyError

__all__ = [
    "SchemaState", "JobState", "ActionType", "ColumnInfo", "IndexColumn",
    "IndexInfo", "TableInfo", "DBInfo", "Job", "Meta", "Allocator",
    "Table", "Index", "DuplicateKeyError",
]
