"""Schema metadata model.

Capability parity with reference parser/model/model.go: DBInfo / TableInfo /
ColumnInfo / IndexInfo and the F1 online-schema-change state enum
StateNone→DeleteOnly→WriteOnly→WriteReorganization→Public (model.go:32-44),
plus the DDL Job model (parser/model/ddl.go).  Everything JSON round-trips
because it is persisted in the KV meta layer.
"""
from __future__ import annotations

import enum
import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ..mytypes import (FieldType, Datum, TYPE_LONGLONG)


class SchemaState(enum.IntEnum):
    """F1 schema states (reference: model.go:32-44)."""
    NONE = 0
    DELETE_ONLY = 1
    WRITE_ONLY = 2
    WRITE_REORG = 3
    PUBLIC = 4


class JobState(enum.IntEnum):
    """DDL job states (reference: parser/model/ddl.go JobState)."""
    NONE = 0
    RUNNING = 1
    ROLLINGBACK = 2
    ROLLBACK_DONE = 3
    DONE = 4
    CANCELLED = 5
    SYNCED = 6


class ActionType(enum.IntEnum):
    """reference: parser/model/ddl.go ActionType (tinysql subset)."""
    CREATE_SCHEMA = 1
    DROP_SCHEMA = 2
    CREATE_TABLE = 3
    DROP_TABLE = 4
    ADD_COLUMN = 5
    DROP_COLUMN = 6
    ADD_INDEX = 7
    DROP_INDEX = 8
    TRUNCATE_TABLE = 11


@dataclass
class ColumnInfo:
    id: int
    name: str
    offset: int
    ft: FieldType
    default: Optional[Datum] = None
    state: SchemaState = SchemaState.PUBLIC

    def to_dict(self) -> dict:
        return {"id": self.id, "name": self.name, "offset": self.offset,
                "tp": self.ft.tp, "flag": self.ft.flag, "flen": self.ft.flen,
                "default": self.default, "state": int(self.state)}

    @classmethod
    def from_dict(cls, d: dict) -> "ColumnInfo":
        return cls(d["id"], d["name"], d["offset"],
                   FieldType(d["tp"], d["flag"], d["flen"]),
                   d.get("default"), SchemaState(d["state"]))


@dataclass
class IndexColumn:
    name: str
    offset: int
    length: int = -1  # prefix length; -1 = whole column


@dataclass
class IndexInfo:
    id: int
    name: str
    columns: List[IndexColumn]
    unique: bool = False
    primary: bool = False
    state: SchemaState = SchemaState.PUBLIC

    def to_dict(self) -> dict:
        return {"id": self.id, "name": self.name,
                "columns": [[c.name, c.offset, c.length] for c in self.columns],
                "unique": self.unique, "primary": self.primary,
                "state": int(self.state)}

    @classmethod
    def from_dict(cls, d: dict) -> "IndexInfo":
        return cls(d["id"], d["name"],
                   [IndexColumn(*c) for c in d["columns"]],
                   d["unique"], d["primary"], SchemaState(d["state"]))


@dataclass
class TableInfo:
    id: int
    name: str
    columns: List[ColumnInfo] = field(default_factory=list)
    indices: List[IndexInfo] = field(default_factory=list)
    pk_is_handle: bool = False   # int PK stored as the row handle
    max_column_id: int = 0
    max_index_id: int = 0
    state: SchemaState = SchemaState.PUBLIC
    update_ts: int = 0

    def get_pk_handle_col(self) -> Optional[ColumnInfo]:
        if not self.pk_is_handle:
            return None
        from ..mytypes import FLAG_PRI_KEY
        for c in self.columns:
            if c.ft.flag & FLAG_PRI_KEY:
                return c
        return None

    def find_column(self, name: str) -> Optional[ColumnInfo]:
        lname = name.lower()
        for c in self.columns:
            if c.name.lower() == lname:
                return c
        return None

    def find_index(self, name: str) -> Optional[IndexInfo]:
        lname = name.lower()
        for i in self.indices:
            if i.name.lower() == lname:
                return i
        return None

    def public_columns(self) -> List[ColumnInfo]:
        return [c for c in self.columns if c.state == SchemaState.PUBLIC]

    def writable_columns(self) -> List[ColumnInfo]:
        return [c for c in self.columns if c.state >= SchemaState.WRITE_ONLY]

    def public_indices(self) -> List[IndexInfo]:
        return [i for i in self.indices if i.state == SchemaState.PUBLIC]

    def writable_indices(self) -> List[IndexInfo]:
        return [i for i in self.indices if i.state >= SchemaState.WRITE_ONLY]

    def deletable_indices(self) -> List[IndexInfo]:
        return [i for i in self.indices if i.state >= SchemaState.DELETE_ONLY]

    def to_dict(self) -> dict:
        return {"id": self.id, "name": self.name,
                "columns": [c.to_dict() for c in self.columns],
                "indices": [i.to_dict() for i in self.indices],
                "pk_is_handle": self.pk_is_handle,
                "max_column_id": self.max_column_id,
                "max_index_id": self.max_index_id,
                "state": int(self.state), "update_ts": self.update_ts}

    @classmethod
    def from_dict(cls, d: dict) -> "TableInfo":
        return cls(d["id"], d["name"],
                   [ColumnInfo.from_dict(c) for c in d["columns"]],
                   [IndexInfo.from_dict(i) for i in d["indices"]],
                   d["pk_is_handle"], d["max_column_id"], d["max_index_id"],
                   SchemaState(d["state"]), d.get("update_ts", 0))

    def clone(self) -> "TableInfo":
        return TableInfo.from_dict(self.to_dict())


@dataclass
class DBInfo:
    id: int
    name: str
    state: SchemaState = SchemaState.PUBLIC

    def to_dict(self) -> dict:
        return {"id": self.id, "name": self.name, "state": int(self.state)}

    @classmethod
    def from_dict(cls, d: dict) -> "DBInfo":
        return cls(d["id"], d["name"], SchemaState(d["state"]))


@dataclass
class Job:
    """Async DDL job (reference: parser/model/ddl.go Job)."""
    id: int
    tp: ActionType
    schema_id: int
    table_id: int
    args: List[Any] = field(default_factory=list)
    state: JobState = JobState.NONE
    schema_state: SchemaState = SchemaState.NONE
    schema_version: int = 0
    error: Optional[str] = None
    snapshot_ver: int = 0      # reorg progress snapshot
    reorg_handle: int = 0      # reorg backfill checkpoint (reference: ddl/reorg.go)
    row_count: int = 0

    def to_json(self) -> str:
        return json.dumps({
            "id": self.id, "tp": int(self.tp), "schema_id": self.schema_id,
            "table_id": self.table_id, "args": self.args,
            "state": int(self.state), "schema_state": int(self.schema_state),
            "schema_version": self.schema_version, "error": self.error,
            "snapshot_ver": self.snapshot_ver,
            "reorg_handle": self.reorg_handle, "row_count": self.row_count})

    @classmethod
    def from_json(cls, s: str) -> "Job":
        d = json.loads(s)
        return cls(d["id"], ActionType(d["tp"]), d["schema_id"], d["table_id"],
                   d["args"], JobState(d["state"]), SchemaState(d["schema_state"]),
                   d["schema_version"], d.get("error"),
                   d.get("snapshot_ver", 0), d.get("reorg_handle", 0),
                   d.get("row_count", 0))

    def is_finished(self) -> bool:
        return self.state in (JobState.DONE, JobState.SYNCED,
                              JobState.CANCELLED, JobState.ROLLBACK_DONE)
