"""Virtual INFORMATION_SCHEMA mem-tables (reference: infoschema/tables.go —
schema-backed tables computed on read, no storage).

Supported: SCHEMATA, TABLES, COLUMNS, STATISTICS (index metadata).
Rows are produced from the live InfoSchema at query time.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

from ..mytypes import FieldType, new_int_type, new_string_type

DB_NAME = "information_schema"

# table name -> (column name, field type factory)
_TABLES = {
    "schemata": [("catalog_name", new_string_type),
                 ("schema_name", new_string_type)],
    "tables": [("table_schema", new_string_type),
               ("table_name", new_string_type),
               ("tidb_table_id", new_int_type)],
    "columns": [("table_schema", new_string_type),
                ("table_name", new_string_type),
                ("column_name", new_string_type),
                ("ordinal_position", new_int_type),
                ("data_type", new_string_type),
                ("is_nullable", new_string_type),
                ("column_key", new_string_type)],
    "statistics": [("table_schema", new_string_type),
                   ("table_name", new_string_type),
                   ("non_unique", new_int_type),
                   ("index_name", new_string_type),
                   ("seq_in_index", new_int_type),
                   ("column_name", new_string_type)],
}


def is_memtable(db: str, table: str) -> bool:
    return db.lower() == DB_NAME and table.lower() in _TABLES


def memtable_columns(table: str) -> List[Tuple[str, FieldType]]:
    return [(n, f()) for n, f in _TABLES[table.lower()]]


def memtable_rows(infoschema, table: str) -> List[list]:
    t = table.lower()
    out: List[list] = []
    if t == "schemata":
        for db in infoschema.all_schemas():
            out.append(["def", db.name])
        return out
    for db in infoschema.all_schemas():
        for ti in infoschema.schema_tables(db.name):
            if t == "tables":
                out.append([db.name, ti.name, ti.id])
            elif t == "columns":
                for i, c in enumerate(ti.public_columns()):
                    key = "PRI" if (c.ft.flag & 0x2) else ""
                    out.append([db.name, ti.name, c.name, i + 1,
                                _type_name(c.ft),
                                "NO" if c.ft.not_null else "YES", key])
            elif t == "statistics":
                for idx in ti.public_indices():
                    for seq, ic in enumerate(idx.columns):
                        out.append([db.name, ti.name,
                                    0 if idx.unique else 1,
                                    idx.name, seq + 1, ic.name])
    return out


def _type_name(ft: FieldType) -> str:
    et = ft.eval_type.name
    if et == "INT":
        return "bigint unsigned" if ft.is_unsigned else "bigint"
    if et == "REAL":
        return "double"
    return "varchar"
