"""Virtual INFORMATION_SCHEMA mem-tables (reference: infoschema/tables.go —
schema-backed tables computed on read, no storage).

Supported: SCHEMATA, TABLES, COLUMNS, STATISTICS (index metadata) plus
the observability tables the volcano executor can scan, join, and
filter like any other source:

- ``statements_summary``: the windowed per-(sql digest, plan digest)
  aggregation store (obs/stmtsummary.py);
- ``processlist``: live sessions from the interruption registry
  (utils/interrupt.py) joined with their MemTracker bytes and elapsed
  statement time;
- ``slow_query``: the structured slow-log ring (obs/slowlog.py);
- ``metrics_history`` / ``metrics_summary``: the time-series metrics
  ring (obs/tsring.py) — raw samples, and windowed delta/rate/avg/max
  per metric ("what changed in the last N minutes");
- ``inspection_result``: the automated inspection engine's findings
  (obs/inspect.py), evaluated over the ring at scan time;
- ``compiled_programs``: the per-program catalog (ops/progcache.py) —
  dispatch counts, compile walls, measured device time, cost-analysis
  flops/bytes, joinable with ``statements_summary`` on plan_digest;
- ``continuous_profiling``: the continuous host profiler's windowed
  folded stacks (obs/conprof.py) — per (window, thread role, stack)
  sample counts and estimated cpu_ms;
- ``memory_usage``: the memory reconciliation ledger (obs/memprof.py)
  — tracked MemTracker bytes vs measured heap/RSS vs the HBM census
  with per-owner attribution and the unattributed leak bucket;
- ``flight_incarnations``: the flight recorder's run catalogue
  (obs/flight.py) — one row per process incarnation with boundaries
  and the clean-vs-torn shutdown verdict.  The history-shaped tables
  (``statements_summary_history``, ``metrics_history``,
  ``continuous_profiling``, ``inspection_result``) carry an
  ``incarnation`` column: prior runs replay read-only from the
  durable flight store, the current run is the highest id.

Rows are produced from the live InfoSchema / obs stores at query time.
The catalog lists ITSELF: ``information_schema`` appears in SCHEMATA,
and every mem-table (id -1 = virtual) in TABLES/COLUMNS, so tooling
that introspects the catalog sees the whole surface.
"""
from __future__ import annotations

import time
from typing import List, Tuple

from ..mytypes import (FieldType, new_int_type, new_real_type,
                       new_string_type)

DB_NAME = "information_schema"

_KIND = {"int": new_int_type, "str": new_string_type,
         "real": new_real_type}


def _summary_cols():
    from ..obs.stmtsummary import COLUMNS
    return [(name, kind) for name, kind in COLUMNS]


# The cross-incarnation surfaces (ISSUE 20): the history-shaped
# mem-tables gain an ``incarnation`` column — the current run is the
# highest id, prior runs replay read-only from the flight store
# (obs/flight.py).  Current-window tables (statements_summary,
# metrics_summary) stay incarnation-free: they are by definition live.

def _summary_history_cols():
    return _summary_cols() + [("incarnation", "int")]


def _metrics_history_cols():
    from ..obs.tsring import HISTORY_COLUMNS
    return list(HISTORY_COLUMNS) + [("incarnation", "int")]


def _metrics_summary_cols():
    from ..obs.tsring import SUMMARY_COLUMNS
    return list(SUMMARY_COLUMNS)


def _inspection_cols():
    from ..obs.inspect import COLUMNS
    return list(COLUMNS) + [("incarnation", "int")]


def _programs_cols():
    from ..ops.progcache import CATALOG_COLUMNS
    return list(CATALOG_COLUMNS)


def _conprof_cols():
    from ..obs.conprof import COLUMNS
    return list(COLUMNS) + [("incarnation", "int")]


def _flight_incarnation_cols():
    from ..obs.flight import INCARNATION_COLUMNS
    return list(INCARNATION_COLUMNS)


def _memory_usage_cols():
    from ..obs.memprof import MEMORY_USAGE_COLUMNS
    return list(MEMORY_USAGE_COLUMNS)


# table name -> [(column name, kind)];  statements_summary's layout is
# owned by obs/stmtsummary.COLUMNS (one definition for store + catalog)
_TABLES = {
    "schemata": [("catalog_name", "str"),
                 ("schema_name", "str")],
    "tables": [("table_schema", "str"),
               ("table_name", "str"),
               ("tidb_table_id", "int")],
    "columns": [("table_schema", "str"),
                ("table_name", "str"),
                ("column_name", "str"),
                ("ordinal_position", "int"),
                ("data_type", "str"),
                ("is_nullable", "str"),
                ("column_key", "str")],
    "statistics": [("table_schema", "str"),
                   ("table_name", "str"),
                   ("non_unique", "int"),
                   ("index_name", "str"),
                   ("seq_in_index", "int"),
                   ("column_name", "str")],
    "statements_summary": _summary_cols,
    "statements_summary_history": _summary_history_cols,
    "metrics_history": _metrics_history_cols,
    "metrics_summary": _metrics_summary_cols,
    "inspection_result": _inspection_cols,
    "compiled_programs": _programs_cols,
    "continuous_profiling": _conprof_cols,
    "flight_incarnations": _flight_incarnation_cols,
    "memory_usage": _memory_usage_cols,
    "processlist": [("id", "int"),
                    ("user", "str"),
                    ("db", "str"),
                    ("command", "str"),
                    ("time_ms", "int"),
                    ("state", "str"),
                    ("mem_bytes", "int"),
                    ("info", "str"),
                    ("plan_digest", "str")],
    "slow_query": [("time", "str"),
                   ("conn_id", "int"),
                   ("db", "str"),
                   ("success", "int"),
                   ("total_ms", "real"),
                   ("parse_ms", "real"),
                   ("plan_ms", "real"),
                   ("exec_ms", "real"),
                   ("queue_wait_ms", "real"),
                   ("batch_wait_ms", "real"),
                   ("plan_digest", "str"),
                   ("sql_digest", "str"),
                   ("query", "str")],
}


def _columns_of(table: str) -> List[Tuple[str, str]]:
    spec = _TABLES[table]
    return spec() if callable(spec) else spec


def is_memtable(db: str, table: str) -> bool:
    return db.lower() == DB_NAME and table.lower() in _TABLES


def memtable_columns(table: str) -> List[Tuple[str, FieldType]]:
    return [(n, _KIND[k]()) for n, k in _columns_of(table.lower())]


def memtable_rows(infoschema, table: str) -> List[list]:
    t = table.lower()
    if t == "statements_summary":
        from ..obs import stmtsummary
        return stmtsummary.rows()
    if t == "statements_summary_history":
        from ..obs import stmtsummary
        return _with_incarnations("summary", stmtsummary.history_rows())
    if t == "processlist":
        return _processlist_rows()
    if t == "slow_query":
        return _slow_query_rows()
    if t == "metrics_history":
        from ..obs import tsring
        return _with_incarnations("metrics", tsring.history_rows())
    if t == "metrics_summary":
        from ..obs import tsring
        return tsring.summary_rows()
    if t == "inspection_result":
        from ..obs import inspect as obs_inspect
        return _with_incarnations("findings", obs_inspect.rows())
    if t == "flight_incarnations":
        from ..obs import flight
        return flight.incarnation_rows()
    if t == "compiled_programs":
        # the per-program catalog (ops/progcache.py): dispatch counts,
        # compile walls, measured device time, cost-analysis flops/bytes
        # — joinable against statements_summary on plan_digest
        from ..ops import progcache
        return progcache.catalog_rows()
    if t == "continuous_profiling":
        # the continuous host profiler's windowed folded stacks
        # (obs/conprof.py): role, stack, samples, estimated cpu_ms —
        # the SQL face of /debug/conprof
        from ..obs import conprof
        return _with_incarnations("conprof", conprof.rows())
    if t == "memory_usage":
        # the memory reconciliation ledger (obs/memprof.py): tracked vs
        # measured vs HBM census — the SQL face of /debug/heap's truth
        from ..obs import memprof
        return memprof.memory_usage_rows()
    out: List[list] = []
    if t == "schemata":
        out.append(["def", DB_NAME])
        for db in infoschema.all_schemas():
            out.append(["def", db.name])
        return out
    for db in infoschema.all_schemas():
        for ti in infoschema.schema_tables(db.name):
            if t == "tables":
                out.append([db.name, ti.name, ti.id])
            elif t == "columns":
                for i, c in enumerate(ti.public_columns()):
                    key = "PRI" if (c.ft.flag & 0x2) else ""
                    out.append([db.name, ti.name, c.name, i + 1,
                                _type_name(c.ft),
                                "NO" if c.ft.not_null else "YES", key])
            elif t == "statistics":
                for idx in ti.public_indices():
                    for seq, ic in enumerate(idx.columns):
                        out.append([db.name, ti.name,
                                    0 if idx.unique else 1,
                                    idx.name, seq + 1, ic.name])
    # the catalog's own virtual tables (id -1: no storage behind them)
    if t == "tables":
        for name in sorted(_TABLES):
            out.append([DB_NAME, name, -1])
    elif t == "columns":
        for name in sorted(_TABLES):
            for i, (cn, ft) in enumerate(memtable_columns(name)):
                out.append([DB_NAME, name, cn, i + 1, _type_name(ft),
                            "YES", ""])
    return out


def _with_incarnations(tier: str, live_rows: List[list]) -> List[list]:
    """Cross-incarnation splice (obs/flight.py): prior runs' replayed
    rows (ascending incarnation) followed by the live rows, every row
    tagged with its incarnation id in a trailing column.  Volatile
    (no flight store armed) degrades to live rows + current id — the
    column exists either way so queries need no arming awareness."""
    from ..obs import flight
    out: List[list] = []
    for inc, rows in flight.prior_tier_rows(tier):
        out.extend(r + [inc] for r in rows)
    cur = flight.current_incarnation()
    out.extend(r + [cur] for r in live_rows)
    return out


def _processlist_rows() -> List[list]:
    """Live sessions (reference: infoschema PROCESSLIST fed from the
    server's ShowProcessList): one row per registered session; running
    statements carry their SQL, elapsed wall, and the statement
    MemTracker's live byte count.

    TIME semantics by state (documented contract, tested in
    tests/test_tsring.py): ``state='executing'`` reports elapsed wall
    since the statement began executing; ``state='queued'`` reports the
    statement's WAIT-SO-FAR in the admission queue (since pool submit) —
    not elapsed-since-statement-start, because a queued statement has
    not started.  Once a queued statement is claimed by a worker its row
    flips to 'executing' and TIME restarts from execution start; the
    full wait it accumulated is attributed separately as
    ``queue_wait_s`` (statements_summary / slow_query / span trace)."""
    from ..utils import interrupt
    now = time.time()
    out: List[list] = []
    for cid, sess in interrupt.sessions():
        running = bool(getattr(sess, "stmt_running", False))
        queued = not running and \
            getattr(sess, "stmt_state", "") == "queued"
        qobs = getattr(sess, "last_query_stats", None)
        elapsed_ms = 0
        mem = 0
        info = ""
        digest = ""
        if running and qobs is not None:
            elapsed_ms = int((now - qobs.started_at) * 1e3)
            info = qobs.sql[:512]
            digest = qobs.plan_digest
            mt = getattr(sess, "_stmt_mem", None)
            if mt is not None:
                mem = mt.consumed
        elif queued:
            # waiting in the statement pool's admission queue
            # (server/pool.py): no worker yet, so no obs scope / memory
            # — but the statement and its wait are live, KILLable state
            info = getattr(sess, "pending_sql", "")[:512]
            elapsed_ms = int((now - getattr(sess, "queue_ts", now)) * 1e3)
        out.append([cid, getattr(sess, "user", "") or "",
                    getattr(sess, "current_db", ""),
                    "Query" if running or queued else "Sleep", elapsed_ms,
                    "executing" if running
                    else ("queued" if queued else ""), mem, info, digest])
    out.sort(key=lambda r: r[0])
    return out


def _slow_query_rows() -> List[list]:
    from ..obs import slowlog
    out: List[list] = []
    for r in slowlog.recent():
        out.append([r.get("time", ""), int(r.get("conn_id", 0) or 0),
                    r.get("db", ""),
                    1 if r.get("success", True) else 0,
                    float(r.get("total_ms", 0.0)),
                    float(r.get("parse_ms", 0.0)),
                    float(r.get("plan_ms", 0.0)),
                    float(r.get("exec_ms", 0.0)),
                    float(r.get("queue_wait_ms", 0.0)),
                    float(r.get("batch_wait_ms", 0.0)),
                    r.get("plan_digest", "") or "",
                    r.get("sql_digest", "") or "",
                    r.get("sql", "")])
    return out


def _type_name(ft: FieldType) -> str:
    et = ft.eval_type.name
    if et == "INT":
        return "bigint unsigned" if ft.is_unsigned else "bigint"
    if et == "REAL":
        return "double"
    return "varchar"
