"""Immutable schema snapshot.

Capability parity with reference infoschema/ (InfoSchema iface
infoschema.go:58-70, builder applying diffs): a versioned, immutable view of
all DBs/tables, rebuilt from meta on schema-version change.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from .meta import Meta
from .model import DBInfo, TableInfo


class SchemaError(Exception):
    pass


class TableNotExist(SchemaError):
    def __init__(self, db, name):
        super().__init__(f"Table '{db}.{name}' doesn't exist")


class DatabaseNotExist(SchemaError):
    def __init__(self, name):
        super().__init__(f"Unknown database '{name}'")


class InfoSchema:
    def __init__(self, version: int, dbs: List[DBInfo],
                 tables: Dict[int, List[TableInfo]]):
        self.version = version
        self._dbs = {d.name.lower(): d for d in dbs}
        self._tables: Dict[Tuple[str, str], TableInfo] = {}
        self._by_id: Dict[int, Tuple[str, TableInfo]] = {}
        for d in dbs:
            for t in tables.get(d.id, []):
                self._tables[(d.name.lower(), t.name.lower())] = t
                self._by_id[t.id] = (d.name, t)

    # full loads with at least this many databases fetch per-db table
    # lists concurrently (reference domain.go:155-207 splitForConcurrentFetch)
    CONCURRENT_FETCH_MIN_DBS = 8

    @classmethod
    def load(cls, storage) -> "InfoSchema":
        """Full load (reference: domain.go:66-207 full load path).  Large
        catalogs split the databases across a worker pool, each worker
        reading through its own snapshot; a schema-version re-check
        guards against a DDL landing between snapshots (one consistent
        single-snapshot retry otherwise)."""
        def one_snapshot():
            txn = storage.begin()
            try:
                m = Meta(txn)
                version = m.schema_version()
                dbs = m.list_databases()
                tables = {d.id: m.list_tables(d.id) for d in dbs}
            finally:
                txn.rollback()
            return cls(version, dbs, tables)

        for _ in range(3):
            txn = storage.begin()
            m = Meta(txn)
            version = m.schema_version()
            dbs = m.list_databases()
            if len(dbs) < cls.CONCURRENT_FETCH_MIN_DBS:
                # small catalog (the common case): finish in THIS snapshot
                tables = {d.id: m.list_tables(d.id) for d in dbs}
                txn.rollback()
                return cls(version, dbs, tables)
            txn.rollback()
            from concurrent.futures import ThreadPoolExecutor

            def fetch(chunk):
                t2 = storage.begin()
                try:
                    m2 = Meta(t2)
                    return {d.id: m2.list_tables(d.id) for d in chunk}
                finally:
                    t2.rollback()
            nw = min(8, len(dbs))
            tables = {}
            with ThreadPoolExecutor(max_workers=nw,
                                    thread_name_prefix="kv-schema") as ex:
                for part in ex.map(fetch,
                                   [dbs[i::nw] for i in range(nw)]):
                    tables.update(part)
            txn = storage.begin()
            v2 = Meta(txn).schema_version()
            txn.rollback()
            if v2 == version:
                return cls(version, dbs, tables)
        # version moved 3 times under the concurrent fetch (DDL storm):
        # give up on parallelism, one consistent snapshot
        return one_snapshot()

    def schema_by_name(self, name: str) -> Optional[DBInfo]:
        return self._dbs.get(name.lower())

    def schema_exists(self, name: str) -> bool:
        return name.lower() in self._dbs

    def table_by_name(self, db: str, table: str) -> TableInfo:
        t = self._tables.get((db.lower(), table.lower()))
        if t is None:
            if not self.schema_exists(db):
                raise DatabaseNotExist(db)
            raise TableNotExist(db, table)
        return t

    def table_exists(self, db: str, table: str) -> bool:
        return (db.lower(), table.lower()) in self._tables

    def table_by_id(self, tid: int) -> Optional[TableInfo]:
        hit = self._by_id.get(tid)
        return hit[1] if hit else None

    def all_schemas(self) -> List[DBInfo]:
        return list(self._dbs.values())

    def schema_tables(self, db: str) -> List[TableInfo]:
        d = self._dbs.get(db.lower())
        if d is None:
            raise DatabaseNotExist(db)
        return [t for (dbn, _), t in self._tables.items()
                if dbn == db.lower()]
