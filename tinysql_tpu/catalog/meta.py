"""Catalog persisted in KV under the `m` prefix.

Capability parity with reference meta/meta.go:79-471 (+ structure/*.go
encodings): DBInfo/TableInfo CRUD, global ID and schema-version counters,
DDL job queues (general queue, history).  Keys sort *outside* the table data
keyspace (`m` < `t`), so meta scans never collide with row scans.
"""
from __future__ import annotations

import json
import struct
from typing import List, Optional

from ..kv.errors import KeyNotFound
from .model import DBInfo, Job, TableInfo

M_PREFIX = b"m"
_DB_PREFIX = b"m:db:"              # m:db:{db_id:08d} -> DBInfo
_TABLE_PREFIX = b"m:tbl:"          # m:tbl:{db_id:08d}:{tid:08d} -> TableInfo
_GLOBAL_ID_KEY = b"m:next_gid"
_SCHEMA_VER_KEY = b"m:schema_ver"
_AUTOID_PREFIX = b"m:autoid:"      # m:autoid:{tid:08d} -> int
_JOB_QUEUE_KEY = b"m:ddl_jobq"     # json list of job jsons (small, teaching-scale)
_JOB_HISTORY_PREFIX = b"m:ddl_hist:"  # m:ddl_hist:{job_id:016d} -> job json
_BOOTSTRAP_KEY = b"m:bootstrapped"


def _db_key(db_id: int) -> bytes:
    return _DB_PREFIX + b"%08d" % db_id


def _table_key(db_id: int, tid: int) -> bytes:
    return _TABLE_PREFIX + b"%08d:%08d" % (db_id, tid)


class Meta:
    """Catalog accessor bound to one KV transaction (reference: meta.Meta)."""

    def __init__(self, txn):
        self.txn = txn

    # ---- counters -------------------------------------------------------
    def _get_int(self, key: bytes, default: int = 0) -> int:
        try:
            return int(self.txn.get(key))
        except KeyNotFound:
            return default

    def _set_int(self, key: bytes, v: int) -> None:
        self.txn.set(key, b"%d" % v)

    def gen_global_id(self) -> int:
        v = self._get_int(_GLOBAL_ID_KEY) + 1
        self._set_int(_GLOBAL_ID_KEY, v)
        return v

    def schema_version(self) -> int:
        return self._get_int(_SCHEMA_VER_KEY)

    def bump_schema_version(self) -> int:
        v = self._get_int(_SCHEMA_VER_KEY) + 1
        self._set_int(_SCHEMA_VER_KEY, v)
        return v

    # ---- autoid ---------------------------------------------------------
    def autoid(self, tid: int) -> int:
        return self._get_int(_AUTOID_PREFIX + b"%08d" % tid)

    def advance_autoid(self, tid: int, step: int) -> int:
        """Reserve [cur+1, cur+step]; returns new high-water mark
        (reference: meta/autoid batched Alloc)."""
        v = self.autoid(tid) + step
        self._set_int(_AUTOID_PREFIX + b"%08d" % tid, v)
        return v

    def rebase_autoid(self, tid: int, at_least: int) -> None:
        if self.autoid(tid) < at_least:
            self._set_int(_AUTOID_PREFIX + b"%08d" % tid, at_least)

    # ---- databases ------------------------------------------------------
    def create_database(self, db: DBInfo) -> None:
        self.txn.insert(_db_key(db.id), json.dumps(db.to_dict()).encode())

    def update_database(self, db: DBInfo) -> None:
        self.txn.set(_db_key(db.id), json.dumps(db.to_dict()).encode())

    def drop_database(self, db_id: int) -> None:
        self.txn.delete(_db_key(db_id))
        for t in self.list_tables(db_id):
            self.txn.delete(_table_key(db_id, t.id))

    def get_database(self, db_id: int) -> Optional[DBInfo]:
        try:
            return DBInfo.from_dict(json.loads(self.txn.get(_db_key(db_id))))
        except KeyNotFound:
            return None

    def list_databases(self) -> List[DBInfo]:
        out = []
        for _, v in self.txn.iter_range(_DB_PREFIX, _DB_PREFIX + b"\xff"):
            out.append(DBInfo.from_dict(json.loads(v)))
        return out

    # ---- tables ---------------------------------------------------------
    def create_table(self, db_id: int, tbl: TableInfo) -> None:
        self.txn.insert(_table_key(db_id, tbl.id),
                        json.dumps(tbl.to_dict()).encode())

    def update_table(self, db_id: int, tbl: TableInfo) -> None:
        self.txn.set(_table_key(db_id, tbl.id),
                     json.dumps(tbl.to_dict()).encode())

    def drop_table(self, db_id: int, tid: int) -> None:
        self.txn.delete(_table_key(db_id, tid))

    def get_table(self, db_id: int, tid: int) -> Optional[TableInfo]:
        try:
            return TableInfo.from_dict(
                json.loads(self.txn.get(_table_key(db_id, tid))))
        except KeyNotFound:
            return None

    def list_tables(self, db_id: int) -> List[TableInfo]:
        p = _TABLE_PREFIX + b"%08d:" % db_id
        out = []
        for _, v in self.txn.iter_range(p, p + b"\xff"):
            out.append(TableInfo.from_dict(json.loads(v)))
        return out

    # ---- DDL job queues (reference: meta.go:462 EnQueueDDLJob etc.) -----
    def _load_queue(self) -> List[Job]:
        try:
            raw = json.loads(self.txn.get(_JOB_QUEUE_KEY))
        except KeyNotFound:
            return []
        return [Job.from_json(j) for j in raw]

    def _store_queue(self, jobs: List[Job]) -> None:
        self.txn.set(_JOB_QUEUE_KEY,
                     json.dumps([j.to_json() for j in jobs]).encode())

    def enqueue_job(self, job: Job) -> None:
        q = self._load_queue()
        q.append(job)
        self._store_queue(q)

    def first_job(self) -> Optional[Job]:
        q = self._load_queue()
        return q[0] if q else None

    def update_job(self, job: Job) -> None:
        q = self._load_queue()
        for i, j in enumerate(q):
            if j.id == job.id:
                q[i] = job
                self._store_queue(q)
                return
        raise KeyNotFound(f"job {job.id} not in queue")

    def pop_job(self, job_id: int) -> None:
        q = [j for j in self._load_queue() if j.id != job_id]
        self._store_queue(q)

    def queue_length(self) -> int:
        return len(self._load_queue())

    def add_history_job(self, job: Job) -> None:
        self.txn.set(_JOB_HISTORY_PREFIX + b"%016d" % job.id,
                     job.to_json().encode())

    def get_history_job(self, job_id: int) -> Optional[Job]:
        try:
            return Job.from_json(
                self.txn.get(_JOB_HISTORY_PREFIX + b"%016d" % job_id).decode())
        except KeyNotFound:
            return None

    def history_jobs(self) -> List[Job]:
        out = []
        for _, v in self.txn.iter_range(_JOB_HISTORY_PREFIX,
                                        _JOB_HISTORY_PREFIX + b"\xff"):
            out.append(Job.from_json(v.decode()))
        return out

    # ---- bootstrap flag -------------------------------------------------
    def is_bootstrapped(self) -> bool:
        return self._get_int(_BOOTSTRAP_KEY) == 1

    def set_bootstrapped(self) -> None:
        self._set_int(_BOOTSTRAP_KEY, 1)
