"""Equal-depth histograms + estimation.

Capability parity with reference statistics/histogram.go:38-79 (buckets
{lower, upper, count, repeat}) and the row-count estimators :255-306
(equal/less/greater/between), built numpy-first: the histogram is
constructed from sorted sample arrays in one vectorized pass.
"""
from __future__ import annotations

import bisect
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

import numpy as np

from ..mytypes import Datum, coerce_for_compare, datum_compare


@dataclass
class Bucket:
    lower: Datum
    upper: Datum
    count: int       # cumulative rows up to and including this bucket
    repeat: int      # occurrences of `upper`


@dataclass
class Histogram:
    col_id: int
    ndv: int = 0
    null_count: int = 0
    total_count: int = 0
    buckets: List[Bucket] = field(default_factory=list)

    # ---- construction ---------------------------------------------------
    @classmethod
    def build(cls, col_id: int, values: List[Datum], null_count: int = 0,
              max_buckets: int = 64) -> "Histogram":
        """Build an equal-depth histogram from (non-null) sample values
        (reference: statistics/builder.go BuildColumn)."""
        h = cls(col_id, null_count=null_count)
        vals = sorted((v for v in values if v is not None),
                      key=_sort_key)
        n = len(vals)
        h.total_count = n + null_count
        if n == 0:
            return h
        per = max(1, (n + max_buckets - 1) // max_buckets)
        ndv = 1
        i = 0
        while i < n:
            j = min(i + per, n)
            # extend bucket to include all duplicates of the boundary value
            while j < n and datum_compare(vals[j], vals[j - 1]) == 0:
                j += 1
            upper = vals[j - 1]
            repeat = 1
            k = j - 2
            while k >= i and datum_compare(vals[k], upper) == 0:
                repeat += 1
                k -= 1
            h.buckets.append(Bucket(vals[i], upper, j, repeat))
            i = j
        # ndv
        ndv = 1
        for a, b in zip(vals, vals[1:]):
            if datum_compare(a, b) != 0:
                ndv += 1
        h.ndv = ndv
        return h

    # ---- estimation (reference: histogram.go estimate fns) -------------
    def not_null_count(self) -> int:
        return self.buckets[-1].count if self.buckets else 0

    def avg_count_per_value(self) -> float:
        nn = self.not_null_count()
        return nn / max(self.ndv, 1)

    def equal_row_count(self, v: Datum) -> float:
        if v is None:
            return float(self.null_count)
        idx = self._bucket_index(v)
        if idx < 0:
            return 0.0
        b = self.buckets[idx]
        if datum_compare(v, b.upper) == 0:
            return float(b.repeat)
        return self.avg_count_per_value()

    def less_row_count(self, v: Datum) -> float:
        """Rows strictly < v (NULLs excluded)."""
        if v is None:
            return 0.0
        idx = self._bucket_index(v)
        if idx < 0:
            if self.buckets and datum_compare(v, self.buckets[0].lower) < 0:
                return 0.0
            return float(self.not_null_count())
        b = self.buckets[idx]
        prev = self.buckets[idx - 1].count if idx > 0 else 0
        in_bucket = b.count - prev
        if datum_compare(v, b.lower) == 0:
            return float(prev)
        if datum_compare(v, b.upper) == 0:
            return float(b.count - b.repeat)
        # interpolate inside the bucket
        frac = _fraction(b.lower, b.upper, v)
        return prev + frac * in_bucket

    def greater_row_count(self, v: Datum) -> float:
        return max(0.0, self.not_null_count() - self.less_row_count(v)
                   - self.equal_row_count(v))

    def between_row_count(self, lo: Datum, hi: Datum,
                          lo_open: bool = False, hi_open: bool = True) -> float:
        """Rows in [lo, hi) by default (range semantics of util/ranger)."""
        cnt = self.less_row_count(hi) - self.less_row_count(lo)
        if not lo_open:
            pass  # lo included already (less(lo) excludes lo)
        else:
            cnt -= self.equal_row_count(lo)
        if not hi_open:
            cnt += self.equal_row_count(hi)
        return max(0.0, cnt)

    def _bucket_index(self, v: Datum) -> int:
        lo, hi = 0, len(self.buckets) - 1
        ans = -1
        while lo <= hi:
            mid = (lo + hi) // 2
            b = self.buckets[mid]
            if datum_compare(v, b.upper) <= 0:
                if datum_compare(v, b.lower) >= 0:
                    return mid
                hi = mid - 1
            else:
                lo = mid + 1
        return ans

    def to_dict(self) -> dict:
        return {"col_id": self.col_id, "ndv": self.ndv,
                "null_count": self.null_count,
                "total_count": self.total_count,
                "buckets": [[b.lower, b.upper, b.count, b.repeat]
                            for b in self.buckets]}

    @classmethod
    def from_dict(cls, d: dict) -> "Histogram":
        h = cls(d["col_id"], d["ndv"], d["null_count"], d["total_count"])
        h.buckets = [Bucket(*b) for b in d["buckets"]]
        return h


def _sort_key(v: Datum):
    from ..mytypes import sort_key
    return sort_key(v)


def _fraction(lo: Datum, hi: Datum, v: Datum) -> float:
    """Position of v inside (lo, hi) for interpolation."""
    try:
        a, b = coerce_for_compare(lo, hi)
        _, x = coerce_for_compare(lo, v)
        if isinstance(a, str) or isinstance(b, str):
            return 0.5
        if b == a:
            return 0.5
        return min(1.0, max(0.0, (x - a) / (b - a)))
    except Exception:
        return 0.5
