"""Count-Min sketch + FM sketch + reservoir sampling.

Capability parity with reference statistics/cmsketch.go:29-171 (d x w
counters, point-frequency estimate — the course stubs :52/:70 implemented
for real, numpy-vectorized), statistics/fmsketch.go (distinct-count
estimation), statistics/sample.go (reservoir sampling during ANALYZE).
"""
from __future__ import annotations

import hashlib
import random
import struct
from typing import List, Optional

import numpy as np

from ..mytypes import Datum


def _hash128(data: bytes) -> tuple:
    h = hashlib.blake2b(data, digest_size=16).digest()
    return struct.unpack("<QQ", h)


def _encode_datum(v: Datum) -> bytes:
    if v is None:
        return b"\x00"
    if isinstance(v, bool):
        v = int(v)
    if isinstance(v, int):
        # normalize mod 2^64 so a wrapped -1 and unwrapped 2^64-1 hash the
        # same (the two ANALYZE paths may see either representation)
        return b"i" + struct.pack("<Q", v & ((1 << 64) - 1))
    if isinstance(v, float):
        return b"f" + struct.pack("<d", v)
    return b"s" + str(v).encode("utf-8", "surrogateescape")


class CMSketch:
    """Count-Min: insert adds 1 to one counter per row; query takes the
    min over rows (reference: cmsketch.go InsertBytes :52 / queryBytes :70)."""

    def __init__(self, depth: int = 5, width: int = 2048):
        self.depth = depth
        self.width = width
        self.count = 0
        self.table = np.zeros((depth, width), dtype=np.uint32)

    def _positions(self, data: bytes) -> np.ndarray:
        h1, h2 = _hash128(data)
        # d independent hashes via h1 + i*h2 (Kirsch-Mitzenmacher)
        idx = (h1 + np.arange(self.depth, dtype=np.uint64) * np.uint64(h2 & ((1 << 63) - 1)))
        return (idx % np.uint64(self.width)).astype(np.int64)

    def insert(self, v: Datum, count: int = 1) -> None:
        self.insert_bytes(_encode_datum(v), count)

    def insert_bytes(self, data: bytes, count: int = 1) -> None:
        pos = self._positions(data)
        self.table[np.arange(self.depth), pos] += np.uint32(count)
        self.count += count

    def query(self, v: Datum) -> int:
        return self.query_bytes(_encode_datum(v))

    def query_bytes(self, data: bytes) -> int:
        pos = self._positions(data)
        vals = self.table[np.arange(self.depth), pos]
        # noise correction (reference: queryBytes subtracts the estimated
        # uniform noise, clamped)
        noise = self.count / self.width
        adjusted = np.where(vals > noise, vals - noise, 0.0)
        return int(min(vals.min(), np.mean(adjusted) + 0.5))

    def merge(self, other: "CMSketch") -> None:
        assert (self.depth, self.width) == (other.depth, other.width)
        self.table += other.table
        self.count += other.count

    def to_dict(self) -> dict:
        return {"depth": self.depth, "width": self.width,
                "count": self.count, "rows": self.table.tolist()}

    @classmethod
    def from_dict(cls, d: dict) -> "CMSketch":
        s = cls(d["depth"], d["width"])
        s.count = d["count"]
        s.table = np.array(d["rows"], dtype=np.uint32)
        return s


class FMSketch:
    """Flajolet-Martin distinct-count sketch (reference: fmsketch.go):
    keep hashes whose trailing zeros >= current mask level, bounded set."""

    def __init__(self, max_size: int = 10_000):
        self.max_size = max_size
        self.mask = np.uint64(0)
        self.hashset: set = set()

    def insert(self, v: Datum) -> None:
        h, _ = _hash128(_encode_datum(v))
        h = np.uint64(h)
        if h & self.mask == 0:
            self.hashset.add(int(h))
            if len(self.hashset) > self.max_size:
                self.mask = np.uint64((int(self.mask) << 1) | 1)
                self.hashset = {x for x in self.hashset
                                if x & int(self.mask) == 0}

    def ndv(self) -> int:
        return (int(self.mask) + 1) * len(self.hashset)

    def merge(self, other: "FMSketch") -> None:
        """Union two sketches (per-region ANALYZE partials): lift both to
        the coarser mask, union the surviving hashes, shrink as needed."""
        mask = max(int(self.mask), int(other.mask))
        merged = {x for x in self.hashset if x & mask == 0}
        merged |= {x for x in other.hashset if x & mask == 0}
        while len(merged) > self.max_size:
            mask = (mask << 1) | 1
            merged = {x for x in merged if x & mask == 0}
        self.mask = np.uint64(mask)
        self.hashset = merged


class ReservoirSampler:
    """Fixed-size uniform row sample (reference: sample.go
    SampleCollector)."""

    def __init__(self, capacity: int = 10_000, seed: int = 1):
        self.capacity = capacity
        self.samples: List[Datum] = []
        self.seen = 0
        self.null_count = 0
        self._rng = random.Random(seed)
        self.fm = FMSketch()
        self.cms = CMSketch()

    def collect(self, v: Datum) -> None:
        if v is None:
            self.null_count += 1
            return
        self.fm.insert(v)
        self.cms.insert(v)
        self.seen += 1
        if len(self.samples) < self.capacity:
            self.samples.append(v)
            return
        j = self._rng.randrange(self.seen)
        if j < self.capacity:
            self.samples[j] = v

    def collect_column(self, values: np.ndarray, null: np.ndarray) -> None:
        for i in range(len(values)):
            self.collect(None if null[i] else
                         (values[i].item() if hasattr(values[i], "item")
                          else values[i]))
