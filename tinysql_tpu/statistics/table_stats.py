"""Per-table statistics + selectivity estimation + persistence.

Capability parity with reference statistics/table.go (HistColl),
statistics/selectivity.go:129-306 (combine expressions -> estimates),
statistics/handle.go (lifecycle: save after ANALYZE, cached load, feeds
the planner's DeriveStats).  Persisted as JSON in the meta keyspace
(reference persists in mysql.stats_* system tables; same contract —
survives restarts, versioned by update ts).
"""
from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..expression import Column as ExprColumn, Constant, Expression, ScalarFunction
from ..kv.errors import KeyNotFound
from .histogram import Histogram
from .sketches import CMSketch

_STATS_PREFIX = b"m:stats:"  # m:stats:{table_id:08d} -> json

DEFAULT_SELECTIVITY = 0.8       # reference: selectionFactor
EQ_DEFAULT = 1.0 / 1000         # pseudo eq selectivity (pseudo table)
LT_DEFAULT = 1.0 / 3


@dataclass
class TableStats:
    table_id: int
    row_count: int = 0
    modify_count: int = 0
    version: int = 0
    columns: Dict[int, Histogram] = field(default_factory=dict)   # col_id
    cms: Dict[int, CMSketch] = field(default_factory=dict)
    indices: Dict[int, Histogram] = field(default_factory=dict)   # index_id

    @property
    def pseudo(self) -> bool:
        return self.row_count == 0 and not self.columns

    # ---- per-expression selectivity ------------------------------------
    def expr_selectivity(self, e: Expression) -> float:
        """Selectivity of one conjunct (reference: selectivity.go — reduced
        to per-conjunct independence; the disjoint-set cover over index
        prefixes lands with the index-path chooser)."""
        if self.row_count == 0:
            return DEFAULT_SELECTIVITY
        if isinstance(e, ScalarFunction):
            name = e.name
            if name in ("=", "<=>") and len(e.args) == 2:
                col, const = _col_const(e.args)
                if col is not None:
                    h = self.columns.get(col)
                    if h is not None and const is not None:
                        cms = self.cms.get(col)
                        cnt = (cms.query(const) if cms is not None
                               else h.equal_row_count(const))
                        return min(1.0, cnt / max(self.row_count, 1))
                    return EQ_DEFAULT
            if name in ("<", "<=", ">", ">=") and len(e.args) == 2:
                col, const = _col_const(e.args)
                if col is not None and const is not None:
                    h = self.columns.get(col)
                    if h is not None and h.total_count > 0:
                        less = h.less_row_count(const)
                        eq = h.equal_row_count(const)
                        flipped = isinstance(e.args[0], Constant)
                        op = name
                        if flipped:
                            op = {"<": ">", "<=": ">=", ">": "<", ">=": "<="}[op]
                        if op == "<":
                            cnt = less
                        elif op == "<=":
                            cnt = less + eq
                        elif op == ">":
                            cnt = h.not_null_count() - less - eq
                        else:
                            cnt = h.not_null_count() - less
                        return min(1.0, max(cnt, 0) / max(self.row_count, 1))
                return LT_DEFAULT
            if name == "and":
                return (self.expr_selectivity(e.args[0])
                        * self.expr_selectivity(e.args[1]))
            if name == "or":
                a = self.expr_selectivity(e.args[0])
                b = self.expr_selectivity(e.args[1])
                return min(1.0, a + b - a * b)
            if name == "isnull" and isinstance(e.args[0], ExprColumn):
                h = self.columns.get(_col_id(e.args[0]))
                if h is not None and h.total_count > 0:
                    return h.null_count / h.total_count
                return EQ_DEFAULT
            if name == "in":
                col = _col_id(e.args[0])
                consts = [a.value for a in e.args[1:]
                          if isinstance(a, Constant)]
                if col is not None and len(consts) == len(e.args) - 1:
                    h = self.columns.get(col)
                    if h is not None:
                        cnt = sum(h.equal_row_count(c) for c in consts)
                        return min(1.0, cnt / max(self.row_count, 1))
                return min(1.0, EQ_DEFAULT * max(len(e.args) - 1, 1))
        return DEFAULT_SELECTIVITY

    def selectivity(self, conds: List[Expression]) -> float:
        """Combined selectivity with a per-column cover (reference:
        selectivity.go:129-306 greedy disjoint-set cover, reduced to the
        single-column case): ALL range/eq conjuncts on one histogrammed
        column merge into one interval estimate, so `a > 5 AND a < 10`
        stops multiplying as if independent.  Index-prefix covers are
        handled upstream by the access-path ranger; everything not
        claimed by a cover falls back to per-conjunct independence."""
        groups: dict = {}
        rest: List[Expression] = []
        for c in conds:
            col = self._range_cond_col(c)
            if col is not None and self.columns.get(col) is not None:
                groups.setdefault(col, []).append(c)
            else:
                rest.append(c)
        s = 1.0
        for col, cs in groups.items():
            if len(cs) == 1:
                s *= self.expr_selectivity(cs[0])
            else:
                try:
                    s *= self._interval_selectivity(col, cs)
                except TypeError:  # incomparable mixed-type constants
                    for c in cs:
                        s *= self.expr_selectivity(c)
        for c in rest:
            s *= self.expr_selectivity(c)
        return s

    @staticmethod
    def _range_cond_col(e: Expression) -> Optional[int]:
        """col id when `e` is a col-vs-const compare mergeable into an
        interval; None otherwise."""
        if isinstance(e, ScalarFunction) and e.name in ("=", "<", "<=",
                                                        ">", ">="):
            col, const = _col_const(e.args)
            if col is not None and const is not None:
                return col
        return None

    def _interval_selectivity(self, col: int,
                              cs: List[Expression]) -> float:
        """Intersect all compares on `col` into [lo, hi] and estimate one
        histogram range count."""
        h = self.columns[col]
        if h.total_count <= 0:
            return DEFAULT_SELECTIVITY
        lo, lo_open = None, False   # None = unbounded
        hi, hi_open = None, False
        for e in cs:
            c0, const = _col_const(e.args)
            op = e.name
            if isinstance(e.args[0], Constant):  # const OP col -> flip
                op = {"<": ">", "<=": ">=", ">": "<", ">=": "<="}.get(op, op)
            if op == "=":
                lo2, hi2, lo2o, hi2o = const, const, False, False
            elif op in (">", ">="):
                lo2, hi2, lo2o, hi2o = const, None, op == ">", False
            else:  # <, <=
                lo2, hi2, lo2o, hi2o = None, const, False, op == "<"
            if lo2 is not None and (lo is None or lo2 > lo
                                    or (lo2 == lo and lo2o)):
                lo, lo_open = lo2, lo2o
            if hi2 is not None and (hi is None or hi2 < hi
                                    or (hi2 == hi and hi2o)):
                hi, hi_open = hi2, hi2o
        if lo is not None and hi is not None and (
                lo > hi or (lo == hi and (lo_open or hi_open))):
            return 0.0  # contradictory range
        cnt = float(h.not_null_count())
        upper = (h.less_row_count(hi) + (0 if hi_open
                                         else h.equal_row_count(hi))
                 if hi is not None else cnt)
        lower = (h.less_row_count(lo) + (h.equal_row_count(lo)
                                         if lo_open else 0)
                 if lo is not None else 0.0)
        est = max(upper - lower, 0.0)
        return min(1.0, est / max(self.row_count, 1))

    # ---- persistence ----------------------------------------------------
    def to_json(self) -> str:
        return json.dumps({
            "table_id": self.table_id, "row_count": self.row_count,
            "modify_count": self.modify_count, "version": self.version,
            "columns": {str(k): h.to_dict() for k, h in self.columns.items()},
            "cms": {str(k): s.to_dict() for k, s in self.cms.items()},
            "indices": {str(k): h.to_dict() for k, h in self.indices.items()},
        })

    @classmethod
    def from_json(cls, s: str) -> "TableStats":
        d = json.loads(s)
        t = cls(d["table_id"], d["row_count"], d["modify_count"],
                d["version"])
        t.columns = {int(k): Histogram.from_dict(v)
                     for k, v in d["columns"].items()}
        t.cms = {int(k): CMSketch.from_dict(v) for k, v in d["cms"].items()}
        t.indices = {int(k): Histogram.from_dict(v)
                     for k, v in d["indices"].items()}
        return t


def _col_id(e: Expression) -> Optional[int]:
    return getattr(e, "stats_col_id", None)


def _col_const(args) -> tuple:
    a, b = args
    if isinstance(a, ExprColumn) and isinstance(b, Constant):
        return _col_id(a), b.value
    if isinstance(b, ExprColumn) and isinstance(a, Constant):
        return _col_id(b), a.value
    return None, None


# ---- handle (per-storage cache; reference: statistics/handle.go) ----------

def save_stats(storage, stats: TableStats) -> None:
    with _stats_write_lock:
        stats.version = storage.current_version()
        txn = storage.begin()
        txn.set(_STATS_PREFIX + b"%08d" % stats.table_id,
                stats.to_json().encode())
        txn.commit()
        _cache_of(storage)[stats.table_id] = stats


import threading

# serializes read-modify-write of the shared stats record across
# concurrently committing sessions and ANALYZE (reference: the stats
# Handle owns all stats_meta writes behind one collector); RLock because
# update_count_delta calls save_stats under the same lock
_stats_write_lock = threading.RLock()


def update_count_delta(storage, table_id: int, delta: int) -> None:
    """Live row-count maintenance without ANALYZE (reference:
    mysql.stats_meta count/modify_count deltas flushed at commit by the
    session stats collector, picked up by handle.Update) — feeds the
    planner real table sizes so e.g. the TPU row-gate never routes a
    3-row table to an XLA compile."""
    if delta == 0:
        return
    with _stats_write_lock:
        stats = load_stats(storage, table_id)
        if stats is None:
            stats = TableStats(table_id)
        stats.row_count = max(0, stats.row_count + delta)
        stats.modify_count += abs(delta)
        try:
            save_stats(storage, stats)
        except Exception:
            # stats are advisory: a conflicting concurrent writer must
            # never surface an error AFTER the data commit succeeded
            _cache_of(storage).pop(table_id, None)


def set_count(storage, table_id: int, n: int) -> None:
    """Absolute row-count set (bulk loads REPLACE a table's contents);
    one atomic read-modify-write under the stats lock."""
    with _stats_write_lock:
        stats = load_stats(storage, table_id)
        if stats is None:
            stats = TableStats(table_id)
        stats.row_count = max(0, int(n))
        stats.modify_count += 1
        try:
            save_stats(storage, stats)
        except Exception:
            _cache_of(storage).pop(table_id, None)


def drop_stats(storage, table_id: int) -> None:
    """Forget a table's stats (DROP/TRUNCATE TABLE)."""
    txn = storage.begin()
    txn.delete(_STATS_PREFIX + b"%08d" % table_id)
    txn.commit()
    _cache_of(storage).pop(table_id, None)


def load_stats(storage, table_id: int) -> Optional[TableStats]:
    cache = _cache_of(storage)
    hit = cache.get(table_id)
    if hit is not None:
        return hit
    txn = storage.begin()
    try:
        raw = txn.get(_STATS_PREFIX + b"%08d" % table_id)
    except KeyNotFound:
        return None
    finally:
        txn.rollback()
    stats = TableStats.from_json(raw.decode())
    cache[table_id] = stats
    return stats


def _cache_of(storage) -> dict:
    c = getattr(storage, "_stats_cache", None)
    if c is None:
        c = storage._stats_cache = {}
    return c
