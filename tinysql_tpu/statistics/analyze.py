"""ANALYZE TABLE: collect per-column histograms, CMSketch, FMSketch NDV.

Capability parity with reference executor/analyze.go (:44-470 — column and
index pushdown tasks, result merge) + statistics/builder.go, redesigned
columnar-first: when the columnar replica is available the whole column is
sampled vectorized; otherwise a row scan feeds reservoir samplers.  Results
persist through statistics/table_stats.py (the mysql.stats_* analogue).
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ..catalog.model import TableInfo
from ..catalog.table import Table
from ..mytypes import EvalType
from .histogram import Histogram
from .sketches import CMSketch, ReservoirSampler
from .table_stats import TableStats, load_stats, save_stats

SAMPLE_CAP = 100_000
MAX_BUCKETS = 64


def analyze_table(session, info: TableInfo) -> TableStats:
    storage = session.storage
    from ..columnar.store import replica_for_read
    txn = storage.begin()
    try:
        rep = replica_for_read(storage, txn, info.id)
        if rep is not None:
            stats = _analyze_columnar(info, rep)
        else:
            stats = _analyze_rows(info, txn)
    finally:
        txn.rollback()
    save_stats(storage, stats)
    return stats


def _analyze_columnar(info: TableInfo, rep) -> TableStats:
    stats = TableStats(info.id, row_count=rep.n_rows)
    rng = np.random.default_rng(0)
    for c in info.public_columns():
        if c.id not in rep.columns:
            continue
        v, m = rep.columns[c.id]
        n = len(v)
        null_count = int(m.sum())
        if n > SAMPLE_CAP:
            idx = rng.choice(n, SAMPLE_CAP, replace=False)
            sv, sm = v[idx], m[idx]
            scale = n / SAMPLE_CAP
        else:
            sv, sm = v, m
            scale = 1.0
        uns = c.ft.eval_type is EvalType.INT and c.ft.is_unsigned
        vals = []
        for i in range(len(sv)):
            if sm[i]:
                continue
            x = sv[i].item() if hasattr(sv[i], "item") else sv[i]
            if uns and isinstance(x, int) and x < 0:
                x += 1 << 64  # unwrap wrapped uint64: match the row path's
                # decoded semantic values so both ANALYZE paths agree
            vals.append(x)
        if c.ft.eval_type is EvalType.STRING:
            vals = [str(x) for x in vals]
        h = Histogram.build(c.id, vals,
                            null_count=int(null_count / max(scale, 1)),
                            max_buckets=MAX_BUCKETS)
        _scale_histogram(h, scale, n, null_count)
        stats.columns[c.id] = h
        cms = CMSketch()
        for x in vals:
            cms.insert(x)
        if scale > 1:
            cms.table = (cms.table.astype(np.float64) * scale).astype(np.uint32)
            cms.count = int(cms.count * scale)
        stats.cms[c.id] = cms
    return stats


def _analyze_rows(info: TableInfo, txn) -> TableStats:
    cols = info.public_columns()
    samplers = {c.id: ReservoirSampler(SAMPLE_CAP) for c in cols}
    n = 0
    for _, row in Table(info).iter_records(txn):
        n += 1
        for c in cols:
            samplers[c.id].collect(row[c.offset])
    stats = TableStats(info.id, row_count=n)
    for c in cols:
        s = samplers[c.id]
        scale = max(1.0, s.seen / max(len(s.samples), 1))
        h = Histogram.build(c.id, s.samples, null_count=s.null_count,
                            max_buckets=MAX_BUCKETS)
        _scale_histogram(h, scale, s.seen + s.null_count, s.null_count)
        h.ndv = max(h.ndv, s.fm.ndv() if scale > 1 else h.ndv)
        stats.columns[c.id] = h
        stats.cms[c.id] = s.cms
    return stats


def _scale_histogram(h: Histogram, scale: float, total: int,
                     null_count: int) -> None:
    if scale <= 1.0:
        return
    for b in h.buckets:
        b.count = int(b.count * scale)
        b.repeat = max(1, int(b.repeat * scale))
    h.ndv = min(int(h.ndv * scale), total)
    h.total_count = total
    h.null_count = null_count


def table_row_count(storage, table_id: int) -> int:
    s = load_stats(storage, table_id)
    return s.row_count if s else 0
