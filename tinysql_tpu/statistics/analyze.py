"""ANALYZE TABLE: collect per-column histograms, CMSketch, FMSketch NDV.

Capability parity with reference executor/analyze.go (:44-470 — column
pushdown tasks, result merge) + statistics/builder.go, redesigned
columnar-first: when the columnar replica is available the whole column is
sampled vectorized; otherwise per-region analyze tasks run through the
coprocessor (reservoir samples + CMSketch + FMSketch partials, merged at
root with live-count weighting).  Results persist through
statistics/table_stats.py (the mysql.stats_* analogue).
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ..catalog.model import TableInfo
from ..mytypes import EvalType
from .histogram import Histogram
from .sketches import CMSketch, ReservoirSampler
from .table_stats import TableStats, load_stats, save_stats

SAMPLE_CAP = 100_000
MAX_BUCKETS = 64


def analyze_table(session, info: TableInfo) -> TableStats:
    storage = session.storage
    from ..columnar.store import replica_for_read
    txn = storage.begin()
    try:
        rep = replica_for_read(storage, txn, info.id)
        if rep is not None:
            stats = _analyze_columnar(info, rep)
        else:
            stats = _analyze_distributed(storage, info, txn)
    finally:
        txn.rollback()
    save_stats(storage, stats)
    return stats


def _analyze_distributed(storage, info: TableInfo, txn) -> TableStats:
    """Per-region analyze tasks merged at root (reference:
    executor/analyze.go pushdown builders :171,318 + result merge
    :251-316; region partials carry samples + CMSketch + FMSketch)."""
    from ..codec import tablecodec
    from ..distsql import DAGRequest, ScanInfo, select
    from ..distsql.exprpb import _ft_to_pb
    cols = info.public_columns()
    req = DAGRequest(
        start_ts=txn.start_ts,
        scan=ScanInfo(
            table_id=info.id,
            col_ids=[c.id for c in cols],
            col_fts=[_ft_to_pb(c.ft) for c in cols],
            col_defaults=[c.default for c in cols],
            handle_slots=[],
            pk_id=(info.get_pk_handle_col().id
                   if info.get_pk_handle_col() else None)),
        analyze=True)
    stats = TableStats(info.id, row_count=0)
    # keep PER-REGION partials: regions of different sizes contribute to
    # the final sample proportionally to their live counts (reference:
    # statistics.MergeSampleCollector's weighted merge), otherwise a
    # 10k-row region would weigh as much as a 1M-row one
    parts: Dict[int, list] = {}
    for batch in select(storage, req,
                        [tablecodec.record_range(info.id)]):
        for part in batch:
            stats.row_count += part["rows"]
            for cid, p in part["cols"].items():
                parts.setdefault(cid, []).append(p)
    rng = np.random.default_rng(0)
    for cid, plist in parts.items():
        live = sum(p["live"] for p in plist)
        nulls = sum(p["nulls"] for p in plist)
        target = min(SAMPLE_CAP, live)
        samples: list = []
        for p in plist:
            if live == 0 or not p["samples"]:
                continue
            want = max(1, round(target * p["live"] / live))
            src = p["samples"]
            if want >= len(src):
                samples.extend(src)
            else:
                idx = rng.choice(len(src), want, replace=False)
                samples.extend(src[i] for i in idx)
        cms = plist[0]["cms"]
        fm = plist[0]["fm"]
        for p in plist[1:]:
            cms.merge(p["cms"])
            fm.merge(p["fm"])
        scale = max(1.0, live / max(len(samples), 1))
        h = Histogram.build(cid, samples, null_count=nulls,
                            max_buckets=MAX_BUCKETS)
        _scale_histogram(h, scale, live + nulls, nulls)
        h.ndv = max(h.ndv, fm.ndv() if scale > 1 else h.ndv)
        stats.columns[cid] = h
        stats.cms[cid] = cms
    return stats


def _analyze_columnar(info: TableInfo, rep) -> TableStats:
    stats = TableStats(info.id, row_count=rep.n_rows)
    rng = np.random.default_rng(0)
    for c in info.public_columns():
        if c.id not in rep.columns:
            continue
        v, m = rep.columns[c.id]
        n = len(v)
        null_count = int(m.sum())
        if n > SAMPLE_CAP:
            idx = rng.choice(n, SAMPLE_CAP, replace=False)
            sv, sm = v[idx], m[idx]
            scale = n / SAMPLE_CAP
        else:
            sv, sm = v, m
            scale = 1.0
        uns = c.ft.eval_type is EvalType.INT and c.ft.is_unsigned
        vals = []
        for i in range(len(sv)):
            if sm[i]:
                continue
            x = sv[i].item() if hasattr(sv[i], "item") else sv[i]
            if uns and isinstance(x, int) and x < 0:
                x += 1 << 64  # unwrap wrapped uint64: match the row path's
                # decoded semantic values so both ANALYZE paths agree
            vals.append(x)
        if c.ft.eval_type is EvalType.STRING:
            vals = [str(x) for x in vals]
        h = Histogram.build(c.id, vals,
                            null_count=int(null_count / max(scale, 1)),
                            max_buckets=MAX_BUCKETS)
        _scale_histogram(h, scale, n, null_count)
        stats.columns[c.id] = h
        cms = CMSketch()
        for x in vals:
            cms.insert(x)
        if scale > 1:
            cms.table = (cms.table.astype(np.float64) * scale).astype(np.uint32)
            cms.count = int(cms.count * scale)
        stats.cms[c.id] = cms
    return stats


def _scale_histogram(h: Histogram, scale: float, total: int,
                     null_count: int) -> None:
    if scale <= 1.0:
        return
    for b in h.buckets:
        b.count = int(b.count * scale)
        b.repeat = max(1, int(b.repeat * scale))
    h.ndv = min(int(h.ndv * scale), total)
    h.total_count = total
    h.null_count = null_count


def table_row_count(storage, table_id: int) -> int:
    s = load_stats(storage, table_id)
    return s.row_count if s else 0
