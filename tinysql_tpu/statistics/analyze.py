"""ANALYZE TABLE collection — placeholder until the statistics phase lands
(histograms + CMSketch + FMSketch per SURVEY §2.10).  Collects row counts so
the planner's stats hooks have something real immediately."""
from __future__ import annotations

from typing import Dict

from ..catalog.model import TableInfo
from ..catalog.table import Table

# per-storage, per-table basic stats (row counts) until the full Handle
# (statistics/handle.py) replaces this
_BASIC: Dict[int, Dict[int, int]] = {}


def analyze_table(session, info: TableInfo) -> None:
    txn = session.storage.begin()
    try:
        n = sum(1 for _ in Table(info).iter_records(txn))
    finally:
        txn.rollback()
    _BASIC.setdefault(id(session.storage), {})[info.id] = n


def table_row_count(storage, table_id: int) -> int:
    return _BASIC.get(id(storage), {}).get(table_id, 0)
