"""Columnar table store — the analytics fast path.

TPU-first design decision (no direct reference counterpart; the TiFlash
analogue of TiDB's row store): analytical scans read contiguous numpy
columns that marshal straight onto device HBM, instead of decoding
rowcodec values row-by-row.  The row-oriented KV + 2PC path (SURVEY §2.6)
remains the write path and source of truth; this store is a cache/replica:

- `bulk_load` ingests whole tables column-wise (the LOAD DATA analogue).
- A full KV scan hydrates the cache as a side effect.
- Any committed write touching a table bumps its data version
  (hooked in the 2PC committer), invalidating the replica.
- A transaction may read the replica only if it has no buffered writes on
  the table and the replica was built from data unchanged since the txn's
  snapshot.
"""
from __future__ import annotations

import threading
import weakref
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..catalog.model import TableInfo
from ..mytypes import EvalType
from ..obs import memprof as _memprof


@dataclass
class ColumnarTable:
    table_id: int
    n_rows: int
    built_ts: int                  # oracle ts when built
    data_version: int              # storage table-version at build
    # col_id -> (values ndarray, null ndarray); handles as int64 array
    columns: Dict[int, Tuple[np.ndarray, np.ndarray]] = field(default_factory=dict)
    handles: Optional[np.ndarray] = None
    # derived-state memo (device-resident padded uploads, string dictionary
    # codes) — lives and dies with this replica version, so invalidation is
    # free: a bump drops the whole ColumnarTable
    cache: Dict[object, object] = field(default_factory=dict)

    def memo(self, key, build):
        v = self.cache.get(key)
        if v is None:
            v = self.cache[key] = build()
        return v


#: every live store, weakly held — the HBM census walks them to claim
#: replica-memoized device buffers, and the spill gates read measured
#: row widths off them (obs/memprof.measured_row_bytes)
_STORES: "weakref.WeakSet[ColumnarStore]" = weakref.WeakSet()


def live_stores() -> List["ColumnarStore"]:
    return list(_STORES)


class ColumnarStore:
    def __init__(self):
        self._tables: Dict[int, ColumnarTable] = {}
        self._mu = threading.Lock()
        _STORES.add(self)

    def tables_snapshot(self) -> List[ColumnarTable]:
        with self._mu:
            return list(self._tables.values())

    def get(self, table_id: int) -> Optional[ColumnarTable]:
        with self._mu:
            return self._tables.get(table_id)

    def put(self, tbl: ColumnarTable) -> None:
        with self._mu:
            self._tables[tbl.table_id] = tbl

    def invalidate(self, table_id: int) -> None:
        with self._mu:
            self._tables.pop(table_id, None)


def _replica_memo_values():
    """HBM census walker: every replica's derived-state memo values —
    where ALL long-lived device buffers in the engine are born
    (rep.memo(..., lambda: kernels.h2d(...)) in the executors)."""
    for s in live_stores():
        for tbl in s.tables_snapshot():
            yield list(tbl.cache.values())


_memprof.register_census_walker("replica", _replica_memo_values)


def store_of(storage) -> ColumnarStore:
    s = getattr(storage, "_columnar", None)
    if s is None:
        s = storage._columnar = ColumnarStore()
    return s


def table_data_version(storage, table_id: int) -> int:
    versions = getattr(storage, "_table_versions", None)
    if versions is None:
        versions = storage._table_versions = {}
    return versions.get(table_id, (0, 0))[0]


def table_version_ts(storage, table_id: int) -> int:
    """Oracle ts at which the table's data version was last bumped: a
    snapshot at/after this ts sees all data of the current version."""
    versions = getattr(storage, "_table_versions", None)
    if versions is None:
        versions = storage._table_versions = {}
    return versions.get(table_id, (0, 0))[1]


def bump_table_version(storage, table_id: int) -> None:
    versions = getattr(storage, "_table_versions", None)
    if versions is None:
        versions = storage._table_versions = {}
    ver = versions.get(table_id, (0, 0))[0]
    versions[table_id] = (ver + 1, storage.current_version())
    store_of(storage).invalidate(table_id)


def replica_for_read(storage, txn, table_id: int) -> Optional[ColumnarTable]:
    """The replica is readable by `txn` iff it reflects exactly the data the
    txn's snapshot would see and the txn has no own writes on the table."""
    rep = store_of(storage).get(table_id)
    if rep is None:
        return None
    if rep.data_version != table_data_version(storage, table_id):
        return None
    if txn is not None and rep.built_ts > txn.start_ts:
        return None  # built from newer data than the snapshot
    if txn is not None and _txn_touches_table(txn, table_id):
        return None
    return rep


def _txn_touches_table(txn, table_id: int) -> bool:
    from ..codec import tablecodec
    prefix = tablecodec.encode_table_prefix(table_id)
    for k, _ in txn.us.buffer.iter_range(prefix, prefix + b"\xff" * 20):
        return True
    return False


def _np_dtype(et: EvalType):
    if et is EvalType.INT:
        return np.int64
    if et is EvalType.REAL:
        return np.float64
    return object


def bulk_load(storage, info: TableInfo,
              data: Dict[str, np.ndarray],
              nulls: Optional[Dict[str, np.ndarray]] = None,
              handles: Optional[np.ndarray] = None) -> int:
    """Columnar bulk ingest (LOAD DATA analogue): columns keyed by name.
    Writes the replica AND the row-store contract metadata (row count via
    handles).  Returns n_rows."""
    nulls = nulls or {}
    cols: Dict[int, Tuple[np.ndarray, np.ndarray]] = {}
    n = None
    for c in info.public_columns():
        if c.name not in data:
            raise ValueError(f"bulk_load missing column {c.name}")
        v = np.asarray(data[c.name])
        dt = _np_dtype(c.ft.eval_type)
        if dt is object:
            # keep fixed-width <U dtype: string filters vectorize in C
            if v.dtype.kind != "U":
                v = v.astype(str)
        else:
            v = v.astype(dt)
        m = np.asarray(nulls.get(c.name, np.zeros(len(v), dtype=bool)),
                       dtype=bool)
        if n is None:
            n = len(v)
        assert len(v) == n and len(m) == n
        cols[c.id] = (v, m)
    if handles is None:
        # a clustered int PK *is* the handle: the replica's handle array
        # must carry the PK VALUES, not a synthetic row number —
        # otherwise PK predicates (handle ranges) select the wrong rows
        pk = info.get_pk_handle_col()
        if pk is not None and pk.name in data:
            handles = np.asarray(data[pk.name], dtype=np.int64)
        else:
            handles = np.arange(1, (n or 0) + 1, dtype=np.int64)
    ver = table_data_version(storage, info.id)
    rep = ColumnarTable(info.id, n or 0, storage.current_version(), ver,
                        cols, np.asarray(handles, dtype=np.int64))
    store_of(storage).put(rep)
    # bulk ingest bypasses add_record, so feed the live stats count here
    # (keeps planner estimates and the TPU row-gate truthful); absolute
    # set — the replica REPLACES the table's contents
    from ..statistics.table_stats import set_count
    set_count(storage, info.id, n or 0)
    return n or 0


def ensure_row_store(storage, info: TableInfo) -> int:
    """Materialize a bulk-loaded table into the MVCC row store before
    its first WRITE statement.  ``bulk_load`` writes ONLY the columnar
    replica; a write statement commits through the row store and bumps
    the table version — invalidating the replica and silently dropping
    every row the write didn't touch.  This backfills the replica's
    rows (indices included, via the Table write path) directly into
    MVCC at the replica's BUILD timestamp: the rows logically existed
    since the bulk load, every snapshot >= built_ts already serves them
    from the replica, and open transactions (start_ts > built_ts) see
    values identical to what they were reading — so no version bump and
    the replica stays valid until the write's own commit.  No-op unless
    the table is replica-only (valid replica, empty row store); returns
    the number of rows installed."""
    from ..catalog.table import Table
    from ..codec import tablecodec
    from ..kv.txn import Transaction
    rep = store_of(storage).get(info.id)
    if rep is None or rep.n_rows == 0:
        return 0
    if rep.data_version != table_data_version(storage, info.id):
        return 0  # stale replica: the row store is already the truth
    lo, hi = tablecodec.record_range(info.id)
    from ..kv.errors import KeyIsLocked
    try:
        if storage.mvcc.scan(lo, hi, storage.current_version(), limit=1):
            return 0  # row store already populated
    except KeyIsLocked:
        # an in-flight writer holds a record lock — every writer passes
        # through this gate first, so materialization already ran
        return 0
    tbl = Table(info)
    scratch = Transaction(storage, rep.built_ts)
    n_cols = len(info.columns)
    pub = [(c, rep.columns.get(c.id)) for c in info.public_columns()]
    handles = rep.handles
    for i in range(rep.n_rows):
        row = [None] * n_cols
        for c, pair in pub:
            if pair is None:
                continue
            v, m = pair
            if not m[i]:
                x = v[i]
                row[c.offset] = str(x) if v.dtype.kind == "U" \
                    else x.item()
        tbl.add_record(scratch, row, handle=int(handles[i]))
    # the scratch buffer holds only puts (add_record never deletes), so
    # every entry backfills verbatim — row records and index entries
    return storage.mvcc.backfill(list(scratch.us.buffer._m.items()),
                                 rep.built_ts)


def hydrate_from_scan(storage, txn, info: TableInfo,
                      col_ids: List[int],
                      arrays: Dict[int, Tuple[np.ndarray, np.ndarray]],
                      handles: np.ndarray) -> None:
    """Cache the result of a completed full scan (only when the txn could
    have used a replica, i.e. it had no own writes).

    Staleness gate: the scan saw data as of txn.start_ts.  If the table's
    version was bumped AFTER that snapshot, the scan is missing newer
    committed rows and must not be published under the current version."""
    if _txn_touches_table(txn, info.id):
        return
    if txn.start_ts < table_version_ts(storage, info.id):
        return  # snapshot predates the current data version
    existing = store_of(storage).get(info.id)
    ver = table_data_version(storage, info.id)
    if existing is not None and existing.data_version == ver:
        existing.columns.update(arrays)
        return
    rep = ColumnarTable(info.id, len(handles), txn.start_ts, ver,
                        dict(arrays), handles)
    store_of(storage).put(rep)
