"""Columnar replica store (TPU-first analytics fast path)."""
from .store import (ColumnarStore, ColumnarTable, bulk_load,
                    bump_table_version, ensure_row_store, hydrate_from_scan,
                    replica_for_read,
                    store_of, table_data_version)

__all__ = ["ColumnarStore", "ColumnarTable", "bulk_load",
           "bump_table_version", "ensure_row_store", "hydrate_from_scan",
           "replica_for_read",
           "store_of", "table_data_version"]
