"""Typed exponential backoff budgets (reference: store/tikv/backoff.go:98-222).

Each retry class has its own base/cap; a Backoffer carries a total budget and
raises BackoffExceeded when spent.  `SLEEP_SCALE` lets tests run the full
retry ladder without real wall-clock sleeps.
"""
from __future__ import annotations

import random
import time
from dataclasses import dataclass
from typing import Dict

from ..utils import interrupt
from .errors import BackoffExceeded, TaskCancelled

SLEEP_SCALE = 1.0  # tests set tinysql_tpu.kv.backoff.SLEEP_SCALE = 0


@dataclass(frozen=True)
class BackoffType:
    name: str
    base_ms: int
    cap_ms: int

    def sleep_ms(self, attempt: int) -> float:
        v = min(self.cap_ms, self.base_ms * (2 ** attempt))
        return v / 2 + random.random() * v / 2  # equal-jitter


BO_RPC = BackoffType("tikvRPC", 100, 2000)
BO_REGION_MISS = BackoffType("regionMiss", 2, 500)
BO_TXN_LOCK = BackoffType("txnLock", 200, 3000)
BO_TXN_LOCK_FAST = BackoffType("txnLockFast", 100, 3000)
BO_PD_RPC = BackoffType("pdRPC", 500, 3000)

GET_MAX_BACKOFF = 20000
SCAN_MAX_BACKOFF = 20000
PREWRITE_MAX_BACKOFF = 20000
COMMIT_MAX_BACKOFF = 41000
COP_NEXT_MAX_BACKOFF = 20000
CLEANUP_MAX_BACKOFF = 20000


class Backoffer:
    def __init__(self, max_sleep_ms: int, cancel=None,
                 interruptible: bool = True):
        """``cancel``: optional threading.Event — a set event aborts the
        NEXT backoff with TaskCancelled instead of sleeping (the distsql
        early-close path), and an in-flight sleep wakes on it.
        ``interruptible=False`` exempts this ladder from the statement
        kill/deadline check: the 2PC COMMIT phase sets it, because once
        the primary batch committed the txn is durable and aborting a
        secondary retry would misreport a committed txn as interrupted
        (and skip its columnar invalidation)."""
        self.max_sleep_ms = max_sleep_ms
        self.total_ms = 0.0
        self.attempts: Dict[str, int] = {}
        self.errors = []
        self.cancel = cancel
        self.interruptible = interruptible

    def backoff(self, bo: BackoffType, err: Exception) -> None:
        # statement kill / max_execution_time both land here: a retry
        # ladder is exactly where a doomed statement would otherwise
        # burn its whole budget before noticing
        if self.interruptible:
            interrupt.check()
        if self.cancel is not None and self.cancel.is_set():
            raise TaskCancelled(f"cancelled during {bo.name} backoff") \
                from err
        self.errors.append(err)
        n = self.attempts.get(bo.name, 0)
        self.attempts[bo.name] = n + 1
        ms = bo.sleep_ms(n)
        self.total_ms += ms
        if self.total_ms >= self.max_sleep_ms:
            raise BackoffExceeded(
                f"backoff budget {self.max_sleep_ms}ms exceeded; "
                f"errors: {self.errors[-5:]}") from err
        if SLEEP_SCALE > 0:
            if self.cancel is not None:
                self.cancel.wait(ms / 1000.0 * SLEEP_SCALE)
            else:
                time.sleep(ms / 1000.0 * SLEEP_SCALE)

    def fork(self) -> "Backoffer":
        b = Backoffer(self.max_sleep_ms, cancel=self.cancel,
                      interruptible=self.interruptible)
        b.total_ms = self.total_ms
        return b
