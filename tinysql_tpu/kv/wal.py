"""Write-ahead log + checkpoint persistence for the MVCC store.

The reference store is durable (mvcc_leveldb.go persists every Percolator
lock/write/data column to goleveldb); this module gives `kv/mvcc.py` the
same contract without a storage engine: every MVCC mutation (prewrite /
commit / rollback / resolve / gc / backfill) is journaled as one
length-prefixed, CRC-checksummed record inside the store's existing
critical section, and a periodic checkpoint folds the log into a single
atomically-renamed snapshot of the full entry map — including in-flight
locks, so the lock-resolution ladder (`check_txn_status`) fences or
completes interrupted transactions after a restart exactly as it does on
a live store.

Layout under ``data_dir``:

- ``wal.log``       append-only record log; rotated (truncated) after
                    each checkpoint
- ``checkpoint.bin`` full entry-map snapshot + the LSN it covers;
                    written to ``checkpoint.tmp`` then atomically renamed
- ``checkpoint.tmp`` in-flight checkpoint; ignored by recovery

Record framing: ``u32 payload_len | u32 crc32(payload) | payload`` where
``payload = u64 lsn | u8 type | body``.  Recovery replays records with
``lsn > checkpoint.last_lsn`` in order and truncates the log at the first
bad length/short read/checksum — the torn-tail rule.  A torn record can
only be the final one: under the ``strict`` fsync policy every ack-bearing
record is fsynced before the client sees OK (a torn write means the fsync
never returned, so nothing was acked against it), and under
``relaxed``/``off`` the ack was never durability-promised in the first
place.  Recovery therefore never truncates behind an fsync'd ack.

Fsync policy (sysvar ``tidb_wal_fsync``, default env ``TINYSQL_WAL_FSYNC``
or ``relaxed``):

- ``strict``   fsync before acking every commit-class record
               (commit / resolve / rollback)
- ``relaxed``  group commit: commit-class records fsync at most once per
               ``GROUP_COMMIT_S`` window; a crash of the *machine* can
               lose acks inside the open window (a SIGKILL cannot — the
               bytes are already in the page cache)
- ``off``      never fsync the log (checkpoints still fsync)

Failpoints (fail/points.py): ``walAppendError`` (append raises before any
state mutates), ``walFsyncError`` (the fsync syscall fails), ``walTornTail``
(the next record is deliberately half-written — the crash-boundary lever),
``checkpointError`` (a checkpoint attempt fails/stalls; counted, never
fatal).
"""
from __future__ import annotations

import os
import struct
import threading
import time
import zlib
from typing import Dict, List, Optional, Tuple

from ..utils import failpoint
from .errors import CheckpointError, WalError

# ---- record types ----------------------------------------------------------
REC_PREWRITE = 1
REC_COMMIT = 2
REC_ROLLBACK = 3
REC_RESOLVE = 4
REC_GC = 5
REC_BACKFILL = 6

#: ack-bearing record types: their fsync (per policy) is the durability
#: promise behind the wire-level OK
_COMMIT_CLASS = (REC_COMMIT, REC_ROLLBACK, REC_RESOLVE)

GROUP_COMMIT_S = 0.02          # relaxed-policy group-commit window
DEFAULT_CHECKPOINT_BYTES = 4 << 20   # auto-checkpoint threshold

_CKPT_MAGIC = b"TSQLCKP1"
_FSYNC_POLICIES = ("off", "relaxed", "strict")

_HDR = struct.Struct("<II")          # payload_len, crc32
_REC = struct.Struct("<QB")          # lsn, type

# ---- process-cumulative stats (METRICS -> tsring -> /metrics) --------------
_STATS_MU = threading.Lock()
STATS: Dict[str, float] = {
    "appends": 0, "append_bytes": 0, "append_errors": 0,
    "fsyncs": 0, "fsync_s": 0.0, "fsync_errors": 0,
    "torn_writes": 0,
    "checkpoints": 0, "checkpoint_s": 0.0, "checkpoint_errors": 0,
    "recoveries": 0, "replayed_records": 0, "recovered_locks": 0,
    "truncated_tails": 0,
    "gc_runs": 0, "gc_removed": 0,
    "wal_size_bytes": 0,         # gauge: bytes in the live log
}


def _bump(key: str, n: float = 1) -> None:
    with _STATS_MU:
        STATS[key] = STATS.get(key, 0) + n


def _set(key: str, v: float) -> None:
    with _STATS_MU:
        STATS[key] = v


def stats_snapshot() -> Dict[str, float]:
    with _STATS_MU:
        return dict(STATS)


def reset_stats() -> None:
    """Test hook: zero the cumulative counters."""
    with _STATS_MU:
        for k in STATS:
            STATS[k] = 0


# ---- codec helpers ---------------------------------------------------------

def _pb(buf: bytearray, b: bytes) -> None:
    buf += struct.pack("<I", len(b))
    buf += b


def _rb(mv: memoryview, off: int) -> Tuple[bytes, int]:
    (n,) = struct.unpack_from("<I", mv, off)
    off += 4
    return bytes(mv[off:off + n]), off + n


def encode_prewrite(primary: bytes, start_ts: int, ttl_ms: int,
                    muts: List[Tuple[int, bytes, bytes]]) -> bytes:
    buf = bytearray(struct.pack("<QQ", start_ts, ttl_ms))
    _pb(buf, primary)
    buf += struct.pack("<I", len(muts))
    for op, key, value in muts:
        buf += struct.pack("<B", op)
        _pb(buf, key)
        _pb(buf, value)
    return bytes(buf)


def decode_prewrite(body: bytes):
    mv = memoryview(body)
    start_ts, ttl_ms = struct.unpack_from("<QQ", mv, 0)
    primary, off = _rb(mv, 16)
    (n,) = struct.unpack_from("<I", mv, off)
    off += 4
    muts = []
    for _ in range(n):
        (op,) = struct.unpack_from("<B", mv, off)
        off += 1
        key, off = _rb(mv, off)
        value, off = _rb(mv, off)
        muts.append((op, key, value))
    return primary, start_ts, ttl_ms, muts


def encode_commit(start_ts: int, commit_ts: int,
                  items: List[Tuple[bytes, int, bytes]]) -> bytes:
    # items carry the committed VALUE, not just the key: a commit record
    # is a self-contained redo, so replay never depends on the matching
    # prewrite record having survived
    buf = bytearray(struct.pack("<QQI", start_ts, commit_ts, len(items)))
    for key, wtype, value in items:
        buf += struct.pack("<B", wtype)
        _pb(buf, key)
        _pb(buf, value)
    return bytes(buf)


def decode_commit(body: bytes):
    mv = memoryview(body)
    start_ts, commit_ts, n = struct.unpack_from("<QQI", mv, 0)
    off = 20
    items = []
    for _ in range(n):
        (wtype,) = struct.unpack_from("<B", mv, off)
        off += 1
        key, off = _rb(mv, off)
        value, off = _rb(mv, off)
        items.append((key, wtype, value))
    return start_ts, commit_ts, items


def encode_rollback(start_ts: int, keys: List[bytes]) -> bytes:
    buf = bytearray(struct.pack("<QI", start_ts, len(keys)))
    for k in keys:
        _pb(buf, k)
    return bytes(buf)


def decode_rollback(body: bytes):
    mv = memoryview(body)
    start_ts, n = struct.unpack_from("<QI", mv, 0)
    off = 12
    keys = []
    for _ in range(n):
        k, off = _rb(mv, off)
        keys.append(k)
    return start_ts, keys


def encode_resolve(key: bytes, start_ts: int, commit_ts: int,
                   wtype: int, value: bytes) -> bytes:
    buf = bytearray(struct.pack("<QQB", start_ts, commit_ts, wtype))
    _pb(buf, key)
    _pb(buf, value)
    return bytes(buf)


def decode_resolve(body: bytes):
    mv = memoryview(body)
    start_ts, commit_ts, wtype = struct.unpack_from("<QQB", mv, 0)
    key, off = _rb(mv, 17)
    value, off = _rb(mv, off)
    return key, start_ts, commit_ts, wtype, value


def encode_gc(safepoint_ts: int) -> bytes:
    return struct.pack("<Q", safepoint_ts)


def decode_gc(body: bytes) -> int:
    return struct.unpack_from("<Q", body, 0)[0]


def encode_backfill(ts: int, kvs: List[Tuple[bytes, bytes]]) -> bytes:
    buf = bytearray(struct.pack("<QI", ts, len(kvs)))
    for k, v in kvs:
        _pb(buf, k)
        _pb(buf, v)
    return bytes(buf)


def decode_backfill(body: bytes):
    mv = memoryview(body)
    ts, n = struct.unpack_from("<QI", mv, 0)
    off = 12
    kvs = []
    for _ in range(n):
        k, off = _rb(mv, off)
        v, off = _rb(mv, off)
        kvs.append((k, v))
    return ts, kvs


# ---- checkpoint entry-map codec -------------------------------------------

def _encode_entries(entries) -> bytes:
    buf = bytearray(struct.pack("<I", len(entries)))
    for key, e in entries.items():
        _pb(buf, key)
        if e.lock is not None:
            buf += b"\x01"
            buf += struct.pack("<QQB", e.lock.start_ts, e.lock.ttl_ms,
                               e.lock.op)
            _pb(buf, e.lock.primary)
            _pb(buf, e.lock.value)
        else:
            buf += b"\x00"
        buf += struct.pack("<I", len(e.writes))
        for cts, wt, sts in e.writes:
            buf += struct.pack("<QBQ", cts, wt, sts)
        buf += struct.pack("<I", len(e.data))
        for sts, val in e.data.items():
            buf += struct.pack("<Q", sts)
            _pb(buf, val)
    return bytes(buf)


def _decode_entries(body: bytes):
    from .mvcc import Lock, _Entry
    mv = memoryview(body)
    (n_entries,) = struct.unpack_from("<I", mv, 0)
    off = 4
    entries = {}
    for _ in range(n_entries):
        key, off = _rb(mv, off)
        e = _Entry()
        has_lock = mv[off]
        off += 1
        if has_lock:
            start_ts, ttl_ms, op = struct.unpack_from("<QQB", mv, off)
            off += 17
            primary, off = _rb(mv, off)
            value, off = _rb(mv, off)
            e.lock = Lock(primary, start_ts, ttl_ms, op, value)
        (n_writes,) = struct.unpack_from("<I", mv, off)
        off += 4
        for _ in range(n_writes):
            cts, wt, sts = struct.unpack_from("<QBQ", mv, off)
            off += 17
            e.writes.append((cts, wt, sts))
        (n_data,) = struct.unpack_from("<I", mv, off)
        off += 4
        for _ in range(n_data):
            (sts,) = struct.unpack_from("<Q", mv, off)
            off += 8
            val, off = _rb(mv, off)
            e.data[sts] = val
        entries[key] = e
    return entries


class WriteAheadLog:
    """One store's journal + checkpoint lifecycle.  All appends happen
    inside the MVCC store's own RLock; this class's lock only guards the
    file descriptor against the explicit checkpoint/close entry points."""

    def __init__(self, data_dir: str,
                 fsync_policy: Optional[str] = None,
                 checkpoint_bytes: Optional[int] = None):
        self.data_dir = data_dir
        os.makedirs(data_dir, exist_ok=True)
        self.wal_path = os.path.join(data_dir, "wal.log")
        self.ckpt_path = os.path.join(data_dir, "checkpoint.bin")
        self.ckpt_tmp = os.path.join(data_dir, "checkpoint.tmp")
        policy = (fsync_policy
                  or os.environ.get("TINYSQL_WAL_FSYNC", "")
                  or "relaxed")
        self.set_fsync_policy(policy)
        self.checkpoint_bytes = int(
            checkpoint_bytes
            or os.environ.get("TINYSQL_WAL_CHECKPOINT_BYTES", 0)
            or DEFAULT_CHECKPOINT_BYTES)
        self._mu = threading.Lock()
        self._fd: Optional[int] = None
        self._lsn = 0                  # last lsn handed out
        self._ckpt_lsn = 0             # last lsn folded into checkpoint.bin
        self._wal_bytes = 0            # bytes in wal.log
        self._records_since_ckpt = 0
        self._unsynced = False
        self._last_fsync = 0.0
        self._torn = False             # a torn tail was written: poisoned
        self._closed = False

    # ---- policy ---------------------------------------------------------
    def set_fsync_policy(self, policy: str) -> None:
        p = str(policy).strip().lower()
        if p not in _FSYNC_POLICIES:
            raise ValueError(
                f"bad fsync policy {policy!r} (want off|relaxed|strict)")
        self.fsync_policy = p

    # ---- append path ----------------------------------------------------
    def _open_for_append(self) -> None:
        if self._fd is None:
            self._fd = os.open(self.wal_path,
                               os.O_CREAT | os.O_WRONLY | os.O_APPEND, 0o644)
            self._wal_bytes = os.fstat(self._fd).st_size
            _set("wal_size_bytes", self._wal_bytes)

    def append(self, rec_type: int, body: bytes) -> int:
        """Journal one record; returns its LSN.  Raises WalError without
        having written anything when the append cannot be made (the
        caller must NOT apply the mutation it was journaling)."""
        with self._mu:
            if self._closed:
                raise WalError("wal is closed")
            if self._torn:
                # a deliberately torn record is a crash boundary: the
                # in-memory store is ahead of a log that can no longer
                # be appended to coherently
                raise WalError("wal tail is torn; store must be recovered")
            try:
                failpoint.inject("walAppendError")
            except Exception as e:
                _bump("append_errors")
                raise WalError(f"wal append failed: {e}") from e
            self._open_for_append()
            self._lsn += 1
            payload = _REC.pack(self._lsn, rec_type) + body
            frame = _HDR.pack(len(payload),
                              zlib.crc32(payload) & 0xFFFFFFFF) + payload
            if failpoint.eval("walTornTail"):
                # model the crash's final torn write: half the frame
                # reaches the file, the rest never will
                os.write(self._fd, frame[:max(1, len(frame) // 2)])
                self._torn = True
                _bump("torn_writes")
                if self.fsync_policy == "strict":
                    # strict promises fsync-before-ack: a torn record
                    # means the fsync never returned, so no ack either
                    raise WalError("torn wal write before fsync ack")
                return self._lsn
            try:
                os.write(self._fd, frame)
            except OSError as e:
                _bump("append_errors")
                raise WalError(f"wal write failed: {e}") from e
            self._wal_bytes += len(frame)
            self._records_since_ckpt += 1
            self._unsynced = True
            _bump("appends")
            _bump("append_bytes", len(frame))
            _set("wal_size_bytes", self._wal_bytes)
            if rec_type in _COMMIT_CLASS:
                if self.fsync_policy == "strict":
                    self._fsync_locked()
                elif (self.fsync_policy == "relaxed"
                      and time.monotonic() - self._last_fsync
                      >= GROUP_COMMIT_S):
                    self._fsync_locked()
            return self._lsn

    def _fsync_locked(self) -> None:
        try:
            failpoint.inject("walFsyncError")
            t0 = time.monotonic()
            os.fsync(self._fd)
            _bump("fsyncs")
            _bump("fsync_s", time.monotonic() - t0)
        except Exception as e:
            _bump("fsync_errors")
            raise WalError(f"wal fsync failed: {e}") from e
        self._unsynced = False
        self._last_fsync = time.monotonic()

    def flush(self) -> None:
        """Fsync any unsynced tail (graceful-close / policy-boundary
        hook); a no-op when everything already hit the platter."""
        with self._mu:
            if self._fd is not None and self._unsynced and not self._torn:
                self._fsync_locked()

    # ---- typed journal entry points (called under the store's RLock) ----
    def log_prewrite(self, primary: bytes, start_ts: int, ttl_ms: int,
                     muts: List[Tuple[int, bytes, bytes]]) -> int:
        return self.append(REC_PREWRITE,
                           encode_prewrite(primary, start_ts, ttl_ms, muts))

    def log_commit(self, start_ts: int, commit_ts: int,
                   items: List[Tuple[bytes, int, bytes]]) -> int:
        return self.append(REC_COMMIT,
                           encode_commit(start_ts, commit_ts, items))

    def log_rollback(self, start_ts: int, keys: List[bytes]) -> int:
        return self.append(REC_ROLLBACK, encode_rollback(start_ts, keys))

    def log_resolve(self, key: bytes, start_ts: int, commit_ts: int,
                    wtype: int, value: bytes) -> int:
        return self.append(REC_RESOLVE,
                           encode_resolve(key, start_ts, commit_ts,
                                          wtype, value))

    def log_gc(self, safepoint_ts: int) -> int:
        return self.append(REC_GC, encode_gc(safepoint_ts))

    def log_backfill(self, ts: int, kvs: List[Tuple[bytes, bytes]]) -> int:
        return self.append(REC_BACKFILL, encode_backfill(ts, kvs))

    # ---- checkpoint ------------------------------------------------------
    def maybe_checkpoint(self, store) -> None:
        """Auto-trigger: fold the log once it outgrows the threshold.
        Called at the END of a mutator (never between a record and its
        apply — a checkpoint there would mark an unapplied LSN covered).
        Failures are counted, never raised: the old checkpoint + log
        remain the recovery source."""
        if self._wal_bytes < self.checkpoint_bytes or self._torn:
            return
        try:
            self.checkpoint(store)
        except CheckpointError:
            pass

    def checkpoint(self, store) -> None:
        """Serialize the full entry map (locks included), atomically
        replace checkpoint.bin, then rotate (truncate) the log.  The
        caller must be able to hold the store's RLock; a crash between
        rename and truncate is benign because replay skips records with
        lsn <= the checkpoint's last_lsn."""
        t0 = time.monotonic()
        try:
            failpoint.inject("checkpointError")
            with store._mu:
                with self._mu:
                    last_lsn = self._lsn
                    body = _encode_entries(store._entries)
                    payload = (struct.pack("<Q", last_lsn) + body)
                    blob = (_CKPT_MAGIC + payload
                            + struct.pack("<I",
                                          zlib.crc32(payload) & 0xFFFFFFFF))
                    fd = os.open(self.ckpt_tmp,
                                 os.O_CREAT | os.O_WRONLY | os.O_TRUNC,
                                 0o644)
                    try:
                        os.write(fd, blob)
                        os.fsync(fd)
                    finally:
                        os.close(fd)
                    os.replace(self.ckpt_tmp, self.ckpt_path)
                    self._fsync_dir()
                    # rotate: everything <= last_lsn now lives in the
                    # checkpoint
                    if self._fd is not None:
                        os.ftruncate(self._fd, 0)
                        os.fsync(self._fd)
                    else:
                        open(self.wal_path, "wb").close()
                    self._ckpt_lsn = last_lsn
                    self._wal_bytes = 0
                    self._records_since_ckpt = 0
                    self._unsynced = False
                    _set("wal_size_bytes", 0)
        except Exception as e:
            _bump("checkpoint_errors")
            raise CheckpointError(f"checkpoint failed: {e}") from e
        _bump("checkpoints")
        _bump("checkpoint_s", time.monotonic() - t0)

    def _fsync_dir(self) -> None:
        try:
            dfd = os.open(self.data_dir, os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        except OSError:
            pass  # directory fsync is best-effort on exotic filesystems

    def is_checkpoint_clean(self) -> bool:
        """True when every journaled record is folded into checkpoint.bin
        (the graceful-close postcondition)."""
        with self._mu:
            return (self._records_since_ckpt == 0
                    and os.path.exists(self.ckpt_path))

    # ---- recovery --------------------------------------------------------
    def recover_into(self, store) -> Dict[str, float]:
        """Rebuild ``store._entries`` from checkpoint + log; re-arm lock
        TTLs from restart time; fold the replayed state into a fresh
        checkpoint.  Returns a recovery-info dict."""
        from .oracle import extract_physical
        t0 = time.monotonic()
        ckpt_loaded, last_lsn = self._load_checkpoint(store)
        self._lsn = self._ckpt_lsn = last_lsn
        replayed, truncated = self._replay(store, last_lsn)
        # TTL re-arm: a recovered lock's expiry clock restarts NOW.  Its
        # start_ts (txn identity) cannot change, and is_expired() computes
        # from the start_ts's physical part — so extend the TTL by the
        # lock's pre-crash age instead.  Without this every recovered lock
        # is instantly expired and check_txn_status would unilaterally
        # roll back txns whose coordinator may still be alive.
        now_ms = int(time.time() * 1000)
        locks = 0
        for e in store._entries.values():
            if e.lock is not None:
                age_ms = max(0, now_ms - extract_physical(e.lock.start_ts))
                e.lock.ttl_ms += age_ms
                locks += 1
        store._dirty = True
        self._open_for_append()
        # fold what we just replayed so a restart loop cannot replay
        # unboundedly; a failure (checkpointError) is counted and the
        # unrotated log stays authoritative — recovery itself remains
        # crash-consistent at every instruction
        try:
            self.checkpoint(store)
        except CheckpointError:
            pass
        _bump("recoveries")
        _bump("replayed_records", replayed)
        _bump("recovered_locks", locks)
        if truncated:
            _bump("truncated_tails")
        return {"checkpoint_loaded": ckpt_loaded,
                "checkpoint_lsn": last_lsn,
                "replayed_records": replayed,
                "truncated_tail_bytes": truncated,
                "recovered_locks": locks,
                "entries": len(store._entries),
                "wall_s": time.monotonic() - t0}

    def _load_checkpoint(self, store) -> Tuple[bool, int]:
        try:
            os.unlink(self.ckpt_tmp)  # a half-written checkpoint is noise
        except OSError:
            pass
        if not os.path.exists(self.ckpt_path):
            return False, 0
        with open(self.ckpt_path, "rb") as f:
            blob = f.read()
        if (len(blob) < len(_CKPT_MAGIC) + 12
                or blob[:len(_CKPT_MAGIC)] != _CKPT_MAGIC):
            raise WalError(f"corrupt checkpoint header in {self.ckpt_path}")
        payload, (crc,) = blob[len(_CKPT_MAGIC):-4], struct.unpack(
            "<I", blob[-4:])
        if zlib.crc32(payload) & 0xFFFFFFFF != crc:
            raise WalError(f"checkpoint checksum mismatch in "
                           f"{self.ckpt_path}")
        (last_lsn,) = struct.unpack_from("<Q", payload, 0)
        store._entries = _decode_entries(payload[8:])
        return True, last_lsn

    def _replay(self, store, skip_upto_lsn: int) -> Tuple[int, int]:
        """Apply log records in order; truncate at the first torn/corrupt
        frame.  Returns (records applied, bytes truncated)."""
        if not os.path.exists(self.wal_path):
            return 0, 0
        replayed = 0
        with open(self.wal_path, "rb") as f:
            data = f.read()
        size = len(data)
        off = 0
        good_end = 0
        while off + _HDR.size <= size:
            plen, crc = _HDR.unpack_from(data, off)
            start = off + _HDR.size
            end = start + plen
            if plen < _REC.size or end > size:
                break  # torn length header or short final record
            payload = data[start:end]
            if zlib.crc32(payload) & 0xFFFFFFFF != crc:
                break  # torn/corrupt record: the tail stops here
            lsn, rtype = _REC.unpack_from(payload, 0)
            body = payload[_REC.size:]
            if lsn > skip_upto_lsn:
                self._apply(store, rtype, body)
                replayed += 1
            self._lsn = max(self._lsn, lsn)
            off = good_end = end
        truncated = size - good_end
        if truncated:
            with open(self.wal_path, "r+b") as f:
                f.truncate(good_end)
        self._wal_bytes = good_end
        _set("wal_size_bytes", good_end)
        return replayed, truncated

    @staticmethod
    def _apply(store, rtype: int, body: bytes) -> None:
        if rtype == REC_PREWRITE:
            store._replay_prewrite(*decode_prewrite(body))
        elif rtype == REC_COMMIT:
            store._replay_commit(*decode_commit(body))
        elif rtype == REC_ROLLBACK:
            store._replay_rollback(*decode_rollback(body))
        elif rtype == REC_RESOLVE:
            store._replay_resolve(*decode_resolve(body))
        elif rtype == REC_GC:
            store._replay_gc(decode_gc(body))
        elif rtype == REC_BACKFILL:
            store._replay_backfill(*decode_backfill(body))
        else:
            raise WalError(f"unknown wal record type {rtype}")

    # ---- lifecycle -------------------------------------------------------
    def records_since_checkpoint(self) -> int:
        with self._mu:
            return self._records_since_ckpt

    def close(self) -> None:
        with self._mu:
            if self._closed:
                return
            self._closed = True
            if self._fd is not None:
                if self._unsynced and not self._torn:
                    try:
                        self._fsync_locked()
                    except WalError:
                        pass
                try:
                    os.close(self._fd)
                except OSError:
                    pass
                self._fd = None
