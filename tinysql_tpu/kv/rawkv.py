"""Raw (non-transactional) KV client — reference: store/tikv/rawkv.go
(RawKVClient Get/BatchGet/Put/BatchPut/Delete/Scan over the raw column
family, region-routed with backoff retry, bypassing MVCC timestamps).

The raw keyspace lives beside the MVCC entries in the mock store (the
reference's raw CF beside the txn CFs); raw writes are immediately
visible — no locks, no commit point, no snapshot isolation.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from . import backoff as bo
from .backoff import Backoffer
from .errors import RegionError
from .rpc import RegionCache, RegionCtx, RPCClient


class RawStore:
    """The raw column family: a sorted plain keyspace on the storage
    node (no MVCC versions)."""

    def __init__(self):
        self._kv: Dict[bytes, bytes] = {}
        self._sorted: List[bytes] = []
        self._dirty = False
        self._mu = threading.RLock()

    def put(self, key: bytes, value: bytes) -> None:
        with self._mu:
            if key not in self._kv:
                self._dirty = True
            self._kv[key] = value

    def get(self, key: bytes) -> Optional[bytes]:
        with self._mu:
            return self._kv.get(key)

    def delete(self, key: bytes) -> None:
        with self._mu:
            if self._kv.pop(key, None) is not None:
                self._dirty = True

    def scan(self, start: bytes, end: bytes,
             limit: int) -> List[Tuple[bytes, bytes]]:
        with self._mu:
            if self._dirty:
                self._sorted = sorted(self._kv)
                self._dirty = False
            out = []
            import bisect
            i = bisect.bisect_left(self._sorted, start)
            while i < len(self._sorted) and len(out) < limit:
                k = self._sorted[i]
                if end and k >= end:
                    break
                out.append((k, self._kv[k]))
                i += 1
            return out


class RawKVClient:
    """Client side: region routing + typed backoff retry, same loop shape
    as the transactional client (rawkv.go:30-188)."""

    def __init__(self, client: RPCClient, cache: RegionCache):
        self.client = client
        self.cache = cache

    def _retry(self, key: bytes, fn):
        boer = Backoffer(bo.COP_NEXT_MAX_BACKOFF)
        while True:
            r = self.cache.locate_key(key)
            try:
                return fn(RegionCtx(r.id, r.epoch), r)
            except RegionError as e:
                self.cache.invalidate(r.id)
                boer.backoff(bo.BO_REGION_MISS, e)

    def get(self, key: bytes) -> Optional[bytes]:
        return self._retry(key, lambda ctx, _r:
                           self.client.raw_get(ctx, key))

    def put(self, key: bytes, value: bytes) -> None:
        self._retry(key, lambda ctx, _r:
                    self.client.raw_put(ctx, key, value))

    def delete(self, key: bytes) -> None:
        self._retry(key, lambda ctx, _r:
                    self.client.raw_delete(ctx, key))

    def batch_put(self, pairs: List[Tuple[bytes, bytes]]) -> None:
        """Group by region, one RPC per group (rawkv.go BatchPut)."""
        boer = Backoffer(bo.COP_NEXT_MAX_BACKOFF)
        pending = list(pairs)
        while pending:
            groups = self.cache.group_by_region(pending, lambda p: p[0])
            retry: List[Tuple[bytes, bytes]] = []
            for region, items in groups:
                try:
                    self.client.raw_batch_put(
                        RegionCtx(region.id, region.epoch), items)
                except RegionError as e:
                    self.cache.invalidate(region.id)
                    boer.backoff(bo.BO_REGION_MISS, e)
                    retry.extend(items)
            pending = retry

    def scan(self, start: bytes, end: bytes,
             limit: int = 1024) -> List[Tuple[bytes, bytes]]:
        """Cross-region scan: per-region RPCs stitched in key order."""
        out: List[Tuple[bytes, bytes]] = []
        cur = start
        boer = Backoffer(bo.COP_NEXT_MAX_BACKOFF)
        while len(out) < limit and (not end or cur < end or not cur):
            r = self.cache.locate_key(cur)
            sub_end = min(r.end, end) if (r.end and end) else (r.end or end)
            try:
                got = self.client.raw_scan(
                    RegionCtx(r.id, r.epoch), cur, sub_end,
                    limit - len(out))
            except RegionError as e:
                self.cache.invalidate(r.id)
                boer.backoff(bo.BO_REGION_MISS, e)
                continue
            out.extend(got)
            from .cluster import INF
            if not r.end or r.end >= INF:
                break  # last region (the cluster's end sentinel is INF)
            cur = r.end
            if end and cur >= end:
                break
        return out[:limit]
