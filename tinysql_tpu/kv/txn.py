"""Client-side transaction stack: snapshot reads, lock resolution,
Percolator two-phase commit.

Capability parity with reference store/tikv/: snapshot.go (point get w/
lock-encounter→resolve loop), scan.go, lock_resolver.go:37-335 (txn-status
check, secondary resolution, resolved-txn cache), 2pc.go (mutation
collection :115, primary selection :211, region-batched parallel
prewrite/commit/cleanup :247-543, undetermined-error tracking :417),
txn.go (commit entry).
"""
from __future__ import annotations

import threading
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from typing import Callable, Dict, Iterator, List, Optional, Tuple

from ..utils import failpoint
from . import backoff as bo
from .backoff import Backoffer
from .cluster import Region
from .errors import (BackoffExceeded, KeyExists, KeyIsLocked, KeyNotFound, KVError,
                     RegionError, TxnAborted, UndeterminedError, WriteConflict)
from .memdb import TOMBSTONE, MemDB, UnionStore
from .mvcc import Mutation, OP_DEL, OP_INSERT, OP_PUT
from .oracle import Oracle
from .rpc import RegionCache, RegionCtx, RPCClient

DEFAULT_LOCK_TTL_MS = 3000
MAX_TXN_ENTRIES = 300_000      # reference: kv/kv.go:99-103 size limits
COMMITTER_CONCURRENCY = 16     # reference: 2pc.go rate limit


class LockResolver:
    """reference: lock_resolver.go — decide a blocking txn's fate via its
    primary lock, then resolve the encountered lock."""

    def __init__(self, client: RPCClient, cache: RegionCache, oracle: Oracle,
                 storage=None):
        self.client = client
        self.cache = cache
        self.oracle = oracle
        self.storage = storage  # for columnar invalidation on resolve-commit
        self._resolved: Dict[int, int] = {}  # start_ts -> commit_ts (0=rolled back)
        self._mu = threading.Lock()

    def resolve(self, boer: Backoffer, lock: KeyIsLocked) -> bool:
        """Try to resolve; returns True if the caller may retry immediately,
        False if it must back off (lock still alive)."""
        with self._mu:
            known = self._resolved.get(lock.lock_ts)
        if known is None:
            expired = self.oracle.is_expired(lock.lock_ts, lock.ttl)
            try:
                commit_ts, committed = self._check_txn_status(
                    boer, lock.primary, lock.lock_ts, expired)
            except KeyIsLocked:
                return False  # primary lock alive; wait for TTL
            known = commit_ts if committed else 0
            with self._mu:
                self._resolved[lock.lock_ts] = known
                if len(self._resolved) > 4096:
                    self._resolved.pop(next(iter(self._resolved)))
        self._send_resolve(boer, lock.key, lock.lock_ts, known)
        if known > 0 and self.storage is not None:
            # resolving to COMMITTED makes a crashed writer's data visible:
            # invalidate that table's columnar replica (the crashed
            # committer never ran its own bump)
            from ..columnar.store import bump_table_version
            from ..codec.tablecodec import decode_table_id
            for k in (lock.key, lock.primary):
                if k[:1] == b"t" and len(k) >= 9:
                    try:
                        bump_table_version(self.storage, decode_table_id(k))
                    except ValueError:
                        pass
        return True

    def _check_txn_status(self, boer: Backoffer, primary: bytes,
                          lock_ts: int, expired: bool) -> Tuple[int, bool]:
        while True:
            r = self.cache.locate_key(primary)
            try:
                return self.client.kv_check_txn_status(
                    RegionCtx(r.id, r.epoch), primary, lock_ts, expired)
            except RegionError as e:
                self.cache.invalidate(r.id)
                boer.backoff(bo.BO_REGION_MISS, e)

    def _send_resolve(self, boer: Backoffer, key: bytes, start_ts: int,
                      commit_ts: int) -> None:
        while True:
            r = self.cache.locate_key(key)
            try:
                self.client.kv_resolve_lock(
                    RegionCtx(r.id, r.epoch), key, start_ts, commit_ts)
                return
            except RegionError as e:
                self.cache.invalidate(r.id)
                boer.backoff(bo.BO_REGION_MISS, e)


class Snapshot:
    """MVCC snapshot reads at a fixed ts (reference: snapshot.go:81-166)."""

    def __init__(self, storage: "TiKVStorage", ts: int):
        self.storage = storage
        self.ts = ts

    # -- point get -------------------------------------------------------
    def get(self, key: bytes) -> bytes:
        boer = Backoffer(bo.GET_MAX_BACKOFF)
        while True:
            r = self.storage.cache.locate_key(key)
            try:
                return self.storage.client.kv_get(
                    RegionCtx(r.id, r.epoch), key, self.ts)
            except RegionError as e:
                self.storage.cache.invalidate(r.id)
                boer.backoff(bo.BO_REGION_MISS, e)
            except KeyIsLocked as lk:
                if not self.storage.resolver.resolve(boer, lk):
                    boer.backoff(bo.BO_TXN_LOCK_FAST, lk)

    def batch_get(self, keys: List[bytes]) -> Dict[bytes, bytes]:
        """Region-batched point gets: O(regions) RPCs, not O(keys)
        (reference: snapshot.go BatchGet)."""
        out: Dict[bytes, bytes] = {}
        boer = Backoffer(bo.GET_MAX_BACKOFF)
        pending = list(dict.fromkeys(keys))
        while pending:
            retry: List[bytes] = []
            for r, ks in self.storage.cache.group_keys_by_region(pending):
                try:
                    for k, v in self.storage.client.kv_batch_get(
                            RegionCtx(r.id, r.epoch), ks, self.ts):
                        out[k] = v
                except RegionError as e:
                    self.storage.cache.invalidate(r.id)
                    boer.backoff(bo.BO_REGION_MISS, e)
                    retry.extend(ks)
                except KeyIsLocked as lk:
                    if not self.storage.resolver.resolve(boer, lk):
                        boer.backoff(bo.BO_TXN_LOCK_FAST, lk)
                    retry.extend(ks)
            pending = retry
        return out

    # -- range scan ------------------------------------------------------
    def iter_range(self, start: Optional[bytes],
                   end: Optional[bytes]) -> Iterator[Tuple[bytes, bytes]]:
        start = start or b""
        end = end if end is not None else b"\xff" * 64
        boer = Backoffer(bo.SCAN_MAX_BACKOFF)
        cur = start
        while cur < end:
            r = self.storage.cache.locate_key(cur)
            sub_end = min(end, r.end)
            try:
                batch = self.storage.client.kv_scan(
                    RegionCtx(r.id, r.epoch), cur, sub_end, self.ts)
            except RegionError as e:
                self.storage.cache.invalidate(r.id)
                boer.backoff(bo.BO_REGION_MISS, e)
                continue
            except KeyIsLocked as lk:
                if not self.storage.resolver.resolve(boer, lk):
                    boer.backoff(bo.BO_TXN_LOCK_FAST, lk)
                continue
            yield from batch
            cur = sub_end


class TwoPhaseCommitter:
    """reference: 2pc.go twoPhaseCommitter."""

    def __init__(self, txn: "Transaction"):
        self.txn = txn
        self.storage = txn.storage
        self.mutations: List[Mutation] = []
        self.start_ts = txn.start_ts
        self.commit_ts = 0
        self.primary: Optional[bytes] = None
        self.undetermined = False
        self._init_mutations()

    def _init_mutations(self) -> None:
        """Walk the membuffer (reference: 2pc.go:115 initKeysAndMutations)."""
        for k, v in self.txn.us.buffer.items():
            if v == TOMBSTONE:
                self.mutations.append(Mutation(OP_DEL, k))
            elif k in self.txn.presume_not_exists:
                self.mutations.append(Mutation(OP_INSERT, k, v))
            else:
                self.mutations.append(Mutation(OP_PUT, k, v))
        if len(self.mutations) > MAX_TXN_ENTRIES:
            raise KVError(f"transaction too large: {len(self.mutations)} entries")
        if self.mutations:
            # primary = first mutated key (reference: 2pc.go:211)
            self.primary = self.mutations[0].key

    # ---- region batching ------------------------------------------------
    def _group_mutations(self) -> List[Tuple[Region, List[Mutation]]]:
        return self.storage.cache.group_by_region(self.mutations,
                                                  lambda m: m.key)

    def _run_batches(self, action: Callable, batches, primary_first: bool) -> None:
        """Bounded-parallel per-region execution (reference: 2pc.go:672-721);
        the primary's batch runs first and alone — it is the durability
        point (reference: 2pc.go:429-500)."""
        if not batches:
            return
        if primary_first:
            prim = [b for b in batches
                    if any(self._key_of(x) == self.primary for x in b[1])]
            rest = [b for b in batches if b not in prim]
            for b in prim:
                action(b)
            batches = rest
        if not batches:
            return
        if len(batches) == 1:
            action(batches[0])
            return
        with ThreadPoolExecutor(max_workers=COMMITTER_CONCURRENCY,
                                thread_name_prefix="kv-commit") as ex:
            futures = [ex.submit(action, b) for b in batches]
            for f in futures:
                f.result()

    @staticmethod
    def _key_of(x) -> bytes:
        return x.key if isinstance(x, Mutation) else x

    # ---- phases ---------------------------------------------------------
    def prewrite(self) -> None:
        boer = Backoffer(bo.PREWRITE_MAX_BACKOFF)

        def one(batch: Tuple[Region, List[Mutation]]) -> None:
            r, muts = batch
            b = boer.fork()
            while True:
                try:
                    self.storage.client.kv_prewrite(
                        RegionCtx(r.id, r.epoch), muts, self.primary,
                        self.start_ts, DEFAULT_LOCK_TTL_MS)
                    return
                except RegionError as e:
                    self.storage.cache.invalidate(r.id)
                    b.backoff(bo.BO_REGION_MISS, e)
                    # re-split this batch by fresh regions
                    for sub in self._regroup(muts):
                        one(sub)
                    return
                except KeyIsLocked as lk:
                    if not self.storage.resolver.resolve(b, lk):
                        b.backoff(bo.BO_TXN_LOCK, lk)
                except KeyExists as ke:
                    raise self.txn.dup_info.get(ke.key, ke)

        self._run_batches(one, self._group_mutations(), primary_first=False)

    def _regroup(self, muts: List[Mutation]):
        return self.storage.cache.group_by_region(muts, lambda m: m.key)

    def commit_keys(self) -> None:
        keys = [m.key for m in self.mutations]
        groups = self.storage.cache.group_keys_by_region(keys)
        # NOT interruptible: the primary batch runs first, and once it
        # committed the txn is durable — a statement kill aborting a
        # secondary retry here would report 1317 for a COMMITTED txn and
        # skip the columnar invalidation (kills land before/after the
        # commit phase instead, via the executor checks and prewrite)
        boer = Backoffer(bo.COMMIT_MAX_BACKOFF, interruptible=False)

        def one(batch: Tuple[Region, List[bytes]]) -> None:
            r, ks = batch
            b = boer.fork()
            is_primary = self.primary in ks
            while True:
                try:
                    failpoint.inject("commitPrimaryError" if is_primary
                                     else "commitSecondaryError")
                    self.storage.client.kv_commit(
                        RegionCtx(r.id, r.epoch), ks, self.start_ts, self.commit_ts)
                    return
                except RegionError as e:
                    self.storage.cache.invalidate(r.id)
                    try:
                        b.backoff(bo.BO_REGION_MISS, e)
                    except BackoffExceeded:
                        if is_primary:
                            self.undetermined = True
                        raise
                    for sub in self.storage.cache.group_keys_by_region(ks):
                        one(sub)
                    return
                except TxnAborted:
                    raise
                except Exception as e:
                    if is_primary:
                        # commit RPC failure on the primary = outcome unknown
                        # (reference: 2pc.go:417-428)
                        self.undetermined = True
                        raise UndeterminedError(str(e)) from e
                    # secondary failures are tolerated: the txn is durable
                    # once the primary committed; leftover locks are resolved
                    # by later readers (reference: 2pc.go commits secondaries
                    # async and drops errors)
                    return

        self._run_batches(one, groups, primary_first=True)

    def cleanup(self) -> None:
        """Async rollback on failure (reference: 2pc.go cleanupKeys)."""
        keys = [m.key for m in self.mutations]
        try:
            for r, ks in self.storage.cache.group_keys_by_region(keys):
                try:
                    self.storage.client.kv_rollback(
                        RegionCtx(r.id, r.epoch), ks, self.start_ts)
                except RegionError:
                    self.storage.cache.invalidate(r.id)
        except Exception:
            pass  # best-effort; lock TTL + resolver recover the rest

    def execute(self) -> None:
        """reference: 2pc.go:545 execute."""
        if not self.mutations:
            return
        committed = False
        try:
            self.prewrite()
            # schema re-check at the COMMIT timestamp, before the point of
            # no return (2pc.go:633): a DDL landing between prewrite and
            # commit_ts logically precedes this txn and must abort it
            self.commit_ts = self.storage.oracle.get_timestamp()
            if self.txn.schema_check is not None:
                self.txn.schema_check(self.commit_ts)
            failpoint.inject("beforeCommit")
            self.commit_keys()
            committed = True
        finally:
            if committed or self.undetermined:
                # undetermined: the primary may have committed (the resolver
                # will finish the job) — invalidating is safe either way,
                # NOT invalidating would leave a stale columnar replica
                self._bump_columnar_versions()
            else:
                self.cleanup()

    def _bump_columnar_versions(self) -> None:
        """Invalidate columnar replicas of every table this txn wrote
        (columnar/store.py data-version protocol)."""
        from ..columnar.store import bump_table_version
        tids = set()
        for m in self.mutations:
            if m.key[:1] == b"t" and len(m.key) >= 9:
                try:
                    from ..codec.tablecodec import decode_table_id
                    tids.add(decode_table_id(m.key))
                except ValueError:
                    pass
        for tid in tids:
            bump_table_version(self.storage, tid)


class Transaction:
    """reference: store/tikv/txn.go tikvTxn + kv.Transaction iface
    (kv/kv.go:105-310)."""

    def __init__(self, storage: "TiKVStorage", start_ts: int):
        self.storage = storage
        self.start_ts = start_ts
        self.snapshot = Snapshot(storage, start_ts)
        self.us = UnionStore(self.snapshot)
        self.presume_not_exists: set = set()
        # key -> exception to raise on duplicate, so the SQL layer's
        # dup-entry message survives the 2PC hop (reference: executor
        # extractKeyErr decodes the key; we carry the error instead)
        self.dup_info: Dict[bytes, Exception] = {}
        self.valid = True
        self.schema_check: Optional[Callable[[int], None]] = None
        self.commit_ts = 0
        # table_id -> net row delta this txn; applied to the live stats
        # count at commit (reference: mysql.stats_meta modify/count deltas
        # flushed by the session stats collector)
        self.stats_delta: Dict[int, int] = {}

    # -- reads ------------------------------------------------------------
    def get(self, key: bytes) -> bytes:
        return self.us.get(key)

    def batch_get(self, keys: List[bytes]) -> Dict[bytes, bytes]:
        """Buffer-aware batch get: buffered values shadow the snapshot;
        the rest go through the region-batched snapshot path."""
        out: Dict[bytes, bytes] = {}
        missing: List[bytes] = []
        for k in keys:
            v = self.us.buffer.get(k)
            if v is None:
                missing.append(k)
            elif v != TOMBSTONE:
                out[k] = v
        out.update(self.snapshot.batch_get(missing))
        return out

    def iter_range(self, start: Optional[bytes],
                   end: Optional[bytes]) -> Iterator[Tuple[bytes, bytes]]:
        return self.us.iter_range(start, end)

    # -- writes -----------------------------------------------------------
    def set(self, key: bytes, value: bytes) -> None:
        self.us.set(key, value)

    def insert(self, key: bytes, value: bytes,
               dup_err: Optional[Exception] = None) -> None:
        """Set with not-exists presumption — prewrite enforces uniqueness
        (reference: kv.PresumeKeyNotExists option).  Duplicates within this
        txn's own buffer are caught immediately."""
        buffered = self.us.buffer.get(key)
        if buffered not in (None, TOMBSTONE):
            raise dup_err if dup_err is not None else KeyExists(key)
        self.us.set(key, value)
        if buffered == TOMBSTONE:
            # delete-then-insert in one txn: the key existed before, so no
            # not-exists presumption — prewrite must treat it as a plain PUT
            self.presume_not_exists.discard(key)
            self.dup_info.pop(key, None)
            return
        self.presume_not_exists.add(key)
        if dup_err is not None:
            self.dup_info[key] = dup_err

    def delete(self, key: bytes) -> None:
        self.us.delete(key)

    def is_readonly(self) -> bool:
        return len(self.us.buffer) == 0

    # -- statement-level rollback (reference: session/txn.go StmtCommit /
    # StmtRollback over the membuffer) ------------------------------------
    def checkpoint(self) -> tuple:
        return (dict(self.us.buffer._m), set(self.presume_not_exists),
                dict(self.dup_info), dict(self.stats_delta))

    def restore(self, cp: tuple) -> None:
        m, pne, dup, sd = cp
        self.stats_delta = dict(sd)
        self.us.buffer._m = dict(m)
        self.us.buffer._dirty = True
        self.presume_not_exists = set(pne)
        self.dup_info = dict(dup)

    def size(self) -> int:
        return len(self.us.buffer)

    # -- lifecycle ---------------------------------------------------------
    def commit(self) -> None:
        if not self.valid:
            raise KVError("commit on invalid txn")
        self.valid = False
        committer = TwoPhaseCommitter(self)
        committer.execute()
        self.commit_ts = committer.commit_ts

    def rollback(self) -> None:
        self.valid = False


def resolve_data_dir(data_dir: Optional[str]) -> str:
    """Durability arming chain: explicit arg > TINYSQL_DATA_DIR env >
    config.data_dir; empty everywhere = today's volatile store."""
    if data_dir is not None:
        return data_dir
    import os
    env = os.environ.get("TINYSQL_DATA_DIR", "")
    if env:
        return env
    from .. import config as cfgmod
    return getattr(cfgmod.get_global_config(), "data_dir", "") or ""


class TiKVStorage:
    """Storage facade: cluster + mvcc + oracle + client + caches
    (reference: store/tikv/kv.go tikvStore + store/mockstore driver).

    With a ``data_dir`` the MVCC store journals to a WAL and recovers on
    construction (kv/wal.py); the oracle is then fenced past every
    recovered timestamp so restart loops cannot mint colliding or
    backwards timestamps."""

    def __init__(self, num_stores: int = 1,
                 data_dir: Optional[str] = None):
        from .cluster import Cluster
        from .mvcc import MVCCStore
        self.data_dir = resolve_data_dir(data_dir)
        self.cluster = Cluster()
        self.cluster.bootstrap(num_stores)
        self.mvcc = MVCCStore(self.data_dir or None)
        self.client = RPCClient(self.cluster, self.mvcc)
        self.cache = RegionCache(self.cluster)
        self.oracle = Oracle()
        if self.mvcc.recovery_info is not None:
            self.oracle.ensure_after(self.mvcc.max_known_ts())
        self.resolver = LockResolver(self.client, self.cache, self.oracle,
                                     storage=self)
        from ..distsql.copr import make_cop_handler
        self.client.cop_handler = make_cop_handler(self.mvcc)
        self._gc_last = 0.0

    def begin(self, start_ts: Optional[int] = None) -> Transaction:
        if start_ts is None:
            start_ts = self.oracle.get_timestamp()
        return Transaction(self, start_ts)

    def get_snapshot(self, ts: Optional[int] = None) -> Snapshot:
        if ts is None:
            ts = self.oracle.get_timestamp()
        return Snapshot(self, ts)

    def current_version(self) -> int:
        return self.oracle.get_timestamp()

    # ---- durability lifecycle -------------------------------------------
    def flush_and_checkpoint(self) -> None:
        """Fsync the WAL tail and fold it into a fresh checkpoint — the
        graceful-close hook (both wire modes route through here).  No-op
        on a volatile store; raises CheckpointError on a failed attempt
        (the unrotated log stays authoritative)."""
        wal = self.mvcc.wal
        if wal is None:
            return
        wal.flush()
        wal.checkpoint(self.mvcc)

    def close(self) -> None:
        """Full shutdown: checkpoint (best effort) and close the WAL."""
        wal = self.mvcc.wal
        if wal is None:
            return
        try:
            self.flush_and_checkpoint()
        except KVError:
            pass
        wal.close()

    # ---- gc safepoint trigger (satellite of the durability story) -------
    def run_gc(self, safepoint_ts: int) -> int:
        """Journal + apply one GC pass at an explicit safepoint."""
        from .wal import _bump
        removed = self.mvcc.gc(safepoint_ts)
        _bump("gc_runs")
        _bump("gc_removed", removed)
        return removed

    def maybe_run_gc(self, retention_s: float,
                     force: bool = False) -> int:
        """The `tidb_gc_safepoint` sysvar's trigger (domain owner loop):
        GC versions older than ``retention_s`` seconds, self-paced to at
        most one pass per half-retention (floor 1s).  retention<=0 =
        disabled."""
        import time as _time
        try:
            retention_s = float(retention_s)
        except (TypeError, ValueError):
            return 0
        if retention_s <= 0:
            return 0
        now = _time.time()
        if not force and now - self._gc_last < max(1.0, retention_s / 2):
            return 0
        self._gc_last = now
        from .oracle import compose_ts
        safepoint = compose_ts(int((now - retention_s) * 1000), 0)
        return self.run_gc(safepoint)


def new_mock_storage(num_stores: int = 1,
                     data_dir: Optional[str] = None) -> TiKVStorage:
    """reference: store/mockstore/tikv.go NewMockTikvStore."""
    return TiKVStorage(num_stores, data_dir=data_dir)
