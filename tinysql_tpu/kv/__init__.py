"""Transactional KV layer (reference: kv/, store/tikv/, store/mockstore/)."""
from .errors import (KVError, KeyNotFound, KeyExists, KeyIsLocked,
                     WriteConflict, TxnAborted, RetryableError, RegionError,
                     BackoffExceeded, UndeterminedError, SchemaOutdated,
                     WalError, CheckpointError)
from .wal import WriteAheadLog
from .oracle import Oracle
from .memdb import MemDB, UnionStore, TOMBSTONE
from .mvcc import MVCCStore, Mutation, OP_PUT, OP_DEL, OP_INSERT
from .cluster import Cluster, Region, Store
from .rpc import RPCClient, RegionCache, RegionCtx
from .backoff import Backoffer
from .txn import (Transaction, Snapshot, TwoPhaseCommitter, LockResolver,
                  TiKVStorage, new_mock_storage)
from .rawkv import RawKVClient, RawStore
from .range_task import RangeTaskRunner, RangeTaskStat

__all__ = [
    "KVError", "KeyNotFound", "KeyExists", "KeyIsLocked", "WriteConflict",
    "TxnAborted", "RetryableError", "RegionError", "BackoffExceeded",
    "UndeterminedError", "SchemaOutdated", "WalError", "CheckpointError",
    "WriteAheadLog",
    "Oracle", "MemDB", "UnionStore", "TOMBSTONE",
    "MVCCStore", "Mutation", "OP_PUT", "OP_DEL", "OP_INSERT",
    "Cluster", "Region", "Store", "RPCClient", "RegionCache", "RegionCtx",
    "Backoffer", "Transaction", "Snapshot", "TwoPhaseCommitter",
    "LockResolver", "TiKVStorage", "new_mock_storage",
    "RawKVClient", "RawStore", "RangeTaskRunner", "RangeTaskStat",
]
