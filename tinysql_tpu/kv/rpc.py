"""In-process RPC layer: region-validated dispatch to the MVCC store.

Capability parity with reference store/mockstore/mocktikv/rpc.go:351-550
(simulated region errors — epoch-not-match, region-not-found, store-down —
before dispatching kv/cop requests) + store/tikv/region_cache.go +
region_request.go (client-side routing cache with invalidation and retry).
The "network" is a function call; everything else — routing, staleness,
partitioning — is real.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ..utils import failpoint
from .cluster import Cluster, Region
from .errors import KeyNotFound, RegionError
from .mvcc import MVCCStore, Mutation


@dataclass(frozen=True)
class RegionCtx:
    region_id: int
    epoch: int


class RPCClient:
    """Server side of the fake wire: validates the caller's region view
    against the live topology, then executes against the MVCC store."""

    def __init__(self, cluster: Cluster, store: MVCCStore):
        self.cluster = cluster
        self.mvcc = store
        self.cop_handler = None  # installed by distsql layer
        self._raw_mu = threading.Lock()  # guards the lazy _raw attach

    # ---- validation ----------------------------------------------------
    def _check(self, ctx: RegionCtx, keys: List[bytes] = (),
               ranges: List[Tuple[bytes, bytes]] = ()) -> Region:
        if failpoint.eval("rpcServerBusy"):
            raise RegionError("server_busy", ctx.region_id)
        r = self.cluster.get_region_by_id(ctx.region_id)
        if r is None:
            raise RegionError("region_not_found", ctx.region_id)
        st = self.cluster.stores.get(r.store_id)
        if st is None or not st.up:
            raise RegionError("store_down", ctx.region_id)
        if st.cancelled:
            raise RegionError("store_cancelled", ctx.region_id)
        self.cluster.maybe_delay(r.store_id)
        if r.epoch != ctx.epoch:
            raise RegionError("epoch_not_match", ctx.region_id)
        for k in keys:
            if not r.contains(k):
                raise RegionError("key_not_in_region", ctx.region_id)
        for s, e in ranges:
            if s < r.start or (e > r.end):
                raise RegionError("range_not_in_region", ctx.region_id)
        return r

    # ---- kv commands ----------------------------------------------------
    def kv_get(self, ctx: RegionCtx, key: bytes, ts: int,
               resolved: Tuple[int, ...] = ()) -> bytes:
        self._check(ctx, keys=[key])
        return self.mvcc.get(key, ts, resolved)

    def kv_batch_get(self, ctx: RegionCtx, keys: List[bytes], ts: int,
                     resolved: Tuple[int, ...] = ()) -> List[Tuple[bytes, bytes]]:
        """Region-batched point gets (reference: kvrpcpb BatchGet)."""
        self._check(ctx, keys=keys)
        out = []
        for k in keys:
            try:
                out.append((k, self.mvcc.get(k, ts, resolved)))
            except KeyNotFound:
                pass
        return out

    def kv_scan(self, ctx: RegionCtx, start: bytes, end: bytes, ts: int,
                limit: int = 0,
                resolved: Tuple[int, ...] = ()) -> List[Tuple[bytes, bytes]]:
        r = self._check(ctx)
        s = max(start, r.start)
        e = min(end, r.end) if end else r.end
        return self.mvcc.scan(s, e, ts, limit, resolved)

    def kv_prewrite(self, ctx: RegionCtx, mutations: List[Mutation],
                    primary: bytes, start_ts: int, ttl_ms: int) -> None:
        failpoint.inject("prewriteError")
        self._check(ctx, keys=[m.key for m in mutations])
        self.mvcc.prewrite(mutations, primary, start_ts, ttl_ms)

    def kv_commit(self, ctx: RegionCtx, keys: List[bytes], start_ts: int,
                  commit_ts: int) -> None:
        failpoint.inject("commitError")
        self._check(ctx, keys=keys)
        self.mvcc.commit(keys, start_ts, commit_ts)

    def kv_rollback(self, ctx: RegionCtx, keys: List[bytes], start_ts: int) -> None:
        self._check(ctx, keys=keys)
        self.mvcc.rollback(keys, start_ts)

    def kv_check_txn_status(self, ctx: RegionCtx, primary: bytes,
                            lock_ts: int, expired: bool) -> Tuple[int, bool]:
        self._check(ctx, keys=[primary])
        return self.mvcc.check_txn_status(primary, lock_ts, expired)

    def kv_resolve_lock(self, ctx: RegionCtx, key: bytes, start_ts: int,
                        commit_ts: int) -> None:
        self._check(ctx, keys=[key])
        self.mvcc.resolve_lock(key, start_ts, commit_ts)

    def coprocessor(self, ctx: RegionCtx, req) -> bytes:
        r = self._check(ctx)
        if self.cop_handler is None:
            raise RuntimeError("no coprocessor handler installed")
        return self.cop_handler(r, req)

    # ---- raw commands (non-transactional CF; reference rawkv.go) -------
    @property
    def raw(self):
        """Lazily-attached raw column family (rawkv.RawStore).  The
        attach is locked: connection threads share one RPCClient, and
        two racing first-touches would each build a RawStore — one
        thread's raw writes silently vanishing with its loser copy."""
        rs = getattr(self, "_raw", None)
        if rs is None:
            from .rawkv import RawStore
            with self._raw_mu:
                rs = getattr(self, "_raw", None)
                if rs is None:
                    rs = self._raw = RawStore()
        return rs

    def raw_get(self, ctx: RegionCtx, key: bytes):
        self._check(ctx, keys=[key])
        return self.raw.get(key)

    def raw_put(self, ctx: RegionCtx, key: bytes, value: bytes) -> None:
        self._check(ctx, keys=[key])
        self.raw.put(key, value)

    def raw_delete(self, ctx: RegionCtx, key: bytes) -> None:
        self._check(ctx, keys=[key])
        self.raw.delete(key)

    def raw_batch_put(self, ctx: RegionCtx, pairs) -> None:
        self._check(ctx, keys=[k for k, _ in pairs])
        for k, v in pairs:
            self.raw.put(k, v)

    def raw_scan(self, ctx: RegionCtx, start: bytes, end: bytes,
                 limit: int):
        r = self._check(ctx)
        s = max(start, r.start) if r.start else start
        e = min(end, r.end) if (end and r.end) else (end or r.end)
        return self.raw.scan(s, e, limit)


class RegionCache:
    """Client-side key->region routing cache with invalidation
    (reference: region_cache.go:167-267)."""

    def __init__(self, cluster: Cluster):
        self.cluster = cluster  # stands in for PD
        self._mu = threading.Lock()
        self._by_id: Dict[int, Region] = {}

    def locate_key(self, key: bytes) -> Region:
        with self._mu:
            for r in self._by_id.values():
                if r.contains(key):
                    return r
        r = self.cluster.locate(key)  # "PD" lookup
        with self._mu:
            self._by_id[r.id] = r
        return r

    def invalidate(self, region_id: int) -> None:
        with self._mu:
            self._by_id.pop(region_id, None)

    def invalidate_all(self) -> None:
        with self._mu:
            self._by_id.clear()

    def group_by_region(self, items, key_fn) -> List[Tuple[Region, list]]:
        """Generic locate-and-group (reference: 2pc.go GroupKeysByRegion) —
        single implementation shared by prewrite/commit/batch-get paths."""
        groups: Dict[int, Tuple[Region, list]] = {}
        for item in sorted(items, key=key_fn):
            r = self.locate_key(key_fn(item))
            groups.setdefault(r.id, (r, []))[1].append(item)
        return list(groups.values())

    def group_keys_by_region(self, keys: List[bytes]) -> List[Tuple[Region, List[bytes]]]:
        return self.group_by_region(keys, lambda k: k)

    def split_range_by_regions(self, start: bytes, end: bytes) -> List[Tuple[Region, bytes, bytes]]:
        """Split [start,end) into per-region subranges (reference:
        coprocessor.go:204 buildCopTasks)."""
        out: List[Tuple[Region, bytes, bytes]] = []
        cur = start
        while cur < end:
            r = self.locate_key(cur)
            sub_end = min(end, r.end)
            out.append((r, cur, sub_end))
            cur = sub_end
        return out
