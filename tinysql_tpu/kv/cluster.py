"""In-memory cluster topology simulator: regions, stores, chaos hooks.

Capability parity with reference store/mockstore/mocktikv/cluster.go:40-353:
Bootstrap, AllocID, Split/Merge, StopStore/CancelStore (partition simulation),
request delay injection.  Regions shard the keyspace exactly as TinyKV's do;
on TPU they are the unit that maps to mesh shards (SURVEY §2.6 note).
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


@dataclass
class Region:
    id: int
    start: bytes              # inclusive; b"" = -inf
    end: bytes                # exclusive; b"\xff"*64 sentinel = +inf
    epoch: int
    store_id: int

    def contains(self, key: bytes) -> bool:
        return self.start <= key and (key < self.end)


INF = b"\xff" * 64


@dataclass
class Store:
    id: int
    up: bool = True
    cancelled: bool = False   # requests dropped silently (timeout)
    delay_ms: float = 0.0


class Cluster:
    def __init__(self):
        self._mu = threading.RLock()
        self._id = 0
        self.stores: Dict[int, Store] = {}
        self.regions: List[Region] = []

    # ---- bootstrap / ids ----------------------------------------------
    def alloc_id(self) -> int:
        with self._mu:
            self._id += 1
            return self._id

    def bootstrap(self, num_stores: int = 1) -> None:
        with self._mu:
            for _ in range(num_stores):
                sid = self.alloc_id()
                self.stores[sid] = Store(sid)
            first = list(self.stores)[0]
            self.regions = [Region(self.alloc_id(), b"", INF, 1, first)]

    # ---- lookup --------------------------------------------------------
    def locate(self, key: bytes) -> Region:
        with self._mu:
            for r in self.regions:
                if r.contains(key):
                    return Region(r.id, r.start, r.end, r.epoch, r.store_id)
            raise RuntimeError(f"no region for key {key!r}")

    def get_region_by_id(self, rid: int) -> Optional[Region]:
        with self._mu:
            for r in self.regions:
                if r.id == rid:
                    return Region(r.id, r.start, r.end, r.epoch, r.store_id)
            return None

    def all_regions(self) -> List[Region]:
        with self._mu:
            return [Region(r.id, r.start, r.end, r.epoch, r.store_id)
                    for r in sorted(self.regions, key=lambda r: r.start)]

    # ---- topology changes ----------------------------------------------
    def split(self, split_key: bytes) -> None:
        """Split the region containing split_key (reference: cluster.go Split)."""
        with self._mu:
            for i, r in enumerate(self.regions):
                if r.contains(split_key) and r.start != split_key:
                    new = Region(self.alloc_id(), split_key, r.end,
                                 1, r.store_id)
                    r.end = split_key
                    r.epoch += 1
                    self.regions.insert(i + 1, new)
                    return

    def split_table(self, table_id: int) -> None:
        from ..codec import tablecodec
        self.split(tablecodec.encode_table_prefix(table_id))

    def split_keys(self, keys: List[bytes]) -> None:
        for k in keys:
            self.split(k)

    def merge(self, rid_a: int, rid_b: int) -> None:
        with self._mu:
            a = next(r for r in self.regions if r.id == rid_a)
            b = next(r for r in self.regions if r.id == rid_b)
            if a.end != b.start:
                raise RuntimeError("regions not adjacent")
            a.end = b.end
            a.epoch += 1
            self.regions.remove(b)

    def move_region(self, rid: int, store_id: int) -> None:
        with self._mu:
            r = next(x for x in self.regions if x.id == rid)
            r.store_id = store_id
            r.epoch += 1

    # ---- chaos ---------------------------------------------------------
    def stop_store(self, sid: int) -> None:
        with self._mu:
            self.stores[sid].up = False

    def start_store(self, sid: int) -> None:
        with self._mu:
            self.stores[sid].up = True
            self.stores[sid].cancelled = False

    def cancel_store(self, sid: int) -> None:
        with self._mu:
            self.stores[sid].cancelled = True

    def set_delay(self, sid: int, ms: float) -> None:
        with self._mu:
            self.stores[sid].delay_ms = ms

    def maybe_delay(self, sid: int) -> None:
        with self._mu:
            d = self.stores[sid].delay_ms if sid in self.stores else 0
        if d:
            time.sleep(d / 1000.0)  # qlint: disable=FP501 -- the injected store latency IS the simulated fault, not a retry sleep
