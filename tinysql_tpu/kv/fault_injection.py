"""Fault-injecting storage wrapper (reference: kv/fault_injection.go:1-124
— InjectionConfig + InjectedStore/InjectedTransaction: configured errors
surface from Begin/Get/Commit without touching the underlying store)."""
from __future__ import annotations

import threading
from typing import Optional


class InjectionConfig:
    def __init__(self):
        self._mu = threading.Lock()
        self._begin_err: Optional[Exception] = None
        self._get_err: Optional[Exception] = None
        self._commit_err: Optional[Exception] = None

    def set_begin_error(self, err: Optional[Exception]) -> None:
        with self._mu:
            self._begin_err = err

    def set_get_error(self, err: Optional[Exception]) -> None:
        with self._mu:
            self._get_err = err

    def set_commit_error(self, err: Optional[Exception]) -> None:
        with self._mu:
            self._commit_err = err

    @property
    def begin_err(self):
        with self._mu:
            return self._begin_err

    @property
    def get_err(self):
        with self._mu:
            return self._get_err

    @property
    def commit_err(self):
        with self._mu:
            return self._commit_err


class InjectedTransaction:
    """Delegates to the real transaction, layering configured failures."""

    def __init__(self, txn, cfg: InjectionConfig):
        self._txn = txn
        self._cfg = cfg

    def get(self, key: bytes) -> bytes:
        err = self._cfg.get_err
        if err is not None:
            raise err
        return self._txn.get(key)

    def commit(self) -> None:
        err = self._cfg.commit_err
        if err is not None:
            raise err
        self._txn.commit()

    def __getattr__(self, name):
        return getattr(self._txn, name)


class InjectedSnapshot:
    """Snapshot wrapper: injected get errors cover the snapshot/coprocessor
    read path too (reference wraps snapshots as well)."""

    def __init__(self, snap, cfg: InjectionConfig):
        self._snap = snap
        self._cfg = cfg

    def get(self, key: bytes) -> bytes:
        err = self._cfg.get_err
        if err is not None:
            raise err
        return self._snap.get(key)

    def iter_range(self, start, end):
        err = self._cfg.get_err
        if err is not None:
            raise err
        return self._snap.iter_range(start, end)

    def __getattr__(self, name):
        return getattr(self._snap, name)


class InjectedStorage:
    """Storage facade wrapper (reference: InjectedStore)."""

    def __init__(self, storage, cfg: InjectionConfig):
        self._storage = storage
        self._cfg = cfg

    def begin(self, start_ts=None):
        err = self._cfg.begin_err
        if err is not None:
            raise err
        return InjectedTransaction(self._storage.begin(start_ts), self._cfg)

    def get_snapshot(self, ts=None):
        return InjectedSnapshot(self._storage.get_snapshot(ts), self._cfg)

    def __getattr__(self, name):
        return getattr(self._storage, name)
