"""Sorted in-memory write buffer.

Capability parity with reference kv/memdb (skiplist-in-arena membuffer,
memdb.go:28-296) + BufferStore/UnionStore (buffer_store.go, union_store.go):
a transaction's uncommitted writes, ordered, merged over a snapshot on read.
Python build: dict + lazily-sorted key list (teaching-scale data; the hot
read path is columnar/TPU, not this buffer).
"""
from __future__ import annotations

import bisect
from typing import Dict, Iterator, List, Optional, Tuple

from .errors import KeyNotFound

TOMBSTONE = b""  # empty value marks deletion inside a txn buffer


class MemDB:
    """Ordered key-value buffer; empty value = delete marker."""

    def __init__(self):
        self._m: Dict[bytes, bytes] = {}
        self._sorted: List[bytes] = []
        self._dirty = False

    def __len__(self) -> int:
        return len(self._m)

    def set(self, key: bytes, value: bytes) -> None:
        if key not in self._m:
            self._dirty = True
        self._m[key] = value

    def delete(self, key: bytes) -> None:
        self.set(key, TOMBSTONE)

    def get(self, key: bytes) -> Optional[bytes]:
        """Returns the buffered value; TOMBSTONE if deleted; None if absent."""
        return self._m.get(key)

    def _keys(self) -> List[bytes]:
        if self._dirty:
            self._sorted = sorted(self._m)
            self._dirty = False
        return self._sorted

    def iter_range(self, start: Optional[bytes] = None,
                   end: Optional[bytes] = None) -> Iterator[Tuple[bytes, bytes]]:
        ks = self._keys()
        i = bisect.bisect_left(ks, start) if start is not None else 0
        while i < len(ks):
            k = ks[i]
            if end is not None and k >= end:
                return
            yield k, self._m[k]
            i += 1

    def items(self) -> Iterator[Tuple[bytes, bytes]]:
        return self.iter_range()


class UnionStore:
    """Txn membuffer over a snapshot (reference: kv/union_store.go): reads
    check the buffer first; range scans merge the two ordered sources."""

    def __init__(self, snapshot):
        self.buffer = MemDB()
        self.snapshot = snapshot

    def get(self, key: bytes) -> bytes:
        v = self.buffer.get(key)
        if v is not None:
            if v == TOMBSTONE:
                raise KeyNotFound(key)
            return v
        return self.snapshot.get(key)

    def set(self, key: bytes, value: bytes) -> None:
        if value == TOMBSTONE:
            raise ValueError("empty values are reserved as delete markers")
        self.buffer.set(key, value)

    def delete(self, key: bytes) -> None:
        self.buffer.delete(key)

    def iter_range(self, start: Optional[bytes],
                   end: Optional[bytes]) -> Iterator[Tuple[bytes, bytes]]:
        """Two-source ordered merge (reference: kv/union_iter.go)."""
        buf = self.buffer.iter_range(start, end)
        snap = self.snapshot.iter_range(start, end)
        bk = next(buf, None)
        sk = next(snap, None)
        while bk is not None or sk is not None:
            if sk is None or (bk is not None and bk[0] <= sk[0]):
                if sk is not None and bk[0] == sk[0]:
                    sk = next(snap, None)  # buffer shadows snapshot
                if bk[1] != TOMBSTONE:
                    yield bk
                bk = next(buf, None)
            else:
                yield sk
                sk = next(snap, None)
