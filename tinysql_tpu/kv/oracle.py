"""Timestamp oracle (TSO).

Capability parity with reference store/tikv/oracle: PD-backed TSO with async
futures (oracle/oracles/pd.go) and a local oracle for tests (local.go,
mockoracle).  Timestamps are hybrid: physical_ms << 18 | logical, so they
are globally ordered and roughly wall-clock-meaningful.
"""
from __future__ import annotations

import threading
import time

PHYSICAL_SHIFT = 18


def compose_ts(physical_ms: int, logical: int) -> int:
    return (physical_ms << PHYSICAL_SHIFT) + logical


def extract_physical(ts: int) -> int:
    return ts >> PHYSICAL_SHIFT


class Oracle:
    """Monotonic TSO — the host-side central sequencing service that replaces
    PD in the single-process build (SURVEY §2.6 wire-surface note)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._last_physical = 0
        self._logical = 0

    def get_timestamp(self) -> int:
        with self._lock:
            phys = int(time.time() * 1000)
            if phys <= self._last_physical:
                phys = self._last_physical
                self._logical += 1
            else:
                self._last_physical = phys
                self._logical = 0
            if self._logical >= (1 << PHYSICAL_SHIFT):
                self._last_physical += 1
                self._logical = 0
                phys = self._last_physical
            return compose_ts(phys, self._logical)

    def ensure_after(self, ts: int) -> None:
        """Fence the oracle past ``ts``: every future timestamp is
        strictly greater.  Recovery calls this with the max timestamp in
        the replayed entry map — a restart within the same millisecond
        (or under a skewed clock) must never re-mint a pre-crash ts."""
        with self._lock:
            if ts >= compose_ts(self._last_physical, self._logical):
                self._last_physical = ts >> PHYSICAL_SHIFT
                self._logical = ts & ((1 << PHYSICAL_SHIFT) - 1)

    def get_timestamp_async(self):
        """Lazy TSO future (reference: session.go:638-663 lazy txn +
        GetTimestampAsync): capture nothing now, fetch on .wait()."""
        return _TSFuture(self)

    def is_expired(self, lock_ts: int, ttl_ms: int) -> bool:
        now_phys = int(time.time() * 1000)
        return now_phys >= extract_physical(lock_ts) + ttl_ms

    def until_expired_ms(self, lock_ts: int, ttl_ms: int) -> int:
        now_phys = int(time.time() * 1000)
        return extract_physical(lock_ts) + ttl_ms - now_phys


class _TSFuture:
    __slots__ = ("_oracle", "_ts")

    def __init__(self, oracle: Oracle):
        self._oracle = oracle
        self._ts = None

    def wait(self) -> int:
        if self._ts is None:
            self._ts = self._oracle.get_timestamp()
        return self._ts
