"""Range task runner — reference: store/tikv/range_task.go
(RangeTaskRunner: split a key range by region, run a handler per
subrange on a bounded worker pool, re-split and retry on region errors,
aggregate completed-region / failure statistics).

The consumer shape is background maintenance over the whole keyspace —
GC, diagnostics, bulk deletes — where per-region parallelism and
stale-topology retry matter but transactional isolation does not.
"""
from __future__ import annotations

import threading
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Tuple

from . import backoff as bo
from .backoff import Backoffer
from .errors import BackoffExceeded, RegionError
from .rpc import RegionCache


@dataclass
class RangeTaskStat:
    """Mirrors range_task.go's completed/failed region counters."""
    completed_regions: int = 0
    failed_regions: int = 0
    _mu: threading.Lock = field(default_factory=threading.Lock,
                                repr=False)

    def _add(self, ok: bool) -> None:
        with self._mu:
            if ok:
                self.completed_regions += 1
            else:
                self.failed_regions += 1


# handler(start, end) -> None; raises RegionError to trigger a re-split
RangeTaskHandler = Callable[[bytes, bytes], None]


class RangeTaskRunner:
    """Split [start, end) by region and run `handler` per subrange with
    bounded concurrency (range_task.go RunOnRange)."""

    def __init__(self, name: str, cache: RegionCache,
                 concurrency: int = 4, max_retries_per_range: int = 8):
        self.name = name
        self.cache = cache
        self.concurrency = max(1, concurrency)
        self.max_retries = max_retries_per_range

    def run_on_range(self, start: bytes, end: bytes,
                     handler: RangeTaskHandler) -> RangeTaskStat:
        stat = RangeTaskStat()
        splits = self.cache.split_range_by_regions(start, end)
        with ThreadPoolExecutor(max_workers=self.concurrency,
                                thread_name_prefix=f"range-{self.name}"
                                ) as pool:
            futs = [pool.submit(self._run_one, s, e, handler, stat)
                    for _r, s, e in splits]
            errs = [f.exception() for f in futs]
        for e in errs:
            if e is not None:
                raise e
        return stat

    def _run_one(self, start: bytes, end: bytes,
                 handler: RangeTaskHandler, stat: RangeTaskStat) -> None:
        """One subrange: on a region error the topology moved under us —
        invalidate, RE-SPLIT the remaining subrange, and run the pieces
        (a split/merge mid-task must neither drop nor double keys)."""
        boer = Backoffer(bo.COP_NEXT_MAX_BACKOFF)
        for _ in range(self.max_retries):
            try:
                handler(start, end)
                stat._add(True)
                return
            except RegionError as e:
                self.cache.invalidate_all()
                boer.backoff(bo.BO_REGION_MISS, e)
                pieces = self.cache.split_range_by_regions(start, end)
                if len(pieces) > 1:
                    for _r, s, e2 in pieces:
                        self._run_one(s, e2, handler, stat)
                    return
        stat._add(False)
        raise BackoffExceeded(
            f"range task {self.name}: {start!r}..{end!r} kept failing")
