"""KV error taxonomy (reference: kv/error.go, store/tikv error surface)."""
from __future__ import annotations


class KVError(Exception):
    pass


class KeyNotFound(KVError):
    pass


class KeyExists(KVError):
    """Duplicate key on prewrite/insert (reference: kv.ErrKeyExists)."""
    def __init__(self, key: bytes):
        super().__init__(f"key already exists: {key!r}")
        self.key = key


class KeyIsLocked(KVError):
    """Encountered another txn's lock (reference: kvrpcpb KeyError.Locked)."""
    def __init__(self, key: bytes, primary: bytes, start_ts: int, ttl: int):
        super().__init__(f"key is locked: {key!r} by txn {start_ts}")
        self.key = key
        self.primary = primary
        self.lock_ts = start_ts
        self.ttl = ttl


class WriteConflict(KVError):
    """A newer commit landed after our start_ts (reference: ErrWriteConflict)."""
    def __init__(self, key: bytes, start_ts: int, conflict_ts: int):
        super().__init__(
            f"write conflict on {key!r}: start_ts={start_ts} conflict_commit_ts={conflict_ts}")
        self.key = key
        self.start_ts = start_ts
        self.conflict_ts = conflict_ts


class TxnAborted(KVError):
    """Commit arrived for a rolled-back txn (reference: ErrTxnAborted)."""


class RetryableError(KVError):
    """Transaction should be retried by the session layer."""


class RegionError(KVError):
    """Routing error — retry after refreshing the region cache
    (reference: errorpb region errors; region_request.go)."""
    def __init__(self, kind: str, region_id: int = 0):
        super().__init__(f"region error: {kind} (region {region_id})")
        self.kind = kind
        self.region_id = region_id


class BackoffExceeded(KVError):
    """Retry budget exhausted (reference: backoff.go maxSleep)."""


class UndeterminedError(KVError):
    """Commit outcome unknown (error on primary-commit RPC) —
    reference: 2pc.go:417-428."""


class WalError(KVError):
    """Write-ahead-log append/fsync failure — the mutation it was meant
    to journal is NOT applied (the store never diverges ahead of a log
    it could not write)."""


class CheckpointError(WalError):
    """A checkpoint attempt failed — counted and retried on the next
    trigger; the previous checkpoint + the unrotated log remain the
    recovery source, so this is never fatal to the store."""


class TaskCancelled(KVError):
    """A cooperative cancel (early close of a scatter-gather, statement
    kill) interrupted this task's retry loop — never user-visible: the
    canceller discards the task's result."""


class SchemaOutdated(RetryableError):
    """Schema changed during txn; lease check failed
    (reference: domain/schema_validator.go)."""
