"""In-process Percolator MVCC store — the storage-node keystone.

Capability parity with reference store/mockstore/mocktikv/mvcc_leveldb.go
(lock/write/data column layout, prewrite/commit/rollback/scan/resolve-lock,
1,547 LoC) — the fake backend every integration test rides (SURVEY §2.7).
One instance holds the whole keyspace; the Cluster/RPC layers shard access
by region on top of it.
"""
from __future__ import annotations

import bisect
import threading
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Tuple

from .errors import (KeyExists, KeyIsLocked, KeyNotFound, TxnAborted,
                     WriteConflict)

# Durability (kv/wal.py): when constructed with a data_dir the store
# journals every mutation inside its critical section — the journal is
# written AFTER validation but BEFORE the in-memory apply, so a failed
# append (WalError) leaves the store unmutated and a journaled record is
# always appliable on replay.

# write-record types (reference: mvcc.go WriteType)
W_PUT, W_DELETE, W_ROLLBACK = 0, 1, 2

OP_PUT, OP_DEL, OP_INSERT = 0, 1, 2  # mutation ops (kvrpcpb.Op subset)


@dataclass
class Lock:
    primary: bytes
    start_ts: int
    ttl_ms: int
    op: int
    value: bytes


@dataclass
class _Entry:
    lock: Optional[Lock] = None
    # (commit_ts desc, write_type, start_ts) — newest first
    writes: List[Tuple[int, int, int]] = field(default_factory=list)
    data: Dict[int, bytes] = field(default_factory=dict)  # start_ts -> value


@dataclass
class Mutation:
    op: int
    key: bytes
    value: bytes = b""


class MVCCStore:
    def __init__(self, data_dir: Optional[str] = None):
        self._entries: Dict[bytes, _Entry] = {}
        self._sorted: List[bytes] = []
        self._dirty = False
        self._mu = threading.RLock()
        self._wal = None
        self._replaying = False
        self.recovery_info: Optional[dict] = None
        if data_dir:
            from .wal import WriteAheadLog
            self._wal = WriteAheadLog(data_dir)
            self.recovery_info = self._wal.recover_into(self)

    @property
    def wal(self):
        return self._wal

    def _journal(self, fn) -> None:
        """Append one redo record via ``fn(wal)``; called with self._mu
        held, after validation, before the apply.  A WalError here must
        propagate — the caller skips the apply, so store and log never
        diverge with the store ahead."""
        if self._wal is not None and not self._replaying:
            fn(self._wal)

    def _maybe_checkpoint(self) -> None:
        if self._wal is not None and not self._replaying:
            self._wal.maybe_checkpoint(self)

    # ---- helpers ------------------------------------------------------
    def _entry(self, key: bytes) -> _Entry:
        e = self._entries.get(key)
        if e is None:
            e = self._entries[key] = _Entry()
            self._dirty = True
        return e

    def _keys(self) -> List[bytes]:
        if self._dirty:
            self._sorted = sorted(self._entries)
            self._dirty = False
        return self._sorted

    @staticmethod
    def _find_write(e: _Entry, ts: int) -> Optional[Tuple[int, int, int]]:
        """Newest committed write with commit_ts <= ts, skipping rollbacks."""
        for w in e.writes:
            if w[0] <= ts and w[1] != W_ROLLBACK:
                return w
        return None

    # ---- reads --------------------------------------------------------
    def get(self, key: bytes, ts: int, resolved: Tuple[int, ...] = ()) -> bytes:
        with self._mu:
            e = self._entries.get(key)
            if e is None:
                raise KeyNotFound(key)
            lk = e.lock
            if lk is not None and lk.start_ts <= ts and lk.start_ts not in resolved:
                raise KeyIsLocked(key, lk.primary, lk.start_ts, lk.ttl_ms)
            w = self._find_write(e, ts)
            if w is None or w[1] == W_DELETE:
                raise KeyNotFound(key)
            return e.data[w[2]]

    def scan(self, start: Optional[bytes], end: Optional[bytes], ts: int,
             limit: int = 0, resolved: Tuple[int, ...] = ()) -> List[Tuple[bytes, bytes]]:
        out: List[Tuple[bytes, bytes]] = []
        with self._mu:
            ks = self._keys()
            i = bisect.bisect_left(ks, start) if start is not None else 0
            while i < len(ks):
                k = ks[i]
                if end is not None and k >= end:
                    break
                e = self._entries[k]
                lk = e.lock
                if lk is not None and lk.start_ts <= ts and lk.start_ts not in resolved:
                    raise KeyIsLocked(k, lk.primary, lk.start_ts, lk.ttl_ms)
                w = self._find_write(e, ts)
                if w is not None and w[1] == W_PUT:
                    out.append((k, e.data[w[2]]))
                    if limit and len(out) >= limit:
                        break
                i += 1
        return out

    # ---- bulk-load backfill -------------------------------------------
    def backfill(self, kvs: List[Tuple[bytes, bytes]], ts: int) -> int:
        """Install committed PUT records directly at a HISTORICAL
        commit_ts, bypassing Percolator — the columnar bulk-load
        materialization path (columnar/store.py ensure_row_store): the
        rows logically existed since the bulk load's timestamp, so
        every snapshot >= ts must see them, exactly as the replica
        already serves them.  Keys with any existing write or a live
        lock are skipped untouched (they are already row-store-real);
        returns the number installed."""
        with self._mu:
            installed: List[Tuple[bytes, bytes]] = []
            planned = set()
            for key, value in kvs:
                e = self._entry(key)
                if e.lock is not None or e.writes or key in planned:
                    continue
                installed.append((key, value))
                planned.add(key)
            if installed:
                self._journal(lambda w: w.log_backfill(ts, installed))
            for key, value in installed:
                e = self._entry(key)
                e.data[ts] = value
                e.writes.append((ts, W_PUT, ts))
            self._maybe_checkpoint()
        return len(installed)

    # ---- percolator write protocol ------------------------------------
    def prewrite(self, mutations: List[Mutation], primary: bytes,
                 start_ts: int, ttl_ms: int) -> None:
        """All-or-nothing prewrite of a batch (reference:
        mvcc_leveldb.go Prewrite)."""
        with self._mu:
            errs = []
            plans: List[Mutation] = []
            seen = set()
            for m in mutations:
                if m.key in seen:
                    continue  # same-batch re-prewrite is idempotent
                try:
                    if self._check_prewrite(m, primary, start_ts, ttl_ms):
                        plans.append(m)
                        seen.add(m.key)
                except (KeyIsLocked, WriteConflict, KeyExists) as ex:
                    errs.append(ex)
            if plans:
                self._journal(lambda w: w.log_prewrite(
                    primary, start_ts, ttl_ms,
                    [(m.op, m.key, m.value) for m in plans]))
            for m in plans:
                self._entry(m.key).lock = Lock(primary, start_ts, ttl_ms,
                                               m.op, m.value)
            self._maybe_checkpoint()
            if errs:
                raise errs[0]

    def _check_prewrite(self, m: Mutation, primary: bytes, start_ts: int,
                        ttl_ms: int) -> bool:
        """Validation half of prewrite: raises on conflict, returns False
        for an idempotent re-prewrite, True when a lock must be taken."""
        e = self._entry(m.key)
        if e.lock is not None:
            if e.lock.start_ts != start_ts:
                raise KeyIsLocked(m.key, e.lock.primary, e.lock.start_ts, e.lock.ttl_ms)
            return False  # idempotent re-prewrite
        if e.writes:
            newest = e.writes[0]
            if newest[0] >= start_ts:
                raise WriteConflict(m.key, start_ts, newest[0])
            # our own rollback record aborts the txn
            for w in e.writes:
                if w[2] == start_ts and w[1] == W_ROLLBACK:
                    raise WriteConflict(m.key, start_ts, w[0])
        if m.op == OP_INSERT:
            w = self._find_write(e, start_ts)
            if w is not None and w[1] == W_PUT:
                raise KeyExists(m.key)
        return True

    def commit(self, keys: List[bytes], start_ts: int, commit_ts: int) -> None:
        with self._mu:
            plans: List[Tuple[bytes, Lock]] = []
            for k in keys:
                lk = self._check_commit(k, start_ts)
                if lk is not None:
                    plans.append((k, lk))
            if plans:
                self._journal(lambda w: w.log_commit(
                    start_ts, commit_ts,
                    [(k, W_DELETE if lk.op == OP_DEL else W_PUT, lk.value)
                     for k, lk in plans]))
            for k, lk in plans:
                self._apply_commit(k, lk, start_ts, commit_ts)
            self._maybe_checkpoint()

    def _check_commit(self, key: bytes, start_ts: int) -> Optional[Lock]:
        """Validation half of commit: returns the lock to commit, None
        for an idempotent re-commit, raises TxnAborted otherwise."""
        e = self._entries.get(key)
        if e is None:
            raise TxnAborted(f"commit of unknown key {key!r}")
        lk = e.lock
        if lk is not None and lk.start_ts == start_ts:
            return lk
        # lock gone: committed already (idempotent) or rolled back (abort)
        for w in e.writes:
            if w[2] == start_ts:
                if w[1] == W_ROLLBACK:
                    raise TxnAborted(f"txn {start_ts} already rolled back")
                return None
        raise TxnAborted(f"txn {start_ts} has no lock and no write on {key!r}")

    def _apply_commit(self, key: bytes, lk: Lock, start_ts: int,
                      commit_ts: int) -> None:
        e = self._entry(key)
        wtype = W_DELETE if lk.op == OP_DEL else W_PUT
        if wtype == W_PUT:
            e.data[start_ts] = lk.value
        e.writes.append((commit_ts, wtype, start_ts))
        e.writes.sort(key=lambda w: -w[0])  # keep newest-first invariant
        if e.lock is not None and e.lock.start_ts == start_ts:
            e.lock = None

    def rollback(self, keys: List[bytes], start_ts: int) -> None:
        with self._mu:
            plans: List[bytes] = []
            for k in keys:
                e = self._entry(k)
                committed = None
                for w in e.writes:
                    if w[2] == start_ts:
                        committed = w
                        break
                if committed is not None and committed[1] != W_ROLLBACK:
                    raise TxnAborted(
                        f"cannot roll back committed txn {start_ts}")
                if ((e.lock is not None and e.lock.start_ts == start_ts)
                        or committed is None):
                    plans.append(k)
            if plans:
                self._journal(lambda w: w.log_rollback(start_ts, plans))
            for k in plans:
                e = self._entry(k)
                if e.lock is not None and e.lock.start_ts == start_ts:
                    e.lock = None
                if not any(w[2] == start_ts for w in e.writes):
                    e.writes.append((start_ts, W_ROLLBACK, start_ts))
                    e.writes.sort(key=lambda w: -w[0])
            self._maybe_checkpoint()

    # ---- recovery (lock resolution) -----------------------------------
    def check_txn_status(self, primary: bytes, lock_ts: int,
                         expired: bool) -> Tuple[int, bool]:
        """Return (commit_ts, is_committed); commit_ts==0 + False means the
        txn was (or now is) rolled back (reference: lock_resolver.go
        getTxnStatus).  `expired` tells whether the caller observed TTL
        expiry — only then may we unilaterally roll back the primary."""
        with self._mu:
            e = self._entries.get(primary)
            if e is not None:
                for w in e.writes:
                    if w[2] == lock_ts:
                        if w[1] == W_ROLLBACK:
                            return 0, False
                        return w[0], True
                lk = e.lock
                if lk is not None and lk.start_ts == lock_ts:
                    if not expired:
                        raise KeyIsLocked(primary, lk.primary, lk.start_ts, lk.ttl_ms)
                    self.rollback([primary], lock_ts)
                    return 0, False
            # no lock, no write: orphan prewrite never reached the primary —
            # write a rollback record to fence it out
            self.rollback([primary], lock_ts)
            return 0, False

    def gc(self, safepoint_ts: int) -> int:
        """Garbage-collect versions no snapshot at/after `safepoint_ts` can
        see (reference: the GC the tinykv side performs under the
        safepoint watched by store/tikv/safepoint.go).  Keeps, per key,
        the newest write with commit_ts <= safepoint plus everything
        newer; drops rollback records at/below the safepoint and orphaned
        data versions.  Returns versions removed."""
        removed = 0
        with self._mu:
            self._journal(lambda w: w.log_gc(safepoint_ts))
            for key, e in list(self._entries.items()):
                keep: List[Tuple[int, int, int]] = []
                kept_visible = False
                for w in e.writes:  # newest first
                    cts, wtype, sts = w
                    if cts > safepoint_ts:
                        keep.append(w)
                        continue
                    if wtype == W_ROLLBACK:
                        removed += 1
                        continue
                    if not kept_visible:
                        kept_visible = True
                        if wtype == W_DELETE:
                            removed += 1  # tombstone below safepoint: drop
                        else:
                            keep.append(w)
                    else:
                        removed += 1
                e.writes = keep
                live = {w[2] for w in keep}
                for sts in [s for s in e.data if s not in live]:
                    del e.data[sts]
                if not e.writes and e.lock is None and not e.data:
                    del self._entries[key]
                    self._dirty = True
            self._maybe_checkpoint()
        return removed

    def resolve_lock(self, key: bytes, start_ts: int, commit_ts: int) -> None:
        """Resolve one secondary per txn status (reference:
        lock_resolver.go resolveLock)."""
        with self._mu:
            e = self._entries.get(key)
            if e is None or e.lock is None or e.lock.start_ts != start_ts:
                return
            if commit_ts > 0:
                lk = e.lock
                wtype = W_DELETE if lk.op == OP_DEL else W_PUT
                self._journal(lambda w: w.log_resolve(
                    key, start_ts, commit_ts, wtype, lk.value))
                self._apply_commit(key, lk, start_ts, commit_ts)
                self._maybe_checkpoint()
            else:
                self.rollback([key], start_ts)

    # ---- raw/debug ----------------------------------------------------
    def locked_keys(self, start_ts: Optional[int] = None) -> List[bytes]:
        with self._mu:
            return [k for k, e in self._entries.items()
                    if e.lock is not None and
                    (start_ts is None or e.lock.start_ts == start_ts)]

    def max_known_ts(self) -> int:
        """Largest timestamp recorded anywhere in the entry map — after
        recovery the oracle must be advanced past it so a fast restart
        loop can never mint a timestamp that collides with (or sorts
        below) pre-crash history."""
        with self._mu:
            m = 0
            for e in self._entries.values():
                if e.lock is not None and e.lock.start_ts > m:
                    m = e.lock.start_ts
                for w in e.writes:
                    if w[0] > m:
                        m = w[0]
                    if w[2] > m:
                        m = w[2]
                for sts in e.data:
                    if sts > m:
                        m = sts
            return m

    # ---- recovery replay (kv/wal.py) ----------------------------------
    # Raw redo application: validation already happened when the record
    # was journaled, so these rebuild state without re-checking — the
    # byte-for-byte shape a live store would have reached.
    def _replay_prewrite(self, primary: bytes, start_ts: int, ttl_ms: int,
                         muts: List[Tuple[int, bytes, bytes]]) -> None:
        with self._mu:
            for op, key, value in muts:
                self._entry(key).lock = Lock(primary, start_ts, ttl_ms,
                                             op, value)

    def _replay_commit(self, start_ts: int, commit_ts: int,
                       items: List[Tuple[bytes, int, bytes]]) -> None:
        with self._mu:
            for key, wtype, value in items:
                e = self._entry(key)
                if wtype == W_PUT:
                    e.data[start_ts] = value
                e.writes.append((commit_ts, wtype, start_ts))
                e.writes.sort(key=lambda w: -w[0])
                if e.lock is not None and e.lock.start_ts == start_ts:
                    e.lock = None

    def _replay_rollback(self, start_ts: int, keys: List[bytes]) -> None:
        with self._mu:
            for key in keys:
                e = self._entry(key)
                if e.lock is not None and e.lock.start_ts == start_ts:
                    e.lock = None
                if not any(w[2] == start_ts for w in e.writes):
                    e.writes.append((start_ts, W_ROLLBACK, start_ts))
                    e.writes.sort(key=lambda w: -w[0])

    def _replay_resolve(self, key: bytes, start_ts: int, commit_ts: int,
                        wtype: int, value: bytes) -> None:
        if commit_ts > 0:
            self._replay_commit(start_ts, commit_ts, [(key, wtype, value)])
        else:
            self._replay_rollback(start_ts, [key])

    def _replay_gc(self, safepoint_ts: int) -> None:
        with self._mu:
            was = self._replaying
            self._replaying = True
            try:
                self.gc(safepoint_ts)
            finally:
                self._replaying = was

    def _replay_backfill(self, ts: int,
                         kvs: List[Tuple[bytes, bytes]]) -> None:
        with self._mu:
            for key, value in kvs:
                e = self._entry(key)
                e.data[ts] = value
                e.writes.append((ts, W_PUT, ts))
