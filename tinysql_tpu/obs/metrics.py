"""Process-level metrics and the Prometheus text renderer (/metrics).

Two counter families:

- query-lifecycle counters owned here (``observe_query``): statements
  executed, errors, slow queries, summed wall seconds — labeled by
  statement kind;
- device-economics counters owned by the device layer (``kernels.STATS``
  and ``ops/progcache.STATS``), read at render time.  Those dicts are
  process-cumulative accumulators (plus the ``pipe_depth_hwm`` high-water
  mark, exported as a gauge): exactly the monotonic shape Prometheus
  counters want.

Rendering follows the Prometheus text exposition format 0.0.4 (HELP/TYPE
comment pairs, ``\\n``-terminated sample lines).
"""
from __future__ import annotations

import threading
from typing import Dict, List, Tuple

_mu = threading.Lock()

#: (metric, labels-tuple) -> value;  labels-tuple is ((k, v), ...)
_QUERY_COUNTERS: Dict[Tuple[str, tuple], float] = {}

#: device-layer STATS key -> (prometheus name, help text)
_DEVICE_METRICS = {
    "dispatches": ("tinysql_dispatches_total",
                   "Compiled device-program dispatches"),
    "d2h_transfers": ("tinysql_d2h_transfers_total",
                      "Device-to-host transfer operations"),
    "d2h_bytes": ("tinysql_d2h_bytes_total",
                  "Bytes materialized device-to-host"),
    "h2d_transfers": ("tinysql_h2d_transfers_total",
                      "Host-to-device upload operations (ParamTable "
                      "pushes, column/mask uploads)"),
    "h2d_bytes": ("tinysql_h2d_bytes_total",
                  "Bytes uploaded host-to-device"),
    "device_s": ("tinysql_device_busy_seconds_total",
                 "MEASURED device busy seconds from profiled dispatches "
                 "(block_until_ready-closed; tidb_device_profile_rate)"),
    "profiled_dispatches": ("tinysql_profiled_dispatches_total",
                            "Dispatches closed with block_until_ready "
                            "by the sampling profiler"),
    "host_dispatches": ("tinysql_host_dispatches_total",
                        "Host-twin kernel invocations (numpy twins "
                        "serving the XLA:CPU backend)"),
    "flops": ("tinysql_device_flops_total",
              "XLA cost-analysis FLOPs of dispatched programs"),
    "bytes_accessed": ("tinysql_device_bytes_accessed_total",
                       "XLA cost-analysis bytes accessed"),
    "pipe_blocks": ("tinysql_pipe_blocks_total",
                    "Blocks staged through the async block pipeline"),
    "pipe_stage_s": ("tinysql_pipe_stage_seconds_total",
                     "Host staging wall seconds (pipeline producer)"),
    "pipe_dispatch_s": ("tinysql_pipe_dispatch_seconds_total",
                        "Device dispatch wall seconds inside pipelines"),
    "pipe_drain_s": ("tinysql_pipe_drain_seconds_total",
                     "Result drain wall seconds inside pipelines"),
    "pipe_wall_s": ("tinysql_pipe_wall_seconds_total",
                    "End-to-end pipeline wall seconds"),
    "pipe_depth_hwm": ("tinysql_pipe_depth_hwm",
                       "Staging-queue depth high-water mark"),
}


#: THE central metric-name registry: every name exported on /metrics OR
#: sampled into the time-series ring (obs/tsring.py) is declared here —
#: name -> (kind, help).  The ring validates names against this table at
#: sample time (unregistered names are dropped + counted) and qlint
#: OB404 checks it statically, so /metrics, metrics_history, and
#: metrics_summary can never drift apart on what a metric is called.
METRICS: Dict[str, Tuple[str, str]] = {
    # query lifecycle (owned here)
    "tinysql_queries_total": ("counter", "Statements executed"),
    "tinysql_query_seconds_sum":
        ("counter", "Summed statement execution wall seconds "
                    "(parse excluded)"),
    "tinysql_slow_queries_total":
        ("counter", "Statements whose exec wall exceeded "
                    "tidb_slow_log_threshold"),
    "tinysql_query_errors_total": ("counter", "Statements that raised"),
    # progcache / prewarm provenance (ops/progcache.py)
    "tinysql_progcache_hits_total":
        ("counter", "In-process program-registry hits"),
    "tinysql_progcache_misses_total":
        ("counter", "In-process program-registry misses (program builds)"),
    "tinysql_prewarm_seeded_total":
        ("counter", "Programs compiled inside a prewarm scope "
                    "(auto-prewarm worker / tools/warm.py)"),
    "tinysql_prewarm_hits_total":
        ("counter", "Query-path registry hits on prewarm-seeded programs "
                    "(compiles the prewarmer saved real queries)"),
    "tinysql_progcache_programs": ("gauge", "Registered compiled programs"),
    "tinysql_compile_seconds_total":
        ("counter", "Summed program-build wall seconds (inclusive of "
                    "nested builds, like the compile spans)"),
    "tinysql_pending_cost_analyses":
        ("gauge", "Deferred XLA cost analyses awaiting resolution "
                  "(drained by the tsring sampler tick / bench; "
                  "bounded at kernels.PENDING_COSTS_MAX)"),
    # SLO error-budget accounting (obs/inspect.slo_sample, fed from the
    # exec-phase latency histogram against tidb_slo_p99_ms)
    "tinysql_slo_exec_measurements_total":
        ("counter", "Exec-phase latency measurements while an SLO "
                    "(tidb_slo_p99_ms) was armed"),
    "tinysql_slo_exec_breaches_total":
        ("counter", "Exec-phase measurements provably over the armed "
                    "tidb_slo_p99_ms threshold"),
    "tinysql_slo_p99_ms":
        ("gauge", "The armed SLO threshold at sample time (the slo-burn "
                  "rule discards windows where it changed)"),
    # resilience (fail/, ops/degrade.py, utils/memory.py)
    "tinysql_failpoint_hits_total":
        ("counter", "Failpoint fires by name"),
    "tinysql_device_loss_total":
        ("counter", "Mid-statement accelerator losses observed"),
    "tinysql_degraded_statements_total":
        ("counter", "Statements transparently re-executed on CPU after a "
                    "device loss"),
    "tinysql_cpu_pinned":
        ("gauge", "1 while planning is pinned to CPU (device-loss "
                  "cooldown)"),
    "tinysql_mem_quota_exceeded_total":
        ("counter", "Statements aborted by tidb_mem_quota_query"),
    # memory-adaptive spilling (ops/spill.py)
    "tinysql_spill_bytes_total":
        ("counter", "Bytes written to the host spill store (partitions "
                    "+ sort/top-k run files)"),
    "tinysql_spill_reload_bytes_total":
        ("counter", "Spilled bytes read back for probing/merging"),
    "tinysql_spill_partitions_total":
        ("counter", "Partitions / run files written to the spill store"),
    "tinysql_spill_repartitions_total":
        ("counter", "Recursive repartition events (a partition "
                    "overflowed its working-set budget)"),
    "tinysql_spill_stream_runs_total":
        ("counter", "Streamed partial-aggregation slices (an "
                    "unsplittable partition merged in budget-sized "
                    "runs)"),
    "tinysql_spilled_statements_total":
        ("counter", "Statements that spilled at least once"),
    "tinysql_spill_open_slots":
        ("gauge", "Live spill-store slots (0 between statements — "
                  "anything else is a leak)"),
    # mesh-sharded operator tier (ops/shardops.py STATS)
    "tinysql_shard_rounds_total":
        ("counter", "Sharded program dispatches (partition-parallel "
                    "join/semijoin/agg/sort/top-k rounds)"),
    "tinysql_shard_rows_hwm":
        ("gauge", "Per-shard row high-water mark (largest partition "
                  "block / row slice one device has carried)"),
    "tinysql_shard_exchange_bytes_total":
        ("counter", "Bytes scattered through shard exchanges "
                    "(partition-block scatter + shuffle-join lanes)"),
    "tinysql_shard_skew_retries_total":
        ("counter", "Sharded attempts abandoned for partition skew "
                    "(fell back to the single-device kernel)"),
    "tinysql_shard_stacked_rounds_total":
        ("counter", "Batch rounds dispatched B stacked queries OVER a "
                    "sharded program (the B x N product)"),
    # serving layer (server/admission.py, server/pool.py, ops/batching.py)
    "tinysql_admission_admitted_total":
        ("counter", "Statements that began executing on the statement "
                    "pool"),
    "tinysql_admission_queued_total":
        ("counter", "Statements that waited in the admission queue first"),
    "tinysql_admission_rejected_total":
        ("counter", "Statements shed by admission control (MySQL 1041)"),
    "tinysql_admission_queue_wait_seconds_total":
        ("counter", "Summed seconds pooled statements spent waiting for "
                    "a worker (the pool-side half of the per-statement "
                    "queue_wait attribution)"),
    "tinysql_pool_queued":
        ("gauge", "Statements waiting in the admission queue (live "
                  "pools)"),
    "tinysql_pool_running":
        ("gauge", "Statements executing on pool workers (live pools)"),
    "tinysql_batch_rounds_total":
        ("counter", "Coalesced same-digest batch rounds dispatched"),
    "tinysql_batch_statements_total":
        ("counter", "Statements served through a batch round dispatch"),
    "tinysql_batch_occupancy_sum":
        ("counter", "Summed batch occupancy (divide by rounds for the "
                    "average)"),
    "tinysql_batch_fallbacks_total":
        ("counter", "Replay consume misses that fell back to solo "
                    "dispatch"),
    "tinysql_batch_stacked_rounds_total":
        ("counter", "Batch groups served by ONE stacked-params "
                    "vmap-batched dispatch (tidb_batch_stack_max)"),
    "tinysql_batch_stacked_occupancy_sum":
        ("counter", "Summed stacked-group occupancy (divide by stacked "
                    "rounds for the average members per stacked "
                    "dispatch)"),
    "tinysql_batch_stack_fallbacks_total":
        ("counter", "Batch groups that fell back from the stacked leg "
                    "to back-to-back replays (layout mismatch, missing "
                    "stacking recipe, stacked dispatch error)"),
    "tinysql_batch_dispatch_seconds_total":
        ("counter", "Wall seconds spent inside batch-round device "
                    "dispatch legs"),
    "tinysql_stmt_mem_inflight_bytes":
        ("gauge", "Aggregate live MemTracker bytes held by RUNNING "
                  "statements (the admission gate's pressure signal)"),
    # wire front end (server/server.py accept gate + server/aio.py):
    # the connection-pressure inspection rule's evidence
    "tinysql_conn_open":
        ("gauge", "Open wire connections across live servers (both "
                  "wire modes)"),
    "tinysql_conn_idle":
        ("gauge", "Open connections with no statement executing or "
                  "queued (parked aio file objects / blocked legacy "
                  "readers)"),
    "tinysql_conn_active":
        ("gauge", "Open connections with a statement executing or "
                  "queued"),
    "tinysql_conn_accepts_total":
        ("counter", "Connections admitted at accept (handed to a wire "
                    "front end)"),
    "tinysql_conn_sheds_total":
        ("counter", "Connects refused with MySQL 1040 at accept "
                    "(tidb_max_server_connections)"),
    # histograms / debug surfaces
    "tinysql_stmt_phase_seconds":
        ("histogram", "Statement latency by phase (statement summary "
                      "store)"),
    "tinysql_dispatch_device_seconds":
        ("histogram", "Measured device busy time per profiled dispatch "
                      "(ops/profiler.py, tidb_device_profile_rate)"),
    "tinysql_trace_ring_entries":
        ("gauge", "Query traces buffered for /debug/trace"),
    # continuous host profiler (obs/conprof.py)
    "tinysql_conprof_samples_total":
        ("counter", "Thread-stack samples folded by the continuous "
                    "host profiler"),
    "tinysql_conprof_idle_samples_total":
        ("counter", "Samples whose leaf frame was a blocking primitive "
                    "(parked threads; excluded from busy-CPU shares)"),
    "tinysql_conprof_attributed_samples_total":
        ("counter", "Samples attributed to a running statement "
                    "(statements_summary sum_cpu_ms/cpu_samples)"),
    "tinysql_conprof_ticks_total":
        ("counter", "Continuous-profiler sampling ticks"),
    "tinysql_conprof_self_seconds_total":
        ("counter", "Wall seconds the profiler spent walking/folding "
                    "frames (its own overhead; the profiler-overhead "
                    "rule's evidence)"),
    "tinysql_conprof_evicted_total":
        ("counter", "Folded stacks evicted into the (evicted) tombstone "
                    "by the per-window tidb_conprof_max_stacks cap"),
    "tinysql_conprof_backoff":
        ("gauge", "Live overhead-backoff divisor (effective rate = "
                  "tidb_conprof_rate / backoff; 1 = at full rate)"),
    "tinysql_conprof_stacks":
        ("gauge", "Distinct folded stacks in the current window"),
    "tinysql_conprof_windows":
        ("gauge", "Retained profile windows (current + rotated)"),
    # continuous heap profiler (obs/memprof.py)
    "tinysql_memprof_ticks_total":
        ("counter", "Heap-profiler sampling ticks (tracemalloc "
                    "snapshots taken)"),
    "tinysql_memprof_sites_total":
        ("counter", "Allocation sites folded by the heap profiler"),
    "tinysql_memprof_attributed_total":
        ("counter", "Statement attributions of traced-heap growth "
                    "(statements_summary sum_heap_alloc_kb)"),
    "tinysql_memprof_self_seconds_total":
        ("counter", "Wall seconds the heap profiler spent snapshotting "
                    "and folding (its own overhead; the bench_serve "
                    "memprof gate's evidence)"),
    "tinysql_memprof_evicted_total":
        ("counter", "Allocation sites evicted into the (evicted) "
                    "tombstone by the per-window tidb_memprof_max_sites "
                    "cap"),
    "tinysql_memprof_errors_total":
        ("counter", "Heap-profiler ticks that failed (torn snapshots, "
                    "memprofSampleError) — counted, never fatal"),
    "tinysql_memprof_backoff":
        ("gauge", "Live overhead-backoff divisor (effective rate = "
                  "tidb_memprof_rate / backoff; 1 = at full rate)"),
    # measured-vs-tracked memory reconciliation (obs/memprof.py
    # memory_state — the heap-growth / hbm-pressure / mem-untracked
    # rules' evidence series)
    "tinysql_mem_tracked_bytes":
        ("gauge", "Live statement MemTracker bytes (the ledger the "
                  "spill/admission gates act on)"),
    "tinysql_mem_traced_bytes":
        ("gauge", "Measured python heap (tracemalloc current traced "
                  "bytes; 0 when tracing is off)"),
    "tinysql_mem_traced_peak_bytes":
        ("gauge", "Measured python heap high water since tracing "
                  "started"),
    "tinysql_mem_rss_bytes":
        ("gauge", "Process resident set size (/proc/self/statm)"),
    "tinysql_mem_untracked_bytes":
        ("gauge", "Measured heap beyond the MemTracker ledger (the "
                  "mem-untracked rule's divergence)"),
    "tinysql_hbm_live_bytes":
        ("gauge", "Total bytes of live device buffers (HBM census)"),
    "tinysql_hbm_buffers":
        ("gauge", "Live device buffers counted by the HBM census"),
    "tinysql_hbm_unattributed_bytes":
        ("gauge", "Live device bytes no registered owner claims — the "
                  "leak bucket (hbm census)"),
    "tinysql_hbm_limit_bytes":
        ("gauge", "Backend device-memory capacity when exposed "
                  "(memory_stats bytes_limit; 0 on CPU)"),
    # durable MVCC: WAL + checkpoint + crash recovery (kv/wal.py STATS)
    "tinysql_wal_appends_total":
        ("counter", "WAL records journaled (prewrite/commit/rollback/"
                    "resolve/gc/backfill)"),
    "tinysql_wal_append_bytes_total":
        ("counter", "Framed bytes written to the write-ahead log"),
    "tinysql_wal_append_errors_total":
        ("counter", "WAL appends that failed BEFORE mutating the store "
                    "(typed WalError surfaced to the caller)"),
    "tinysql_wal_fsyncs_total":
        ("counter", "WAL fsync syscalls (strict: per commit-class "
                    "record; relaxed: group commit)"),
    "tinysql_wal_fsync_seconds_total":
        ("counter", "Wall seconds inside WAL fsync — the durability "
                    "tax; the wal-stall rule's evidence"),
    "tinysql_wal_fsync_errors_total":
        ("counter", "WAL fsync failures (outcome undetermined: bytes "
                    "may survive in the page cache)"),
    "tinysql_wal_torn_writes_total":
        ("counter", "Deliberately half-written records (walTornTail "
                    "crash-boundary lever)"),
    "tinysql_wal_size_bytes":
        ("gauge", "Bytes in the live log since the last checkpoint "
                  "rotation"),
    "tinysql_wal_checkpoints_total":
        ("counter", "Full entry-map snapshots atomically installed "
                    "(tmp -> fsync -> rename -> log truncate)"),
    "tinysql_wal_checkpoint_seconds_total":
        ("counter", "Wall seconds spent writing checkpoints"),
    "tinysql_wal_checkpoint_errors_total":
        ("counter", "Checkpoint attempts that failed before the atomic "
                    "rename — counted, never fatal"),
    "tinysql_recovery_runs_total":
        ("counter", "Crash-recovery passes (checkpoint load + wal "
                    "replay) at store open"),
    "tinysql_recovery_replayed_records_total":
        ("counter", "WAL records re-applied during recovery"),
    "tinysql_recovery_locks_total":
        ("counter", "In-flight Percolator locks rebuilt by recovery "
                    "(TTL re-armed from restart time) for the "
                    "lock-resolution ladder to fence or complete"),
    "tinysql_recovery_truncated_tails_total":
        ("counter", "Torn log tails truncated at the first bad "
                    "checksum during recovery"),
    "tinysql_gc_runs_total":
        ("counter", "MVCC garbage-collection sweeps run under the "
                    "tidb_gc_safepoint trigger"),
    "tinysql_gc_removed_versions_total":
        ("counter", "Stale MVCC versions removed below the safepoint"),
    # flight recorder (obs/flight.py STATS): durable observability
    # segments — all-zero means no data dir was armed (volatile
    # byte-identity: the family never appears)
    "tinysql_flight_segments_total":
        ("counter", "Flight-recorder segments appended (crc-framed, "
                    "zlib-compressed tier snapshots)"),
    "tinysql_flight_segment_bytes_total":
        ("counter", "Framed bytes appended to the flight store"),
    "tinysql_flight_fsyncs_total":
        ("counter", "Flight-store fsync syscalls (one per segment "
                    "append)"),
    "tinysql_flight_final_flushes_total":
        ("counter", "Final black-box segments force-flushed on a death "
                    "path (close / atexit)"),
    "tinysql_flight_compactions_total":
        ("counter", "Retention-bounded in-file compactions (rewrite "
                    "keeping the newest N segments)"),
    "tinysql_flight_torn_truncations_total":
        ("counter", "Torn segment tails truncated at the last good "
                    "crc boundary on writer open"),
    "tinysql_flight_prior_segments_total":
        ("counter", "Prior-incarnation segments loaded read-only at "
                    "boot"),
    "tinysql_flight_errors_total":
        ("counter", "Flight writer errors (collection or append "
                    "failures — counted, never fatal)"),
    "tinysql_flight_self_seconds_total":
        ("counter", "Wall seconds inside the flight writer's "
                    "snapshot+append path (the bench overhead gate's "
                    "evidence)"),
    # boot identity (obs/flight.py): the join key every flight surface
    # shares — always emitted, armed or not
    "tinysql_incarnation":
        ("gauge", "This process's incarnation id (monotonic across "
                  "restarts when a data dir is armed)"),
    "tinysql_server_start_timestamp":
        ("gauge", "Unix timestamp of this incarnation's boot"),
    # time-series sampler self-accounting (obs/tsring.py)
    "tinysql_metrics_samples_total":
        ("counter", "Time-series ring samples taken"),
    "tinysql_metrics_sample_seconds_total":
        ("counter", "Wall seconds spent collecting ring samples (the "
                    "sampler's own overhead)"),
    "tinysql_metrics_dropped_unregistered_total":
        ("counter", "Sampled values dropped because their metric name "
                    "was not in the central registry"),
    "tinysql_metrics_ring_entries":
        ("gauge", "Samples currently retained in the time-series ring"),
}

#: shardops.STATS key -> metric name (ONE map shared by the /metrics
#: render and the tsring "shardops" source, so the two surfaces can
#: never disagree on the sharded tier's names)
SHARD_METRIC_NAMES = (
    ("shard_rounds", "tinysql_shard_rounds_total"),
    ("shard_rows_hwm", "tinysql_shard_rows_hwm"),
    ("shard_exchange_bytes", "tinysql_shard_exchange_bytes_total"),
    ("shard_skew_retries", "tinysql_shard_skew_retries_total"),
    ("shard_stacked_rounds", "tinysql_shard_stacked_rounds_total"),
)

#: kv/wal.py STATS key -> metric name (ONE map shared by the /metrics
#: render and the tsring "wal" source).  tinysql_wal_size_bytes is the
#: only gauge — everything else accumulates.
WAL_METRIC_NAMES = (
    ("appends", "tinysql_wal_appends_total"),
    ("append_bytes", "tinysql_wal_append_bytes_total"),
    ("append_errors", "tinysql_wal_append_errors_total"),
    ("fsyncs", "tinysql_wal_fsyncs_total"),
    ("fsync_s", "tinysql_wal_fsync_seconds_total"),
    ("fsync_errors", "tinysql_wal_fsync_errors_total"),
    ("torn_writes", "tinysql_wal_torn_writes_total"),
    ("wal_size_bytes", "tinysql_wal_size_bytes"),
    ("checkpoints", "tinysql_wal_checkpoints_total"),
    ("checkpoint_s", "tinysql_wal_checkpoint_seconds_total"),
    ("checkpoint_errors", "tinysql_wal_checkpoint_errors_total"),
    ("recoveries", "tinysql_recovery_runs_total"),
    ("replayed_records", "tinysql_recovery_replayed_records_total"),
    ("recovered_locks", "tinysql_recovery_locks_total"),
    ("truncated_tails", "tinysql_recovery_truncated_tails_total"),
    ("gc_runs", "tinysql_gc_runs_total"),
    ("gc_removed", "tinysql_gc_removed_versions_total"),
)

#: obs/flight.py STATS key -> metric name (ONE map shared by the
#: /metrics render and the tsring "flight" source).  All counters; the
#: family only appears once the recorder is armed and moving.
FLIGHT_METRIC_NAMES = (
    ("segments", "tinysql_flight_segments_total"),
    ("segment_bytes", "tinysql_flight_segment_bytes_total"),
    ("fsyncs", "tinysql_flight_fsyncs_total"),
    ("final_flushes", "tinysql_flight_final_flushes_total"),
    ("compactions", "tinysql_flight_compactions_total"),
    ("torn_truncations", "tinysql_flight_torn_truncations_total"),
    ("prior_segments_loaded", "tinysql_flight_prior_segments_total"),
    ("errors", "tinysql_flight_errors_total"),
    ("self_s", "tinysql_flight_self_seconds_total"),
)

#: STATS keys that are high-water marks (gauges), not accumulators —
#: THE definition; kernels imports it (as ``_HWM_KEYS``) so the
#: /metrics render and this registry can never disagree on
#: gauge-vs-counter, and declaring it here keeps this module
#: importable without jax
HWM_STATS_KEYS = ("pipe_depth_hwm",)

# device-economics names come from the _DEVICE_METRICS map above (one
# definition of the STATS-key -> prometheus-name mapping)
for _k, (_name, _help) in _DEVICE_METRICS.items():
    METRICS[_name] = ("gauge" if _k in HWM_STATS_KEYS else "counter",
                      _help)
# auto-prewarm worker counters (session/prewarm.py PREWARM_STATS keys)
for _k in ("cycles", "families_warmed", "bucket_programs",
           "stacked_programs", "errors",
           "skipped_cooldown", "skipped_budget", "skipped_satisfied"):
    METRICS[f"tinysql_prewarm_worker_{_k}_total"] = (
        "counter", f"Auto-prewarm worker {_k.replace('_', ' ')}")
# per-role busy-sample counters (obs/conprof.py): the role catalogue is
# closed and owned by conprof (one definition shared with the ring
# source and the cpu-saturation rule), so every role's counter is a
# registered name
from .conprof import ROLES as _CONPROF_ROLES  # noqa: E402  (jax-free)
from .conprof import role_metric as _conprof_role_metric  # noqa: E402
for _r in _CONPROF_ROLES:
    METRICS[_conprof_role_metric(_r)] = (
        "counter", f"Busy (non-idle) stack samples on {_r} threads")


def registered(name: str) -> bool:
    """Is ``name`` a declared metric?  (The tsring sample-time check.)"""
    return name in METRICS


def query_counter_totals() -> Dict[str, float]:
    """The query-lifecycle counters summed across their ``kind`` labels —
    the flat (label-free) form the time-series ring samples."""
    with _mu:
        out: Dict[str, float] = {}
        for (metric, _labels), v in _QUERY_COUNTERS.items():
            out[metric] = out.get(metric, 0) + v
    return out


def _bump(metric: str, labels: tuple, n: float) -> None:
    with _mu:
        key = (metric, labels)
        _QUERY_COUNTERS[key] = _QUERY_COUNTERS.get(key, 0) + n


def observe_query(kind: str, seconds: float, slow: bool = False,
                  error: bool = False) -> None:
    """Record one finished statement (kind = lowercased statement class,
    e.g. ``select`` / ``insert`` / ``explain``)."""
    labels = (("kind", kind),)
    _bump("tinysql_queries_total", labels, 1)
    _bump("tinysql_query_seconds_sum", labels, seconds)
    if slow:
        _bump("tinysql_slow_queries_total", labels, 1)
    if error:
        _bump("tinysql_query_errors_total", labels, 1)


def reset() -> None:
    """Tests only."""
    with _mu:
        _QUERY_COUNTERS.clear()


def _fmt_labels(labels: tuple) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in labels)
    return "{" + inner + "}"


def _fmt_value(v) -> str:
    if isinstance(v, float) and not v.is_integer():
        return repr(v)
    return str(int(v))


def render_prometheus() -> str:
    """The /metrics payload.  Imports the device layer lazily so the
    status server stays importable without jax."""
    lines: List[str] = []

    def emit(name: str, help_text: str, mtype: str,
             samples: List[Tuple[tuple, float]]) -> None:
        lines.append(f"# HELP {name} {help_text}")
        lines.append(f"# TYPE {name} {mtype}")
        for labels, v in samples:
            lines.append(f"{name}{_fmt_labels(labels)} {_fmt_value(v)}")

    # query-lifecycle counters
    with _mu:
        grouped: Dict[str, List[Tuple[tuple, float]]] = {}
        for (metric, labels), v in sorted(_QUERY_COUNTERS.items()):
            grouped.setdefault(metric, []).append((labels, v))
    for metric in sorted(grouped):
        emit(metric, METRICS.get(metric, ("counter", metric))[1],
             "counter", grouped[metric])

    # device-economics counters (kernels.STATS); the HWM-key set is
    # owned by kernels — one definition, so a new high-water counter
    # can never be mis-exported as an ever-increasing counter here
    try:
        from ..ops import kernels, progcache
        stats = dict(kernels.STATS)
        hwm_keys = kernels._HWM_KEYS
        pstats = progcache.stats_snapshot()
        psize = progcache.size()
    except Exception:  # jax import failure must not kill /metrics
        stats, hwm_keys, pstats, psize = {}, (), {}, None
    for key, (name, help_text) in _DEVICE_METRICS.items():
        if key not in stats:
            continue
        mtype = "gauge" if key in hwm_keys else "counter"
        emit(name, help_text, mtype, [((), stats[key])])
    try:
        pending = len(kernels._PENDING_COSTS) if stats else 0
    except Exception:
        pending = None
    if pending is not None and stats:
        emit("tinysql_pending_cost_analyses",
             METRICS["tinysql_pending_cost_analyses"][1], "gauge",
             [((), pending)])
    if pstats:
        emit("tinysql_progcache_hits_total",
             "In-process program-registry hits", "counter",
             [((), pstats.get("hits", 0))])
        emit("tinysql_compile_seconds_total",
             METRICS["tinysql_compile_seconds_total"][1], "counter",
             [((), pstats.get("compile_wall_s", 0.0))])
        emit("tinysql_progcache_misses_total",
             "In-process program-registry misses (program builds)",
             "counter", [((), pstats.get("misses", 0))])
        emit("tinysql_prewarm_seeded_total",
             "Programs compiled inside a prewarm scope (auto-prewarm "
             "worker / tools/warm.py)", "counter",
             [((), pstats.get("prewarm_seeded", 0))])
        emit("tinysql_prewarm_hits_total",
             "Query-path registry hits on prewarm-seeded programs "
             "(compiles the prewarmer saved real queries)", "counter",
             [((), pstats.get("prewarm_hits", 0))])
    if psize is not None:
        emit("tinysql_progcache_programs", "Registered compiled programs",
             "gauge", [((), psize)])

    # auto-prewarm worker counters (session/prewarm.py PrewarmWorker)
    try:
        from ..session.prewarm import stats_snapshot as prewarm_stats
        pw = prewarm_stats()
    except Exception:
        pw = {}
    if any(pw.values()):
        for k in sorted(pw):
            emit(f"tinysql_prewarm_worker_{k}_total",
                 f"Auto-prewarm worker {k.replace('_', ' ')}", "counter",
                 [((), pw[k])])

    # resilience counters: failpoint fires (per name), device-loss
    # degradation, memory-quota aborts — chaos runs read these to prove
    # every injected fault was actually observed
    try:
        from .. import fail
        fhits = fail.hits()
    except Exception:
        fhits = {}
    if fhits:
        emit("tinysql_failpoint_hits_total", "Failpoint fires by name",
             "counter",
             [((("name", k),), v) for k, v in sorted(fhits.items())])
    try:
        from ..ops import degrade
        dsnap = degrade.snapshot()
    except Exception:
        dsnap = None
    if dsnap is not None:
        emit("tinysql_device_loss_total",
             "Mid-statement accelerator losses observed", "counter",
             [((), dsnap["device_loss_total"])])
        emit("tinysql_degraded_statements_total",
             "Statements transparently re-executed on CPU after a "
             "device loss", "counter",
             [((), dsnap["degraded_statements_total"])])
        emit("tinysql_cpu_pinned",
             "1 while planning is pinned to CPU (device-loss cooldown)",
             "gauge", [((), dsnap["cpu_pinned"])])
    try:
        from ..utils import memory as mem
        emit("tinysql_mem_quota_exceeded_total",
             "Statements aborted by tidb_mem_quota_query", "counter",
             [((), mem.aborts_total())])
    except Exception:
        pass
    # memory-adaptive spill economics (ops/spill.py STATS)
    try:
        from ..ops.spill import stats_snapshot as spill_stats
        sp = spill_stats()
    except Exception:
        sp = {}
    if sp:
        for key, name in (("spill_bytes", "tinysql_spill_bytes_total"),
                          ("spill_reload_bytes",
                           "tinysql_spill_reload_bytes_total"),
                          ("spill_partitions",
                           "tinysql_spill_partitions_total"),
                          ("spill_repartitions",
                           "tinysql_spill_repartitions_total"),
                          ("spill_stream_runs",
                           "tinysql_spill_stream_runs_total"),
                          ("spilled_statements",
                           "tinysql_spilled_statements_total")):
            emit(name, METRICS[name][1], "counter",
                 [((), sp.get(key, 0))])
        emit("tinysql_spill_open_slots",
             METRICS["tinysql_spill_open_slots"][1], "gauge",
             [((), sp.get("open_slots", 0))])
    # mesh-sharded operator tier (ops/shardops.py STATS): rounds,
    # per-shard row HWM, exchange bytes, skew fall-backs, stacked BxN
    try:
        from ..ops.shardops import stats_snapshot as shard_stats
        sh = shard_stats()
    except Exception:
        sh = {}
    if sh:
        for key, name in SHARD_METRIC_NAMES:
            kind = METRICS[name][0]
            emit(name, METRICS[name][1], kind, [((), sh.get(key, 0))])
    # durable MVCC (kv/wal.py STATS): all-zero means no data dir was
    # ever armed — emit nothing so the volatile store's /metrics output
    # is byte-identical to the pre-WAL build
    try:
        from ..kv.wal import stats_snapshot as wal_stats
        wl = wal_stats()
    except Exception:
        wl = {}
    if any(wl.values()):
        for key, name in WAL_METRIC_NAMES:
            kind = METRICS[name][0]
            emit(name, METRICS[name][1], kind, [((), wl.get(key, 0))])
    # flight recorder (obs/flight.py STATS): all-zero means no data dir
    # was armed — emit nothing, same volatile byte-identity discipline
    # as the WAL family above
    try:
        from .flight import stats_snapshot as flight_stats
        fl = flight_stats()
    except Exception:
        fl = {}
    if any(fl.values()):
        for key, name in FLIGHT_METRIC_NAMES:
            emit(name, METRICS[name][1], "counter",
                 [((), fl.get(key, 0))])
    # boot identity: incarnation + start timestamp are the join key the
    # flight surfaces share — emitted armed or not (constant gauges)
    try:
        from .flight import current_incarnation, server_start_ts
        emit("tinysql_incarnation", METRICS["tinysql_incarnation"][1],
             "gauge", [((), current_incarnation())])
        emit("tinysql_server_start_timestamp",
             METRICS["tinysql_server_start_timestamp"][1], "gauge",
             [((), server_start_ts())])
    except Exception:
        pass

    # serving-layer counters: admission verdicts (server/admission.py)
    # and cross-query micro-batching (ops/batching.py)
    try:
        from ..server.admission import stats_snapshot as adm_stats
        adm = adm_stats()
    except Exception:
        adm = {}
    if adm:
        for key in ("admitted", "queued", "rejected"):
            name = f"tinysql_admission_{key}_total"
            emit(name, METRICS[name][1], "counter",
                 [((), adm.get(key, 0))])
        emit("tinysql_admission_queue_wait_seconds_total",
             METRICS["tinysql_admission_queue_wait_seconds_total"][1],
             "counter", [((), adm.get("queue_wait_s_sum", 0.0))])
        try:
            from ..server.admission import aggregate_stmt_mem
            emit("tinysql_stmt_mem_inflight_bytes",
                 METRICS["tinysql_stmt_mem_inflight_bytes"][1], "gauge",
                 [((), aggregate_stmt_mem())])
        except Exception:
            pass
    # wire-layer connection economics: the 1040 accept gate's verdicts
    # (server/admission.py CONN_STATS) + open/idle/active across live
    # servers — the C10k front end's parked connections are visible here
    try:
        from ..server.admission import conn_stats_snapshot
        from ..server.server import conn_gauges
        cst = conn_stats_snapshot()
        cg = conn_gauges()
    except Exception:
        cst, cg = {}, None
    if cst.get("accepts") or cst.get("sheds"):
        emit("tinysql_conn_accepts_total",
             METRICS["tinysql_conn_accepts_total"][1], "counter",
             [((), cst.get("accepts", 0))])
        emit("tinysql_conn_sheds_total",
             METRICS["tinysql_conn_sheds_total"][1], "counter",
             [((), cst.get("sheds", 0))])
    if cg is not None and cg["open"]:
        for key in ("open", "idle", "active"):
            name = f"tinysql_conn_{key}"
            emit(name, METRICS[name][1], "gauge", [((), cg[key])])
    try:
        from ..server.pool import gauges as pool_gauges
        pg = pool_gauges()
    except Exception:
        pg = None
    if pg is not None:
        emit("tinysql_pool_queued", "Statements waiting in the admission "
             "queue (live pools)", "gauge", [((), pg["queued"])])
        emit("tinysql_pool_running", "Statements executing on pool "
             "workers (live pools)", "gauge", [((), pg["running"])])
    try:
        from ..ops.batching import stats_snapshot as batch_stats
        bst = batch_stats()
    except Exception:
        bst = {}
    if bst:
        emit("tinysql_batch_rounds_total",
             "Coalesced same-digest batch rounds dispatched", "counter",
             [((), bst.get("batches", 0))])
        emit("tinysql_batch_statements_total",
             "Statements served through a batch round dispatch",
             "counter", [((), bst.get("batched_statements", 0))])
        emit("tinysql_batch_occupancy_sum",
             "Summed batch occupancy (divide by rounds for the average)",
             "counter", [((), bst.get("occupancy_sum", 0))])
        emit("tinysql_batch_fallbacks_total",
             "Replay consume misses that fell back to solo dispatch",
             "counter", [((), bst.get("fallbacks", 0))])
        emit("tinysql_batch_stacked_rounds_total",
             METRICS["tinysql_batch_stacked_rounds_total"][1],
             "counter", [((), bst.get("stacked_rounds", 0))])
        emit("tinysql_batch_stacked_occupancy_sum",
             METRICS["tinysql_batch_stacked_occupancy_sum"][1],
             "counter", [((), bst.get("stacked_occupancy_sum", 0))])
        emit("tinysql_batch_stack_fallbacks_total",
             METRICS["tinysql_batch_stack_fallbacks_total"][1],
             "counter", [((), bst.get("stack_fallbacks", 0))])
        emit("tinysql_batch_dispatch_seconds_total",
             METRICS["tinysql_batch_dispatch_seconds_total"][1],
             "counter", [((), bst.get("dispatch_s_sum", 0.0))])

    # continuous host profiler (obs/conprof.py): samples, attribution,
    # self-cost, and the per-role busy split — the host-CPU truth feed
    try:
        from . import conprof
        cp = conprof.stats_snapshot()
    except Exception:
        cp = {}
    if cp.get("ticks"):
        for key, name in (("samples", "tinysql_conprof_samples_total"),
                          ("idle_samples",
                           "tinysql_conprof_idle_samples_total"),
                          ("attributed",
                           "tinysql_conprof_attributed_samples_total"),
                          ("ticks", "tinysql_conprof_ticks_total"),
                          ("self_s",
                           "tinysql_conprof_self_seconds_total"),
                          ("evicted", "tinysql_conprof_evicted_total")):
            emit(name, METRICS[name][1], "counter", [((), cp.get(key, 0))])
        for key, name in (("backoff", "tinysql_conprof_backoff"),
                          ("stacks", "tinysql_conprof_stacks"),
                          ("windows", "tinysql_conprof_windows")):
            emit(name, METRICS[name][1], "gauge", [((), cp.get(key, 0))])
        for role, n in sorted(cp.get("role_busy", {}).items()):
            if n:
                name = conprof.role_metric(role)
                emit(name, METRICS[name][1], "counter", [((), n)])

    # continuous heap profiler (obs/memprof.py): sampler self-accounting
    # only — the reconciliation gauges ride the memory_state ring source
    # (a /metrics scrape must never pay for an HBM census walk)
    try:
        from . import memprof
        mp = memprof.stats_snapshot()
    except Exception:
        mp = {}
    if mp.get("ticks"):
        for key, name in (("ticks", "tinysql_memprof_ticks_total"),
                          ("sites", "tinysql_memprof_sites_total"),
                          ("attributed",
                           "tinysql_memprof_attributed_total"),
                          ("self_s",
                           "tinysql_memprof_self_seconds_total"),
                          ("evicted", "tinysql_memprof_evicted_total"),
                          ("errors", "tinysql_memprof_errors_total")):
            emit(name, METRICS[name][1], "counter", [((), mp.get(key, 0))])
        emit("tinysql_memprof_backoff",
             METRICS["tinysql_memprof_backoff"][1], "gauge",
             [((), mp.get("backoff", 1))])

    # time-series sampler self-accounting (obs/tsring.py): the cost of
    # observing is itself observable (bench obs_overhead_frac reads it)
    try:
        from .tsring import stats_snapshot as tsring_stats, RING
        ts = tsring_stats()
        ring_len = RING.size()
    except Exception:
        ts, ring_len = {}, None
    if ts.get("samples"):
        emit("tinysql_metrics_samples_total",
             METRICS["tinysql_metrics_samples_total"][1], "counter",
             [((), ts.get("samples", 0))])
        emit("tinysql_metrics_sample_seconds_total",
             METRICS["tinysql_metrics_sample_seconds_total"][1],
             "counter", [((), ts.get("sample_wall_s", 0.0))])
        emit("tinysql_metrics_dropped_unregistered_total",
             METRICS["tinysql_metrics_dropped_unregistered_total"][1],
             "counter", [((), ts.get("dropped_unregistered", 0))])
    if ring_len is not None:
        emit("tinysql_metrics_ring_entries",
             METRICS["tinysql_metrics_ring_entries"][1], "gauge",
             [((), ring_len)])

    # per-phase statement latency histograms, fed from the statement
    # summary store's ingest path (obs/stmtsummary.py) — the SQL-visible
    # aggregates and the Prometheus histograms share one write hook
    try:
        from .stmtsummary import histogram_snapshot
        hists = histogram_snapshot()
    except Exception:
        hists = {}
    if any(h["count"] for h in hists.values()):
        name = "tinysql_stmt_phase_seconds"
        lines.append(f"# HELP {name} Statement latency by phase "
                     "(statement summary store)")
        lines.append(f"# TYPE {name} histogram")
        for phase in sorted(hists):
            h = hists[phase]
            cum = 0
            for le, count in h["buckets"]:
                cum += count
                lines.append(f'{name}_bucket{{phase="{phase}",'
                             f'le="{le:g}"}} {cum}')
            lines.append(f'{name}_bucket{{phase="{phase}",le="+Inf"}} '
                         f'{h["count"]}')
            lines.append(f'{name}_sum{{phase="{phase}"}} '
                         f'{_fmt_value(float(h["sum"]))}')
            lines.append(f'{name}_count{{phase="{phase}"}} {h["count"]}')

    # measured device-time-per-dispatch histogram (ops/profiler.py) —
    # empty until tidb_device_profile_rate samples a dispatch
    try:
        from ..ops.profiler import histogram_snapshot as prof_hist
        ph = prof_hist()
    except Exception:
        ph = {"count": 0}
    if ph.get("count"):
        name = "tinysql_dispatch_device_seconds"
        lines.append(f"# HELP {name} "
                     f"{METRICS[name][1]}")
        lines.append(f"# TYPE {name} histogram")
        cum = 0
        for le, count in ph["buckets"]:
            cum += count
            lines.append(f'{name}_bucket{{le="{le:g}"}} {cum}')
        lines.append(f'{name}_bucket{{le="+Inf"}} {ph["count"]}')
        lines.append(f'{name}_sum {_fmt_value(float(ph["sum"]))}')
        lines.append(f'{name}_count {ph["count"]}')

    from .trace import recent_traces
    emit("tinysql_trace_ring_entries", "Query traces buffered for "
         "/debug/trace", "gauge", [((), len(recent_traces()))])
    return "\n".join(lines) + "\n"
