"""Process-level metrics and the Prometheus text renderer (/metrics).

Two counter families:

- query-lifecycle counters owned here (``observe_query``): statements
  executed, errors, slow queries, summed wall seconds — labeled by
  statement kind;
- device-economics counters owned by the device layer (``kernels.STATS``
  and ``ops/progcache.STATS``), read at render time.  Those dicts are
  process-cumulative accumulators (plus the ``pipe_depth_hwm`` high-water
  mark, exported as a gauge): exactly the monotonic shape Prometheus
  counters want.

Rendering follows the Prometheus text exposition format 0.0.4 (HELP/TYPE
comment pairs, ``\\n``-terminated sample lines).
"""
from __future__ import annotations

import threading
from typing import Dict, List, Tuple

_mu = threading.Lock()

#: (metric, labels-tuple) -> value;  labels-tuple is ((k, v), ...)
_QUERY_COUNTERS: Dict[Tuple[str, tuple], float] = {}

#: device-layer STATS key -> (prometheus name, help text)
_DEVICE_METRICS = {
    "dispatches": ("tinysql_dispatches_total",
                   "Compiled device-program dispatches"),
    "d2h_transfers": ("tinysql_d2h_transfers_total",
                      "Device-to-host transfer operations"),
    "d2h_bytes": ("tinysql_d2h_bytes_total",
                  "Bytes materialized device-to-host"),
    "host_dispatches": ("tinysql_host_dispatches_total",
                        "Host-twin kernel invocations (numpy twins "
                        "serving the XLA:CPU backend)"),
    "flops": ("tinysql_device_flops_total",
              "XLA cost-analysis FLOPs of dispatched programs"),
    "bytes_accessed": ("tinysql_device_bytes_accessed_total",
                       "XLA cost-analysis bytes accessed"),
    "pipe_blocks": ("tinysql_pipe_blocks_total",
                    "Blocks staged through the async block pipeline"),
    "pipe_stage_s": ("tinysql_pipe_stage_seconds_total",
                     "Host staging wall seconds (pipeline producer)"),
    "pipe_dispatch_s": ("tinysql_pipe_dispatch_seconds_total",
                        "Device dispatch wall seconds inside pipelines"),
    "pipe_drain_s": ("tinysql_pipe_drain_seconds_total",
                     "Result drain wall seconds inside pipelines"),
    "pipe_wall_s": ("tinysql_pipe_wall_seconds_total",
                    "End-to-end pipeline wall seconds"),
    "pipe_depth_hwm": ("tinysql_pipe_depth_hwm",
                       "Staging-queue depth high-water mark"),
}


def _bump(metric: str, labels: tuple, n: float) -> None:
    with _mu:
        key = (metric, labels)
        _QUERY_COUNTERS[key] = _QUERY_COUNTERS.get(key, 0) + n


def observe_query(kind: str, seconds: float, slow: bool = False,
                  error: bool = False) -> None:
    """Record one finished statement (kind = lowercased statement class,
    e.g. ``select`` / ``insert`` / ``explain``)."""
    labels = (("kind", kind),)
    _bump("tinysql_queries_total", labels, 1)
    _bump("tinysql_query_seconds_sum", labels, seconds)
    if slow:
        _bump("tinysql_slow_queries_total", labels, 1)
    if error:
        _bump("tinysql_query_errors_total", labels, 1)


def reset() -> None:
    """Tests only."""
    with _mu:
        _QUERY_COUNTERS.clear()


def _fmt_labels(labels: tuple) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in labels)
    return "{" + inner + "}"


def _fmt_value(v) -> str:
    if isinstance(v, float) and not v.is_integer():
        return repr(v)
    return str(int(v))


def render_prometheus() -> str:
    """The /metrics payload.  Imports the device layer lazily so the
    status server stays importable without jax."""
    lines: List[str] = []

    def emit(name: str, help_text: str, mtype: str,
             samples: List[Tuple[tuple, float]]) -> None:
        lines.append(f"# HELP {name} {help_text}")
        lines.append(f"# TYPE {name} {mtype}")
        for labels, v in samples:
            lines.append(f"{name}{_fmt_labels(labels)} {_fmt_value(v)}")

    # query-lifecycle counters
    with _mu:
        grouped: Dict[str, List[Tuple[tuple, float]]] = {}
        for (metric, labels), v in sorted(_QUERY_COUNTERS.items()):
            grouped.setdefault(metric, []).append((labels, v))
    helps = {
        "tinysql_queries_total": "Statements executed",
        "tinysql_query_seconds_sum":
            "Summed statement execution wall seconds (parse excluded)",
        "tinysql_slow_queries_total":
            "Statements whose exec wall exceeded tidb_slow_log_threshold",
        "tinysql_query_errors_total": "Statements that raised",
    }
    for metric in sorted(grouped):
        emit(metric, helps.get(metric, metric), "counter", grouped[metric])

    # device-economics counters (kernels.STATS); the HWM-key set is
    # owned by kernels — one definition, so a new high-water counter
    # can never be mis-exported as an ever-increasing counter here
    try:
        from ..ops import kernels, progcache
        stats = dict(kernels.STATS)
        hwm_keys = kernels._HWM_KEYS
        pstats = progcache.stats_snapshot()
        psize = progcache.size()
    except Exception:  # jax import failure must not kill /metrics
        stats, hwm_keys, pstats, psize = {}, (), {}, None
    for key, (name, help_text) in _DEVICE_METRICS.items():
        if key not in stats:
            continue
        mtype = "gauge" if key in hwm_keys else "counter"
        emit(name, help_text, mtype, [((), stats[key])])
    if pstats:
        emit("tinysql_progcache_hits_total",
             "In-process program-registry hits", "counter",
             [((), pstats.get("hits", 0))])
        emit("tinysql_progcache_misses_total",
             "In-process program-registry misses (program builds)",
             "counter", [((), pstats.get("misses", 0))])
        emit("tinysql_prewarm_seeded_total",
             "Programs compiled inside a prewarm scope (auto-prewarm "
             "worker / tools/warm.py)", "counter",
             [((), pstats.get("prewarm_seeded", 0))])
        emit("tinysql_prewarm_hits_total",
             "Query-path registry hits on prewarm-seeded programs "
             "(compiles the prewarmer saved real queries)", "counter",
             [((), pstats.get("prewarm_hits", 0))])
    if psize is not None:
        emit("tinysql_progcache_programs", "Registered compiled programs",
             "gauge", [((), psize)])

    # auto-prewarm worker counters (session/prewarm.py PrewarmWorker)
    try:
        from ..session.prewarm import stats_snapshot as prewarm_stats
        pw = prewarm_stats()
    except Exception:
        pw = {}
    if any(pw.values()):
        for k in sorted(pw):
            emit(f"tinysql_prewarm_worker_{k}_total",
                 f"Auto-prewarm worker {k.replace('_', ' ')}", "counter",
                 [((), pw[k])])

    # resilience counters: failpoint fires (per name), device-loss
    # degradation, memory-quota aborts — chaos runs read these to prove
    # every injected fault was actually observed
    try:
        from .. import fail
        fhits = fail.hits()
    except Exception:
        fhits = {}
    if fhits:
        emit("tinysql_failpoint_hits_total", "Failpoint fires by name",
             "counter",
             [((("name", k),), v) for k, v in sorted(fhits.items())])
    try:
        from ..ops import degrade
        dsnap = degrade.snapshot()
    except Exception:
        dsnap = None
    if dsnap is not None:
        emit("tinysql_device_loss_total",
             "Mid-statement accelerator losses observed", "counter",
             [((), dsnap["device_loss_total"])])
        emit("tinysql_degraded_statements_total",
             "Statements transparently re-executed on CPU after a "
             "device loss", "counter",
             [((), dsnap["degraded_statements_total"])])
        emit("tinysql_cpu_pinned",
             "1 while planning is pinned to CPU (device-loss cooldown)",
             "gauge", [((), dsnap["cpu_pinned"])])
    try:
        from ..utils import memory as mem
        emit("tinysql_mem_quota_exceeded_total",
             "Statements aborted by tidb_mem_quota_query", "counter",
             [((), mem.aborts_total())])
    except Exception:
        pass

    # serving-layer counters: admission verdicts (server/admission.py)
    # and cross-query micro-batching (ops/batching.py)
    try:
        from ..server.admission import stats_snapshot as adm_stats
        adm = adm_stats()
    except Exception:
        adm = {}
    if adm:
        for key, help_text in (
                ("admitted", "Statements that began executing on the "
                             "statement pool"),
                ("queued", "Statements that waited in the admission "
                           "queue first"),
                ("rejected", "Statements shed by admission control "
                             "(MySQL 1041)")):
            emit(f"tinysql_admission_{key}_total", help_text, "counter",
                 [((), adm.get(key, 0))])
    try:
        from ..server.pool import gauges as pool_gauges
        pg = pool_gauges()
    except Exception:
        pg = None
    if pg is not None:
        emit("tinysql_pool_queued", "Statements waiting in the admission "
             "queue (live pools)", "gauge", [((), pg["queued"])])
        emit("tinysql_pool_running", "Statements executing on pool "
             "workers (live pools)", "gauge", [((), pg["running"])])
    try:
        from ..ops.batching import stats_snapshot as batch_stats
        bst = batch_stats()
    except Exception:
        bst = {}
    if bst:
        emit("tinysql_batch_rounds_total",
             "Coalesced same-digest batch rounds dispatched", "counter",
             [((), bst.get("batches", 0))])
        emit("tinysql_batch_statements_total",
             "Statements served through a batch round dispatch",
             "counter", [((), bst.get("batched_statements", 0))])
        emit("tinysql_batch_occupancy_sum",
             "Summed batch occupancy (divide by rounds for the average)",
             "counter", [((), bst.get("occupancy_sum", 0))])
        emit("tinysql_batch_fallbacks_total",
             "Replay consume misses that fell back to solo dispatch",
             "counter", [((), bst.get("fallbacks", 0))])

    # per-phase statement latency histograms, fed from the statement
    # summary store's ingest path (obs/stmtsummary.py) — the SQL-visible
    # aggregates and the Prometheus histograms share one write hook
    try:
        from .stmtsummary import histogram_snapshot
        hists = histogram_snapshot()
    except Exception:
        hists = {}
    if any(h["count"] for h in hists.values()):
        name = "tinysql_stmt_phase_seconds"
        lines.append(f"# HELP {name} Statement latency by phase "
                     "(statement summary store)")
        lines.append(f"# TYPE {name} histogram")
        for phase in sorted(hists):
            h = hists[phase]
            cum = 0
            for le, count in h["buckets"]:
                cum += count
                lines.append(f'{name}_bucket{{phase="{phase}",'
                             f'le="{le:g}"}} {cum}')
            lines.append(f'{name}_bucket{{phase="{phase}",le="+Inf"}} '
                         f'{h["count"]}')
            lines.append(f'{name}_sum{{phase="{phase}"}} '
                         f'{_fmt_value(float(h["sum"]))}')
            lines.append(f'{name}_count{{phase="{phase}"}} {h["count"]}')

    from .trace import recent_traces
    emit("tinysql_trace_ring_entries", "Query traces buffered for "
         "/debug/trace", "gauge", [((), len(recent_traces()))])
    return "\n".join(lines) + "\n"
