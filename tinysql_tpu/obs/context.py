"""Per-query observability scope, threaded through ``contextvars``.

The device layer (ops/kernels.py, ops/progcache.py) reports every
counter increment through ``record`` / ``record_hwm``; this module fans
each one out to

- the active statement's ``QueryObs`` (its query-total counters), and
- the ``RuntimeStats`` of the operator whose ``next()`` frame is live
  (set by ``runtime_stats.instrument_tree`` wrappers),

so two sessions executing concurrently collect disjoint per-query
counters — the global ``kernels.STATS`` dict stays monotonic for
``/metrics`` but is no longer the only (and corruptible) attribution
path.  ``contextvars`` gives thread- and task-local scoping for free;
the devpipe producer thread opts in by running inside
``contextvars.copy_context()`` of its creator (executor/devpipe.py
BlockPipeline), which also parents its spans correctly.

Accumulator vs high-water-mark semantics: ``record`` adds, ``record_hwm``
keeps the max seen *within the query scope* (e.g. ``pipe_depth_hwm`` —
a deep staging queue in query N must not bleed into query N+1).
"""
from __future__ import annotations

import contextlib
import contextvars
import threading
import time
from typing import Dict, List, Optional

from .trace import Tracer

_CURRENT: contextvars.ContextVar = contextvars.ContextVar(
    "tinysql_obs_query", default=None)
_CURRENT_OP: contextvars.ContextVar = contextvars.ContextVar(
    "tinysql_obs_op", default=None)
_CURRENT_SPAN: contextvars.ContextVar = contextvars.ContextVar(
    "tinysql_obs_span", default=None)


class RuntimeStats:
    """Per-operator runtime stats (reference: util/execdetails
    RuntimeStats): actual rows emitted, Next loops, inclusive wall time
    in open+next, and the device counters attributed while this
    operator's ``next()`` frame was the innermost live one."""

    __slots__ = ("label", "act_rows", "loops", "wall_s", "open_s",
                 "device", "_mu")

    def __init__(self, label: str):
        self.label = label
        self.act_rows = 0
        self.loops = 0
        self.wall_s = 0.0
        self.open_s = 0.0
        self.device: Dict[str, float] = {}
        self._mu = threading.Lock()

    def add_device(self, key: str, n) -> None:
        with self._mu:
            self.device[key] = self.device.get(key, 0) + n

    def hwm_device(self, key: str, n) -> None:
        with self._mu:
            if n > self.device.get(key, 0):
                self.device[key] = n

    def to_dict(self) -> dict:
        with self._mu:
            dev = dict(self.device)
        return {"label": self.label, "act_rows": self.act_rows,
                "loops": self.loops, "time_ms": round(self.wall_s * 1e3, 3),
                "open_ms": round(self.open_s * 1e3, 3), "device": dev}


class QueryObs:
    """One statement's observability scope: query-total device counters,
    per-operator RuntimeStats (keyed by physical plan node identity),
    and the span tracer.  Mutated from the executing thread and any
    devpipe producer threads it spawns — counter paths take the lock."""

    def __init__(self, sql: str = ""):
        self.sql = sql
        self.started_at = time.time()
        self.tracer = Tracer()
        self.plan_digest = ""
        #: rendered EXPLAIN rows of the placed plan (set by the session
        #: select/explain paths; statements_summary samples them)
        self.plan_rows = None
        #: serving-path wait attribution (set by the session from the
        #: statement pool's measurement): "admitted" ran immediately,
        #: "queued" waited for a worker first, "" never went through the
        #: pool (control statements, embedded execution, pooling off)
        self.admission_verdict = ""
        self.info: Dict[str, float] = {}
        self._mu = threading.Lock()
        self._counters: Dict[str, float] = {}
        self._ops: Dict[int, RuntimeStats] = {}
        self._op_order: List[RuntimeStats] = []
        self._buckets: set = set()

    # ---- counters -------------------------------------------------------
    def add_counter(self, key: str, n) -> None:
        with self._mu:
            self._counters[key] = self._counters.get(key, 0) + n

    def hwm_counter(self, key: str, n) -> None:
        with self._mu:
            if n > self._counters.get(key, 0):
                self._counters[key] = n

    def device_totals(self) -> Dict[str, float]:
        """This query's device counters (the per-query replacement for a
        global ``kernels.stats_snapshot``/``stats_delta`` pair)."""
        with self._mu:
            return dict(self._counters)

    # ---- observed shape buckets ----------------------------------------
    def add_bucket(self, b: int) -> None:
        with self._mu:
            self._buckets.add(b)

    def observed_shape_buckets(self):
        """Power-of-two buckets this query's kernels ACTUALLY padded to
        (recorded by kernels.bucket while the scope was active) — ground
        truth for the prewarm feedback loop, covering fused-pipeline
        input shapes that never flow through an operator's next()."""
        with self._mu:
            return sorted(self._buckets)

    # ---- per-operator stats --------------------------------------------
    def op_stats(self, plan_node, label: str) -> RuntimeStats:
        key = id(plan_node)
        with self._mu:
            st = self._ops.get(key)
            if st is None:
                st = self._ops[key] = RuntimeStats(label)
                self._op_order.append(st)
            return st

    def op_stats_for(self, plan_node) -> Optional[RuntimeStats]:
        with self._mu:
            return self._ops.get(id(plan_node))

    def operators(self) -> List[dict]:
        with self._mu:
            ops = list(self._op_order)
        return [st.to_dict() for st in ops]

    def summary(self) -> dict:
        return {"sql": self.sql, "plan_digest": self.plan_digest,
                "info": dict(self.info), "device": self.device_totals(),
                "operators": self.operators()}


# ---- scope management ----------------------------------------------------

def activate(qobs: QueryObs):
    """Install ``qobs`` as the current statement scope; returns the token
    for ``deactivate``."""
    return _CURRENT.set(qobs)


def deactivate(token) -> None:
    _CURRENT.reset(token)


def current() -> Optional[QueryObs]:
    return _CURRENT.get()


def current_op() -> Optional[RuntimeStats]:
    return _CURRENT_OP.get()


def push_op(st: RuntimeStats):
    return _CURRENT_OP.set(st)


def pop_op(token) -> None:
    _CURRENT_OP.reset(token)


# ---- the device-layer fan-out (called by kernels.stats_add et al.) -------

def record(key: str, n) -> None:
    q = _CURRENT.get()
    if q is None:
        return
    q.add_counter(key, n)
    op = _CURRENT_OP.get()
    if op is not None:
        op.add_device(key, n)


def record_hwm(key: str, n) -> None:
    q = _CURRENT.get()
    if q is None:
        return
    q.hwm_counter(key, n)
    op = _CURRENT_OP.get()
    if op is not None:
        op.hwm_device(key, n)


def record_bucket(b: int) -> None:
    """Called by kernels.bucket: the actual padded shape this query is
    about to compile/dispatch for."""
    q = _CURRENT.get()
    if q is not None:
        q.add_bucket(b)


# ---- spans ---------------------------------------------------------------

@contextlib.contextmanager
def span(name: str, cat: str = "query", **args):
    """Nested span on the current statement's tracer; no-op (None) when
    no statement scope is active.  Nesting rides a contextvar stack, so
    spans recorded on a copied context (devpipe producer) parent to the
    span that was live at copy time."""
    q = _CURRENT.get()
    if q is None:
        yield None
        return
    parent = _CURRENT_SPAN.get()
    s = q.tracer.begin(name, cat=cat,
                       parent=parent.sid if parent else None,
                       args=args or None)
    tok = _CURRENT_SPAN.set(s)
    try:
        yield s
    finally:
        _CURRENT_SPAN.reset(tok)
        q.tracer.end(s)
