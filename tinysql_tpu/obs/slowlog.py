"""Structured JSONL slow-query log (reference: executor/adapter.go
LogSlowQuery + the slow-log parser's field contract).

Replaces the inline ``logging.warning("slow query ...")`` in
session/session.py with one JSON object per slow statement: timings,
plan digest, per-query device counters, and per-operator RuntimeStats —
enough to answer "where did the time go" without re-running the query.
``conn_id`` / ``db`` / ``success`` / ``sql_digest`` make every record
joinable against ``information_schema.statements_summary`` and
``processlist`` (the ``slow_query`` mem-table reads the ring below).

Destinations:
- the ``tinysql_tpu.slowlog`` logger (one JSON line per record);
- an append-only JSONL file when ``TINYSQL_SLOW_LOG`` names a path
  (resolved once per env value, not per record).
  ``TINYSQL_SLOW_LOG_MAX_BYTES`` caps it: when an append would grow the
  file past the cap, the current file rotates to ``<path>.1``
  (tmp→rename, one rotated generation — the reference keeps bounded
  slow-log files the same way) and the append starts a fresh file.
  Rotation is file-plumbing only: the in-process ring and the
  ``slow_query`` mem-table never change behavior;
- an in-process ring (``recent``) for tests, debug endpoints, and the
  ``slow_query`` mem-table — ``TINYSQL_SLOW_LOG_RING`` sizes it
  (default 64; applied on the next :func:`clear`).

The threshold lives in the ``tidb_slow_log_threshold`` sysvar
(milliseconds, default 300 — the reference's default).
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque
from typing import List, Optional, Tuple

LOGGER = logging.getLogger("tinysql_tpu.slowlog")

DEFAULT_RING = 64


def _ring_maxlen() -> int:
    try:
        n = int(os.environ.get("TINYSQL_SLOW_LOG_RING", DEFAULT_RING))
    except ValueError:
        n = DEFAULT_RING
    return n if n > 0 else DEFAULT_RING


_mu = threading.Lock()
_RING: deque = deque(maxlen=_ring_maxlen())

#: (raw env value, resolved absolute path) — the path is resolved ONCE
#: per distinct env value instead of per record; tests that monkeypatch
#: the env var get a fresh resolution automatically
_PATH_CACHE: Tuple[Optional[str], Optional[str]] = (None, None)


def _log_path() -> Optional[str]:
    global _PATH_CACHE
    raw = os.environ.get("TINYSQL_SLOW_LOG")
    cached_raw, cached_path = _PATH_CACHE
    if raw == cached_raw:
        return cached_path
    path = os.path.abspath(raw) if raw else None
    _PATH_CACHE = (raw, path)
    return path


def build_record(sql: str, info: dict, qobs=None, *, conn_id: int = 0,
                 db: str = "", success: bool = True,
                 sql_digest: str = "") -> dict:
    """One slow-log record; ``info`` is the session's per-statement
    timing dict (parse_s is the per-BATCH parse wall, reported once).
    ``conn_id``/``db``/``success``/``sql_digest`` are the join keys the
    ``slow_query`` mem-table exposes."""
    rec = {
        "time": time.strftime("%Y-%m-%dT%H:%M:%S", time.localtime()),
        "sql": sql[:2048].replace("\n", " "),
        "conn_id": int(conn_id),
        "db": db,
        "success": bool(success),
        "total_ms": round(info.get("total_s", 0.0) * 1e3, 3),
        "parse_ms": round(info.get("parse_s", 0.0) * 1e3, 3),
        "plan_ms": round(info.get("plan_s", 0.0) * 1e3, 3),
        "exec_ms": round(info.get("exec_s", 0.0) * 1e3, 3),
        # serving-path wait attribution (server/pool.py measurement):
        # whether this slow statement was slow because it RAN slow or
        # because it WAITED — queue wait is outside total_ms
        "queue_wait_ms": round(info.get("queue_s", 0.0) * 1e3, 3),
        "batch_wait_ms": round(info.get("batch_s", 0.0) * 1e3, 3),
    }
    if sql_digest:
        rec["sql_digest"] = sql_digest
    if qobs is not None:
        rec["plan_digest"] = qobs.plan_digest
        rec["device"] = qobs.device_totals()
        rec["operators"] = qobs.operators()
        if qobs.admission_verdict:
            rec["admission_verdict"] = qobs.admission_verdict
    return rec


def _max_bytes() -> int:
    """``TINYSQL_SLOW_LOG_MAX_BYTES`` (0/absent/junk = unbounded)."""
    try:
        return max(0, int(os.environ.get("TINYSQL_SLOW_LOG_MAX_BYTES",
                                         "0")))
    except ValueError:
        return 0


def _maybe_rotate(path: str, incoming: int) -> None:
    """Size-capped rotation: if appending ``incoming`` bytes would push
    the file past the cap, move it aside as ``<path>.1`` (via a tmp
    name so a crash mid-rotation never leaves ``.1`` half-replaced)."""
    cap = _max_bytes()
    if cap <= 0:
        return
    try:
        size = os.path.getsize(path)
    except OSError:
        return
    if size + incoming <= cap:
        return
    tmp = path + ".1.tmp"
    try:
        os.replace(path, tmp)
        os.replace(tmp, path + ".1")
    except OSError:
        pass  # rotation is best-effort, like the append itself


def log_slow(record: dict) -> None:
    line = json.dumps(record, default=str, sort_keys=True)
    LOGGER.warning("%s", line)
    path = _log_path()
    if path:
        try:
            _maybe_rotate(path, len(line) + 1)
            with open(path, "a", encoding="utf-8") as f:
                f.write(line + "\n")
        except OSError:
            pass  # a full disk must not fail the query
    with _mu:
        _RING.append(record)


def recent(n: Optional[int] = None) -> List[dict]:
    with _mu:
        out = list(_RING)
    return out[-n:] if n else out


def clear() -> None:
    """Drop buffered records; re-reads ``TINYSQL_SLOW_LOG_RING`` so
    tests can resize the ring without reloading the module."""
    global _RING
    with _mu:
        _RING = deque(maxlen=_ring_maxlen())
