"""Structured JSONL slow-query log (reference: executor/adapter.go
LogSlowQuery + the slow-log parser's field contract).

Replaces the inline ``logging.warning("slow query ...")`` in
session/session.py with one JSON object per slow statement: timings,
plan digest, per-query device counters, and per-operator RuntimeStats —
enough to answer "where did the time go" without re-running the query.

Destinations:
- the ``tinysql_tpu.slowlog`` logger (one JSON line per record);
- an append-only JSONL file when ``TINYSQL_SLOW_LOG`` names a path;
- an in-process ring (``recent``) for tests and debug endpoints.

The threshold lives in the ``tidb_slow_log_threshold`` sysvar
(milliseconds, default 300 — the reference's default).
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque
from typing import List, Optional

LOGGER = logging.getLogger("tinysql_tpu.slowlog")

_mu = threading.Lock()
_RING: deque = deque(maxlen=64)


def build_record(sql: str, info: dict, qobs=None) -> dict:
    """One slow-log record; ``info`` is the session's per-statement
    timing dict (parse_s is the per-BATCH parse wall, reported once)."""
    rec = {
        "time": time.strftime("%Y-%m-%dT%H:%M:%S", time.localtime()),
        "sql": sql[:2048].replace("\n", " "),
        "total_ms": round(info.get("total_s", 0.0) * 1e3, 3),
        "parse_ms": round(info.get("parse_s", 0.0) * 1e3, 3),
        "plan_ms": round(info.get("plan_s", 0.0) * 1e3, 3),
        "exec_ms": round(info.get("exec_s", 0.0) * 1e3, 3),
    }
    if qobs is not None:
        rec["plan_digest"] = qobs.plan_digest
        rec["device"] = qobs.device_totals()
        rec["operators"] = qobs.operators()
    return rec


def log_slow(record: dict) -> None:
    line = json.dumps(record, default=str, sort_keys=True)
    LOGGER.warning("%s", line)
    path = os.environ.get("TINYSQL_SLOW_LOG")
    if path:
        try:
            with open(path, "a", encoding="utf-8") as f:
                f.write(line + "\n")
        except OSError:
            pass  # a full disk must not fail the query
    with _mu:
        _RING.append(record)


def recent(n: Optional[int] = None) -> List[dict]:
    with _mu:
        out = list(_RING)
    return out[-n:] if n else out


def clear() -> None:
    with _mu:
        _RING.clear()
