"""Statement summary: the windowed, evicting per-digest aggregation
store behind ``information_schema.statements_summary`` (reference:
util/stmtsummary/statement_summary.go + infoschema/tables.go).

Every finished statement is folded into one :class:`StmtRecord` keyed by
``(normalized-SQL digest, plan digest)``: execution count, sum/max
latency per phase (parse/plan/exec/total), the per-query device
counters (program dispatches, packed D2H transfers/bytes, compile-cache
hits/misses, pipeline blocks), high-water memory, rows returned,
first/last seen, and a sample of the raw SQL + rendered plan.  The
aggregates double as the steady-state feedback signal the cost model
and bucket prewarming read per plan digest.

Window + eviction semantics (the reference's sysvars):

- ``tidb_stmt_summary_refresh_interval`` (seconds): when the current
  window is older than the interval, it rotates into a bounded history
  and aggregation restarts — ``statements_summary`` always shows the
  CURRENT window.
- ``tidb_stmt_summary_max_stmt_count``: at most N distinct keys per
  window; adding key N+1 evicts the least-recently-seen record into a
  single ``evicted`` tombstone row that keeps aggregating (so totals
  stay accountable even when cardinality explodes).

Latency histograms: every ingest also feeds per-phase exponential
histograms (process-cumulative, never rotated) that ``/metrics`` renders
as ``tinysql_stmt_phase_seconds`` — the summary store is the single
write path for both surfaces.

WRITE DISCIPLINE (enforced by qlint OB403): :func:`ingest` — and the
store's mutating methods — may be called ONLY from the session's
statement-close hook (``session/session.py _finish_obs``).  Any other
writer would double-count statements or bypass the window/eviction
accounting.  Reads (``rows``, ``snapshot``, ``histogram_snapshot``,
``normalize``) are fine anywhere.
"""
from __future__ import annotations

import hashlib
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

DEFAULT_REFRESH_INTERVAL_S = 1800
DEFAULT_MAX_STMT_COUNT = 200

#: phases the ingest path buckets into the /metrics histograms; "queue"
#: is the serving-path wait a pooled statement spent waiting for a
#: worker (info key queue_s, measured by server/pool.py) — so a p99
#: regression can be split into queue wait vs execution straight from
#: the histogram
HIST_PHASES = ("parse", "plan", "exec", "queue")

#: phase keys folded into per-record sum/max aggregates ("total" is the
#: statement wall; "queue"/"batch" are serving-path waits OUTSIDE it)
_FOLD_PHASES = ("parse", "plan", "exec", "total", "queue", "batch")

#: upper bounds (seconds) of the latency histogram buckets; +Inf implied
LATENCY_BUCKETS_S = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                     0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)

EVICTED_DIGEST = "evicted"


def normalize(sql: str) -> Tuple[str, str]:
    """``(digest, normalized text)`` of a statement: literals become
    ``?``, unquoted identifiers/keywords lowercase, whitespace collapses
    — so ``SELECT * FROM t WHERE a = 5`` and ``select * from t where
    a=7`` share one digest (reference: parser.Normalize/DigestHash).
    Unlexable input falls back to whitespace-collapsed raw text."""
    from ..parser.lexer import (T_FLOAT, T_INT, T_QIDENT, T_STRING,
                                tokenize)
    try:
        toks = tokenize(sql)
    except Exception:
        text = " ".join(sql.split()).lower()
        return _digest_of(text), text[:1024]
    parts: List[str] = []
    for t in toks:
        if t.kind in (T_INT, T_FLOAT, T_STRING):
            parts.append("?")
        elif t.kind == T_QIDENT:
            parts.append(f"`{t.value}`")
        else:
            parts.append(str(t.text).lower())
    text = " ".join(parts)
    return _digest_of(text), text[:1024]


def _digest_of(text: str) -> str:
    return hashlib.sha1(text.encode()).hexdigest()[:16]


def plan_text(plan_rows) -> str:
    """Flatten rendered EXPLAIN rows (id/estRows/task/info) into the
    sample-plan string stored on a record."""
    if not plan_rows:
        return ""
    return "\n".join("\t".join(str(c) for c in r) for r in plan_rows)


_flatten_plan = plan_text  # ingest's local `plan_text` param shadows it


def _ts(epoch: float) -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%S", time.localtime(epoch))


class StmtRecord:
    """One (sql digest, plan digest) aggregate within a window."""

    __slots__ = ("sql_digest", "digest_text", "plan_digest", "stmt_type",
                 "schema_name", "exec_count", "sum_errors", "sum_ms",
                 "max_ms", "device", "max_mem", "sum_rows", "first_seen",
                 "last_seen", "sample_sql", "sample_plan", "queued_count",
                 "max_spill_bytes", "spill_count", "max_heap_kb")

    def __init__(self, sql_digest: str, digest_text: str,
                 plan_digest: str):
        self.sql_digest = sql_digest
        self.digest_text = digest_text
        self.plan_digest = plan_digest
        self.stmt_type = ""
        self.schema_name = ""
        self.exec_count = 0
        self.sum_errors = 0
        self.sum_ms: Dict[str, float] = {}
        self.max_ms: Dict[str, float] = {}
        self.device: Dict[str, float] = {}
        self.max_mem = 0
        self.sum_rows = 0
        self.first_seen = 0.0
        self.last_seen = 0.0
        self.sample_sql = ""
        self.sample_plan = ""
        self.queued_count = 0
        self.max_spill_bytes = 0
        self.spill_count = 0
        self.max_heap_kb = 0.0

    def fold(self, *, stmt_type: str, schema_name: str,
             info: Dict[str, float], device: Dict[str, float],
             rows_returned: int, error: bool, max_mem: int, sql: str,
             plan: str, now: float, queued: bool = False) -> None:
        self.exec_count += 1
        if error:
            self.sum_errors += 1
        if queued:
            self.queued_count += 1
        self.stmt_type = stmt_type or self.stmt_type
        self.schema_name = schema_name or self.schema_name
        for phase in _FOLD_PHASES:
            ms = float(info.get(f"{phase}_s", 0.0)) * 1e3
            self.sum_ms[phase] = self.sum_ms.get(phase, 0.0) + ms
            if ms > self.max_ms.get(phase, 0.0):
                self.max_ms[phase] = ms
        for k, v in device.items():
            self.device[k] = self.device.get(k, 0) + v
        # memory-adaptive execution: this EXECUTION's spill volume (the
        # device dict is per-statement, so the max/count fold here)
        sp = int(device.get("spill_bytes", 0))
        if sp > 0:
            self.spill_count += 1
            if sp > self.max_spill_bytes:
                self.max_spill_bytes = sp
        # heap truth (obs/memprof.py): this EXECUTION's traced-heap high
        # water (the hwm counter is per-statement, so the max folds here;
        # heap_kb sums through the device loop above)
        hk = float(device.get("heap_peak_kb", 0.0))
        if hk > self.max_heap_kb:
            self.max_heap_kb = hk
        if max_mem > self.max_mem:
            self.max_mem = int(max_mem)
        self.sum_rows += int(rows_returned)
        if not self.first_seen:
            self.first_seen = now
        self.last_seen = now
        if sql and not self.sample_sql:
            self.sample_sql = sql[:2048]
        if plan and not self.sample_plan:
            self.sample_plan = plan[:4096]

    def merge(self, other: "StmtRecord") -> None:
        """Fold ``other`` into this record (tombstone accounting)."""
        self.exec_count += other.exec_count
        self.sum_errors += other.sum_errors
        self.queued_count += other.queued_count
        for p, v in other.sum_ms.items():
            self.sum_ms[p] = self.sum_ms.get(p, 0.0) + v
        for p, v in other.max_ms.items():
            if v > self.max_ms.get(p, 0.0):
                self.max_ms[p] = v
        for k, v in other.device.items():
            self.device[k] = self.device.get(k, 0) + v
        self.max_mem = max(self.max_mem, other.max_mem)
        self.max_spill_bytes = max(self.max_spill_bytes,
                                   other.max_spill_bytes)
        self.spill_count += other.spill_count
        self.max_heap_kb = max(self.max_heap_kb, other.max_heap_kb)
        self.sum_rows += other.sum_rows
        if other.first_seen and (not self.first_seen
                                 or other.first_seen < self.first_seen):
            self.first_seen = other.first_seen
        self.last_seen = max(self.last_seen, other.last_seen)

    def _overlap_frac(self) -> float:
        if not self.device.get("pipe_blocks"):
            return 0.0
        try:
            from ..ops.kernels import pipe_overlap_frac
            return round(pipe_overlap_frac(self.device), 4)
        except Exception:
            return 0.0

    def row(self, window_begin: float) -> list:
        d = self.device
        return [
            _ts(window_begin), self.sql_digest, self.digest_text,
            self.plan_digest, self.stmt_type, self.schema_name,
            self.exec_count, self.sum_errors,
            round(self.sum_ms.get("total", 0.0), 3),
            round(self.max_ms.get("total", 0.0), 3),
            round(self.sum_ms.get("parse", 0.0), 3),
            round(self.max_ms.get("parse", 0.0), 3),
            round(self.sum_ms.get("plan", 0.0), 3),
            round(self.max_ms.get("plan", 0.0), 3),
            round(self.sum_ms.get("exec", 0.0), 3),
            round(self.max_ms.get("exec", 0.0), 3),
            round(self.sum_ms.get("queue", 0.0), 3),
            round(self.max_ms.get("queue", 0.0), 3),
            round(self.sum_ms.get("batch", 0.0), 3),
            self.queued_count,
            int(d.get("dispatches", 0)), int(d.get("d2h_transfers", 0)),
            int(d.get("d2h_bytes", 0)),
            int(d.get("h2d_transfers", 0)), int(d.get("h2d_bytes", 0)),
            int(d.get("progcache_hits", 0)),
            int(d.get("progcache_misses", 0)),
            # device-time truth (ISSUE 11): MEASURED device busy ms from
            # profiled dispatches (0 with tidb_device_profile_rate=0)
            # and the program-build wall attributed to these executions
            round(float(d.get("device_s", 0.0)) * 1e3, 3),
            int(d.get("profiled_dispatches", 0)),
            round(float(d.get("compile_s", 0.0)) * 1e3, 3),
            # host-CPU truth (ISSUE 13): sample-estimated on-thread ms
            # attributed by the continuous profiler (obs/conprof.py; 0
            # with tidb_conprof_rate=0 or no sampler running)
            round(float(d.get("cpu_s", 0.0)) * 1e3, 3),
            int(d.get("cpu_samples", 0)),
            # heap truth (obs/memprof.py): traced-heap growth attributed
            # to these executions (the sum across concurrent statements
            # never exceeds measured process growth) and the traced high
            # water while any of them ran (0 with tidb_memprof_rate=0)
            round(float(d.get("heap_kb", 0.0)), 1),
            round(self.max_heap_kb, 1),
            int(d.get("pipe_blocks", 0)), self._overlap_frac(),
            int(d.get("coalesced", 0)),
            int(d.get("spill_bytes", 0)), self.max_spill_bytes,
            self.spill_count,
            self.max_mem, self.sum_rows,
            _ts(self.first_seen) if self.first_seen else "",
            _ts(self.last_seen) if self.last_seen else "",
            self.sample_sql, self.sample_plan,
        ]

    def to_dict(self) -> dict:
        return {"digest": self.sql_digest, "digest_text": self.digest_text,
                "plan_digest": self.plan_digest,
                "stmt_type": self.stmt_type, "schema": self.schema_name,
                "exec_count": self.exec_count, "errors": self.sum_errors,
                "queued_count": self.queued_count,
                "sum_ms": dict(self.sum_ms), "max_ms": dict(self.max_ms),
                "device": dict(self.device), "max_mem": self.max_mem,
                "max_spill_bytes": self.max_spill_bytes,
                "spill_count": self.spill_count,
                "max_heap_kb": self.max_heap_kb,
                "rows": self.sum_rows, "sample_sql": self.sample_sql}


#: information_schema.statements_summary column order — MUST match
#: StmtRecord.row (catalog/memtables.py builds FieldTypes from this)
COLUMNS = [
    ("summary_begin_time", "str"), ("digest", "str"),
    ("digest_text", "str"), ("plan_digest", "str"), ("stmt_type", "str"),
    ("schema_name", "str"), ("exec_count", "int"), ("sum_errors", "int"),
    ("sum_latency_ms", "real"), ("max_latency_ms", "real"),
    ("sum_parse_ms", "real"), ("max_parse_ms", "real"),
    ("sum_plan_ms", "real"), ("max_plan_ms", "real"),
    ("sum_exec_ms", "real"), ("max_exec_ms", "real"),
    ("sum_queue_wait_ms", "real"), ("max_queue_wait_ms", "real"),
    ("sum_batch_wait_ms", "real"), ("queued_count", "int"),
    ("dispatches", "int"), ("d2h_transfers", "int"), ("d2h_bytes", "int"),
    ("h2d_transfers", "int"), ("h2d_bytes", "int"),
    ("compile_cache_hits", "int"), ("compile_cache_misses", "int"),
    ("sum_device_ms", "real"), ("profiled_dispatches", "int"),
    ("sum_compile_ms", "real"),
    ("sum_cpu_ms", "real"), ("cpu_samples", "int"),
    ("sum_heap_alloc_kb", "real"), ("max_heap_kb", "real"),
    ("pipe_blocks", "int"), ("pipe_overlap_frac", "real"),
    ("coalesced", "int"),
    ("sum_spill_bytes", "int"), ("max_spill_bytes", "int"),
    ("spill_count", "int"),
    ("max_mem_bytes", "int"), ("sum_rows_returned", "int"),
    ("first_seen", "str"), ("last_seen", "str"),
    ("sample_sql", "str"), ("sample_plan", "str"),
]


class SummaryStore:
    """The aggregation store: current window + bounded rotated history
    + process-cumulative latency histograms.  Written from any session
    thread through the designated hook — all paths take the lock."""

    HISTORY_WINDOWS = 4

    def __init__(self, refresh_interval_s: float = DEFAULT_REFRESH_INTERVAL_S,
                 max_stmt_count: int = DEFAULT_MAX_STMT_COUNT):
        self.refresh_interval_s = float(refresh_interval_s)
        self.max_stmt_count = int(max_stmt_count)
        self._mu = threading.Lock()
        self._entries: Dict[Tuple[str, str], StmtRecord] = {}
        self._tombstone: Optional[StmtRecord] = None
        #: anchored by the FIRST ingest (not construction), so injected
        #: test clocks and long-idle processes both start a fresh window
        #: at the first statement
        self.window_begin: Optional[float] = None
        #: rotated windows: (window_begin, [rows...]) — newest last
        self.history: deque = deque(maxlen=self.HISTORY_WINDOWS)
        self._hist = {p: [0] * (len(LATENCY_BUCKETS_S) + 1)
                      for p in HIST_PHASES}
        self._hist_sum = {p: 0.0 for p in HIST_PHASES}
        self._hist_count = {p: 0 for p in HIST_PHASES}

    # ---- the designated write path (session close hook ONLY) ------------
    def ingest(self, *, sql: str, stmt_type: str, schema_name: str,
               plan_digest: str, info: Dict[str, float],
               device: Dict[str, float], rows_returned: int = 0,
               error: bool = False, max_mem: int = 0,
               plan_text: str = "", plan_rows=None,
               sql_digest: str = "",
               digest_text: str = "",
               queued: bool = False,
               refresh_interval_s: Optional[float] = None,
               max_stmt_count: Optional[int] = None,
               now: Optional[float] = None) -> str:
        """Fold one finished statement in; returns the SQL digest.
        ``now`` is injectable for window-rotation tests; the per-call
        interval/max-count overrides carry the session's sysvars."""
        if not sql_digest:
            sql_digest, digest_text = normalize(sql)
        if now is None:
            now = time.time()
        if refresh_interval_s is not None:
            # reads use the most recent session-provided interval for
            # their own staleness check
            self.refresh_interval_s = float(refresh_interval_s)
        interval = self.refresh_interval_s
        max_count = self.max_stmt_count if max_stmt_count is None \
            else int(max_stmt_count)
        key = (sql_digest, plan_digest or "")
        with self._mu:
            if self.window_begin is None:
                self.window_begin = now
            elif interval > 0 and now - self.window_begin >= interval:
                self._rotate(now)
            if max_count > 0:
                # enforce the cap even when it was LOWERED mid-window:
                # one-in-one-out eviction alone would pin the entry
                # count at its old high-water forever
                while len(self._entries) > max_count:
                    self._evict_one()
            rec = self._entries.get(key)
            if rec is None:
                if max_count > 0 and len(self._entries) >= max_count:
                    self._evict_one()
                rec = self._entries[key] = StmtRecord(
                    sql_digest, digest_text, plan_digest or "")
            if not rec.sample_plan and not plan_text and plan_rows:
                # flatten lazily: only the FIRST execution of a digest
                # pays the O(plan-rows) render-to-string
                plan_text = _flatten_plan(plan_rows)
            rec.fold(stmt_type=stmt_type, schema_name=schema_name,
                     info=info, device=device,
                     rows_returned=rows_returned, error=error,
                     max_mem=max_mem, sql=sql, plan=plan_text, now=now,
                     queued=queued)
            for phase in HIST_PHASES:
                v = float(info.get(f"{phase}_s", 0.0))
                # 0.0 means "no measurement for this phase" (wire
                # statements carry no parse wall, non-first batch
                # statements amortize it, bookkeeping statements never
                # plan) — piling zeros into the lowest bucket would make
                # the histogram count statements, not measurements
                if v > 0.0:
                    self._observe(phase, v)
        return sql_digest

    def _rotate(self, now: float) -> None:
        # caller holds the lock
        rows = [r.row(self.window_begin)
                for r in self._window_records()]
        if rows:
            self.history.append((self.window_begin, rows))
        self._entries.clear()
        self._tombstone = None
        self.window_begin = now

    def _evict_one(self) -> None:
        # caller holds the lock: least-recently-seen record folds into
        # the tombstone so window totals stay accountable
        victim_key = min(self._entries,
                         key=lambda k: self._entries[k].last_seen)
        victim = self._entries.pop(victim_key)
        if self._tombstone is None:
            self._tombstone = StmtRecord(EVICTED_DIGEST, "(evicted)", "")
        self._tombstone.merge(victim)

    def _observe(self, phase: str, seconds: float) -> None:
        # caller holds the lock
        buckets = self._hist[phase]
        for i, le in enumerate(LATENCY_BUCKETS_S):
            if seconds <= le:
                buckets[i] += 1
                break
        else:
            buckets[-1] += 1
        self._hist_sum[phase] += seconds
        self._hist_count[phase] += 1

    # ---- reads -----------------------------------------------------------
    def _window_records(self) -> List[StmtRecord]:
        recs = list(self._entries.values())
        if self._tombstone is not None:
            recs.append(self._tombstone)
        return recs

    def _maybe_rotate_stale(self, now: Optional[float]) -> None:
        # caller holds the lock.  Reads must not present a long-expired
        # window as current: after an idle gap the first SELECT scans
        # BEFORE its own close-hook ingest, so rotation has to happen on
        # the read side too.
        if now is None:
            now = time.time()
        if self.window_begin is not None and self.refresh_interval_s > 0 \
                and now - self.window_begin >= self.refresh_interval_s:
            self._rotate(now)

    def rows(self, now: Optional[float] = None) -> List[list]:
        """Current-window rows in ``COLUMNS`` order (the
        ``statements_summary`` mem-table payload), tombstone last.
        ``now`` is injectable for window tests."""
        with self._mu:
            self._maybe_rotate_stale(now)
            begin = self.window_begin or (now if now is not None
                                          else time.time())
            return [r.row(begin) for r in self._window_records()]

    def history_rows(self, now: Optional[float] = None) -> List[list]:
        """Rotated windows (oldest first) followed by the current one —
        the ``statements_summary_history`` mem-table payload (reference:
        statements_summary_history spans the retained windows)."""
        with self._mu:
            self._maybe_rotate_stale(now)
            out = [row for _, wrows in self.history for row in wrows]
            begin = self.window_begin or (now if now is not None
                                          else time.time())
            out.extend(r.row(begin) for r in self._window_records())
            return out

    def snapshot(self, now: Optional[float] = None) -> List[dict]:
        """Debug-endpoint form (dicts, current window)."""
        with self._mu:
            self._maybe_rotate_stale(now)
            return [r.to_dict() for r in self._window_records()]

    def histogram_snapshot(self) -> Dict[str, dict]:
        """Per-phase ``{"buckets": [(le_s, count), ...], "sum": s,
        "count": n}`` with PER-BUCKET (non-cumulative) counts; /metrics
        renders the Prometheus cumulative form."""
        with self._mu:
            out = {}
            for p in HIST_PHASES:
                out[p] = {
                    "buckets": list(zip(LATENCY_BUCKETS_S, self._hist[p])),
                    "overflow": self._hist[p][-1],
                    "sum": self._hist_sum[p],
                    "count": self._hist_count[p],
                }
            return out

    def reset(self) -> None:
        """Tests only: drop windows, history, and histograms."""
        with self._mu:
            self._entries.clear()
            self._tombstone = None
            self.history.clear()
            self.window_begin = None
            for p in HIST_PHASES:
                self._hist[p] = [0] * (len(LATENCY_BUCKETS_S) + 1)
                self._hist_sum[p] = 0.0
                self._hist_count[p] = 0


#: the process-global store every session aggregates into
STORE = SummaryStore()


def ingest(**kw) -> str:
    """THE designated writer (qlint OB403): called from the session's
    statement-close hook only."""
    return STORE.ingest(**kw)


def rows() -> List[list]:
    return STORE.rows()


def history_rows() -> List[list]:
    return STORE.history_rows()


def snapshot() -> List[dict]:
    return STORE.snapshot()


def histogram_snapshot() -> Dict[str, dict]:
    return STORE.histogram_snapshot()
