"""Measured-runtime feedback for bucket prewarming.

The prewarmer (tools/warm.py) derives shape buckets from planner
*estimates*; this module closes the loop with *observed* per-operator
cardinalities: after a statement finishes, ``maybe_emit`` appends one
JSONL record — plan digest, per-operator actual rows, and the
power-of-two buckets those rows land in — to the file named by
``TINYSQL_STATS_FEEDBACK``.  ``tools/warm.py --from-stats FILE`` (via
planner/buckets.merge_feedback) merges those buckets into the AOT
prewarm set, so buckets the estimates missed (stats drift, filters more
or less selective than modeled) still compile ahead of time.
"""
from __future__ import annotations

import json
import os
import threading
from typing import List, Optional

_mu = threading.Lock()


def observed_buckets(qobs) -> List[int]:
    """Buckets this query actually touched, two sources unioned:

    - the shape buckets its kernels PADDED TO (``kernels.bucket``
      reports into the scope) — covers fused-pipeline input shapes that
      never flow through an operator's ``next()``;
    - per-operator actual output rows, re-bucketed.

    Both get the same growth headroom the estimate path applies
    (planner/buckets.buckets_for_rows)."""
    from ..planner.buckets import buckets_for_rows
    out = set()
    for b in qobs.observed_shape_buckets():
        out.update(buckets_for_rows(int(b)))
    for op in qobs.operators():
        out.update(buckets_for_rows(int(op.get("act_rows", 0) or 0)))
    return sorted(out)


def build_record(qobs) -> dict:
    return {"plan_digest": qobs.plan_digest,
            "sql": qobs.sql[:256].replace("\n", " "),
            "buckets": observed_buckets(qobs),
            "operators": [{"label": o["label"],
                           "act_rows": o["act_rows"]}
                          for o in qobs.operators()]}


def maybe_emit(qobs, path: Optional[str] = None) -> Optional[dict]:
    """Append this query's feedback record when a destination is
    configured (arg > TINYSQL_STATS_FEEDBACK env); never raises."""
    path = path or os.environ.get("TINYSQL_STATS_FEEDBACK")
    if not path or qobs is None:
        return None
    try:
        rec = build_record(qobs)
        if not rec["buckets"]:
            return None
        with _mu:
            with open(path, "a", encoding="utf-8") as f:
                f.write(json.dumps(rec, sort_keys=True) + "\n")
        return rec
    except Exception:
        return None  # feedback is advisory; the query already succeeded
