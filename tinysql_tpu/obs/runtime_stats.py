"""Executor-tree instrumentation: collect per-operator RuntimeStats via
the Open/Next/Close interface.

``instrument_tree`` walks an executor tree (the ``children`` lists) and
wraps each node's ``open``/``next``/``close`` *instance* methods with
timing + row-count closures.  No executor class changes: the wrappers
shadow the class methods per instance, so internal calls like
``Executor.drain`` (``self.next()``) and parent→child pulls hit the
instrumented path.

Attribution model:

- ``act_rows`` / ``loops`` — rows emitted by / calls into ``next()``.
- ``wall_s`` — INCLUSIVE time in ``next()`` (children included), the
  reference's EXPLAIN ANALYZE `time` semantics.
- device counters — while an operator's ``next()`` frame runs, it is
  pushed as the *current op* (a contextvar), and every
  ``kernels.stats_add`` lands on the innermost live operator: the one
  actually dispatching programs / pulling D2H.  Device work done by a
  devpipe producer thread attributes to the operator that created the
  pipeline (BlockPipeline copies the creator's context).

DevPipeExec builds its per-operator fallback tree lazily inside
``open``/``next``; it checks for the ``_obs_qobs`` attribute this module
plants and instruments the fallback tree with the same scope, so a
pipeline bail-out still yields per-operator stats.
"""
from __future__ import annotations

import time
from typing import Optional

from .context import QueryObs, pop_op, push_op


def _plan_of(ex):
    """The physical plan node an executor was built from (tagged by
    executor builders as ``_obs_plan``; TPU/CPU executors that keep a
    ``plan`` attribute work untagged)."""
    p = getattr(ex, "_obs_plan", None)
    if p is None:
        p = getattr(ex, "plan", None)
    return p if p is not None else ex


def _label(ex, plan) -> str:
    op = getattr(plan, "op_name", None)
    if callable(op):
        try:
            name = op()
        except Exception:
            name = type(ex).__name__
    else:
        name = type(ex).__name__
    if getattr(plan, "use_tpu", False):
        name += "(TPU)"
    return name


def instrument_node(ex, qobs: QueryObs) -> None:
    """Wrap one executor instance's open/next/close (idempotent)."""
    if getattr(ex, "_obs_wrapped", False):
        return
    ex._obs_wrapped = True
    ex._obs_qobs = qobs  # DevPipeExec fallback-tree hook
    plan = _plan_of(ex)
    if qobs.op_stats_for(plan) is not None:
        # a delegate pair shares one plan node (DevPipeExec and the root
        # of its per-operator fallback tree): the outer wrapper already
        # counts every chunk the inner one emits — wrapping both would
        # double actRows/loops/wall
        return
    st = qobs.op_stats(plan, _label(ex, plan))
    orig_open, orig_next, orig_close = ex.open, ex.next, ex.close

    def open_(ctx):
        t0 = time.perf_counter()
        tok = push_op(st)
        try:
            return orig_open(ctx)
        finally:
            pop_op(tok)
            st.open_s += time.perf_counter() - t0

    def next_():
        t0 = time.perf_counter()
        tok = push_op(st)
        try:
            chk = orig_next()
        finally:
            pop_op(tok)
            st.wall_s += time.perf_counter() - t0
        st.loops += 1
        if chk is not None:
            st.act_rows += chk.num_rows()
        return chk

    def close_():
        tok = push_op(st)
        try:
            return orig_close()
        finally:
            pop_op(tok)

    ex.open = open_
    ex.next = next_
    ex.close = close_


def instrument_tree(root, qobs: Optional[QueryObs]) -> None:
    """Instrument every node reachable through ``children`` (and the
    devpipe fallback tree, when one already exists)."""
    if qobs is None or root is None:
        return
    stack = [root]
    seen = set()
    while stack:
        ex = stack.pop()
        if id(ex) in seen:
            continue
        seen.add(id(ex))
        instrument_node(ex, qobs)
        stack.extend(getattr(ex, "children", ()) or ())
        fb = getattr(ex, "_fallback", None)
        if fb is not None:
            stack.append(fb)
