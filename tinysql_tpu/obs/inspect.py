"""Automated inspection engine: the system diagnosing itself (reference
lineage: TiDB's ``information_schema.inspection_result`` — a registered
rule catalogue evaluated over the metrics store, each finding carrying
severity, details, and the metric evidence that triggered it).

Rules evaluate over the time-series ring (obs/tsring.py) and the
statement-summary store: each one is a plain function registered in the
RULE catalogue via :func:`rule`, receiving an :class:`InspectionContext`
(windowed metric deltas/series + summary records) and yielding
:class:`Finding`\\ s.  ``run()`` — the ``inspection_result`` mem-table
payload and the ``/debug/inspection`` endpoint — evaluates every rule
and never raises: a broken rule reports ITSELF as a finding instead of
taking the surface down.

The registered catalogue (each has an induced-condition test in
tests/test_tsring.py):

- **compile-storm**: program-build (progcache miss) burst within the
  window — literal parameterization or prewarm regressed, or an
  unparameterized workload arrived;
- **progcache-hit-rate**: registry hit rate collapsed under real lookup
  traffic;
- **pool-saturation**: admission shed statements (1041) in the window,
  or the queue gauge stayed deep — the serving tier is saturated;
- **cooldown-flapping**: repeated device losses within one window keep
  re-pinning planning to CPU (a flapping accelerator, not a blip);
- **memory-pressure**: statements aborted on tidb_mem_quota_query;
- **spill-pressure**: statements running memory-adaptive (spilling)
  execution within the window — the quota is actively constraining the
  workload (warning), escalating to critical when recursive
  repartitioning fires (working sets far beyond the budget);
- **prewarm-starvation**: the auto-prewarm worker left candidates
  unwarmed (budget exhausted / errors) while cold-run-shaped latency
  exists — the cold-start killer is starved;
- **dispatch-storm** (ISSUE 11): device dispatches per query regressed
  past the threshold within the window — plan fusion / micro-batching
  stopped covering the workload, or block sizes collapsed;
- **transfer-bound** (ISSUE 11): D2H bytes moved in the window dwarf
  the MEASURED device busy time (sampling profiler on) — latency is
  the link, not the kernels;
- **recompile-churn** (ISSUE 11): program builds keep landing on WARM
  digest families (statements_summary: misses across executions far
  beyond the first run's) — literal parameterization or shape
  bucketing regressed for those families;
- **slo-burn** (ISSUE 11, ROADMAP item 3): the exec-phase latency
  histogram shows > 1% of windowed measurements over the armed
  ``tidb_slo_p99_ms`` — the p99 objective's error budget is burning.
  Fed by the ``slo`` ring source (:func:`slo_sample`).
- **batching-degraded** (ISSUE 14): too many batched replay attempts
  fell back to solo dispatch within the window (consume misses —
  replica rotation, plan re-placement, param-layout churn): the
  coalescer is paying its protocol cost without the one-dispatch win;
- **connection-pressure** (ISSUE 15): the accept gate is refusing
  connects with MySQL 1040 (``tinysql_conn_sheds_total``); critical
  when a window sheds more connections than it admits;
- **shard-imbalance** (ISSUE 17): sharded operator attempts keep
  abandoning for partition skew (``tinysql_shard_skew_retries_total``)
  — one hash partition rivals the whole input, so the mesh sits idle
  while those operators run single-device; critical when the window
  abandoned more attempts than it completed sharded rounds;
- **wal-stall** (ISSUE 19): the durability journal is degraded — mean
  WAL fsync wall time within the window past threshold (under
  ``tidb_wal_fsync=strict`` every commit-class ack pays it), or any
  append/fsync ERROR at all (critical: writes surface typed WalErrors
  and nothing new is durable until the log is writable);
- **cpu-saturation** (ISSUE 13): one thread role dominates the busy
  host-CPU samples (obs/conprof.py) while the admission queue is
  non-empty — the serving tier's latency is host CPU in that role, and
  /debug/conprof has the dominant stacks;
- **profiler-overhead** (ISSUE 13): the continuous profiler's own
  sampling cost ran past its budget share of one core — the rule
  reports it while the sampler's backoff divisor absorbs it;
- **heap-growth** (ISSUE 18): the MEASURED python heap
  (obs/memprof.py) rose monotonically across the window past the
  threshold — leak-shaped growth, with /debug/heap holding the sites;
- **hbm-pressure** (ISSUE 18): the HBM census approaches the backend's
  exposed device-memory capacity (silent on CPU, which exposes none);
- **mem-untracked** (ISSUE 18): measured heap growth diverged from the
  MemTracker ledger beyond the documented band — allocation the
  spill/admission gates cannot see.

Thresholds are module-level constants, deliberately conservative: an
inspection finding is a diagnosis, so false positives cost trust.
"""
from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional

from . import tsring

# ---- thresholds -----------------------------------------------------------

#: default evidence window for the serving surfaces (inspection_result,
#: /debug/inspection): a finding is a diagnosis of what is wrong NOW,
#: so the mem-table judges the last 5 minutes — not the whole retained
#: ring, where one transient 1041 spike would read as a live critical
#: finding until it aged past tidb_metrics_retention (15 min default)
DEFAULT_WINDOW_S = 300

#: progcache misses within the window that count as a compile storm
COMPILE_STORM_MISSES = 8
#: minimum registry lookups before the hit-rate rule may judge
HIT_RATE_MIN_LOOKUPS = 20
HIT_RATE_FLOOR = 0.5
#: sustained queue depth (max over window) that flags saturation even
#: without sheds
POOL_QUEUED_WARN = 8
#: device losses within one window = flapping (one loss is a blip the
#: cooldown already absorbs)
COOLDOWN_FLAP_LOSSES = 2
#: spilled bytes within the window that make the spill-pressure rule
#: speak up (a trickle of spilling is the feature working as designed)
SPILL_PRESSURE_BYTES = 1 << 20
#: device dispatches per query that count as a storm (warm fused plans
#: run 1-6 programs per query; micro-batching drives it under 1), and
#: the minimum windowed query traffic before the ratio may judge
DISPATCH_STORM_PER_QUERY = 12
DISPATCH_STORM_MIN_QUERIES = 10
#: D2H volume floor and the bytes-per-measured-device-second ratio
#: beyond which a window reads transfer-bound (1 GiB per busy second:
#: the link is doing orders of magnitude more work than the device)
TRANSFER_BOUND_MIN_BYTES = 32 << 20
TRANSFER_BOUND_BYTES_PER_DEVICE_S = 1 << 30
#: recompile churn: a digest family with at least this many executions
#: whose summed program builds exceed misses-per-exec x executions is
#: compiling on WARM runs, not just its first
RECOMPILE_MIN_EXECS = 4
RECOMPILE_MISSES_PER_EXEC = 1.5
#: SLO burn: minimum windowed exec measurements before judging, and the
#: breach fraction that burns a p99 objective's error budget (1%)
SLO_MIN_MEASUREMENTS = 20
SLO_BURN_FRAC = 0.01
#: cpu-saturation: minimum windowed BUSY profiler samples before the
#: role-share ratio may judge, and the share at which one role reads
#: window-dominant (only judged while the admission queue was non-empty
#: — a dominant role with an empty queue is just the workload's shape)
CPU_SAT_MIN_BUSY_SAMPLES = 50
CPU_SAT_DOMINANT_SHARE = 0.6
CPU_SAT_CRITICAL_SHARE = 0.85
#: profiler-overhead: the sampler's self-cost share of one core beyond
#: which the finding fires (obs/conprof.py backs its rate off at the
#: same budget — the rule reports what the backoff is absorbing)
PROFILER_OVERHEAD_BUDGET = 0.03
#: batching-degraded: minimum windowed replay ATTEMPTS (replays +
#: consume-miss fallbacks) / stacked-leg GROUPS (stacked rounds +
#: stack fallbacks) before either degradation ratio may judge, and the
#: degraded share at warning / critical (shared by both legs).  The
#: stacked leg is judged separately in its own units — a group that
#: fell back to back-to-back replays still coalesced and replays
#: cleanly, but the one-dispatch win is gone
BATCH_DEGRADED_MIN_ATTEMPTS = 10
BATCH_DEGRADED_MIN_GROUPS = 5
BATCH_DEGRADED_WARN = 0.20
BATCH_DEGRADED_CRIT = 0.50

#: shard-imbalance: sharded attempts abandoned for partition skew
#: within the window before the rule speaks — one clustered key set
#: bailing to the single-device kernel is the capacity gate working as
#: designed, a stream of them means the mesh is idle for this workload
SHARD_SKEW_RETRIES_WARN = 2

#: wal-stall (ISSUE 19): minimum windowed fsyncs before the mean may
#: judge (one slow sync on a cold disk is noise), and the mean fsync
#: wall seconds at warning / critical — past these every commit-class
#: ack under the strict policy eats the stall, so commit latency IS
#: the disk.  Any windowed append/fsync error is critical outright:
#: the durability path itself failed.
WAL_STALL_MIN_FSYNCS = 5
WAL_STALL_MEAN_WARN_S = 0.010
WAL_STALL_MEAN_CRIT_S = 0.050

#: connection-pressure (ISSUE 15): minimum windowed 1040 sheds before
#: the rule speaks at all — one refused connect is a client retrying
#: against a deliberately small cap, not pressure
CONN_SHEDS_WARN = 2

#: heap-growth (ISSUE 18): minimum sampled points of the traced-heap
#: gauge before monotone-rise leak detection may judge, the fraction of
#: point-to-point steps that must be rises (a sawtooth heap is a cache,
#: not a leak), and the total windowed rise in bytes that makes the
#: pattern worth reporting
HEAP_GROWTH_MIN_POINTS = 4
HEAP_GROWTH_RISE_FRAC = 0.9
HEAP_GROWTH_MIN_BYTES = 32 << 20
#: hbm-pressure: census share of the backend's exposed device-memory
#: capacity at which the finding fires (never on backends that expose
#: no limit — CPU reads bytes_limit 0)
HBM_PRESSURE_FRAC = 0.85
HBM_PRESSURE_CRIT_FRAC = 0.95


class Finding:
    """One diagnosis: rule, severity, the metric evidence window."""

    __slots__ = ("rule", "item", "severity", "details", "metric",
                 "start_ts", "end_ts", "first_value", "last_value",
                 "max_value")

    def __init__(self, rule: str, item: str, severity: str, details: str,
                 metric: str = "", start_ts: float = 0.0,
                 end_ts: float = 0.0, first_value: float = 0.0,
                 last_value: float = 0.0, max_value: float = 0.0):
        self.rule = rule
        self.item = item
        self.severity = severity      # "warning" | "critical"
        self.details = details
        self.metric = metric
        self.start_ts = start_ts
        self.end_ts = end_ts
        self.first_value = first_value
        self.last_value = last_value
        self.max_value = max_value

    def to_dict(self) -> dict:
        return {k: getattr(self, k) for k in self.__slots__}

    def row(self) -> list:
        stamp = tsring._ts(time.time())
        return [stamp, self.rule, self.item, self.severity, self.details,
                self.metric,
                tsring._ts(self.start_ts) if self.start_ts else "",
                tsring._ts(self.end_ts) if self.end_ts else "",
                float(self.first_value), float(self.last_value),
                float(self.max_value)]


#: information_schema.inspection_result column order — MUST match
#: Finding.row
COLUMNS = [
    ("time", "str"), ("rule", "str"), ("item", "str"),
    ("severity", "str"), ("details", "str"), ("metric", "str"),
    ("evidence_start", "str"), ("evidence_end", "str"),
    ("first_value", "real"), ("last_value", "real"),
    ("max_value", "real"),
]


class InspectionContext:
    """What a rule sees: windowed reads over the ring + the statement
    summary.  ``window_s`` bounds the evidence span (None = everything
    retained)."""

    def __init__(self, ring: tsring.MetricsRing,
                 now: Optional[float] = None,
                 window_s: Optional[float] = None):
        self.now = time.time() if now is None else now
        self.window_s = window_s
        # ONE consistent copy for the whole evaluation: every rule's
        # delta/max/evidence reads see the same samples, so a finding's
        # evidence can never disagree with the delta that triggered it
        # (and a full run takes one ring lock, not ~15)
        self._samples = ring.snapshot_samples()

    def series(self, metric: str) -> List[tuple]:
        since = self.now - self.window_s if self.window_s else None
        out: List[tuple] = []
        for ts, vals in self._samples:
            if (since is not None and ts < since) or ts > self.now:
                continue
            if metric in vals:
                out.append((ts, float(vals[metric])))
        return out

    def delta(self, metric: str) -> float:
        """last - first over the window (0 with < 2 points)."""
        pts = self.series(metric)
        return pts[-1][1] - pts[0][1] if len(pts) >= 2 else 0.0

    def max_value(self, metric: str) -> float:
        pts = self.series(metric)
        return max(v for _, v in pts) if pts else 0.0

    def last(self, metric: str) -> float:
        pts = self.series(metric)
        return pts[-1][1] if pts else 0.0

    def evidence(self, rule: str, item: str, severity: str, details: str,
                 metric: str) -> Finding:
        """Build a finding whose evidence window is the metric's sampled
        span."""
        pts = self.series(metric)
        return Finding(
            rule, item, severity, details, metric,
            start_ts=pts[0][0] if pts else 0.0,
            end_ts=pts[-1][0] if pts else 0.0,
            first_value=pts[0][1] if pts else 0.0,
            last_value=pts[-1][1] if pts else 0.0,
            max_value=max((v for _, v in pts), default=0.0))

    def summary_records(self) -> List[dict]:
        from . import stmtsummary
        return stmtsummary.snapshot()


# ---- the SLO objective (tidb_slo_p99_ms) ----------------------------------

#: the armed p99 objective in MILLISECONDS (0 = no SLO): process-global
#: module state applied by the session SET hook / server start, like
#: kernels.set_compile_cache_dir — there is one latency surface
SLO_STATE = {"p99_ms": 0.0}


def set_slo_p99_ms(ms: float) -> None:
    try:
        v = float(ms)
    except (TypeError, ValueError):
        v = 0.0
    # qlint: disable=CC701 -- single GIL-atomic scalar-slot publish (no compound invariant); readers tolerate either the old or new objective
    SLO_STATE["p99_ms"] = max(v, 0.0)


def slo_p99_ms() -> float:
    return SLO_STATE["p99_ms"]


def slo_sample() -> Dict[str, float]:
    """The ``slo`` ring source payload: total exec-phase measurements
    and the count PROVABLY over the armed threshold (bucket lower edge
    >= SLO — conservative; the overflow bucket counts whenever the SLO
    sits at or under the last bound).  Sampled into the ring so the
    slo-burn rule judges a windowed DELTA, not the whole process
    history.  Empty while no SLO is armed."""
    slo_ms = SLO_STATE["p99_ms"]
    if slo_ms <= 0:
        return {}
    from .stmtsummary import histogram_snapshot
    h = histogram_snapshot().get("exec")
    if not h:
        return {}
    slo_s = slo_ms / 1e3
    over = 0
    prev = 0.0
    for le, count in h["buckets"]:
        if prev >= slo_s:
            over += count
        prev = le
    if slo_s <= prev:
        over += h.get("overflow", 0)
    # the armed threshold rides along as a gauge: breach counts are
    # recomputed over the cumulative histogram against the CURRENT
    # threshold, so the slo-burn rule must discard windows where the
    # objective changed (a lowered SLO would otherwise reclassify all
    # history as one window's breach delta)
    return {"tinysql_slo_exec_measurements_total": h["count"],
            "tinysql_slo_exec_breaches_total": over,
            "tinysql_slo_p99_ms": slo_ms}


# ---- the rule catalogue ---------------------------------------------------

RULES: Dict[str, Callable[[InspectionContext], List[Finding]]] = {}


def rule(name: str):
    """Register one inspection rule (decorator)."""
    def deco(fn):
        RULES[name] = fn
        return fn
    return deco


@rule("compile-storm")
def _rule_compile_storm(ctx: InspectionContext) -> List[Finding]:
    metric = "tinysql_progcache_misses_total"
    d = ctx.delta(metric)
    if d < COMPILE_STORM_MISSES:
        return []
    sev = "critical" if d >= 2 * COMPILE_STORM_MISSES else "warning"
    return [ctx.evidence(
        "compile-storm", "progcache", sev,
        f"{d:.0f} program builds within the window (threshold "
        f"{COMPILE_STORM_MISSES}): literal parameterization or prewarm "
        "is missing this workload's digest families", metric)]


@rule("progcache-hit-rate")
def _rule_hit_rate(ctx: InspectionContext) -> List[Finding]:
    hits = ctx.delta("tinysql_progcache_hits_total")
    misses = ctx.delta("tinysql_progcache_misses_total")
    lookups = hits + misses
    if lookups < HIT_RATE_MIN_LOOKUPS:
        return []
    rate = hits / lookups
    if rate >= HIT_RATE_FLOOR:
        return []
    return [ctx.evidence(
        "progcache-hit-rate", "progcache", "warning",
        f"registry hit rate {rate:.2f} over {lookups:.0f} lookups "
        f"(floor {HIT_RATE_FLOOR}): the program cache stopped covering "
        "the live workload", "tinysql_progcache_hits_total")]


@rule("pool-saturation")
def _rule_pool_saturation(ctx: InspectionContext) -> List[Finding]:
    out: List[Finding] = []
    shed = ctx.delta("tinysql_admission_rejected_total")
    if shed > 0:
        out.append(ctx.evidence(
            "pool-saturation", "admission", "critical",
            f"{shed:.0f} statement(s) shed with MySQL 1041 within the "
            "window: the admission queue hit its cap — raise "
            "tidb_stmt_pool_size / queue_depth or reduce load",
            "tinysql_admission_rejected_total"))
    deep = ctx.max_value("tinysql_pool_queued")
    if not out and deep >= POOL_QUEUED_WARN:
        out.append(ctx.evidence(
            "pool-saturation", "pool", "warning",
            f"statement queue reached depth {deep:.0f} (threshold "
            f"{POOL_QUEUED_WARN}) without shedding: latency is queue "
            "wait, not execution", "tinysql_pool_queued"))
    return out


@rule("connection-pressure")
def _rule_connection_pressure(ctx: InspectionContext) -> List[Finding]:
    """Sustained 1040 sheds at the accept gate (ISSUE 15): warning
    while some connects are refused, critical when the window shed MORE
    connects than it admitted — the wire tier is turning away the
    majority of new clients."""
    sheds = ctx.delta("tinysql_conn_sheds_total")
    if sheds < CONN_SHEDS_WARN:
        return []
    accepts = ctx.delta("tinysql_conn_accepts_total")
    sev = "critical" if sheds > accepts else "warning"
    return [ctx.evidence(
        "connection-pressure", "wire", sev,
        f"{sheds:.0f} connection(s) refused with MySQL 1040 within the "
        f"window ({accepts:.0f} admitted): tidb_max_server_connections "
        "is actively shedding connects — raise the cap (the aio front "
        "end holds idle connections at ~zero thread cost) or add "
        "serving capacity", "tinysql_conn_sheds_total")]


@rule("cooldown-flapping")
def _rule_cooldown_flapping(ctx: InspectionContext) -> List[Finding]:
    metric = "tinysql_device_loss_total"
    d = ctx.delta(metric)
    if d < COOLDOWN_FLAP_LOSSES:
        return []
    return [ctx.evidence(
        "cooldown-flapping", "device", "critical",
        f"{d:.0f} device losses within the window: the accelerator is "
        "flapping, planning keeps re-pinning to CPU "
        "(tidb_device_cooldown) — investigate the backend, not the "
        "queries", metric)]


@rule("memory-pressure")
def _rule_memory_pressure(ctx: InspectionContext) -> List[Finding]:
    metric = "tinysql_mem_quota_exceeded_total"
    d = ctx.delta(metric)
    if d <= 0:
        return []
    return [ctx.evidence(
        "memory-pressure", "quota", "warning",
        f"{d:.0f} statement(s) aborted on tidb_mem_quota_query within "
        "the window (error 8175): quotas are actively shedding memory "
        "pressure", metric)]


@rule("spill-pressure")
def _rule_spill_pressure(ctx: InspectionContext) -> List[Finding]:
    out: List[Finding] = []
    spilled = ctx.delta("tinysql_spill_bytes_total")
    stmts = ctx.delta("tinysql_spilled_statements_total")
    repart = ctx.delta("tinysql_spill_repartitions_total")
    if repart > 0:
        out.append(ctx.evidence(
            "spill-pressure", "repartition", "critical",
            f"{repart:.0f} recursive repartition event(s) within the "
            "window: working sets far exceed the spill budget "
            "(tidb_mem_quota_query x tidb_mem_quota_spill_ratio) — "
            "statements are one depth-exhaustion away from 8175",
            "tinysql_spill_repartitions_total"))
    if not out and spilled >= SPILL_PRESSURE_BYTES:
        mb = spilled / (1 << 20)
        out.append(ctx.evidence(
            "spill-pressure", "spill", "warning",
            f"{mb:.1f} MiB spilled by {stmts:.0f} statement(s) within "
            "the window: memory-adaptive execution is actively bounding "
            "working sets — latency includes spill I/O; raise "
            "tidb_mem_quota_query if this workload should run resident",
            "tinysql_spill_bytes_total"))
    return out


@rule("prewarm-starvation")
def _rule_prewarm_starvation(ctx: InspectionContext) -> List[Finding]:
    out: List[Finding] = []
    budget = ctx.delta("tinysql_prewarm_worker_skipped_budget_total")
    if budget > 0:
        # size the blast radius from statements_summary: every SELECT
        # family currently aggregating is a potential cold-start victim
        # of a starved warmer
        try:
            fams = sum(1 for r in ctx.summary_records()
                       if (r.get("stmt_type") or "").lower() == "select")
        except Exception:
            fams = 0
        out.append(ctx.evidence(
            "prewarm-starvation", "budget", "warning",
            f"{budget:.0f} prewarm candidate(s) deferred by "
            "tidb_auto_prewarm_budget_ms within the window "
            f"({fams} SELECT families live in statements_summary): "
            "cold-start compiles will land on real queries — raise the "
            "budget or top_k",
            "tinysql_prewarm_worker_skipped_budget_total"))
    errs = ctx.delta("tinysql_prewarm_worker_errors_total")
    if errs > 0:
        out.append(ctx.evidence(
            "prewarm-starvation", "errors", "warning",
            f"{errs:.0f} prewarm warm attempt(s) failed within the "
            "window: their families stay cold for the cooldown",
            "tinysql_prewarm_worker_errors_total"))
    return out


@rule("dispatch-storm")
def _rule_dispatch_storm(ctx: InspectionContext) -> List[Finding]:
    queries = ctx.delta("tinysql_queries_total")
    if queries < DISPATCH_STORM_MIN_QUERIES:
        return []
    dispatches = ctx.delta("tinysql_dispatches_total")
    per_query = dispatches / queries
    if per_query < DISPATCH_STORM_PER_QUERY:
        return []
    sev = "critical" if per_query >= 2 * DISPATCH_STORM_PER_QUERY \
        else "warning"
    return [ctx.evidence(
        "dispatch-storm", "dispatches", sev,
        f"{per_query:.1f} device dispatches per query over "
        f"{queries:.0f} statements within the window (threshold "
        f"{DISPATCH_STORM_PER_QUERY}): plan fusion / micro-batching "
        "stopped covering this workload, or block sizes collapsed — "
        "every extra dispatch pays the link's round trip",
        "tinysql_dispatches_total")]


@rule("transfer-bound")
def _rule_transfer_bound(ctx: InspectionContext) -> List[Finding]:
    moved = ctx.delta("tinysql_d2h_bytes_total")
    if moved < TRANSFER_BOUND_MIN_BYTES:
        return []
    profiled = ctx.delta("tinysql_profiled_dispatches_total")
    if profiled <= 0:
        # no measured device time in the window: judging the ratio
        # against an async submit wall would be exactly the fiction
        # this PR removes
        return []
    busy = ctx.delta("tinysql_device_busy_seconds_total")
    # busy accrues only on SAMPLED dispatches: at a fractional profile
    # rate it covers ~rate of the true device time, so extrapolate by
    # the window's dispatches-per-profiled-dispatch before judging —
    # otherwise a healthy workload at rate 0.1 reads 10x too
    # transfer-bound
    dispatches = ctx.delta("tinysql_dispatches_total")
    est_busy = busy * (max(dispatches, profiled) / profiled)
    ratio = moved / max(est_busy, 1e-9)
    if ratio < TRANSFER_BOUND_BYTES_PER_DEVICE_S:
        return []
    return [ctx.evidence(
        "transfer-bound", "d2h", "warning",
        f"{moved / (1 << 20):.1f} MiB pulled device-to-host against "
        f"~{est_busy * 1e3:.1f} ms of device busy time within the "
        f"window (measured {busy * 1e3:.1f} ms over {profiled:.0f} of "
        f"{dispatches:.0f} dispatches): the workload is transfer-bound "
        "— push projections/filters device-side, or keep results "
        "resident (tidb_device_passthrough)",
        "tinysql_d2h_bytes_total")]


@rule("recompile-churn")
def _rule_recompile_churn(ctx: InspectionContext) -> List[Finding]:
    out: List[Finding] = []
    for r in ctx.summary_records():
        n = int(r.get("exec_count", 0))
        if n < RECOMPILE_MIN_EXECS:
            continue
        misses = float(r.get("device", {}).get("progcache_misses", 0))
        if misses <= n * RECOMPILE_MISSES_PER_EXEC:
            continue
        out.append(Finding(
            "recompile-churn", r.get("digest", ""), "warning",
            f"{misses:.0f} program builds across {n} executions of a "
            "warm digest family (threshold "
            f"{RECOMPILE_MISSES_PER_EXEC}/exec beyond the first run): "
            "literal parameterization or shape bucketing stopped "
            "covering this family — constant variants are compiling "
            "instead of hitting", "tinysql_progcache_misses_total"))
    return out


@rule("batching-degraded")
def _rule_batching_degraded(ctx: InspectionContext) -> List[Finding]:
    out: List[Finding] = []

    def sev_of(ratio: float) -> Optional[str]:
        if ratio < BATCH_DEGRADED_WARN:
            return None
        return "critical" if ratio >= BATCH_DEGRADED_CRIT else "warning"

    # replay leg: members served from round dispatches plus the
    # consume misses that fell back to solo re-dispatch
    replays = ctx.delta("tinysql_batch_statements_total")
    misses = ctx.delta("tinysql_batch_fallbacks_total")
    attempts = replays + misses
    if attempts >= BATCH_DEGRADED_MIN_ATTEMPTS:
        sev = sev_of(misses / attempts)
        if sev:
            out.append(ctx.evidence(
                "batching-degraded", "replay", sev,
                f"{misses / attempts:.0%} of {attempts:.0f} batched "
                "replay attempts within the window fell back to solo "
                f"dispatch (warning {BATCH_DEGRADED_WARN:.0%} / critical "
                f"{BATCH_DEGRADED_CRIT:.0%}): replica rotation or plan "
                "re-placement is defeating the coalescer — batching "
                "pays its collect/replay cost without the win",
                "tinysql_batch_fallbacks_total"))
    # stacked leg, in its own units: groups that should have ridden ONE
    # vmap-batched dispatch but fell back to back-to-back replays
    rounds = ctx.delta("tinysql_batch_stacked_rounds_total")
    stack_falls = ctx.delta("tinysql_batch_stack_fallbacks_total")
    groups = rounds + stack_falls
    if groups >= BATCH_DEGRADED_MIN_GROUPS:
        sev = sev_of(stack_falls / groups)
        if sev:
            out.append(ctx.evidence(
                "batching-degraded", "stacked", sev,
                f"{stack_falls / groups:.0%} of {groups:.0f} stackable "
                "batch groups within the window fell back to the legacy "
                f"back-to-back leg (warning {BATCH_DEGRADED_WARN:.0%} / "
                f"critical {BATCH_DEGRADED_CRIT:.0%}): param-layout "
                "churn or a missing stacking recipe is costing the "
                "one-dispatch-per-round win (results stay correct)",
                "tinysql_batch_stack_fallbacks_total"))
    return out


@rule("shard-imbalance")
def _rule_shard_imbalance(ctx: InspectionContext) -> List[Finding]:
    """Sharded attempts repeatedly abandoned for partition skew
    (ISSUE 17): the hash partitioner keeps producing one block that
    rivals the whole input, so partition-parallel operators bail to
    their single-device kernels and the mesh sits idle.  Evidence is
    the skew-retry delta judged against completed sharded rounds, with
    the per-shard row high-water mark as sizing context."""
    retries = ctx.delta("tinysql_shard_skew_retries_total")
    if retries < SHARD_SKEW_RETRIES_WARN:
        return []
    rounds = ctx.delta("tinysql_shard_rounds_total")
    hwm = ctx.max_value("tinysql_shard_rows_hwm")
    sev = "critical" if retries > rounds else "warning"
    return [ctx.evidence(
        "shard-imbalance", "mesh", sev,
        f"{retries:.0f} sharded attempt(s) abandoned for partition skew "
        f"within the window against {rounds:.0f} completed sharded "
        f"rounds (per-shard row high-water mark {hwm:.0f}): one hash "
        "partition keeps rivaling the whole input, so those operators "
        "ran single-device — this key distribution defeats the "
        "partitioner; results stay correct, the mesh speedup is gone",
        "tinysql_shard_skew_retries_total")]


@rule("wal-stall")
def _rule_wal_stall(ctx: InspectionContext) -> List[Finding]:
    """Durability path degraded (ISSUE 19): WAL fsyncs stalling (under
    the strict policy every commit-class ack waits on one, so commit
    latency IS the disk) or — worse — append/fsync errors, meaning the
    journal itself is failing while the store keeps refusing to diverge
    ahead of it."""
    out: List[Finding] = []
    errs = (ctx.delta("tinysql_wal_append_errors_total")
            + ctx.delta("tinysql_wal_fsync_errors_total"))
    if errs > 0:
        out.append(ctx.evidence(
            "wal-stall", "storage", "critical",
            f"{errs:.0f} WAL append/fsync error(s) within the window: "
            "the durability journal is failing — affected mutations "
            "surfaced typed WalErrors without mutating the store, but "
            "no new write is durable until the log is writable again "
            "(check the data dir's filesystem)",
            "tinysql_wal_fsync_errors_total"))
    fsyncs = ctx.delta("tinysql_wal_fsyncs_total")
    if fsyncs >= WAL_STALL_MIN_FSYNCS:
        mean_s = ctx.delta("tinysql_wal_fsync_seconds_total") / fsyncs
        if mean_s >= WAL_STALL_MEAN_WARN_S:
            sev = ("critical" if mean_s >= WAL_STALL_MEAN_CRIT_S
                   else "warning")
            out.append(ctx.evidence(
                "wal-stall", "storage", sev,
                f"mean WAL fsync took {mean_s * 1000:.1f}ms over "
                f"{fsyncs:.0f} sync(s) in the window: commit-class "
                "acks under tidb_wal_fsync=strict are paying this "
                "stall per statement — a slow or contended data-dir "
                "disk; consider tidb_wal_fsync=relaxed (group commit) "
                "if power-loss durability per ack is not required",
                "tinysql_wal_fsync_seconds_total"))
    return out


@rule("cpu-saturation")
def _rule_cpu_saturation(ctx: InspectionContext) -> List[Finding]:
    # judged only while the admission queue was non-empty in the
    # window: host CPU concentrating in one role while statements WAIT
    # is the serving tier's bottleneck signature (ROADMAP items 2/3)
    queued = ctx.max_value("tinysql_pool_queued")
    if queued <= 0:
        return []
    from .conprof import ROLES, role_metric
    busy = {role: ctx.delta(role_metric(role)) for role in ROLES}
    total = sum(busy.values())
    if total < CPU_SAT_MIN_BUSY_SAMPLES:
        return []
    top_role = max(busy, key=lambda r: busy[r])
    share = busy[top_role] / total
    if share < CPU_SAT_DOMINANT_SHARE:
        return []
    sev = "critical" if share >= CPU_SAT_CRITICAL_SHARE else "warning"
    return [ctx.evidence(
        "cpu-saturation", top_role, sev,
        f"{share:.0%} of {total:.0f} busy host-CPU samples landed on "
        f"{top_role} threads while the admission queue held up to "
        f"{queued:.0f} statement(s): the host tier is CPU-bound in one "
        "role — check /debug/conprof for the dominant stacks before "
        "raising pool size (more workers on a saturated role only adds "
        "queue wait)", role_metric(top_role))]


@rule("profiler-overhead")
def _rule_profiler_overhead(ctx: InspectionContext) -> List[Finding]:
    metric = "tinysql_conprof_self_seconds_total"
    pts = ctx.series(metric)
    if len(pts) < 2:
        return []
    span = pts[-1][0] - pts[0][0]
    self_d = pts[-1][1] - pts[0][1]
    if span <= 0 or self_d <= 0:
        return []
    frac = self_d / span
    if frac <= PROFILER_OVERHEAD_BUDGET:
        return []
    backoff = ctx.last("tinysql_conprof_backoff") or 1
    return [ctx.evidence(
        "profiler-overhead", "conprof", "warning",
        f"the continuous profiler spent {frac:.1%} of one core on its "
        f"own sampling within the window (budget "
        f"{PROFILER_OVERHEAD_BUDGET:.0%}); the sampler is backing off "
        f"(current divisor {backoff:.0f} — effective rate = "
        "tidb_conprof_rate / divisor).  Lower tidb_conprof_rate or "
        "tidb_conprof_max_stacks if the backoff keeps climbing",
        metric)]


@rule("slo-burn")
def _rule_slo_burn(ctx: InspectionContext) -> List[Finding]:
    slo_ms = SLO_STATE["p99_ms"]
    if slo_ms <= 0:
        return []
    # an objective that CHANGED within (or since) the window makes the
    # breach delta meaningless — the samples were judged against
    # different thresholds; wait for a stable window
    armed = ctx.series("tinysql_slo_p99_ms")
    if armed and (min(v for _, v in armed) != max(v for _, v in armed)
                  or armed[-1][1] != slo_ms):
        return []
    total = ctx.delta("tinysql_slo_exec_measurements_total")
    if total < SLO_MIN_MEASUREMENTS:
        return []
    over = ctx.delta("tinysql_slo_exec_breaches_total")
    if over <= 0:
        return []
    frac = over / total
    if frac <= SLO_BURN_FRAC:
        return []
    sev = "critical" if frac >= 5 * SLO_BURN_FRAC else "warning"
    return [ctx.evidence(
        "slo-burn", "p99", sev,
        f"{over:.0f} of {total:.0f} statements ({frac:.1%}) exceeded "
        f"the armed tidb_slo_p99_ms={slo_ms:g} within the window "
        f"(budget {SLO_BURN_FRAC:.0%} for a p99 objective): the error "
        "budget is burning — split the regression into queue wait vs "
        "execution via the phase histograms and statements_summary",
        "tinysql_slo_exec_breaches_total")]


@rule("heap-growth")
def _rule_heap_growth(ctx: InspectionContext) -> List[Finding]:
    # monotone-rise leak detection over the MEASURED python heap
    # (obs/memprof.py memory_state): a heap that only goes up, window
    # after window, is a leak — a working set breathes back down
    metric = "tinysql_mem_traced_bytes"
    pts = ctx.series(metric)
    if len(pts) < HEAP_GROWTH_MIN_POINTS:
        return []
    rise = pts[-1][1] - pts[0][1]
    if rise < HEAP_GROWTH_MIN_BYTES:
        return []
    steps = len(pts) - 1
    rises = sum(1 for i in range(steps) if pts[i + 1][1] >= pts[i][1])
    if rises / steps < HEAP_GROWTH_RISE_FRAC:
        return []
    return [ctx.evidence(
        "heap-growth", "heap", "warning",
        f"traced python heap rose {rise / 1048576.0:.1f} MiB "
        f"monotonically across {len(pts)} samples in the window "
        f"({rises}/{steps} rising steps): leak-shaped growth — "
        "/debug/heap has the allocation sites holding the bytes",
        metric)]


@rule("hbm-pressure")
def _rule_hbm_pressure(ctx: InspectionContext) -> List[Finding]:
    # HBM census vs the backend's exposed capacity; silent on backends
    # without a limit (CPU) — a share of zero is not evidence
    metric = "tinysql_hbm_live_bytes"
    limit = ctx.last("tinysql_hbm_limit_bytes")
    if limit <= 0:
        return []
    live = ctx.last(metric)
    share = live / limit
    if share < HBM_PRESSURE_FRAC:
        return []
    sev = "critical" if share >= HBM_PRESSURE_CRIT_FRAC else "warning"
    return [ctx.evidence(
        "hbm-pressure", "device", sev,
        f"live device buffers hold {share:.0%} of the backend's "
        f"{limit / 1048576.0:.0f} MiB capacity "
        "(information_schema.memory_usage attributes them by owner; a "
        "non-empty unattributed bucket there is a leak)", metric)]


@rule("mem-untracked")
def _rule_mem_untracked(ctx: InspectionContext) -> List[Finding]:
    # measured-vs-tracked divergence: windowed MEASURED heap growth
    # beyond everything the MemTracker ledger ever held in the window.
    # Deltas, not absolutes — the absolute traced number includes the
    # interpreter baseline no statement should answer for.  The band
    # (obs/memprof.UNTRACKED_BAND_BYTES) is the documented tolerance.
    from .memprof import UNTRACKED_BAND_BYTES
    metric = "tinysql_mem_traced_bytes"
    d_traced = ctx.delta(metric)
    tracked_peak = ctx.max_value("tinysql_mem_tracked_bytes")
    over = d_traced - tracked_peak - UNTRACKED_BAND_BYTES
    if over <= 0:
        return []
    return [ctx.evidence(
        "mem-untracked", "ledger", "warning",
        f"measured heap grew {d_traced / 1048576.0:.1f} MiB in the "
        "window while the statement MemTracker ledger peaked at "
        f"{tracked_peak / 1048576.0:.1f} MiB — "
        f"{over / 1048576.0:.1f} MiB past the "
        f"{UNTRACKED_BAND_BYTES >> 20} MiB band is allocation the "
        "spill/admission gates cannot see (operator working state "
        "missing its tracker charge)", metric)]


# ---- evaluation -----------------------------------------------------------

def run(now: Optional[float] = None, window_s: Optional[float] = None,
        ring: Optional[tsring.MetricsRing] = None) -> List[Finding]:
    """Evaluate every registered rule; never raises (a broken rule
    becomes its own finding)."""
    ctx = InspectionContext(ring if ring is not None else tsring.RING,
                            now=now, window_s=window_s)
    findings: List[Finding] = []
    for name, fn in RULES.items():
        try:
            findings.extend(fn(ctx) or [])
        except Exception as e:
            findings.append(Finding(
                name, "rule", "warning",
                f"inspection rule raised: {e!r}"))
    return findings


def rows(now: Optional[float] = None,
         window_s: Optional[float] = DEFAULT_WINDOW_S) -> List[list]:
    """The ``inspection_result`` mem-table payload.  Bounded to the
    recent window by default (``None`` = the whole retained ring)."""
    return [f.row() for f in run(now=now, window_s=window_s)]


def snapshot(now: Optional[float] = None,
             window_s: Optional[float] = DEFAULT_WINDOW_S) -> List[dict]:
    """The ``/debug/inspection`` payload.  Bounded to the recent window
    by default (``None`` = the whole retained ring)."""
    return [f.to_dict() for f in run(now=now, window_s=window_s)]
