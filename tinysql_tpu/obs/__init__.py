"""Query-lifecycle observability.

The reference surface this reproduces: executor runtime stats feeding
``EXPLAIN ANALYZE`` (reference: util/execdetails + distsql/select_result
CopRuntimeStats), the slow-query log (executor/adapter.go LogSlowQuery),
and the HTTP status server's metrics export.  The TPU twist: the
interesting runtime facts here are *device economics* — program
dispatches, packed D2H transfers, compile-cache behavior, pipeline
stage/compute overlap — which used to live only in the process-global
``kernels.STATS`` dict, unattributable to a query or an operator.

Three cooperating pieces:

- **context** (`context.py`): a ``contextvars``-scoped ``QueryObs`` per
  statement.  Device-layer accessors (``kernels.stats_add`` /
  ``stats_hwm``, progcache hit/miss) fan each increment out to the
  active query scope and to the operator whose ``next()`` frame is live,
  so two concurrent sessions collect disjoint counters while the global
  totals stay monotonic for ``/metrics``.  The devpipe producer thread
  inherits the creator's scope via ``contextvars.copy_context``.
- **RuntimeStats** (`context.py` + `runtime_stats.py`): per-operator
  actual rows, Next loops, wall time, and device counters, collected by
  wrapping the Open/Next/Close executor interface (``instrument_tree``)
  — no per-executor code changes.
- **surfaces**: ``EXPLAIN ANALYZE`` (planner/explain.py), Prometheus
  ``/metrics`` + ``/debug/trace`` (server/http_status.py via
  `metrics.py` / `trace.py`), the JSONL slow-query log (`slowlog.py`,
  threshold sysvar ``tidb_slow_log_threshold``), and the bucket-prewarm
  feedback file (`feedback.py`, consumed by ``tools/warm.py
  --from-stats``).
- **SQL-queryable aggregates** (`stmtsummary.py`): the windowed,
  evicting per-(sql digest, plan digest) summary store behind
  ``information_schema.statements_summary`` / ``processlist`` /
  ``slow_query`` (catalog/memtables.py), ``EXPLAIN FOR CONNECTION``,
  and the ``/metrics`` per-phase latency histograms.  Written ONLY from
  the session statement-close hook (qlint OB403).
- **time series + self-diagnosis** (`tsring.py` + `inspect.py`): a
  background sampler snapshots every registered counter source into a
  bounded ring (``metrics_history`` / ``metrics_summary`` mem-tables,
  ``tidb_metrics_interval`` / ``tidb_metrics_retention``), metric
  names pinned to the central registry in `metrics.py` (qlint OB404);
  an inspection rule catalogue evaluates the ring into
  ``inspection_result`` / ``/debug/inspection`` findings with severity
  and the metric evidence window.  The serving path attributes each
  statement's queue/batch wait (server/pool.py measurement → spans,
  summary columns, slow-log fields, the ``queue`` phase histogram).
- **host-CPU truth** (`conprof.py`, ISSUE 13): an always-on
  continuous stack-sampling profiler — a background sampler walks
  ``sys._current_frames()`` at ``tidb_conprof_rate`` Hz, classifies
  threads by serving role (the stable thread-name vocabulary),
  folds stacks into stmtsummary-style rotating windows
  (``information_schema.continuous_profiling``, ``/debug/conprof``
  collapsed text for flamegraph.pl/speedscope), and attributes
  samples to the statement running on the sampled thread
  (``statements_summary`` ``sum_cpu_ms``/``cpu_samples``, invariant
  cpu <= exec wall; qlint OB406 guards the write path).  ``TRACE
  <stmt>`` renders the span tracer's tree as rows over SQL.
- **device-time truth** (ops/profiler.py + ops/progcache.py, ISSUE
  11): the default timings are host walls around ASYNC enqueues; the
  opt-in sampling profiler (``tidb_device_profile_rate``) closes
  sampled dispatches with ``block_until_ready`` so ``device_s`` /
  ``compile_s`` carry measured truth into EXPLAIN ANALYZE,
  ``statements_summary``, the per-program catalog
  (``information_schema.compiled_programs``), and the
  ``tinysql_dispatch_device_seconds`` histogram (qlint OB405 guards
  the write path).

See docs/OBSERVABILITY.md.
"""
from .context import (QueryObs, RuntimeStats, activate, current,
                      current_op, deactivate, record, record_hwm, span)
from .runtime_stats import instrument_tree
from .trace import Tracer, recent_traces

__all__ = [
    "QueryObs", "RuntimeStats", "Tracer", "activate", "current",
    "current_op", "deactivate", "instrument_tree", "record", "record_hwm",
    "recent_traces", "span",
]
