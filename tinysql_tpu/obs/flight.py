"""Durable flight recorder: the observability black box that crosses
process death (reference: TiDB persists the slow log and statements
summary across restarts, and TiDB Dashboard's continuous-profiling
store keeps historical profiles for post-hoc diagnosis).

Every surface PRs 3–18 built — the metrics ring (tsring), statements
summary windows, conprof/memprof folded stacks, inspection findings —
lives in process memory, so a crash destroys the telemetry exactly when
it matters most.  This module rides the PR 19 durability arming
convention: when the store has a data dir, a background ``FlightWriter``
appends length-prefixed crc32-checksummed segments (zlib-compressed
JSON snapshots of every tier) to ``<data-dir>/flight/inc-<N>.flt``; when
there is no data dir, nothing is armed and behavior is byte-identical
to the volatile server (zero ``tinysql_flight_*`` movement — the
/metrics render and the tsring source both gate on any-counter-moved,
the same discipline kv/wal.py uses).

One process lifetime = one **incarnation**: a monotonic id read-bumped
from ``flight/INCARNATION`` at boot (tmp→fsync→rename, like every other
metadata write here; an in-process counter still advances when
volatile so the id is always a usable join key).  On startup prior
incarnations load read-only and are served through the existing SQL
surfaces — ``metrics_history`` / ``statements_summary_history`` /
``continuous_profiling`` / ``inspection_result`` gain an
``incarnation`` column (current run = highest id) and the new
``flight_incarnations`` mem-table lists each run's boundaries and
whether it shut down clean or torn (last segment carries ``final``).

Segment framing reuses wal.py's record discipline: ``u32 payload_len |
u32 crc32(payload) | payload`` after an 8-byte magic, torn tails
truncated at the last good boundary on writer open, and a
retention-bounded in-file compaction (keep the newest ``retention``
segments, rewrite tmp→fsync→rename) plus pruning of the oldest
incarnation files keeps the directory bounded.

A crash-scoped fatal path — ``atexit`` + ``faulthandler`` into
``flight/fatal-<N>.log`` + both wire-mode close paths — force-flushes a
final segment carrying the last trace-span ring and the active
processlist, so even a graceful-degradation death leaves a readable
black box for tools/postmortem.py.

Blind spots (documented contract): SIGKILL between writer ticks loses
at most one ``tidb_flight_interval`` of telemetry (the post-mortem
window is the last *completed* segment); faulthandler records the
C-level stack on a hard fault but cannot run the Python flush hook, so
a segfault's last window is also the last tick, plus the native
traceback file.
"""
from __future__ import annotations

import atexit
import faulthandler
import json
import os
import struct
import threading
import time
import weakref
import zlib
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "FlightStore", "FlightWriter", "DEFAULT_INTERVAL_S",
    "DEFAULT_RETENTION", "INCARNATION_COLUMNS", "current_incarnation",
    "server_start_ts", "active_store", "prior_tier_rows",
    "incarnation_rows", "stats_snapshot", "reset_stats",
    "live_overhead_frac",
]

#: GLOBAL sysvar defaults (session.DEFAULT_SYSVARS mirrors these)
DEFAULT_INTERVAL_S = 10
DEFAULT_RETENTION = 8

SUBDIR = "flight"
_COUNTER_FILE = "INCARNATION"
_MAGIC = b"TSQLFLT1"
_HDR = struct.Struct("<II")          # payload length, crc32(payload)

#: replayable tiers a segment snapshots (postmortem + mem-tables read
#: these keys back; "metrics" is a delta, the rest are
#: last-segment-wins full snapshots)
TIERS = ("metrics", "summary", "conprof", "memprof", "findings",
         "counters")

# ---- process-cumulative stats (METRICS -> tsring -> /metrics) --------------
_STATS_MU = threading.Lock()
STATS: Dict[str, float] = {
    "segments": 0, "segment_bytes": 0, "fsyncs": 0,
    "final_flushes": 0, "compactions": 0, "torn_truncations": 0,
    "prior_segments_loaded": 0, "errors": 0,
    "self_s": 0.0,               # writer self-cost (bench overhead gate)
}


def _bump(key: str, n: float = 1) -> None:
    with _STATS_MU:
        STATS[key] = STATS.get(key, 0) + n


def stats_snapshot() -> Dict[str, float]:
    with _STATS_MU:
        return dict(STATS)


def reset_stats() -> None:
    """Test hook: zero the cumulative counters."""
    with _STATS_MU:
        for k in STATS:
            STATS[k] = 0


def live_overhead_frac(stats_before: Dict[str, float],
                       stats_after: Dict[str, float],
                       wall_s: float) -> float:
    """Writer self-cost over a measured live window — same definition
    as conprof/memprof.live_overhead_frac, so bench_serve can gate the
    three samplers' combined live fraction under one budget."""
    if wall_s <= 0:
        return 0.0
    d = stats_after.get("self_s", 0.0) - stats_before.get("self_s", 0.0)
    return max(0.0, d) / wall_s


# ---- incarnation identity --------------------------------------------------
# One process lifetime = one incarnation.  Armed boots read-bump the
# persisted counter; volatile boots advance an in-process counter so
# the id is still a monotone join key within the process (ISSUE 20
# satellite: "counter even when volatile").
_ID_MU = threading.Lock()
_INCARNATION = 0                      # 0 = no boot yet (reads clamp to 1)
_SERVER_START_TS = time.time()        # refreshed at every writer boot


def current_incarnation() -> int:
    with _ID_MU:
        return max(1, _INCARNATION)


def server_start_ts() -> float:
    with _ID_MU:
        return _SERVER_START_TS


def _boot_identity(incarnation: Optional[int]) -> int:
    """Stamp boot identity: explicit id from the persisted counter, or
    the next in-process id when volatile.  Returns the assigned id."""
    global _INCARNATION, _SERVER_START_TS
    with _ID_MU:
        if incarnation is not None:
            _INCARNATION = int(incarnation)
        else:
            _INCARNATION = max(1, _INCARNATION + 1)
        _SERVER_START_TS = time.time()
        return _INCARNATION


# ---- codec -----------------------------------------------------------------

def _encode_segment(doc: dict) -> bytes:
    payload = zlib.compress(
        json.dumps(doc, separators=(",", ":"), sort_keys=True,
                   default=str).encode("utf-8"))
    return _HDR.pack(len(payload),
                     zlib.crc32(payload) & 0xFFFFFFFF) + payload


def _scan_segments(path: str) -> Tuple[List[dict], int, bool]:
    """Decode every intact segment of one incarnation file.  Returns
    ``(docs, good_end, clean_tail)`` — ``good_end`` is the byte offset
    after the last intact record (the writer truncates there),
    ``clean_tail`` is False when trailing garbage followed it (a torn
    append).  Same replay discipline as WriteAheadLog._replay: stop at
    the first short header, short record, or crc mismatch."""
    docs: List[dict] = []
    try:
        with open(path, "rb") as f:
            blob = f.read()
    except OSError:
        return [], 0, False
    if not blob.startswith(_MAGIC):
        return [], 0, False
    off = len(_MAGIC)
    size = len(blob)
    good_end = off
    clean = True
    while off + _HDR.size <= size:
        plen, crc = _HDR.unpack_from(blob, off)
        body = blob[off + _HDR.size: off + _HDR.size + plen]
        if len(body) < plen or (zlib.crc32(body) & 0xFFFFFFFF) != crc:
            clean = False
            break
        try:
            docs.append(json.loads(zlib.decompress(body).decode("utf-8")))
        except Exception:
            clean = False
            break
        off += _HDR.size + plen
        good_end = off
    if off != size:
        clean = False
    return docs, good_end, clean


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


# ---- store -----------------------------------------------------------------

def _inc_path(flight_dir: str, n: int) -> str:
    return os.path.join(flight_dir, "inc-%08d.flt" % n)


def _list_incarnation_files(flight_dir: str) -> List[Tuple[int, str]]:
    out: List[Tuple[int, str]] = []
    try:
        names = os.listdir(flight_dir)
    except OSError:
        return out
    for name in names:
        if name.startswith("inc-") and name.endswith(".flt"):
            try:
                out.append((int(name[4:-4]),
                            os.path.join(flight_dir, name)))
            except ValueError:
                continue
    out.sort()
    return out


class FlightStore:
    """One ``<data-dir>/flight/`` directory: the incarnation counter,
    the current incarnation's append-only segment file, and the prior
    incarnations loaded read-only at open."""

    def __init__(self, data_dir: str):
        self.data_dir = data_dir
        self.dir = os.path.join(data_dir, SUBDIR)
        self.incarnation = 0
        self.path = ""
        self._f = None
        self._mu = threading.Lock()
        self._segments = 0            # records in the current file
        #: incarnation -> (docs, clean_tail) for every PRIOR run
        self.prior: Dict[int, Tuple[List[dict], bool]] = {}

    # -- counter ------------------------------------------------------------
    def _counter_path(self) -> str:
        return os.path.join(self.dir, _COUNTER_FILE)

    def _read_counter(self) -> int:
        try:
            with open(self._counter_path(), "r", encoding="utf-8") as f:
                return int(f.read().strip() or "0")
        except (OSError, ValueError):
            return 0

    def _write_counter(self, n: int) -> None:
        tmp = self._counter_path() + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write("%d\n" % n)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._counter_path())
        _fsync_dir(self.dir)

    # -- lifecycle ----------------------------------------------------------
    def open_writer(self) -> int:
        """Assign this boot's incarnation (read-bump-persist the
        counter), open its segment file, truncate any torn tail left by
        a previous crash of the SAME file (only possible if the counter
        write raced a kill), and load every prior incarnation
        read-only.  Returns the assigned incarnation id."""
        os.makedirs(self.dir, exist_ok=True)
        n = self._read_counter() + 1
        self._write_counter(n)
        self.incarnation = n
        self.path = _inc_path(self.dir, n)
        segs = 0
        if os.path.exists(self.path):
            docs, good_end, clean = _scan_segments(self.path)
            if not clean:
                with open(self.path, "r+b") as f:
                    f.truncate(max(good_end, len(_MAGIC)))
                _bump("torn_truncations")
            segs = len(docs)
        f = open(self.path, "ab")
        if f.tell() == 0:
            f.write(_MAGIC)
            f.flush()
            os.fsync(f.fileno())
        with self._mu:
            self._f = f
            self._segments = segs
        self._load_prior(exclude=n)
        return n

    def open_read_only(self) -> None:
        """Post-mortem entry: load every incarnation (including the
        last writer's) WITHOUT bumping the counter or truncating
        anything on disk."""
        self.incarnation = self._read_counter()
        self._load_prior(exclude=None)

    def _load_prior(self, exclude: Optional[int]) -> None:
        prior: Dict[int, Tuple[List[dict], bool]] = {}
        for n, path in _list_incarnation_files(self.dir):
            if exclude is not None and n >= exclude:
                continue
            docs, _good_end, clean = _scan_segments(path)
            if docs:
                prior[n] = (docs, clean)
                _bump("prior_segments_loaded", len(docs))
        with self._mu:
            self.prior = prior

    def close(self) -> None:
        with self._mu:
            f, self._f = self._f, None
        if f is not None:
            try:
                f.flush()
                os.fsync(f.fileno())
            except OSError:
                pass
            f.close()

    # -- writes -------------------------------------------------------------
    def append_segment(self, doc: dict, retention: int) -> None:
        """Frame, append, fsync one segment; then bound the store:
        in-file compaction keeps the newest ``retention`` segments once
        the file holds twice that, and incarnation files older than the
        newest ``retention`` runs are pruned."""
        rec = _encode_segment(doc)
        with self._mu:
            if self._f is None:
                return
            self._f.write(rec)
            self._f.flush()
            os.fsync(self._f.fileno())
            self._segments += 1
            segs = self._segments
        _bump("segments")
        _bump("segment_bytes", len(rec))
        _bump("fsyncs")
        if retention > 0 and segs > 2 * retention:
            self._compact(retention)
        if retention > 0:
            self._prune(retention)

    def _compact(self, retention: int) -> None:
        """Rewrite the current file keeping only the newest
        ``retention`` segments (tmp→fsync→rename, the checkpoint
        discipline)."""
        with self._mu:
            if self._f is None:
                return
            docs, _end, _clean = _scan_segments(self.path)
            keep = docs[-retention:]
            tmp = self.path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(_MAGIC)
                for d in keep:
                    f.write(_encode_segment(d))
                f.flush()
                os.fsync(f.fileno())
            self._f.close()
            os.replace(tmp, self.path)
            _fsync_dir(self.dir)
            self._f = open(self.path, "ab")
            self._segments = len(keep)
        _bump("compactions")

    def _prune(self, retention: int) -> None:
        files = _list_incarnation_files(self.dir)
        if len(files) <= retention:
            return
        for n, path in files[:len(files) - retention]:
            if n == self.incarnation:
                continue
            try:
                os.remove(path)
            except OSError:
                continue
            with self._mu:
                self.prior.pop(n, None)

    # -- replay -------------------------------------------------------------
    def tier_rows(self, incarnation: int, tier: str) -> List[list]:
        """Replay one prior incarnation's mem-table payload.
        ``metrics`` concatenates every segment's delta back into
        metrics_history rows; the other tiers are last-segment-wins
        full snapshots (each segment re-snapshots the whole retained
        window, so the newest one supersedes)."""
        entry = self.prior.get(incarnation)
        if entry is None:
            return []
        docs = entry[0]
        if tier == "metrics":
            from . import tsring
            out: List[list] = []
            for doc in docs:
                for ts, vals in doc.get("tiers", {}).get("metrics", []):
                    stamp = tsring._ts(ts)
                    for name in sorted(vals):
                        out.append([stamp, float(ts), name,
                                    float(vals[name])])
            return out
        payload = docs[-1].get("tiers", {}).get(tier, [])
        return payload if isinstance(payload, list) else []

    def last_segment(self, incarnation: Optional[int] = None
                     ) -> Optional[dict]:
        if incarnation is None:
            incarnation = max(self.prior) if self.prior else 0
        entry = self.prior.get(incarnation)
        return entry[0][-1] if entry else None

    def incarnation_summary(self) -> List[dict]:
        """One dict per loaded prior incarnation (ascending):
        boundaries, clean-vs-torn verdict, last WAL LSN, tier counts."""
        out: List[dict] = []
        for n in sorted(self.prior):
            docs, clean_tail = self.prior[n]
            first, last = docs[0], docs[-1]
            final = bool(last.get("final"))
            counters = last.get("tiers", {}).get("counters", {})
            out.append({
                "incarnation": n,
                "start_ts": float(first.get("server_start_ts",
                                            first.get("ts", 0.0))),
                "end_ts": float(last.get("ts", 0.0)),
                "status": "clean" if (final and clean_tail) else "torn",
                "last_lsn": int(counters.get("wal_last_lsn", 0)),
                "segments": len(docs),
                "metrics_samples": sum(
                    len(d.get("tiers", {}).get("metrics", []))
                    for d in docs),
                "summary_rows": len(last.get("tiers", {})
                                    .get("summary", [])),
                "conprof_rows": len(last.get("tiers", {})
                                    .get("conprof", [])),
                "findings": len(last.get("tiers", {})
                                .get("findings", [])),
            })
        return out


# ---- writer ----------------------------------------------------------------

#: armed writers with a pending final flush — a single atexit hook
#: drains the set so a plain interpreter exit still leaves a black box
_FATAL_WRITERS: "weakref.WeakSet[FlightWriter]" = weakref.WeakSet()
_ATEXIT_ARMED = False
_ATEXIT_MU = threading.Lock()


def _atexit_flush() -> None:
    for w in list(_FATAL_WRITERS):
        try:
            w.final_flush(reason="atexit")
        except Exception:
            _bump("errors")


def _arm_atexit() -> None:
    global _ATEXIT_ARMED
    with _ATEXIT_MU:
        if not _ATEXIT_ARMED:
            atexit.register(_atexit_flush)
            _ATEXIT_ARMED = True


class FlightWriter:
    """Background segment writer on the server lifecycle (same
    start/close discipline as obs/memprof.MemprofSampler: daemon
    thread, Event-paced waits sliced at ≤0.25 s, GLOBAL sysvars
    re-read every tick so ``SET GLOBAL`` takes effect without a
    restart; ``tidb_flight_interval = 0`` pauses without stopping).

    Construction stamps the boot identity (incarnation +
    server_start_ts) whether or not a data dir is armed; everything
    else — the store, the fatal hooks, the segment stream — exists
    only when armed, preserving volatile byte-identity."""

    def __init__(self, storage):
        self.storage = storage
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._mu = threading.Lock()
        self._final_done = False
        self._seq = 0
        self._last_metrics_ts = 0.0
        self._fatal_file = None
        self.store: Optional[FlightStore] = None
        data_dir = getattr(storage, "data_dir", "") or ""
        if data_dir:
            self.store = FlightStore(data_dir)
            inc = self.store.open_writer()
            _boot_identity(inc)
            _set_active(self)
            self._enable_fatal_hooks()
        else:
            _boot_identity(None)
            _set_active(None)

    # -- sysvars ------------------------------------------------------------
    def _int_sysvar(self, name: str, default: int) -> int:
        from ..server.pool import read_global_int
        return read_global_int(self.storage, name, default)

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        if self.store is None:
            return
        with self._mu:
            if self._thread is not None:
                return
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="flight-writer")
            self._thread.start()

    def close(self) -> None:
        with self._mu:
            self._stop.set()
            t = self._thread
        if t is not None:
            t.join(timeout=5.0)
        with self._mu:
            if self._thread is t:
                self._thread = None
        self.final_flush(reason="close")
        self._disable_fatal_hooks()
        if self.store is not None:
            self.store.close()

    def _loop(self) -> None:
        # the interval sysvar is re-read every 0.25 s slice (not once
        # per tick) so SET GLOBAL tidb_flight_interval takes effect
        # within a slice even mid-wait; interval <= 0 pauses and also
        # resets the accumulated wait
        waited = 0.0
        while not self._stop.is_set():
            interval = self._int_sysvar("tidb_flight_interval",
                                        DEFAULT_INTERVAL_S)
            if interval <= 0:
                waited = 0.0
                self._stop.wait(0.25)
                continue
            if waited < interval:
                t0 = time.monotonic()
                self._stop.wait(min(0.25, interval - waited))
                waited += time.monotonic() - t0
                continue
            waited = 0.0
            try:
                self.flush_now()
            except Exception:
                _bump("errors")

    # -- fatal hooks ---------------------------------------------------------
    def _enable_fatal_hooks(self) -> None:
        _FATAL_WRITERS.add(self)
        _arm_atexit()
        try:
            path = os.path.join(self.store.dir,
                                "fatal-%08d.log" % self.store.incarnation)
            self._fatal_file = open(path, "w", encoding="utf-8")
            faulthandler.enable(self._fatal_file)
        except Exception:
            self._fatal_file = None

    def _disable_fatal_hooks(self) -> None:
        _FATAL_WRITERS.discard(self)
        if self._fatal_file is not None:
            try:
                faulthandler.disable()
            except Exception:
                pass
            try:
                self._fatal_file.close()
            except Exception:
                pass
            self._fatal_file = None

    # -- segments ------------------------------------------------------------
    def _counters(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        probes = (
            ("wal", "..kv.wal"), ("shard", "..ops.shardops"),
            ("batching", "..ops.batching"),
            ("admission", "..server.admission"),
            ("spill", "..ops.spill"), ("prewarm", "..session.prewarm"),
            ("tsring", ".tsring"), ("conprof", ".conprof"),
            ("memprof", ".memprof"),
        )
        import importlib
        for key, modname in probes:
            try:
                mod = importlib.import_module(modname, package=__package__)
                out[key] = {k: float(v) for k, v
                            in mod.stats_snapshot().items()}
            except Exception:
                continue
        try:
            wal = self.storage.mvcc.wal
            if wal is not None:
                out["wal_last_lsn"] = int(getattr(wal, "_lsn", 0))
        except Exception:
            pass
        return out

    def _collect(self, final: bool) -> dict:
        from . import conprof, memprof, stmtsummary, tsring
        from . import inspect as obs_inspect
        now = time.time()
        samples = tsring.RING.snapshot_samples()
        with self._mu:
            last_ts = self._last_metrics_ts
        delta = [[ts, vals] for ts, vals in samples if ts > last_ts]
        if delta:
            with self._mu:
                self._last_metrics_ts = delta[-1][0]
        tiers: Dict[str, Any] = {
            "metrics": delta,
            "summary": stmtsummary.history_rows(),
            "conprof": conprof.rows(),
            "findings": obs_inspect.rows(now=now, window_s=None),
            "counters": self._counters(),
        }
        try:
            tiers["memprof"] = {
                "collapsed": memprof.collapsed(),
                "memory_usage": memprof.memory_usage_rows(),
            }
        except Exception:
            tiers["memprof"] = {}
        with self._mu:
            seq = self._seq
            self._seq = seq + 1
        doc = {
            "v": 1,
            "incarnation": self.store.incarnation,
            "seq": seq,
            "ts": now,
            "server_start_ts": server_start_ts(),
            "final": final,
            "tiers": tiers,
        }
        if final:
            from ..catalog.memtables import _processlist_rows
            from .trace import recent_traces
            try:
                doc["traces"] = recent_traces(64)
            except Exception:
                doc["traces"] = []
            try:
                doc["processlist"] = _processlist_rows()
            except Exception:
                doc["processlist"] = []
        return doc

    def flush_now(self, final: bool = False, reason: str = "tick") -> None:
        """Snapshot every tier and append one segment.  ``final``
        segments carry the trace ring + processlist and mark the run
        clean for incarnation_summary."""
        if self.store is None:
            return
        t0 = time.monotonic()
        try:
            doc = self._collect(final)
            if final:
                doc["reason"] = reason
            retention = self._int_sysvar("tidb_flight_retention",
                                         DEFAULT_RETENTION)
            self.store.append_segment(doc, retention)
            if final:
                _bump("final_flushes")
        finally:
            _bump("self_s", time.monotonic() - t0)

    def final_flush(self, reason: str = "close") -> None:
        """Idempotent last-segment flush — every death path (graceful
        close in both wire modes, atexit) funnels here."""
        with self._mu:
            if self._final_done or self.store is None:
                return
            self._final_done = True
        try:
            self.flush_now(final=True, reason=reason)
        except Exception:
            _bump("errors")


# ---- module-level read surface (mem-tables + /debug + postmortem) ----------

_ACTIVE: Optional["weakref.ReferenceType[FlightWriter]"] = None
_ACTIVE_MU = threading.Lock()


def _set_active(writer: Optional[FlightWriter]) -> None:
    global _ACTIVE
    with _ACTIVE_MU:
        _ACTIVE = weakref.ref(writer) if writer is not None else None


def active_writer() -> Optional[FlightWriter]:
    with _ACTIVE_MU:
        ref = _ACTIVE
    return ref() if ref is not None else None


def active_store() -> Optional[FlightStore]:
    w = active_writer()
    return w.store if w is not None else None


def prior_tier_rows(tier: str) -> List[Tuple[int, List[list]]]:
    """``[(incarnation, rows), ...]`` ascending for every loaded prior
    incarnation — the mem-table extensions append the incarnation
    column and splice these ahead of the live rows.  Empty when
    volatile (no store armed)."""
    store = active_store()
    if store is None:
        return []
    return [(n, store.tier_rows(n, tier)) for n in sorted(store.prior)]


#: information_schema.flight_incarnations layout — MUST match
#: incarnation_rows
INCARNATION_COLUMNS = [
    ("incarnation", "int"), ("start_time", "str"), ("end_time", "str"),
    ("status", "str"), ("last_lsn", "int"), ("segments", "int"),
    ("metrics_samples", "int"), ("summary_rows", "int"),
    ("conprof_rows", "int"), ("findings", "int"),
]


def incarnation_rows() -> List[list]:
    """``flight_incarnations`` payload: loaded prior runs (ascending)
    then the current run (status ``running``; its counters reflect the
    live stores, not yet any segment)."""
    from . import tsring
    out: List[list] = []
    store = active_store()
    if store is not None:
        for s in store.incarnation_summary():
            out.append([s["incarnation"], tsring._ts(s["start_ts"]),
                        tsring._ts(s["end_ts"]), s["status"],
                        s["last_lsn"], s["segments"],
                        s["metrics_samples"], s["summary_rows"],
                        s["conprof_rows"], s["findings"]])
    segs = store._segments if store is not None else 0
    out.append([current_incarnation(), tsring._ts(server_start_ts()),
                "", "running", 0, int(segs), 0, 0, 0, 0])
    return out


def debug_snapshot() -> dict:
    """The ``/debug/flight`` payload."""
    store = active_store()
    return {
        "armed": store is not None,
        "incarnation": current_incarnation(),
        "server_start_ts": server_start_ts(),
        "dir": store.dir if store is not None else "",
        "stats": stats_snapshot(),
        "incarnations": (store.incarnation_summary()
                         if store is not None else []),
    }
