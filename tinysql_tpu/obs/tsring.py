"""Time-series metrics ring: the "what changed in the last N minutes"
substrate (reference lineage: TiDB's metrics_schema — PromQL-backed
mem-tables computed on read; here the process IS the metrics store, so a
background sampler snapshots every registered counter/gauge source into
a bounded in-memory ring instead).

Three cooperating pieces:

- **sources**: named callables returning a flat ``{metric name: value}``
  dict.  The built-ins cover every counter family the engine publishes
  — kernels.STATS device economics, the program registry, the serving
  layer (pool gauges, admission verdicts + queue wait, batching),
  the MemTracker aggregate, device-loss degradation, failpoint hits,
  the query-lifecycle counters, and the auto-prewarm worker.  Every
  name MUST come from the central registry (``obs/metrics.METRICS``);
  unregistered names are dropped at sample time and counted
  (``dropped_unregistered``), and qlint OB404 rejects them statically —
  /metrics, ``metrics_history``, and ``metrics_summary`` can never
  drift on what a metric is called.
- **MetricsRing**: the bounded sample store.  ``sample_once`` collects
  all sources OUTSIDE the lock, then appends one ``(ts, values)``
  sample and trims by ``tidb_metrics_retention`` seconds (re-read every
  sample, so shrinking retention mid-flight trims immediately; a hard
  ``MAX_SAMPLES`` cap bounds memory even under a pathological
  interval).  Readers (the ``metrics_history`` / ``metrics_summary``
  mem-tables, the inspection engine) take the same lock, so a scan can
  never observe a torn sample.
- **Sampler**: the background thread wired into the server lifecycle
  (server/server.py), pacing ``sample_once`` by the GLOBAL
  ``tidb_metrics_interval`` sysvar (seconds; 0 disables sampling, the
  thread keeps watching for a re-enable).

Self-accounting is PER RING (``MetricsRing.stats_snapshot``): the
module-level :func:`stats_snapshot` reports the live global ring, so a
private probe ring (bench overhead measurement, tests) can never
inflate the background sampler's own cost metrics.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

DEFAULT_INTERVAL_S = 5
DEFAULT_RETENTION_S = 900

#: hard sample-count bound: retention/interval normally bounds the ring,
#: but a tiny interval with a huge retention must not grow memory
#: without limit
MAX_SAMPLES = 4096

def stats_snapshot() -> Dict[str, float]:
    """The LIVE ring's self-accounting (samples taken, unregistered
    drops, collection wall) — what /metrics and the "tsring" source
    report; private rings keep their own books."""
    return RING.stats_snapshot()


def reset_stats() -> None:
    """Tests only."""
    RING.reset_stats()


# ---- source registry ------------------------------------------------------

#: source name -> callable returning {registered metric name: value};
#: insertion-ordered so samples are reproducible
_src_mu = threading.Lock()
_SOURCES: Dict[str, Callable[[], Dict[str, float]]] = {}


def register_source(name: str,
                    fn: Callable[[], Dict[str, float]]) -> None:
    """Register (or replace) one named sample source.  Metric names the
    callable returns must be declared in ``obs/metrics.METRICS`` —
    unregistered names are dropped at sample time (and qlint OB404
    flags them statically)."""
    with _src_mu:
        _SOURCES[name] = fn


def sources() -> List[str]:
    with _src_mu:
        return list(_SOURCES)


def _collect() -> Dict[str, float]:
    """One raw pass over every source.  A broken source contributes
    nothing — sampling must never raise into the sampler thread or a
    mem-table scan."""
    with _src_mu:
        fns = list(_SOURCES.values())
    values: Dict[str, float] = {}
    for fn in fns:
        try:
            values.update(fn() or {})
        except Exception:
            continue
    return values


# ---- the ring -------------------------------------------------------------

# ONE time-format for every observability row stamp: metrics_history,
# statements_summary, and inspection_result must stay joinable on their
# time columns
from .stmtsummary import _ts  # noqa: E402


class MetricsRing:
    """Bounded (ts, {name: value}) sample store.  Writes and reads share
    one lock: a ``metrics_history`` scan racing the sampler sees whole
    samples or nothing — never a half-written one."""

    def __init__(self, retention_s: float = DEFAULT_RETENTION_S):
        self.retention_s = float(retention_s)
        self._mu = threading.Lock()
        self._samples: deque = deque()
        #: this ring's OWN self-accounting — a private probe ring must
        #: not inflate the live sampler's cost metrics
        self._stats = {"samples": 0, "dropped_unregistered": 0,
                       "sample_wall_s": 0.0}

    def _stat_add(self, key: str, n) -> None:
        with self._mu:
            self._stats[key] = self._stats.get(key, 0) + n

    def stats_snapshot(self) -> Dict[str, float]:
        with self._mu:
            return dict(self._stats)

    def reset_stats(self) -> None:
        """Tests only."""
        with self._mu:
            self._stats = {"samples": 0, "dropped_unregistered": 0,
                           "sample_wall_s": 0.0}

    def sample_once(self, now: Optional[float] = None,
                    retention_s: Optional[float] = None) -> Dict[str, float]:
        """Collect every source into one sample; returns the values.
        ``now`` is injectable for deterministic tests; ``retention_s``
        carries the live sysvar (also applied to ALREADY-stored samples,
        so a retention shrink trims immediately)."""
        t0 = time.perf_counter()
        values = self.record(_collect(), now=now, retention_s=retention_s)
        self._stat_add("sample_wall_s", time.perf_counter() - t0)
        return values

    def record(self, raw: Dict[str, float], now: Optional[float] = None,
               retention_s: Optional[float] = None) -> Dict[str, float]:
        """Append one pre-collected sample (sample_once's storage leg;
        also the deterministic entry for tests and offline replays).
        Names are validated against the central registry — an
        unregistered or non-numeric value is dropped and counted, so
        the ring can NEVER contain a name /metrics doesn't know."""
        from .metrics import registered
        values: Dict[str, float] = {}
        dropped = 0
        for name, v in raw.items():
            if not registered(name):
                dropped += 1
                continue
            try:
                values[name] = float(v)
            except (TypeError, ValueError):
                dropped += 1
        if now is None:
            now = time.time()
        with self._mu:
            if retention_s is not None:
                self.retention_s = float(retention_s)
            self._samples.append((now, values))
            self._trim(now)
            self._stats["samples"] += 1
            self._stats["dropped_unregistered"] += dropped
        return values

    def _trim(self, now: float) -> None:
        # caller holds the lock
        horizon = now - self.retention_s
        while self._samples and self._samples[0][0] < horizon:
            self._samples.popleft()
        while len(self._samples) > MAX_SAMPLES:
            self._samples.popleft()

    def size(self) -> int:
        with self._mu:
            return len(self._samples)

    def reset(self) -> None:
        """Tests only."""
        with self._mu:
            self._samples.clear()

    # ---- reads (mem-tables + inspection) --------------------------------
    def snapshot_samples(self) -> List[Tuple[float, Dict[str, float]]]:
        """One consistent copy of the retained samples — THE read
        entry: every consumer (mem-table scans, the inspection
        engine's whole rule evaluation) copies the deque exactly once
        under the lock instead of re-copying per read."""
        with self._mu:
            return [(ts, dict(vals)) for ts, vals in self._samples]

    def rows(self) -> List[list]:
        """``metrics_history`` payload: one row per (sample, metric) in
        sample order — (time, ts epoch, metric, value)."""
        samples = self.snapshot_samples()
        out: List[list] = []
        for ts, vals in samples:
            stamp = _ts(ts)
            for name in sorted(vals):
                out.append([stamp, float(ts), name, float(vals[name])])
        return out

    def summary_rows(self, now: Optional[float] = None,
                     window_s: Optional[float] = None) -> List[list]:
        """``metrics_summary`` payload: per metric over the retained
        window — (metric, kind, samples, window_s, first/last value,
        delta, rate_per_s, avg, min, max).  ``rate_per_s`` is the
        counter reading (delta over the sampled span, clamped at 0 so a
        process-counter reset shows 0 not a negative rate); gauges are
        summarized by avg/min/max."""
        from .metrics import METRICS
        samples = self.snapshot_samples()
        if now is None:
            now = time.time()
        if window_s is not None:
            samples = [s for s in samples if s[0] >= now - window_s]
        series: Dict[str, List[Tuple[float, float]]] = {}
        for ts, vals in samples:
            for name, v in vals.items():
                series.setdefault(name, []).append((ts, v))
        out: List[list] = []
        for name in sorted(series):
            pts = series[name]
            kind = METRICS.get(name, ("gauge", ""))[0]
            vals = [v for _, v in pts]
            t_first, v_first = pts[0]
            t_last, v_last = pts[-1]
            span = t_last - t_first
            delta = v_last - v_first
            rate = max(delta, 0.0) / span if span > 0 else 0.0
            out.append([
                name, kind, len(pts),
                round(span, 3), float(v_first), float(v_last),
                round(delta, 6), round(rate, 6),
                round(sum(vals) / len(vals), 6),
                float(min(vals)), float(max(vals)),
            ])
        return out

    def series(self, metric: str, since: Optional[float] = None,
               until: Optional[float] = None) -> List[Tuple[float, float]]:
        """(ts, value) points of one metric — the inspection engine's
        evidence-window read."""
        with self._mu:
            samples = list(self._samples)
        out = []
        for ts, vals in samples:
            if since is not None and ts < since:
                continue
            if until is not None and ts > until:
                continue
            if metric in vals:
                out.append((ts, float(vals[metric])))
        return out


#: the process-global ring every surface reads (mem-tables, /metrics
#: ring gauge, the inspection engine)
RING = MetricsRing()


# ---- mem-table payloads (catalog/memtables.py reads these) ---------------

#: information_schema.metrics_history column order — MUST match
#: MetricsRing.rows
HISTORY_COLUMNS = [
    ("time", "str"), ("ts", "real"), ("metric", "str"), ("value", "real"),
]

#: information_schema.metrics_summary column order — MUST match
#: MetricsRing.summary_rows
SUMMARY_COLUMNS = [
    ("metric", "str"), ("kind", "str"), ("samples", "int"),
    ("window_s", "real"), ("first_value", "real"), ("last_value", "real"),
    ("delta", "real"), ("rate_per_s", "real"), ("avg_value", "real"),
    ("min_value", "real"), ("max_value", "real"),
]


def history_rows() -> List[list]:
    return RING.rows()


def summary_rows() -> List[list]:
    return RING.summary_rows()


def drain_pending_costs() -> None:
    """Resolve deferred XLA cost analyses (kernels._PENDING_COSTS) —
    called every Sampler tick.  Before ISSUE 11 only bench.py ever
    drained the queue, so serving mode with cost tracking enabled
    accumulated pending analyses forever and flops/bytes undercounted;
    the sampler is the natural steady-state drainer (off the query
    path, already paced).  Exception-isolated: a broken backend must
    not kill the sampler thread."""
    try:
        from ..ops import kernels
        if kernels._PENDING_COSTS:
            kernels.resolve_pending_costs()
    except Exception:
        pass


def measure_overhead(n: int = 50) -> Dict[str, float]:
    """The sampler's steady-state cost, THE definition both benches
    publish as ``obs_overhead_frac``: one sample's wall (averaged over
    ``n`` live collections, lazy imports warmed outside the timed loop)
    over the default sampling interval.  Probes a PRIVATE ring, so the
    measurement never pollutes the live ring or its self-accounting."""
    ring = MetricsRing()
    ring.sample_once()
    t0 = time.perf_counter()
    for _ in range(n):
        ring.sample_once()
    per_sample_s = (time.perf_counter() - t0) / n
    return {"sample_wall_s": round(per_sample_s, 6),
            "interval_s": DEFAULT_INTERVAL_S,
            "obs_overhead_frac": round(
                per_sample_s / DEFAULT_INTERVAL_S, 6)}


# ---- the background sampler (server lifecycle) ---------------------------

class Sampler:
    """Background thread pacing ``RING.sample_once`` by the GLOBAL
    ``tidb_metrics_interval`` sysvar (re-read every tick, like the
    auto-prewarm worker): 0 pauses sampling without stopping the
    thread, so ``SET GLOBAL tidb_metrics_interval = 5`` resumes it."""

    def __init__(self, storage, ring: Optional[MetricsRing] = None):
        self.storage = storage
        self.ring = ring if ring is not None else RING
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        #: guards the start/close lifecycle (qlint CC7xx triage): two
        #: concurrent start() calls both passing the None-check would
        #: leak a second sampler thread ticking the same ring
        self._mu = threading.Lock()

    def _int_sysvar(self, name: str, default: int) -> int:
        # THE server-side config-read helper (server/pool.py) — one
        # definition of the GLOBAL-scope-with-defaults int read
        from ..server.pool import read_global_int
        return read_global_int(self.storage, name, default)

    def interval_s(self) -> int:
        return self._int_sysvar("tidb_metrics_interval",
                                DEFAULT_INTERVAL_S)

    def start(self) -> None:
        with self._mu:
            if self._thread is not None:
                return
            self._stop.clear()  # restartable after close()
            self._thread = threading.Thread(target=self._loop,
                                            daemon=True,
                                            name="metrics-sampler")
            self._thread.start()

    def close(self) -> None:
        # set the stop flag UNDER the lock, atomically with reading the
        # thread slot: a start() interleaved between the two would
        # clear the flag and spawn a thread this close() then orphans
        with self._mu:
            self._stop.set()
            t = self._thread
        if t is not None:
            t.join(timeout=5.0)
        # clear the slot only AFTER the join: a start() racing this
        # close must keep seeing the old thread (and stay a no-op)
        # until it has actually exited — nulling early would let start
        # clear _stop before the old loop observed it
        with self._mu:
            if self._thread is t:
                self._thread = None

    def _loop(self) -> None:
        # wait in 1 s slices, re-reading the interval each slice: an
        # operator who drops tidb_metrics_interval from 300 to 1 during
        # an incident gets fine-grained samples within ~1 s, not after
        # the old interval drains.  Disabled (0) pauses the elapsed
        # clock without stopping the thread, so a re-enable resumes.
        elapsed = 0.0
        while True:
            if self._stop.wait(1.0):
                return
            interval = self.interval_s()
            if interval <= 0:
                elapsed = 0.0
                continue
            elapsed += 1.0
            if elapsed + 1e-9 < interval:
                continue
            elapsed = 0.0
            # deferred cost analyses resolve on the sampler's cadence
            # (the serving-mode _PENDING_COSTS drain, ISSUE 11) — BEFORE
            # the sample, so resolved flops/bytes start accruing into
            # the very counters this tick snapshots
            drain_pending_costs()
            try:
                self.ring.sample_once(
                    retention_s=self._int_sysvar(
                        "tidb_metrics_retention", DEFAULT_RETENTION_S))
            except Exception:
                # a broken source must never kill the sampler thread
                import logging
                logging.getLogger("tinysql_tpu.tsring").warning(
                    "metrics sample failed", exc_info=True)


# ---- built-in sources -----------------------------------------------------
# Each source is lazy-importing and exception-isolated: /metrics and the
# ring must stay alive without jax, without a server, without a pool.

def _src_queries() -> Dict[str, float]:
    from .metrics import query_counter_totals
    return query_counter_totals()


def _src_kernels() -> Dict[str, float]:
    from ..ops import kernels
    from .metrics import _DEVICE_METRICS
    stats = dict(kernels.STATS)
    out = {name: stats[key]
           for key, (name, _help) in _DEVICE_METRICS.items()
           if key in stats}
    out["tinysql_pending_cost_analyses"] = len(kernels._PENDING_COSTS)
    return out


def _src_progcache() -> Dict[str, float]:
    from ..ops import progcache
    p = progcache.stats_snapshot()
    return {"tinysql_progcache_hits_total": p.get("hits", 0),
            "tinysql_progcache_misses_total": p.get("misses", 0),
            "tinysql_prewarm_seeded_total": p.get("prewarm_seeded", 0),
            "tinysql_prewarm_hits_total": p.get("prewarm_hits", 0),
            "tinysql_compile_seconds_total": p.get("compile_wall_s", 0.0),
            "tinysql_progcache_programs": progcache.size()}


def _src_pool() -> Dict[str, float]:
    from ..server.pool import gauges
    g = gauges()
    return {"tinysql_pool_queued": g["queued"],
            "tinysql_pool_running": g["running"]}


def _src_conn() -> Dict[str, float]:
    from ..server.admission import conn_stats_snapshot
    from ..server.server import conn_gauges
    g = conn_gauges()
    a = conn_stats_snapshot()
    return {"tinysql_conn_open": g["open"],
            "tinysql_conn_idle": g["idle"],
            "tinysql_conn_active": g["active"],
            "tinysql_conn_accepts_total": a.get("accepts", 0),
            "tinysql_conn_sheds_total": a.get("sheds", 0)}


def _src_admission() -> Dict[str, float]:
    from ..server.admission import aggregate_stmt_mem, stats_snapshot
    a = stats_snapshot()
    return {"tinysql_admission_admitted_total": a.get("admitted", 0),
            "tinysql_admission_queued_total": a.get("queued", 0),
            "tinysql_admission_rejected_total": a.get("rejected", 0),
            "tinysql_admission_queue_wait_seconds_total":
                a.get("queue_wait_s_sum", 0.0),
            "tinysql_stmt_mem_inflight_bytes": aggregate_stmt_mem()}


def _src_batching() -> Dict[str, float]:
    from ..ops.batching import stats_snapshot
    b = stats_snapshot()
    return {"tinysql_batch_rounds_total": b.get("batches", 0),
            "tinysql_batch_statements_total":
                b.get("batched_statements", 0),
            "tinysql_batch_occupancy_sum": b.get("occupancy_sum", 0),
            "tinysql_batch_fallbacks_total": b.get("fallbacks", 0),
            "tinysql_batch_stacked_rounds_total":
                b.get("stacked_rounds", 0),
            "tinysql_batch_stacked_occupancy_sum":
                b.get("stacked_occupancy_sum", 0),
            "tinysql_batch_stack_fallbacks_total":
                b.get("stack_fallbacks", 0),
            "tinysql_batch_dispatch_seconds_total":
                b.get("dispatch_s_sum", 0.0)}


def _src_memory() -> Dict[str, float]:
    from ..utils import memory as mem
    return {"tinysql_mem_quota_exceeded_total": mem.aborts_total()}


def _src_spill() -> Dict[str, float]:
    from ..ops.spill import stats_snapshot
    s = stats_snapshot()
    return {"tinysql_spill_bytes_total": s.get("spill_bytes", 0),
            "tinysql_spill_reload_bytes_total":
                s.get("spill_reload_bytes", 0),
            "tinysql_spill_partitions_total":
                s.get("spill_partitions", 0),
            "tinysql_spill_repartitions_total":
                s.get("spill_repartitions", 0),
            "tinysql_spill_stream_runs_total":
                s.get("spill_stream_runs", 0),
            "tinysql_spilled_statements_total":
                s.get("spilled_statements", 0),
            "tinysql_spill_open_slots": s.get("open_slots", 0)}


def _src_shardops() -> Dict[str, float]:
    from ..ops.shardops import stats_snapshot
    from .metrics import SHARD_METRIC_NAMES
    s = stats_snapshot()
    return {name: s.get(key, 0) for key, name in SHARD_METRIC_NAMES}


def _src_wal() -> Dict[str, float]:
    from ..kv.wal import stats_snapshot
    from .metrics import WAL_METRIC_NAMES
    s = stats_snapshot()
    if not any(s.values()):
        return {}  # volatile store: zero movement, zero samples
    return {name: s.get(key, 0) for key, name in WAL_METRIC_NAMES}


def _src_flight() -> Dict[str, float]:
    from .flight import stats_snapshot
    from .metrics import FLIGHT_METRIC_NAMES
    s = stats_snapshot()
    if not any(s.values()):
        return {}  # no data dir armed: zero movement, zero samples
    return {name: s.get(key, 0) for key, name in FLIGHT_METRIC_NAMES}


def _src_identity() -> Dict[str, float]:
    from .flight import current_incarnation, server_start_ts
    return {"tinysql_incarnation": float(current_incarnation()),
            "tinysql_server_start_timestamp": server_start_ts()}


def _src_degrade() -> Dict[str, float]:
    from ..ops import degrade
    d = degrade.snapshot()
    return {"tinysql_device_loss_total": d["device_loss_total"],
            "tinysql_degraded_statements_total":
                d["degraded_statements_total"],
            "tinysql_cpu_pinned": d["cpu_pinned"]}


def _src_failpoints() -> Dict[str, float]:
    from .. import fail
    return {"tinysql_failpoint_hits_total": sum(fail.hits().values())}


def _src_prewarm() -> Dict[str, float]:
    from ..session.prewarm import stats_snapshot
    return {f"tinysql_prewarm_worker_{k}_total": v
            for k, v in stats_snapshot().items()}


def _src_slo() -> Dict[str, float]:
    # SLO error-budget accounting: empty while tidb_slo_p99_ms is
    # unarmed (obs/inspect.slo_sample owns the bucket-edge math so the
    # source and the slo-burn rule share one definition)
    from . import inspect as oinspect
    return oinspect.slo_sample()


def _src_memory_state() -> Dict[str, float]:
    # measured-vs-tracked memory reconciliation (obs/memprof.py): the
    # tracked MemTracker ledger vs tracemalloc heap / RSS vs the HBM
    # census, plus the heap sampler's self-accounting — the evidence
    # series the heap-growth / hbm-pressure / mem-untracked rules judge
    from . import memprof
    return memprof.memory_state()


def _src_conprof() -> Dict[str, float]:
    # continuous host profiler (obs/conprof.py): the cpu-saturation and
    # profiler-overhead inspection rules judge these windowed deltas
    from . import conprof
    s = conprof.stats_snapshot()
    out = {"tinysql_conprof_samples_total": s.get("samples", 0),
           "tinysql_conprof_idle_samples_total":
               s.get("idle_samples", 0),
           "tinysql_conprof_attributed_samples_total":
               s.get("attributed", 0),
           "tinysql_conprof_ticks_total": s.get("ticks", 0),
           "tinysql_conprof_self_seconds_total": s.get("self_s", 0.0),
           "tinysql_conprof_evicted_total": s.get("evicted", 0),
           "tinysql_conprof_backoff": s.get("backoff", 1),
           "tinysql_conprof_stacks": s.get("stacks", 0),
           "tinysql_conprof_windows": s.get("windows", 0)}
    for role, n in s.get("role_busy", {}).items():
        out[conprof.role_metric(role)] = n
    return out


def _src_tsring() -> Dict[str, float]:
    s = stats_snapshot()
    return {"tinysql_metrics_samples_total": s.get("samples", 0),
            "tinysql_metrics_sample_seconds_total":
                s.get("sample_wall_s", 0.0),
            "tinysql_metrics_dropped_unregistered_total":
                s.get("dropped_unregistered", 0),
            "tinysql_metrics_ring_entries": RING.size()}


for _name, _fn in (("queries", _src_queries), ("kernels", _src_kernels),
                   ("progcache", _src_progcache), ("pool", _src_pool),
                   ("conn", _src_conn), ("admission", _src_admission),
                   ("batching", _src_batching), ("memory", _src_memory),
                   ("spill", _src_spill), ("shardops", _src_shardops),
                   ("wal", _src_wal),
                   ("flight", _src_flight),
                   ("identity", _src_identity),
                   ("degrade", _src_degrade),
                   ("failpoints", _src_failpoints),
                   ("prewarm", _src_prewarm), ("slo", _src_slo),
                   ("conprof", _src_conprof),
                   ("memory_state", _src_memory_state),
                   ("tsring", _src_tsring)):
    register_source(_name, _fn)
