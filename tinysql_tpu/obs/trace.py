"""Lightweight nested span tracer with Chrome trace-event export.

One ``Tracer`` per statement (owned by its ``QueryObs``).  Spans are
recorded as *complete* events — name, category, start, duration, thread
id, span id, parent id — cheap enough to leave always-on: a statement
records a handful of lifecycle spans (parse → plan → place → execute)
plus one span per program dispatch / D2H drain / compile-cache miss /
pipeline stage block.

Cross-thread parenting: the devpipe producer thread runs inside a
``contextvars`` copy of the creator's context, so a ``stage`` span's
parent is whatever span was live when the pipeline was constructed (the
operator's ``next()`` frame), even though it executes on another thread.
Chrome's viewer lanes by ``tid``; our own JSON keeps explicit ``parent``
ids so tests (and tools/trace2json.py) can verify the nesting.

A process-global ring buffer keeps the last N query traces for the
status server's ``/debug/trace`` endpoint (``TINYSQL_TRACE_RING`` caps
N, default 32).
"""
from __future__ import annotations

import itertools
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

_ids = itertools.count(1)


class Span:
    __slots__ = ("sid", "name", "cat", "start_s", "dur_s", "tid",
                 "tname", "parent", "args")

    def __init__(self, name: str, cat: str, parent: Optional[int],
                 args: Optional[dict] = None):
        self.sid = next(_ids)
        self.name = name
        self.cat = cat
        self.start_s = time.perf_counter()
        self.dur_s = 0.0
        self.tid = threading.get_ident()
        # the recording thread's NAME rides along so TRACE <stmt> and
        # offline tooling can classify the span by serving role
        # (obs/conprof.classify) without a live thread table
        self.tname = threading.current_thread().name
        self.parent = parent
        self.args = args or {}

    def to_dict(self) -> dict:
        return {"id": self.sid, "name": self.name, "cat": self.cat,
                "ts_us": round(self.start_s * 1e6, 1),
                "dur_us": round(self.dur_s * 1e6, 1),
                "tid": self.tid, "thread": self.tname,
                "parent": self.parent, "args": self.args}


class Tracer:
    """Span sink for one statement.  Append-only under a lock — the
    devpipe producer thread and the consumer record concurrently."""

    def __init__(self):
        self._mu = threading.Lock()
        self._spans: List[Span] = []

    def begin(self, name: str, cat: str = "query",
              parent: Optional[int] = None,
              args: Optional[dict] = None) -> Span:
        return Span(name, cat, parent, args)

    def end(self, span: Span) -> None:
        span.dur_s = time.perf_counter() - span.start_s
        with self._mu:
            self._spans.append(span)

    def add_complete(self, name: str, start_s: float, dur_s: float,
                     cat: str = "query", parent: Optional[int] = None,
                     args: Optional[dict] = None) -> Span:
        """Record an already-measured interval (e.g. the batch parse wall
        measured before the statement scope existed)."""
        s = Span(name, cat, parent, args)
        s.start_s = start_s
        s.dur_s = dur_s
        with self._mu:
            self._spans.append(s)
        return s

    def spans(self) -> List[dict]:
        with self._mu:
            return [s.to_dict() for s in self._spans]

    def chrome_trace(self, pid: int = 0,
                     label: str = "") -> Dict[str, list]:
        """chrome://tracing / Perfetto ``traceEvents`` JSON (via the
        shared ``spans_to_events`` converter)."""
        out = {"traceEvents": spans_to_events(self.spans(), pid=pid)}
        if label:
            out["otherData"] = {"query": label}
        return out


def spans_to_events(spans: List[dict], pid: int = 0,
                    label: str = "") -> List[dict]:
    """THE span-dict -> Chrome-trace-event conversion, shared by
    ``Tracer.chrome_trace`` and tools/trace2json.py so the two export
    surfaces cannot drift.  Spans become phase-``X`` complete events;
    thread lanes come from the recording thread's ident; ``label``
    (when given) names the process track."""
    events: List[dict] = []
    tids: Dict[int, int] = {}
    for sp in spans:
        tids.setdefault(sp.get("tid", 0), len(tids))
    if label:
        events.append({"ph": "M", "pid": pid, "tid": 0,
                       "name": "process_name", "args": {"name": label}})
    for tid, lane in tids.items():
        events.append({"ph": "M", "pid": pid, "tid": lane,
                       "name": "thread_name",
                       "args": {"name": ("main" if lane == 0
                                         else f"stage-{lane}")}})
    for sp in spans:
        events.append({
            "ph": "X", "pid": pid, "tid": tids[sp.get("tid", 0)],
            "name": sp.get("name", "?"), "cat": sp.get("cat", "query"),
            "ts": sp.get("ts_us", 0.0), "dur": sp.get("dur_us", 0.0),
            "args": dict(sp.get("args") or {}, span_id=sp.get("id"),
                         parent=sp.get("parent")),
        })
    return events


# ---- TRACE <stmt> rendering -----------------------------------------------

#: TRACE <stmt> result columns (session/_exec_trace)
TRACE_COLUMNS = ("span", "parent", "start_offset_us", "duration_us",
                 "thread_role")


def trace_rows(spans: List[dict]) -> List[list]:
    """Render recorded span dicts as the ``TRACE <stmt>`` resultset:
    depth-indented span name (tree order: children by start time under
    their parent), parent span name, start offset relative to the
    earliest span (µs), duration (µs), and the recording thread's
    serving role (obs/conprof.classify over the captured thread name —
    a devpipe stage span reads ``devpipe`` even though it parents into
    the statement's chain)."""
    from .conprof import classify
    if not spans:
        return []
    by_id = {sp["id"]: sp for sp in spans}
    children: Dict[Optional[int], List[dict]] = {}
    for sp in spans:
        parent = sp.get("parent")
        if parent is not None and parent not in by_id:
            parent = None  # parent never ended (e.g. the outer execute)
        children.setdefault(parent, []).append(sp)
    for sibs in children.values():
        sibs.sort(key=lambda s: s.get("ts_us", 0.0))
    t0 = min(sp.get("ts_us", 0.0) for sp in spans)
    out: List[list] = []

    def render(sp: dict, depth: int) -> None:
        parent = by_id.get(sp.get("parent"))
        pname = ""
        if parent is not None:
            pname = str(parent.get("name", ""))
        out.append(["  " * depth + str(sp.get("name", "?")),
                    pname,
                    round(sp.get("ts_us", 0.0) - t0, 1),
                    round(sp.get("dur_us", 0.0), 1),
                    classify(str(sp.get("thread", "")))])
        for child in children.get(sp["id"], []):
            render(child, depth + 1)

    for root in children.get(None, []):
        render(root, 0)
    return out


# ---- process-global ring of recent query traces (/debug/trace) ----------

def _ring_cap() -> int:
    try:
        return max(1, int(os.environ.get("TINYSQL_TRACE_RING", "32")))
    except ValueError:
        return 32


_ring_mu = threading.Lock()
_RING: deque = deque(maxlen=_ring_cap())


def publish_trace(entry: dict) -> None:
    """Append one finished statement's trace record:
    ``{"sql", "ts", "total_ms", "spans", "chrome"}``."""
    with _ring_mu:
        _RING.append(entry)


def recent_traces(n: Optional[int] = None) -> List[dict]:
    with _ring_mu:
        out = list(_RING)
    return out[-n:] if n else out


def clear_traces() -> None:
    with _ring_mu:
        _RING.clear()
