"""Memory truth: continuous heap profiler, device-buffer census, and
measured-vs-tracked reconciliation (reference lineage: TiDB Dashboard's
continuous profiling applied to the HEAP axis + TiDB's memory-usage
introspection — the ledger every OOM postmortem wishes it had).

The "measured truth" series made device time (ISSUE 11), host CPU
(ISSUE 13), and device transfers (ISSUE 16) measured rather than
estimated; memory — the input to every spill-ladder and admission
decision — was still bookkeeping-only: ``utils/memory.MemTracker``
charges nominal byte counts and nothing ever checks the ledger against
the process.  This module owns the measured answer, from two sources
plus one reconciler:

1. **Host heap** (:class:`HeapProfiler` + :class:`MemprofSampler`): a
   tracemalloc-based sampling profiler following conprof's exact design
   — a background sampler on the server lifecycle paced by the GLOBAL
   ``tidb_memprof_rate`` sysvar (Hz, 0 = off, re-read live every tick),
   folding the top allocation SITES (``file:lineno`` chains) into
   bounded per-window aggregates with the stmtsummary/conprof
   rotation/eviction/tombstone semantics, classifying each site by
   serving ROLE (matched against live thread stacks through the
   conprof thread-name vocabulary), and attributing each tick's
   positive traced-heap delta to the statements currently EXECUTING
   (resolved through the interrupt registry) — so
   ``statements_summary`` gains ``sum_heap_alloc_kb`` / ``max_heap_kb``
   columns, all under the same hard <3% self-cost budget and backoff
   divisor conprof runs under.
2. **Device HBM census** (:func:`hbm_census`): a
   ``jax.live_arrays()``-walking snapshot classifier that attributes
   every live device buffer to its birth site — replica-memoized
   columns (columnar/store.py device memos), ParamTable uploads, the
   spill working set, progcache-registered program state — with an
   *unattributed* leak bucket that must read empty after a quiesced
   workload.  Owners register walkers (:func:`register_census_walker`)
   so the census needs no knowledge of individual caches.  The census
   also feeds measured per-table row width back into the spill gates
   (:func:`measured_row_bytes` — replacing the nominal
   ``_NOMINAL_ROW_BYTES`` pricing with replica truth).
3. **Reconciliation** (:func:`memory_state`): one snapshot sampling
   tracked MemTracker bytes (the ledger) vs measured tracemalloc heap /
   RSS vs the HBM census — the ``memory_state`` time-series source the
   ``heap-growth`` / ``hbm-pressure`` / ``mem-untracked`` inspection
   rules judge, served as ``information_schema.memory_usage`` and
   ``/debug/heap`` (collapsed-site text sharing conprof's parser).

Semantics and honesty notes (the blind-spot contract, documented like
ISSUE 16's ``np.ascontiguousarray`` caveat):

- tracemalloc sees PYTHON allocations only.  XLA's C++ device arena,
  numpy buffers allocated before ``tracemalloc.start()``, and any
  malloc outside the CPython allocator are invisible to the traced
  number — that is exactly why RSS and the HBM census ride alongside
  it in ``memory_state`` instead of one number pretending to be truth.
- allocation sites carry ``file:lineno`` chains, NOT thread identity —
  tracemalloc drops the allocating thread.  Role classification is
  therefore best-effort: a site is attributed to a role when one of
  its call-site frames is live on a thread of that role at sample time
  (call-site ``(file, lineno)`` pairs match exactly between a
  traceback and a suspended frame); sites whose allocation path is no
  longer on any stack read ``other``.
- statement attribution splits each tick's POSITIVE traced-heap delta
  evenly among the statements executing at that instant, so the sum of
  ``sum_heap_alloc_kb`` across concurrent statements can never exceed
  the process's measured heap growth (the heap analogue of conprof's
  ``cpu <= wall`` cap); ``max_heap_kb`` is the traced-heap high water
  observed while the statement ran — an upper bound, process-wide by
  construction.
- the sampler's self-cost is measured every tick; past
  ``OVERHEAD_BUDGET_FRAC`` of one core the ``backoff`` divisor doubles
  (conprof's exact hysteresis) — the profiler may get coarser under
  load, never expensive.  ``tidb_memprof_rate = 0`` costs one sysvar
  read per idle slice and leaves every surface byte-identical.

WRITE DISCIPLINE (qlint OB407): the fold/attribution state here — and
the statement heap/HBM counters (``heap_kb`` / ``heap_peak_kb`` /
``hbm_bytes``) — are written ONLY from this module.  Any other writer
would publish un-measured bookkeeping as memory truth or corrupt the
window accounting.
"""
from __future__ import annotations

import os
import threading
import time
import tracemalloc
from collections import deque
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from .. import fail

DEFAULT_RATE_HZ = 1
DEFAULT_WINDOW_S = 60
DEFAULT_HISTORY = 15
DEFAULT_MAX_SITES = 256

#: ceiling on the applied rate regardless of the sysvar: a tracemalloc
#: snapshot is orders pricier than a frame walk — beyond this the
#: backoff would only fight the sysvar
MAX_RATE_HZ = 50

#: tracemalloc frames kept per allocation site (tracemalloc.start
#: depth; deeper costs every allocation in the process, not just ticks)
MAX_SITE_DEPTH = 12

#: top allocation sites (by live size) folded per tick — the window
#: aggregates the union across ticks, so the cap bounds tick cost, not
#: coverage
TOP_SITES_PER_TICK = 64

#: the sampler's self-cost budget as a fraction of one core; past it
#: the backoff divisor doubles (mem analogue of conprof's rule)
OVERHEAD_BUDGET_FRAC = 0.03
BACKOFF_MAX = 16

EVICTED_SITE = "(evicted)"

#: band for the mem-untracked reconciliation (obs/inspect.py): windowed
#: traced-heap growth may run this far past the MemTracker ledger
#: before the divergence is a finding — interpreter caches, compiled
#: program metadata, and obs stores all legitimately allocate outside
#: the statement ledger
UNTRACKED_BAND_BYTES = 64 << 20


def fold_site(frames: Iterable[Tuple[str, int]],
              max_depth: int = MAX_SITE_DEPTH) -> str:
    """``(file, lineno)`` chain (root->leaf) -> the folded site string
    ``base.py:lineno;...`` — same shape contract as conprof's folded
    stacks, so /debug/heap shares conprof.parse_collapsed and the
    flamegraph toolchain."""
    parts = [f"{f.rsplit('/', 1)[-1]}:{ln}" for f, ln in frames]
    return ";".join(parts[-max_depth:])


def _live_frame_roles(frames: Optional[Dict[int, object]] = None,
                      skip_idents: Tuple[int, ...] = ()) -> \
        Dict[Tuple[str, int], str]:
    """``(file basename, lineno) -> role`` over every frame currently
    suspended on a live thread (conprof's thread-name vocabulary).  The
    non-leaf entries are CALL SITES — the exact (file, lineno) pairs a
    tracemalloc traceback carries for its non-leaf frames — so a heap
    site allocated under a still-running call path matches its role."""
    import sys
    from .conprof import classify
    if frames is None:
        frames = sys._current_frames()
    names = {t.ident: t.name for t in threading.enumerate()}
    out: Dict[Tuple[str, int], str] = {}
    for tid, frame in frames.items():
        if tid in skip_idents:
            continue
        role = classify(names.get(tid, ""))
        f = frame
        while f is not None:
            key = (f.f_code.co_filename.rsplit("/", 1)[-1], f.f_lineno)
            if key not in out or out[key] == "other":
                out[key] = role
            f = f.f_back
    return out


def classify_site(frames: Iterable[Tuple[str, int]],
                  rolemap: Dict[Tuple[str, int], str]) -> str:
    """Best-effort role of an allocation site: leaf-most frame that is
    live on some thread's stack wins; ``other`` when the allocation
    path is no longer executing anywhere."""
    for f, ln in reversed(list(frames)):
        role = rolemap.get((f.rsplit("/", 1)[-1], ln))
        if role is not None:
            return role
    return "other"


# ---- the windowed site store ----------------------------------------------

class _SiteAgg:
    __slots__ = ("samples", "size_kb", "peak_kb", "last_seen")

    def __init__(self):
        self.samples = 0
        self.size_kb = 0.0       # last-observed live bytes at this site
        self.peak_kb = 0.0       # max observed within the window
        self.last_seen = 0.0

    def merge(self, other: "_SiteAgg") -> None:
        # tombstone accounting: sizes SUM (distinct sites folded into
        # one bucket), peaks keep the max single site
        self.samples += other.samples
        self.size_kb += other.size_kb
        self.peak_kb = max(self.peak_kb, other.peak_kb)
        self.last_seen = max(self.last_seen, other.last_seen)


class HeapProfiler:
    """The fold/attribution store: current window + bounded rotated
    history, conprof-style.  Written from the sampler thread; read from
    any session scanning ``memory_usage`` or hitting ``/debug/heap`` —
    all paths take the lock."""

    def __init__(self, window_s: float = DEFAULT_WINDOW_S,
                 history: int = DEFAULT_HISTORY,
                 max_sites: int = DEFAULT_MAX_SITES):
        self.window_s = float(window_s)
        self.max_history = int(history)
        self.max_sites = int(max_sites)
        self._mu = threading.Lock()
        #: (role, folded site) -> aggregate, current window
        self._entries: Dict[Tuple[str, str], _SiteAgg] = {}
        #: anchored by the FIRST fold (stmtsummary window discipline)
        self.window_begin: Optional[float] = None
        #: rotated windows, oldest first: (window_begin, {key: agg})
        self.history: deque = deque()
        #: adaptive rate divisor: effective period = backoff / rate
        self.backoff = 1
        self._cost_ewma = 0.0
        #: traced-heap KB at the previous tick (attribution baseline);
        #: None = no baseline (first tick / tracing restarted)
        self._last_traced_kb: Optional[float] = None
        self._stats = {"ticks": 0, "sites": 0, "attributed": 0,
                       "self_s": 0.0, "evicted": 0, "errors": 0,
                       "traced_kb": 0.0, "traced_peak_kb": 0.0}

    # ---- the designated write path (sampler thread ONLY) ----------------
    def sample_once(self, period_s: float, now: Optional[float] = None,
                    stats: Optional[List[tuple]] = None,
                    frames: Optional[Dict[int, object]] = None,
                    traced_kb: Optional[float] = None,
                    traced_peak_kb: Optional[float] = None,
                    hbm_bytes: Optional[float] = None,
                    window_s: Optional[float] = None,
                    history: Optional[int] = None,
                    max_sites: Optional[int] = None,
                    skip_idents: Tuple[int, ...] = (),
                    attribute: bool = True) -> int:
        """One sampling tick: snapshot the traced heap, fold the top
        allocation sites, attribute the tick's positive traced-heap
        delta to executing statements.  ``now``/``stats``/``frames``/
        ``traced_kb`` are injectable for deterministic tests (``stats``
        is ``[(frames root->leaf as (file, lineno) tuples, size_bytes),
        ...]``); the ``window_s``/``history``/``max_sites`` overrides
        carry the live sysvars.  ``attribute=False`` folds only — the
        overhead probe's back-to-back ticks must never write statement
        heap.  Returns the number of sites folded."""
        t0 = time.perf_counter()
        fail.inject("memprofSampleError")
        if now is None:
            now = time.time()
        if stats is None:
            stats = self._snapshot_sites()
        if traced_kb is None:
            if tracemalloc.is_tracing():
                cur, peak = tracemalloc.get_traced_memory()
                traced_kb = cur / 1024.0
                if traced_peak_kb is None:
                    traced_peak_kb = peak / 1024.0
            else:
                traced_kb = 0.0
        if traced_peak_kb is None:
            traced_peak_kb = traced_kb
        if hbm_bytes is None:
            hbm_bytes = _hbm_total_fast()
        rolemap = _live_frame_roles(frames=frames,
                                    skip_idents=skip_idents)
        n = 0
        for site_frames, size in stats:
            folded = fold_site(site_frames)
            if not folded:
                continue
            role = classify_site(site_frames, rolemap)
            self._fold(role, folded, size / 1024.0, now,
                       window_s=window_s, history=history,
                       max_sites=max_sites)
            n += 1
        delta_kb = 0.0
        if self._last_traced_kb is not None:
            delta_kb = traced_kb - self._last_traced_kb
        self._last_traced_kb = traced_kb
        if attribute and delta_kb > 0:
            self._attribute(delta_kb, traced_kb, hbm_bytes)
        wall = time.perf_counter() - t0
        with self._mu:
            self._stats["ticks"] += 1
            self._stats["self_s"] += wall
            self._stats["traced_kb"] = traced_kb
            if traced_peak_kb > self._stats["traced_peak_kb"]:
                self._stats["traced_peak_kb"] = traced_peak_kb
        self._note_cost(wall, period_s)
        return n

    @staticmethod
    def _snapshot_sites() -> List[tuple]:
        """Live top-N allocation sites as ``[(frames root->leaf,
        size_bytes), ...]`` — empty when tracemalloc is off (the
        sampler starts it; a bare profiler without tracing still ticks,
        it just has no sites to fold)."""
        if not tracemalloc.is_tracing():
            return []
        snap = tracemalloc.take_snapshot()
        try:
            snap = snap.filter_traces((
                tracemalloc.Filter(False, tracemalloc.__file__),))
        except Exception:
            pass
        out: List[tuple] = []
        for st in snap.statistics("traceback")[:TOP_SITES_PER_TICK]:
            frames = tuple((f.filename, f.lineno) for f in st.traceback)
            out.append((frames, st.size))
        return out

    @staticmethod
    def _statement_scopes() -> List[object]:
        """QueryObs of every statement currently EXECUTING (interrupt
        registry — the processlist feed)."""
        from ..utils import interrupt
        out: List[object] = []
        seen: set = set()
        for tid, sess in interrupt.executing_threads().items():
            qobs = getattr(sess, "last_query_stats", None)
            if qobs is not None and id(qobs) not in seen:
                seen.add(id(qobs))
                out.append(qobs)
        return out

    def _fold(self, role: str, folded: str, size_kb: float, now: float,
              window_s=None, history=None, max_sites=None) -> None:
        with self._mu:
            if window_s is not None:
                self.window_s = float(window_s)
            if history is not None:
                self.max_history = int(history)
            if max_sites is not None:
                self.max_sites = int(max_sites)
            if self.window_begin is None:
                self.window_begin = now
            elif self.window_s > 0 \
                    and now - self.window_begin >= self.window_s:
                self._rotate(now)
            key = (role, folded)
            agg = self._entries.get(key)
            if agg is None:
                if self.max_sites > 0:
                    # _evict_one reports progress (the conprof
                    # tombstone-floor discipline): once only tombstones
                    # remain, looping on an unchanged length would spin
                    # under the lock forever
                    while len(self._entries) >= self.max_sites:
                        if not self._evict_one():
                            break
                agg = self._entries[key] = _SiteAgg()
            agg.samples += 1
            agg.size_kb = size_kb
            if size_kb > agg.peak_kb:
                agg.peak_kb = size_kb
            agg.last_seen = now
            self._stats["sites"] += 1

    def _attribute(self, delta_kb: float, traced_kb: float,
                   hbm_bytes: float) -> None:
        """Split this tick's positive traced-heap growth evenly among
        the executing statements — each share is <= the total growth,
        so the sum of per-statement heap attribution can never exceed
        the process's measured allocation (the <=-growth invariant,
        tested).  The traced high water and the HBM census total ride
        along as high-water marks."""
        try:
            scopes = self._statement_scopes()
            if not scopes:
                return
            share = delta_kb / len(scopes)
            for qobs in scopes:
                qobs.add_counter("heap_kb", share)
                qobs.hwm_counter("heap_peak_kb", traced_kb)
                if hbm_bytes > 0:
                    qobs.hwm_counter("hbm_bytes", hbm_bytes)
            with self._mu:
                self._stats["attributed"] += len(scopes)
        except Exception:
            # a statement finishing mid-attribution must never kill the
            # sampler tick
            pass

    def _rotate(self, now: float) -> None:
        # caller holds the lock
        if self._entries:
            self.history.append((self.window_begin, self._entries))
            while len(self.history) > max(self.max_history, 0):
                self.history.popleft()
        self._entries = {}
        self.window_begin = now

    def _evict_one(self) -> bool:
        # caller holds the lock: least-recently-seen site folds into its
        # role's tombstone (stmtsummary eviction discipline).  Returns
        # False when no evictable entry remains OR the eviction CREATED
        # the tombstone (no slot freed) — the caller must stop, not spin.
        victims = [k for k in self._entries if k[1] != EVICTED_SITE]
        if not victims:
            return False
        vkey = min(victims, key=lambda k: self._entries[k].last_seen)
        victim = self._entries.pop(vkey)
        tkey = (vkey[0], EVICTED_SITE)
        tomb = self._entries.get(tkey)
        created = tomb is None
        if created:
            tomb = self._entries[tkey] = _SiteAgg()
        tomb.merge(victim)
        self._stats["evicted"] += 1
        return not created

    def note_error(self) -> None:
        """Sampler-tick failure accounting (memprofSampleError and any
        torn snapshot): the error is COUNTED, the thread lives on."""
        with self._mu:
            self._stats["errors"] += 1

    def _note_cost(self, tick_wall_s: float, period_s: float) -> None:
        """conprof's adaptive overhead control verbatim: EWMA the
        per-tick self cost; past the budget share of one core the
        backoff divisor doubles, stepping back down only with
        hysteresis."""
        with self._mu:
            self._cost_ewma = tick_wall_s if self._cost_ewma == 0.0 \
                else 0.8 * self._cost_ewma + 0.2 * tick_wall_s
            cost_frac = self._cost_ewma / max(period_s, 1e-9)
            if cost_frac > OVERHEAD_BUDGET_FRAC \
                    and self.backoff < BACKOFF_MAX:
                self.backoff *= 2
            elif self.backoff > 1 \
                    and cost_frac * 2 < 0.5 * OVERHEAD_BUDGET_FRAC:
                self.backoff //= 2

    # ---- reads -----------------------------------------------------------
    def _maybe_rotate_stale(self, now: Optional[float]) -> None:
        # caller holds the lock (read-side rotation: a long-expired
        # window must not present as current)
        if now is None:
            now = time.time()
        if self.window_begin is not None and self.window_s > 0 \
                and now - self.window_begin >= self.window_s:
            self._rotate(now)

    def collapsed(self, window_s: Optional[float] = None,
                  now: Optional[float] = None) -> str:
        """The /debug/heap payload: collapsed-site text, one
        ``role;file:line;... kb`` line per distinct (role, site), merged
        across every retained window whose begin falls inside the last
        ``window_s`` seconds (None or 0 = everything retained).  Counts
        are live KB (max across windows — a persistent allocation must
        not double across rotations); conprof.parse_collapsed ingests
        it, as does flamegraph.pl."""
        if now is None:
            now = time.time()
        horizon = now - window_s if window_s else None
        merged: Dict[str, int] = {}
        with self._mu:
            self._maybe_rotate_stale(now)
            windows = list(self.history)
            if self._entries:
                windows.append((self.window_begin, self._entries))
            for begin, entries in windows:
                if horizon is not None and begin < horizon:
                    continue
                for (role, folded), agg in entries.items():
                    line = f"{role};{folded}"
                    kb = int(round(agg.peak_kb))
                    if kb > merged.get(line, -1):
                        merged[line] = kb
        return "\n".join(f"{site} {kb}"
                         for site, kb in sorted(merged.items()))

    def stats_snapshot(self) -> Dict[str, float]:
        with self._mu:
            out = dict(self._stats)
            out["backoff"] = self.backoff
            out["site_entries"] = len(self._entries)
            out["windows"] = len(self.history) + (
                1 if self._entries else 0)
            return out

    def reset(self) -> None:
        """Tests only."""
        with self._mu:
            self._entries = {}
            self.history.clear()
            self.window_begin = None
            self.backoff = 1
            self._cost_ewma = 0.0
            self._last_traced_kb = None
            self._stats = {"ticks": 0, "sites": 0, "attributed": 0,
                           "self_s": 0.0, "evicted": 0, "errors": 0,
                           "traced_kb": 0.0, "traced_peak_kb": 0.0}


#: the process-global profiler every surface reads
PROF = HeapProfiler()


def collapsed(window_s: Optional[float] = None) -> str:
    return PROF.collapsed(window_s=window_s)


def stats_snapshot() -> Dict[str, float]:
    return PROF.stats_snapshot()


def reset() -> None:
    """Tests only."""
    PROF.reset()


def measure_overhead(n: int = 20,
                     rate_hz: int = DEFAULT_RATE_HZ) -> Dict[str, float]:
    """The heap profiler's steady-state cost, THE definition both
    benches publish as ``memprof_overhead_frac`` when no live sampler
    ran: one tick's wall (averaged over ``n`` live snapshots of THIS
    process) times the ticks-per-second at ``rate_hz``.  Probes a
    PRIVATE HeapProfiler so the measurement never pollutes the live
    store; starts tracemalloc only if it was off, and stops it again."""
    prof = HeapProfiler()
    period = 1.0 / max(rate_hz, 1)
    started = False
    if not tracemalloc.is_tracing():
        tracemalloc.start(MAX_SITE_DEPTH)
        started = True
    try:
        # attribute=False: back-to-back probe ticks must not fabricate
        # statement heap growth
        prof.sample_once(period, attribute=False)  # warm lazy imports
        t0 = time.perf_counter()
        for _ in range(n):
            prof.sample_once(period, attribute=False)
        per_tick_s = (time.perf_counter() - t0) / n
    finally:
        if started:
            tracemalloc.stop()
    return {"tick_wall_s": round(per_tick_s, 6), "rate_hz": rate_hz,
            "memprof_overhead_frac": round(per_tick_s * rate_hz, 6)}


def live_overhead_frac(stats_before: Dict[str, float],
                       stats_after: Dict[str, float],
                       wall_s: float) -> float:
    """Sampler self-cost over a measured live window: the delta of the
    profiler's own accumulated tick wall divided by the elapsed wall —
    what bench_serve.py hard-gates against the 3% budget (alongside the
    conprof gate)."""
    d = float(stats_after.get("self_s", 0.0)) \
        - float(stats_before.get("self_s", 0.0))
    return round(d / max(wall_s, 1e-9), 6)


# ---- the device HBM census ------------------------------------------------

#: census category -> walker yielding candidate owner objects (arrays,
#: or containers searched recursively for device arrays).  Owners
#: register here (columnar/store.py, ops/exprjit.py, ops/spill.py,
#: ops/progcache.py) so the census needs no per-cache knowledge.
_CENSUS_WALKERS: Dict[str, Callable[[], Iterable[object]]] = {}


def register_census_walker(category: str,
                           fn: Callable[[], Iterable[object]]) -> None:
    _CENSUS_WALKERS[category] = fn


def _jax_if_loaded():
    """The jax module ONLY if something already imported it — the
    census must never be the thing that pays jax's import+backend cost
    (a pure-KV process has no device buffers to count anyway)."""
    from ..ops import kernels
    return kernels._jax


def _iter_device_arrays(obj, jax_mod, depth: int = 0):
    """Device arrays nested anywhere inside ``obj`` (tuples/lists/dicts
    of memo values — the replica cache stores (values, codes, n)
    bundles)."""
    if depth > 4 or obj is None:
        return
    if isinstance(obj, jax_mod.Array):
        yield obj
        return
    if isinstance(obj, dict):
        for v in obj.values():
            yield from _iter_device_arrays(v, jax_mod, depth + 1)
    elif isinstance(obj, (tuple, list)):
        for v in obj:
            yield from _iter_device_arrays(v, jax_mod, depth + 1)


def hbm_census() -> dict:
    """Snapshot of every live device buffer, attributed to its birth
    site: ``{"total_bytes", "buffers", "by_category": {cat: {"bytes",
    "buffers"}}, "unattributed_bytes", "unattributed_buffers"}``.
    Buffers no registered owner claims land in the *unattributed*
    bucket — the leak bucket, asserted empty after a quiesced workload
    (tools/memprof_smoke.py)."""
    jax_mod = _jax_if_loaded()
    by_cat = {cat: {"bytes": 0, "buffers": 0} for cat in _CENSUS_WALKERS}
    out = {"total_bytes": 0, "buffers": 0, "by_category": by_cat,
           "unattributed_bytes": 0, "unattributed_buffers": 0}
    if jax_mod is None:
        return out
    owned: Dict[int, str] = {}
    for cat, walker in _CENSUS_WALKERS.items():
        try:
            for obj in walker():
                for arr in _iter_device_arrays(obj, jax_mod):
                    owned.setdefault(id(arr), cat)
        except Exception:
            continue
    try:
        live = jax_mod.live_arrays()
    except Exception:
        return out
    for arr in live:
        try:
            nbytes = int(arr.nbytes)
        except Exception:
            continue
        out["total_bytes"] += nbytes
        out["buffers"] += 1
        cat = owned.get(id(arr))
        if cat is None:
            out["unattributed_bytes"] += nbytes
            out["unattributed_buffers"] += 1
        else:
            by_cat[cat]["bytes"] += nbytes
            by_cat[cat]["buffers"] += 1
    return out


def _hbm_total_fast() -> float:
    """Total live device bytes for per-tick statement attribution —
    skips the owner walk (the census classifies; the tick only needs
    the high-water number), and free when jax never loaded."""
    jax_mod = _jax_if_loaded()
    if jax_mod is None:
        return 0.0
    try:
        return float(sum(int(a.nbytes) for a in jax_mod.live_arrays()))
    except Exception:
        return 0.0


def hbm_limit_bytes() -> float:
    """The backend's device-memory capacity when the runtime exposes it
    (TPU/GPU ``memory_stats()['bytes_limit']``; 0 on CPU and older
    runtimes) — the hbm-pressure rule's denominator."""
    jax_mod = _jax_if_loaded()
    if jax_mod is None:
        return 0.0
    try:
        stats = jax_mod.devices()[0].memory_stats() or {}
        return float(stats.get("bytes_limit", 0) or 0)
    except Exception:
        return 0.0


def measured_row_bytes(table_id: int, default: int,
                       storage=None) -> int:
    """Measured per-row working-set width of a table, census-derived:
    the replica's device-memoized column bytes (falling back to its
    host column bytes before any device upload) divided by row count.
    ``default`` (the old nominal constant) applies when no replica
    exists — so the spill gates price rows from measured truth whenever
    there is any, and never regress when there is none.  ``storage``
    scopes the lookup to ONE storage's replica store (the statement's
    own); without it every live store is consulted — fine in a server
    process, ambiguous when several storages share table ids (tests)."""
    jax_mod = _jax_if_loaded()
    try:
        from ..columnar import store as colstore
        if storage is not None:
            stores = [colstore.store_of(storage)]
        else:
            stores = colstore.live_stores()
        for s in stores:
            tbl = s.get(table_id)
            if tbl is None or not tbl.n_rows:
                continue
            dev = 0
            if jax_mod is not None:
                for arr in _iter_device_arrays(list(tbl.cache.values()),
                                               jax_mod):
                    dev += int(arr.nbytes)
            if dev <= 0:
                for v, m in tbl.columns.values():
                    dev += int(v.nbytes) + int(m.nbytes)
                if tbl.handles is not None:
                    dev += int(tbl.handles.nbytes)
            if dev > 0:
                return max(1, dev // tbl.n_rows)
    except Exception:
        pass
    return int(default)


# ---- reconciliation: tracked vs measured ----------------------------------

def _rss_bytes() -> float:
    """Resident set from /proc/self/statm (0 where proc is absent)."""
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        return float(pages * os.sysconf("SC_PAGE_SIZE"))
    except Exception:
        return 0.0


def tracked_bytes() -> float:
    """The ledger: live statement MemTracker bytes summed over the
    interrupt session registry (the processlist number)."""
    from ..utils import interrupt
    total = 0
    for _cid, sess in interrupt.sessions():
        mt = getattr(sess, "_stmt_mem", None)
        if mt is not None and getattr(sess, "stmt_running", False):
            total += mt.consumed
    return float(total)


def memory_state() -> Dict[str, float]:
    """The ``memory_state`` time-series source: tracked-ledger bytes vs
    measured heap (tracemalloc) / RSS vs the HBM census, plus the
    sampler's self-accounting — everything the heap-growth /
    hbm-pressure / mem-untracked inspection rules judge."""
    if tracemalloc.is_tracing():
        cur, peak = tracemalloc.get_traced_memory()
    else:
        cur, peak = 0, 0
    tracked = tracked_bytes()
    census = hbm_census()
    s = PROF.stats_snapshot()
    return {
        "tinysql_mem_tracked_bytes": tracked,
        "tinysql_mem_traced_bytes": float(cur),
        "tinysql_mem_traced_peak_bytes": float(peak),
        "tinysql_mem_rss_bytes": _rss_bytes(),
        "tinysql_mem_untracked_bytes": max(0.0, float(cur) - tracked),
        "tinysql_hbm_live_bytes": float(census["total_bytes"]),
        "tinysql_hbm_buffers": float(census["buffers"]),
        "tinysql_hbm_unattributed_bytes":
            float(census["unattributed_bytes"]),
        "tinysql_hbm_limit_bytes": hbm_limit_bytes(),
        "tinysql_memprof_ticks_total": s.get("ticks", 0),
        "tinysql_memprof_sites_total": s.get("sites", 0),
        "tinysql_memprof_attributed_total": s.get("attributed", 0),
        "tinysql_memprof_self_seconds_total": s.get("self_s", 0.0),
        "tinysql_memprof_evicted_total": s.get("evicted", 0),
        "tinysql_memprof_errors_total": s.get("errors", 0),
        "tinysql_memprof_backoff": s.get("backoff", 1),
    }


#: information_schema.memory_usage column order — MUST match
#: memory_usage_rows (catalog/memtables.py builds FieldTypes from this)
MEMORY_USAGE_COLUMNS = [
    ("source", "str"), ("item", "str"), ("bytes", "int"),
    ("detail", "str"),
]


def memory_usage_rows() -> List[list]:
    """The ``memory_usage`` mem-table payload: one row per ledger /
    measurement / census bucket, reconciliation last — so ``SELECT *
    FROM information_schema.memory_usage`` answers "where is the
    memory, and does the ledger agree" in one scan."""
    if tracemalloc.is_tracing():
        cur, peak = tracemalloc.get_traced_memory()
    else:
        cur, peak = 0, 0
    tracked = int(tracked_bytes())
    census = hbm_census()
    rows: List[list] = [
        ["tracked", "statements", tracked,
         "sum of live statement MemTracker bytes (the ledger; "
         "processlist mem_bytes)"],
        ["measured", "traced_heap", int(cur),
         "tracemalloc current traced bytes (python allocations only — "
         "XLA's C++ arena is invisible here)"],
        ["measured", "traced_peak", int(peak),
         "tracemalloc peak traced bytes since tracing started"],
        ["measured", "rss", int(_rss_bytes()),
         "resident set size (/proc/self/statm)"],
    ]
    for cat in sorted(census["by_category"]):
        c = census["by_category"][cat]
        rows.append(["hbm", cat, int(c["bytes"]),
                     f"{c['buffers']} live device buffer(s)"])
    rows.append(["hbm", "unattributed",
                 int(census["unattributed_bytes"]),
                 f"{census['unattributed_buffers']} live device "
                 "buffer(s) no registered owner claims — the leak "
                 "bucket"])
    rows.append(["recon", "untracked", max(0, int(cur) - tracked),
                 "traced heap beyond the MemTracker ledger; the "
                 f"mem-untracked rule fires past a {UNTRACKED_BAND_BYTES >> 20}"
                 " MiB windowed band"])
    return rows


# ---- per-query probe (bench detail) ---------------------------------------

class QueryMemProbe:
    """Bracket one query with measured memory detail (bench.py's
    per-query ``peak_heap_kb`` / ``peak_hbm_bytes`` /
    ``mem_untracked_frac``).  Uses tracemalloc's resettable peak where
    available, so the probe measures THIS query's heap high water, not
    the process's history.  All writes stay inside this module
    (qlint OB407)."""

    def __init__(self):
        self._started = False
        self._base_kb = 0.0

    def start(self) -> None:
        if not tracemalloc.is_tracing():
            tracemalloc.start(MAX_SITE_DEPTH)
            self._started = True
        try:
            tracemalloc.reset_peak()
        except AttributeError:
            pass
        self._base_kb = tracemalloc.get_traced_memory()[0] / 1024.0

    def finish(self, tracked_peak_bytes: int = 0) -> Dict[str, float]:
        cur, peak = tracemalloc.get_traced_memory()
        peak_kb = max(0.0, peak / 1024.0 - self._base_kb)
        alloc_bytes = peak_kb * 1024.0
        untracked = max(0.0, alloc_bytes - float(tracked_peak_bytes))
        out = {
            "peak_heap_kb": round(peak_kb, 1),
            "peak_hbm_bytes": _hbm_total_fast(),
            "mem_untracked_frac":
                round(untracked / alloc_bytes, 4) if alloc_bytes > 0
                else 0.0,
        }
        if self._started:
            tracemalloc.stop()
            self._started = False
        return out


# ---- the background sampler (server lifecycle) ---------------------------

class MemprofSampler:
    """Background thread pacing ``PROF.sample_once`` by the GLOBAL
    ``tidb_memprof_rate`` sysvar (Hz; re-read every tick like the
    conprof/tsring samplers — 0 pauses sampling at the cost of ONE
    sysvar read per idle slice).  Starts tracemalloc on first demand
    and stops it again when the rate drops to 0 (tracing taxes every
    allocation in the process, so off must mean OFF).  The effective
    period is ``backoff / rate``: the profiler's own overhead control
    stretches it when a snapshot costs too much."""

    def __init__(self, storage, profiler: Optional[HeapProfiler] = None):
        self.storage = storage
        self.profiler = profiler if profiler is not None else PROF
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._started_tracing = False
        #: start/close lifecycle lock (the tsring Sampler discipline)
        self._mu = threading.Lock()

    def _int_sysvar(self, name: str, default: int) -> int:
        from ..server.pool import read_global_int
        return read_global_int(self.storage, name, default)

    def rate_hz(self) -> int:
        return self._int_sysvar("tidb_memprof_rate", DEFAULT_RATE_HZ)

    def start(self) -> None:
        with self._mu:
            if self._thread is not None:
                return
            self._stop.clear()  # restartable after close()
            self._thread = threading.Thread(target=self._loop,
                                            daemon=True,
                                            name="memprof-sampler")
            self._thread.start()

    def close(self) -> None:
        with self._mu:
            self._stop.set()
            t = self._thread
        if t is not None:
            t.join(timeout=5.0)
        with self._mu:
            if self._thread is t:
                self._thread = None
        self._stop_tracing()

    def _stop_tracing(self) -> None:
        with self._mu:
            started, self._started_tracing = self._started_tracing, False
        if started and tracemalloc.is_tracing():
            tracemalloc.stop()
            # baseline is gone with the traces: the next tick must not
            # read a restart as a huge negative (or positive) delta
            self.profiler._last_traced_kb = None

    def _loop(self) -> None:
        elapsed = 0.0
        while True:
            rate = self.rate_hz()
            if rate <= 0:
                # disabled: ONE sysvar read per slice, nothing else —
                # and no tracemalloc tax on the allocator
                self._stop_tracing()
                if self._stop.wait(0.25):
                    return
                elapsed = 0.0
                continue
            if not tracemalloc.is_tracing():
                tracemalloc.start(MAX_SITE_DEPTH)
                with self._mu:
                    self._started_tracing = True
            rate = min(rate, MAX_RATE_HZ)
            period = self.profiler.backoff / rate
            slice_s = min(period, 0.25)
            if self._stop.wait(slice_s):
                return
            elapsed += slice_s
            if elapsed + 1e-9 < period:
                continue
            elapsed = 0.0
            try:
                self.profiler.sample_once(
                    period,
                    window_s=self._int_sysvar("tidb_memprof_window",
                                              DEFAULT_WINDOW_S),
                    history=self._int_sysvar("tidb_memprof_history",
                                             DEFAULT_HISTORY),
                    max_sites=self._int_sysvar("tidb_memprof_max_sites",
                                               DEFAULT_MAX_SITES),
                    skip_idents=(threading.get_ident(),))
            except Exception:
                # a torn snapshot (or an armed memprofSampleError) must
                # never kill the sampler thread — counted, logged, the
                # next tick runs clean
                self.profiler.note_error()
                import logging
                logging.getLogger("tinysql_tpu.memprof").warning(
                    "memprof sample failed", exc_info=True)
