"""Continuous host-CPU profiler: the host half of the truth story
(reference lineage: TiDB Dashboard's continuous profiling + TopSQL's
statement CPU attribution — an always-on, low-overhead profiler treated
as a first-class subsystem, not a tool someone attaches after the
incident).

ISSUE 11 made *device* time measured truth; every host-side number was
still a wall clock around who-knows-what.  This module owns the
host-side answer: a background sampler thread walks
``sys._current_frames()`` at ``tidb_conprof_rate`` Hz (0 = off, re-read
live like the tsring sampler), classifies each thread by its serving
ROLE (the thread-name vocabulary below — pool workers, conn threads,
the accept loop, devpipe producers, the tsring/prewarm/distsql workers),
folds each stack into bounded per-window aggregates with
retention/rotation semantics matching obs/stmtsummary.py (window
rotation into bounded history; over-cap stacks evict into a single
``(evicted)`` tombstone that keeps counting), and attributes samples
landing on a thread that is currently EXECUTING a statement (resolved
through the interrupt session registry) to that statement's QueryObs —
so ``statements_summary`` gains ``sum_cpu_ms`` / ``cpu_samples``
columns and a latency regression can be split into "the CPU went here"
straight from SQL.

Serving surfaces (all computed from this module's state):

- ``information_schema.continuous_profiling`` (catalog/memtables.py):
  one row per (window, role, folded stack) with sample counts and
  estimated cpu_ms;
- ``/debug/conprof?window=N`` (server/http_status.py): collapsed-stack
  text (``role;frame;frame... count`` per line) that flamegraph.pl and
  speedscope ingest directly;
- ``tinysql_conprof_*`` metrics in the central registry and the
  time-series ring (the ``conprof`` source in obs/tsring.py);
- two inspection rules (obs/inspect.py): ``cpu-saturation`` (one role
  window-dominant in busy samples while the admission queue is
  non-empty) and ``profiler-overhead`` (the sampler's own cost ran past
  its budget — the rule reports it AND the sampler backs off its rate
  via the ``backoff`` divisor below).

Semantics and honesty notes:

- "cpu_ms" is SAMPLE-ESTIMATED on-thread milliseconds (samples x the
  effective sampling period), not an OS scheduler reading — the same
  estimate flamegraphs are built from.  Samples whose leaf frame is a
  known blocking primitive (``wait``/``select``/``accept``/...) are
  counted separately as IDLE: they appear in the folded stacks (a
  thread parked in a lock is diagnostic gold) but stay out of busy-CPU
  shares and the cpu-saturation rule.  Caveat: a thread blocked in a C
  BUILTIN called directly (raw ``time.sleep``, a bare ``sock.recv``)
  has no Python wrapper frame, so its caller reads as the leaf and the
  sample counts busy — the engine's own threads all park through
  ``threading``/wire wrappers that classify idle, and qlint FP501
  already bans raw ``time.sleep`` in retry paths.
- Statement attribution counts only samples on the statement's OWN
  executing thread (session.stmt_thread_ident), never its helper
  threads (devpipe producer, distsql workers) — so the invariant
  ``sum_cpu_ms <= exec wall`` holds per statement; each attribution
  increment is additionally capped by the statement's elapsed wall so
  period quantization cannot break it.
- The sampler's self-cost is measured every tick; when its EWMA runs
  past ``OVERHEAD_BUDGET_FRAC`` of one core the ``backoff`` divisor
  doubles (halving the effective rate) until the cost fits — the
  profiler may get coarser under load, never expensive.

WRITE DISCIPLINE (qlint OB406): the fold/attribution state here — and
the statement cpu counters (``cpu_s`` / ``cpu_samples``) — are written
ONLY from this module.  Any other writer would publish un-sampled wall
time as CPU truth or corrupt the window accounting.
"""
from __future__ import annotations

import sys
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

DEFAULT_RATE_HZ = 10
DEFAULT_WINDOW_S = 60
DEFAULT_HISTORY = 15
DEFAULT_MAX_STACKS = 512

#: ceiling on the applied rate regardless of the sysvar: beyond this a
#: pure-Python frame walk is all overhead, no additional signal
MAX_RATE_HZ = 250

#: frames kept per folded stack (leaf-most win; the role prefix keeps
#: the root context)
MAX_STACK_DEPTH = 48

#: the sampler's self-cost budget as a fraction of one core; past it
#: the backoff divisor doubles (profiler-overhead rule evidence)
OVERHEAD_BUDGET_FRAC = 0.03
BACKOFF_MAX = 16

EVICTED_STACK = "(evicted)"

# ---- the thread-role vocabulary -------------------------------------------
# THE shared naming contract (the PR 13 thread-name sweep): every thread
# the engine spawns carries one of these stable ``name=`` prefixes, so
# conprof role classification, race-stress contention reports, and
# py-spy output all read the same words.  tests/test_conprof.py asserts
# live threads classify off this table; the thread-root coverage test
# (tests/test_lint.py) pins the spawn sites themselves.

ROLE_PREFIXES: Tuple[Tuple[str, str], ...] = (
    ("stmt-pool-", "pool-worker"),      # server/pool.py workers
    ("conn-", "conn"),                  # server/server.py per-connection
    ("mysql-accept", "accept"),         # server/server.py accept loop
    ("aio-loop-", "aio"),               # server/aio.py event loops
    ("devpipe-stage", "devpipe"),       # executor/devpipe.py producer
    ("metrics-sampler", "tsring"),      # obs/tsring.py Sampler
    ("conprof-sampler", "conprof"),     # this module's own sampler
    ("memprof-sampler", "memprof"),     # obs/memprof.py heap sampler
    ("flight-writer", "flight"),        # obs/flight.py segment writer
    ("auto-prewarm", "prewarm"),        # session/prewarm.py worker
    ("distsql-cop", "distsql"),         # distsql/client.py task pool
    ("status-http", "http"),            # server/http_status.py
    ("domain-reload-", "domain"),       # domain/domain.py ticker
    ("ddl-owner-", "ddl"),              # domain/domain.py owner loop
    ("range-", "kv"),                   # kv/range_task.py pools
    ("kv-", "kv"),                      # kv commit / lookup / schema pools
    ("MainThread", "main"),
)

#: the closed role set (per-role busy-sample counters are registered
#: metrics, so the catalogue must be finite and known to obs/metrics.py)
ROLES: Tuple[str, ...] = tuple(sorted(
    {role for _, role in ROLE_PREFIXES} | {"other"}))


def classify(thread_name: str) -> str:
    """Thread name -> serving role (``other`` for anything outside the
    vocabulary, e.g. http handler threads or test harness threads)."""
    for prefix, role in ROLE_PREFIXES:
        if thread_name.startswith(prefix):
            return role
    return "other"


def role_metric(role: str) -> str:
    """The registered per-role busy-sample counter name."""
    return f"tinysql_conprof_{role.replace('-', '_')}_busy_samples_total"


# ---- stack folding --------------------------------------------------------

#: leaf function names that mean "parked, not computing" — the sample
#: still folds (a thread stuck in a lock is diagnostic gold) but counts
#: as idle, outside busy-CPU shares and the cpu-saturation rule
_IDLE_LEAVES = frozenset((
    "wait", "wait_for_tstate_lock", "acquire", "select", "poll", "epoll",
    "accept", "recv", "recv_into", "recvfrom", "read", "readinto",
    "sleep", "get", "put", "join", "getaddrinfo", "settimeout",
    "_recv_bytes", "do_wait", "block_until_ready",
    # the wire layer's blocking-socket wrappers: a thread whose leaf is
    # one of these sits in sock.recv/sendall (C frames are invisible to
    # sys._current_frames, so the WRAPPER is the leaf we see)
    "_read_exact", "read_packet", "sendall", "_accept_loop",
))

#: stdlib files whose leaf frames are treated as parked even when the
#: function name is project-like
_IDLE_FILES = ("threading.py", "selectors.py", "socket.py", "queue.py",
               "ssl.py")


def fold_stack(frame, max_depth: int = MAX_STACK_DEPTH) -> Tuple[str, bool]:
    """(folded stack root->leaf joined with ';', is_idle).  Frame labels
    are ``module.function`` (file basename, extension stripped) — stable
    across runs, compact enough to keep per-window aggregates small."""
    parts: List[str] = []
    idle = False
    f = frame
    first = True
    while f is not None and len(parts) < max_depth:
        code = f.f_code
        fname = code.co_filename
        base = fname.rsplit("/", 1)[-1]
        if first:
            leaf_file = base
            idle = (code.co_name in _IDLE_LEAVES
                    or leaf_file in _IDLE_FILES)
            first = False
        parts.append(f"{base[:-3] if base.endswith('.py') else base}"
                     f".{code.co_name}")
        f = f.f_back
    parts.reverse()
    return ";".join(parts), idle


def parse_collapsed(text: str) -> Dict[str, int]:
    """Inverse of :func:`Profiler.collapsed` — ``{stack: count}``.  The
    format round-trip test and any offline tooling share this parser
    (it is the exact contract flamegraph.pl consumes: everything up to
    the last space is the stack, the tail is the count)."""
    out: Dict[str, int] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        stack, _, count = line.rpartition(" ")
        out[stack] = out.get(stack, 0) + int(count)
    return out


# ---- the windowed aggregate store -----------------------------------------

class _StackAgg:
    __slots__ = ("samples", "idle_samples", "cpu_s", "last_seen")

    def __init__(self):
        self.samples = 0
        self.idle_samples = 0
        self.cpu_s = 0.0
        self.last_seen = 0.0

    def merge(self, other: "_StackAgg") -> None:
        self.samples += other.samples
        self.idle_samples += other.idle_samples
        self.cpu_s += other.cpu_s
        self.last_seen = max(self.last_seen, other.last_seen)


#: information_schema.continuous_profiling column order — MUST match
#: Profiler.rows
COLUMNS = [
    ("window_begin", "str"), ("role", "str"), ("folded_stack", "str"),
    ("samples", "int"), ("idle_samples", "int"), ("cpu_ms", "real"),
]


class Profiler:
    """The fold/attribution store: current window + bounded rotated
    history, stmtsummary-style.  Written from the sampler thread; read
    from any session scanning ``continuous_profiling`` or hitting
    ``/debug/conprof`` — all paths take the lock."""

    def __init__(self, window_s: float = DEFAULT_WINDOW_S,
                 history: int = DEFAULT_HISTORY,
                 max_stacks: int = DEFAULT_MAX_STACKS):
        self.window_s = float(window_s)
        self.max_history = int(history)
        self.max_stacks = int(max_stacks)
        self._mu = threading.Lock()
        #: (role, folded stack) -> aggregate, current window
        self._entries: Dict[Tuple[str, str], _StackAgg] = {}
        #: anchored by the FIRST fold, like stmtsummary's window_begin
        self.window_begin: Optional[float] = None
        #: rotated windows, oldest first: (window_begin, {key: agg})
        self.history: deque = deque()
        #: adaptive rate divisor (profiler-overhead backoff): the
        #: effective sampling period is backoff / tidb_conprof_rate
        self.backoff = 1
        self._cost_ewma = 0.0
        self._stats = {"ticks": 0, "samples": 0, "idle_samples": 0,
                       "attributed": 0, "self_s": 0.0, "evicted": 0}
        #: process-cumulative busy samples per role (ring source feed)
        self._role_busy: Dict[str, int] = {r: 0 for r in ROLES}

    # ---- the designated write path (sampler thread ONLY) ----------------
    def sample_once(self, period_s: float, now: Optional[float] = None,
                    frames: Optional[Dict[int, object]] = None,
                    window_s: Optional[float] = None,
                    history: Optional[int] = None,
                    max_stacks: Optional[int] = None,
                    skip_idents: Tuple[int, ...] = (),
                    attribute: bool = True) -> int:
        """One sampling tick: walk every live thread's frame, fold, and
        attribute.  ``now``/``frames`` are injectable for deterministic
        tests; the ``window_s``/``history``/``max_stacks`` overrides
        carry the live sysvars.  ``attribute=False`` folds only — the
        overhead probe must never write statement CPU (its ticks are
        back-to-back, not period-spaced, so attributing them would
        fabricate un-sampled CPU time).  Returns the number of threads
        sampled."""
        t0 = time.perf_counter()
        if now is None:
            now = time.time()
        if frames is None:
            frames = sys._current_frames()
        names = {t.ident: t.name for t in threading.enumerate()}
        att = self._statement_threads() if attribute else {}
        n = 0
        for tid, frame in frames.items():
            if tid in skip_idents:
                continue
            folded, idle = fold_stack(frame)
            if not folded:
                continue
            role = classify(names.get(tid, ""))
            self._fold(role, folded, idle, period_s, now,
                       window_s=window_s, history=history,
                       max_stacks=max_stacks)
            n += 1
            # attribution counts every on-thread sample (blocked time
            # is still the statement's wall); the busy/idle split only
            # matters for role shares
            qobs = att.get(tid)
            if qobs is not None:
                self._attribute(qobs, period_s, now)
        wall = time.perf_counter() - t0
        with self._mu:
            self._stats["ticks"] += 1
            self._stats["self_s"] += wall
        self._note_cost(wall, period_s)
        return n

    @staticmethod
    def _statement_threads() -> Dict[int, object]:
        """ident -> QueryObs of the statement currently EXECUTING on
        that thread, resolved through the interrupt session registry
        (``interrupt.executing_threads`` — the processlist feed).
        Helper threads a statement spawns are deliberately absent —
        per-statement cpu must stay <= wall."""
        from ..utils import interrupt
        out: Dict[int, object] = {}
        for tid, sess in interrupt.executing_threads().items():
            qobs = getattr(sess, "last_query_stats", None)
            if qobs is not None:
                out[tid] = qobs
        return out

    def _fold(self, role: str, folded: str, idle: bool, period_s: float,
              now: float, window_s=None, history=None,
              max_stacks=None) -> None:
        with self._mu:
            if window_s is not None:
                self.window_s = float(window_s)
            if history is not None:
                self.max_history = int(history)
            if max_stacks is not None:
                self.max_stacks = int(max_stacks)
            if self.window_begin is None:
                self.window_begin = now
            elif self.window_s > 0 \
                    and now - self.window_begin >= self.window_s:
                self._rotate(now)
            key = (role, folded)
            agg = self._entries.get(key)
            if agg is None:
                if self.max_stacks > 0:
                    # _evict_one reports progress: once only tombstones
                    # remain there is nothing left to fold away, and
                    # looping on an unchanged length would spin forever
                    # under the lock (wedging the sampler AND every
                    # reader) — e.g. max_stacks=1 with one tombstone
                    while len(self._entries) >= self.max_stacks:
                        if not self._evict_one():
                            break
                agg = self._entries[key] = _StackAgg()
            agg.samples += 1
            agg.last_seen = now
            self._stats["samples"] += 1
            if idle:
                agg.idle_samples += 1
                self._stats["idle_samples"] += 1
            else:
                agg.cpu_s += period_s
                self._role_busy[role] = self._role_busy.get(role, 0) + 1

    def _attribute(self, qobs, period_s: float, now: float) -> None:
        """Fold one sample into the running statement's scope.  The
        increment is capped by the statement's elapsed wall so the
        quantized estimate can never exceed it (the cpu_ms <= exec wall
        invariant, tested)."""
        try:
            elapsed = max(0.0, now - qobs.started_at)
            cur = float(qobs.device_totals().get("cpu_s", 0.0))
            inc = min(period_s, elapsed - cur)
            if inc > 0:
                qobs.add_counter("cpu_s", inc)
            qobs.add_counter("cpu_samples", 1)
            with self._mu:
                self._stats["attributed"] += 1
        except Exception:
            # a statement finishing mid-attribution must never kill the
            # sampler tick
            pass

    def _rotate(self, now: float) -> None:
        # caller holds the lock
        if self._entries:
            self.history.append((self.window_begin, self._entries))
            while len(self.history) > max(self.max_history, 0):
                self.history.popleft()
        self._entries = {}
        self.window_begin = now

    def _evict_one(self) -> bool:
        # caller holds the lock: least-recently-seen stack folds into
        # its role's tombstone so window sample totals stay accountable
        # (the stmtsummary eviction discipline).  Returns False when no
        # evictable (non-tombstone) entry remains — the caller must
        # stop, not spin.  An eviction that CREATES the tombstone frees
        # no slot either, so that also reports no progress.
        victims = [k for k in self._entries if k[1] != EVICTED_STACK]
        if not victims:
            return False
        vkey = min(victims, key=lambda k: self._entries[k].last_seen)
        victim = self._entries.pop(vkey)
        tkey = (vkey[0], EVICTED_STACK)
        tomb = self._entries.get(tkey)
        created = tomb is None
        if created:
            tomb = self._entries[tkey] = _StackAgg()
        tomb.merge(victim)
        self._stats["evicted"] += 1
        return not created

    def _note_cost(self, tick_wall_s: float, period_s: float) -> None:
        """Adaptive overhead control: EWMA the per-tick self cost; when
        it runs past the budget share of one core the backoff divisor
        doubles (the sampler thread halves its rate next tick).  Steps
        back down only when a halved backoff would still sit well under
        budget (hysteresis — no flapping at the boundary)."""
        with self._mu:
            self._cost_ewma = tick_wall_s if self._cost_ewma == 0.0 \
                else 0.8 * self._cost_ewma + 0.2 * tick_wall_s
            cost_frac = self._cost_ewma / max(period_s, 1e-9)
            if cost_frac > OVERHEAD_BUDGET_FRAC \
                    and self.backoff < BACKOFF_MAX:
                self.backoff *= 2
            elif self.backoff > 1 \
                    and cost_frac * 2 < 0.5 * OVERHEAD_BUDGET_FRAC:
                self.backoff //= 2

    # ---- reads -----------------------------------------------------------
    def _maybe_rotate_stale(self, now: Optional[float]) -> None:
        # caller holds the lock (stmtsummary read-side rotation: a
        # long-expired window must not present as current)
        if now is None:
            now = time.time()
        if self.window_begin is not None and self.window_s > 0 \
                and now - self.window_begin >= self.window_s:
            self._rotate(now)

    def rows(self, now: Optional[float] = None) -> List[list]:
        """``continuous_profiling`` payload: retained windows oldest
        first, current window last, stacks ordered by samples desc
        within each window."""
        from .stmtsummary import _ts
        with self._mu:
            self._maybe_rotate_stale(now)
            windows = list(self.history)
            if self._entries:
                windows.append((self.window_begin, self._entries))
            out: List[list] = []
            for begin, entries in windows:
                stamp = _ts(begin)
                for (role, folded), agg in sorted(
                        entries.items(),
                        key=lambda kv: -kv[1].samples):
                    out.append([stamp, role, folded, agg.samples,
                                agg.idle_samples,
                                round(agg.cpu_s * 1e3, 3)])
            return out

    def collapsed(self, window_s: Optional[float] = None,
                  now: Optional[float] = None) -> str:
        """The /debug/conprof payload: flamegraph.pl / speedscope
        collapsed-stack text, one ``role;frame;... count`` line per
        distinct (role, stack), merged across every retained window
        whose begin falls inside the last ``window_s`` seconds (None or
        0 = everything retained)."""
        if now is None:
            now = time.time()
        horizon = now - window_s if window_s else None
        merged: Dict[str, int] = {}
        with self._mu:
            self._maybe_rotate_stale(now)
            windows = list(self.history)
            if self._entries:
                windows.append((self.window_begin, self._entries))
            for begin, entries in windows:
                if horizon is not None and begin < horizon:
                    continue
                for (role, folded), agg in entries.items():
                    line = f"{role};{folded}"
                    merged[line] = merged.get(line, 0) + agg.samples
        return "\n".join(f"{stack} {count}"
                         for stack, count in sorted(merged.items()))

    def stats_snapshot(self) -> Dict[str, float]:
        with self._mu:
            out = dict(self._stats)
            out["backoff"] = self.backoff
            out["stacks"] = len(self._entries)
            out["windows"] = len(self.history) + (
                1 if self._entries else 0)
            out["role_busy"] = dict(self._role_busy)
            return out

    def reset(self) -> None:
        """Tests only."""
        with self._mu:
            self._entries = {}
            self.history.clear()
            self.window_begin = None
            self.backoff = 1
            self._cost_ewma = 0.0
            self._stats = {"ticks": 0, "samples": 0, "idle_samples": 0,
                           "attributed": 0, "self_s": 0.0, "evicted": 0}
            self._role_busy = {r: 0 for r in ROLES}


#: the process-global profiler every surface reads
PROF = Profiler()


def rows() -> List[list]:
    return PROF.rows()


def collapsed(window_s: Optional[float] = None) -> str:
    return PROF.collapsed(window_s=window_s)


def stats_snapshot() -> Dict[str, float]:
    return PROF.stats_snapshot()


def reset() -> None:
    """Tests only."""
    PROF.reset()


def measure_overhead(n: int = 50,
                     rate_hz: int = DEFAULT_RATE_HZ) -> Dict[str, float]:
    """The profiler's steady-state cost, THE definition both benches
    publish as ``conprof_overhead_frac`` when no live sampler ran: one
    tick's wall (averaged over ``n`` live frame walks against THIS
    process) times the ticks-per-second at ``rate_hz``.  Probes a
    PRIVATE Profiler so the measurement never pollutes the live store.
    """
    prof = Profiler()
    period = 1.0 / max(rate_hz, 1)
    # attribute=False: the probe's ticks are back-to-back, and a live
    # statement in this process must not collect fabricated CPU time
    prof.sample_once(period, attribute=False)  # warm lazy imports
    t0 = time.perf_counter()
    for _ in range(n):
        prof.sample_once(period, attribute=False)
    per_tick_s = (time.perf_counter() - t0) / n
    return {"tick_wall_s": round(per_tick_s, 6), "rate_hz": rate_hz,
            "conprof_overhead_frac": round(per_tick_s * rate_hz, 6)}


def live_overhead_frac(stats_before: Dict[str, float],
                       stats_after: Dict[str, float],
                       wall_s: float) -> float:
    """Sampler self-cost over a measured live window: the delta of the
    profiler's own accumulated tick wall divided by the elapsed wall —
    what bench_serve.py hard-gates against the 3% budget."""
    d = float(stats_after.get("self_s", 0.0)) \
        - float(stats_before.get("self_s", 0.0))
    return round(d / max(wall_s, 1e-9), 6)


# ---- the background sampler (server lifecycle) ---------------------------

class ConprofSampler:
    """Background thread pacing ``PROF.sample_once`` by the GLOBAL
    ``tidb_conprof_rate`` sysvar (Hz; re-read every tick like the
    tsring sampler — 0 pauses sampling without stopping the thread).
    The effective period is ``backoff / rate``: the profiler's own
    overhead control stretches it when a tick costs too much."""

    def __init__(self, storage, profiler: Optional[Profiler] = None):
        self.storage = storage
        self.profiler = profiler if profiler is not None else PROF
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        #: start/close lifecycle lock (the tsring Sampler discipline):
        #: two racing start() calls must not leak a second sampler
        self._mu = threading.Lock()

    def _int_sysvar(self, name: str, default: int) -> int:
        from ..server.pool import read_global_int
        return read_global_int(self.storage, name, default)

    def rate_hz(self) -> int:
        return self._int_sysvar("tidb_conprof_rate", DEFAULT_RATE_HZ)

    def start(self) -> None:
        with self._mu:
            if self._thread is not None:
                return
            self._stop.clear()  # restartable after close()
            self._thread = threading.Thread(target=self._loop,
                                            daemon=True,
                                            name="conprof-sampler")
            self._thread.start()

    def close(self) -> None:
        # stop flag set atomically with the thread-slot read; the slot
        # clears only after the join (the tsring close() contract — an
        # interleaved start() must keep seeing the old thread)
        with self._mu:
            self._stop.set()
            t = self._thread
        if t is not None:
            t.join(timeout=5.0)
        with self._mu:
            if self._thread is t:
                self._thread = None

    def _loop(self) -> None:
        elapsed = 0.0
        while True:
            rate = self.rate_hz()
            if rate <= 0:
                # disabled: ONE sysvar read per slice, nothing else —
                # the query path never notices the profiler exists
                if self._stop.wait(0.25):
                    return
                elapsed = 0.0
                continue
            rate = min(rate, MAX_RATE_HZ)
            period = self.profiler.backoff / rate
            slice_s = min(period, 0.25)
            if self._stop.wait(slice_s):
                return
            elapsed += slice_s
            if elapsed + 1e-9 < period:
                continue
            elapsed = 0.0
            try:
                self.profiler.sample_once(
                    period,
                    window_s=self._int_sysvar("tidb_conprof_window",
                                              DEFAULT_WINDOW_S),
                    history=self._int_sysvar("tidb_conprof_history",
                                             DEFAULT_HISTORY),
                    max_stacks=self._int_sysvar("tidb_conprof_max_stacks",
                                                DEFAULT_MAX_STACKS),
                    skip_idents=(threading.get_ident(),))
            except Exception:
                # a torn frame walk must never kill the sampler thread
                import logging
                logging.getLogger("tinysql_tpu.conprof").warning(
                    "conprof sample failed", exc_info=True)
