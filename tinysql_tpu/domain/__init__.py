"""Domain layer: per-server schema cache + version registry + syncer
barrier (reference: domain/ + ddl/util/syncer.go)."""
from .domain import Domain, shared_domain, wait_schema_synced

__all__ = ["Domain", "shared_domain", "wait_schema_synced"]
