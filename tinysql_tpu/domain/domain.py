"""Per-SERVER schema-cache domain (reference: domain/domain.go —
`Domain.InfoSchema` :242 / `Reload` :264 / the lease-driven reload loop
:319) plus the in-proc schema-version registry that feeds the DDL
syncer barrier (reference: ddl/util/syncer.go — each server publishes
the schema version it has loaded; the DDL owner waits for every live
server to catch up before the next F1 state transition).

In the reference the registry and the watch channel live in etcd; this
in-process build keeps them on the shared storage object (SURVEY §2.6:
"host RPC + plain function calls replace gRPC in the single-process
teaching build") — same protocol, no sockets.  Each `Server` owns one
Domain; embedded sessions without a Domain keep the always-fresh lazy
reload and never enter the registry.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from ..catalog.infoschema import InfoSchema
from ..catalog.meta import Meta


def _registry_of(storage) -> Dict[str, "Domain"]:
    reg = getattr(storage, "_domain_registry", None)
    if reg is None:
        reg = storage._domain_registry = {}
    return reg


class Domain:
    def __init__(self, storage, server_id: Optional[str] = None,
                 lease_s: float = 0.0, background: bool = False):
        """lease_s=0: every info_schema() call re-checks the stored
        version (embedded default — always fresh).  lease_s>0: the cache
        is trusted for that long, like the reference's schema lease; pair
        with background=True to reload from a ticker thread the way
        domain.go:319 does."""
        self.storage = storage
        self.server_id = server_id or f"server-{id(self):x}"
        self.lease_s = lease_s
        self._is: Optional[InfoSchema] = None
        self._loaded_at = 0.0
        self._mu = threading.RLock()
        self._closed = threading.Event()
        self._ticker: Optional[threading.Thread] = None
        self._ddl = None
        _registry_of(storage)[self.server_id] = self
        self.reload()
        if background and lease_s > 0:
            self._ticker = threading.Thread(
                target=self._reload_loop, daemon=True,
                name=f"domain-reload-{self.server_id}")
            self._ticker.start()
            # owner duty loop (reference: ddl_worker.go:112 — the owner's
            # background worker drains jobs OTHER servers enqueued; without
            # it a non-owner's DDL would stall until the lease lapses)
            self._owner_loop = threading.Thread(
                target=self._ddl_owner_loop, daemon=True,
                name=f"ddl-owner-{self.server_id}")
            self._owner_loop.start()

    # ---- reference Domain.InfoSchema ------------------------------------
    def info_schema(self) -> InfoSchema:
        with self._mu:
            stale = (self._is is None
                     or time.monotonic() - self._loaded_at >= self.lease_s)
            if stale:
                self.reload()
            return self._is

    # ---- reference Domain.Reload ----------------------------------------
    def reload(self) -> None:
        with self._mu:
            txn = self.storage.begin()
            try:
                ver = Meta(txn).schema_version()
            finally:
                txn.rollback()
            if self._is is None or self._is.version != ver:
                self._is = InfoSchema.load(self.storage)
            self._loaded_at = time.monotonic()

    def loaded_version(self) -> int:
        with self._mu:
            return self._is.version if self._is is not None else -1

    def _reload_loop(self) -> None:
        while not self._closed.wait(self.lease_s / 2):
            try:
                self.reload()
            except Exception:
                pass  # storage being torn down; next tick retries

    def _ddl_owner_loop(self) -> None:
        while not self._closed.wait(max(self.lease_s, 0.02)):
            try:
                ddl = self.ddl()
                if ddl.owner.campaign():
                    ddl.worker.run_pending(owner=ddl.owner)
                    # the GC safepoint trigger rides the owner duty loop
                    # (reference: the gc worker leader): exactly one
                    # server per storage advances the safepoint, paced
                    # by storage.maybe_run_gc itself
                    self._maybe_gc()
            except Exception:
                pass

    def _maybe_gc(self) -> None:
        """Invoke mvcc GC when the GLOBAL ``tidb_gc_safepoint`` sysvar
        arms a retention window (seconds; 0 = disabled)."""
        run = getattr(self.storage, "maybe_run_gc", None)
        if run is None:
            return
        g = getattr(self.storage, "_global_vars", None) or {}
        retention = g.get("tidb_gc_safepoint", 0)
        if retention:
            run(retention)

    def ddl(self):
        """Per-server DDL facade whose owner manager campaigns under
        this server's identity (reference: ddl owned by the domain,
        domain.go:474 Init starts ddl with the owner manager)."""
        with self._mu:
            if self._ddl is None:
                # closed domains must not mint NEW facades: close() has
                # already taken its retire snapshot under this lock, so
                # a facade created now would campaign unretired and its
                # ownership could only lapse by TTL
                if self._closed.is_set():
                    raise RuntimeError(
                        f"domain {self.server_id} is closed")
                from ..ddl.ddl import DDL
                from ..ddl.owner import OwnerManager
                self._ddl = DDL(self.storage,
                                owner=OwnerManager(self.storage,
                                                   self.server_id))
            return self._ddl

    def close(self) -> None:
        # ordering closes the race with ddl(): _closed is set BEFORE the
        # locked snapshot, so any facade created earlier is visible here
        # and retired, and any ddl() still waiting on _mu sees _closed
        # and refuses to mint a facade that would campaign unretired
        self._closed.set()
        with self._mu:
            ddl = self._ddl
        if ddl is not None:
            # clean shutdown resigns DDL ownership (reference:
            # owner.Manager ResignOwner on server close) so surviving
            # servers take over immediately, not after the lease TTL
            try:
                ddl.owner.retire()
            except Exception:
                pass
        _registry_of(self.storage).pop(self.server_id, None)


def shared_domain(storage) -> "Domain":
    """The storage's always-fresh (lease 0) embedded domain — the default
    for sessions constructed without a per-server Domain.  Lease-0
    domains are exempt from the syncer barrier (they cannot serve stale
    schema) and share ONE owner identity so embedded DDL participates in
    the same election as server DDL."""
    d = getattr(storage, "_shared_domain", None)
    if d is None or d._closed.is_set():
        d = storage._shared_domain = Domain(storage, "embedded-shared",
                                            lease_s=0.0)
    return d


def wait_schema_synced(storage, version: int, timeout_s: float = 1.0,
                       poll_s: float = 0.002) -> bool:
    """The syncer barrier (reference: ddl/util/syncer.go
    OwnerCheckAllVersions): block until every registered live domain has
    loaded `version` or newer.  Times out like the reference does when a
    server lags past the lease — safe because the schema VALIDATOR
    (2PC commit-time version re-check) aborts any transaction that
    committed against a schema the DDL has since moved past."""
    deadline = time.monotonic() + timeout_s
    while True:
        domains = list(_registry_of(storage).values())
        # lease-0 domains re-check the stored version on EVERY access, so
        # they can never serve a stale schema — treat as always synced
        if all(d.loaded_version() >= version for d in domains
               if not d._closed.is_set() and d.lease_s > 0):
            return True
        if time.monotonic() >= deadline:
            return False
        time.sleep(poll_s)
