"""Columnar memory (reference: util/chunk)."""
from .column import Column, DeviceColumn
from .chunk import Chunk, INIT_CHUNK_SIZE, MAX_CHUNK_SIZE, new_chunk_like, chunk_from_rows
from .codec import encode_chunk, decode_chunk, encode_column, decode_column

__all__ = [
    "Column", "DeviceColumn", "Chunk", "INIT_CHUNK_SIZE", "MAX_CHUNK_SIZE",
    "new_chunk_like", "chunk_from_rows",
    "encode_chunk", "decode_chunk", "encode_column", "decode_column",
]
