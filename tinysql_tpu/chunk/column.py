"""Columnar vector: fixed-width numpy buffer + null bitmap.

Capability parity with reference util/chunk/column.go:28 (data buffer +
null bitmap + elem buf), redesigned TPU-first: the numeric families are
contiguous numpy int64/float64 arrays that marshal zero-copy-ish to
`jax.Array`; the null bitmap is a boolean mask (True = NULL) that becomes the
device-side validity mask.  Strings stay host-side (object array) — the
planner's device enforcer (planner/core/task.py) keeps them off TPU, mirroring
the north-star numeric-only gate.
"""
from __future__ import annotations

from typing import Iterable, List, Optional, Sequence

import numpy as np

from ..mytypes import EvalType, FieldType, Datum
from ..utils import memory as _memory

_INIT_CAP = 32


def _np_dtype(et: EvalType):
    if et is EvalType.INT:
        return np.int64
    if et is EvalType.REAL:
        return np.float64
    return object


class Column:
    """A growable typed vector with a null mask.

    Memory accounting is PAIRED: every buffer charge remembers the
    tracker it hit (utils/memory.py), and the charge is released when
    the buffers are freed (``__del__`` / :meth:`free` / ``truncate(0)``)
    — so a statement's tracker reports its LIVE working set, long-lived
    sessions don't monotonically over-report, and spill reloads net out
    instead of double-counting."""

    __slots__ = ("ft", "_data", "_null", "_len", "_tracker", "_charged")

    def __init__(self, ft: FieldType, cap: int = _INIT_CAP):
        self.ft = ft
        dt = _np_dtype(ft.eval_type)
        self._data = np.zeros(max(cap, 1), dtype=dt)
        self._null = np.zeros(max(cap, 1), dtype=bool)
        self._len = 0
        # per-query memory quota (utils/memory.py): charge the buffer
        # capacity; no-op without an active tracker
        self._tracker = None
        self._charged = 0
        self._charge(self._data.nbytes + self._null.nbytes)

    # ---- quota pairing ------------------------------------------------
    def _charge(self, n: int) -> None:
        if n <= 0:
            return
        if self._tracker is None:
            self._tracker = _memory.consume_tracked(n)
            if self._tracker is not None:
                self._charged = n
        else:
            # later growth charges the column's OWN tracker (the one it
            # was born under), keeping the charge/release pair balanced
            # even if the column outlives its statement
            self._tracker.consume(n)
            self._charged += n

    def _release_all(self) -> None:
        if self._tracker is not None and self._charged > 0:
            self._tracker.release(self._charged)
        self._charged = 0

    def _adopt_charge(self, other: "Column") -> None:
        """Take over ``other``'s charge (its buffers became ours): the
        lazily-materializing subclasses steal the freshly built column's
        arrays, so the release must move with them."""
        self._release_all()
        self._tracker = other._tracker
        self._charged = other._charged
        other._tracker = None
        other._charged = 0

    def free(self) -> None:
        """Drop the buffers and release their charge now (spill paths
        call this the moment a partition is written out, instead of
        waiting for GC)."""
        self._release_all()
        self._data = np.zeros(1, dtype=_np_dtype(self.ft.eval_type))
        self._null = np.zeros(1, dtype=bool)
        self._len = 0

    def __del__(self):
        try:
            self._release_all()
        except Exception:  # interpreter teardown: modules half-gone
            pass

    # ---- constructors -------------------------------------------------
    @classmethod
    def from_numpy(cls, ft: FieldType, data: np.ndarray,
                   null: Optional[np.ndarray] = None) -> "Column":
        c = cls(ft, cap=1)
        n = len(data)
        dt = _np_dtype(ft.eval_type)
        c._data = np.ascontiguousarray(data, dtype=dt)
        c._null = (np.zeros(n, dtype=bool) if null is None
                   else np.asarray(null, dtype=bool).copy())
        c._len = n
        # the cap-1 seed buffers were just replaced: re-pair the charge
        # against the real materialization
        c._release_all()
        c._tracker = None
        c._charge(c._data.nbytes + c._null.nbytes)
        return c

    @classmethod
    def wrap_raw(cls, ft: FieldType, data: np.ndarray,
                 null: Optional[np.ndarray] = None) -> "Column":
        """Zero-copy wrap: `data` is used as-is (any dtype, incl. <U string
        arrays) — the columnar-replica fast path's view constructor."""
        c = cls(ft, cap=1)
        c._data = data
        c._null = (null if null is not None
                   else np.zeros(len(data), dtype=bool))
        c._len = len(data)
        return c

    @classmethod
    def from_datums(cls, ft: FieldType, values: Iterable[Datum]) -> "Column":
        c = cls(ft)
        for v in values:
            c.append(v)
        return c

    # ---- size ---------------------------------------------------------
    def __len__(self) -> int:
        return self._len

    def _ensure_host(self) -> None:
        """Hook for lazily-materialized subclasses (DeviceColumn): a
        no-op here.  Every accessor that touches the host buffers calls
        it first, so device-resident columns stay on device until a host
        consumer actually reads them."""

    def _grow(self, need: int) -> None:
        self._ensure_host()
        cap = len(self._data)
        if self._len + need <= cap:
            return
        new_cap = max(cap * 2, self._len + need)
        self._charge((new_cap - cap)
                     * (self._data.itemsize + self._null.itemsize))
        self._data = np.resize(self._data, new_cap)
        self._null = np.resize(self._null, new_cap)

    # ---- append -------------------------------------------------------
    def append(self, v: Datum) -> None:
        self._grow(1)
        i = self._len
        if v is None:
            self._null[i] = True
            self._data[i] = 0 if self.ft.eval_type is not EvalType.STRING else ""
        else:
            self._null[i] = False
            if isinstance(v, int) and not (-(1 << 63) <= v < (1 << 63)):
                # unsigned values live in the int64 buffer two's-complement
                # wrapped (reference: column.go stores uint64 in the same buf)
                v = (v & ((1 << 64) - 1)) - (1 << 64) if v & (1 << 63) else v & ((1 << 64) - 1)
            self._data[i] = v
        self._len = i + 1

    def append_null(self) -> None:
        self.append(None)

    def extend(self, other: "Column", start: int = 0,
               end: Optional[int] = None) -> None:
        other._ensure_host()
        end = other._len if end is None else end
        n = end - start
        if n <= 0:
            return
        self._grow(n)
        self._data[self._len:self._len + n] = other._data[start:end]
        self._null[self._len:self._len + n] = other._null[start:end]
        self._len += n

    def extend_take(self, other: "Column", idx: np.ndarray) -> None:
        other._ensure_host()
        n = len(idx)
        if n == 0:
            return
        self._grow(n)
        self._data[self._len:self._len + n] = other._data[:other._len][idx]
        self._null[self._len:self._len + n] = other._null[:other._len][idx]
        self._len += n

    # ---- access -------------------------------------------------------
    def get(self, i: int) -> Datum:
        self._ensure_host()
        if self._null[i]:
            return None
        v = self._data[i]
        et = self.ft.eval_type
        if et is EvalType.INT:
            iv = int(v)
            if self.ft.is_unsigned and iv < 0:
                iv += 1 << 64
            return iv
        if et is EvalType.REAL:
            return float(v)
        return str(v)  # normalize np.str_ -> str

    def is_null(self, i: int) -> bool:
        self._ensure_host()
        return bool(self._null[i])

    def values(self) -> np.ndarray:
        """Raw buffer view, length-trimmed (reference: column.go Int64s())."""
        self._ensure_host()
        return self._data[:self._len]

    def null_mask(self) -> np.ndarray:
        self._ensure_host()
        return self._null[:self._len]

    def datums(self) -> List[Datum]:
        return [self.get(i) for i in range(self._len)]

    # ---- transforms ---------------------------------------------------
    def take(self, idx: np.ndarray) -> "Column":
        c = Column(self.ft, cap=max(len(idx), 1))
        c.extend_take(self, np.asarray(idx, dtype=np.int64))
        return c

    def slice(self, start: int, end: int) -> "Column":
        c = Column(self.ft, cap=max(end - start, 1))
        c.extend(self, start, end)
        return c

    def copy(self) -> "Column":
        return self.slice(0, self._len)

    def truncate(self, n: int) -> None:
        self._len = min(self._len, n)
        if n == 0 and self._data is not None and len(self._data) > _INIT_CAP:
            # a full reset frees the (possibly large) buffers and returns
            # their charge — a truncated-then-idle column must not pin a
            # statement-sized allocation on the session's books
            self.free()

    def __repr__(self) -> str:  # pragma: no cover
        return f"Column({self.ft.type_name()}, {self.datums()[:8]}{'...' if self._len > 8 else ''})"


class DeviceColumn(Column):
    """Device-resident column: values/null live as `jax.Array`s padded to
    a power-of-two bucket; host buffers materialize lazily on first host
    access.  The per-op TPU tier's late-materialization carrier — an
    aggregate output consumed by the join above it never round-trips
    through host memory (the reference's chunk always lives in Go heap,
    column.go:28; on TPU the chunk's natural home is HBM).

    Rows [0:_len) are live; padding rows carry null=True so device
    consumers (join match) treat them as no-match.  `sorted_live` marks
    values ascending among live non-null rows (a single-key aggregate
    output inherits the segment table's order) — joins against such a
    build side skip their device sort."""

    __slots__ = ("_dev_v", "_dev_n", "sorted_live")

    def __init__(self, ft: FieldType, dev_v, dev_n, n: int):
        self.ft = ft
        self._data = None         # host buffers: absent until demanded
        self._null = None
        self._len = n
        self._tracker = None
        self._charged = 0
        self._dev_v = dev_v
        self._dev_n = dev_n
        self.sorted_live = False

    def device_pair(self):
        """(values, null) jax arrays, bucket-padded (padding null=True)."""
        return self._dev_v, self._dev_n

    def device_bucket(self) -> int:
        return int(self._dev_v.shape[0])

    def _ensure_host(self) -> None:
        if self._data is None:
            # one COUNTED pull for both streams (values + null mask) —
            # raw np.asarray here was a hidden uncounted d2h (DF801)
            from ..ops import kernels
            v, m = kernels.d2h_many([self._dev_v, self._dev_n])
            v = v[:self._len]
            m = m[:self._len]
            dt = _np_dtype(self.ft.eval_type)
            self._data = np.ascontiguousarray(v, dtype=dt)
            self._null = np.asarray(m, dtype=bool).copy()
            self._charge(self._data.nbytes + self._null.nbytes)

    def take(self, idx: np.ndarray) -> "Column":
        """Gather on device, land only the gathered rows on host — the
        late-materialization payoff: a join keeping k of n rows downloads
        k values, not n."""
        if self._data is not None:
            return super().take(idx)
        from ..ops import kernels
        di = kernels.h2d(np.asarray(idx, dtype=np.int64))
        v, m = kernels.d2h_many([self._dev_v[di], self._dev_n[di]])
        dt = _np_dtype(self.ft.eval_type)
        return Column.from_numpy(
            self.ft, np.ascontiguousarray(v, dtype=dt),
            np.asarray(m, dtype=bool))


class LazyTakeColumn(Column):
    """Deferred gather: (source column, row indices) materialized only on
    first host access.  Joins emit their output columns as lazy takes, so
    a chain join -> join -> TopN gathers each payload column ONCE at the
    final (smallest) cardinality instead of at every operator — the
    late-materialization analogue of the reference's chunk.Row indirection
    (util/chunk/chunk.go:573 Sel semantics), generalized across operators.

    take() composes index arrays without touching the data, and the source
    may itself be a DeviceColumn (the final gather then runs on device)."""

    __slots__ = ("_src", "_idx")

    def __init__(self, src: Column, idx: np.ndarray):
        self.ft = src.ft
        self._data = None
        self._null = None
        self._tracker = None
        self._charged = 0
        self._idx = np.asarray(idx, dtype=np.int64)
        self._len = len(self._idx)
        self._src = src

    def _ensure_host(self) -> None:
        if self._data is None:
            mat = self._src.take(self._idx)
            mat._ensure_host()
            self._data = mat._data
            self._null = mat._null
            # the materialized column's buffers are now OURS: move its
            # charge here so `mat`'s __del__ doesn't release live bytes
            self._adopt_charge(mat)

    def take(self, idx: np.ndarray) -> "Column":
        if self._data is not None:
            return super().take(idx)
        return LazyTakeColumn(self._src,
                              self._idx[np.asarray(idx, dtype=np.int64)])
