"""Chunk wire codec: Chunk <-> bytes.

Capability parity with reference util/chunk/codec.go:353 (the SelectResponse
chunk wire format used by the coprocessor response path).  Layout per column:
  [u32 length][null bitmap bytes][payload]
payload = raw little-endian buffer for fixed-width; [u32 offsets][utf8 bytes]
for strings.  The selection vector is materialized before encode.
"""
from __future__ import annotations

import struct
from typing import List, Sequence

import numpy as np

from ..mytypes import EvalType, FieldType
from .column import Column
from .chunk import Chunk


def encode_column(col: Column) -> bytes:
    n = len(col)
    out = [struct.pack("<I", n)]
    out.append(np.packbits(col.null_mask(), bitorder="little").tobytes())
    if col.ft.eval_type is EvalType.STRING:
        vals = ["" if col.is_null(i) else str(col.values()[i]) for i in range(n)]
        raw = [v.encode("utf-8") for v in vals]
        offsets = np.zeros(n + 1, dtype=np.uint32)
        for i, b in enumerate(raw):
            offsets[i + 1] = offsets[i] + len(b)
        out.append(offsets.tobytes())
        out.append(b"".join(raw))
    else:
        out.append(np.ascontiguousarray(col.values()).tobytes())
    return b"".join(out)


def _need(buf: bytes, pos: int, n: int) -> None:
    if pos + n > len(buf):
        raise ValueError(f"truncated chunk buffer: need {n} bytes at {pos}, have {len(buf) - pos}")


def decode_column(buf: bytes, pos: int, ft: FieldType) -> tuple[Column, int]:
    _need(buf, pos, 4)
    (n,) = struct.unpack_from("<I", buf, pos)
    pos += 4
    nb = (n + 7) // 8
    _need(buf, pos, nb)
    null = np.unpackbits(
        np.frombuffer(buf, dtype=np.uint8, count=nb, offset=pos),
        bitorder="little")[:n].astype(bool)
    pos += nb
    if ft.eval_type is EvalType.STRING:
        _need(buf, pos, 4 * (n + 1))
        offsets = np.frombuffer(buf, dtype=np.uint32, count=n + 1, offset=pos)
        pos += 4 * (n + 1)
        total = int(offsets[-1]) if n else 0
        _need(buf, pos, total)
        blob = buf[pos:pos + total]
        pos += total
        data = np.empty(n, dtype=object)
        for i in range(n):
            data[i] = blob[offsets[i]:offsets[i + 1]].decode("utf-8")
        col = Column.from_numpy(ft, data, null)
    else:
        _need(buf, pos, 8 * n)
        dt = np.int64 if ft.eval_type is EvalType.INT else np.float64
        data = np.frombuffer(buf, dtype=dt, count=n, offset=pos).copy()
        pos += 8 * n
        col = Column.from_numpy(ft, data, null)
    return col, pos


def encode_chunk(chk: Chunk) -> bytes:
    c = chk.compact()
    return b"".join(encode_column(col) for col in c.columns)


def decode_chunk(buf: bytes, fields: Sequence[FieldType]) -> Chunk:
    cols: List[Column] = []
    pos = 0
    for ft in fields:
        col, pos = decode_column(buf, pos, ft)
        cols.append(col)
    return Chunk.from_columns(cols)
