"""Chunk: a batch of columns with an optional selection vector.

Capability parity with reference util/chunk/chunk.go:31 (Chunk = []Column +
sel) and chunk.go:573-588 (Sel semantics: operators read only selected rows
without materializing).  `required_rows` early-stop mirrors chunk.go:151-165.
"""
from __future__ import annotations

from typing import Iterable, List, Optional, Sequence

import numpy as np

from ..mytypes import FieldType, Datum
from .column import Column

INIT_CHUNK_SIZE = 32      # reference: sessionctx tidb_vars.go:241
MAX_CHUNK_SIZE = 1024     # reference: sessionctx tidb_vars.go:242


class Chunk:
    __slots__ = ("columns", "sel", "required_rows", "virtual_rows")

    def __init__(self, fields: Sequence[FieldType], cap: int = INIT_CHUNK_SIZE):
        self.columns: List[Column] = [Column(ft, cap) for ft in fields]
        self.sel: Optional[np.ndarray] = None
        self.required_rows: int = MAX_CHUNK_SIZE
        # row count for zero-column chunks (TableDual / `SELECT 1`)
        self.virtual_rows: int = 0

    @classmethod
    def from_columns(cls, cols: List[Column], virtual_rows: int = 0) -> "Chunk":
        c = cls([], 1)
        c.columns = cols
        c.virtual_rows = virtual_rows
        return c

    # ---- size ---------------------------------------------------------
    def num_rows(self) -> int:
        if self.sel is not None:
            return len(self.sel)
        if not self.columns:
            return self.virtual_rows
        return len(self.columns[0])

    def full_rows(self) -> int:
        """Physical row count ignoring the selection vector."""
        return len(self.columns[0]) if self.columns else self.virtual_rows

    def num_cols(self) -> int:
        return len(self.columns)

    def is_full(self) -> bool:
        return self.num_rows() >= self.required_rows

    def reset(self) -> None:
        for c in self.columns:
            c.truncate(0)
        self.sel = None
        self.virtual_rows = 0

    # ---- selection vector ---------------------------------------------
    def set_sel(self, sel: Optional[np.ndarray]) -> None:
        self.sel = None if sel is None else np.asarray(sel, dtype=np.int64)

    def compact(self) -> "Chunk":
        """Materialize the selection vector (marshalling boundary only —
        reference keeps Sel lazy, chunk.go:573)."""
        if self.sel is None:
            return self
        if not self.columns:
            # zero-column chunk: the sel vector's length IS the row count
            return Chunk.from_columns([], virtual_rows=len(self.sel))
        return Chunk.from_columns([c.take(self.sel) for c in self.columns])

    # ---- row append ----------------------------------------------------
    def append_row(self, values: Sequence[Datum]) -> None:
        assert self.sel is None
        for c, v in zip(self.columns, values):
            c.append(v)

    def append_chunk_row(self, other: "Chunk", i: int) -> None:
        if not self.columns:
            self.virtual_rows += 1
            return
        phys = other.sel[i] if other.sel is not None else i
        for dst, src in zip(self.columns, other.columns):
            dst.extend(src, phys, phys + 1)

    def append_chunk(self, other: "Chunk") -> None:
        o = other.compact()
        if not self.columns:
            self.virtual_rows += o.num_rows()
            return
        for dst, src in zip(self.columns, o.columns):
            dst.extend(src)

    # ---- row access ----------------------------------------------------
    def get_row(self, i: int) -> List[Datum]:
        phys = self.sel[i] if self.sel is not None else i
        return [c.get(phys) for c in self.columns]

    def rows(self) -> Iterable[List[Datum]]:
        for i in range(self.num_rows()):
            yield self.get_row(i)

    def to_rows(self) -> List[List[Datum]]:
        return [self.get_row(i) for i in range(self.num_rows())]

    def field_types(self) -> List[FieldType]:
        return [c.ft for c in self.columns]

    def __repr__(self) -> str:  # pragma: no cover
        return f"Chunk({self.num_rows()}x{self.num_cols()})"


def new_chunk_like(chk: Chunk, cap: int = INIT_CHUNK_SIZE) -> Chunk:
    return Chunk(chk.field_types(), cap)


def chunk_from_rows(fields: Sequence[FieldType],
                    rows: Iterable[Sequence[Datum]]) -> Chunk:
    c = Chunk(fields)
    for r in rows:
        c.append_row(r)
    return c
