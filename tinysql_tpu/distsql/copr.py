"""Storage-side coprocessor interpreter (reference:
mocktikv/cop_handler_dag.go:49-160 + executor.go/aggregate.go/topn.go —
the row-at-a-time reference interpreter, here chunk-vectorized: the scan
decodes into a Chunk and the pushed chain runs the same numpy builtins the
root executor uses).

Installed on the RPC client as `cop_handler`; one call = one region's worth
of one DAGRequest.  Lock conflicts surface as KeyIsLocked and are resolved
by the client (store/tikv semantics).
"""
from __future__ import annotations

from typing import List, Optional

from ..chunk import Chunk
from ..codec import rowcodec, tablecodec
from ..expression import vectorized_filter
from ..expression.aggregation import AggFuncDesc, AggMode
from ..mytypes import FieldType
from .exprpb import _ft_from_pb, pb_to_expr
from .request import DAGRequest


def make_cop_handler(mvcc):
    def handle(region, task) -> list:
        req: DAGRequest = task["req"]
        start, end = task["range"]
        s = max(start, region.start)
        e = min(end, region.end) if region.end else end
        pairs = mvcc.scan(s, e, req.start_ts, 0, req.resolved)
        return run_dag(req, _decode_chunk(req, pairs))
    return handle


def _decode_chunk(req: DAGRequest, pairs) -> Chunk:
    scan = req.scan
    fts = [_ft_from_pb(d) for d in scan.col_fts]
    chk = Chunk(fts, cap=max(len(pairs), 1))
    for k, v in pairs:
        if not tablecodec.is_record_key(k):
            continue
        _, handle = tablecodec.decode_record_key(k)
        row = rowcodec.decode_row_to_datums(
            v, scan.col_ids, fts, defaults=scan.col_defaults)
        for slot in scan.handle_slots:
            row[slot] = handle
        if scan.pk_id is not None:
            for i, cid in enumerate(scan.col_ids):
                if cid == scan.pk_id:
                    row[i] = handle
        chk.append_row(row)
    return chk


def run_dag(req: DAGRequest, chk: Chunk) -> list:
    """Execute the pushed chain over decoded rows; returns output rows as
    plain value lists (the 'tipb.SelectResponse chunk' analogue)."""
    import numpy as np
    if req.analyze:
        return _analyze_partial(req, chk)
    if req.filters:
        conds = [pb_to_expr(d) for d in req.filters]
        if chk.num_rows():
            mask = vectorized_filter(conds, chk)
            chk.set_sel(np.nonzero(mask)[0])
            chk = chk.compact()
    if req.agg is not None:
        return _partial_agg(req.agg, chk)
    rows = [list(chk.get_row(i)) for i in range(chk.num_rows())]
    if req.topn is not None:
        rows = _topn(req.topn, chk, rows)
    if req.limit is not None:
        rows = rows[:req.limit]
    return rows


ANALYZE_REGION_SAMPLES = 10_000


def _analyze_partial(req: DAGRequest, chk: Chunk) -> list:
    """Per-region ANALYZE task (reference: tipb.AnalyzeReq handled by
    mocktikv/analyze.go): per scan column, one ReservoirSampler pass
    producing the bounded uniform sample + null count + CMSketch +
    FMSketch partials for the root's weighted merge."""
    from ..statistics.sketches import ReservoirSampler
    n = chk.num_rows()
    out_cols = {}
    for cid, col in zip(req.scan.col_ids, chk.columns):
        rs = ReservoirSampler(ANALYZE_REGION_SAMPLES)
        null = col.null_mask()
        for i in range(n):
            rs.collect(None if null[i] else col.get(i))
        out_cols[cid] = {
            "nulls": rs.null_count,
            "live": rs.seen,
            "samples": rs.samples,
            "cms": rs.cms,
            "fm": rs.fm,
        }
    return [{"rows": n, "cols": out_cols}]


def _parse_agg_pb(agg_pb: dict):
    gb = [pb_to_expr(d) for d in agg_pb["group_by"]]
    descs = [AggFuncDesc(a["name"], [pb_to_expr(x) for x in a["args"]],
                         AggMode.PARTIAL1, a["distinct"],
                         _ft_from_pb(a["ret"]) if "ret" in a else None)
             for a in agg_pb["aggs"]]
    return gb, descs


def _partial_agg_pairs(agg_pb: dict, chk: Chunk):
    """Columnar per-region PARTIAL1 aggregation: factorize group keys,
    bincount/reduce-at per aggregate.  Returns (pairs, uns_flags) where
    pairs = [(np values, np null)] per output column (group keys then
    partial-state columns, RAW int64 representation for wrapped unsigned)
    and uns_flags marks which columns hold wrapped unsigned ints — or
    None for shapes numpy cannot reduce (DISTINCT, string min/max)."""
    import numpy as np
    gb, descs = _parse_agg_pb(agg_pb)
    n = chk.num_rows()
    if n == 0:
        return [], []
    if any(d.distinct for d in descs):
        return None

    # ---- factorize the group keys -------------------------------------
    codes = np.zeros(n, dtype=np.int64)
    key_cols = []
    total = 1
    for e in gb:
        v, null = e.vec_eval(chk)
        raw = v
        if v.dtype == object:
            v = np.where(null, "", v).astype(str)
        kc, inv = np.unique(v, return_inverse=True)
        # null gets its own code (one extra bin)
        inv = np.where(null, len(kc), inv)
        total *= len(kc) + 1
        if total > (1 << 62):  # composite code would overflow int64
            return None
        codes = codes * (len(kc) + 1) + inv
        key_cols.append((raw, null))
    uniq, gid, counts = np.unique(codes, return_inverse=True,
                                  return_counts=True)
    ng = len(uniq)
    first_idx = np.full(ng, n, dtype=np.int64)
    np.minimum.at(first_idx, gid, np.arange(n))

    pairs = []
    uns_flags = []
    for v, null in key_cols:
        pairs.append((v[first_idx], null[first_idx]))
        uns_flags.append(False)
    for d in descs:
        cols = _vector_partial(d, chk, gid, ng, first_idx)
        if cols is None:
            return None
        for v, nl, uns in cols:
            pairs.append((v, nl))
            uns_flags.append(uns)
    return pairs, uns_flags


def _partial_agg(agg_pb: dict, chk: Chunk) -> list:
    """Per-region PARTIAL1 aggregation as rows (the wire-path shape,
    reference mocktikv/aggregate.go); row-at-a-time only for shapes
    numpy cannot reduce."""
    import numpy as np
    got = _partial_agg_pairs(agg_pb, chk)
    if got is None:
        gb, descs = _parse_agg_pb(agg_pb)
        return _partial_agg_rows(gb, descs, chk)
    pairs, uns_flags = got
    if not pairs:
        return []
    cols_py = []
    for (v, nl), uns in zip(pairs, uns_flags):
        lst = v.tolist()
        if uns:
            lst = [x + (1 << 64) if x < 0 else x for x in lst]
        for i in np.nonzero(nl)[0]:
            lst[i] = None
        cols_py.append(lst)
    return [list(t) for t in zip(*cols_py)]


def partial_agg_chunk(agg_pb: dict, chk: Chunk,
                      fts: List[FieldType]) -> Optional[Chunk]:
    """Columnar partial aggregation straight into a Chunk — the
    in-process replica fast path (no per-row marshalling).  Wrapped
    unsigned values stay raw; `fts` carries the unsigned flags.  Falls
    back to the row interpreter for unsupported shapes."""
    from ..chunk import Column as CCol
    got = _partial_agg_pairs(agg_pb, chk)
    if got is None:
        rows = _partial_agg(agg_pb, chk)
        out = Chunk(fts, cap=max(len(rows), 1))
        for r in rows:
            out.append_row(r)
        return out
    pairs, _uns = got
    if not pairs:
        return Chunk(fts, cap=1)
    return Chunk.from_columns(
        [CCol.from_numpy(ft, v, nl) for ft, (v, nl) in zip(fts, pairs)])


def _vector_partial(d: AggFuncDesc, chk: Chunk, gid, ng, first_idx):
    """Vectorized partial state columns for one descriptor as
    [(values, null, is_wrapped_unsigned)], or None when the shape needs
    the row fallback."""
    import numpy as np
    from ..expression import Constant
    name = d.name
    if name == "count":
        a = d.args[0]
        if isinstance(a, Constant):
            live = np.ones(len(gid), dtype=bool) if a.value is not None \
                else np.zeros(len(gid), dtype=bool)
        else:
            v, null = a.vec_eval(chk)
            live = ~null
        cnt = np.bincount(gid, weights=live.astype(np.float64),
                          minlength=ng).astype(np.int64)
        return [(cnt, np.zeros(ng, dtype=bool), False)]
    if name == "sum":
        v, null = d.args[0].vec_eval(chk)
        if v.dtype == object or v.dtype.kind == "U":
            return None
        uns = d.args[0].ret_type.is_unsigned and v.dtype == np.int64
        is_real = d.ret_type.eval_type.name == "REAL"
        live = ~null
        cnt = np.bincount(gid, weights=live.astype(np.float64),
                          minlength=ng).astype(np.int64)
        if is_real:
            w = np.where(live, v.astype(np.float64), 0.0)
            if uns:
                w = np.where(live & (v < 0), w + 2.0**64, w)
            s = np.bincount(gid, weights=w, minlength=ng)
            return [(s, cnt == 0, False)]
        # int sums: exact mod-2^64 accumulation via int64 reduce-at
        s = np.zeros(ng, dtype=np.int64)
        with np.errstate(over="ignore"):
            np.add.at(s, gid[live], v[live])
        return [(s, cnt == 0, uns)]
    if name in ("max", "min"):
        v, null = d.args[0].vec_eval(chk)
        if v.dtype == object or v.dtype.kind == "U":
            return None  # string min/max: row fallback
        uns = d.args[0].ret_type.is_unsigned and v.dtype == np.int64
        work = v ^ np.int64(-2**63) if uns else v
        live = ~null
        if v.dtype == np.int64:
            fill = np.iinfo(np.int64).max if name == "min" \
                else np.iinfo(np.int64).min
        else:
            fill = np.inf if name == "min" else -np.inf
        acc = np.full(ng, fill, dtype=work.dtype)
        op = np.minimum if name == "min" else np.maximum
        op.at(acc, gid[live], work[live])
        cnt = np.bincount(gid, weights=live.astype(np.float64),
                          minlength=ng).astype(np.int64)
        if uns:
            acc = acc ^ np.int64(-2**63)  # back to the raw wrapped form
        return [(acc, cnt == 0, uns)]
    if name == "first_row":
        v, null = d.args[0].vec_eval(chk)
        return [(v[first_idx], null[first_idx], False)]
    return None  # avg never appears: split() emits sum+count partials


def _partial_agg_rows(gb, descs, chk: Chunk) -> list:
    """Row-at-a-time fallback (the mocktikv-style interpreter)."""
    from ..executor.aggfuncs import new_state
    n = chk.num_rows()
    groups = {}
    order = []
    rows = [chk.get_row(i) for i in range(n)]
    for i in range(n):
        key = tuple(_sem(v) for v in (e.eval(rows[i]) for e in gb))
        st = groups.get(key)
        if st is None:
            st = groups[key] = [new_state(d) for d in descs]
            order.append(key)
        for j, d in enumerate(descs):
            st[j].update([a.eval(rows[i]) for a in d.args])
    out = []
    for key in order:
        row = list(key)
        for st in groups[key]:
            row.extend(st.partial())
        out.append(row)
    return out


def _sem(v):
    return v.item() if hasattr(v, "item") else v


def _topn(topn_pb: dict, chk: Chunk, rows: list) -> list:
    from ..mytypes import sort_key
    by = [(pb_to_expr(d), desc) for d, desc in topn_pb["by"]]

    def key_fn(row):
        ks = []
        for e, desc in by:
            v = e.eval(row)
            if v is None:
                ks.append((0 if not desc else 2, 0))
            else:
                sk = sort_key(v)
                ks.append((1, _Rev(sk) if desc else sk))
        return ks
    rows = sorted(rows, key=key_fn)
    return rows[:topn_pb["n"]]


class _Rev:
    __slots__ = ("v",)

    def __init__(self, v):
        self.v = v

    def __lt__(self, other):
        return other.v < self.v

    def __eq__(self, other):
        return other.v == self.v
