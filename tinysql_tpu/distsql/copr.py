"""Storage-side coprocessor interpreter (reference:
mocktikv/cop_handler_dag.go:49-160 + executor.go/aggregate.go/topn.go —
the row-at-a-time reference interpreter, here chunk-vectorized: the scan
decodes into a Chunk and the pushed chain runs the same numpy builtins the
root executor uses).

Installed on the RPC client as `cop_handler`; one call = one region's worth
of one DAGRequest.  Lock conflicts surface as KeyIsLocked and are resolved
by the client (store/tikv semantics).
"""
from __future__ import annotations

from typing import List, Optional

from ..chunk import Chunk
from ..codec import rowcodec, tablecodec
from ..expression import vectorized_filter
from ..expression.aggregation import AggFuncDesc, AggMode
from ..mytypes import FieldType
from .exprpb import _ft_from_pb, pb_to_expr
from .request import DAGRequest


def make_cop_handler(mvcc):
    def handle(region, task) -> list:
        req: DAGRequest = task["req"]
        start, end = task["range"]
        s = max(start, region.start)
        e = min(end, region.end) if region.end else end
        pairs = mvcc.scan(s, e, req.start_ts, 0, req.resolved)
        return run_dag(req, _decode_chunk(req, pairs))
    return handle


def _decode_chunk(req: DAGRequest, pairs) -> Chunk:
    scan = req.scan
    fts = [_ft_from_pb(d) for d in scan.col_fts]
    chk = Chunk(fts, cap=max(len(pairs), 1))
    for k, v in pairs:
        if not tablecodec.is_record_key(k):
            continue
        _, handle = tablecodec.decode_record_key(k)
        row = rowcodec.decode_row_to_datums(
            v, scan.col_ids, fts, defaults=scan.col_defaults)
        for slot in scan.handle_slots:
            row[slot] = handle
        if scan.pk_id is not None:
            for i, cid in enumerate(scan.col_ids):
                if cid == scan.pk_id:
                    row[i] = handle
        chk.append_row(row)
    return chk


def run_dag(req: DAGRequest, chk: Chunk) -> list:
    """Execute the pushed chain over decoded rows; returns output rows as
    plain value lists (the 'tipb.SelectResponse chunk' analogue)."""
    import numpy as np
    if req.filters:
        conds = [pb_to_expr(d) for d in req.filters]
        if chk.num_rows():
            mask = vectorized_filter(conds, chk)
            chk.set_sel(np.nonzero(mask)[0])
            chk = chk.compact()
    if req.agg is not None:
        return _partial_agg(req.agg, chk)
    rows = [list(chk.get_row(i)) for i in range(chk.num_rows())]
    if req.topn is not None:
        rows = _topn(req.topn, chk, rows)
    if req.limit is not None:
        rows = rows[:req.limit]
    return rows


def _partial_agg(agg_pb: dict, chk: Chunk) -> list:
    """Per-region PARTIAL1 aggregation (reference: mocktikv/aggregate.go);
    output rows = [group key values..., flattened partial states...]."""
    from ..executor.aggfuncs import new_state
    gb = [pb_to_expr(d) for d in agg_pb["group_by"]]
    descs = []
    for a in agg_pb["aggs"]:
        descs.append(AggFuncDesc(a["name"], [pb_to_expr(x) for x in a["args"]],
                                 AggMode.PARTIAL1, a["distinct"],
                                 _ft_from_pb(a["ret"]) if "ret" in a
                                 else None))
    n = chk.num_rows()
    groups = {}
    order = []
    rows = [chk.get_row(i) for i in range(n)]
    for i in range(n):
        key = tuple(_sem(v) for v in (e.eval(rows[i]) for e in gb))
        st = groups.get(key)
        if st is None:
            st = groups[key] = [new_state(d) for d in descs]
            order.append(key)
        for j, d in enumerate(descs):
            st[j].update([a.eval(rows[i]) for a in d.args])
    out = []
    for key in order:
        row = list(key)
        for st in groups[key]:
            row.extend(st.partial())
        out.append(row)
    return out


def _sem(v):
    return v.item() if hasattr(v, "item") else v


def _topn(topn_pb: dict, chk: Chunk, rows: list) -> list:
    from ..mytypes import sort_key
    by = [(pb_to_expr(d), desc) for d, desc in topn_pb["by"]]

    def key_fn(row):
        ks = []
        for e, desc in by:
            v = e.eval(row)
            if v is None:
                ks.append((0 if not desc else 2, 0))
            else:
                sk = sort_key(v)
                ks.append((1, _Rev(sk) if desc else sk))
        return ks
    rows = sorted(rows, key=key_fn)
    return rows[:topn_pb["n"]]


class _Rev:
    __slots__ = ("v",)

    def __init__(self, v):
        self.v = v

    def __lt__(self, other):
        return other.v < self.v

    def __eq__(self, other):
        return other.v == self.v
