"""Expression <-> wire-form codec for coprocessor pushdown.

Capability parity with reference expression/expr_to_pb.go (expression ->
tipb.Expr with pushdown eligibility checks) and distsql_builtin.go (the
reverse decode on the storage side).  The wire form is a plain dict tree —
the in-process analogue of the protobuf — and the decode path rebuilds
through `new_function`, so the storage side executes the SAME typed builtin
implementations the root executor would.
"""
from __future__ import annotations

from typing import List, Optional

from ..expression import Column, Constant, Expression, ScalarFunction
from ..expression.builtins import new_function
from ..mytypes import FieldType

# functions the coprocessor can evaluate (reference expr_to_pb.go canFuncBePushed)
PUSHABLE_FUNCS = {
    "+", "-", "*", "/", "div", "%", "unaryminus",
    "=", "!=", "<", "<=", ">", ">=", "<=>",
    "and", "or", "xor", "not", "isnull", "istrue", "isfalse",
    "if", "ifnull", "case", "in", "like",
}


def _ft_to_pb(ft: FieldType) -> dict:
    return {"tp": ft.tp, "flag": ft.flag, "flen": ft.flen}


def _ft_from_pb(d: dict) -> FieldType:
    return FieldType(tp=d["tp"], flag=d["flag"], flen=d["flen"])


def can_push(e: Expression) -> bool:
    if isinstance(e, (Column, Constant)):
        return True
    if isinstance(e, ScalarFunction):
        if e.name not in PUSHABLE_FUNCS:
            return False
        return all(can_push(a) for a in e.args)
    return False


def expr_to_pb(e: Expression) -> dict:
    """Offset-bound expression -> wire dict.  Raises ValueError on
    non-pushable trees (caller gates with can_push)."""
    if isinstance(e, Column):
        if e.index < 0:
            raise ValueError(f"unbound column {e!r}")
        return {"t": "col", "i": e.index, "ft": _ft_to_pb(e.ret_type)}
    if isinstance(e, Constant):
        return {"t": "const", "v": e.value, "ft": _ft_to_pb(e.ret_type)}
    if isinstance(e, ScalarFunction):
        if e.name not in PUSHABLE_FUNCS:
            raise ValueError(f"not pushable: {e.name}")
        return {"t": "func", "name": e.name,
                "args": [expr_to_pb(a) for a in e.args]}
    raise ValueError(f"cannot encode {type(e).__name__}")


def pb_to_expr(d: dict) -> Expression:
    """Wire dict -> executable expression (reference: distsql_builtin.go
    PBToExpr).  Columns come back offset-bound to the scan output."""
    t = d["t"]
    if t == "col":
        return Column(_ft_from_pb(d["ft"]), index=d["i"])
    if t == "const":
        return Constant(d["v"], _ft_from_pb(d["ft"]))
    if t == "func":
        return new_function(d["name"], [pb_to_expr(a) for a in d["args"]])
    raise ValueError(f"bad expr pb {d!r}")


def exprs_to_pb(exprs: List[Expression]) -> Optional[List[dict]]:
    """All-or-nothing encode (reference: ExpressionsToPBList)."""
    if not all(can_push(e) for e in exprs):
        return None
    return [expr_to_pb(e) for e in exprs]
