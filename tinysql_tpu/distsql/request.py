"""Coprocessor DAG request model (reference: kv.Request + tipb.DAGRequest
built by distsql/request_builder.go:36-130 and executor/builder.go's
PB assembly).

The request carries everything the storage side needs to run the pushed
executor chain: scan column layout, snapshot ts, wire-form filter /
partial-aggregation / topn / limit nodes.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple


@dataclass
class ScanInfo:
    """Table scan column layout: ids, field types (wire form), defaults,
    and which output slot (if any) is the integer handle."""
    table_id: int
    col_ids: List[int]
    col_fts: List[dict]            # exprpb._ft_to_pb form
    col_defaults: List[object]
    handle_slots: List[int]        # output offsets filled with the handle
    pk_id: Optional[int] = None    # pk-as-handle column id (value == handle)


@dataclass
class DAGRequest:
    """reference: tipb.DAGRequest {TableScan, Selection, Aggregation, TopN,
    Limit} executor list."""
    start_ts: int
    scan: ScanInfo
    filters: Optional[List[dict]] = None      # exprpb trees over scan cols
    agg: Optional[dict] = None                # {"group_by": [pb], "aggs":
    #   [{"name","args":[pb],"distinct"}]} — PARTIAL1 on the cop side
    topn: Optional[dict] = None               # {"by": [(pb, desc)], "n": int}
    limit: Optional[int] = None
    analyze: bool = False                     # per-region stats partials
    resolved: Tuple[int, ...] = ()            # resolved-lock start_ts cache
