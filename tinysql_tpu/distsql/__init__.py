"""Distributed query layer: coprocessor pushdown over the region-sharded
store (reference: distsql/ + store/tikv/coprocessor.go + the mocktikv cop
interpreter, SURVEY §2.6/§2.7)."""
from .client import CopClient, select
from .request import DAGRequest, ScanInfo

__all__ = ["CopClient", "DAGRequest", "ScanInfo", "select"]
