"""Coprocessor client: region scatter-gather with parallel workers
(reference: store/tikv/coprocessor.go — buildCopTasks :204, copIterator
worker pool :317-521, per-task retry with region re-split :569-640; and
distsql/distsql.go Select / select_result.go SelectResult).

`select()` splits the key ranges into per-region tasks, runs them on a
bounded worker pool (`tidb_distsql_scan_concurrency`), retries region
errors after re-splitting against the refreshed cache, resolves lock
conflicts, and yields each task's rows in task (key) order.

Robustness contract:

- every task attempt passes the ``copTaskError`` failpoint, so chaos
  tests can drive the whole retry ladder (RegionError -> re-split,
  KeyIsLocked -> resolve) or surface a typed error;
- workers run inside a COPY of the caller's context, so the statement
  kill flag / max_execution_time deadline (utils/interrupt.py) and the
  per-query observability scope both reach them;
- early close (a root LIMIT abandoning the iterator) sets a cancel
  event that every worker observes at its next attempt or mid-backoff
  (Backoffer wakes on it), then joins the pool with
  ``shutdown(wait=True, cancel_futures=True)`` — no worker thread
  survives the generator (the reference copIterator Close contract).
"""
from __future__ import annotations

import concurrent.futures as cf
import contextvars
import threading
from dataclasses import replace
from typing import Iterator, List, Optional, Tuple

from .. import fail
from ..kv import backoff as bo
from ..kv.backoff import Backoffer
from ..kv.errors import KeyIsLocked, RegionError, TaskCancelled
from ..kv.rpc import RegionCtx
from ..utils import interrupt
from .request import DAGRequest

DEFAULT_CONCURRENCY = 15


class CopClient:
    def __init__(self, storage):
        self.storage = storage

    def build_tasks(self, ranges: List[Tuple[bytes, bytes]]):
        tasks = []
        for start, end in ranges:
            for region, s, e in \
                    self.storage.cache.split_range_by_regions(start, end):
                tasks.append((region, s, e))
        return tasks

    def _run_task(self, req: DAGRequest, region, s: bytes, e: bytes,
                  cancel: Optional[threading.Event] = None,
                  boer: Optional[Backoffer] = None) -> list:
        """Execute one region task with backoff; re-splits on region errors
        (reference: coprocessor.go handleTaskOnce + onRegionError).  The
        Backoffer is threaded through re-split recursion — each level
        must spend the SAME retry budget, so a persistently failing
        region exhausts it as a typed BackoffExceeded instead of
        recursing a fresh budget per level."""
        if boer is None:
            boer = Backoffer(bo.COP_NEXT_MAX_BACKOFF, cancel=cancel)
        resolved: Tuple[int, ...] = req.resolved
        while True:
            interrupt.check()
            if cancel is not None and cancel.is_set():
                raise TaskCancelled("cop task cancelled")
            try:
                fail.inject("copTaskError")
                return self.storage.client.coprocessor(
                    RegionCtx(region.id, region.epoch),
                    {"req": replace(req, resolved=resolved), "range": (s, e)})
            except RegionError as err:
                self.storage.cache.invalidate(region.id)
                boer.backoff(bo.BO_REGION_MISS, err)
                out = []
                for r2, s2, e2 in \
                        self.storage.cache.split_range_by_regions(s, e):
                    out.extend(self._run_task(req, r2, s2, e2, cancel,
                                              boer))
                return out
            except KeyIsLocked as lk:
                if self.storage.resolver.resolve(boer, lk):
                    # outcome KNOWN (committed/rolled back) and the
                    # resolve was sent: the server may now ignore this
                    # txn's leftovers.  A still-LIVE lock must NOT be
                    # added — reading around it would miss a commit
                    # that lands with commit_ts below our snapshot
                    # (chaos-suite find: stale point reads under a
                    # pending 2PC)
                    if lk.lock_ts not in resolved:
                        resolved = resolved + (lk.lock_ts,)
                else:
                    boer.backoff(bo.BO_TXN_LOCK_FAST, lk)

    def select(self, req: DAGRequest, ranges: List[Tuple[bytes, bytes]],
               concurrency: int = DEFAULT_CONCURRENCY) -> Iterator[list]:
        """Yield per-task row batches in task order (keep-order semantics;
        reference: copIterator with keepOrder + sendToRespCh)."""
        tasks = self.build_tasks(ranges)
        if not tasks:
            return
        if concurrency <= 1 or len(tasks) == 1:
            for region, s, e in tasks:
                yield self._run_task(req, region, s, e)
            return
        # bounded in-flight window: at most `concurrency` region results
        # buffered (the reference copIterator's respChan backpressure)
        cancel = threading.Event()
        # "distsql-cop" is the conprof role vocabulary
        # (obs/conprof.ROLE_PREFIXES): cop workers classify as role
        # `distsql` in continuous_profiling / race-stress / py-spy
        pool = cf.ThreadPoolExecutor(max_workers=min(concurrency, len(tasks)),
                                     thread_name_prefix="distsql-cop")

        def submit(task):
            region, s, e = task
            # fresh context COPY per task: one Context object cannot be
            # entered concurrently, and workers must see the caller's
            # statement guard + obs scope
            ctx = contextvars.copy_context()
            return pool.submit(ctx.run, self._run_task, req, region, s, e,
                               cancel)
        try:
            futs = []
            nxt = 0
            done = 0
            while done < len(tasks):
                while nxt < len(tasks) and nxt - done < concurrency:
                    futs.append(submit(tasks[nxt]))
                    nxt += 1
                yield futs[done].result()
                futs[done] = None  # release the buffered rows
                done += 1
        except BaseException:
            # early close (root LIMIT satisfied -> GeneratorExit), a
            # statement kill raised out of .result(), or any task error:
            # cancel pending work and JOIN the pool — a worker mid-retry
            # observes `cancel` at its next attempt or mid-backoff, so
            # the join is bounded and no thread outlives the iterator
            cancel.set()
            pool.shutdown(wait=True, cancel_futures=True)
            raise
        pool.shutdown(wait=True)


def select(storage, req: DAGRequest, ranges, concurrency=DEFAULT_CONCURRENCY):
    return CopClient(storage).select(req, ranges, concurrency)
