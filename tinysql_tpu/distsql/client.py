"""Coprocessor client: region scatter-gather with parallel workers
(reference: store/tikv/coprocessor.go — buildCopTasks :204, copIterator
worker pool :317-521, per-task retry with region re-split :569-640; and
distsql/distsql.go Select / select_result.go SelectResult).

`select()` splits the key ranges into per-region tasks, runs them on a
bounded worker pool (`tidb_distsql_scan_concurrency`), retries region
errors after re-splitting against the refreshed cache, resolves lock
conflicts, and yields each task's rows in task (key) order.
"""
from __future__ import annotations

import concurrent.futures as cf
from dataclasses import replace
from typing import Iterator, List, Tuple

from ..kv import backoff as bo
from ..kv.backoff import Backoffer
from ..kv.errors import KeyIsLocked, RegionError
from ..kv.rpc import RegionCtx
from .request import DAGRequest

DEFAULT_CONCURRENCY = 15


class CopClient:
    def __init__(self, storage):
        self.storage = storage

    def build_tasks(self, ranges: List[Tuple[bytes, bytes]]):
        tasks = []
        for start, end in ranges:
            for region, s, e in \
                    self.storage.cache.split_range_by_regions(start, end):
                tasks.append((region, s, e))
        return tasks

    def _run_task(self, req: DAGRequest, region, s: bytes, e: bytes) -> list:
        """Execute one region task with backoff; re-splits on region errors
        (reference: coprocessor.go handleTaskOnce + onRegionError)."""
        boer = Backoffer(bo.COP_NEXT_MAX_BACKOFF)
        resolved: Tuple[int, ...] = req.resolved
        while True:
            try:
                return self.storage.client.coprocessor(
                    RegionCtx(region.id, region.epoch),
                    {"req": replace(req, resolved=resolved), "range": (s, e)})
            except RegionError as err:
                self.storage.cache.invalidate(region.id)
                boer.backoff(bo.BO_REGION_MISS, err)
                out = []
                for r2, s2, e2 in \
                        self.storage.cache.split_range_by_regions(s, e):
                    out.extend(self._run_task(req, r2, s2, e2))
                return out
            except KeyIsLocked as lk:
                if not self.storage.resolver.resolve(boer, lk):
                    boer.backoff(bo.BO_TXN_LOCK_FAST, lk)
                resolved = resolved + (lk.lock_ts,) \
                    if lk.lock_ts not in resolved else resolved

    def select(self, req: DAGRequest, ranges: List[Tuple[bytes, bytes]],
               concurrency: int = DEFAULT_CONCURRENCY) -> Iterator[list]:
        """Yield per-task row batches in task order (keep-order semantics;
        reference: copIterator with keepOrder + sendToRespCh)."""
        tasks = self.build_tasks(ranges)
        if not tasks:
            return
        if concurrency <= 1 or len(tasks) == 1:
            for region, s, e in tasks:
                yield self._run_task(req, region, s, e)
            return
        # bounded in-flight window: at most `concurrency` region results
        # buffered (the reference copIterator's respChan backpressure);
        # early close (root LIMIT satisfied) cancels pending tasks
        pool = cf.ThreadPoolExecutor(max_workers=min(concurrency, len(tasks)))
        try:
            futs = []
            nxt = 0
            done = 0
            while done < len(tasks):
                while nxt < len(tasks) and nxt - done < concurrency:
                    region, s, e = tasks[nxt]
                    futs.append(pool.submit(self._run_task, req, region, s, e))
                    nxt += 1
                yield futs[done].result()
                futs[done] = None  # release the buffered rows
                done += 1
        except GeneratorExit:
            pool.shutdown(wait=False, cancel_futures=True)
            raise
        pool.shutdown(wait=True)


def select(storage, req: DAGRequest, ranges, concurrency=DEFAULT_CONCURRENCY):
    return CopClient(storage).select(req, ranges, concurrency)
