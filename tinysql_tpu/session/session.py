"""Session: the statement lifecycle loop.

Capability parity with reference session/session.go (parse→compile→run
:569-629, txn lifecycle with lazy TSO :638-663, autocommit handling,
sysvar get/set :464-523), executor/compiler.go, executor/adapter.go
(ExecStmt), plus the SHOW / EXPLAIN / ADMIN / SimpleExec statement family
(executor/show.go, simple.go, set.go, ddl.go, explain.go).
"""
from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..catalog.infoschema import InfoSchema
from ..catalog.meta import Meta
from ..catalog.model import SchemaState, TableInfo
from ..executor.executors import ExecContext, build_executor
from ..executor.write import DeleteExec, InsertExec, WriteError
from ..expression import Constant, Schema
from ..kv import RetryableError, new_mock_storage
from ..mytypes import Datum, to_string
from ..parser import ParseError, ast, parse
from ..planner.builder import (ExprRewriter, HANDLE_COL_NAME, PlanBuilder,
                               PlanError)
from ..planner.logical import LogicalSelection
from ..planner.optimizer import optimize
from ..expression import Column as ExprColumn, split_cnf
from ..mytypes import new_int_type
from ..utils import interrupt, memory

DEFAULT_SYSVARS: Dict[str, Datum] = {
    # reference: sessionctx/variable/tidb_vars.go defaults
    "autocommit": 1,
    "tidb_max_chunk_size": 1024,
    "tidb_init_chunk_size": 32,
    "tidb_hash_join_concurrency": 5,
    "tidb_projection_concurrency": 4,
    "tidb_hashagg_partial_concurrency": 4,
    "tidb_hashagg_final_concurrency": 4,
    "tidb_distsql_scan_concurrency": 15,
    "tidb_index_lookup_concurrency": 4,
    "tidb_use_tpu": 1,           # device enforcer master switch
    "tidb_tpu_min_rows": 8192,   # row gate: smaller inputs stay on CPU
    "tidb_devpipe": -1,          # device pipelines: -1 auto (device
                                 # backends only), 0 off, 1 force
    "tidb_enable_cascades_planner": 0,
    "tidb_mesh_parallel": 0,     # shard fused aggregates over the device mesh
    # mesh join strategy: build sides with more (bucketed) rows than this
    # shuffle-partition over the mesh via all_to_all; smaller ones
    # broadcast (reference P4 "partition build-side tables" north star)
    "tidb_broadcast_build_max_rows": 1 << 20,
    # device memory budget in ROWS per upload block: AGGREGATION over
    # tables above it runs block-wise (partial-state carry) instead of
    # whole-column resident, and the fused device pipeline stands down
    # (SURVEY §5.7 long-context analogue).  Other device operators are
    # not budget-aware yet.  0 = unlimited
    "tidb_device_block_rows": 0,
    # late materialization: aggregate outputs consumed by device joins
    # stay resident in device memory (DeviceColumn chunks); 0 forces the
    # host-extraction path
    "tidb_device_passthrough": 1,
    # async block pipeline: staged blocks in flight ahead of the device
    # (executor/devpipe.py BlockPipeline — block-wise aggregation and
    # join probe streaming overlap host staging with device compute).
    # 0 = synchronous staging (byte-identical results, no thread);
    # the TINYSQL_PIPELINE_DEPTH env var overrides for tests/CI
    "tidb_pipeline_depth": 2,
    # persistent XLA compile-cache directory so bucketed kernels survive
    # process restarts ("" = engine default <repo>/.jax_cache; see
    # ops/kernels.py set_compile_cache_dir for the resolution chain)
    "tidb_compile_cache_dir": "",
    # opt-in runtime arm of the qlint plan-device checker: verify every
    # placed plan's device invariants before execution (analysis/
    # plan_device.py) and fail the statement on violation
    "tidb_qlint_verify": 0,
    # slow-query log threshold in MILLISECONDS (reference:
    # tidb_slow_log_threshold, default 300): statements whose exec wall
    # exceeds it emit a structured JSONL record (obs/slowlog.py)
    "tidb_slow_log_threshold": 300,
    # statement-summary window length in SECONDS: when the current
    # aggregation window of information_schema.statements_summary is
    # older, it rotates into bounded history (obs/stmtsummary.py)
    "tidb_stmt_summary_refresh_interval": 1800,
    # max distinct (sql digest, plan digest) keys per summary window;
    # beyond it the least-recently-seen record folds into the single
    # 'evicted' tombstone row
    "tidb_stmt_summary_max_stmt_count": 200,
    "sql_mode": "STRICT_TRANS_TABLES",
    # SELECT wall-clock budget in MILLISECONDS (0 = unlimited): checked
    # at every block boundary (utils/interrupt.py), surfaces MySQL 3024
    "max_execution_time": 0,
    # per-query chunk-allocation budget in BYTES (0 = unlimited): blown
    # quota aborts the statement with error 8175 (utils/memory.py)
    "tidb_mem_quota_query": 0,
    # memory-adaptive execution (ops/spill.py): crossing spill_ratio x
    # quota flips join/agg/sort/topn into partitioned spill mode instead
    # of dying at the quota (0 disables the soft watermark); partitions
    # 0 = auto fan-out from the planner's estRows; max_depth bounds the
    # recursive-repartition ladder before the typed 8175 last resort
    "tidb_mem_quota_spill_ratio": 0.8,
    "tidb_spill_partitions": 0,
    "tidb_spill_max_depth": 3,
    # seconds the backend stays pinned to CPU after a mid-statement
    # device loss (ops/degrade.py runtime degradation)
    "tidb_device_cooldown": 30,
    # failpoint arming spec (fail.configure): "name=error(msg);..." —
    # process-global, empty string disarms everything
    "tidb_failpoints": "",
    # ---- durability (kv/wal.py; active only on a data_dir store) ------
    # WAL fsync policy, applied to the live store at SET time:
    # 'strict' = fsync before acking every commit-class record,
    # 'relaxed' = group commit (one fsync per GROUP_COMMIT_S window;
    # a POWER loss can lose acks inside the open window, a SIGKILL
    # cannot), 'off' = never fsync the log (checkpoints still fsync)
    "tidb_wal_fsync": "relaxed",
    # GC retention in SECONDS: versions older than this are collectable
    # by the domain owner loop's safepoint trigger (storage.maybe_run_gc,
    # self-paced to one pass per half-retention).  0 = GC disabled —
    # mvcc.gc() is never invoked, today's unbounded-history behavior
    "tidb_gc_safepoint": 0,
    # stats-driven auto-prewarm (session/prewarm.py PrewarmWorker, wired
    # into the server lifecycle): a background worker ranks the top-K
    # (digest, bucket) families from statements_summary by exec count x
    # observed miss cost and AOT-compiles their programs off the query
    # path.  The worker reads the GLOBAL scope (SET GLOBAL) each cycle.
    "tidb_auto_prewarm": 1,
    "tidb_auto_prewarm_top_k": 8,
    # seconds between worker cycles (first cycle fires one interval
    # after server start, never at startup)
    "tidb_auto_prewarm_interval": 60,
    # per-cycle warming wall budget in MILLISECONDS (0 = unlimited):
    # once spent, remaining candidates wait for the next cycle
    "tidb_auto_prewarm_budget_ms": 60000,
    # seconds a warmed (or failed) family is exempt from re-warming
    "tidb_auto_prewarm_cooldown": 600,
    # ---- serving layer (server/pool.py + server/admission.py; the
    # GLOBAL scope is what the server reads — SET GLOBAL to tune) -------
    # accept-loop connection cap: further connects get MySQL 1040
    # "Too many connections" before the handshake (0 = unlimited)
    "tidb_max_server_connections": 0,
    # wire front end for NEW connections (server/server.py reads it per
    # accept): 'legacy' = thread-per-connection, 'aio' = the event-loop
    # front end (server/aio.py) parking idle connections as registered
    # file objects — the C10k path.  Flippable mid-server; established
    # connections keep the mode they were accepted under
    "tidb_wire_mode": "legacy",
    # event-loop thread count for the aio front end (>= 1; read once at
    # front-end start — the first aio-mode accept)
    "tidb_aio_loops": 1,
    # slowloris guard: a connection stalled mid-handshake or mid-frame
    # (partial packet buffered) longer than this is closed (0 = off).
    # Parked IDLE connections — no partial frame — never time out
    "tidb_aio_frame_timeout_ms": 10000,
    # statement-execution pool: worker-thread count for pooled
    # statements (SELECT/INSERT/DELETE over the wire; 0 = pooling off,
    # statements run on their connection thread unbounded)
    "tidb_stmt_pool_size": 4,
    # bounded admission queue in front of the pool; a full queue sheds
    # load with MySQL 1041 + retry hint (server/admission.py; halved
    # while device-loss cooldown pins the backend to CPU)
    "tidb_stmt_pool_queue_depth": 64,
    # aggregate in-flight statement memory (sum of running statements'
    # MemTracker bytes) above which admission sheds new statements
    # (0 = off)
    "tidb_admission_mem_limit": 0,
    # cross-query micro-batching (ops/batching.py): max same-digest
    # statements coalesced into one device round (<2 disables), and how
    # long a worker tops up a forming batch from the queue
    "tidb_batch_max_size": 16,
    "tidb_batch_window_ms": 2,
    # stacked-params batch execution: max parked members one
    # vmap-batched dispatch may carry (rounds stack on a leading batch
    # axis padded to a power-of-two occupancy bucket; 0/1 = legacy
    # back-to-back ParamTable replays)
    "tidb_batch_stack_max": 16,
    # ---- time-series metrics ring (obs/tsring.py; GLOBAL scope — the
    # server's background sampler re-reads both every tick) -------------
    # seconds between ring samples (0 pauses the sampler without
    # stopping it)
    "tidb_metrics_interval": 5,
    # seconds of sample history information_schema.metrics_history /
    # metrics_summary retain; shrinking it trims the ring immediately
    "tidb_metrics_retention": 900,
    # ---- device-time truth (ops/profiler.py + obs/inspect.py; both are
    # process-global module state applied at SET time, like
    # tidb_compile_cache_dir) --------------------------------------------
    # fraction of device dispatches the sampling profiler closes with
    # block_until_ready to record MEASURED device busy time (0 = off and
    # byte-identical; 1 = every dispatch — diagnosis, not steady state)
    "tidb_device_profile_rate": 0,
    # p99 latency objective in MILLISECONDS the slo-burn inspection rule
    # judges the exec-phase histogram against (0 = no SLO armed)
    "tidb_slo_p99_ms": 0,
    # ---- continuous host profiler (obs/conprof.py; GLOBAL scope — the
    # server's background stack sampler re-reads all four every tick) --
    # sampling rate in Hz (0 = off; the sampler's own overhead backoff
    # may stretch the effective period under load)
    "tidb_conprof_rate": 10,
    # seconds per aggregation window of
    # information_schema.continuous_profiling (stmtsummary-style
    # rotation into bounded history)
    "tidb_conprof_window": 60,
    # rotated windows retained
    "tidb_conprof_history": 15,
    # max distinct folded stacks per window; beyond it the
    # least-recently-seen stack folds into the '(evicted)' tombstone
    "tidb_conprof_max_stacks": 512,
    # ---- continuous heap profiler (obs/memprof.py; GLOBAL scope — the
    # server's background memory sampler re-reads all four every tick) --
    # sampling rate in Hz (0 = off AND tracemalloc stopped — tracing
    # taxes every allocation, so off must mean off; a tracemalloc
    # snapshot is far pricier than a stack walk, hence the low default)
    "tidb_memprof_rate": 1,
    # seconds per aggregation window of the /debug/heap site store
    "tidb_memprof_window": 60,
    # rotated windows retained
    "tidb_memprof_history": 15,
    # max distinct allocation sites per window; beyond it the
    # least-recently-seen site folds into the '(evicted)' tombstone
    "tidb_memprof_max_sites": 256,
    # ---- flight recorder (obs/flight.py; GLOBAL scope — the server's
    # background segment writer re-reads both every tick; inert without
    # a data dir) --------------------------------------------------------
    # seconds between durable flight segments (0 pauses the writer
    # without stopping it)
    "tidb_flight_interval": 10,
    # retention bound: newest N segments kept per incarnation (in-file
    # compaction) and newest N incarnation files kept in the flight dir
    "tidb_flight_retention": 8,
}


@dataclass
class ResultSet:
    columns: List[str]
    rows: List[list]
    fields: Optional[list] = None  # FieldType per column (wire protocol)

    def __iter__(self):
        return iter(self.rows)

    def __len__(self):
        return len(self.rows)


class SessionError(Exception):
    """Statement-level error with an optional MySQL wire code (the
    server maps ``mysql_code``/``sqlstate`` into its ERR packet;
    1105 = generic server error)."""

    def __init__(self, msg: str, mysql_code: int = 1105,
                 sqlstate: str = "HY000"):
        super().__init__(msg)
        self.mysql_code = mysql_code
        self.sqlstate = sqlstate


SLOW_QUERY_THRESHOLD_MS = 300.0  # fallback when the sysvar is unset/bad


class Session:
    """reference: session/session.go session struct."""

    def __init__(self, storage, current_db: str = "", domain=None):
        """`domain`: a per-server schema cache (domain.Domain) with its
        own lease + owner manager; None = the storage's shared embedded
        domain (lease 0 — always fresh; reference: sessions hold a Domain
        via domainMap)."""
        self.storage = storage
        if domain is None:
            from ..domain import shared_domain
            domain = shared_domain(storage)
        self.domain = domain
        self.current_db = current_db
        # session scope initialized from defaults overlaid with globals
        # (reference: session.go loadCommonGlobalVariablesIfNeeded); the
        # global scope lives ON the storage object — id(storage) keys
        # collide when CPython reuses a freed address
        self.sysvars: Dict[str, Datum] = dict(DEFAULT_SYSVARS)
        self.sysvars.update(getattr(storage, "_global_vars", {}))
        self.uservars: Dict[str, Datum] = {}
        self._txn = None
        self._explicit_txn = False
        self._pinned_is: Optional[InfoSchema] = None
        self.ddl = domain.ddl()
        self.last_affected = 0
        # (Level, Code, Message) triples of the LAST statement
        # (reference: StatementContext warnings, SHOW WARNINGS/ERRORS)
        self.last_warnings: List[tuple] = []
        # per-statement phase timings (reference: session.go DurationParse
        # :590 / DurationCompile :612 + slow-query logging).  parse_s is
        # the per-BATCH parse wall (reported once); "statements" carries
        # the per-statement phase list
        self.last_query_info: Dict[str, float] = {}
        # the last statement's observability scope (obs/context.QueryObs):
        # per-query device counters, per-operator RuntimeStats, span trace
        self.last_query_stats = None
        # live-statement state surfaced by information_schema.processlist:
        # stmt_running flips inside _execute_stmt; _stmt_mem is the
        # always-installed per-statement MemTracker (quota 0 = track only)
        self.stmt_running = False
        self._stmt_mem = None
        # the thread ident the current statement EXECUTES on (pool
        # worker / conn thread / embedded caller) — the continuous
        # profiler's statement-attribution key (obs/conprof.py): a
        # stack sample landing on this thread while stmt_running is
        # the statement's on-thread time
        self.stmt_thread_ident = 0
        # statement-pool admission state (server/pool.py): "queued" while
        # waiting for a worker, with the pending SQL for processlist
        self.stmt_state = ""
        self.pending_sql = ""
        # serving-path wait attribution handoff: the pool measures this
        # statement's queue/batch wait + admission verdict and deposits
        # it here right before invoking execute_stmt on a worker; the
        # statement scope consumes (and clears) it in _execute_one
        self.pending_wait = None
        # rendered EXPLAIN rows of the last planned statement — the
        # EXPLAIN FOR CONNECTION <id> payload (set before execution so a
        # live statement's plan is readable from another session)
        self.last_plan_rows = None
        # wire identity (the server fills this in after auth; embedded
        # sessions have no user)
        self.user = ""
        # internal sessions (auto-prewarm worker) execute real statements
        # but stay OUT of the observability fan-out (_finish_obs)
        self.internal = False
        # statement interruption (utils/interrupt.py): a process-unique
        # connection id (the KILL target / server thread id) + the guard
        # any thread may flip to abort the running statement
        self.conn_id = interrupt.register_session(self)
        self.guard = interrupt.StatementGuard(self.conn_id)
        self.killed = False  # plain KILL: server drops the conn after
        #                      the current command

    def _globals(self) -> Dict[str, Datum]:
        g = getattr(self.storage, "_global_vars", None)
        if g is None:
            g = self.storage._global_vars = {}
        return g

    # ---- schema cache (reference: domain.Reload via the Domain) --------
    def infoschema(self) -> InfoSchema:
        """Pinned per STATEMENT: every read within one statement — and
        the commit-time validator anchor — sees the same InfoSchema
        object even if the domain's background ticker reloads mid-flight
        (otherwise a plan built at version V could commit under an
        anchor captured at V+1, silently skipping index maintenance)."""
        if self._pinned_is is None:
            self._pinned_is = self.domain.info_schema()
        return self._pinned_is

    # ---- variables ------------------------------------------------------
    def get_sysvar(self, name: str, scope: str = "") -> Datum:
        if scope == "global":
            return self._globals().get(name, DEFAULT_SYSVARS.get(name))
        return self.sysvars.get(name, self._globals().get(
            name, DEFAULT_SYSVARS.get(name)))

    def get_uservar(self, name: str) -> Datum:
        return self.uservars.get(name)

    # ---- txn lifecycle (reference: session/txn.go TxnState) ------------
    def get_txn(self):
        if self._txn is None:
            self._txn = self.storage.begin()
            # schema validity re-check before the commit point (reference:
            # domain/schema_validator.go Check via 2pc.go:633): a DDL that
            # landed mid-transaction would make buffered writes miss index
            # maintenance, so the commit must abort and retry instead
            # anchor on the schema version this session PLANS with (the
            # domain cache may legitimately lag the store under its
            # lease; a stale-planned txn must fail the commit check)
            start_ver = self.infoschema().version
            storage = self.storage

            def schema_check(commit_ts):
                txn = storage.begin()
                try:
                    now_ver = Meta(txn).schema_version()
                finally:
                    txn.rollback()
                if now_ver != start_ver:
                    raise RetryableError(
                        "Information schema is changed during the "
                        "execution of the statement (schema version "
                        f"{start_ver} -> {now_ver})")
            self._txn.schema_check = schema_check
        return self._txn

    def in_txn(self) -> bool:
        return self._explicit_txn

    def commit_txn(self) -> None:
        if self._txn is not None:
            txn, self._txn = self._txn, None
            self._explicit_txn = False
            txn.commit()
            # flush live row-count deltas (reference: stats collector ->
            # mysql.stats_meta at commit); post-commit, non-transactional
            if txn.stats_delta:
                from ..statistics.table_stats import update_count_delta
                for tid, d in txn.stats_delta.items():
                    update_count_delta(self.storage, tid, d)

    def rollback_txn(self) -> None:
        if self._txn is not None:
            self._txn.rollback()
            self._txn = None
        self._explicit_txn = False

    def _finish_stmt(self, ok: bool) -> None:
        """Autocommit boundary (reference: session/tidb.go finishStmt):
        with autocommit=0 the implicit transaction stays open across
        statements until COMMIT/ROLLBACK, exactly like BEGIN."""
        if self._explicit_txn or not bool(self.get_sysvar("autocommit")):
            return  # statement-level rollback handled via checkpoints
        if ok:
            self.commit_txn()
        else:
            self.rollback_txn()

    # ---- entry -----------------------------------------------------------
    def execute(self, sql: str) -> List[Optional[ResultSet]]:
        t0 = time.perf_counter()
        stmts = parse(sql)
        t_parse = time.perf_counter() - t0
        out = []
        stmt_infos: List[Dict[str, float]] = []
        try:
            for i, s in enumerate(stmts):
                label = sql if len(stmts) == 1 else \
                    f"{sql[:200]} [stmt {i + 1}/{len(stmts)}]"
                try:
                    out.append(self._execute_one(
                        s, label,
                        parse_wall=t_parse if i == 0 else 0.0,
                        parse_t0=t0 if i == 0 else None,
                        n_stmts=len(stmts)))
                finally:
                    q = self.last_query_stats
                    if q is not None and q.info:
                        stmt_infos.append(q.info)
        finally:
            if stmt_infos:
                # batch scope throughout, so the fields ADD UP: total =
                # parse + sum(exec); plan is inside exec.  Per-statement
                # phases live in the "statements" list
                self.last_query_info = {
                    "parse_s": t_parse,
                    "plan_s": sum(x["plan_s"] for x in stmt_infos),
                    "exec_s": sum(x["exec_s"] for x in stmt_infos),
                    "total_s": t_parse + sum(x["exec_s"]
                                             for x in stmt_infos),
                    "statements": stmt_infos,
                }
        return out

    def execute_stmt(self, stmt: ast.StmtNode,
                     sql_text: str = "") -> Optional[ResultSet]:
        """One pre-parsed statement under the FULL observability
        lifecycle (QueryObs scope, statement-summary ingest, slow log,
        trace ring) — the server's COM_QUERY / COM_STMT_EXECUTE entry,
        so wire connections are first-class obs citizens exactly like
        :meth:`execute` callers."""
        return self._execute_one(stmt, sql_text or type(stmt).__name__)

    def _execute_one(self, s: ast.StmtNode, label: str,
                     parse_wall: float = 0.0,
                     parse_t0: Optional[float] = None,
                     n_stmts: int = 1) -> Optional[ResultSet]:
        from ..obs import context as obs_context
        qobs = obs_context.QueryObs(sql=label)
        if parse_t0 is not None:
            # TRUE per-batch parse wall, reported ONCE — not amortized
            # into every statement and re-added to each total_s
            qobs.tracer.add_complete("parse", parse_t0, parse_wall,
                                     args={"statements": n_stmts})
        tok = obs_context.activate(qobs)
        self.last_query_stats = qobs
        t1 = time.perf_counter()
        # serving-path wait attribution: consume the pool's measurement
        # (one statement each — cleared so a later non-pooled statement
        # on this session can't inherit it).  Waits predate this scope,
        # so they enter the trace as already-measured complete spans
        # ending where execution begins.
        wait, self.pending_wait = self.pending_wait, None
        queue_s = float(wait.get("queue_wait_s", 0.0)) if wait else 0.0
        batch_s = float(wait.get("batch_wait_s", 0.0)) if wait else 0.0
        if wait:
            qobs.admission_verdict = wait.get("admission_verdict", "")
            if queue_s > 0:
                qobs.tracer.add_complete(
                    "queue_wait", t1 - queue_s - batch_s, queue_s,
                    cat="serving",
                    args={"verdict": qobs.admission_verdict})
            if batch_s > 0:
                qobs.tracer.add_complete("batch_wait", t1 - batch_s,
                                         batch_s, cat="serving")
        self._plan_s = 0.0
        err = True
        parked = False
        n_rows = 0
        try:
            with obs_context.span("execute", kind=type(s).__name__):
                rs = self._execute_stmt(s)
            n_rows = len(rs.rows) if isinstance(rs, ResultSet) \
                else self.last_affected
            err = False
            return rs
        except Exception as e:
            # a batch-round collect leg parking at the dispatch boundary
            # (ops/batching.Parked) is control flow, not a statement: it
            # must stay invisible to statements_summary / slow log /
            # /metrics — the member's REPLAY execution reports instead
            from ..ops.batching import Parked
            parked = isinstance(e, Parked)
            raise
        finally:
            obs_context.deactivate(tok)
            t_exec = time.perf_counter() - t1
            info = {"parse_s": parse_wall,
                    "plan_s": self._plan_s,
                    "exec_s": t_exec,
                    "total_s": parse_wall + t_exec}
            if wait:
                # waits stay OUTSIDE total_s (they are not execution);
                # statements_summary / slow_query / the "queue" phase
                # histogram attribute them separately
                info["queue_s"] = queue_s
                info["batch_s"] = batch_s
            qobs.info = info
            if not parked:
                self._finish_obs(s, qobs, info, err, n_rows)

    def _finish_obs(self, stmt: ast.StmtNode, qobs, info: Dict[str, float],
                    err: bool, rows_returned: int = 0) -> None:
        """Post-statement observability fan-out: query metrics, the trace
        ring (/debug/trace), the structured slow-query log, the
        statement-summary store (THE designated stmtsummary write hook —
        qlint OB403), and the bucket-prewarm feedback file.  Never
        raises.  INTERNAL sessions (the auto-prewarm worker) skip the
        fan-out entirely: their warming executions must not inflate
        statements_summary (the worker ranks from it — feeding its own
        runs back in would self-amplify), the slow log, or /metrics."""
        if self.internal:
            return
        from ..obs import metrics as obs_metrics
        from ..obs import slowlog as obs_slowlog
        from ..obs import stmtsummary
        from ..obs.feedback import maybe_emit
        from ..obs.trace import publish_trace
        try:
            kind = type(stmt).__name__.replace("Stmt", "").lower()
            thr = SLOW_QUERY_THRESHOLD_MS
            try:
                thr = float(self.get_sysvar("tidb_slow_log_threshold"))
            except (TypeError, ValueError):
                pass
            total_ms = info["total_s"] * 1e3
            # classify on the statement's OWN exec wall: the batch parse
            # time rides statement 0's total_s for reporting, but must
            # not tip statement 0 over the slow threshold on behalf of
            # the whole batch
            slow = info["exec_s"] * 1e3 > thr
            obs_metrics.observe_query(kind, info["exec_s"], slow=slow,
                                      error=err)
            # spans only: Chrome trace events derive from them on demand
            # (session.last_trace, tools/trace2json.py) — storing both
            # would double ring memory and /debug/trace payloads.
            # Bookkeeping statements (SET/USE/txn control) stay out of
            # the bounded ring: bench-style clients interleave them with
            # every query and would evict the traces /debug/trace is for
            if not isinstance(stmt, (ast.SetStmt, ast.UseStmt,
                                     ast.BeginStmt, ast.CommitStmt,
                                     ast.RollbackStmt, ast.EmptyStmt)):
                publish_trace({
                    "sql": qobs.sql[:512], "ts": qobs.started_at,
                    "total_ms": round(total_ms, 3), "error": err,
                    "spans": qobs.tracer.spans(),
                })
            # digest/sample from the statement's OWN source slice: a
            # batch label ("... [stmt 2/3]") would fall back to raw-text
            # normalization and never share a digest with the
            # standalone form
            src = getattr(stmt, "src", "") or qobs.sql
            sql_digest = digest_text = ""
            if not isinstance(stmt, ast.EmptyStmt):
                sql_digest, digest_text = stmtsummary.normalize(src)
            if slow:
                obs_slowlog.log_slow(obs_slowlog.build_record(
                    src, info, qobs, conn_id=self.conn_id,
                    db=self.current_db, success=not err,
                    sql_digest=sql_digest))
            if not isinstance(stmt, ast.EmptyStmt):
                try:
                    interval = float(self.get_sysvar(
                        "tidb_stmt_summary_refresh_interval") or 0)
                except (TypeError, ValueError):
                    interval = stmtsummary.DEFAULT_REFRESH_INTERVAL_S
                try:
                    max_count = int(self.get_sysvar(
                        "tidb_stmt_summary_max_stmt_count") or 0)
                except (TypeError, ValueError):
                    max_count = stmtsummary.DEFAULT_MAX_STMT_COUNT
                # the summary's MEM column is the statement's high-water
                # mark: live-set release accounting (chunk free / spill)
                # makes `consumed` drop as buffers go away
                mem = self._stmt_mem.peak \
                    if self._stmt_mem is not None else 0
                stmtsummary.ingest(
                    sql=src, sql_digest=sql_digest,
                    digest_text=digest_text, stmt_type=kind,
                    schema_name=self.current_db,
                    plan_digest=qobs.plan_digest, info=info,
                    device=qobs.device_totals(),
                    rows_returned=rows_returned, error=err, max_mem=mem,
                    plan_rows=qobs.plan_rows,
                    queued=qobs.admission_verdict == "queued",
                    refresh_interval_s=interval,
                    max_stmt_count=max_count)
            if not err:
                maybe_emit(qobs)
                # cross-query micro-batching learns family eligibility
                # here: statements that executed a params-compiled fused
                # dispatch (the `batchable` marker) make their digest a
                # coalescing candidate for the statement pool
                if sql_digest and qobs.device_totals().get("batchable"):
                    from ..ops.batching import note_family
                    note_family(sql_digest)
        except Exception:
            logging.getLogger("tinysql_tpu").warning(
                "observability fan-out failed", exc_info=True)

    def query(self, sql: str) -> ResultSet:
        out = [r for r in self.execute(sql) if r is not None]
        if len(out) != 1:
            raise SessionError(f"expected one result set, got {len(out)}")
        return out[0]

    def _execute_stmt(self, stmt: ast.StmtNode) -> Optional[ResultSet]:
        # arm the interruption guard + memory quota for THIS statement.
        # Done here (not in execute()) because the server's query/prepared
        # paths enter per statement through this method directly.
        deadline = None
        if isinstance(stmt, ast.SelectStmt):
            # max_execution_time applies to SELECT (MySQL semantics);
            # value is validated at SET time, so a bad stored value is a
            # config bug — fall back to no deadline instead of failing
            try:
                met = int(self.get_sysvar("max_execution_time") or 0)
            except (TypeError, ValueError):
                met = 0
            if met > 0:
                deadline = time.monotonic() + met / 1000.0
        self.guard.begin(deadline)
        gtok = interrupt.activate(self.guard)
        try:
            quota = int(self.get_sysvar("tidb_mem_quota_query") or 0)
        except (TypeError, ValueError):
            quota = 0
        try:
            ratio = float(
                self.get_sysvar("tidb_mem_quota_spill_ratio") or 0)
        except (TypeError, ValueError):
            ratio = 0.0
        # the tracker is ALWAYS installed (quota 0 = track, never abort):
        # information_schema.processlist reports its live byte count and
        # statements_summary its per-statement high-water mark.  The
        # soft watermark (ratio x quota) is where spill-capable
        # operators flip into partitioned mode (ops/spill.py)
        wm = int(quota * ratio) if quota > 0 and 0 < ratio <= 1 else 0
        self._stmt_mem = memory.MemTracker(quota if quota > 0 else 0,
                                           spill_watermark=wm)
        mtok = memory.activate(self._stmt_mem)
        self.stmt_thread_ident = threading.get_ident()
        self.stmt_running = True
        try:
            return self._execute_stmt_guarded(stmt)
        finally:
            self.stmt_running = False
            memory.deactivate(mtok)
            interrupt.deactivate(gtok)

    def _execute_stmt_guarded(self, stmt: ast.StmtNode) \
            -> Optional[ResultSet]:
        # statement-level rollback inside an explicit txn (reference:
        # session/txn.go StmtRollback): a failed statement undoes only its
        # own buffered writes, the transaction stays open
        in_txn_scope = self._explicit_txn or not bool(
            self.get_sysvar("autocommit"))
        cp = self._txn.checkpoint() if (in_txn_scope and self._txn) else None
        self.last_affected = 0  # per-statement affected-rows counter
        self._pinned_is = None  # each statement pins a fresh InfoSchema
        if not isinstance(stmt, ast.ShowStmt):
            # statement-scoped warning sink (reference StatementContext
            # warnings); SHOW itself must not clear what it reports
            self.last_warnings = []
        try:
            rs = self._dispatch(stmt)
            self._finish_stmt(ok=True)
            return rs
        except Exception as e:
            from ..ops.batching import Parked
            if not isinstance(stmt, ast.ShowStmt) \
                    and not isinstance(e, Parked):
                # SHOW ERRORS reports the failed statement (reference:
                # fetchShowWarnings(errors=true)); typed errors carry
                # their MySQL code (kill 1317, timeout 3024, OOM 8175),
                # 1105 = generic server error.  A batch-round park is
                # control flow, not a failure — no phantom warning
                self.last_warnings.append(
                    ("Error", getattr(e, "mysql_code", 1105), str(e)))
            if cp is not None and self._txn is not None:
                self._txn.restore(cp)
            elif in_txn_scope and self._txn is not None:
                # the failed statement itself lazily created the implicit
                # txn (cp is None), so its partial writes are the txn's
                # ONLY writes — roll the txn back, else a later COMMIT
                # would persist them (statement atomicity)
                self.rollback_txn()
            else:
                self._finish_stmt(ok=False)
            raise

    # ---- dispatch (reference: planbuilder.go:243 Build switch) ----------
    def _dispatch(self, stmt: ast.StmtNode) -> Optional[ResultSet]:
        if isinstance(stmt, ast.SelectStmt):
            return self._exec_select(stmt)
        if isinstance(stmt, ast.InsertStmt):
            return self._exec_insert(stmt)
        if isinstance(stmt, ast.DeleteStmt):
            return self._exec_delete(stmt)
        if isinstance(stmt, ast.UpdateStmt):
            return self._exec_update(stmt)
        if isinstance(stmt, (ast.CreateDatabaseStmt, ast.DropDatabaseStmt,
                             ast.CreateTableStmt, ast.DropTableStmt,
                             ast.CreateIndexStmt, ast.DropIndexStmt,
                             ast.AlterTableStmt, ast.TruncateTableStmt)):
            return self._exec_ddl(stmt)
        if isinstance(stmt, ast.UseStmt):
            from ..catalog.memtables import DB_NAME as INFO_SCHEMA_DB
            if (stmt.db.lower() != INFO_SCHEMA_DB
                    and not self.infoschema().schema_exists(stmt.db)):
                raise SessionError(f"Unknown database '{stmt.db}'")
            self.current_db = stmt.db
            return None
        if isinstance(stmt, ast.SetStmt):
            return self._exec_set(stmt)
        if isinstance(stmt, ast.BeginStmt):
            self.commit_txn()
            self.get_txn()  # hooks the schema validator on the fresh txn
            self._explicit_txn = True
            return None
        if isinstance(stmt, ast.CommitStmt):
            self.commit_txn()
            return None
        if isinstance(stmt, ast.RollbackStmt):
            self.rollback_txn()
            return None
        if isinstance(stmt, ast.ShowStmt):
            return self._exec_show(stmt)
        if isinstance(stmt, ast.ExplainStmt):
            return self._exec_explain(stmt)
        if isinstance(stmt, ast.TraceStmt):
            return self._exec_trace(stmt)
        if isinstance(stmt, ast.AnalyzeTableStmt):
            return self._exec_analyze(stmt)
        if isinstance(stmt, ast.AdminStmt):
            return self._exec_admin(stmt)
        if isinstance(stmt, ast.KillStmt):
            # KILL [QUERY] <id> (reference: executor/simple.go Kill +
            # server.Kill): resolves through the process-global session
            # registry, so embedded sessions and server connections are
            # both killable
            if not interrupt.kill(stmt.conn_id, stmt.query_only):
                raise SessionError(f"Unknown thread id: {stmt.conn_id}",
                                   mysql_code=1094)
            return None
        if isinstance(stmt, ast.EmptyStmt):
            return None
        raise SessionError(f"unsupported statement {type(stmt).__name__}")

    # ---- SELECT ---------------------------------------------------------
    def _use_tpu(self) -> bool:
        """The device switch, gated by the runtime degradation pin: a
        mid-statement device loss pins planning to the CPU tier for the
        tidb_device_cooldown window (ops/degrade.py)."""
        from ..ops import degrade
        return bool(self.get_sysvar("tidb_use_tpu")) \
            and not degrade.cpu_pinned()

    def _exec_select(self, stmt: ast.SelectStmt) -> ResultSet:
        from ..obs import context as obs_context
        from ..ops import degrade
        qobs = obs_context.current()
        t0 = time.perf_counter()
        builder = PlanBuilder(self)
        with obs_context.span("plan"):
            logical = builder.build_select(stmt)
        columns = [c.name for c in logical.schema.columns]
        use_tpu = self._use_tpu()
        with obs_context.span("place", tpu=use_tpu):
            phys = self._optimize(logical, use_tpu)
        t_plan = time.perf_counter() - t0
        from ..planner.explain import explain_text, plan_digest
        # published BEFORE execution: a concurrently-running statement's
        # plan is readable via EXPLAIN FOR CONNECTION <id> / processlist
        self.last_plan_rows = explain_text(phys)
        if qobs is not None:
            qobs.plan_digest = plan_digest(phys)
            qobs.plan_rows = self.last_plan_rows
        try:
            rows = self._run_phys(phys, use_tpu, qobs)
        except Exception as e:
            # runtime device-loss degradation: a SELECT is read-only, so
            # one transparent CPU re-execution is safe; anything that is
            # not a device loss stays a loud statement error
            if not (use_tpu and degrade.is_device_loss(e)):
                raise
            rows = self._degraded_rerun(logical, qobs, e)
        # compile/plan vs run split surfaces in last_query_info (the
        # reference's DurationCompile analogue; exec_s wraps both)
        self._plan_s = t_plan
        return ResultSet(columns, rows,
                         [c.ret_type for c in logical.schema.columns])

    def _run_phys(self, phys, use_tpu: bool, qobs) -> List[list]:
        from ..obs.runtime_stats import instrument_tree
        ex = build_executor(phys, use_tpu=use_tpu)
        instrument_tree(ex, qobs)
        ex.open(ExecContext(self.get_txn(), self.sysvars,
                            self.infoschema(), self.storage))
        try:
            return ex.drain()
        finally:
            ex.close()

    def _degraded_rerun(self, logical, qobs, cause: Exception) \
            -> List[list]:
        """The accelerator died mid-SELECT: record the loss, pin the
        backend to CPU for the cooldown window, and re-execute this one
        statement on the CPU volcano path (reads only — writes never
        reach here; their executors surface the error)."""
        from ..obs import context as obs_context
        from ..ops import degrade
        try:
            cooldown = float(self.get_sysvar("tidb_device_cooldown") or 0)
        except (TypeError, ValueError):
            cooldown = degrade.DEFAULT_COOLDOWN_S
        degrade.record_loss(cooldown)
        degrade.record_degraded_statement()
        logging.getLogger("tinysql_tpu").warning(
            "device lost mid-statement (%s) — re-executing on CPU, "
            "backend pinned to CPU for %.0fs", cause, cooldown)
        self.add_warning("Warning", 1105,
                         f"device lost mid-statement ({cause}); "
                         "re-executed on the CPU path")
        # fresh memory tracker for the rerun: the dead TPU attempt's
        # allocations are not live, and double-counting them would turn
        # a transient device loss into a spurious quota abort
        mt = memory.current()
        mtok = memory.activate(memory.MemTracker(mt.quota)) \
            if mt is not None else None
        try:
            with obs_context.span("degraded-rerun"):
                phys = self._optimize(logical, False)
                return self._run_phys(phys, False, qobs)
        finally:
            if mtok is not None:
                memory.deactivate(mtok)

    def select_metadata(self, stmt) -> Optional[tuple]:
        """(column names, FieldTypes) of a SELECT WITHOUT executing it —
        COM_STMT_PREPARE result metadata (reference: prepare-time column
        info in the writeResultset protocol contract).  Builds the
        logical plan only and clears its own InfoSchema pin (a prepare
        must not leave later statements planning against a stale
        catalog)."""
        if not isinstance(stmt, ast.SelectStmt):
            return None
        try:
            builder = PlanBuilder(self)
            logical = builder.build_select(stmt)
            return ([c.name for c in logical.schema.columns],
                    [c.ret_type for c in logical.schema.columns])
        finally:
            self._pinned_is = None

    def _optimize(self, logical, use_tpu: bool):
        """Route between the two optimizer frameworks (reference:
        planner/optimize.go:29-56 EnableCascadesPlanner switch)."""
        min_rows = float(self.get_sysvar("tidb_tpu_min_rows") or 0)
        shards = 0
        if use_tpu and bool(self.get_sysvar("tidb_mesh_parallel")):
            # mesh size feeds the planner's broadcast-vs-shuffle join
            # cost compare (device.py _mesh_join_strategy)
            try:
                from ..ops import kernels
                shards = len(kernels.jax().devices())
            except Exception:
                shards = 0
        verify = bool(self.get_sysvar("tidb_qlint_verify"))
        if bool(self.get_sysvar("tidb_enable_cascades_planner")):
            from ..planner.cascades import find_best_plan
            phys = find_best_plan(logical, tpu=use_tpu,
                                  tpu_min_rows=min_rows,
                                  mesh_shards=shards)
            if verify:
                from ..analysis.plan_device import verify_plan
                verify_plan(phys)
            return phys
        return optimize(logical, tpu=use_tpu, tpu_min_rows=min_rows,
                        mesh_shards=shards, verify=verify)

    def _run_select_plan(self, stmt: ast.SelectStmt, txn) -> List[list]:
        builder = PlanBuilder(self)
        use_tpu = self._use_tpu()
        phys = self._optimize(builder.build_select(stmt), use_tpu)
        ex = build_executor(phys, use_tpu=use_tpu)
        ex.open(ExecContext(txn, self.sysvars, self.infoschema(),
                            self.storage))
        try:
            return ex.drain()
        finally:
            ex.close()

    def eval_const_expr(self, e: ast.ExprNode) -> Datum:
        rw = ExprRewriter(Schema([]), PlanBuilder(self))
        return rw.rewrite(e).eval([])

    # ---- INSERT / DELETE -------------------------------------------------
    def _exec_insert(self, stmt: ast.InsertStmt) -> None:
        db = stmt.table.db or self.current_db
        if not db:
            raise SessionError("No database selected")
        info = self.infoschema().table_by_name(db, stmt.table.name)
        self._ensure_writable(info)
        ex = InsertExec(self, stmt, info, db)
        self.last_affected = ex.execute(self.get_txn())
        return None

    def _ensure_writable(self, info) -> None:
        """Bulk-loaded tables exist only as a columnar replica; the
        first write statement must materialize the row store first or
        its commit invalidates the replica and drops every untouched
        row (columnar/store.py ensure_row_store)."""
        if self.storage is not None:
            from ..columnar.store import ensure_row_store
            ensure_row_store(self.storage, info)

    def _exec_delete(self, stmt: ast.DeleteStmt) -> None:
        builder = PlanBuilder(self)
        src = stmt.table
        ds = builder._build_table_source(src)
        info = ds.table_info
        self._ensure_writable(info)
        handle_col = ExprColumn(new_int_type(), name=HANDLE_COL_NAME,
                                table=ds.alias)
        ds.schema = Schema(ds.schema.columns + [handle_col])
        plan = self._where_plan(builder, ds, stmt.where)
        use_tpu = self._use_tpu()
        phys = self._optimize(plan, use_tpu)
        txn = self.get_txn()
        ex = build_executor(phys, use_tpu=use_tpu)
        ex.open(ExecContext(txn, self.sysvars, self.infoschema(),
                            self.storage))
        try:
            rows = ex.drain()
        finally:
            ex.close()
        dex = DeleteExec(self, info)
        self.last_affected = dex.execute(txn, rows)
        return None

    @staticmethod
    def _where_plan(builder, ds, where):
        """DML read plan for a WHERE over one table — the same
        decorrelation the SELECT front door runs (IN/EXISTS subquery
        conjuncts -> semi/anti joins; the join mirrors the scan schema,
        hidden handle included, so the write executors see full rows)."""
        if where is None:
            return ds
        from ..planner.decorrelate import apply_where_subqueries
        plan, residual = apply_where_subqueries(builder, ds, where)
        rw = ExprRewriter(plan.schema, builder)
        conds = []
        for conj in residual:
            conds.extend(split_cnf(rw.rewrite(conj)))
        if conds:
            plan = LogicalSelection(conds, plan)
        return plan

    def _exec_update(self, stmt: ast.UpdateStmt) -> None:
        """UPDATE t SET c = expr [...] WHERE ... — scan qualifying rows
        (same planned read path as DELETE, hidden handle included), then
        read-modify-write through the row store so the 2PC
        prewrite/commit machinery (and its failpoints/chaos matrix)
        covers the statement unchanged."""
        from ..executor.write import UpdateExec
        builder = PlanBuilder(self)
        ds = builder._build_table_source(stmt.table)
        info = ds.table_info
        self._ensure_writable(info)
        handle_col = ExprColumn(new_int_type(), name=HANDLE_COL_NAME,
                                table=ds.alias)
        ds.schema = Schema(ds.schema.columns + [handle_col])
        scan_schema = ds.schema
        plan = self._where_plan(builder, ds, stmt.where)
        # bind SET targets/expressions against the scan schema BEFORE
        # optimization prunes it (rows arrive in full-schema order)
        rw = ExprRewriter(scan_schema, builder)
        assigns = []
        cols_by_name = {c.name.lower(): c for c in info.public_columns()}
        # the only legal SET-target qualifier is the table's visible
        # name in this statement (the alias when one is set — MySQL
        # rejects the base name once aliased)
        visible = (stmt.table.as_name or stmt.table.source.name).lower()
        for a in stmt.assignments:
            q = (a.column.table or "").lower()
            ci = cols_by_name.get(a.column.name.lower())
            if ci is None or (q and q != visible):
                bad = f"{q}.{a.column.name}" if q else a.column.name
                raise SessionError(
                    f"Unknown column '{bad}' in 'field list'")
            expr = rw.rewrite(a.expr).resolve_indices(scan_schema)
            assigns.append((ci, expr))
        use_tpu = self._use_tpu()
        phys = self._optimize(plan, use_tpu)
        txn = self.get_txn()
        ex = build_executor(phys, use_tpu=use_tpu)
        ex.open(ExecContext(txn, self.sysvars, self.infoschema(),
                            self.storage))
        try:
            rows = ex.drain()
        finally:
            ex.close()
        uex = UpdateExec(self, info, assigns)
        self.last_affected = uex.execute(txn, rows)
        return None

    def add_warning(self, level: str, code: int, msg: str) -> None:
        self.last_warnings.append((level, code, msg))

    # ---- DDL (implicit commit, reference: session commits before DDL) ---
    def _exec_ddl(self, stmt) -> None:
        self.commit_txn()
        d = self.ddl
        # IF [NOT] EXISTS Notes ride the DDL layer's AUTHORITATIVE
        # existence checks (the ops return True on a no-op), recorded
        # only AFTER the op succeeded — a failing statement must not
        # leave success-path warnings behind
        if isinstance(stmt, ast.CreateDatabaseStmt):
            if d.create_database(stmt.name, stmt.if_not_exists):
                self.add_warning("Note", 1007,
                                 f"Can't create database '{stmt.name}'; "
                                 "database exists")
        elif isinstance(stmt, ast.DropDatabaseStmt):
            if d.drop_database(stmt.name, stmt.if_exists):
                self.add_warning("Note", 1008,
                                 f"Can't drop database '{stmt.name}'; "
                                 "database doesn't exist")
            if self.current_db.lower() == stmt.name.lower():
                self.current_db = ""
        elif isinstance(stmt, ast.CreateTableStmt):
            db = stmt.table.db or self.current_db
            if not db:
                raise SessionError("No database selected")
            if d.create_table(db, stmt):
                self.add_warning("Note", 1050,
                                 f"Table '{stmt.table.name}' already "
                                 "exists")
        elif isinstance(stmt, ast.DropTableStmt):
            for tn in stmt.tables:
                db = tn.db or self.current_db
                if d.drop_table(db, tn.name, stmt.if_exists):
                    self.add_warning("Note", 1051,
                                     f"Unknown table '{db}.{tn.name}'")
        elif isinstance(stmt, ast.CreateIndexStmt):
            d.add_index(stmt.table.db or self.current_db, stmt.table.name,
                        stmt.index_name, stmt.columns, stmt.unique)
        elif isinstance(stmt, ast.DropIndexStmt):
            d.drop_index(stmt.table.db or self.current_db, stmt.table.name,
                         stmt.index_name)
        elif isinstance(stmt, ast.TruncateTableStmt):
            d.truncate_table(stmt.table.db or self.current_db,
                             stmt.table.name)
        elif isinstance(stmt, ast.AlterTableStmt):
            db = stmt.table.db or self.current_db
            for spec in stmt.specs:
                if spec.tp == "add_column":
                    d.add_column(db, stmt.table.name, spec.column)
                elif spec.tp == "drop_column":
                    d.drop_column(db, stmt.table.name, spec.name)
                elif spec.tp == "add_index":
                    cons = spec.constraint
                    d.add_index(db, stmt.table.name, cons.name,
                                list(cons.columns), cons.tp == "unique")
                elif spec.tp == "drop_index":
                    d.drop_index(db, stmt.table.name, spec.name)
        self._pinned_is = None  # next statement re-pins post-DDL schema
        self.domain.reload()
        return None

    # ---- SET -------------------------------------------------------------
    #: sysvars that must be non-negative integers, validated AT SET TIME
    #: (reference: variable sysvar type validation; a bad value must fail
    #: the SET, not silently disable the feature at read time)
    _UINT_SYSVARS = ("max_execution_time", "tidb_mem_quota_query",
                     "tidb_stmt_summary_refresh_interval",
                     "tidb_stmt_summary_max_stmt_count",
                     "tidb_auto_prewarm_top_k",
                     "tidb_auto_prewarm_interval",
                     "tidb_auto_prewarm_budget_ms",
                     "tidb_auto_prewarm_cooldown",
                     "tidb_max_server_connections",
                     "tidb_aio_loops",
                     "tidb_aio_frame_timeout_ms",
                     "tidb_stmt_pool_size",
                     "tidb_stmt_pool_queue_depth",
                     "tidb_admission_mem_limit",
                     "tidb_batch_max_size",
                     "tidb_batch_window_ms",
                     "tidb_batch_stack_max",
                     "tidb_metrics_interval",
                     "tidb_metrics_retention",
                     "tidb_spill_partitions",
                     "tidb_spill_max_depth",
                     "tidb_slo_p99_ms",
                     "tidb_conprof_rate",
                     "tidb_conprof_window",
                     "tidb_conprof_history",
                     "tidb_conprof_max_stacks",
                     "tidb_memprof_rate",
                     "tidb_memprof_window",
                     "tidb_memprof_history",
                     "tidb_memprof_max_sites",
                     "tidb_flight_interval",
                     "tidb_flight_retention")

    @staticmethod
    def _validate_uint_sysvar(name: str, v: Datum) -> int:
        if isinstance(v, bool) or isinstance(v, float):
            # 1232: Incorrect argument type (floats are not valid here)
            raise SessionError(
                f"Incorrect argument type to variable '{name}'",
                mysql_code=1232, sqlstate="42000")
        if isinstance(v, str):
            try:
                v = int(v.strip())
            except ValueError:
                raise SessionError(
                    f"Incorrect argument type to variable '{name}'",
                    mysql_code=1232, sqlstate="42000")
        if not isinstance(v, int):
            raise SessionError(
                f"Incorrect argument type to variable '{name}'",
                mysql_code=1232, sqlstate="42000")
        if v < 0:
            raise SessionError(
                f"Variable '{name}' can't be set to the value of '{v}'",
                mysql_code=1231, sqlstate="42000")
        return v

    def _exec_set(self, stmt: ast.SetStmt) -> None:
        for scope, name, expr in stmt.assignments:
            v = self.eval_const_expr(expr)
            if scope == "user":
                self.uservars[name] = v
                continue
            if name in self._UINT_SYSVARS:
                v = self._validate_uint_sysvar(name, v)
            if name in ("tidb_mem_quota_spill_ratio",
                        "tidb_device_profile_rate"):
                # fractions validated to [0, 1] at SET time (spill
                # ratio: 0 disables the soft watermark; profile rate:
                # 0 disables dispatch sampling entirely)
                try:
                    fv = float(v if not isinstance(v, bool) else "x")
                except (TypeError, ValueError):
                    raise SessionError(
                        f"Incorrect argument type to variable '{name}'",
                        mysql_code=1232, sqlstate="42000")
                if not 0.0 <= fv <= 1.0:
                    raise SessionError(
                        f"Variable '{name}' can't be set to the value "
                        f"of '{v}'", mysql_code=1231, sqlstate="42000")
                v = fv
            if name == "tidb_wire_mode":
                # enum validated at SET time (reference: sysvar type
                # validation): the accept loop reads this per connection
                # and must never see a junk mode
                mv = str(v).strip().lower() if v is not None else ""
                if mv not in ("legacy", "aio"):
                    raise SessionError(
                        f"Variable 'tidb_wire_mode' can't be set to the "
                        f"value of '{v}'", mysql_code=1231,
                        sqlstate="42000")
                v = mv
            if name == "tidb_wal_fsync":
                # enum validated at SET time, applied to the live WAL
                # immediately (no-op on a volatile store): the fsync
                # policy is a store property, not a per-session one
                pv = str(v).strip().lower() if v is not None else ""
                if pv not in ("off", "relaxed", "strict"):
                    raise SessionError(
                        f"Variable 'tidb_wal_fsync' can't be set to the "
                        f"value of '{v}'", mysql_code=1231,
                        sqlstate="42000")
                v = pv
            if name == "tidb_gc_safepoint":
                # retention seconds, numeric >= 0 (0 disables GC)
                try:
                    gv = float(v if not isinstance(v, bool) else "x")
                except (TypeError, ValueError):
                    raise SessionError(
                        f"Incorrect argument type to variable '{name}'",
                        mysql_code=1232, sqlstate="42000")
                if gv < 0:
                    raise SessionError(
                        f"Variable '{name}' can't be set to the value "
                        f"of '{v}'", mysql_code=1231, sqlstate="42000")
                v = gv
            if name == "tidb_failpoints":
                # validate + apply atomically BEFORE storing: a bad spec
                # must fail the SET and leave the armed set unchanged
                from .. import fail
                try:
                    fail.configure(str(v) if v else "")
                except ValueError as e:
                    raise SessionError(str(e), mysql_code=1231,
                                       sqlstate="42000")
            if scope == "global":
                self._globals()[name] = v
            else:
                self.sysvars[name] = v
            if name == "tidb_compile_cache_dir":
                # apply to the live jax config immediately: compiled
                # bucket programs from this point on persist under the
                # new directory (ops/kernels.py resolution chain)
                from ..ops import kernels
                kernels.set_compile_cache_dir(str(v) if v else "")
            elif name == "tidb_device_profile_rate":
                # the dispatch path is process-global: apply immediately
                # (ops/profiler.py owns the sampling decision)
                from ..ops import profiler
                profiler.set_rate(float(v))
            elif name == "tidb_slo_p99_ms":
                # arm the slo-burn inspection rule + the `slo` ring
                # source (obs/inspect.py owns the objective state)
                from ..obs import inspect as obs_inspect
                obs_inspect.set_slo_p99_ms(float(v))
            elif name == "tidb_wal_fsync":
                wal = getattr(getattr(self.storage, "mvcc", None),
                              "wal", None)
                if wal is not None:
                    wal.set_fsync_policy(str(v))
        return None

    # ---- SHOW (reference: executor/show.go) ------------------------------
    def _exec_show(self, stmt: ast.ShowStmt) -> ResultSet:
        from ..expression import like_to_regex
        pat = like_to_regex(stmt.pattern) if stmt.pattern else None
        isc = self.infoschema()
        if stmt.tp == "databases":
            names = sorted(d.name for d in isc.all_schemas())
            rows = [[n] for n in names if pat is None or pat.match(n)]
            return ResultSet(["Database"], rows)
        if stmt.tp == "tables":
            db = stmt.db or self.current_db
            if not db:
                raise SessionError("No database selected")
            names = sorted(t.name for t in isc.schema_tables(db)
                           if t.state == SchemaState.PUBLIC)
            rows = [[n] for n in names if pat is None or pat.match(n)]
            return ResultSet([f"Tables_in_{db}"], rows)
        if stmt.tp == "columns":
            db = stmt.table.db or stmt.db or self.current_db
            t = isc.table_by_name(db, stmt.table.name)
            rows = []
            for c in t.public_columns():
                tp = c.ft.type_name()
                if c.ft.flen >= 0 and tp in ("varchar", "char"):
                    tp = f"{tp}({c.ft.flen})"
                null = "NO" if c.ft.not_null else "YES"
                key = ("PRI" if c.ft.flag & 0x2 else
                       ("UNI" if c.ft.flag & 0x4 else ""))
                rows.append([c.name, tp, null, key,
                             to_string(c.default), ""])
            return ResultSet(["Field", "Type", "Null", "Key", "Default",
                              "Extra"], rows)
        if stmt.tp == "create_table":
            db = stmt.table.db or self.current_db
            t = isc.table_by_name(db, stmt.table.name)
            return ResultSet(["Table", "Create Table"],
                             [[t.name, _show_create_table(t)]])
        if stmt.tp == "indexes":
            db = stmt.table.db or self.current_db
            t = isc.table_by_name(db, stmt.table.name)
            rows = []
            for idx in t.public_indices():
                for seq, ic in enumerate(idx.columns):
                    rows.append([t.name, 0 if idx.unique else 1, idx.name,
                                 seq + 1, ic.name])
            return ResultSet(["Table", "Non_unique", "Key_name",
                              "Seq_in_index", "Column_name"], rows)
        if stmt.tp == "variables":
            merged = dict(DEFAULT_SYSVARS)
            merged.update(self._globals())
            if not stmt.global_scope:
                merged.update(self.sysvars)
            rows = [[k, to_string(v)] for k, v in sorted(merged.items())
                    if pat is None or pat.match(k)]
            return ResultSet(["Variable_name", "Value"], rows)
        if stmt.tp == "create_database":
            from ..catalog.infoschema import DatabaseNotExist
            d = isc.schema_by_name(stmt.db)
            if d is None:
                raise DatabaseNotExist(stmt.db)
            return ResultSet(
                ["Database", "Create Database"],
                [[d.name, f"CREATE DATABASE `{d.name}` /*!40100 DEFAULT "
                          "CHARACTER SET utf8mb4 */"]])
        if stmt.tp == "processlist":
            # SHOW [FULL] PROCESSLIST (reference: executor/show.go
            # fetchShowProcessList) — same feed as the
            # information_schema.processlist mem-table
            from ..catalog.memtables import memtable_rows
            rows = []
            for (cid, user, db, cmd, time_ms, state, mem,
                 info, _digest) in memtable_rows(isc, "processlist"):
                info_out = info if stmt.full else info[:100]
                rows.append([cid, user, "", db, cmd, time_ms // 1000,
                             state, info_out, mem])
            return ResultSet(["Id", "User", "Host", "db", "Command",
                              "Time", "State", "Info", "Mem"], rows)
        if stmt.tp in ("warnings", "errors"):
            rows = [[lv, cd, msg] for lv, cd, msg in self.last_warnings
                    if stmt.tp == "warnings" or lv == "Error"]
            return ResultSet(["Level", "Code", "Message"], rows)
        raise SessionError(f"unsupported SHOW {stmt.tp}")

    # ---- EXPLAIN ---------------------------------------------------------
    def _exec_explain(self, stmt: ast.ExplainStmt) -> ResultSet:
        if stmt.for_conn is not None:
            # EXPLAIN FOR CONNECTION <id> (reference: common_plans.go
            # ExplainFor): render the target session's last placed plan
            # through the process-global registry — works for live
            # statements (the plan publishes before execution) and for
            # idle connections (their most recent plan)
            target = interrupt.lookup(stmt.for_conn)
            if target is None:
                raise SessionError(
                    f"Unknown thread id: {stmt.for_conn}",
                    mysql_code=1094)
            rows = getattr(target, "last_plan_rows", None)
            if not rows:
                raise SessionError(
                    f"connection {stmt.for_conn} has no recorded plan "
                    "(no SELECT/EXPLAIN executed yet)")
            return ResultSet(["id", "estRows", "task", "operator info"],
                             [list(r) for r in rows])
        if not isinstance(stmt.stmt, ast.SelectStmt):
            raise SessionError("EXPLAIN supports SELECT only for now")
        from ..obs import context as obs_context
        builder = PlanBuilder(self)
        use_tpu = self._use_tpu()
        with obs_context.span("plan"):
            logical = builder.build_select(stmt.stmt)
        with obs_context.span("place", tpu=use_tpu):
            phys = self._optimize(logical, use_tpu)
        if stmt.analyze:
            # EXPLAIN ANALYZE (reference: explain.go with RuntimeStats):
            # run the statement under the active obs scope, then render
            # the plan annotated with actRows / wall time / device
            # counters next to the estimates
            from ..obs.runtime_stats import instrument_tree
            from ..planner.explain import (EXPLAIN_ANALYZE_COLUMNS,
                                           explain_analyze_text,
                                           explain_text, plan_digest)
            self.last_plan_rows = explain_text(phys)
            qobs = obs_context.current()
            if qobs is not None:
                qobs.plan_digest = plan_digest(phys)
                qobs.plan_rows = self.last_plan_rows
            ex = build_executor(phys, use_tpu=use_tpu)
            instrument_tree(ex, qobs)
            ex.open(ExecContext(self.get_txn(), self.sysvars,
                                self.infoschema(), self.storage))
            try:
                ex.drain()
            finally:
                ex.close()
            return ResultSet(list(EXPLAIN_ANALYZE_COLUMNS),
                             explain_analyze_text(phys, qobs))
        from ..planner.explain import explain_text
        rows = explain_text(phys)
        self.last_plan_rows = rows
        return ResultSet(["id", "estRows", "task", "operator info"], rows)

    # ---- TRACE (reference: executor/trace.go) ---------------------------
    def _exec_trace(self, stmt: ast.TraceStmt) -> ResultSet:
        """TRACE <stmt>: execute the statement FOR REAL inside the
        current observability scope (the span tracer obs/trace.py was
        already recording everything a render needs), then return the
        span tree as rows — span (depth-indented), parent, start offset
        + duration in µs, and the recording thread's serving role.  The
        traced statement's own resultset is discarded (the trace IS the
        result, TiDB semantics); its side effects are not."""
        from ..obs import context as obs_context
        from ..obs.trace import TRACE_COLUMNS, trace_rows
        if stmt.stmt is None or isinstance(stmt.stmt, ast.TraceStmt):
            raise SessionError("TRACE expects a statement")
        qobs = obs_context.current()
        before = len(qobs.tracer.spans()) if qobs is not None else 0
        # the traced statement gets its own execute span (the outer
        # TRACE's wrapper span is still open at render time, so this is
        # what roots the rendered tree)
        with obs_context.span("execute", kind=type(stmt.stmt).__name__):
            self._dispatch(stmt.stmt)
        if qobs is None:
            return ResultSet(list(TRACE_COLUMNS), [])
        # only the spans the traced statement recorded: a batch's
        # earlier statements (or the pool's wait spans) stay out
        return ResultSet(list(TRACE_COLUMNS),
                         trace_rows(qobs.tracer.spans()[before:]))

    @property
    def last_trace(self):
        """Chrome trace-event JSON of the last statement (load in
        chrome://tracing / Perfetto; tools/trace2json.py exports the
        ring)."""
        q = self.last_query_stats
        return q.tracer.chrome_trace(label=q.sql[:200]) \
            if q is not None else None

    # ---- ANALYZE (stats phase wires this up) ----------------------------
    def _exec_analyze(self, stmt: ast.AnalyzeTableStmt) -> None:
        from ..statistics.analyze import analyze_table
        for tn in stmt.tables:
            db = tn.db or self.current_db
            info = self.infoschema().table_by_name(db, tn.name)
            analyze_table(self, info)
        return None

    # ---- ADMIN -----------------------------------------------------------
    def _exec_admin(self, stmt: ast.AdminStmt) -> ResultSet:
        txn = self.storage.begin()
        m = Meta(txn)
        if stmt.tp in ("show_ddl", "show_ddl_jobs"):
            jobs = m.history_jobs()[-20:]
            queued = m._load_queue()
            txn.rollback()
            rows = []
            for j in reversed(queued):
                rows.append([j.id, j.tp.name, j.schema_id, j.table_id,
                             j.state.name, j.row_count, j.error or ""])
            for j in reversed(jobs):
                rows.append([j.id, j.tp.name, j.schema_id, j.table_id,
                             j.state.name, j.row_count, j.error or ""])
            return ResultSet(["JOB_ID", "TYPE", "SCHEMA_ID", "TABLE_ID",
                              "STATE", "ROW_COUNT", "ERROR"], rows)
        if stmt.tp == "check_table":
            txn.rollback()
            from ..executor.admin import check_table
            for tn in stmt.tables:
                db = tn.db or self.current_db
                info = self.infoschema().table_by_name(db, tn.name)
                check_table(self.storage, info)
            return ResultSet(["Result"], [["OK"]])
        txn.rollback()
        raise SessionError(f"unsupported ADMIN {stmt.tp}")


def _show_create_table(t: TableInfo) -> str:
    parts = []
    for c in t.public_columns():
        tp = c.ft.type_name()
        if c.ft.flen >= 0 and tp in ("varchar", "char"):
            tp = f"{tp}({c.ft.flen})"
        s = f"  `{c.name}` {tp}"
        if c.ft.is_unsigned:
            s += " unsigned"
        if c.ft.not_null:
            s += " NOT NULL"
        if c.default is not None:
            s += f" DEFAULT '{c.default}'"
        if c.ft.flag & 0x200:
            s += " AUTO_INCREMENT"
        parts.append(s)
    pk = t.get_pk_handle_col()
    if pk is not None:
        parts.append(f"  PRIMARY KEY (`{pk.name}`)")
    for idx in t.public_indices():
        cols = ", ".join(f"`{ic.name}`" for ic in idx.columns)
        if idx.primary:
            parts.append(f"  PRIMARY KEY ({cols})")
        elif idx.unique:
            parts.append(f"  UNIQUE KEY `{idx.name}` ({cols})")
        else:
            parts.append(f"  KEY `{idx.name}` ({cols})")
    body = ",\n".join(parts)
    return f"CREATE TABLE `{t.name}` (\n{body}\n)"


def new_session(storage=None, db: str = "") -> Session:
    """Bootstrap entry (reference: session.BootstrapSession +
    CreateSession)."""
    if storage is None:
        storage = new_mock_storage()
    return Session(storage, db)
