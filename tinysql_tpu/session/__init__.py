"""Session / statement lifecycle (reference: session/)."""
from .session import Session, ResultSet, SessionError, new_session

__all__ = ["Session", "ResultSet", "SessionError", "new_session"]
