"""Stats-driven auto-prewarm: the serving-side answer to the cold start.

Every bench round shows the same shape — a warm TPC-H run beats sqlite
while the FIRST run of the same query pays 15s+ of XLA compilation.
Literal parameterization (ops/exprjit.ParamTable + shape-keyed program
caches) already makes one compiled program serve an entire
normalized-SQL digest family; this module spends idle serving time
making sure those family programs exist BEFORE the next query needs
them.

:class:`PrewarmWorker` is a background thread wired into the server
lifecycle (server/server.py).  Each cycle it:

1. reads ``statements_summary`` (obs/stmtsummary.py) and ranks digest
   families by ``exec_count x max observed exec wall`` — the max wall of
   a family is dominated by its cold run, so the product is an
   exec-count-weighted miss-cost proxy;
2. takes the top K (``tidb_auto_prewarm_top_k``), skips families inside
   their cooldown window (``tidb_auto_prewarm_cooldown`` seconds,
   applied after success AND failure) or whose last warm compiled
   NOTHING (already fully warm — re-executing their sample would be
   pure wasted query work; the skip lifts when the program registry is
   reset), and stops once the per-cycle wall budget
   (``tidb_auto_prewarm_budget_ms``) is spent;
3. warms each family inside ``progcache.prewarm_scope()``: AOT-compiles
   the plan-derived + feedback-observed shape buckets
   (kernels.prewarm_bucket) and executes the family's sample SQL once on
   an INTERNAL session — tracing the fused structural programs into the
   shared registry and the persistent XLA compile cache.  Internal
   sessions skip the observability fan-out, so the worker's own runs
   never feed the ranking they came from.

Provenance: programs built under a prewarm scope are marked in
ops/progcache; a later query-path hit on one counts as a
``prewarm_hits`` stat (per-query detail, bench, /metrics) — the compile
the worker saved that query.

The worker reads the GLOBAL sysvar scope every cycle, so
``SET GLOBAL tidb_auto_prewarm = 0`` takes effect without a restart.
``tools/warm.py`` shares :func:`plan_buckets`; the CLI remains the
manual/offline form of the same warming.
"""
from __future__ import annotations

import logging
import os
import threading
import time
from typing import Dict, List, Optional

from .. import fail

log = logging.getLogger("tinysql_tpu.prewarm")

#: worker counters for /metrics (tinysql_prewarm_*) and /debug/prewarm
PREWARM_STATS: Dict[str, int] = {
    "cycles": 0, "families_warmed": 0, "bucket_programs": 0,
    "stacked_programs": 0,
    "errors": 0, "skipped_cooldown": 0, "skipped_budget": 0,
    "skipped_satisfied": 0,
}
_STATS_MU = threading.Lock()


def _bump(key: str, n: int = 1) -> None:
    with _STATS_MU:
        PREWARM_STATS[key] = PREWARM_STATS.get(key, 0) + n


def stats_snapshot() -> Dict[str, int]:
    with _STATS_MU:
        return dict(PREWARM_STATS)


def reset_stats() -> None:
    """Tests only."""
    with _STATS_MU:
        for k in PREWARM_STATS:
            PREWARM_STATS[k] = 0


def plan_buckets(session, sql: str) -> set:
    """Plan one statement (parse -> logical -> placed physical, no
    execution) and return its estimated shape buckets.  Shared by the
    worker and tools/warm.py; warming must never fail the caller."""
    from ..parser import parse
    from ..planner.builder import PlanBuilder
    from ..planner.buckets import bucket_estimates
    try:
        phys = session._optimize(
            PlanBuilder(session).build_select(parse(sql)[0]), True)
        return bucket_estimates(phys, session.sysvars)
    except Exception:
        return set()
    finally:
        session._pinned_is = None


def rank_candidates(records: List[dict], top_k: int) -> List[dict]:
    """Rank statement-summary records (stmtsummary.snapshot() dicts) into
    the top-K prewarm candidates: SELECT families with a replayable
    sample, scored by ``exec_count x max exec ms`` (the family's max
    wall is dominated by its cold run — an exec-weighted miss-cost
    proxy).  The eviction tombstone and bookkeeping statements never
    qualify."""
    from ..obs.stmtsummary import EVICTED_DIGEST
    scored = []
    for r in records:
        if r.get("digest") == EVICTED_DIGEST:
            continue
        if (r.get("stmt_type") or "").lower() != "select":
            continue
        sql = r.get("sample_sql") or ""
        if not sql:
            continue
        count = int(r.get("exec_count", 0) or 0)
        max_exec_ms = float((r.get("max_ms") or {}).get("exec", 0.0))
        scored.append((count * max(max_exec_ms, 1.0), r))
    scored.sort(key=lambda t: -t[0])
    return [r for _, r in scored[:max(int(top_k), 0)]]


class PrewarmWorker:
    """Background family warmer owned by the server (one per process is
    the intended shape; tests drive :meth:`run_cycle` directly)."""

    def __init__(self, storage, domain=None):
        self.storage = storage
        self.domain = domain
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._session = None
        #: family key -> monotonic timestamp of the last warm attempt
        self._last_warm: Dict[tuple, float] = {}
        #: families whose last warm compiled NOTHING, mapped to the
        #: program-registry size observed then: re-executing their sample
        #: SQL would be pure wasted query work, so they are skipped until
        #: the registry shrinks (progcache.clear — a fresh cache)
        self._satisfied: Dict[tuple, int] = {}
        self._mu = threading.Lock()

    # ---- sysvars (GLOBAL scope, re-read every cycle) --------------------
    def _sysvar(self, name: str):
        from .session import DEFAULT_SYSVARS
        g = getattr(self.storage, "_global_vars", None) or {}
        return g.get(name, DEFAULT_SYSVARS.get(name))

    def _int_sysvar(self, name: str, default: int = 0) -> int:
        try:
            return int(self._sysvar(name) or 0)
        except (TypeError, ValueError):
            return default

    def enabled(self) -> bool:
        try:
            return bool(int(self._sysvar("tidb_auto_prewarm") or 0))
        except (TypeError, ValueError):
            return False

    # ---- lifecycle ------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()  # a worker may be restarted after close()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="auto-prewarm")
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
        self._thread = None
        # sessions are weakref-registered (utils/interrupt): dropping the
        # reference retires the worker's conn id from the processlist.
        # Under _mu (qlint CC701): a close() racing a still-draining
        # cycle must not null the slot between _ensure_session's
        # None-check and its use — the worker would crash on a vanished
        # session instead of finishing its cycle
        with self._mu:
            self._session = None

    def _loop(self) -> None:
        # first cycle one full interval AFTER start: a cold server has an
        # empty summary anyway, and short-lived test servers never pay
        # for a worker cycle they don't want
        while True:
            interval = max(self._int_sysvar("tidb_auto_prewarm_interval",
                                            60), 1)
            if self._stop.wait(interval):
                return
            try:
                self.run_cycle()
            except Exception:
                # a broken cycle must never kill the worker thread
                _bump("errors")
                log.warning("prewarm cycle failed", exc_info=True)

    # ---- one cycle (tests call this directly) ---------------------------
    def run_cycle(self, now: Optional[float] = None) -> dict:
        """Rank -> cooldown/budget gate -> warm.  Returns a cycle report
        (also the /debug/prewarm payload shape)."""
        if not self.enabled():
            return {"enabled": False}
        from ..obs import stmtsummary
        top_k = self._int_sysvar("tidb_auto_prewarm_top_k", 8)
        budget_ms = self._int_sysvar("tidb_auto_prewarm_budget_ms", 0)
        cooldown_s = self._int_sysvar("tidb_auto_prewarm_cooldown", 0)
        now = time.monotonic() if now is None else now
        t0 = time.monotonic()
        report = {"enabled": True, "candidates": 0, "warmed": [],
                  "skipped_cooldown": 0, "skipped_satisfied": 0,
                  "skipped_budget": 0, "errors": 0}
        cands = rank_candidates(stmtsummary.snapshot(), top_k)
        report["candidates"] = len(cands)
        from ..ops import progcache
        for rec in cands:
            if self._stop.is_set():
                break
            spent_ms = (time.monotonic() - t0) * 1e3
            if budget_ms > 0 and spent_ms >= budget_ms:
                n_left = len(cands) - len(report["warmed"]) \
                    - report["skipped_cooldown"] \
                    - report["skipped_satisfied"] - report["errors"]
                _bump("skipped_budget", n_left)
                report["skipped_budget"] = n_left
                break
            fam = (rec.get("digest", ""), rec.get("plan_digest", ""))
            with self._mu:
                sat_size = self._satisfied.get(fam)
                if sat_size is not None:
                    # the registry only shrinks on clear(): while it has
                    # not, everything the family's sample would trace is
                    # still registered — re-executing it warms nothing
                    if progcache.size() >= sat_size:
                        _bump("skipped_satisfied")
                        report["skipped_satisfied"] += 1
                        continue
                    del self._satisfied[fam]  # cache was reset: re-warm
                last = self._last_warm.get(fam)
                if last is not None and cooldown_s > 0 \
                        and now - last < cooldown_s:
                    _bump("skipped_cooldown")
                    report["skipped_cooldown"] += 1
                    continue
                # claim the slot BEFORE warming: success and failure both
                # start the cooldown window (a family whose compile keeps
                # failing must not be retried every cycle)
                self._last_warm[fam] = now
            try:
                misses0 = progcache.stats_snapshot()["misses"]
                self._warm_family(rec)
                _bump("families_warmed")
                report["warmed"].append(rec.get("digest", ""))
                if progcache.stats_snapshot()["misses"] == misses0:
                    # nothing compiled: the family was already fully warm
                    with self._mu:
                        self._satisfied[fam] = progcache.size()
            except Exception as e:
                _bump("errors")
                report["errors"] += 1
                log.warning("prewarm of digest %s failed: %s",
                            rec.get("digest", ""), e)
        _bump("cycles")
        report["wall_ms"] = round((time.monotonic() - t0) * 1e3, 1)
        return report

    def _warm_family(self, rec: dict) -> None:
        """AOT-compile one digest family: plan-derived + feedback buckets
        through kernels.prewarm_bucket, then one execution of the sample
        SQL inside a prewarm scope (programs it builds are marked
        prewarm-seeded)."""
        fail.inject("prewarmCompileError")
        from ..ops import kernels, progcache
        from ..planner.buckets import merge_feedback
        s = self._ensure_session()
        schema = rec.get("schema") or ""
        if schema:
            s.execute(f"use `{schema}`")
        sql = rec["sample_sql"]
        with progcache.prewarm_scope():
            buckets = plan_buckets(s, sql)
            fb = os.environ.get("TINYSQL_STATS_FEEDBACK")
            if fb:
                merge_feedback(fb, into=buckets)
            for nb in sorted(buckets):
                _bump("bucket_programs", kernels.prewarm_bucket(nb))
            s.query(sql)
            # B-bucketed stacked variants of whatever batchable fused
            # programs the sample just traced (ops/batching.py stacked
            # dispatch leg): a storm's first multi-member round is then
            # a plain cache hit at every occupancy bucket up to
            # tidb_batch_stack_max
            stack_max = self._int_sysvar("tidb_batch_stack_max", 16)
            if stack_max >= 2:
                bs, b = [], 2
                while b <= kernels.occupancy_bucket(stack_max):
                    bs.append(b)
                    b <<= 1
                _bump("stacked_programs", kernels.prewarm_stacked(bs))

    def _ensure_session(self):
        from .session import DEFAULT_SYSVARS, Session
        # check-and-create under _mu, then work on the LOCAL reference:
        # a concurrent close() nulling self._session between the check
        # and the use was a crash (AttributeError on None) in the
        # middle of a warming cycle (qlint CC701)
        with self._mu:
            s = self._session
            if s is None:
                s = Session(self.storage, domain=self.domain)
                s.internal = True  # stay OUT of the obs fan-out (see
                #                    Session._finish_obs)
                self._session = s
        # re-overlay the GLOBAL scope every use: Session.__init__
        # snapshots globals once, but the worker lives for the server's
        # lifetime — a later SET GLOBAL (tidb_use_tpu=0, block rows,
        # pipeline depth, ...) must reach warming executions
        s.sysvars = dict(DEFAULT_SYSVARS)
        s.sysvars.update(getattr(self.storage, "_global_vars", None) or {})
        return s

    def snapshot(self) -> dict:
        """/debug/prewarm payload: process counters + per-family cooldown
        state."""
        with self._mu:
            families = {f"{d}/{p}": round(time.monotonic() - ts, 1)
                        for (d, p), ts in self._last_warm.items()}
        return {"enabled": self.enabled(), "stats": stats_snapshot(),
                "families_last_warmed_s_ago": families}
