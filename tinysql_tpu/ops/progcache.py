"""Shared compiled-program registry — the ``_PROJ_CACHE`` pattern,
hoisted into ONE keyed table.

Every device-program cache in the engine used to be an ad-hoc module
dict (``_PROJ_CACHE`` / ``_FILTER_CACHE`` in tpu_executors.py,
``_JIT_CACHE`` in devpipe.py, half a dozen ``*_CACHE`` tables in
kernels.py).  They all implemented the same two-line idiom and none of
them could answer the bench's question "did this query compile anything
or did it run warm?".  This registry replaces them:

- keys are NAMESPACED tuples of hashable scalars (first element a short
  domain string: ``"proj"``, ``"sort"``, ``"seg"``, ``"pipe"``, ...) so
  consumers can never collide (qlint TS105 applies to the key shapes);
- values are whatever the builder returns — usually a ``counted_jit``
  wrapper or a ``(fn, schema)`` pair for packed kernels;
- every lookup counts a hit or a miss; the bench exports the per-query
  delta (``progcache_hits`` / ``progcache_misses`` in kernels.STATS) as
  the in-process half of the compile-cache story (the on-disk half is
  jax's persistent compilation cache, kernels.set_compile_cache_dir);
- the prewarmer (tools/warm.py) seeds entries AOT through the same
  ``get`` path, so a prewarmed program is a plain hit at query time.

Thread-safe: lookups and publishes take the registry lock; builders run
OUTSIDE it (they may recurse into the registry while tracing).  A lost
build race is benign — ``setdefault`` keeps the first-published entry,
and both candidates dispatch the same XLA program.
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional

from ..obs import context as _obs

_mu = threading.Lock()
_REG: Dict[tuple, object] = {}
_MISS = object()

#: registry hit/miss counters, exported through kernels.stats_snapshot as
#: progcache_hits / progcache_misses.  The prewarm pair carries program
#: provenance: ``prewarm_seeded`` counts programs built inside a
#: prewarm_scope (the auto-prewarm worker / tools/warm.py compiling off
#: the query path), ``prewarm_hits`` counts query-path lookups that found
#: such a seeded program — the compiles the prewarmer saved real queries.
STATS = {"hits": 0, "misses": 0, "prewarm_seeded": 0, "prewarm_hits": 0}

#: keys whose entries were built inside a prewarm scope
_PREWARMED: set = set()

#: thread-local prewarm marker: the worker warms on its own thread, and
#: BlockPipeline stage threads it spawns inherit the obs context — but
#: progcache attribution only needs the directly-calling thread
_TLS = threading.local()


class prewarm_scope:
    """Mark this thread's registry builds as prewarm-seeded (reentrant)."""

    def __enter__(self):
        _TLS.depth = getattr(_TLS, "depth", 0) + 1
        return self

    def __exit__(self, *exc):
        _TLS.depth -= 1
        return False


def prewarming() -> bool:
    return getattr(_TLS, "depth", 0) > 0


def get(key: tuple, build: Callable[[], object]):
    """The one lookup path: return the entry for ``key``, building (and
    publishing) it on first sight.  ``build`` runs outside the lock."""
    warming = prewarming()
    prewarm_hit = False
    with _mu:
        ent = _REG.get(key, _MISS)
        if ent is not _MISS:
            STATS["hits"] += 1
            hit = True
            if not warming and key in _PREWARMED:
                STATS["prewarm_hits"] += 1
                prewarm_hit = True
        else:
            STATS["misses"] += 1
            hit = False
    # per-query attribution rides the obs scope (kernels.stats_snapshot
    # exports the global pair as progcache_hits/progcache_misses)
    _obs.record("progcache_hits" if hit else "progcache_misses", 1)
    if prewarm_hit:
        _obs.record("prewarm_hits", 1)
    if hit:
        return ent
    with _obs.span("compile", cat="device", key=str(key[0])):
        ent = build()
    with _mu:
        if warming and key not in _PREWARMED:
            _PREWARMED.add(key)
            STATS["prewarm_seeded"] += 1
        return _REG.setdefault(key, ent)


def peek(key: tuple):
    """Entry or None, without counting or building (introspection)."""
    with _mu:
        return _REG.get(key)


def keys(domain: Optional[str] = None) -> List[tuple]:
    """Registered keys, optionally filtered by their namespace tag."""
    with _mu:
        return [k for k in _REG
                if domain is None or (len(k) > 0 and k[0] == domain)]


def size() -> int:
    with _mu:
        return len(_REG)


def clear() -> None:
    """Drop every entry (tests; a backend reset invalidates programs)."""
    with _mu:
        _REG.clear()
        _PREWARMED.clear()


def stats_snapshot() -> dict:
    with _mu:
        return dict(STATS)
