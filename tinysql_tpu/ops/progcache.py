"""Shared compiled-program registry — the ``_PROJ_CACHE`` pattern,
hoisted into ONE keyed table — plus the per-program CATALOG behind
``information_schema.compiled_programs`` and ``/debug/programs``.

Every device-program cache in the engine used to be an ad-hoc module
dict (``_PROJ_CACHE`` / ``_FILTER_CACHE`` in tpu_executors.py,
``_JIT_CACHE`` in devpipe.py, half a dozen ``*_CACHE`` tables in
kernels.py).  They all implemented the same two-line idiom and none of
them could answer the bench's question "did this query compile anything
or did it run warm?".  This registry replaces them:

- keys are NAMESPACED tuples of hashable scalars (first element a short
  domain string: ``"proj"``, ``"sort"``, ``"seg"``, ``"pipe"``, ...) so
  consumers can never collide (qlint TS105 applies to the key shapes);
- values are whatever the builder returns — usually a ``counted_jit``
  wrapper or a ``(fn, schema)`` pair for packed kernels;
- every lookup counts a hit or a miss; the bench exports the per-query
  delta (``progcache_hits`` / ``progcache_misses`` in kernels.STATS) as
  the in-process half of the compile-cache story (the on-disk half is
  jax's persistent compilation cache, kernels.set_compile_cache_dir);
- the prewarmer (tools/warm.py) seeds entries AOT through the same
  ``get`` path, so a prewarmed program is a plain hit at query time.

The catalog (``_CATALOG``) carries one :class:`ProgramMeta` per key:
domain, compile wall, prewarm provenance, per-program dispatch count,
cumulative MEASURED device time (fed by the sampling profiler,
ops/profiler.py), the program's XLA cost-analysis flops/bytes, and the
plan digest of the last statement that dispatched it — the join key
against ``statements_summary``.  ``counted_jit`` learns its key through
the build-scope thread-local (:func:`building_key`) and reports
dispatches back through :func:`note_dispatch`, so the catalog needs no
cooperation from individual builders.

Thread-safe: lookups and publishes take the registry lock; builders run
OUTSIDE it (they may recurse into the registry while tracing).  A lost
build race is benign — ``setdefault`` keeps the first-published entry,
and both candidates dispatch the same XLA program.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from ..obs import context as _obs

_mu = threading.Lock()
_REG: Dict[tuple, object] = {}
_MISS = object()

#: registry hit/miss counters, exported through kernels.stats_snapshot as
#: progcache_hits / progcache_misses.  The prewarm pair carries program
#: provenance: ``prewarm_seeded`` counts programs built inside a
#: prewarm_scope (the auto-prewarm worker / tools/warm.py compiling off
#: the query path), ``prewarm_hits`` counts query-path lookups that found
#: such a seeded program — the compiles the prewarmer saved real queries.
#: ``compile_wall_s`` accrues every build's wall (INCLUSIVE of nested
#: builds a builder recurses into — same nesting the "compile" spans
#: show), the process half of the per-query ``compile_s`` attribution.
STATS = {"hits": 0, "misses": 0, "prewarm_seeded": 0, "prewarm_hits": 0,
         "compile_wall_s": 0.0}

#: keys whose entries were built inside a prewarm scope
_PREWARMED: set = set()

#: thread-local prewarm marker: the worker warms on its own thread, and
#: BlockPipeline stage threads it spawns inherit the obs context — but
#: progcache attribution only needs the directly-calling thread.  Also
#: carries the key currently being BUILT on this thread, so counted_jit
#: wrappers constructed inside a builder know their catalog identity.
_TLS = threading.local()


class prewarm_scope:
    """Mark this thread's registry builds as prewarm-seeded (reentrant)."""

    def __enter__(self):
        _TLS.depth = getattr(_TLS, "depth", 0) + 1
        return self

    def __exit__(self, *exc):
        _TLS.depth -= 1
        return False


def prewarming() -> bool:
    return getattr(_TLS, "depth", 0) > 0


def building_key() -> Optional[tuple]:
    """The registry key whose builder is running on THIS thread (None
    outside a build) — counted_jit captures it at construction time as
    the program's catalog identity."""
    return getattr(_TLS, "build_key", None)


class ProgramMeta:
    """One compiled program's catalog entry (compiled_programs row)."""

    __slots__ = ("key", "domain", "created_at", "compile_s", "prewarmed",
                 "dispatches", "device_s", "profiled_dispatches",
                 "flops", "bytes_accessed", "peak_temp_bytes",
                 "peak_arg_bytes", "peak_out_bytes", "plan_digest",
                 "last_used")

    def __init__(self, key: tuple):
        self.key = key
        self.domain = str(key[0]) if key else ""
        self.created_at = 0.0
        self.compile_s = 0.0
        self.prewarmed = False
        self.dispatches = 0
        self.device_s = 0.0
        self.profiled_dispatches = 0
        self.flops = 0.0
        self.bytes_accessed = 0.0
        # XLA memory_analysis of the compiled program (peak scratch /
        # operand / result bytes) — the static half of the HBM story,
        # fed by the pending-cost resolver (kernels.resolve_pending_costs)
        self.peak_temp_bytes = 0.0
        self.peak_arg_bytes = 0.0
        self.peak_out_bytes = 0.0
        self.plan_digest = ""
        self.last_used = 0.0

    def to_dict(self) -> dict:
        return {"domain": self.domain, "key": str(self.key)[:256],
                "created_at": self.created_at,
                "compile_ms": round(self.compile_s * 1e3, 3),
                "prewarmed": int(self.prewarmed),
                "dispatches": self.dispatches,
                "device_ms": round(self.device_s * 1e3, 3),
                "profiled_dispatches": self.profiled_dispatches,
                "flops": self.flops,
                "bytes_accessed": self.bytes_accessed,
                "peak_temp_bytes": self.peak_temp_bytes,
                "peak_arg_bytes": self.peak_arg_bytes,
                "peak_out_bytes": self.peak_out_bytes,
                "plan_digest": self.plan_digest,
                "last_used": self.last_used}


#: per-key ProgramMeta (guarded by the registry lock)
_CATALOG: Dict[tuple, ProgramMeta] = {}


def _meta_locked(key: tuple) -> ProgramMeta:
    # caller holds _mu
    meta = _CATALOG.get(key)
    if meta is None:
        meta = _CATALOG[key] = ProgramMeta(key)
    return meta


def get(key: tuple, build: Callable[[], object]):
    """The one lookup path: return the entry for ``key``, building (and
    publishing) it on first sight.  ``build`` runs outside the lock."""
    warming = prewarming()
    prewarm_hit = False
    with _mu:
        ent = _REG.get(key, _MISS)
        if ent is not _MISS:
            STATS["hits"] += 1
            hit = True
            if not warming and key in _PREWARMED:
                STATS["prewarm_hits"] += 1
                prewarm_hit = True
        else:
            STATS["misses"] += 1
            hit = False
    # per-query attribution rides the obs scope (kernels.stats_snapshot
    # exports the global pair as progcache_hits/progcache_misses)
    _obs.record("progcache_hits" if hit else "progcache_misses", 1)
    if prewarm_hit:
        _obs.record("prewarm_hits", 1)
    if hit:
        return ent
    prev_key = getattr(_TLS, "build_key", None)
    _TLS.build_key = key
    t0 = time.perf_counter()
    try:
        with _obs.span("compile", cat="device", key=str(key[0])):
            ent = build()
    finally:
        _TLS.build_key = prev_key
    wall = time.perf_counter() - t0
    # the per-query compile attribution (EXPLAIN ANALYZE `compile:` cell,
    # statements_summary sum_compile_ms); nested builds accrue inclusive
    # walls, exactly like their nested "compile" spans
    _obs.record("compile_s", wall)
    now = time.time()
    with _mu:
        STATS["compile_wall_s"] += wall
        if warming and key not in _PREWARMED:
            _PREWARMED.add(key)
            STATS["prewarm_seeded"] += 1
        meta = _meta_locked(key)
        meta.compile_s += wall
        meta.prewarmed = meta.prewarmed or warming
        if not meta.created_at:
            meta.created_at = now
        return _REG.setdefault(key, ent)


def note_dispatch(key: Optional[tuple], device_s: Optional[float] = None,
                  cost: Optional[tuple] = None) -> None:
    """One dispatch of the program built under ``key`` (called by
    kernels.counted_jit; ``key`` None = a jit wrapper constructed
    outside any registry build — nothing to catalog).  ``device_s``
    carries the profiler's measured wall on sampled dispatches; ``cost``
    the resolved XLA cost analysis ``(flops, bytes_accessed)`` of the
    dispatched (program, shape) — static per program, so the catalog
    stores the per-dispatch value, not an accumulation."""
    if key is None:
        return
    q = _obs.current()
    digest = q.plan_digest if q is not None else ""
    now = time.time()
    with _mu:
        meta = _meta_locked(key)
        meta.dispatches += 1
        meta.last_used = now
        if device_s is not None:
            meta.device_s += device_s
            meta.profiled_dispatches += 1
        # (0, 0) is also the over-cap / unresolvable SENTINEL from the
        # pending-cost queue — never let it clobber a real measurement
        # from a previously resolved shape of this program
        if cost is not None and (cost[0] or cost[1]):
            meta.flops, meta.bytes_accessed = cost
        if digest:
            meta.plan_digest = digest


def note_memory(key: Optional[tuple], temp_bytes: float, arg_bytes: float,
                out_bytes: float) -> None:
    """Fold a compiled program's XLA ``memory_analysis`` (peak temp /
    argument / output bytes) into its catalog entry — called by the
    pending-cost resolver alongside cost analysis.  Shapes of the same
    program keep the LARGEST footprint seen (the conservative number
    admission wants); all-zero reports (backends without the API) never
    clobber a real measurement."""
    if key is None or not (temp_bytes or arg_bytes or out_bytes):
        return
    with _mu:
        meta = _meta_locked(key)
        meta.peak_temp_bytes = max(meta.peak_temp_bytes, float(temp_bytes))
        meta.peak_arg_bytes = max(meta.peak_arg_bytes, float(arg_bytes))
        meta.peak_out_bytes = max(meta.peak_out_bytes, float(out_bytes))


def _census_registry_values():
    """HBM census walker: every registered program entry.  Wrapper
    functions keep their program state inside XLA (not as live arrays),
    so this category normally reads 0 — but a builder that publishes a
    (fn, device-constant) tuple is claimed here instead of leaking into
    the unattributed bucket."""
    with _mu:
        return list(_REG.values())


from ..obs import memprof as _memprof  # noqa: E402  (cycle-free: memprof
#                                        imports no ops module at top level)
_memprof.register_census_walker("progcache", _census_registry_values)


def peek(key: tuple):
    """Entry or None, without counting or building (introspection)."""
    with _mu:
        return _REG.get(key)


def keys(domain: Optional[str] = None) -> List[tuple]:
    """Registered keys, optionally filtered by their namespace tag."""
    with _mu:
        return [k for k in _REG
                if domain is None or (len(k) > 0 and k[0] == domain)]


def size() -> int:
    with _mu:
        return len(_REG)


def clear() -> None:
    """Drop every entry (tests; a backend reset invalidates programs)."""
    with _mu:
        _REG.clear()
        _PREWARMED.clear()
        _CATALOG.clear()


def stats_snapshot() -> dict:
    with _mu:
        return dict(STATS)


# ---- the catalog read surfaces -------------------------------------------

#: information_schema.compiled_programs column order — MUST match
#: catalog_rows (catalog/memtables.py builds FieldTypes from this)
CATALOG_COLUMNS = [
    ("domain", "str"), ("prog_key", "str"), ("created", "str"),
    ("compile_ms", "real"), ("prewarmed", "int"), ("dispatches", "int"),
    ("device_ms", "real"), ("profiled_dispatches", "int"),
    ("flops", "real"), ("bytes_accessed", "real"),
    ("plan_digest", "str"), ("last_used", "str"),
    ("peak_temp_bytes", "real"), ("peak_arg_bytes", "real"),
    ("peak_out_bytes", "real"),
]


def catalog_snapshot() -> List[dict]:
    """Dict-form catalog (the ``/debug/programs`` payload), dispatch
    count descending so the hottest programs lead."""
    with _mu:
        metas = [m.to_dict() for m in _CATALOG.values()]
    metas.sort(key=lambda m: (-m["dispatches"], m["domain"], m["key"]))
    return metas


def catalog_rows() -> List[list]:
    """The ``compiled_programs`` mem-table payload, in CATALOG_COLUMNS
    order."""
    from ..obs.stmtsummary import _ts
    out: List[list] = []
    for m in catalog_snapshot():
        out.append([
            m["domain"], m["key"],
            _ts(m["created_at"]) if m["created_at"] else "",
            float(m["compile_ms"]), int(m["prewarmed"]),
            int(m["dispatches"]), float(m["device_ms"]),
            int(m["profiled_dispatches"]), float(m["flops"]),
            float(m["bytes_accessed"]), m["plan_digest"],
            _ts(m["last_used"]) if m["last_used"] else "",
            float(m["peak_temp_bytes"]), float(m["peak_arg_bytes"]),
            float(m["peak_out_bytes"]),
        ])
    return out
