"""TPU relational kernels: sort-based group-aggregate, sort-merge equi-join
expansion, multi-key sort, top-k.

TPU-first redesign of the reference's goroutine operators (SURVEY §2.4
note): no pointer-chasing hash tables — grouping and join matching are
sort + segment primitives (`jnp.lexsort`, `jax.ops.segment_*`,
`searchsorted`), which XLA tiles onto the MXU/VPU.  All shapes are padded
to power-of-two buckets so each bucket compiles once (SURVEY §7 "dynamic
shapes vs XLA").

Every kernel takes a `valid` mask so padding rows are inert, and carries
per-column null masks with MySQL semantics (NULLs group together, NULLs
never equi-join, NULL sorts first ASC / last DESC).
"""
from __future__ import annotations

import os
import threading
import time

from functools import partial
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import profiler, progcache
from .. import fail
from ..obs import context as _obs

_jax = None


_probed = False


def ensure_live_backend(jax_mod=None, timeout: float = None,
                        force: bool = False) -> None:
    """First-touch backend liveness, at ENGINE level (not just bench.py):
    the runner image's sitecustomize pins jax_platforms="axon,cpu" in
    config — overriding a later JAX_PLATFORMS env var — and the first
    backend use then blocks on the TPU tunnel forever when the relay is
    down.  Two defenses, applied once per process before any backend
    init: (1) an explicit JAX_PLATFORMS env var wins over the pinned
    config; (2) otherwise, probe backend init in a subprocess with a
    timeout and pin "cpu" on failure so embedded sessions and the server
    never hang (VERDICT r1: the probe lived only in bench.py)."""
    global _probed
    if _probed:
        return
    _probed = True
    import logging
    import os
    import subprocess
    import sys
    if jax_mod is None:
        import jax as jax_mod
    want = os.environ.get("JAX_PLATFORMS")
    if want:
        try:
            jax_mod.config.update("jax_platforms", want)
        except Exception:
            pass
    try:
        plats = str(jax_mod.config.jax_platforms or "")
    except Exception:
        plats = ""
    effective = want or plats
    try:
        probe_fail = bool(fail.eval_point("backendProbeFail"))
    except Exception:
        # ANY armed action (return, error, ...) means "the probe failed":
        # the contract is pin-cpu-never-hang, not propagate
        probe_fail = True
    if probe_fail:
        # injected probe failure: behave exactly like an unreachable
        # backend — pin cpu, never hang
        logging.getLogger("tinysql_tpu").warning(
            "jax backend %r probe failed (injected) — pinning "
            "jax_platforms=cpu", effective or "<default>")
        try:
            jax_mod.config.update("jax_platforms", "cpu")
        except Exception:
            pass
        return
    names = [p.strip() for p in effective.split(",") if p.strip()]
    if not names or all(n == "cpu" for n in names):
        # nothing pinned to a device backend: plain auto-detect (cpu on
        # ordinary machines) — skip the subprocess probe entirely
        return
    if timeout is None:
        timeout = float(os.environ.get("TINYSQL_BACKEND_PROBE_TIMEOUT", "180"))
    # a recent successful probe of the SAME platform chain (sentinel next
    # to the persistent XLA cache) skips the duplicate backend init —
    # probe cost is per machine per TTL window, not per process
    import hashlib
    import time as time_mod
    ttl = float(os.environ.get("TINYSQL_BACKEND_PROBE_TTL", "600"))
    tag = hashlib.sha1(effective.encode()).hexdigest()[:12]
    sentinel = os.path.join(_cache_dir(), "probe_ok_" + tag)
    # failures are cached too (shorter TTL): while the tunnel is down one
    # machine pays ONE probe timeout, not one per process
    fail_sentinel = os.path.join(_cache_dir(), "probe_fail_" + tag)
    fail_ttl = float(os.environ.get("TINYSQL_BACKEND_PROBE_FAIL_TTL", "120"))

    def _fresh(path, window):
        try:
            return window > 0 and time_mod.time() - os.path.getmtime(path) < window
        except OSError:
            return False

    # NOTE: a fresh success sentinel means hang exposure is bounded by the
    # TTL window, not zero — callers that must NEVER block on a backend
    # that died since the last probe (bench.py emitting its JSON line)
    # pass force=True to re-probe unconditionally.
    if not force and _fresh(sentinel, ttl):
        return
    if not force and _fresh(fail_sentinel, fail_ttl):
        logging.getLogger("tinysql_tpu").warning(
            "jax backend %r recently probed unreachable (cached failure, "
            "TTL %ss) — pinning jax_platforms=cpu", effective, fail_ttl)
        try:
            jax_mod.config.update("jax_platforms", "cpu")
        except Exception:
            pass
        return
    # TINYSQL_BACKEND_PROBE_CMD override exists for tests (a command that
    # hangs simulates a dead tunnel without network surgery).  The default
    # probe re-pins the EFFECTIVE chain inside the child — the child's own
    # sitecustomize would otherwise re-pin the image default and probe the
    # wrong backend when JAX_PLATFORMS overrides it.
    cmd = os.environ.get(
        "TINYSQL_BACKEND_PROBE_CMD",
        "import os, jax; "
        "jax.config.update('jax_platforms', os.environ['TINYSQL_PROBE_PLATFORMS']); "
        "print(jax.devices()[0].platform)")
    env = dict(os.environ, TINYSQL_PROBE_PLATFORMS=effective)
    # bounded retry: a flapping tunnel gets TINYSQL_BACKEND_PROBE_RETRIES
    # attempts (bench sets >1) with a short wait between, so a transient
    # relay hiccup does not silently demote a whole bench run to cpu
    attempts = max(1, int(os.environ.get("TINYSQL_BACKEND_PROBE_RETRIES",
                                         "1")))
    wait = float(os.environ.get("TINYSQL_BACKEND_PROBE_RETRY_WAIT", "15"))
    ok = False
    for i in range(attempts):
        try:
            r = subprocess.run([sys.executable, "-c", cmd],
                               capture_output=True, text=True,
                               timeout=timeout, env=env)
            ok = r.returncode == 0
        except Exception:
            ok = False
        if ok:
            break
        if i + 1 < attempts:
            logging.getLogger("tinysql_tpu").warning(
                "jax backend %r probe attempt %d/%d failed — retrying "
                "in %.0fs", effective, i + 1, attempts, wait)
            time_mod.sleep(wait)  # qlint: disable=FP501 -- process-start probe retry; no Backoffer exists before a backend does
    def _touch(path):
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            with open(path, "w") as f:
                f.write(str(time_mod.time()))
        except OSError:
            pass

    if not ok:
        logging.getLogger("tinysql_tpu").warning(
            "jax backend %r unreachable (TPU tunnel down?) — "
            "pinning jax_platforms=cpu", effective)
        try:
            jax_mod.config.update("jax_platforms", "cpu")
        except Exception:
            pass
        _touch(fail_sentinel)
    else:
        _touch(sentinel)


# runtime override for the persistent-compile-cache directory (sysvar
# tidb_compile_cache_dir); a dict cell so set_compile_cache_dir never
# races module reloads
_CACHE_DIR_STATE = {"override": None}


def set_compile_cache_dir(path: str) -> None:
    """Point jax's persistent compilation cache at ``path`` (sysvar
    ``tidb_compile_cache_dir`` / config ``compile_cache_dir``): bucketed
    kernels then survive process restarts — the second process's
    "first run" skips the 20-40s XLA compiles entirely.  Empty path
    restores the resolution chain below.  Applies immediately when the
    backend is already initialized."""
    # qlint: disable=CC701 -- single GIL-atomic scalar-slot publish; _cache_dir readers tolerate either the old or new override
    _CACHE_DIR_STATE["override"] = str(path) if path else None
    if _jax is not None:
        try:
            _jax.config.update("jax_compilation_cache_dir", _cache_dir())
        except Exception:
            pass


def _machine_sig() -> str:
    """Short host/backend machine signature partitioning the persistent
    compile cache: AOT artifacts embed target machine features (CPU ISA
    flags, TPU generation), and reloading one compiled for a different
    target makes cpu_aot_loader spam "Target machine feature ... is not
    supported" on every multichip run.  Keying the cache subdirectory by
    (platform, machine, ISA flag set) means each compile target owns its
    own cache instead of fighting over one directory."""
    import hashlib
    import platform as _platform
    parts = [_platform.system(), _platform.machine()]
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("flags") or line.startswith("Features"):
                    parts.append(" ".join(sorted(line.split(":", 1)[1].split())))
                    break
    except OSError:
        parts.append(_platform.processor() or "")
    if _jax is not None:
        try:
            parts.append(_jax.default_backend())
        except Exception:
            pass
    return hashlib.sha256("|".join(parts).encode()).hexdigest()[:12]


def _cache_dir() -> str:
    """Persistent compile-cache directory.  Resolution: the sysvar
    override (set_compile_cache_dir) > TINYSQL_JAX_CACHE env > the
    config file's compile_cache_dir > <repo>/.jax_cache — always suffixed
    with the _machine_sig partition so caches shared across hosts (NFS
    home, container image layers) never mix AOT compile targets."""
    import os
    base = None
    if _CACHE_DIR_STATE["override"]:
        base = _CACHE_DIR_STATE["override"]
    if base is None:
        env = os.environ.get("TINYSQL_JAX_CACHE")
        if env:
            base = env
    if base is None:
        try:
            from ..config import get_global_config
            cfg = get_global_config().compile_cache_dir
            if cfg:
                base = cfg
        except Exception:
            pass
    if base is None:
        base = os.path.join(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))), ".jax_cache")
    return os.path.join(base, "mt-" + _machine_sig())


def jax():
    global _jax
    if _jax is None:
        import jax as jax_mod
        # engine semantics are int64/float64 (reference: the 3 eval
        # families); the env var is not honored by all builds, so force it
        jax_mod.config.update("jax_enable_x64", True)
        ensure_live_backend(jax_mod)
        # persistent compile cache: TPU kernel compiles are 20-40s; shape
        # buckets recur across runs
        try:
            jax_mod.config.update("jax_compilation_cache_dir", _cache_dir())
            jax_mod.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        except Exception:
            pass
        _jax = jax_mod
    return _jax


def jnp():
    return jax().numpy


# device-economics counters (bench diagnosability, VERDICT r2 weak-3):
# every compiled-program dispatch and packed D2H transfer increments
# these, so BENCH json can split engine time from link time per query.
# The pipe_* family is fed by the async block pipeline (devpipe
# BlockPipeline consumers) via pipe_record: per-stage walls for the
# host-staging / device-compute overlap accounting, block count, and the
# staging-queue depth high-water mark (reported as an absolute value by
# stats_delta — a high-water is not a per-interval delta).
STATS = {"dispatches": 0, "d2h_transfers": 0, "d2h_bytes": 0,
         "h2d_transfers": 0, "h2d_bytes": 0,
         "host_dispatches": 0,
         "device_s": 0.0, "profiled_dispatches": 0,
         "flops": 0.0, "bytes_accessed": 0.0,
         "pipe_blocks": 0, "pipe_stage_s": 0.0, "pipe_dispatch_s": 0.0,
         "pipe_drain_s": 0.0, "pipe_wall_s": 0.0, "pipe_depth_hwm": 0}

#: STATS keys that are high-water marks, not accumulators — declared in
#: the central metric registry so the registry's gauge-vs-counter kinds
#: and the /metrics render share one definition
from ..obs.metrics import HWM_STATS_KEYS as _HWM_KEYS  # noqa: E402

#: guards the global STATS read-modify-writes — sessions and devpipe
#: producer threads increment concurrently
_STATS_MU = threading.Lock()


def stats_add(key: str, n) -> None:
    """THE accumulator write path (qlint OB401 bans direct ``STATS[...]``
    writes outside this module): bumps the process-global counter under
    the lock AND fans the increment out to the active per-query scope +
    the operator whose next() frame is live (obs/context.py), so
    concurrent sessions collect disjoint per-query counters."""
    with _STATS_MU:
        STATS[key] = STATS.get(key, 0) + n
    _obs.record(key, n)


def stats_hwm(key: str, n) -> None:
    """High-water-mark write path: keeps the max, globally and in the
    per-query scope (a deep staging queue in one query must not bleed
    into another's detail)."""
    with _STATS_MU:
        if n > STATS.get(key, 0):
            STATS[key] = n
    _obs.record_hwm(key, n)


def host_dispatch(n: int = 1) -> None:
    """Count one HOST-TWIN kernel invocation — the numpy implementations
    that deliberately serve join match / top-k selection / group-by on
    the XLA:CPU backend (host_kernels_ok), where they beat the serial
    device lowerings.  Without this counter a query served entirely by
    twins reports dispatches=0 and is indistinguishable from one that
    silently fell off the accelerated paths (the BENCH_r05 Q3 mystery);
    bench.py asserts dispatches + host_dispatches > 0 per device-tier
    query."""
    stats_add("host_dispatches", n)


def pipe_overlap_frac(d: dict) -> float:
    """Staging/compute overlap estimate from a counter scope's ``pipe_*``
    walls (global STATS delta, or a per-query ``device_totals()``): busy
    time beyond the pipeline wall is work that ran CONCURRENTLY on the
    stage thread.  THE one formula — bench detail and EXPLAIN ANALYZE
    must agree."""
    pw = d.get("pipe_wall_s", 0.0)
    if not pw or pw <= 0:
        return 0.0
    busy = (d.get("pipe_stage_s", 0.0) + d.get("pipe_dispatch_s", 0.0)
            + d.get("pipe_drain_s", 0.0))
    return max(0.0, busy - pw) / pw


def pipe_record(blocks: int = 0, stage_s: float = 0.0,
                dispatch_s: float = 0.0, drain_s: float = 0.0,
                wall_s: float = 0.0, depth_hwm: int = 0) -> None:
    """Accrue one pipelined run's stage/compute/drain walls into STATS
    (called once per BlockPipeline consumer loop, not per block)."""
    stats_add("pipe_blocks", blocks)
    stats_add("pipe_stage_s", stage_s)
    stats_add("pipe_dispatch_s", dispatch_s)
    stats_add("pipe_drain_s", drain_s)
    stats_add("pipe_wall_s", wall_s)
    stats_hwm("pipe_depth_hwm", depth_hwm)

# when on, every counted_jit dispatch also accrues the program's XLA cost
# analysis (flops / bytes accessed) into STATS — the bench's MFU and
# HBM-bandwidth accounting (VERDICT r3 weak-4).  Off by default: the
# one-time lower().compile() per (fn, shape) hits the persistent cache but
# still costs a retrace.
_COST_TRACKING = {"on": False}


def enable_cost_tracking(flag: bool = True) -> None:
    _COST_TRACKING["on"] = flag


def stats_snapshot() -> dict:
    from . import progcache
    with _STATS_MU:
        out = dict(STATS)
        # high-water marks are PER INTERVAL: a snapshot opens a new
        # interval (sequential snapshot/delta pairs, the bench's usage),
        # so a deep queue in query N never bleeds into query N+1's detail
        for k in _HWM_KEYS:
            STATS[k] = 0
    pc = progcache.stats_snapshot()
    out["progcache_hits"] = pc["hits"]
    out["progcache_misses"] = pc["misses"]
    out["prewarm_seeded"] = pc.get("prewarm_seeded", 0)
    out["prewarm_hits"] = pc.get("prewarm_hits", 0)
    return out


def stats_delta(since: dict) -> dict:
    now = stats_snapshot()
    return {k: (v if k in _HWM_KEYS else v - since.get(k, 0))
            for k, v in now.items()}


def _arg_spec(tree):
    import jax as j
    return tuple((getattr(x, "shape", None), str(getattr(x, "dtype", type(x))))
                 for x in j.tree_util.tree_leaves(tree))


def _abstractify(tree):
    """Replace arrays with ShapeDtypeStructs so pending cost analyses hold
    no device buffers alive."""
    import jax as j

    def conv(x):
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            return j.ShapeDtypeStruct(x.shape, x.dtype)
        return x
    return j.tree_util.tree_map(conv, tree)


# (costs dict, spec, jitted fn, abstract args) awaiting cost analysis —
# resolved OUTSIDE the timed region (resolve_pending_costs: the bench
# between timed runs, the tsring Sampler every tick in serving mode), so
# the AOT retrace never inflates the walls the MFU is computed from.
# BOUNDED: beyond the cap a new spec records (0, 0) instead of queueing
# — with cost tracking on and no drainer the list must not grow forever
# (the pre-ISSUE-11 serving-mode leak).  GUARDED (_PENDING_MU, qlint
# CC701): query threads append while the tsring Sampler AND bench.py can
# drain concurrently — an unguarded pop raced against another drainer
# raises IndexError out of whichever caller loses, and the cap check
# raced against a concurrent append overshoots the bound
_PENDING_COSTS: list = []
_PENDING_MU = threading.Lock()
PENDING_COSTS_MAX = 256


def resolve_pending_costs() -> None:
    """Run the deferred cost analyses (bench calls this between timed
    runs; the tsring Sampler drains it every tick — both may run at
    once, so each entry is claimed under the lock and the expensive
    lower/compile happens OUTSIDE it).  Unresolvable programs record
    (0, 0)."""
    while True:
        with _PENDING_MU:
            if not _PENDING_COSTS:
                return
            costs, spec, w, absargs, prog_key = _PENDING_COSTS.pop()
        a, k = absargs
        try:
            compiled = w.lower(*a, **k).compile()
            ca = compiled.cost_analysis()
            if isinstance(ca, (list, tuple)):  # older jax: dict per device
                ca = ca[0] if ca else {}
            costs[spec] = (float(ca.get("flops", 0.0) or 0.0),
                           float(ca.get("bytes accessed", 0.0) or 0.0))
        except Exception:
            costs[spec] = (0.0, 0.0)
            continue
        try:
            # the program's static HBM footprint (peak scratch / operand /
            # result bytes) rides the same deferred resolution into the
            # catalog — compiled_programs' peak_*_bytes columns
            ma = compiled.memory_analysis()
            progcache.note_memory(
                prog_key,
                float(getattr(ma, "temp_size_in_bytes", 0) or 0),
                float(getattr(ma, "argument_size_in_bytes", 0) or 0),
                float(getattr(ma, "output_size_in_bytes", 0) or 0))
        except Exception:
            pass  # backends without memory_analysis keep zeros


def counted_jit(fn, **kw):
    """jax.jit wrapper that counts program dispatches (and, when cost
    tracking is on, the dispatched program's flops / bytes accessed —
    first sight of a (program, shape) only ENQUEUES the analysis; counts
    accrue on dispatches after resolve_pending_costs ran).

    Constructed inside a progcache builder, the wrapper learns its
    registry key (progcache.building_key) and reports every dispatch to
    the per-program catalog; when the sampling profiler is on
    (ops/profiler.py, tidb_device_profile_rate) a sampled dispatch is
    closed with block_until_ready so the recorded wall is MEASURED
    device busy time, not async submit time."""
    # qlint: disable=TS104 -- counted_jit IS the wrapper factory; callers cache its result
    w = jax().jit(fn, **kw)
    costs: Dict[tuple, Optional[tuple]] = {}
    prog_key = progcache.building_key()

    def call(*a, **k):
        fail.inject("kernelDispatchError")
        stats_add("dispatches", 1)
        cost = None
        if _COST_TRACKING["on"]:
            spec = _arg_spec((a, k))
            c = costs.get(spec)
            if c is not None:
                cost = c
                stats_add("flops", c[0])
                stats_add("bytes_accessed", c[1])
            elif spec not in costs:
                with _PENDING_MU:
                    # re-check under the lock: two threads first-
                    # dispatching the same spec must not both enqueue
                    # (duplicate cost analyses + wasted queue slots)
                    if spec not in costs:
                        if len(_PENDING_COSTS) >= PENDING_COSTS_MAX:
                            # nothing is draining the queue: record
                            # zeros (an honest undercount), not a leak
                            costs[spec] = (0.0, 0.0)
                        else:
                            costs[spec] = None
                            _PENDING_COSTS.append(
                                (costs, spec, w, _abstractify((a, k)),
                                 prog_key))
        sampled = profiler.should_sample()
        t0 = time.perf_counter() if sampled else 0.0
        with _obs.span("dispatch", cat="device"):
            res = w(*a, **k)
            if sampled:
                # close the async enqueue: the span and the recorded
                # wall now cover true device busy time for this dispatch
                jax().block_until_ready(res)
        if sampled:
            dt = time.perf_counter() - t0
            stats_add("device_s", dt)
            stats_add("profiled_dispatches", 1)
            profiler.observe(dt)
            progcache.note_dispatch(prog_key, device_s=dt, cost=cost)
        else:
            progcache.note_dispatch(prog_key, cost=cost)
        return res
    # AOT hook for the bucket prewarmer (tools/warm.py):
    # fn.lower(*abstract).compile() compiles without dispatching
    call.lower = w.lower
    return call


def h2d(a):
    """Counted host->device upload — the H2D mirror of :func:`d2h`, so
    transfer accounting is symmetric (pre-ISSUE-11, ParamTable pushes
    and column uploads were invisible: d2h had counters, h2d had none).
    One transfer per array; bytes charged from the HOST buffer."""
    host = np.asarray(a)
    out = jnp().asarray(host)
    stats_add("h2d_transfers", 1)
    stats_add("h2d_bytes", int(host.nbytes))
    return out


def h2d_pad(a: np.ndarray, n: int, fill=0):
    """Counted upload of ``pad1(a, n, fill)`` — THE bucketed column
    upload idiom (bytes charged at the padded size actually shipped)."""
    return h2d(pad1(a, n, fill))


def d2h(dev_arr) -> np.ndarray:
    """Counted device->host materialization."""
    fail.inject("kernelD2HError")
    with _obs.span("drain", cat="device"):
        out = np.asarray(dev_arr)
    stats_add("d2h_transfers", 1)
    stats_add("d2h_bytes", out.nbytes)
    return out


def d2h_many(dev_arrs) -> List[np.ndarray]:
    """ONE counted device->host pull for several arrays:
    jax.device_get gathers the copies behind a single sync point, so a
    kernel result split across the int64 and float64 streams pays the
    link's per-transfer latency once, not once per stream (the Q6
    dispatches=1 / d2h_transfers=2 accounting bug, BENCH_r05)."""
    fail.inject("kernelD2HError")
    with _obs.span("drain", cat="device"):
        outs = [np.asarray(a) for a in jax().device_get(list(dev_arrs))]
    stats_add("d2h_transfers", 1)
    stats_add("d2h_bytes", sum(o.nbytes for o in outs))
    return outs


I64_MIN = -(1 << 63)


# =========================================================================
# packed device->host transfer
# =========================================================================
# The device link (axon tunnel on this host; PCIe/DCN generally) charges a
# large fixed latency PER device->host transfer and is far slower D2H than
# H2D.  Every kernel therefore returns ONE packed int64 buffer: float64
# bitcasts losslessly, bools widen, and the host splits the single download
# back into typed arrays.  Data-dependent result sizes are handled with a
# two-phase protocol: phase 1 computes on device and syncs ONE scalar (the
# live count), phase 2 compacts device-side to a static bucket and packs.

def pack_arrays(schema: list, arrays) -> tuple:
    """Inside jit: concat 1-D arrays into one int64 and one float64 stream
    (f64<->i64 bitcast does not lower under the TPU X64-emulation rewrite,
    so the two element classes ride separate buffers — at most two D2H
    transfers per kernel).  Appends (dtype, length, stream) to `schema`
    (cleared first) for unpack_flat; tracing runs once per compile-cache
    entry, so the schema paired with the jitted fn is stable by the time
    results are unpacked."""
    jn = jnp()
    del schema[:]
    ints, floats = [], []
    for a in arrays:
        if a.dtype == jn.float64:
            schema.append(("float64", int(a.shape[0]), "f"))
            floats.append(a)
        elif a.dtype in (jn.int64, jn.bool_, jn.int32):
            schema.append((str(a.dtype), int(a.shape[0]), "i"))
            ints.append(a if a.dtype == jn.int64 else a.astype(jn.int64))
        else:  # float32 etc. would silently truncate through the int path
            raise TypeError(f"pack_arrays: unsupported dtype {a.dtype}")
    zi = jn.zeros(0, dtype=jn.int64)
    zf = jn.zeros(0, dtype=jn.float64)
    return (jn.concatenate(ints) if ints else zi,
            jn.concatenate(floats) if floats else zf)


def _split_flat(flat_i, flat_f, schema: list) -> List[np.ndarray]:
    """Split the two host streams back into typed arrays per the
    recorded schema (shared by :func:`unpack_flat` and
    :func:`unpack_host`)."""
    out = []
    pi = pf = 0
    for dt, ln, stream in schema:
        if stream == "f":
            out.append(flat_f[pf:pf + ln])
            pf += ln
        else:
            seg = flat_i[pi:pi + ln]
            pi += ln
            if dt == "int64":
                out.append(seg)
            elif dt == "bool":
                out.append(seg != 0)
            else:
                out.append(seg.astype(np.dtype(dt)))
    return out


def unpack_flat(pair, schema: list) -> List[np.ndarray]:
    """ONE D2H pull (both streams batch through d2h_many when a result
    spans int64 and float64), then split per the recorded schema."""
    dev_i, dev_f = pair
    need_i = any(s == "i" for _, _, s in schema)
    need_f = any(s == "f" for _, _, s in schema)
    if need_i and need_f:
        flat_i, flat_f = d2h_many([dev_i, dev_f])
    else:
        flat_i = d2h(dev_i) if need_i else None
        flat_f = d2h(dev_f) if need_f else None
    return _split_flat(flat_i, flat_f, schema)


def unpack_host(pair, schema: list) -> List[np.ndarray]:
    """``unpack_flat`` for a stacked batch round's already-downloaded
    member rows: the round's dispatch leg pulled the WHOLE stacked
    output in one packed transfer (ops/batching.py), so the member's
    row pair is host memory here — splitting must not count (or pay
    for) another download."""
    host_i, host_f = pair
    return _split_flat(host_i, host_f, schema)


def bucket(n: int) -> int:
    """Pad target: next power of two (min 16) — bounds recompiles to
    O(log n) distinct shapes.  Each resolved bucket is reported to the
    active per-query scope (obs/context.py): the ground truth the
    prewarm feedback loop records, since fused-pipeline input shapes
    never flow through an operator's next()."""
    b = 16
    while b < n:
        b <<= 1
    _obs.record_bucket(b)
    return b


def pad1(a: np.ndarray, n: int, fill=0) -> np.ndarray:
    if len(a) == n:
        return a
    if fill == 0 or fill is False:
        out = np.zeros(n, dtype=a.dtype)  # calloc: no fill pass
    else:
        out = np.full(n, fill, dtype=a.dtype)
    out[: len(a)] = a
    return out


# one-RTT threshold: below this many rows, downloading the FULL dense
# arrays in one packed transfer beats a scalar sync + compacted transfer
# (the link's per-transfer latency dwarfs the extra bytes)
SMALL_PACK = 1 << 16

def _slice_pack(items, ob: int):
    """Pack device arrays sliced to [:ob] — one download.  Returns host
    arrays (still ob-long; callers slice to the live count)."""
    key = ("slice_pack", ob, tuple(str(a.dtype) for a in items),
           tuple(int(a.shape[0]) for a in items))

    def build():
        schema: list = []

        def kernel(arrs):
            return pack_arrays(schema, [a[:ob] for a in arrs])
        return counted_jit(kernel), schema
    fn, schema = progcache.get(key, build)
    return unpack_flat(fn(items), schema)


def _present_pack(presence, items, ob: int):
    """Device-compact rows where presence>0 into a static ob bucket, pack,
    one download.  Returns (ids, gathered items), each ob-long with
    out-of-range id fill past the live count."""
    jn_ = jnp()
    ns = int(presence.shape[0])
    key = ("present_pack", ob, ns, tuple(str(a.dtype) for a in items))

    def build():
        schema: list = []

        def kernel(pres, arrs):
            idx = jn_.nonzero(pres > 0, size=ob, fill_value=ns)[0]
            safe = jn_.minimum(idx, ns - 1)
            return pack_arrays(schema, [idx] + [a[safe] for a in arrs])
        return counted_jit(kernel), schema
    fn, schema = progcache.get(key, build)
    vals = unpack_flat(fn(presence, items), schema)
    return vals[0], vals[1:]


# =========================================================================
# group aggregate
# =========================================================================
# agg spec tuple: (func, has_arg) where func in
#   count_star | count | sum | sum_int | min | max | first


def _sort_perm(keys, valid):
    """Device lexsort: invalid rows last, NULL keys first within a key."""
    j = jnp()
    ops = []
    for kv, kn in reversed(keys):
        ops.append(kv)
        ops.append(j.where(kn, 0, 1).astype(j.int8))  # NULL first
    ops.append(j.where(valid, 0, 1).astype(j.int8))   # invalid last (primary)
    return j.lexsort(ops)


def _group_agg_kernel(n_keys: int, specs: tuple):
    j = jax()
    jn = jnp()

    def kernel(key_vals, key_nulls, valid, arg_vals, arg_nulls):
        n = valid.shape[0]
        keys = list(zip(key_vals, key_nulls))
        perm = _sort_perm(keys, valid)
        kv_s = [v[perm] for v in key_vals]
        kn_s = [m[perm] for m in key_nulls]
        valid_s = valid[perm]
        # group boundary: any key cell differs (null-aware)
        boundary = jn.zeros(n, dtype=bool).at[0].set(True)
        for v, m in zip(kv_s, kn_s):
            dv = (v[1:] != v[:-1]) & ~(m[1:] & m[:-1])
            dm = m[1:] != m[:-1]
            boundary = boundary.at[1:].set(boundary[1:] | dv | dm)
        gid = jn.cumsum(boundary) - 1
        seg = partial(j.ops.segment_sum, segment_ids=gid, num_segments=n)
        first_idx = j.ops.segment_min(jn.arange(n), gid, num_segments=n)
        first_idx = jn.minimum(first_idx, n - 1)
        n_valid = jn.sum(valid_s.astype(jn.int32))
        n_groups = jn.where(n_valid > 0, gid[jn.maximum(n_valid - 1, 0)] + 1, 0)
        # representative ORIGINAL row id per group (host gathers any-typed
        # columns — string group keys, first_row aggs — with this)
        first_orig = perm[first_idx]

        group_keys = [(v[first_idx], m[first_idx])
                      for v, m in zip(kv_s, kn_s)]
        outs = []
        ai = 0
        for func, has_arg in specs:
            if has_arg:
                av = arg_vals[ai][perm]
                an = arg_nulls[ai][perm]
                ai += 1
            if func == "count_star":
                outs.append((seg(valid_s.astype(jn.int64)),
                             jn.zeros(n, dtype=bool)))
            elif func == "count":
                live = valid_s & ~an
                outs.append((seg(live.astype(jn.int64)),
                             jn.zeros(n, dtype=bool)))
            elif func in ("sum", "sum_int", "sum0"):
                live = valid_s & ~an
                total = seg(jn.where(live, av, 0))
                cnt = seg(live.astype(jn.int64))
                outs.append((total, jn.zeros_like(cnt, dtype=bool)
                             if func == "sum0" else cnt == 0))
            elif func in ("min", "max"):
                live = valid_s & ~an
                if func == "min":
                    fill = (jn.iinfo(jn.int64).max if av.dtype == jn.int64
                            else jn.inf)
                    r = j.ops.segment_min(jn.where(live, av, fill), gid,
                                          num_segments=n)
                else:
                    fill = (jn.iinfo(jn.int64).min if av.dtype == jn.int64
                            else -jn.inf)
                    r = j.ops.segment_max(jn.where(live, av, fill), gid,
                                          num_segments=n)
                cnt = seg(live.astype(jn.int64))
                outs.append((r, cnt == 0))
            elif func == "first":
                outs.append((av[first_idx], an[first_idx]))
            else:  # pragma: no cover
                raise ValueError(func)
        return n_groups, first_orig, group_keys, outs

    return counted_jit(kernel)


def group_aggregate(key_cols: List[Tuple[np.ndarray, np.ndarray]],
                    agg_specs: List[Tuple[str, bool]],
                    arg_cols: List[Tuple[np.ndarray, np.ndarray]],
                    n_rows: int, filter_mask: np.ndarray = None):
    """Host wrapper: pad, run kernel, slice to n_groups.

    key_cols/arg_cols: (values, null) numpy pairs of length n_rows.
    `filter_mask` folds a selection into the kernel's valid mask — the
    fused filter+aggregate path skips host-side compaction entirely.
    Returns (group_key_cols, agg_out_cols) as numpy (values, null) pairs.
    """
    jn = jnp()
    nb = bucket(max(n_rows, 1))
    valid = np.zeros(nb, dtype=bool)
    if filter_mask is not None:
        valid[:n_rows] = filter_mask
    else:
        valid[:n_rows] = True
    kv = [h2d_pad(v, nb) for v, _ in key_cols]
    kn = [h2d_pad(m, nb, True) for _, m in key_cols]
    av = [h2d_pad(v, nb) for v, _ in arg_cols]
    an = [h2d_pad(m, nb, True) for _, m in arg_cols]
    key = ("group_agg", len(key_cols), tuple(agg_specs), nb,
           tuple(str(v.dtype) for v in kv), tuple(str(v.dtype) for v in av))
    fn = progcache.get(key, lambda: _group_agg_kernel(len(key_cols),
                                                      tuple(agg_specs)))
    n_groups, first_orig, gkeys, outs = fn(kv, kn, h2d(valid), av, an)
    items = [first_orig]
    for v, m in gkeys:
        items += [v, m]
    for v, m in outs:
        items += [v, m]
    if nb <= SMALL_PACK:
        # one RTT: download the full (small) dense arrays with n_groups
        # packed in, slice on host
        vals = _slice_pack([n_groups[None].astype(jn.int64)] + items, nb)
        ng = int(vals[0][0])
        vals = vals[1:]
    else:
        ng = int(n_groups)  # scalar sync, then one compacted download
        ob = min(bucket(max(ng, 1)), nb)
        vals = _slice_pack(items, ob)
    first = vals[0][:ng]
    rest = vals[1:]
    nk = len(gkeys)
    out_keys = [(rest[2 * i][:ng], rest[2 * i + 1][:ng]) for i in range(nk)]
    out_aggs = [(rest[2 * nk + 2 * i][:ng], rest[2 * nk + 2 * i + 1][:ng])
                for i in range(len(outs))]
    return out_keys, out_aggs, first


def _segment_agg_kernel(specs: tuple, n_segments: int):
    """Known-cardinality group aggregate: direct segment reductions over
    composite group ids — NO sort (the shape dist.make_sharded_group_sum
    uses per shard; here single-chip).  Invalid rows route to an overflow
    segment that is sliced away."""
    j = jax()
    jn = jnp()

    def kernel(gid, valid, arg_vals, arg_nulls):
        seg = _SegReduce(j, jn, gid, valid, n_segments)
        presence, first_orig = seg.presence_first()
        first_orig = jn.minimum(first_orig, gid.shape[0] - 1)
        outs = []
        ai = 0
        for func, has_arg in specs:
            if has_arg:
                av = arg_vals[ai]
                an = arg_nulls[ai]
                ai += 1
            if func == "count_star":
                outs.append((presence, jn.zeros(n_segments, dtype=bool)))
                continue
            live = valid & ~an
            cnt = seg.sum(live.astype(jn.int64), live)
            if func == "count":
                outs.append((cnt, jn.zeros(n_segments, dtype=bool)))
            elif func in ("sum", "sum_int", "sum0"):
                outs.append((seg.sum(av, live),
                             jn.zeros_like(cnt, dtype=bool)
                             if func == "sum0" else cnt == 0))
            elif func in ("min", "max"):
                outs.append((seg.minmax(av, live, func == "min"), cnt == 0))
            else:  # pragma: no cover
                raise ValueError(func)
        n_present = jn.sum((presence > 0).astype(jn.int64))
        return presence, first_orig, outs, n_present

    return counted_jit(kernel)


MAX_SEGMENTS = 1 << 16
# dense scatter-add beats the sort-based path by >30x even at millions of
# bins (segment arrays are tiny next to the input); allow high-cardinality
# int keys up to this many bins when the input is large enough to amortize
# the per-bin present-extraction
MAX_DENSE_SEGMENTS = 1 << 21


def seg_limit(n_rows: int) -> int:
    """Segment-count budget for the scatter-add aggregate paths: small
    inputs keep the tight cap (present-extraction is O(bins)), large
    inputs may spread across millions of bins."""
    return min(MAX_DENSE_SEGMENTS, max(MAX_SEGMENTS, 8 * max(n_rows, 1)))


def segment_group_aggregate(gids: np.ndarray, n_segments: int,
                            agg_specs, arg_cols, n_rows: int,
                            filter_mask: np.ndarray = None):
    """Host wrapper: composite small-cardinality group ids -> per-present-
    segment aggregates.  Returns (present_segment_ids, out_aggs,
    first_orig) with empty segments compressed away."""
    jn = jnp()
    nb = bucket(max(n_rows, 1))
    valid = np.zeros(nb, dtype=bool)
    if filter_mask is not None:
        valid[:n_rows] = filter_mask
    else:
        valid[:n_rows] = True
    g = h2d_pad(gids.astype(np.int64), nb)
    av = [h2d_pad(v, nb) for v, _ in arg_cols]
    an = [h2d_pad(m, nb, True) for _, m in arg_cols]
    # bucket the segment count too: one compiled kernel serves every
    # cardinality in the bucket (gids above the true count never occur,
    # their segments simply stay empty and are compressed away)
    ns = bucket(max(n_segments, 1))
    key = ("segment_agg", tuple(agg_specs), ns, nb,
           tuple(str(v.dtype) for v in av))
    fn = progcache.get(key, lambda: _segment_agg_kernel(tuple(agg_specs),
                                                        ns))
    presence, first_orig, outs, n_present = fn(g, h2d(valid), av, an)
    return _present_extract(presence, first_orig, outs, n_present, ns)


def _present_extract(presence, first_orig, outs, n_present, ns: int,
                     limit: int = None):
    """Shared segment-table extraction: one packed download (small tables)
    or scalar-sync + device compaction (large).  Returns
    (present_ids, out_aggs, first_orig) host arrays."""
    jn = jnp()
    items = [first_orig]
    for v, m in outs:
        items += [v, m]
    if ns <= SMALL_PACK:
        vals = _slice_pack(items + [presence], ns)
        pres = vals[-1]
        rest = vals[:-1]
        present = np.nonzero(pres > 0)[0]
        first = rest[0][present]
        out_aggs = [(rest[1 + 2 * i][present], rest[2 + 2 * i][present])
                    for i in range(len(outs))]
    else:
        np_ = int(n_present)
        ob = min(bucket(max(np_, 1)), ns)
        ids, vals = _present_pack(presence, items, ob)
        present = ids[:np_]
        first = vals[0][:np_]
        out_aggs = [(vals[1 + 2 * i][:np_], vals[2 + 2 * i][:np_])
                    for i in range(len(outs))]
    if limit is not None:
        keep = present < limit
        if not keep.all():
            present = present[keep]
            first = first[keep]
            out_aggs = [(v[keep], m[keep]) for v, m in out_aggs]
    return present, out_aggs, first


def _unpack_scalar_agg(vals):
    """Unpacked [n_valid, first_orig, v0, m0, ...] -> the scalar-aggregate
    contract (out_aggs, first_orig) with zero or one output row."""
    ng = 1 if int(vals[0][0]) > 0 else 0
    first_orig = vals[1][:ng]
    rest = vals[2:]
    out_aggs = [(rest[2 * i][:ng], rest[2 * i + 1][:ng])
                for i in range(len(rest) // 2)]
    return out_aggs, first_orig


# Below this many segments the kernels unroll per-segment masked
# reductions instead of scatter-based segment ops: on TPU (esp. under the
# X64-emulation rewrite) a scatter-add over millions of rows costs
# hundreds of ms while ns full-array masked reductions fuse into a few
# streaming passes (measured ~100x faster at ns<=64).
SEG_UNROLL = 64


class _SegReduce:
    """Segment-reduction strategy: scatter-based (any ns) or unrolled
    masked reductions (small ns).  gid/valid fixed at construction."""

    def __init__(self, j, jn, gid, valid, ns: int):
        self.j, self.jn, self.gid, self.valid, self.ns = j, jn, gid, valid, ns
        # XLA:CPU lowers scatter-adds to a tight loop (fast) and would pay
        # ns full passes for the unroll; on TPU it's the reverse
        self.unroll = ns <= SEG_UNROLL and j.default_backend() != "cpu"
        if self.unroll:
            # one bool mask per segment; XLA fuses these into streaming
            # passes over gid without materializing ns x n
            self.seg_masks = [(gid == s) & valid for s in range(ns)]

    def sum(self, x, live):
        jn = self.jn
        if self.unroll:
            lx = jn.where(live, x, jn.zeros((), dtype=x.dtype))
            return jn.stack([jn.sum(jn.where(sm, lx, 0)) for sm in self.seg_masks])
        gl = jn.where(self.valid & live, self.gid, self.ns)
        return self.j.ops.segment_sum(
            jn.where(live, x, 0), gl, num_segments=self.ns + 1)[:self.ns]

    def minmax(self, x, live, is_min: bool):
        jn = self.jn
        if x.dtype == jn.int64:
            fill = (jn.iinfo(jn.int64).max if is_min
                    else jn.iinfo(jn.int64).min)
        else:
            fill = jn.inf if is_min else -jn.inf
        if self.unroll:
            red = jn.min if is_min else jn.max
            return jn.stack([red(jn.where(sm & live, x, fill))
                             for sm in self.seg_masks])
        gl = jn.where(self.valid & live, self.gid, self.ns)
        op = self.j.ops.segment_min if is_min else self.j.ops.segment_max
        return op(jn.where(live, x, fill), gl,
                  num_segments=self.ns + 1)[:self.ns]

    def presence_first(self):
        """(presence counts, first row id) per segment; empty segments
        carry the sentinel n (callers clip or remap — the sharded kernel
        must see the sentinel to keep pmin from picking a bogus shard)."""
        j, jn = self.j, self.jn
        n = self.gid.shape[0]
        if self.unroll:
            presence = jn.stack([jn.sum(sm.astype(jn.int64))
                                 for sm in self.seg_masks])
            idx = jn.arange(n)
            first = jn.stack([jn.min(jn.where(sm, idx, n))
                              for sm in self.seg_masks])
            return presence, first
        g = jn.where(self.valid, self.gid, self.ns)
        presence = j.ops.segment_sum(self.valid.astype(jn.int64), g,
                                     num_segments=self.ns + 1)[:self.ns]
        first = j.ops.segment_min(jn.arange(n), g,
                                  num_segments=self.ns + 1)[:self.ns]
        return presence, jn.minimum(first, n)


def _fused_agg_outs(j, jn, agg_specs, arg_fns, cols, gid, valid,
                    ns, presence, merge_sum, merge_min, merge_max, seg,
                    pr=((), ())):
    """Per-aggregate switch shared by the single-device and sharded fused
    kernels; merge_* combine per-shard partials (identity single-device,
    psum/pmin/pmax over the mesh axis); ``pr`` is the runtime constant
    vector pair the params-compiled argument closures read."""
    outs = []
    for (func, has_arg), af in zip(agg_specs, arg_fns):
        av = an = None
        if has_arg and af is not None:
            av, an = af(cols, pr)
        if func == "count_star":
            outs.append((presence, jn.zeros(ns, dtype=bool)))
            continue
        live = valid & ~an
        cnt = merge_sum(seg.sum(live.astype(jn.int64), live))
        if func == "count":
            outs.append((cnt, jn.zeros(ns, dtype=bool)))
        elif func in ("sum", "sum0"):
            # sum0: a COUNT merged from partial states — 0 over empty
            # input, never NULL (unlike SUM)
            total = merge_sum(seg.sum(av, live))
            outs.append((total, jn.zeros(ns, dtype=bool)
                         if func == "sum0" else cnt == 0))
        elif func in ("min", "max"):
            local = seg.minmax(av, live, func == "min")
            merged = merge_min(local) if func == "min" else merge_max(local)
            outs.append((merged, cnt == 0))
        else:  # pragma: no cover
            raise ValueError(func)
    return outs


# ---- fully fused aggregation over device-resident columns -----------------
# The flagship TPU path: raw table columns live padded in HBM (memoized on
# the columnar replica), aggregate ARGUMENT expressions evaluate on device
# through the exprjit lowering, the whole thing is ONE XLA program, and the
# FILTER MASK itself computes on device: scan conditions AND aggregate
# arguments lower through exprjit with constants as runtime params
# (exprjit.ParamTable), so the per-query traffic is a ~100-byte param
# upload instead of an nb-bool mask — and the program-cache key is the
# expression SHAPE (stable_shape_key), never a constant value: one
# compiled program serves the whole normalized-SQL digest family.
#
# mask spec accepted by the fused entry points:
#   ("host", bool_mask_dev)     — legacy: host-evaluated, uploaded
#   ("dev", mask_fn, key)       — mask_fn(cols, params, row_idx) traced
#     into the kernel; `key` joins the program cache key.
#
# arg_exprs entries: None, a closure (cols, params) -> (values, null)
# (the executor's params-compiled lowering / count-mask programs), or a
# bare Expression (legacy callers: lowers literal-baked — the caller's
# program_key must then pin constant values).
#
# `params`: the per-query (int64[], float64[]) constant vectors every
# params-compiled closure reads its slots from (exprjit.ParamTable
# .arrays()); None when nothing is parameterized.

_EMPTY_I64 = None
_EMPTY_F64 = None
_EMPTY_MASK = None


def _mask_parts(mask):
    """Normalize a mask spec -> (mask_fn|None, cache key, runtime mask
    array).  An absent runtime mask rides a 0-length array so every
    variant shares one call signature."""
    global _EMPTY_MASK
    jn = jnp()
    if _EMPTY_MASK is None:
        _EMPTY_MASK = jn.zeros(0, dtype=bool)
    if mask[0] == "host":
        return None, ("hostmask",), mask[1]
    _, mask_fn, key = mask[:3]
    return mask_fn, ("devmask", key), _EMPTY_MASK


def _params_dev(params):
    """Upload the per-query constant vectors (absent slots ride 0-length
    arrays so parameterless programs share the call signature)."""
    global _EMPTY_I64, _EMPTY_F64
    jn = jnp()
    if _EMPTY_I64 is None:
        _EMPTY_I64 = jn.zeros(0, dtype=jn.int64)
        _EMPTY_F64 = jn.zeros(0, dtype=jn.float64)
    if params is None:
        return (_EMPTY_I64, _EMPTY_F64)
    pi, pf = params
    return (h2d(pi), h2d(pf))


def _lower_arg(e):
    """One aggregate-argument entry -> (cols, params) closure or None.
    Callables pass through (the executor's params-compiled closures);
    bare Expressions lower literal-baked via cached_compile_expr for
    legacy callers whose program_key pins the constant values."""
    if e is None or callable(e):
        return e
    from .exprjit import cached_compile_expr
    fn = cached_compile_expr(e)
    return lambda cols, params: fn(cols)


def _batch_round(mask, params, batchable: bool):
    """Cross-query micro-batching eligibility at a fused dispatch site
    (ops/batching.py): only explicitly-batchable single-shot call sites
    with a params-compiled device mask qualify — the combination that
    makes one compiled program serve a whole constant-variant digest
    family.  Records the ``batchable`` obs marker (the session's close
    hook learns family eligibility from it) and returns the active
    batch round, or None."""
    if not (batchable and mask[0] == "dev" and params is not None):
        return None
    _obs.record("batchable", 1)
    from . import batching
    return batching.current()


# ---- stacked-params batch execution (ops/batching.py dispatch leg) --------
# A batch round's parked members share one compiled program and one set
# of replica-memoized data columns; only their ~100-byte ParamTables
# differ.  Stacking those on a leading batch axis (exprjit
# ParamTable.stack) and dispatching ONE jax.vmap-batched program variant
# makes an entire round cost one XLA dispatch instead of N back-to-back
# replays.  Variants register in progcache under the base key extended
# with a power-of-two OCCUPANCY BUCKET (occupancy 3 rides the B=4
# program with an inert padding row) — no key explosion, prewarmable
# like any program family (prewarm_stacked).

def _stackable_jit(kernel, kind: str, n_data: int, make_kernel):
    """counted_jit + the stacking recipe (`stack_info`) the batched
    variant builder reads: ``kind`` is the output protocol ("packed" =
    one downloadable [B, L] pair, "tree" = per-member device slices),
    ``n_data`` the shared data operands before the vmapped params
    operand, ``make_kernel`` a factory yielding a FRESH (kernel,
    schema) pair for the vmap re-trace."""
    w = counted_jit(kernel)  # qlint: disable=TS104 -- factory: returned straight to the progcache builder, which owns caching
    w.stack_info = (kind, n_data, make_kernel)
    return w


def occupancy_bucket(n: int) -> int:
    """Power-of-two batch bucket for a stacked round (min 2 — a solo
    member never stacks)."""
    b = 2
    while b < n:
        b <<= 1
    return b


def _stacked_key(key: tuple, b: int) -> tuple:
    return key + (("stacked", b),)


def is_stacked_key(key: tuple) -> bool:
    """Is this registry key a B-stacked variant of a batchable program?"""
    return bool(key) and isinstance(key[-1], tuple) and len(key[-1]) == 2 \
        and key[-1][0] == "stacked"


def stacked_variant(key: tuple, base_fn, b: int):
    """The B-stacked variant of a batchable fused program: the base
    kernel re-traced under ``jax.vmap`` over the params operand (shared
    data columns stay unmapped), registered under the base key extended
    with the occupancy bucket ``b``.  Returns ``(jitted fn, kind,
    schema)`` — kind ``"packed"`` outputs download as one ``[B, L]``
    pair, kind ``"tree"`` outputs slice per member on device — or None
    when the base program carries no stacking recipe (legacy entries,
    non-fused programs)."""
    info = getattr(base_fn, "stack_info", None)
    if info is None:
        return None
    kind, n_data, make_kernel = info

    def build():
        kern, schema = make_kernel()
        axes = tuple([None] * n_data + [0])
        vk = jax().vmap(kern, in_axes=axes)
        return counted_jit(vk), kind, schema
    return progcache.get(_stacked_key(key, b), build)


def prewarm_stacked(buckets=(2, 4, 8, 16)) -> int:
    """AOT-build the B-bucketed stacked variants of every registered
    batchable fused program (the auto-prewarm worker calls this inside
    its prewarm scope; bench_serve/batch_smoke call it so the storm's
    first stacked round is a plain cache hit).  Returns the number of
    variants now registered."""
    n = 0
    for key in progcache.keys("scalar") + progcache.keys("seg"):
        if is_stacked_key(key):
            continue
        ent = progcache.peek(key)
        fn = ent[0] if isinstance(ent, tuple) else ent
        if getattr(fn, "stack_info", None) is None:
            continue
        for b in buckets:
            if stacked_variant(key, fn, int(b)) is not None:
                n += 1
    return n


def _fused_segment_raw(dev_cols, gid_dev, n_segments: int,
                       agg_specs, arg_exprs, mask,
                       program_key: tuple = (), params=None,
                       batchable: bool = False):
    """The fused segment-aggregate device program WITHOUT extraction:
    returns (presence, first_orig, outs, n_present, ns) as device arrays
    (n_present a device scalar).  Shared by the host-extract and
    device-resident (late-materialization) paths."""
    j = jax()
    jn = jnp()
    nb = int(gid_dev.shape[0])
    ns = bucket(max(n_segments, 1))
    mask_fn, mask_key, mask_arr = _mask_parts(mask)
    key = ("seg", tuple(agg_specs), program_key, mask_key, ns, nb)
    rnd = _batch_round(mask, params, batchable)
    if rnd is not None and rnd.collecting:
        ent = progcache.peek(key)
        if ent is not None:  # warm programs only: cold families stay solo
            rnd.park(key, ent, (dev_cols, gid_dev, mask_arr), params)

    def build():
        arg_fns = [_lower_arg(e) for e in arg_exprs]

        def make_kernel():
            def kernel(cols, gid, mask_in, pr):
                if mask_fn is not None:
                    valid = mask_fn(cols, pr, jn.arange(nb))
                else:
                    valid = mask_in  # covers filter AND padding rows
                seg = _SegReduce(j, jn, gid, valid, ns)
                presence, first_orig = seg.presence_first()
                first_orig = jn.minimum(first_orig, gid.shape[0] - 1)
                ident = lambda x: x
                outs = _fused_agg_outs(j, jn, agg_specs, arg_fns, cols,
                                       gid, valid, ns, presence, ident,
                                       ident, ident, seg=seg, pr=pr)
                n_present = jn.sum((presence > 0).astype(jn.int64))
                return presence, first_orig, outs, n_present
            return kernel, None

        kernel, _ = make_kernel()
        # tree output: member rows slice off axis 0 and flow into
        # _present_extract in the member's own scope
        return _stackable_jit(kernel, "tree", 3, make_kernel)
    fn = progcache.get(key, build)
    if rnd is not None and rnd.replaying:
        got = rnd.consume(key, (dev_cols, gid_dev, mask_arr), params)
        if got is not None:
            # consume attributed the member's occupancy-weighted share
            # of the round dispatch into this scope (the global counter
            # accrued at dispatch time through counted_jit on the pool
            # worker)
            _tag, (presence, first_orig, outs, n_present) = got
            return presence, first_orig, outs, n_present, ns
    presence, first_orig, outs, n_present = fn(dev_cols, gid_dev,
                                               mask_arr,
                                               _params_dev(params))
    return presence, first_orig, outs, n_present, ns


def fused_segment_aggregate(dev_cols, gid_dev, n_segments: int,
                            agg_specs, arg_exprs, n_rows: int,
                            mask, program_key: tuple = (), params=None,
                            batchable: bool = False):
    """dev_cols: per-schema-slot (values, null) device pairs padded to one
    bucket (None for slots no jittable expression touches); gid_dev:
    composite group ids padded with an out-of-range id; arg_exprs: the agg
    argument programs, lowered on device; mask: a mask spec and params
    the per-query constant vectors (module docstring above).  Returns the
    group_aggregate contract (present_ids, out_aggs, first_orig).
    ``batchable=True`` (single-shot executor call sites only) opts the
    dispatch into cross-query micro-batching (ops/batching.py)."""
    presence, first_orig, outs, n_present, ns = _fused_segment_raw(
        dev_cols, gid_dev, n_segments, agg_specs, arg_exprs, mask,
        program_key=program_key, params=params, batchable=batchable)
    return _present_extract(presence, first_orig, outs, n_present, ns,
                            limit=n_segments)


def fused_segment_aggregate_keep(dev_cols, gid_dev, n_segments: int,
                                 agg_specs, arg_exprs, mask,
                                 program_key: tuple = (), params=None):
    """Device-resident variant (late materialization, VERDICT r4 next-2):
    compacts present segments ON DEVICE and returns
    (present_ids_dev [ob], live_dev [ob], out_aggs_dev, n_present, ob)
    with NO bulk download — only the n_present scalar syncs.  Rows
    [0:n_present) are live (presence ids ascend out of nonzero); padding
    rows carry id=ns and live=False."""
    jn = jnp()
    if mask[0] == "dev" and params is not None:
        # family-eligibility marker only (the session close hook feeds
        # batching.note_family from it): the keep path itself never
        # parks — its per-member device assembly cannot ride a stacked
        # dispatch — but a later batch ROUND re-routes this plan through
        # the batchable fused_segment path (tpu_executors skips the
        # passthrough while a round is live)
        _obs.record("batchable", 1)
    presence, _first, outs, n_present, ns = _fused_segment_raw(
        dev_cols, gid_dev, n_segments, agg_specs, arg_exprs, mask,
        program_key=program_key, params=params)
    np_ = int(n_present)  # one scalar sync
    ob = min(bucket(max(np_, 1)), ns)
    key = ("present_keep", ob, ns, len(outs),
           tuple(str(v.dtype) for v, _ in outs))

    def build():
        def kernel(pres, items):
            idx = jn.nonzero(pres > 0, size=ob, fill_value=ns)[0]
            live = idx < ns
            safe = jn.minimum(idx, ns - 1)
            gathered = [(v[safe], m[safe] | ~live) for v, m in items]
            return idx, live, gathered
        return counted_jit(kernel)
    fn = progcache.get(key, build)
    ids, live, out_aggs = fn(presence, list(outs))
    return ids, live, out_aggs, np_, ob


def fused_scalar_aggregate(dev_cols, agg_specs, arg_exprs, n_rows: int,
                           nb: int, mask, program_key: tuple = (),
                           params=None, batchable: bool = False):
    """Global-group variant of the fused path: masked reductions with
    on-device argument evaluation.  ``batchable=True`` opts the dispatch
    into cross-query micro-batching (ops/batching.py)."""
    j = jax()
    jn = jnp()
    mask_fn, mask_key, mask_arr = _mask_parts(mask)
    key = ("scalar", tuple(agg_specs), program_key, mask_key, nb)
    rnd = _batch_round(mask, params, batchable)
    if rnd is not None and rnd.collecting:
        ent = progcache.peek(key)
        if ent is not None:
            rnd.park(key, ent[0], (dev_cols, mask_arr), params)

    def build():
        arg_fns = [_lower_arg(e) for e in arg_exprs]

        def make_kernel():
            # a FRESH (kernel, schema) pair per call: the stacked-variant
            # builder (stacked_variant) re-traces the kernel under
            # jax.vmap, and pack_arrays rewrites its captured schema at
            # trace time — sharing one list with live solo consumers
            # would expose them to a transiently-cleared schema
            kernel_schema: list = []

            def kernel(cols, mask_in, pr):
                if mask_fn is not None:
                    valid = mask_fn(cols, pr, jn.arange(nb))
                else:
                    valid = mask_in
                outs = []
                for (func, has_arg), af in zip(agg_specs, arg_fns):
                    av = an = None
                    if has_arg and af is not None:
                        av, an = af(cols, pr)
                    if func == "count_star":
                        outs.append((jn.sum(valid.astype(jn.int64))[None],
                                     jn.zeros(1, dtype=bool)))
                        continue
                    live = valid & ~an
                    if func == "count":
                        outs.append((jn.sum(live.astype(jn.int64))[None],
                                     jn.zeros(1, dtype=bool)))
                    elif func in ("sum", "sum0"):
                        total = jn.sum(jn.where(live, av, 0))[None]
                        cnt = jn.sum(live.astype(jn.int64))
                        outs.append((total, jn.zeros(1, dtype=bool)
                                     if func == "sum0"
                                     else (cnt == 0)[None]))
                    elif func in ("min", "max"):
                        if av.dtype == jn.int64:
                            fill = (jn.iinfo(jn.int64).max if func == "min"
                                    else jn.iinfo(jn.int64).min)
                        else:
                            fill = jn.inf if func == "min" else -jn.inf
                        red = jn.min if func == "min" else jn.max
                        r = red(jn.where(live, av, fill))[None]
                        cnt = jn.sum(live.astype(jn.int64))
                        outs.append((r, (cnt == 0)[None]))
                    else:  # pragma: no cover
                        raise ValueError(func)
                n_valid = jn.sum(valid.astype(jn.int64))
                first_orig = jn.argmax(valid)[None]
                items = [n_valid[None], first_orig]
                for v, m in outs:
                    items += [v, m]
                return pack_arrays(kernel_schema, items)
            return kernel, kernel_schema

        kernel, kernel_schema = make_kernel()
        return _stackable_jit(kernel, "packed", 2, make_kernel), \
            kernel_schema
    fn, schema = progcache.get(key, build)
    if rnd is not None and rnd.replaying:
        got = rnd.consume(key, (dev_cols, mask_arr), params)
        if got is not None:
            tag, val = got
            vals = unpack_host(val, schema) if tag == "host" \
                else unpack_flat(val, schema)
            return _unpack_scalar_agg(vals)
    return _unpack_scalar_agg(unpack_flat(
        fn(dev_cols, mask_arr, _params_dev(params)), schema))


def fused_segment_aggregate_sharded(mesh, dev_cols, gid_dev,
                                    n_segments: int, agg_specs, arg_exprs,
                                    n_rows: int, mask,
                                    program_key: tuple = (), params=None):
    """Multi-chip variant of the fused aggregate (SURVEY §2.11 P5: the
    partial/final split AS a reduce-scatter schema): rows shard over the
    mesh axis, each chip segment-reduces its shard with arguments evaluated
    on-device, partial tables merge with psum/pmin/pmax over ICI.

    Inputs must be padded to a bucket divisible by the mesh size (power-of-
    two buckets over power-of-two meshes always are)."""
    from ..parallel import dist
    from . import shardops
    shard_map, P = dist.shard_map_fn()
    j = jax()
    jn = jnp()
    nb = int(gid_dev.shape[0])
    n_dev = dist.mesh_shards(mesh)
    assert nb % n_dev == 0, (nb, n_dev)
    ns = bucket(max(n_segments, 1))
    # the shard_map spec is frozen per closure: the per-slot structure of
    # dev_cols (absent / mask-only / full) MUST key the cache or a
    # same-program query with a different column layout reuses a
    # mismatched spec
    dev_shape = tuple(0 if c is None else (1 if c[0] is None else 2)
                      for c in dev_cols)
    mask_fn, mask_key, mask_arr = _mask_parts(mask)
    key = ("seg_sharded", tuple(agg_specs), program_key, mask_key, ns, nb,
           ("shards", n_dev), dev_shape)

    def build():
        arg_fns = [_lower_arg(e) for e in arg_exprs]

        def kernel(cols, gid, mask_in, pr):
            rows_local = gid.shape[0]
            shard = j.lax.axis_index("shard")
            base = shard.astype(jn.int64) * rows_local
            if mask_fn is not None:
                valid = mask_fn(cols, pr, jn.arange(rows_local) + base)
            else:
                valid = mask_in
            seg = _SegReduce(j, jn, gid, valid, ns)
            presence_local, first_local = seg.presence_first()
            presence = j.lax.psum(presence_local, "shard")
            # local first indexes THIS shard; absent segments carry the
            # sentinel rows_local, which must map to the global max (nb-1)
            # or pmin would prefer an empty low shard over a real high one
            first_global = jn.where(first_local >= rows_local, nb - 1,
                                    first_local + base)
            first_orig = j.lax.pmin(first_global, "shard")
            outs = _fused_agg_outs(
                j, jn, agg_specs, arg_fns, cols, gid, valid, ns, presence,
                merge_sum=lambda x: j.lax.psum(x, "shard"),
                merge_min=lambda x: j.lax.pmin(x, "shard"),
                merge_max=lambda x: j.lax.pmax(x, "shard"),
                seg=seg, pr=pr)
            return presence, first_orig, outs

        col_spec = tuple(
            ((P("shard") if c[0] is not None else None, P("shard"))
             if c is not None else None)
            for c in dev_cols)
        sm = shard_map(kernel, mesh=mesh,
                       in_specs=(col_spec, P("shard"), P("shard"),
                                 (P(), P())),
                       out_specs=(P(), P(), [(P(), P())] * len(agg_specs)))
        kernel_schema: list = []

        def packed(cols, gid, mask_in, pr):
            presence, first_orig, outs = sm(cols, gid, mask_in, pr)
            items = [presence, first_orig]
            for v, m in outs:
                items += [v, m]
            return pack_arrays(kernel_schema, items)
        return counted_jit(packed), kernel_schema
    pfn, schema = progcache.get(key, build)
    shardops.note_round(nb // n_dev)
    vals = unpack_flat(pfn(tuple(dev_cols), gid_dev, mask_arr,
                           _params_dev(params)), schema)
    presence, first_orig = vals[0], vals[1]
    rest = vals[2:]
    present = np.nonzero(presence > 0)[0]
    present = present[present < n_segments]
    out_aggs = [(rest[2 * i][present], rest[2 * i + 1][present])
                for i in range(len(rest) // 2)]
    return present, out_aggs, first_orig[present]


def _scalar_agg_kernel(specs: tuple):
    """No-GROUP-BY aggregation: pure masked reductions — no sort at all
    (the reference's stream-agg analogue for a single global group).
    Returns (jitted fn, schema) with all outputs in one packed buffer."""
    j = jax()
    jn = jnp()
    schema: list = []

    def kernel(valid, arg_vals, arg_nulls):
        outs = []
        ai = 0
        for func, has_arg in specs:
            if has_arg:
                av = arg_vals[ai]
                an = arg_nulls[ai]
                ai += 1
            if func == "count_star":
                outs.append((jn.sum(valid.astype(jn.int64))[None],
                             jn.zeros(1, dtype=bool)))
            elif func == "count":
                live = valid & ~an
                outs.append((jn.sum(live.astype(jn.int64))[None],
                             jn.zeros(1, dtype=bool)))
            elif func in ("sum", "sum_int", "sum0"):
                live = valid & ~an
                total = jn.sum(jn.where(live, av, 0))[None]
                cnt = jn.sum(live.astype(jn.int64))
                outs.append((total, jn.zeros(1, dtype=bool)
                             if func == "sum0" else (cnt == 0)[None]))
            elif func in ("min", "max"):
                live = valid & ~an
                if av.dtype == jn.int64:
                    fill = (jn.iinfo(jn.int64).max if func == "min"
                            else jn.iinfo(jn.int64).min)
                else:
                    fill = jn.inf if func == "min" else -jn.inf
                red = jn.min if func == "min" else jn.max
                r = red(jn.where(live, av, fill))[None]
                cnt = jn.sum(live.astype(jn.int64))
                outs.append((r, (cnt == 0)[None]))
            else:  # pragma: no cover
                raise ValueError(func)
        n_valid = jn.sum(valid.astype(jn.int64))
        first_orig = jn.argmax(valid)[None]  # first valid original row
        items = [n_valid[None], first_orig]
        for v, m in outs:
            items += [v, m]
        return pack_arrays(schema, items)

    return counted_jit(kernel), schema


def scalar_aggregate(agg_specs, arg_cols, n_rows: int,
                     filter_mask: np.ndarray = None):
    """Host wrapper for the global-group aggregate.  Returns
    (out_aggs, first_orig) with one output row when any row survives the
    mask, zero otherwise — same contract slice as group_aggregate."""
    jn = jnp()
    nb = bucket(max(n_rows, 1))
    valid = np.zeros(nb, dtype=bool)
    if filter_mask is not None:
        valid[:n_rows] = filter_mask
    else:
        valid[:n_rows] = True
    av = [h2d_pad(v, nb) for v, _ in arg_cols]
    an = [h2d_pad(m, nb, True) for _, m in arg_cols]
    key = ("scalar_agg", tuple(agg_specs), nb,
           tuple(str(v.dtype) for v in av))
    fn, schema = progcache.get(key,
                               lambda: _scalar_agg_kernel(tuple(agg_specs)))
    return _unpack_scalar_agg(unpack_flat(fn(h2d(valid), av, an),
                                          schema))


# =========================================================================
# equi-join (single int64/float64 key): sort + searchsorted + expand
# =========================================================================


def _join_count_kernel():
    j = jax()
    jn = jnp()

    def kernel(lk, ln, lvalid, rk, rn, rvalid):
        r_live = rvalid & ~rn
        # dead rows get a +max sentinel; a LIVE key can equal the
        # sentinel, so sort (key, dead-flag) lexicographically — live
        # rows first within an equal-key run — and count live rows per
        # window via a prefix sum instead of clipping by the live total
        # (the clip was wrong when sentinels interleaved a live max key)
        sentinel = (jn.iinfo(jn.int64).max if rk.dtype == jn.int64
                    else jn.inf)
        rk_clean = jn.where(r_live, rk, sentinel)
        dead = (~r_live).astype(jn.int8)
        rperm = jn.lexsort([dead, rk_clean])  # primary: key; live first
        rs = rk_clean[rperm]
        pref = jn.cumsum(r_live[rperm].astype(jn.int64))

        def live_upto(p):
            return jn.where(p > 0, pref[jn.maximum(p - 1, 0)], 0)
        lo = jn.searchsorted(rs, lk, side="left")
        hi = jn.searchsorted(rs, lk, side="right")
        l_live = lvalid & ~ln
        counts = jn.where(l_live, live_upto(hi) - live_upto(lo), 0)
        total = jn.sum(counts)
        # outer-mode output size: unmatched VALID left rows emit one row
        eff_total = total + jn.sum((lvalid & (counts == 0)).astype(jn.int64))
        return counts, lo, rperm, jn.stack([total, eff_total])

    return counted_jit(kernel)


def _join_expand_kernel(outer: bool, ob2: int):
    """Expansion packed to the exact output bucket: the totals are synced
    before this runs, so li/ri download exactly bucket(n_out) rows in ONE
    transfer instead of three upper-bound-sized ones."""
    j = jax()
    jn = jnp()
    schema: list = []

    def kernel(counts, lo, rperm, lvalid):
        out_idx = jn.arange(ob2)
        # outer mode: unmatched live-left rows emit one row with ri = -1
        eff_counts = jn.where(outer & lvalid & (counts == 0), 1, counts) \
            if outer else counts
        eff_starts = jn.cumsum(eff_counts) - eff_counts
        li = jn.searchsorted(eff_starts, out_idx, side="right") - 1
        li = jn.clip(li, 0, counts.shape[0] - 1)
        pos = out_idx - eff_starts[li]
        matched = counts[li] > 0
        ridx = jn.clip(lo[li] + pos, 0, rperm.shape[0] - 1)
        ri = jn.where(matched, rperm[ridx], -1)
        return pack_arrays(schema, [li, ri])

    return counted_jit(kernel), schema


def _np_join_expand(lk, ln, lv, rk, rn, rv, outer: bool):
    """Host twin of the expansion join: identical (li, ri) CONTRACT AND
    ORDER (probe-major; within a probe row, build rows in stable
    key-sorted order) so switching paths never reorders results.  Dense
    int64 build keys use a direct-address CSR (bincount starts/counts)
    instead of two searchsorted passes."""
    r_live = rv & ~rn
    bidx = np.nonzero(r_live)[0]
    bk = rk[bidx]
    l_live = lv & ~ln
    n_l = len(lk)
    if len(bk) == 0:
        if outer:
            li = np.nonzero(lv)[0]
            return (li.astype(np.int64),
                    np.full(len(li), -1, dtype=np.int64))
        z = np.empty(0, dtype=np.int64)
        return z, z
    order = np.argsort(bk, kind="stable")
    brow = bidx[order]          # build rows, key-sorted, stable
    if bk.dtype == np.int64:
        kmin = int(bk.min())
        card = int(bk.max()) - kmin + 1
    else:
        card = None
    if card is not None and card <= max(1 << 22, 4 * len(bk)):
        cnt_k = np.bincount(bk - kmin, minlength=card)
        starts_k = np.concatenate(([0], np.cumsum(cnt_k)[:-1]))
        idx = np.clip(lk - kmin, 0, card - 1)
        in_r = l_live & (lk >= kmin) & (lk < kmin + card)
        lo = np.where(in_r, starts_k[idx], 0)
        counts = np.where(in_r, cnt_k[idx], 0)
    else:
        bk_s = bk[order]
        lo = np.searchsorted(bk_s, lk, side="left")
        hi = np.searchsorted(bk_s, lk, side="right")
        counts = np.where(l_live, hi - lo, 0)
    eff = np.where(lv & (counts == 0), 1, counts) if outer else counts
    total = int(eff.sum())
    if total == 0:
        z = np.empty(0, dtype=np.int64)
        return z, z
    li = np.repeat(np.arange(n_l, dtype=np.int64), eff)
    starts = np.cumsum(eff) - eff
    pos = np.arange(total, dtype=np.int64) - starts[li]
    matched = counts[li] > 0
    ridx = np.minimum(lo[li] + pos, len(brow) - 1)
    ri = np.where(matched, brow[ridx], -1)
    return li, ri.astype(np.int64)


def join_match(lkey: Tuple[np.ndarray, np.ndarray], n_left: int,
               rkey: Tuple[np.ndarray, np.ndarray], n_right: int,
               outer: bool = False, lvalid: np.ndarray = None,
               rvalid: np.ndarray = None) -> Tuple[np.ndarray, np.ndarray]:
    """Returns (left_indices, right_indices) of matching row pairs; for
    outer, unmatched VALID left rows appear once with right index -1.
    `lvalid`/`rvalid` fold side filters into the kernel's masks so callers
    skip host compaction AND keep bucket shapes stable across differently
    selective filters (one TPU compile per table size, not per filter).
    Host-array inputs on the CPU backend run the numpy twin."""
    if (isinstance(lkey[0], np.ndarray) and isinstance(rkey[0], np.ndarray)
            and host_kernels_ok()):
        lv = np.ones(n_left, dtype=bool) if lvalid is None \
            else np.asarray(lvalid[:n_left], dtype=bool)
        rv = np.ones(n_right, dtype=bool) if rvalid is None \
            else np.asarray(rvalid[:n_right], dtype=bool)
        host_dispatch()
        return _np_join_expand(
            np.asarray(lkey[0])[:n_left], np.asarray(lkey[1])[:n_left],
            lv, np.asarray(rkey[0])[:n_right],
            np.asarray(rkey[1])[:n_right], rv, outer)
    jn = jnp()
    nlb, nrb = bucket(max(n_left, 1)), bucket(max(n_right, 1))
    lv = np.zeros(nlb, dtype=bool)
    lv[:n_left] = lvalid if lvalid is not None else True
    rv = np.zeros(nrb, dtype=bool)
    rv[:n_right] = rvalid if rvalid is not None else True
    def dev(a, n, fill):
        # already-padded device arrays (replica-memoized keys) pass through
        if isinstance(a, np.ndarray):
            return h2d_pad(a, n, fill)
        assert a.shape[0] == n, (a.shape, n)
        return a
    lk = dev(lkey[0], nlb, 0)
    ln = dev(lkey[1], nlb, True)
    rk = dev(rkey[0], nrb, 0)
    rn = dev(rkey[1], nrb, True)
    ck = ("join_count", nlb, nrb, str(lk.dtype), str(rk.dtype))
    cfn = progcache.get(ck, _join_count_kernel)
    lv_dev = h2d(lv)
    counts, lo, rperm, totals = cfn(lk, ln, lv_dev, rk, rn, h2d(rv))
    totals = d2h(totals)  # ONE scalar-pair sync
    n_out = int(totals[1]) if outer else int(totals[0])
    if n_out == 0:
        return (np.empty(0, dtype=np.int64), np.empty(0, dtype=np.int64))
    ob2 = bucket(n_out)
    ek = ("join_expand", outer, nlb, nrb, ob2)
    efn, schema = progcache.get(ek,
                                lambda: _join_expand_kernel(outer, ob2))
    li, ri = unpack_flat(efn(counts, lo, rperm, lv_dev), schema)
    return li[:n_out], ri[:n_out]


def _unique_join_kernel(build_sorted: bool = False):
    j = jax()
    jn = jnp()

    def kernel(lk, ln, lvalid, rk, rn, rvalid):
        r_live = rvalid & ~rn
        sentinel = (jn.iinfo(jn.int64).max if rk.dtype == jn.int64
                    else jn.inf)
        rk_clean = jn.where(r_live, rk, sentinel)
        if build_sorted:
            # build keys ascend among live rows with dead rows at the
            # tail (a single-key aggregate output): the sentinel rewrite
            # preserves order, so the argsort is the identity
            rs = rk_clean
            cand_all = jn.arange(rs.shape[0], dtype=jn.int64)
        else:
            # live rows first within an equal-key run, so a live key
            # equal to the sentinel is FOUND (searchsorted 'left' lands
            # on it) instead of shadowed by an interleaved dead row
            dead = (~r_live).astype(jn.int8)
            rperm = jn.lexsort([dead, rk_clean])
            rs = rk_clean[rperm]
            cand_all = rperm
        n_r_live = jn.sum(r_live.astype(jn.int32))
        pos = jn.searchsorted(rs, lk, side="left")
        in_range = pos < n_r_live
        cand = cand_all[jn.clip(pos, 0, rs.shape[0] - 1)]
        l_live = lvalid & ~ln
        match = l_live & in_range & (rs[jn.clip(pos, 0, rs.shape[0] - 1)]
                                     == lk)
        # a dead row's sentinel can collide with a LIVE max-valued key;
        # the candidate itself must be live, not just key-equal
        match = match & r_live[cand]
        return match, cand, jn.sum(match.astype(jn.int64))

    return counted_jit(kernel)


def _unique_pick_kernel(ob: int, nlb: int, outer: bool):
    """Phase 2 of the unique join: compact (inner: matched rows; outer:
    all valid left rows) device-side to a static bucket and pack li/ri
    into one download."""
    j = jax()
    jn = jnp()
    schema: list = []

    def kernel(match, cand, lvalid):
        rows = lvalid if outer else match
        li = jn.nonzero(rows, size=ob, fill_value=nlb)[0]
        safe = jn.minimum(li, nlb - 1)
        ri = jn.where(match[safe], cand[safe], -1)
        return pack_arrays(schema, [li, ri])

    return counted_jit(kernel), schema


def host_kernels_ok() -> bool:
    """True when numpy kernel twins should serve host-array inputs: the
    XLA:CPU backend (where device sort/searchsorted run serially) and no
    TINYSQL_DEVICE_JOIN_ONLY override (tests force the device kernels
    with it).  The ONE definition every host-vs-device routing decision
    shares."""
    if os.environ.get("TINYSQL_DEVICE_JOIN_ONLY"):
        return False
    try:
        return jax().default_backend() == "cpu"
    except Exception:
        return False


def _np_unique_join(lk, ln, lv, rk, rn, rv, outer: bool):
    """Host twin of the unique-join kernel (same li/ri contract and tie
    semantics): on XLA:CPU the device sort+searchsorted runs serially
    while numpy's is substantially faster — the same backend-aware kernel
    choice _topk_single makes."""
    r_live = rv & ~rn
    bidx = np.nonzero(r_live)[0]
    bk = rk[bidx]
    l_live = lv & ~ln
    if len(bk) == 0:
        if outer:
            # ALL valid left rows survive (NULL keys null-extend)
            li = np.nonzero(lv)[0]
            return li, np.full(len(li), -1, dtype=np.int64)
        z = np.empty(0, dtype=np.int64)
        return z, z
    if bk.dtype == np.int64:
        kmin = int(bk.min())
        kmax = int(bk.max())
        card = kmax - kmin + 1
    else:
        card = None  # float keys: range addressing is meaningless
    if card is not None and card <= max(1 << 22, 4 * len(bk)):
        # direct-address table over the build key range (~10x faster
        # than searchsorted per probe; devpipe's pos_table twin)
        tbl = np.full(card, -1, dtype=np.int64)
        tbl[bk - kmin] = bidx
        idx = np.clip(lk - kmin, 0, card - 1)
        cand = tbl[idx]
        match = (l_live & (lk >= kmin) & (lk <= kmax) & (cand >= 0))
        if outer:
            li = np.nonzero(lv)[0]
            ri = np.where(match[li], cand[li], -1)
            return li.astype(np.int64), ri.astype(np.int64)
        li = np.nonzero(match)[0]
        return li.astype(np.int64), cand[li].astype(np.int64)
    order = np.argsort(bk, kind="stable")
    bk_s = bk[order]
    brow = bidx[order]
    pos = np.searchsorted(bk_s, lk, side="left")
    pos_c = np.minimum(pos, len(bk_s) - 1)
    match = l_live & (pos < len(bk_s)) & (bk_s[pos_c] == lk)
    if outer:
        li = np.nonzero(lv)[0]
        ri = np.where(match[li], brow[pos_c[li]], -1)
        return li.astype(np.int64), ri.astype(np.int64)
    li = np.nonzero(match)[0]
    return li.astype(np.int64), brow[pos_c[li]].astype(np.int64)


def unique_join_match(lkey, n_left: int, rkey, n_right: int,
                      outer: bool = False, lvalid: np.ndarray = None,
                      rvalid: np.ndarray = None,
                      build_sorted: bool = False):
    """join_match fast path when the RIGHT (build) key is UNIQUE among
    its live rows (clustered pk, or a partial aggregate keyed by the join
    key): each probe row has at most ONE match, so the output size is
    bounded by n_left — no count kernel, no expansion, and no
    device->host size sync.  Same (li, ri) contract as join_match.
    `build_sorted` asserts the build keys already ascend among live rows
    (dead rows at the tail) and skips the device argsort.

    On the CPU backend with HOST key arrays, the match runs in numpy
    (TINYSQL_DEVICE_JOIN_ONLY=1 forces the device kernels, e.g. to
    exercise block-streaming device economics in tests)."""
    if (isinstance(lkey[0], np.ndarray) and isinstance(rkey[0], np.ndarray)
            and host_kernels_ok()):
        lv = np.ones(n_left, dtype=bool) if lvalid is None \
            else np.asarray(lvalid[:n_left], dtype=bool)
        rv = np.ones(n_right, dtype=bool) if rvalid is None \
            else np.asarray(rvalid[:n_right], dtype=bool)
        host_dispatch()
        return _np_unique_join(
            np.asarray(lkey[0])[:n_left], np.asarray(lkey[1])[:n_left],
            lv, np.asarray(rkey[0])[:n_right],
            np.asarray(rkey[1])[:n_right], rv, outer)
    jn = jnp()
    nlb, nrb = bucket(max(n_left, 1)), bucket(max(n_right, 1))
    lv = np.zeros(nlb, dtype=bool)
    lv[:n_left] = lvalid if lvalid is not None else True
    rv = np.zeros(nrb, dtype=bool)
    rv[:n_right] = rvalid if rvalid is not None else True

    def dev(a, n, fill):
        if isinstance(a, np.ndarray):
            return h2d_pad(a, n, fill)
        assert a.shape[0] == n, (a.shape, n)
        return a
    lk = dev(lkey[0], nlb, 0)
    ln = dev(lkey[1], nlb, True)
    rk = dev(rkey[0], nrb, 0)
    rn = dev(rkey[1], nrb, True)
    ck = ("unique_join", nlb, nrb, str(lk.dtype), str(rk.dtype),
          build_sorted)
    fn = progcache.get(ck, lambda: _unique_join_kernel(build_sorted))
    lv_dev = h2d(lv)
    match, cand, n_match = fn(lk, ln, lv_dev, rk, rn, h2d(rv))
    if outer:
        # ALL valid left rows survive — NULL-key rows match nothing and
        # null-extend; the output size is host-known (lv is host-side),
        # so no device sync at all
        n_out = int(np.sum(lv))
    else:
        n_out = int(n_match)  # one scalar sync
    if n_out == 0:
        return (np.empty(0, dtype=np.int64), np.empty(0, dtype=np.int64))
    ob = min(bucket(n_out), nlb)
    pk = ("unique_pick", ob, nlb, outer)
    pfn, schema = progcache.get(pk,
                                lambda: _unique_pick_kernel(ob, nlb, outer))
    li, ri = unpack_flat(pfn(match, cand, lv_dev), schema)
    return li[:n_out], ri[:n_out]


def _semi_kernel(anti: bool, null_aware: bool):
    """Membership test over the build side (sort + searchsorted — the
    same machinery the join kernels ride): per probe row, does ANY live
    build row share its key?  Semi keeps members; anti keeps
    non-members, with the NOT IN three-valued ladder when null_aware:
    an empty build side keeps EVERY valid probe row, any NULL build key
    keeps none, and a NULL probe key never passes."""
    j = jax()
    jn = jnp()

    def kernel(lk, ln, lvalid, rk, rn, rvalid):
        r_live = rvalid & ~rn
        sentinel = (jn.iinfo(jn.int64).max if rk.dtype == jn.int64
                    else jn.inf)
        rk_clean = jn.where(r_live, rk, sentinel)
        # live rows first within an equal-key run so a live key equal to
        # the sentinel is still FOUND (same trick as the join kernels)
        dead = (~r_live).astype(jn.int8)
        rperm = jn.lexsort([dead, rk_clean])
        rs = rk_clean[rperm]
        pos = jn.searchsorted(rs, lk, side="left")
        pos_c = jn.clip(pos, 0, rs.shape[0] - 1)
        l_live = lvalid & ~ln
        member = (l_live & (pos < rs.shape[0]) & (rs[pos_c] == lk)
                  & r_live[rperm][pos_c])
        if not anti:
            keep = member
        else:
            # build-side shape scalars (traced): total live rows incl.
            # NULL keys, and whether any live row's key IS NULL
            n_build = jn.sum(rvalid.astype(jn.int64))
            if null_aware:
                has_null = jn.any(rvalid & rn)
                keep = jn.where(
                    n_build == 0, lvalid,
                    jn.where(has_null, False, l_live & ~member))
            else:
                keep = jn.where(n_build == 0, lvalid, lvalid & ~member)
        return keep, jn.sum(keep.astype(jn.int64))

    return counted_jit(kernel)


def _semi_pick_kernel(ob: int, nlb: int):
    """Compact the kept probe rows device-side to a static bucket — one
    packed download of the surviving indices."""
    j = jax()
    jn = jnp()
    schema: list = []

    def kernel(keep):
        li = jn.nonzero(keep, size=ob, fill_value=nlb)[0]
        return pack_arrays(schema, [li])

    return counted_jit(kernel), schema


def _np_semi_match(lk, ln, lv, rk, rn, rv, anti: bool, null_aware: bool):
    """Host twin of the semi/anti membership kernel: identical keep
    semantics and probe-order output."""
    n_build = int(rv.sum())
    if n_build == 0:
        # empty subquery: semi keeps nothing, anti keeps every valid
        # probe row (NULL probe keys included — NOT IN () is TRUE)
        keep = lv if anti else np.zeros(len(lk), dtype=bool)
        return np.nonzero(keep)[0].astype(np.int64)
    if anti and null_aware and bool((rv & rn).any()):
        return np.empty(0, dtype=np.int64)  # NULL in the build set
    bk = rk[rv & ~rn]
    l_live = lv & ~ln
    member = np.zeros(len(lk), dtype=bool)
    if len(bk):
        member[l_live] = np.isin(lk[l_live], bk)
    if anti:
        keep = lv & ~member & (~ln if null_aware else True)
    else:
        keep = member
    return np.nonzero(keep)[0].astype(np.int64)


def semi_join_match(lkey, n_left: int, rkey, n_right: int,
                    anti: bool = False, null_aware: bool = False,
                    lvalid: np.ndarray = None,
                    rvalid: np.ndarray = None) -> np.ndarray:
    """Probe-row indices surviving a semi (membership) or anti
    (non-membership) test against the build side, in probe order.
    Same host-vs-device routing contract as join_match: host numpy twin
    on the CPU backend, padded-bucket device kernels otherwise (the
    progcache key is shape-only, so literal changes stay cache HITs)."""
    if (isinstance(lkey[0], np.ndarray) and isinstance(rkey[0], np.ndarray)
            and host_kernels_ok()):
        lv = np.ones(n_left, dtype=bool) if lvalid is None \
            else np.asarray(lvalid[:n_left], dtype=bool)
        rv = np.ones(n_right, dtype=bool) if rvalid is None \
            else np.asarray(rvalid[:n_right], dtype=bool)
        host_dispatch()
        return _np_semi_match(
            np.asarray(lkey[0])[:n_left],
            np.asarray(lkey[1])[:n_left], lv,
            np.asarray(rkey[0])[:n_right],
            np.asarray(rkey[1])[:n_right], rv, anti, null_aware)
    jn = jnp()
    nlb, nrb = bucket(max(n_left, 1)), bucket(max(n_right, 1))
    lv = np.zeros(nlb, dtype=bool)
    lv[:n_left] = lvalid if lvalid is not None else True
    rv = np.zeros(nrb, dtype=bool)
    rv[:n_right] = rvalid if rvalid is not None else True

    def dev(a, n, fill):
        if isinstance(a, np.ndarray):
            return h2d_pad(a, n, fill)
        assert a.shape[0] == n, (a.shape, n)
        return a
    lk = dev(lkey[0], nlb, 0)
    ln = dev(lkey[1], nlb, True)
    rk = dev(rkey[0], nrb, 0)
    rn = dev(rkey[1], nrb, True)
    if lk.dtype != rk.dtype:
        lk = lk.astype(jn.float64)
        rk = rk.astype(jn.float64)
    ck = ("semi_match", anti, null_aware, nlb, nrb,
          str(lk.dtype), str(rk.dtype))
    fn = progcache.get(ck, lambda: _semi_kernel(anti, null_aware))
    keep, n_keep = fn(lk, ln, h2d(lv), rk, rn, h2d(rv))
    n_out = int(n_keep)  # one scalar sync
    if n_out == 0:
        return np.empty(0, dtype=np.int64)
    ob = min(bucket(n_out), nlb)
    pk = ("semi_pick", ob, nlb)
    pfn, schema = progcache.get(pk, lambda: _semi_pick_kernel(ob, nlb))
    (li,) = unpack_flat(pfn(keep), schema)
    return li[:n_out]


# =========================================================================
# sort / top-k
# =========================================================================


def _sort_kernel(descs: tuple):
    j = jax()
    jn = jnp()

    def kernel(key_vals, key_nulls, valid):
        # reversed order: lexsort's LAST operand is primary
        ops = []
        for i in range(len(key_vals) - 1, -1, -1):
            v, m, desc = key_vals[i], key_nulls[i], descs[i]
            vv = jn.where(m, 0, v)
            if desc:
                # ~v is the overflow-free order-reversing bijection on int64
                # (-v overflows at int64 min, which the unsigned XOR map hits)
                vv = ~vv if vv.dtype == jn.int64 else -vv
                rank = jn.where(m, 1, 0).astype(jn.int8)  # NULL last
            else:
                rank = jn.where(m, 0, 1).astype(jn.int8)  # NULL first
            ops.append(vv)
            ops.append(rank)
        ops.append(jn.where(valid, 0, 1).astype(jn.int8))  # invalid last
        return jn.lexsort(ops)

    return counted_jit(kernel)


def sort_permutation(key_cols: List[Tuple[np.ndarray, np.ndarray]],
                     descs: List[bool], n_rows: int) -> np.ndarray:
    jn = jnp()
    nb = bucket(max(n_rows, 1))
    valid = np.zeros(nb, dtype=bool)
    valid[:n_rows] = True
    kv = [h2d_pad(v, nb) for v, _ in key_cols]
    kn = [h2d_pad(m, nb, True) for _, m in key_cols]
    key = ("sort", tuple(descs), nb, tuple(str(v.dtype) for v in kv))
    fn = progcache.get(key, lambda: _sort_kernel(tuple(descs)))
    perm = d2h(fn(kv, kn, h2d(valid)))
    return perm[:n_rows]


def _topk_kernel(kb: int):
    j = jax()

    def kernel(score):
        _, ids = j.lax.top_k(score, kb)
        return ids

    return counted_jit(kernel)


def _topk_single(key, desc: bool, n_rows: int, k: int):
    """lax.top_k fast path for ONE sort key: O(n·log k) selection instead
    of a full O(n·log n) sort.  Maps the key onto a single total-order
    score (bigger = earlier in output); NULL ordering (first for asc,
    last for desc) and padding share a worst/best sentinel — lax.top_k's
    stable lowest-index tie-break then prefers real rows, which all sit
    before the padding.  Returns None when an exact mapping isn't safe
    (key values touching the sentinel range, non-finite floats)."""
    v, m = key
    nb = bucket(max(n_rows, 1))
    score = _primary_score(key, desc, n_rows)
    if score is None:
        return None
    pad_val = np.iinfo(np.int64).min if v.dtype == np.int64 else -np.inf
    if jax().default_backend() == "cpu":
        # XLA:CPU's top_k lowering barely beats the full sort; host
        # partition selection is ~100x faster there.  Exact stable-tie
        # semantics: all rows above the threshold, then lowest-index rows
        # AT the threshold.
        host_dispatch()
        s = score[:n_rows]
        kk = min(k, n_rows)
        t = np.partition(s, n_rows - kk)[n_rows - kk]
        above = np.nonzero(s > t)[0]
        at = np.nonzero(s == t)[0][:kk - len(above)]
        ids = np.concatenate([above, at])
        return ids[np.lexsort((ids, -s[ids]))]
    jn = jnp()
    kb = bucket(max(k, 1))
    if kb > nb:
        return None
    ck = ("topk", nb, kb, str(score.dtype))
    fn = progcache.get(ck, lambda: _topk_kernel(kb))
    ids = d2h(fn(h2d_pad(score, nb, pad_val)))[:k]
    return ids[ids < n_rows]  # k may exceed the row count


def _primary_score(key, desc: bool, n_rows: int):
    """Map one sort key onto a total-order score (bigger = earlier) with
    NULL ordering folded in, or None when unsafe.  Shared by the single-
    and multi-key top-k selection paths."""
    v, m = key
    if v.dtype == object or getattr(v.dtype, "kind", "") == "U":
        return None
    if v.dtype == np.int64:
        info = np.iinfo(np.int64)
        vmin = int(v.min()) if n_rows else 0
        vmax = int(v.max()) if n_rows else 0
        if vmin < info.min + 2 or vmax > info.max - 2:
            return None
        if desc:  # null last -> worst score
            return np.where(m, info.min + 1, v)
        return np.where(m, info.max, ~v)  # asc: ~v reverses; null first
    if v.dtype == np.float64:
        w = np.where(m, 0.0, v)
        if n_rows and not np.isfinite(w).all():
            return None
        if desc:
            return np.where(m, -np.inf, w)
        return np.where(m, np.inf, -w)
    return None


def _np_lexsort_perm(key_cols, descs, sub=None) -> np.ndarray:
    """numpy twin of _sort_kernel over the row subset `sub` (None = all
    rows, no subset copies): same operand order, same NULL first/last
    semantics, stable — restricted to a candidate subset it reproduces
    the full sort's relative order."""
    ops = []
    for i in range(len(key_cols) - 1, -1, -1):
        v, m = key_cols[i]
        if sub is not None:
            v, m = v[sub], m[sub]
        vv = np.where(m, 0, v)
        if descs[i]:
            vv = ~vv if vv.dtype == np.int64 else -vv
            rank = np.where(m, 1, 0).astype(np.int8)   # NULL last
        else:
            rank = np.where(m, 0, 1).astype(np.int8)   # NULL first
        ops.append(vv)
        ops.append(rank)
    return np.lexsort(ops)


def host_sort_permutation(key_cols, descs, n_rows: int) -> np.ndarray:
    """Full sort permutation computed ON HOST (numpy lexsort with the
    device kernel's exact semantics): the budget-respecting path for
    tables above tidb_device_block_rows, where uploading every sort key
    whole would violate the device memory budget."""
    host_dispatch()
    keys = [(v[:n_rows], m[:n_rows]) for v, m in key_cols]
    return _np_lexsort_perm(keys, descs)


def _topk_multi(key_cols, descs, n_rows: int, k: int):
    """Multi-key top-k via primary-key threshold selection: rows scoring
    at or above the k-th primary score are a SUPERSET of the true top-k
    (secondary keys only reorder within primary ties), so the full
    lexsort runs over that small candidate set instead of all rows —
    O(n) selection + O(c log c) sort, vs the O(n log n) full sort that
    XLA:CPU executes serially."""
    score = _primary_score(key_cols[0], descs[0], n_rows)
    if score is None:
        return None
    kk = min(k, n_rows)
    s = np.asarray(score[:n_rows])
    t = np.partition(s, n_rows - kk)[n_rows - kk]
    cand = np.nonzero(s >= t)[0]
    if len(cand) * 4 > n_rows * 3:
        return None  # degenerate ties: the full sort is no worse
    host_dispatch()
    order = _np_lexsort_perm(key_cols, descs, cand)
    return cand[order[:kk]]


def top_k(key_cols: List[Tuple[np.ndarray, np.ndarray]], descs: List[bool],
          n_rows: int, k: int) -> np.ndarray:
    """Top-k row indices in requested order.  Single-key inputs take the
    lax.top_k selection path (VERDICT r1 #10); multi-key selects
    candidates by primary-key threshold and sorts only those; the full
    device sort + slice remains the fallback."""
    if k <= 0 or n_rows <= 0:
        return np.empty(0, dtype=np.int64)
    if len(key_cols) == 1:
        ids = _topk_single(key_cols[0], descs[0], n_rows, k)
        if ids is not None:
            return ids
    else:
        ids = _topk_multi(key_cols, descs, n_rows, k)
        if ids is not None:
            return ids
    perm = sort_permutation(key_cols, descs, n_rows)
    return perm[:k]


# =========================================================================
# bucket prewarming (tools/warm.py)
# =========================================================================

def prewarm_bucket(nb: int, k_buckets=(16, 128)) -> int:
    """AOT-compile (``jit(...).lower().compile()``) the shape-GENERIC
    kernels for one power-of-two bucket, so the first real query over a
    table of that size runs warm.  The structural fused programs
    (aggregate specs, expression lowerings, device masks) are warmed by
    EXECUTING the plan once (tools/warm.py does); this covers the purely
    bucket-keyed kernels a grown table hits next — single-key sort
    permutations and the lax.top_k selection — so a cardinality drift
    into the neighboring bucket never pays a cold XLA compile.  Every
    AOT compile lands in the persistent compilation cache
    (set_compile_cache_dir) — the persistence threshold drops to 0 for
    the duration, so sub-second XLA:CPU compiles persist too, not only
    the 20-40s TPU ones.  Returns the number of programs compiled;
    failures are skipped (an unsupported shape must never break
    warming)."""
    j = jax()
    jn = jnp()
    compiled = 0
    try:
        prev_thresh = j.config.jax_persistent_cache_min_compile_time_secs
        j.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    except Exception:
        prev_thresh = None

    def sds(dt):
        return j.ShapeDtypeStruct((nb,), dt)

    try:
        for dts in ("int64", "float64"):
            dt = jn.int64 if dts == "int64" else jn.float64
            for desc in (False, True):
                key = ("sort", (desc,), nb, (dts,))
                fn = progcache.get(key,
                                   lambda desc=desc: _sort_kernel((desc,)))
                try:
                    fn.lower([sds(dt)], [sds(jn.bool_)],
                             sds(jn.bool_)).compile()
                    compiled += 1
                except Exception:
                    pass
            if j.default_backend() == "cpu":
                continue  # _topk_single routes to np.partition on XLA:CPU
            for kb in k_buckets:
                if kb > nb:
                    continue
                key = ("topk", nb, kb, dts)
                fn = progcache.get(key, lambda kb=kb: _topk_kernel(kb))
                try:
                    fn.lower(sds(dt)).compile()
                    compiled += 1
                except Exception:
                    pass
    finally:
        if prev_thresh is not None:
            try:
                j.config.update(
                    "jax_persistent_cache_min_compile_time_secs",
                    prev_thresh)
            except Exception:
                pass
    return compiled
