"""Mesh-sharded operator tier: partition-parallel kernels over N devices.

PR 9's spill partitioner and PR 14's stacked batching meet the mesh
here.  Every remaining accelerated operator family gains a sharded
variant whose SHARD FUNCTION IS THE SPILL PARTITION FUNCTION
(ops/spill.py hash_partition — splitmix64, equal-keys-colocate), so
device placement and the spill ladder share one partitioner and a
spilled partition maps 1:1 onto a shard:

- ``fused_scalar_aggregate_sharded`` — partial→final global aggregation
  ("Partial Partial Aggregates" / "Global Hash Tables Strike Back!"
  design space): each shard reduces its row slice with arguments
  evaluated on-device, partials merge once with psum/pmin/pmax over the
  mesh axis.  STACKABLE: the packed kernel carries a stacking recipe, so
  a coalesced batch round vmaps B queries OVER the N-shard program — one
  dispatch covers B x N.
- ``unique_join_match_sharded`` / ``semi_join_match_sharded`` —
  partitioned build/probe: the host scatters both sides' LIVE rows into
  per-shard hash-partition blocks (spill.hash_partition depth 0), each
  shard joins its partition locally (sort + searchsorted, the same
  machinery as the single-device kernels), and the host re-assembles
  results in probe order — byte-identical to the unsharded kernels.
- ``sort_permutation_sharded`` / ``top_k_sharded`` — per-shard sort /
  selection + device merge: single-key orders map onto the total-order
  score (kernels._primary_score), shards sort locally, and exact global
  ranks come from searchsorted counts against the all_gathered runs
  (ties resolve by global row index because shards are contiguous row
  blocks — the same stability the single-device lexsort guarantees).

Discipline: every program registers under a SHAPE-ONLY progcache key —
partition capacities go through ``kernels.bucket`` and the mesh size
through ``dist.mesh_shards`` (the sanctioned launders; qlint DF803/
DF807) — so prewarm, digest families, and the program catalog apply
unchanged.  All shard_map construction rides ``dist.shard_map_fn`` /
``dist.shard_map_unchecked`` (qlint DF805), and no shard_map body ever
syncs to host (qlint DF806).

Counter-write discipline: ``STATS`` is written only through this
module's locked accessors (qlint OB401/OB402 — shardops.py is an owning
module).  devpipe's probe-skew unsharded-retry path and its shuffle-join
exchanges feed ``record_skew_retry`` / ``record_exchange``.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from .. import fail
from ..obs import context as _obs
from ..parallel import dist
from . import kernels, progcache, spill

# ---- observable state ------------------------------------------------------

_mu = threading.Lock()
#: process-cumulative sharded-tier economics (satellite: rendered on
#: /metrics, sampled into the time-series ring): shard_rounds = sharded
#: program dispatches, shard_rows_hwm = per-shard row high-water mark
#: (partition-block capacity actually used), shard_exchange_bytes =
#: bytes scattered into partition blocks / all_to_all lanes,
#: shard_skew_retries = sharded attempts abandoned for skew (devpipe's
#: unsharded retry + this module's capacity-gate bails),
#: shard_stacked_rounds = B-stacked dispatches OVER sharded programs
STATS: Dict[str, float] = {
    "shard_rounds": 0, "shard_rows_hwm": 0, "shard_exchange_bytes": 0,
    "shard_skew_retries": 0, "shard_stacked_rounds": 0,
}

#: wall seconds of the most recent sharded DEVICE REGION — partition-block
#: upload, the shard_map dispatch, and result download — set by every
#: sharded entry point right after its dispatch.  The multichip bench
#: (bench/operators.run_sharded) reads it to split a measurement into the
#: shard-parallel region and the serial host sections (partition scatter,
#: probe-order re-assembly): a forced host mesh timeshares its N virtual
#: devices onto the physical cores, so raw wall alone cannot show the
#: concurrency a real mesh provides.  A point sample, not a cumulative
#: counter — deliberately NOT part of STATS / the metrics registry.
LAST_DEVICE_REGION_S: float = 0.0


def _note_device_region(t0: float) -> None:
    global LAST_DEVICE_REGION_S
    LAST_DEVICE_REGION_S = time.perf_counter() - t0


def _record(key: str, n: float = 1) -> None:
    """Accumulator write path (the kernels.stats_add double-entry
    pattern): global counter under the lock + per-query obs fan-out."""
    with _mu:
        STATS[key] = STATS.get(key, 0) + n
    _obs.record(key, n)


def _hwm(key: str, n: float) -> None:
    with _mu:
        if n > STATS.get(key, 0):
            STATS[key] = n
    _obs.record_hwm(key, n)


def stats_snapshot() -> Dict[str, float]:
    with _mu:
        return dict(STATS)


def reset_stats() -> None:
    """Tests only."""
    with _mu:
        for k in STATS:
            STATS[k] = 0


def record_skew_retry() -> None:
    """A sharded attempt fell back unsharded because one shard's bound
    blew up (devpipe's CSR probe-skew retry; this module's partition
    capacity gate)."""
    _record("shard_skew_retries")


def record_exchange(nbytes: int) -> None:
    """Bytes moved through a shard exchange (partition-block scatter or
    all_to_all lanes) — devpipe's shuffle join reports its per-compile
    lane volume here."""
    _record("shard_exchange_bytes", int(nbytes))


def note_round(max_shard_rows: int) -> None:
    _record("shard_rounds")
    _hwm("shard_rows_hwm", int(max_shard_rows))


def note_stacked_round() -> None:
    """A coalesced batch round dispatched B stacked queries OVER a
    sharded program — the full B x N throughput product."""
    _record("shard_stacked_rounds")


# ---- exact attribution splits ---------------------------------------------

def split_exact(totals: dict, k: int) -> List[dict]:
    """Split a device-counter dict into ``k`` per-member shares whose
    per-key sums equal the input EXACTLY (float error included): the
    first k-1 members take ``v / k`` and the last takes the remainder.
    Used by the batching dispatch leg for occupancy shares and by the
    sharded tier for per-shard shares — nesting the two (B members x N
    shards) still sums exactly to the round's global counters."""
    if k <= 1:
        return [dict(totals)]
    shares: List[dict] = [dict() for _ in range(k)]
    for key, v in totals.items():
        q = v / k
        acc = type(v)(0)
        for i in range(k - 1):
            shares[i][key] = q
            acc += q
        shares[k - 1][key] = v - acc
    return shares


def member_shard_shares(totals: dict, b: int, n: int) -> List[List[dict]]:
    """B x N attribution cells for one stacked-over-sharded dispatch:
    member shares split exactly, each member's share split exactly again
    across the N shards.  Summed in the nested reduction order (shards
    within a member, then members — the order statements_summary
    reconciles in) the cells equal ``totals`` key by key, exactly;
    a flat sum over all B*N cells is order-sensitive float addition."""
    return [split_exact(m, n) for m in split_exact(totals, b)]


# ---- key introspection -----------------------------------------------------

_SHARDED_DOMAINS = ("scalar_sharded", "seg_sharded", "join_sharded",
                    "semi_sharded", "sort_sharded", "topk_sharded")


def shards_of_key(key: tuple) -> int:
    """Mesh size a sharded progcache key was built for (0 = unsharded
    program).  Sharded domains put the laundered shard count right after
    the domain-specific shape tuple; we tag it explicitly instead:
    every sharded key carries a ``("shards", n)`` marker pair."""
    if not isinstance(key, tuple) or not key:
        return 0
    for part in key:
        if isinstance(part, tuple) and len(part) == 2 \
                and part[0] == "shards":
            return int(part[1])
    return 0


def _shards_tag(mesh) -> tuple:
    return ("shards", dist.mesh_shards(mesh))


# ---- host-side hash partitioning (shard = PR 9 spill partition) -----------

#: a shard's partition block may exceed the balanced share by this
#: factor before the sharded attempt bails to the single-device kernel
#: (skew: a clustered key set would make one device's block rival the
#: whole input)
SKEW_CAP_FACTOR = 2


class _Partitioned:
    """Host-side hash-partition scatter of one input side: LIVE rows
    land in per-shard blocks [n_shards, cap] (cap = bucketed max
    partition size), each row remembering its global index so results
    re-assemble in input order."""

    __slots__ = ("n_shards", "cap", "dest", "order", "slot", "live_idx",
                 "nbytes")

    def __init__(self, keys: np.ndarray, live: np.ndarray, n_shards: int):
        live_idx = np.nonzero(live)[0].astype(np.int64)
        k = np.ascontiguousarray(keys[live_idx])
        # THE spill partitioner at depth 0: equal keys colocate, and a
        # partition that later spills reloads exactly one shard's rows
        dest = spill.hash_partition(k, 0, n_shards) if len(k) \
            else np.empty(0, dtype=np.int64)
        counts = np.bincount(dest, minlength=n_shards)
        self.cap = kernels.bucket(max(int(counts.max()) if len(k) else 1, 1))
        self.n_shards = n_shards
        self.dest = dest
        self.live_idx = live_idx
        order = np.argsort(dest, kind="stable")
        starts = np.zeros(n_shards, dtype=np.int64)
        np.cumsum(counts[:-1], out=starts[1:])
        rank = np.arange(len(dest), dtype=np.int64) - starts[dest[order]]
        self.order = order
        self.slot = dest[order] * self.cap + rank
        self.nbytes = 0

    def skewed(self, n_input_bucket: int) -> bool:
        return self.n_shards * self.cap > max(
            SKEW_CAP_FACTOR * n_input_bucket, 16 * self.n_shards)

    def scatter(self, lane: np.ndarray, fill) -> np.ndarray:
        """One lane -> [n_shards, cap] blocks (live rows only)."""
        out = np.full(self.n_shards * self.cap, fill, dtype=lane.dtype)
        out[self.slot] = lane[self.live_idx][self.order]
        self.nbytes += out.nbytes
        return out.reshape(self.n_shards, self.cap)

    def scatter_ids(self) -> np.ndarray:
        """Global row-index lane (fill -1 marks padding slots)."""
        out = np.full(self.n_shards * self.cap, -1, dtype=np.int64)
        out[self.slot] = self.live_idx[self.order]
        return out.reshape(self.n_shards, self.cap)


def _live_masks(n_left, n_right, lnull, rnull, lvalid, rvalid):
    lv = np.ones(n_left, dtype=bool) if lvalid is None \
        else np.asarray(lvalid[:n_left], dtype=bool)
    rv = np.ones(n_right, dtype=bool) if rvalid is None \
        else np.asarray(rvalid[:n_right], dtype=bool)
    return lv, rv


def _common_key_dtype(lk: np.ndarray, rk: np.ndarray):
    """Coerce both key lanes to one dtype BEFORE hashing: 5 and 5.0 must
    land in the same partition (the raw bit patterns differ)."""
    if lk.dtype != rk.dtype:
        return lk.astype(np.float64), rk.astype(np.float64)
    return lk, rk


# ---- partitioned build/probe unique join ----------------------------------

def _local_unique_join_kernel(mesh, cap_p: int, cap_b: int, kdtype: str):
    """Per-shard local unique join over partition blocks: sort the build
    block by (key, liveness) — live row first among equal keys, so a
    padding slot never shadows a live one — then searchsorted each probe
    key.  Outputs stay block-shaped; the host maps them back to probe
    order through the id lanes."""
    j = kernels.jax()
    jn = kernels.jnp()
    shard_map, P = dist.shard_map_fn()

    def body(pk, pid, bk, bid):
        from jax import lax
        blive = bid >= 0
        sentinel = (jn.iinfo(jn.int64).max if bk.dtype == jn.int64
                    else jn.inf)
        kmask = jn.where(blive, bk, sentinel)
        inv = (~blive).astype(jn.int32)
        sk, sinv, sperm = lax.sort(
            (kmask, inv, jn.arange(cap_b, dtype=jn.int64)), num_keys=2)
        lo = jn.searchsorted(sk, pk, side="left")
        loc = jn.clip(lo, 0, cap_b - 1)
        hit = (pid >= 0) & (lo < cap_b) & (sk[loc] == pk) \
            & (sinv[loc] == 0)
        brow = bid[sperm[loc]]
        return hit, jn.where(hit, brow, -1)

    sm = shard_map(body, mesh=mesh,
                   in_specs=(P("shard"), P("shard"), P("shard"),
                             P("shard")),
                   out_specs=(P("shard"), P("shard")))

    def kernel(pk, pid, bk, bid):
        # blocks travel flattened [n*cap] so the 1-D shard axis carries
        # whole partitions; the body sees its own [cap] slice
        hit, brow = sm(pk.reshape(-1), pid.reshape(-1),
                       bk.reshape(-1), bid.reshape(-1))
        return hit, brow

    return kernels.counted_jit(kernel)


def unique_join_match_sharded(mesh, lkey, n_left: int, rkey, n_right: int,
                              outer: bool = False,
                              lvalid: np.ndarray = None,
                              rvalid: np.ndarray = None):
    """Partitioned build/probe unique join over the mesh: same (li, ri)
    contract and tie semantics as kernels.unique_join_match, or None
    when sharding does not apply (skew, non-numeric keys, tiny input).
    Host work is the O(n) partition scatter; the per-partition sort +
    probe — the actual O(n log n) — runs one-partition-per-device."""
    n = dist.mesh_shards(mesh)
    if n < 2 or not isinstance(lkey[0], np.ndarray) \
            or not isinstance(rkey[0], np.ndarray):
        return None
    lk = np.asarray(lkey[0])[:n_left]
    ln = np.asarray(lkey[1])[:n_left]
    rk = np.asarray(rkey[0])[:n_right]
    rn = np.asarray(rkey[1])[:n_right]
    if lk.dtype not in (np.int64, np.float64) \
            or rk.dtype not in (np.int64, np.float64):
        return None
    lk, rk = _common_key_dtype(lk, rk)
    lv, rv = _live_masks(n_left, n_right, ln, rn, lvalid, rvalid)
    l_live = lv & ~ln
    r_live = rv & ~rn
    if not r_live.any():
        if outer:
            li = np.nonzero(lv)[0].astype(np.int64)
            return li, np.full(len(li), -1, dtype=np.int64)
        z = np.empty(0, dtype=np.int64)
        return z, z
    fail.inject("shardExchangeStall")
    pp = _Partitioned(lk, l_live, n)
    pb = _Partitioned(rk, r_live, n)
    nlb = kernels.bucket(max(n_left, 1))
    nrb = kernels.bucket(max(n_right, 1))
    if pp.skewed(nlb) or pb.skewed(nrb):
        record_skew_retry()
        return None
    kdtype = str(lk.dtype)
    key = ("join_sharded", _shards_tag(mesh), pp.cap, pb.cap, kdtype)
    fn = progcache.get(key, lambda: _local_unique_join_kernel(
        mesh, pp.cap, pb.cap, kdtype))
    pk_h, pi_h = pp.scatter(lk, 0), pp.scatter_ids()
    bk_h, bi_h = pb.scatter(rk, 0), pb.scatter_ids()
    record_exchange(pp.nbytes + pb.nbytes)
    note_round(max(pp.cap, pb.cap))
    t0 = time.perf_counter()
    pkb, pib = kernels.h2d(pk_h), kernels.h2d(pi_h)
    bkb, bib = kernels.h2d(bk_h), kernels.h2d(bi_h)
    hit, brow = kernels.d2h_many(fn(pkb, pib, bkb, bib))
    _note_device_region(t0)
    hit = hit.reshape(-1)
    brow = brow.reshape(-1)
    flat_ids = pp.scatter_ids().reshape(-1)
    sel = flat_ids >= 0
    match = np.zeros(n_left, dtype=bool)
    cand = np.full(n_left, -1, dtype=np.int64)
    match[flat_ids[sel]] = hit[sel]
    cand[flat_ids[sel]] = brow[sel]
    if outer:
        li = np.nonzero(lv)[0].astype(np.int64)
        return li, np.where(match[li], cand[li], -1).astype(np.int64)
    li = np.nonzero(match)[0].astype(np.int64)
    return li, cand[li]


# ---- partitioned semi / anti join -----------------------------------------

def _local_member_kernel(mesh, cap_p: int, cap_b: int, kdtype: str):
    """Per-shard membership bit over partition blocks (semi/anti share
    it; the three-valued NOT IN ladder applies host-side with the
    host-known build globals)."""
    jn = kernels.jnp()
    shard_map, P = dist.shard_map_fn()

    def body(pk, pid, bk, bid):
        from jax import lax
        blive = bid >= 0
        sentinel = (jn.iinfo(jn.int64).max if bk.dtype == jn.int64
                    else jn.inf)
        kmask = jn.where(blive, bk, sentinel)
        inv = (~blive).astype(jn.int32)
        sk, sinv, _ = lax.sort(
            (kmask, inv, jn.arange(cap_b, dtype=jn.int64)), num_keys=2)
        lo = jn.searchsorted(sk, pk, side="left")
        loc = jn.clip(lo, 0, cap_b - 1)
        member = (pid >= 0) & (lo < cap_b) & (sk[loc] == pk) \
            & (sinv[loc] == 0)
        return member

    sm = shard_map(body, mesh=mesh,
                   in_specs=(P("shard"), P("shard"), P("shard"),
                             P("shard")),
                   out_specs=P("shard"))

    def kernel(pk, pid, bk, bid):
        return sm(pk.reshape(-1), pid.reshape(-1),
                  bk.reshape(-1), bid.reshape(-1))

    return kernels.counted_jit(kernel)


def semi_join_match_sharded(mesh, lkey, n_left: int, rkey, n_right: int,
                            anti: bool = False, null_aware: bool = False,
                            lvalid: np.ndarray = None,
                            rvalid: np.ndarray = None):
    """Partitioned semi/anti membership over the mesh: probe and build
    sides hash-partition with the spill partitioner, each shard answers
    membership for its partition, and the host applies the exact
    semi/anti/NOT IN keep ladder (kernels._np_semi_match semantics) over
    the re-assembled member bits.  Returns surviving probe indices in
    probe order, or None when sharding does not apply."""
    n = dist.mesh_shards(mesh)
    if n < 2 or not isinstance(lkey[0], np.ndarray) \
            or not isinstance(rkey[0], np.ndarray):
        return None
    lk = np.asarray(lkey[0])[:n_left]
    ln = np.asarray(lkey[1])[:n_left]
    rk = np.asarray(rkey[0])[:n_right]
    rn = np.asarray(rkey[1])[:n_right]
    if lk.dtype not in (np.int64, np.float64) \
            or rk.dtype not in (np.int64, np.float64):
        return None
    lk, rk = _common_key_dtype(lk, rk)
    lv, rv = _live_masks(n_left, n_right, ln, rn, lvalid, rvalid)
    n_build = int(rv.sum())
    if n_build == 0:
        keep = lv if anti else np.zeros(n_left, dtype=bool)
        return np.nonzero(keep)[0].astype(np.int64)
    if anti and null_aware and bool((rv & rn).any()):
        return np.empty(0, dtype=np.int64)
    fail.inject("shardExchangeStall")
    l_live = lv & ~ln
    pp = _Partitioned(lk, l_live, n)
    pb = _Partitioned(rk, rv & ~rn, n)
    nlb = kernels.bucket(max(n_left, 1))
    nrb = kernels.bucket(max(n_right, 1))
    if pp.skewed(nlb) or pb.skewed(nrb):
        record_skew_retry()
        return None
    kdtype = str(lk.dtype)
    key = ("semi_sharded", _shards_tag(mesh), pp.cap, pb.cap, kdtype)
    fn = progcache.get(key, lambda: _local_member_kernel(
        mesh, pp.cap, pb.cap, kdtype))
    pk_h, pi_h = pp.scatter(lk, 0), pp.scatter_ids()
    bk_h, bi_h = pb.scatter(rk, 0), pb.scatter_ids()
    record_exchange(pp.nbytes + pb.nbytes)
    note_round(max(pp.cap, pb.cap))
    t0 = time.perf_counter()
    pkb, pib = kernels.h2d(pk_h), kernels.h2d(pi_h)
    bkb, bib = kernels.h2d(bk_h), kernels.h2d(bi_h)
    mem_flat = kernels.d2h(fn(pkb, pib, bkb, bib)).reshape(-1)
    _note_device_region(t0)
    flat_ids = pp.scatter_ids().reshape(-1)
    sel = flat_ids >= 0
    member = np.zeros(n_left, dtype=bool)
    member[flat_ids[sel]] = mem_flat[sel]
    if anti:
        keep = lv & ~member
        if null_aware:
            keep &= ~ln
    else:
        keep = member
    return np.nonzero(keep)[0].astype(np.int64)


# ---- sharded partial->final scalar aggregation ----------------------------

def fused_scalar_aggregate_sharded(mesh, dev_cols, agg_specs, arg_exprs,
                                   n_rows: int, nb: int, mask,
                                   program_key: tuple = (), params=None,
                                   batchable: bool = False):
    """Mesh variant of kernels.fused_scalar_aggregate: rows shard over
    the mesh axis, each shard computes the masked partial reductions
    with arguments evaluated on-device, and the partial states merge
    ONCE with psum/pmin/pmax.  Output contract identical to the
    single-device kernel (_unpack_scalar_agg).

    STACKABLE: the packed kernel carries a stacking recipe, so a batch
    round's stacked variant vmaps B param sets over the N-shard program
    — B queries x N shards in one dispatch (jax.vmap composes over
    shard_map; verified on the forced host mesh)."""
    j = kernels.jax()
    jn = kernels.jnp()
    n_dev = dist.mesh_shards(mesh)
    assert nb % n_dev == 0, (nb, n_dev)
    mask_fn, mask_key, mask_arr = kernels._mask_parts(mask)
    dev_shape = tuple(0 if c is None else (1 if c[0] is None else 2)
                      for c in dev_cols)
    key = ("scalar_sharded", tuple(agg_specs), program_key, mask_key, nb,
           _shards_tag(mesh), dev_shape)
    rnd = kernels._batch_round(mask, params, batchable)
    if rnd is not None and rnd.collecting:
        ent = progcache.peek(key)
        if ent is not None:
            rnd.park(key, ent[0], (tuple(dev_cols), mask_arr), params)

    def build():
        arg_fns = [kernels._lower_arg(e) for e in arg_exprs]
        shard_map, P = dist.shard_map_fn()
        col_spec = tuple(
            ((P("shard") if c[0] is not None else None, P("shard"))
             if c is not None else None)
            for c in dev_cols)

        def make_kernel():
            kernel_schema: list = []

            def body(cols, mask_in, pr):
                rows_local = nb // n_dev
                shard = j.lax.axis_index("shard")
                base = shard.astype(jn.int64) * rows_local
                if mask_fn is not None:
                    valid = mask_fn(cols, pr,
                                    jn.arange(rows_local) + base)
                else:
                    valid = mask_in
                outs = []
                for (func, has_arg), af in zip(agg_specs, arg_fns):
                    av = an = None
                    if has_arg and af is not None:
                        av, an = af(cols, pr)
                    if func == "count_star":
                        c = j.lax.psum(
                            jn.sum(valid.astype(jn.int64)), "shard")
                        outs.append((c[None], jn.zeros(1, dtype=bool)))
                        continue
                    live = valid & ~an
                    cnt = j.lax.psum(
                        jn.sum(live.astype(jn.int64)), "shard")
                    if func == "count":
                        outs.append((cnt[None], jn.zeros(1, dtype=bool)))
                    elif func in ("sum", "sum0"):
                        total = j.lax.psum(
                            jn.sum(jn.where(live, av, 0)), "shard")
                        outs.append((total[None],
                                     jn.zeros(1, dtype=bool)
                                     if func == "sum0"
                                     else (cnt == 0)[None]))
                    elif func in ("min", "max"):
                        if av.dtype == jn.int64:
                            fill = (jn.iinfo(jn.int64).max
                                    if func == "min"
                                    else jn.iinfo(jn.int64).min)
                        else:
                            fill = jn.inf if func == "min" else -jn.inf
                        red = jn.min if func == "min" else jn.max
                        local = red(jn.where(live, av, fill))
                        merged = (j.lax.pmin(local, "shard")
                                  if func == "min"
                                  else j.lax.pmax(local, "shard"))
                        outs.append((merged[None], (cnt == 0)[None]))
                    else:  # pragma: no cover
                        raise ValueError(func)
                n_valid = j.lax.psum(
                    jn.sum(valid.astype(jn.int64)), "shard")
                # first valid GLOBAL row index (0 when none — the
                # single-device argmax convention); the sentinel nb maps
                # empty shards past every real row before the pmin
                local_first = jn.where(jn.any(valid),
                                       jn.argmax(valid) + base, nb)
                first = j.lax.pmin(local_first, "shard")
                first = jn.where(first >= nb, 0, first)
                items = [n_valid[None], first[None]]
                for v, m in outs:
                    items += [v, m]
                return items

            sm = shard_map(
                body, mesh=mesh,
                in_specs=(col_spec, P("shard"), (P(), P())),
                out_specs=P())

            def packed(cols, mask_in, pr):
                return kernels.pack_arrays(kernel_schema,
                                          sm(cols, mask_in, pr))
            return packed, kernel_schema

        packed, kernel_schema = make_kernel()
        return kernels._stackable_jit(packed, "packed", 2, make_kernel), \
            kernel_schema
    fn, schema = progcache.get(key, build)
    note_round(nb // n_dev)
    if rnd is not None and rnd.replaying:
        got = rnd.consume(key, (tuple(dev_cols), mask_arr), params)
        if got is not None:
            tag, val = got
            vals = kernels.unpack_host(val, schema) if tag == "host" \
                else kernels.unpack_flat(val, schema)
            return kernels._unpack_scalar_agg(vals)
    t0 = time.perf_counter()
    out = kernels._unpack_scalar_agg(kernels.unpack_flat(
        fn(tuple(dev_cols), mask_arr, kernels._params_dev(params)),
        schema))
    _note_device_region(t0)
    return out


# ---- sharded sort / top-k --------------------------------------------------

def _neg_score(jn, s):
    """Order-reversing bijection on the score lane (bigger-is-earlier ->
    ascending sort key): ~ for int64 (overflow-free), - for float64."""
    return ~s if s.dtype == jn.int64 else -s


def _sort_rank_kernel(mesh, n_shards: int, sdtype: str):
    """Per-shard stable sort + exact global rank merge: each shard sorts
    its contiguous row slice, all_gathers every shard's sorted run, and
    counts — via searchsorted — how many rows order strictly before each
    of its own (ties count when they live in an earlier shard, i.e. at a
    lower global row index).  The resulting ranks are a permutation of
    0..nb-1 that reproduces the single-device stable lexsort exactly."""
    jn = kernels.jnp()
    shard_map, P = dist.shard_map_fn()

    def body(score):
        from jax import lax
        i = lax.axis_index("shard")
        neg = _neg_score(jn, score)
        m = neg.shape[0]
        order = jn.argsort(neg, stable=True)
        run = neg[order]
        inv = jn.zeros(m, dtype=jn.int64).at[order].set(
            jn.arange(m, dtype=jn.int64))
        runs = lax.all_gather(run, "shard")
        rank = inv
        for s in range(n_shards):
            r = jn.searchsorted(runs[s], neg, side="right")
            l = jn.searchsorted(runs[s], neg, side="left")
            rank = rank + jn.where(s < i, r, jn.where(s > i, l, 0))
        return rank

    return kernels.counted_jit(shard_map(
        body, mesh=mesh, in_specs=P("shard"), out_specs=P("shard")))


def _score_pad(score: np.ndarray, nb: int) -> np.ndarray:
    """Pad the score lane with the WORST sentinel (strictly after every
    real row; ties inside the sentinel class resolve by row index, which
    keeps padding after the equal-scored real rows)."""
    pad = np.iinfo(np.int64).min if score.dtype == np.int64 else -np.inf
    return kernels.pad1(score, nb, pad)


def sort_permutation_sharded(mesh, key_cols, descs, n_rows: int):
    """Sharded ORDER BY permutation: per-shard sort + exact device rank
    merge.  Single-key orders only (the total-order score mapping);
    returns None when the mapping is unsafe or sharding does not apply —
    callers fall back to the single-device kernel."""
    n = dist.mesh_shards(mesh)
    if n < 2 or len(key_cols) != 1:
        return None
    nb = kernels.bucket(max(n_rows, 1))
    if not dist.shardable(nb, mesh):
        return None
    score = kernels._primary_score(key_cols[0], descs[0], n_rows)
    if score is None:
        return None
    score = np.asarray(score[:n_rows])
    sdtype = str(score.dtype)
    key = ("sort_sharded", nb, _shards_tag(mesh), sdtype)
    fn = progcache.get(key, lambda: _sort_rank_kernel(mesh, n, sdtype))
    note_round(nb // n)
    sp = _score_pad(score, nb)
    t0 = time.perf_counter()
    rank = kernels.d2h(fn(kernels.h2d(sp)))
    _note_device_region(t0)
    perm = np.empty(nb, dtype=np.int64)
    perm[rank] = np.arange(nb, dtype=np.int64)
    return perm[:n_rows]


def _topk_merge_kernel(mesh, n_shards: int, kb: int, m: int, sdtype: str):
    """Per-shard lax.top_k + all_gather + replicated final selection:
    the classic tournament — any global top-k row is in its shard's
    top-k, and the flattened candidate order (shard-major, score-desc /
    index-asc within a run) makes lax.top_k's lowest-index tie-break
    reproduce the exact global (score desc, row index asc) order."""
    jn = kernels.jnp()
    _, P = dist.shard_map_fn()

    def body(score):
        from jax import lax
        i = lax.axis_index("shard")
        v, idx = lax.top_k(score, kb)
        gid = idx.astype(jn.int64) + i.astype(jn.int64) * m
        gv = lax.all_gather(v, "shard").reshape(n_shards * kb)
        gi = lax.all_gather(gid, "shard").reshape(n_shards * kb)
        _, fi = lax.top_k(gv, kb)
        return gi[fi]

    return kernels.counted_jit(dist.shard_map_unchecked(
        body, mesh, in_specs=P("shard"), out_specs=P()))


def top_k_sharded(mesh, key_cols, descs, n_rows: int, k: int):
    """Sharded top-k row selection (single-key, score-mapped): returns
    the k row indices in requested order, or None when sharding does not
    apply — same contract as kernels._topk_single."""
    n = dist.mesh_shards(mesh)
    if n < 2 or len(key_cols) != 1 or k <= 0:
        return None
    nb = kernels.bucket(max(n_rows, 1))
    if not dist.shardable(nb, mesh):
        return None
    m = nb // n
    if k > m:
        return None  # a shard cannot bound the candidate set
    score = kernels._primary_score(key_cols[0], descs[0], n_rows)
    if score is None:
        return None
    score = np.asarray(score[:n_rows])
    kb = min(kernels.bucket(max(k, 1)), m)
    sdtype = str(score.dtype)
    key = ("topk_sharded", nb, kb, _shards_tag(mesh), sdtype)
    fn = progcache.get(
        key, lambda: _topk_merge_kernel(mesh, n, kb, m, sdtype))
    note_round(m)
    sp = _score_pad(score, nb)
    t0 = time.perf_counter()
    ids = kernels.d2h(fn(kernels.h2d(sp)))[:k]
    _note_device_region(t0)
    return ids[ids < n_rows]
