"""Opt-in dispatch-level device timing (``tidb_device_profile_rate``).

Every timing the engine publishes by default is a HOST wall: the
``dispatch`` span in ops/kernels.counted_jit wraps an *asynchronous* XLA
enqueue, so on a real accelerator it measures submit time, not device
time — the numbers it feeds into EXPLAIN ANALYZE, statements_summary,
and the bench are fiction there.  This module owns the opt-in truth
path: at a *sampled* dispatch, counted_jit closes the call with
``block_until_ready`` and records the measured wall

- into the per-query scope and the global counters through
  ``kernels.stats_add("device_s", ...)`` (so EXPLAIN ANALYZE's
  ``device:`` cell, statements_summary's ``sum_device_ms``, and the
  ``tinysql_device_busy_seconds_total`` ring series all agree),
- into the per-program catalog (ops/progcache.note_dispatch), and
- into the ``tinysql_dispatch_device_seconds`` histogram owned here.

Sampling is DETERMINISTIC — every ``round(1/rate)``-th dispatch — so
tests and repeated runs see stable counts.  Rate 0 (the default) is a
single dict read on the dispatch path and leaves results, program-cache
keys, and dispatch behavior byte-identical to an unprofiled process;
rate 1 forces a sync per dispatch, which also serializes the async
block pipeline's overlap — profile to diagnose, not as a steady state.

WRITE DISCIPLINE (qlint OB405): the device-time counter keys
(``device_s`` / ``profiled_dispatches`` / ``compile_s``) may be written
only from this module, ops/kernels.py, and ops/progcache.py — any other
writer would publish a host wall as device truth.
"""
from __future__ import annotations

import threading
from typing import Dict

#: upper bounds (seconds) of the device-time histogram buckets; +Inf
#: implied.  Device programs span ~10us (tiny bucketed kernels on a
#: local backend) to seconds (cold SF=10 aggregations over a tunnel).
DEVICE_TIME_BUCKETS_S = (1e-5, 2.5e-5, 5e-5, 1e-4, 2.5e-4, 5e-4,
                         1e-3, 2.5e-3, 5e-3, 1e-2, 2.5e-2, 5e-2,
                         0.1, 0.25, 0.5, 1.0, 2.5)

_mu = threading.Lock()

#: rate: fraction of dispatches closed with block_until_ready (0 = off,
#: clamped to [0, 1]); tick: the deterministic sampling counter
_STATE = {"rate": 0.0, "tick": 0}

_hist = [0] * (len(DEVICE_TIME_BUCKETS_S) + 1)
_hist_sum = 0.0
_hist_count = 0


def set_rate(rate: float) -> None:
    """Apply ``tidb_device_profile_rate`` (session SET hook / server
    start).  Process-global, like the compile-cache dir: there is one
    dispatch path."""
    try:
        r = float(rate)
    except (TypeError, ValueError):
        r = 0.0
    with _mu:
        _STATE["rate"] = min(max(r, 0.0), 1.0)


def rate() -> float:
    return _STATE["rate"]


def should_sample() -> bool:
    """The per-dispatch sampling decision: deterministic every-N-th
    (N = round(1/rate)), cheap single read when profiling is off."""
    r = _STATE["rate"]
    if r <= 0.0:
        return False
    if r >= 1.0:
        return True
    period = max(1, int(round(1.0 / r)))
    with _mu:
        _STATE["tick"] += 1
        return _STATE["tick"] % period == 0


def observe(seconds: float) -> None:
    """Record one sampled dispatch's measured device wall into the
    ``tinysql_dispatch_device_seconds`` histogram."""
    global _hist_sum, _hist_count
    with _mu:
        for i, le in enumerate(DEVICE_TIME_BUCKETS_S):
            if seconds <= le:
                _hist[i] += 1
                break
        else:
            _hist[-1] += 1
        _hist_sum += seconds
        _hist_count += 1


def histogram_snapshot() -> Dict[str, object]:
    """``{"buckets": [(le_s, count), ...], "overflow": n, "sum": s,
    "count": n}`` with PER-BUCKET (non-cumulative) counts — the same
    shape as stmtsummary.histogram_snapshot entries; /metrics renders
    the Prometheus cumulative form."""
    with _mu:
        return {"buckets": list(zip(DEVICE_TIME_BUCKETS_S, _hist)),
                "overflow": _hist[-1],
                "sum": _hist_sum, "count": _hist_count}


def snapshot() -> Dict[str, float]:
    with _mu:
        return {"rate": _STATE["rate"], "sampled": _hist_count,
                "device_s_sum": _hist_sum}


def reset() -> None:
    """Tests only."""
    global _hist, _hist_sum, _hist_count
    with _mu:
        _STATE["rate"] = 0.0
        _STATE["tick"] = 0
        _hist = [0] * (len(DEVICE_TIME_BUCKETS_S) + 1)
        _hist_sum = 0.0
        _hist_count = 0
