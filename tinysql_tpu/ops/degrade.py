"""Runtime device-loss degradation.

The planner's device enforcer promises a CPU fallback (ROADMAP north
star); this module supplies the RUNTIME half: when a compiled-program
dispatch or a device->host transfer dies mid-statement (TPU tunnel
dropped, device reset — surfaced by jax as ``XlaRuntimeError``, or
injected via the ``kernelDispatchError``/``kernelD2HError`` failpoints
raising :class:`DeviceLost`), the session

1. records the loss (counters below, exported to /metrics),
2. pins planning to the CPU tier for a cooldown window
   (``tidb_device_cooldown`` seconds; every ``Session._use_tpu`` read
   consults :func:`cpu_pinned`), and
3. transparently re-executes the statement once on the CPU volcano
   path — READ-ONLY statements only; writes surface the error, because
   a re-run after a partially-dispatched write is not idempotent.

Detection is conservative: only :class:`DeviceLost` and exception types
named like jax runtime/backend failures count — a TypeError from a
kernel bug must fail the statement loudly, not silently demote the
process to CPU.
"""
from __future__ import annotations

import threading
import time

DEFAULT_COOLDOWN_S = 30.0

#: exception type names that mean "the device/backend died", not "bug"
_DEVICE_ERROR_TYPES = ("XlaRuntimeError", "JaxRuntimeError",
                      "DeviceLost")


class DeviceLost(RuntimeError):
    """Raised (or injected) at the dispatch/transfer boundary when the
    accelerator vanished mid-statement."""


_mu = threading.Lock()
_pinned_until = 0.0
_losses = 0
_degraded_statements = 0


#: failpoints that sit ON the device boundary: a generic Injected error
#: from them models the accelerator dying (spec strings cannot name an
#: exception class, so `tidb_failpoints='kernelDispatchError=error(x)'`
#: must degrade exactly like a programmatic DeviceLost)
_DEVICE_FAILPOINTS = ("kernelDispatchError", "kernelD2HError")


def is_device_loss(exc: BaseException) -> bool:
    for e in (exc, exc.__cause__, exc.__context__):
        if e is None:
            continue
        if isinstance(e, DeviceLost):
            return True
        if type(e).__name__ in _DEVICE_ERROR_TYPES:
            return True
        if getattr(e, "failpoint", None) in _DEVICE_FAILPOINTS:
            return True
    return False


def record_loss(cooldown_s: float = DEFAULT_COOLDOWN_S) -> None:
    """One observed device loss: bump counters, open/extend the CPU pin
    window."""
    global _pinned_until, _losses
    until = time.monotonic() + max(0.0, float(cooldown_s))
    with _mu:
        _losses += 1
        _pinned_until = max(_pinned_until, until)
    try:
        from ..obs import context as _obs
        _obs.record("device_loss", 1)
    except Exception:
        pass


def record_degraded_statement() -> None:
    global _degraded_statements
    with _mu:
        _degraded_statements += 1


def cpu_pinned() -> bool:
    with _mu:
        return time.monotonic() < _pinned_until


def snapshot() -> dict:
    with _mu:
        return {"device_loss_total": _losses,
                "degraded_statements_total": _degraded_statements,
                "cpu_pinned": 1 if time.monotonic() < _pinned_until else 0}


def reset() -> None:
    """Tests only."""
    global _pinned_until, _losses, _degraded_statements
    with _mu:
        _pinned_until = 0.0
        _losses = 0
        _degraded_statements = 0
