"""Memory-adaptive spilling execution (PAPERS.md "Design Trade-offs for
a Robust Dynamic Hybrid Hash Join" / "Partial Partial Aggregates").

The middle ground between "fits in device memory" and "killed with 8175":
operators that would blow ``tidb_mem_quota_query`` partition their
working structures by a hash of the key, keep a bounded resident set,
and write cold partitions to a host-side spill store (disk-backed numpy
run files), byte-accounted through the statement's MemTracker
(``consume_soft``/``release`` — the live set *drops* when a partition
spills).  A partition that still overflows its budget recursively
repartitions with a fresh hash seed (bounded by ``tidb_spill_max_depth``);
only exhaustion of that ladder raises the typed 8175.

Four entry points, one skeleton:

- :func:`partitioned_join` — the hybrid hash join: build side hashed
  into partitions (spilled cold), probe rows routed to their partition,
  per-partition match through the UNCHANGED kernels (``join_match`` /
  ``unique_join_match``), results restored to the unpartitioned
  kernels' exact (li, ri) order;
- :func:`partitioned_segment_aggregate` — hash agg: rows partitioned by
  group-id hash (a group lands WHOLLY in one partition, so per-group
  accumulation order — and thus float sums — is preserved), partial
  aggregates per partition, disjoint group sets merged at drain;
- :func:`external_sort_permutation` — sorted run files + a vectorized
  bounded-fan-in k-way merge tie-broken by original row id,
  reproducing the full lexsort's exact permutation;
- :func:`external_topk` — per-run top-k candidates carried THROUGH the
  store, merged block-by-block (the blockwise-TopN math, run-file
  edition).

Trigger: :func:`maybe_context` — the ``spillForceAll`` failpoint, the
tracker's soft watermark (``tidb_mem_quota_spill_ratio`` × quota), or a
planner-estimate (estRows × row bytes) that already exceeds the
watermark headroom.

Everything here is observable: module STATS (``tinysql_spill_*`` on
/metrics and the time-series ring), per-query counters through the obs
fan-out (statements_summary ``sum/max_spill_bytes``/``spill_count``,
EXPLAIN ANALYZE device info), and ``spill``-category trace spans for the
store/reload legs.
"""
from __future__ import annotations

import os
import shutil
import tempfile
import threading
import weakref
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from .. import fail
from ..utils import interrupt
from ..utils.memory import MemQuotaExceeded, MemTracker

# ---- observable state ------------------------------------------------------

_mu = threading.Lock()
#: process-cumulative spill economics (rendered on /metrics, sampled into
#: the time-series ring); ``open_slots`` is a live gauge
STATS: Dict[str, float] = {
    "spill_bytes": 0, "spill_reload_bytes": 0, "spill_partitions": 0,
    "spill_repartitions": 0, "spill_stream_runs": 0,
    "spilled_statements": 0, "open_slots": 0,
}

#: default resident budget when spilling is FORCED without a quota
#: (spillForceAll): small enough that every partition actually spills
FORCED_BUDGET = 1 << 16
#: floor for the resident budget derived from a real quota — small: a
#: tight quota needs the spill layer to hold almost nothing resident
MIN_BUDGET = 1 << 16
#: partition-count clamp
MIN_PARTS, MAX_PARTS = 2, 128


def _record(key: str, n: float = 1) -> None:
    """Bump a STATS key and fan into the per-query obs scope (the same
    double-entry bookkeeping kernels.stats_add does)."""
    with _mu:
        STATS[key] = STATS.get(key, 0) + n
    try:
        from ..obs import context as _obs
        if key == "spill_bytes":
            q = _obs.current()
            if q is not None and not q.device_totals().get("spill_bytes"):
                with _mu:
                    STATS["spilled_statements"] += 1
        _obs.record(key, n)
    except Exception:
        pass


def _gauge(key: str, delta: int) -> None:
    with _mu:
        STATS[key] = STATS.get(key, 0) + delta


def stats_snapshot() -> Dict[str, float]:
    with _mu:
        return dict(STATS)


def reset_stats() -> None:
    """Tests only."""
    with _mu:
        for k in STATS:
            STATS[k] = 0


def _span(name: str, **args):
    from ..obs import context as _obs
    return _obs.span(name, cat="spill", **args)


# ---- hashing ---------------------------------------------------------------

#: per-depth seeds: recursion at depth d rehashes with a different mix,
#: so a skewed partition redistributes instead of re-colliding
_SEEDS = (0x9E3779B97F4A7C15, 0xBF58476D1CE4E5B9, 0x94D049BB133111EB,
          0xD6E8FEB86659FD93, 0xA5A3564A1F871D1F, 0xC2B2AE3D27D4EB4F,
          0x165667B19E3779F9, 0x27D4EB2F165667C5)


def hash_partition(keys: np.ndarray, depth: int, n_parts: int) -> np.ndarray:
    """Partition ids for ``keys`` (int64 or float64) at recursion level
    ``depth``: splitmix64-style avalanche over the raw 64-bit pattern.
    Equal keys always land in the same partition at every depth."""
    v = np.ascontiguousarray(keys)
    if v.dtype != np.int64:
        v = np.ascontiguousarray(v, dtype=np.float64)
        # -0.0 and 0.0 compare equal but differ bitwise: canonicalize
        v = np.where(v == 0.0, 0.0, v)
    u = v.view(np.uint64).copy()
    with np.errstate(over="ignore"):
        u += np.uint64(_SEEDS[depth % len(_SEEDS)])
        u ^= u >> np.uint64(30)
        u *= np.uint64(0xBF58476D1CE4E5B9)
        u ^= u >> np.uint64(27)
        u *= np.uint64(0x94D049BB133111EB)
        u ^= u >> np.uint64(31)
    return (u % np.uint64(n_parts)).astype(np.int64)


# ---- the spill store -------------------------------------------------------

class SpillError(RuntimeError):
    """Typed spill-store failure (a failed partition write/reload is an
    I/O-layer statement error, not an engine bug)."""
    mysql_code = 1105
    sqlstate = "HY000"


class SpillSlot:
    """One spilled partition / run: a set of .npy files on disk."""

    __slots__ = ("seq", "paths", "nbytes", "rows")

    def __init__(self, seq: int, paths: Dict[str, str], nbytes: int,
                 rows: int):
        self.seq = seq
        self.paths = paths
        self.nbytes = nbytes
        self.rows = rows


class SpillStore:
    """Disk-backed partition store: one temp directory per store, one
    ``.npy`` file per array (memmap-able for the sort merge).  ``close``
    removes everything; the module-level ``open_slots`` gauge proves no
    partition leaks across statements (the chaos suite checks it)."""

    def __init__(self, tag: str = "op"):
        self._tag = tag
        self._dir: Optional[str] = None
        self._seq = 0
        self._live = 0
        self._closed = False

    def _ensure_dir(self) -> str:
        if self._dir is None:
            base = os.environ.get("TINYSQL_SPILL_DIR") or None
            self._dir = tempfile.mkdtemp(prefix=f"tinysql-spill-{self._tag}-",
                                         dir=base)
        return self._dir

    def put(self, arrays: Dict[str, np.ndarray], rows: int) -> SpillSlot:
        fail.inject("spillPartitionError")
        if self._closed:
            raise SpillError("spill store already closed")
        d = self._ensure_dir()
        seq = self._seq
        self._seq += 1
        paths = {}
        nbytes = 0
        try:
            for name, arr in arrays.items():
                p = os.path.join(d, f"s{seq}.{name}.npy")
                np.save(p, np.ascontiguousarray(arr))
                paths[name] = p
                nbytes += arr.nbytes
        except OSError as e:
            for p in paths.values():
                try:
                    os.unlink(p)
                except OSError:
                    pass
            raise SpillError(f"partition write failed: {e}") from e
        self._live += 1
        _gauge("open_slots", 1)
        return SpillSlot(seq, paths, nbytes, rows)

    def load(self, slot: SpillSlot, mmap: bool = False) \
            -> Dict[str, np.ndarray]:
        fail.inject("spillReloadError")
        try:
            mode = "r" if mmap else None
            return {name: np.load(p, mmap_mode=mode)
                    for name, p in slot.paths.items()}
        except OSError as e:
            raise SpillError(f"partition reload failed: {e}") from e

    def free(self, slot: SpillSlot) -> None:
        for p in slot.paths.values():
            try:
                os.unlink(p)
            except OSError:
                pass
        if slot.paths:
            slot.paths = {}
            self._live -= 1
            _gauge("open_slots", -1)

    def live_slots(self) -> int:
        return self._live

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._live:
            _gauge("open_slots", -self._live)
            self._live = 0
        if self._dir is not None:
            shutil.rmtree(self._dir, ignore_errors=True)
            self._dir = None

    def __del__(self):  # backstop; operators close() explicitly
        try:
            self.close()
        except Exception:
            pass


# ---- the spill context -----------------------------------------------------

#: live spill scopes, weakly held — the HBM census walks their resident
#: partitions so a future device-resident spill working set is born
#: attributed (today's partitions are host numpy: the category reads 0)
_LIVE_CONTEXTS: "weakref.WeakSet[SpillContext]" = weakref.WeakSet()


def _census_working_sets():
    for ctx in list(_LIVE_CONTEXTS):
        for part in list(getattr(ctx, "_resident", ())):
            arrays = getattr(part, "arrays", None)
            if arrays:
                yield list(arrays.values())


from ..obs import memprof as _memprof  # noqa: E402  (cycle-free: memprof
#                                        imports no ops module at top level)
_memprof.register_census_walker("spill", _census_working_sets)


class SpillContext:
    """Per-operator spill scope: budget, partition fan-out, recursion
    bound, the store, and the tracker the partition residency charges
    through.  Create via :func:`maybe_context`; always ``close()``."""

    def __init__(self, tracker: Optional[MemTracker], n_parts: int,
                 max_depth: int, budget: int, spill_all: bool,
                 enforce: bool, label: str = "op"):
        self.tracker = tracker
        self.n_parts = max(int(n_parts), MIN_PARTS)
        self.max_depth = max(int(max_depth), 0)
        #: resident-partition byte budget; with ``enforce``, a single
        #: partition above this repartitions (or, at depth exhaustion,
        #: aborts typed)
        self.budget = max(int(budget), 1)
        #: spillForceAll: write EVERY partition through the store
        self.spill_all = spill_all
        #: True only under a real tidb_mem_quota_query: budget overflow
        #: recursion/abort applies.  Forced spilling WITHOUT a quota
        #: must degrade gracefully on any data, never abort.
        self.enforce = enforce
        self.label = label
        self.store = SpillStore(tag=label)
        _LIVE_CONTEXTS.add(self)
        #: resident partitions, evictable on demand: the tracker's
        #: pressure callback (fired when a chunk allocation crosses the
        #: watermark or would cross the hard quota) spills them, so
        #: ordinary allocations see the freed bytes instead of 8175
        self._resident: List["_Partition"] = []
        self._closed = False
        if tracker is not None:
            tracker.on_pressure(self._evict_resident)
            # while this context lives, the tracker's hard abort defers
            # to THIS layer (overflow() at repartition exhaustion owns
            # the typed 8175); mark_used() makes the deferral sticky
            # once a route actually runs — see MemTracker.spill_enter
            tracker.spill_enter()

    def _evict_resident(self) -> None:
        for part in list(self._resident):
            try:
                part.spill(self)
            except Exception:
                # eviction is best-effort; the hard-quota re-check still
                # enforces the budget if nothing could move
                break

    def note_resident(self, part: "_Partition") -> None:
        self._resident.append(part)

    def note_gone(self, part: "_Partition") -> None:
        try:
            self._resident.remove(part)
        except ValueError:
            pass

    # -- accounting helpers --------------------------------------------------
    def charge(self, n: int) -> None:
        if self.tracker is not None:
            self.tracker.consume_soft(n)

    def release(self, n: int) -> None:
        if self.tracker is not None:
            self.tracker.release(n)

    def spilled(self, nbytes: int) -> None:
        _record("spill_partitions")
        _record("spill_bytes", nbytes)

    def reloaded(self, nbytes: int) -> None:
        _record("spill_reload_bytes", nbytes)

    def repartitioned(self) -> None:
        _record("spill_repartitions")

    def fits(self, nbytes: int) -> bool:
        """Can a partition of ``nbytes`` be loaded resident for
        processing?  The soft budget is the residency TARGET; the
        tracker's hard-quota headroom is the true bound — a partition
        that fits in the remaining quota processes in one piece (after
        evicting the resident set to make room), recursion is for
        partitions that genuinely cannot.  A one-group aggregation
        partition can never split by rehashing, but its output state is
        tiny: as long as its rows fit the quota it must aggregate, not
        die."""
        if nbytes <= self.budget:
            return True
        t = self.tracker
        if t is None or t.quota <= 0:
            return False
        if nbytes > t.quota - t.consumed:
            self._evict_resident()
        return nbytes <= t.quota - t.consumed

    def overflow(self, nbytes: int) -> MemQuotaExceeded:
        """The true last resort: recursive repartition exhausted and the
        partition still exceeds the working-set budget."""
        quota = self.tracker.quota if self.tracker is not None else 0
        return MemQuotaExceeded(
            nbytes, quota,
            detail=f"spill partition of {nbytes} bytes still exceeds the "
                   f"{self.budget}-byte working-set budget after "
                   f"{self.max_depth} recursive repartition level(s)")

    def mark_used(self) -> None:
        """Route entry: output assembly over the route's soft-charged
        staging outlives this context, so the abort deferral must
        survive close() — but ONLY when a route really ran (a context
        opened then closed unused restores hard enforcement)."""
        if self.tracker is not None:
            self.tracker.spill_engage()

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self.tracker is not None:
            self.tracker.remove_pressure(self._evict_resident)
            self.tracker.spill_exit()
        self._resident.clear()
        self.store.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def force_all_armed() -> bool:
    """Is the ``spillForceAll`` failpoint armed?  (A ``return`` action:
    evaluating it consumes one fire.)"""
    try:
        return bool(fail.eval_point("spillForceAll"))
    except Exception:
        return False


def _quota_wants_spill(tracker: Optional[MemTracker],
                       est_bytes: int) -> bool:
    """The tracker side of the spill decision: the soft watermark has
    been crossed (reactive), or the planner's estimate prices the
    working set above the watermark headroom (proactive)."""
    if tracker is None or tracker.quota <= 0:
        return False
    return tracker.spill_requested() or (
        tracker.spill_watermark > 0 and est_bytes > tracker.headroom())


def would_spill(tracker: Optional[MemTracker], est_rows: float,
                row_bytes: int) -> bool:
    """:func:`maybe_context`'s yes/no, side-effect-free: no
    ``spillForceAll`` fire consumed (``fail.is_armed``, not eval — a
    counted ``N*`` arming stays intact for the operator gates), no
    SpillContext or store built.  For probes (the devpipe pipeline's
    step-aside decision) that only need the answer."""
    if fail.is_armed("spillForceAll"):
        return True
    est_bytes = int(max(est_rows, 0) * max(row_bytes, 1))
    return _quota_wants_spill(tracker, est_bytes)


def _sysvar_int(session_vars, name: str, default: int) -> int:
    try:
        v = session_vars.get(name, default)
        return int(v) if v is not None else default
    except (TypeError, ValueError):
        return default


def choose_partitions(est_bytes: int, budget: int,
                      override: int = 0) -> int:
    """Partition fan-out: enough that an average partition fits well
    inside the resident budget (×4 headroom for skew), power-of-two,
    clamped to [MIN_PARTS, MAX_PARTS].  ``override`` pins it
    (tidb_spill_partitions)."""
    if override > 0:
        p = override
    else:
        target = max(budget // 4, 1)
        p = -(-max(est_bytes, 1) // target)  # ceil div
    np2 = 1
    while np2 < p:
        np2 <<= 1
    return min(max(np2, MIN_PARTS), MAX_PARTS)


def maybe_context(session_vars, tracker: Optional[MemTracker],
                  est_rows: float, row_bytes: int,
                  label: str) -> Optional[SpillContext]:
    """The ONE spill-mode decision all operators share.  Returns a live
    SpillContext (caller must close) when the operator should run its
    partitioned path, else None:

    - ``spillForceAll`` armed — always (chaos / CI / bench proofs);
    - the statement's tracker already crossed its soft watermark
      (``tidb_mem_quota_spill_ratio`` × quota) — reactive;
    - the planner's row estimate prices the operator's working set above
      the watermark headroom — proactive (the working structures this
      layer manages are mostly NOT chunk-tracked, so waiting for the
      watermark alone would miss them)."""
    forced = force_all_armed()
    est_bytes = int(max(est_rows, 0) * max(row_bytes, 1))
    budget = 0
    if tracker is not None and tracker.quota > 0:
        wm = tracker.spill_watermark or tracker.quota
        # resident budget: watermark headroom, but never more than half
        # the HARD-quota slack — the spill layer's own residency must
        # leave room for the operator's unavoidable chunk allocations
        slack = (tracker.quota - tracker.consumed) // 2
        budget = max(min(wm - tracker.consumed, slack), MIN_BUDGET)
    want = forced or _quota_wants_spill(tracker, est_bytes)
    if not want:
        return None
    if budget <= 0:
        budget = FORCED_BUDGET
    n_parts = choose_partitions(
        est_bytes, budget,
        override=_sysvar_int(session_vars, "tidb_spill_partitions", 0))
    max_depth = _sysvar_int(session_vars, "tidb_spill_max_depth", 3)
    return SpillContext(tracker, n_parts, max_depth, budget,
                        spill_all=forced,
                        enforce=tracker is not None and tracker.quota > 0,
                        label=label)


# ---- shared partition machinery -------------------------------------------

def _split(part_ids: np.ndarray, n_parts: int) -> List[np.ndarray]:
    """Row selections per partition, original order preserved within
    each (a stable grouped argsort, one pass)."""
    order = np.argsort(part_ids, kind="stable")
    counts = np.bincount(part_ids, minlength=n_parts)
    bounds = np.concatenate(([0], np.cumsum(counts)))
    return [order[bounds[p]:bounds[p + 1]] for p in range(n_parts)]


def _arrays_bytes(arrays: Dict[str, np.ndarray]) -> int:
    return sum(a.nbytes for a in arrays.values())


class _Partition:
    """One build-side partition: resident (charged) or spilled (a
    slot)."""

    __slots__ = ("arrays", "slot", "nbytes", "rows")

    def __init__(self, arrays: Dict[str, np.ndarray], rows: int):
        self.arrays: Optional[Dict[str, np.ndarray]] = arrays
        self.slot: Optional[SpillSlot] = None
        self.nbytes = _arrays_bytes(arrays)
        self.rows = rows

    def spill(self, ctx: SpillContext) -> None:
        if self.arrays is None:
            return
        if self.slot is None:
            with _span("spill_partition", bytes=self.nbytes,
                       rows=self.rows):
                self.slot = ctx.store.put(self.arrays, self.rows)
            ctx.spilled(self.nbytes)
        # already on disk (an evicted reload): just drop residency
        self.arrays = None
        ctx.note_gone(self)
        ctx.release(self.nbytes)

    def load(self, ctx: SpillContext) -> Dict[str, np.ndarray]:
        if self.arrays is not None:
            return self.arrays
        with _span("spill_reload", bytes=self.nbytes, rows=self.rows):
            self.arrays = ctx.store.load(self.slot)
        ctx.charge(self.nbytes)   # resident again; drop() releases
        ctx.note_resident(self)
        ctx.reloaded(self.nbytes)
        return self.arrays

    def peek(self, ctx: SpillContext) -> Dict[str, np.ndarray]:
        """Memmapped read-only view of a spilled partition (or the
        resident arrays): NO residency charge — callers slice bounded
        runs out of it instead of loading the whole thing."""
        if self.arrays is not None:
            return self.arrays
        with _span("spill_reload", bytes=self.nbytes, rows=self.rows):
            arrays = ctx.store.load(self.slot, mmap=True)
        ctx.reloaded(self.nbytes)
        return arrays

    def drop(self, ctx: SpillContext) -> None:
        """Done with this partition: free disk and/or release bytes."""
        if self.slot is not None:
            ctx.store.free(self.slot)
            self.slot = None
        if self.arrays is not None:
            self.arrays = None
            ctx.note_gone(self)
            ctx.release(self.nbytes)


def _make_partitions(ctx: SpillContext, depth: int,
                     key: np.ndarray, extras: Dict[str, np.ndarray],
                     n_parts: int) -> List[Tuple[_Partition, np.ndarray]]:
    """Hash-partition parallel arrays; spill cold partitions.  Returns
    [(partition, row_selection)] — the selection indexes the CALLER's
    arrays (ascending, order-preserving).  Residency policy: partitions
    stay resident while cumulative bytes fit the budget; everything
    after spills (forced mode spills all)."""
    pids = hash_partition(key, depth, n_parts)
    sels = _split(pids, n_parts)
    parts: List[Tuple[_Partition, np.ndarray]] = []
    resident = 0
    for sel in sels:
        arrays = {"k": key[sel]}
        for name, arr in extras.items():
            arrays[name] = arr[sel]
        part = _Partition(arrays, len(sel))
        ctx.charge(part.nbytes)
        if ctx.spill_all or resident + part.nbytes > ctx.budget:
            part.spill(ctx)
        else:
            resident += part.nbytes
            ctx.note_resident(part)
        parts.append((part, sel))
    return parts


# ---- hybrid hash join ------------------------------------------------------

def partitioned_join(ctx: SpillContext,
                     probe: Tuple[np.ndarray, np.ndarray], n_probe: int,
                     build: Tuple[np.ndarray, np.ndarray], n_build: int,
                     match_fn: Callable, outer: bool = False,
                     probe_valid: Optional[np.ndarray] = None,
                     build_valid: Optional[np.ndarray] = None):
    """The memory-adaptive hybrid hash join.  ``match_fn(probe_pair,
    n_probe, build_pair, n_build)`` is one of the UNCHANGED kernel entry
    points (``join_match`` / ``unique_join_match``) called in inner mode
    over pre-compacted live rows — so per-partition matching reuses the
    exact compiled programs (and their progcache entries) the
    unpartitioned path uses.

    Output contract and ORDER are identical to the unpartitioned
    kernels: probe-major (li ascending; a probe row's matches in stable
    build order), outer mode emitting unmatched valid probe rows once
    with ri = -1.  A probe row's matches all live in ONE partition and
    stable selection preserves build order, so the per-row match
    sequence is reproduced exactly; a final stable sort by li restores
    the global interleaving."""
    ctx.mark_used()
    pk, pn = np.asarray(probe[0]), np.asarray(probe[1], dtype=bool)
    bk, bn = np.asarray(build[0]), np.asarray(build[1], dtype=bool)
    pk, pn = pk[:n_probe], pn[:n_probe]
    bk, bn = bk[:n_build], bn[:n_build]
    plive = ~pn if probe_valid is None \
        else (~pn & np.asarray(probe_valid[:n_probe], dtype=bool))
    blive = ~bn if build_valid is None \
        else (~bn & np.asarray(build_valid[:n_build], dtype=bool))
    pidx = np.nonzero(plive)[0]
    bidx = np.nonzero(blive)[0]
    li_out: List[np.ndarray] = []
    ri_out: List[np.ndarray] = []
    if len(pidx) and len(bidx):
        _join_level(ctx, pk[pidx], pidx, bk[bidx], bidx, 0,
                    match_fn, li_out, ri_out)
    if li_out:
        li = np.concatenate(li_out)
        ri = np.concatenate(ri_out)
    else:
        li = np.empty(0, dtype=np.int64)
        ri = np.empty(0, dtype=np.int64)
    if outer:
        matched = np.zeros(n_probe, dtype=bool)
        matched[li] = True
        pvalid = np.ones(n_probe, dtype=bool) if probe_valid is None \
            else np.asarray(probe_valid[:n_probe], dtype=bool)
        un = np.nonzero(pvalid & ~matched)[0]
        if len(un):
            li = np.concatenate([li, un])
            ri = np.concatenate([ri, np.full(len(un), -1,
                                             dtype=np.int64)])
    order = np.argsort(li, kind="stable")
    return li[order].astype(np.int64), ri[order].astype(np.int64)


def _join_level(ctx: SpillContext, pk, pids, bk, bids, depth: int,
                match_fn, li_out, ri_out) -> None:
    interrupt.check()
    n_parts = ctx.n_parts
    bparts = _make_partitions(ctx, depth, bk, {"rid": bids}, n_parts)
    ppart_ids = hash_partition(pk, depth, n_parts)
    psels = _split(ppart_ids, n_parts)
    zeros_cache: Dict[int, np.ndarray] = {}

    def zeros(n: int) -> np.ndarray:
        z = zeros_cache.get(n)
        if z is None or len(z) < n:
            z = zeros_cache[n] = np.zeros(n, dtype=bool)
        return z[:n]

    try:
        for p, (part, _bsel) in enumerate(bparts):
            interrupt.check()
            psel = psels[p]
            try:
                if part.rows == 0 or len(psel) == 0:
                    continue  # no possible matches in this partition
                if ctx.enforce and not ctx.fits(part.nbytes):
                    if depth + 1 > ctx.max_depth:
                        raise ctx.overflow(part.nbytes)
                    # recursive repartition: a fresh hash seed splits
                    # the skew this level's hash collapsed.  peek() —
                    # the partition is by definition over budget, so it
                    # must NOT come back fully resident; the next level
                    # slices its sub-partitions out of the memmap one
                    # at a time (same discipline as _agg_level)
                    ctx.repartitioned()
                    arrays = part.peek(ctx)
                    sub_pk, sub_pids = pk[psel], pids[psel]
                    _join_level(ctx, sub_pk, sub_pids,
                                np.asarray(arrays["k"]),
                                np.asarray(arrays["rid"]), depth + 1,
                                match_fn, li_out, ri_out)
                    continue
                arrays = part.load(ctx)
                bkp, brid = arrays["k"], arrays["rid"]
                pkp = pk[psel]
                li_loc, ri_loc = match_fn(
                    (pkp, zeros(len(pkp))), len(pkp),
                    (bkp, zeros(len(bkp))), len(bkp))
                if len(li_loc):
                    li_out.append(pids[psel][li_loc])
                    ri_out.append(brid[ri_loc])
            finally:
                part.drop(ctx)
    finally:
        # an error (kill, reload fault, 8175) mid-loop must not leak the
        # remaining partitions' slots or resident bytes
        for part, _ in bparts:
            part.drop(ctx)


# ---- hash aggregation ------------------------------------------------------

def partitioned_segment_aggregate(ctx: SpillContext, gid: np.ndarray,
                                  n_segments: int, specs, arg_cols,
                                  n_rows: int,
                                  filter_mask: Optional[np.ndarray] = None):
    """Memory-adaptive segment aggregation: rows hash-partitioned by
    group id (each group wholly in one partition — per-group
    accumulation order, and therefore float sums, match the
    unpartitioned kernel bit-for-bit on a sequential backend), partial
    aggregates computed per partition through the UNCHANGED
    ``kernels.segment_group_aggregate``, and the disjoint per-partition
    group sets merged at drain.  Returns the same (present, out_aggs,
    first_orig) contract."""
    ctx.mark_used()
    live = np.ones(n_rows, dtype=bool) if filter_mask is None \
        else np.asarray(filter_mask[:n_rows], dtype=bool)
    ridx = np.nonzero(live)[0]
    rows_out = []   # (present_ids, out_aggs, first_orig_global)
    if len(ridx):
        extras = {"rid": ridx}
        for i, (v, m) in enumerate(arg_cols):
            extras[f"a{i}v"] = np.asarray(v)[:n_rows][ridx]
            extras[f"a{i}m"] = np.asarray(m)[:n_rows][ridx]
        _agg_level(ctx, gid[ridx].astype(np.int64), extras, 0,
                   n_segments, specs, len(arg_cols), rows_out)
    if not rows_out:
        z = np.empty(0, dtype=np.int64)
        return z, [(z.copy(), np.empty(0, dtype=bool))
                   for _ in specs], z.copy()
    present = np.concatenate([r[0] for r in rows_out])
    first = np.concatenate([r[2] for r in rows_out])
    out_aggs = []
    for i in range(len(specs)):
        vs = np.concatenate([r[1][i][0] for r in rows_out])
        ms = np.concatenate([r[1][i][1] for r in rows_out])
        out_aggs.append((vs, ms))
    # partitions hold disjoint group sets: one stable sort restores the
    # unpartitioned present-ascending order
    order = np.argsort(present, kind="stable")
    return (present[order],
            [(v[order], m[order]) for v, m in out_aggs], first[order])


def _agg_level(ctx: SpillContext, gid, extras, depth: int,
               n_segments: int, specs, n_args: int, rows_out) -> None:
    from . import kernels
    interrupt.check()
    parts = _make_partitions(ctx, depth, gid, extras, ctx.n_parts)
    try:
        for part, _sel in parts:
            interrupt.check()
            try:
                if part.rows == 0:
                    continue
                if ctx.enforce and not ctx.fits(part.nbytes):
                    arrays = part.peek(ctx)
                    g = arrays["k"]
                    # a one-key partition can never split by rehashing
                    # (equal keys colocate at every depth): skip the
                    # futile ladder and stream it directly
                    splittable = len(g) > 1 and bool(
                        (np.asarray(g) != g[0]).any())
                    if depth + 1 <= ctx.max_depth and splittable:
                        ctx.repartitioned()
                        _agg_level(ctx, np.asarray(g),
                                   {k: np.asarray(v)
                                    for k, v in arrays.items()
                                    if k != "k"},
                                   depth + 1, n_segments, specs,
                                   n_args, rows_out)
                    else:
                        _stream_partition_aggregate(
                            ctx, arrays, part.rows, part.nbytes,
                            n_segments, specs, n_args, rows_out)
                    continue
                arrays = part.load(ctx)
                g = arrays["k"]
                rid = arrays["rid"]
                acols = [(arrays[f"a{i}v"], arrays[f"a{i}m"])
                         for i in range(n_args)]
                present, out_aggs, first = kernels.segment_group_aggregate(
                    g, n_segments, specs, acols, len(g))
                if len(present):
                    rows_out.append((present, out_aggs, rid[first]))
            finally:
                part.drop(ctx)
    finally:
        for part, _ in parts:
            part.drop(ctx)


def _stream_partition_aggregate(ctx: SpillContext, arrays, rows: int,
                                nbytes: int, n_segments: int, specs,
                                n_args: int, rows_out) -> None:
    """Partial Partial Aggregates (PAPERS.md): a partition that exceeds
    every budget and cannot usefully split (one giant group, or the
    repartition ladder is exhausted) streams through the UNCHANGED
    kernel in budget-sized row slices, merging the per-slice PARTIAL
    aggregate states on host — so aggregation state stays
    O(n_segments) and the working set stays bounded no matter how
    skewed the grouping is.  count/count_star/min/max/first merge
    exactly; float sums merge left-to-right over the slices, which can
    differ from the one-shot kernel in the last ulp — the documented
    price of completing at quotas below a single group's row
    footprint."""
    from . import kernels
    bpr = max(nbytes // max(rows, 1), 1)
    run = max(int(ctx.budget // bpr), 256)
    acc = None
    with _span("spill_stream_agg", rows=rows, bytes=nbytes):
        for s in range(0, rows, run):
            interrupt.check()
            e = min(s + run, rows)
            g = np.asarray(arrays["k"][s:e])
            rid = np.asarray(arrays["rid"][s:e])
            acols = [(np.asarray(arrays[f"a{i}v"][s:e]),
                      np.asarray(arrays[f"a{i}m"][s:e]))
                     for i in range(n_args)]
            nb = (g.nbytes + rid.nbytes
                  + sum(v.nbytes + m.nbytes for v, m in acols))
            ctx.charge(nb)
            try:
                present, out_aggs, first = \
                    kernels.segment_group_aggregate(
                        g, n_segments, specs, acols, e - s)
                partial = (present, out_aggs, rid[first])
                acc = partial if acc is None else _merge_partials(
                    acc, partial, specs)
            finally:
                ctx.release(nb)
            _record("spill_stream_runs")
    if acc is not None and len(acc[0]):
        rows_out.append(acc)


def _merge_partials(a, b, specs):
    """Merge two partial-aggregate states over the SAME segment-id
    space: union of present segments, per-spec combination (sums/counts
    add, min/max fold, first takes the smallest original row id).  NULL
    semantics match the kernel: a spec's output is NULL only when no
    live row contributed on EITHER side."""
    pres_a, aggs_a, first_a = a
    pres_b, aggs_b, first_b = b
    allp = np.union1d(pres_a, pres_b).astype(np.int64)
    n = len(allp)

    def locate(pres):
        idx = np.searchsorted(pres, allp)
        safe = np.minimum(idx, max(len(pres) - 1, 0))
        inm = (np.zeros(n, dtype=bool) if len(pres) == 0
               else np.asarray(pres)[safe] == allp)
        return safe, inm

    ia, in_a = locate(pres_a)
    ib, in_b = locate(pres_b)

    def gather(vals, idx, inm, fill):
        vals = np.asarray(vals)
        out = np.full(n, fill, dtype=vals.dtype if len(vals) else None)
        if len(vals):
            out[inm] = vals[idx[inm]]
        return out

    big = np.iinfo(np.int64).max
    first = np.minimum(gather(first_a, ia, in_a, big),
                       gather(first_b, ib, in_b, big))
    out_aggs = []
    for i, (func, _has_arg) in enumerate(specs):
        va = gather(aggs_a[i][0], ia, in_a, 0)
        vb = gather(aggs_b[i][0], ib, in_b, 0)
        ma = gather(aggs_a[i][1], ia, in_a, True)
        mb = gather(aggs_b[i][1], ib, in_b, True)
        if func in ("count", "count_star", "sum0"):
            # never NULL; an absent side contributed 0
            out_aggs.append((va + vb, np.zeros(n, dtype=bool)))
        elif func in ("sum", "sum_int"):
            # a NULL side's kernel sum is 0: plain add is correct
            out_aggs.append((va + vb, ma & mb))
        elif func in ("min", "max"):
            fold = np.minimum if func == "min" else np.maximum
            v = np.where(ma, vb, np.where(mb, va, fold(va, vb)))
            out_aggs.append((v, ma & mb))
        else:  # pragma: no cover
            raise ValueError(func)
    return allp, out_aggs, first


# ---- external sort ---------------------------------------------------------

def external_sort_permutation(ctx: SpillContext, key_cols, descs,
                              n_rows: int, run_rows: int) -> np.ndarray:
    """Spilled-run external sort: each run of ``run_rows`` rows sorts on
    host with the device kernel's exact semantics
    (``kernels._np_lexsort_perm``: stable, NULL first/last per
    direction, original row id as the implicit final tie-break) and
    spills (sorted keys + permutation) as a run file; a vectorized
    k-way merge over the run files — ordering by (transformed keys...,
    row id) in bounded blocks — reproduces the full lexsort's EXACT
    permutation."""
    ctx.mark_used()
    from . import kernels
    runs: List[SpillSlot] = []
    nk = len(key_cols)
    try:
        for s in range(0, n_rows, run_rows):
            interrupt.check()
            e = min(s + run_rows, n_rows)
            sub = [(np.asarray(v)[s:e], np.asarray(m)[s:e])
                   for v, m in key_cols]
            perm = kernels._np_lexsort_perm(sub, descs) + s
            arrays = {"perm": perm.astype(np.int64)}
            for i, (v, m) in enumerate(key_cols):
                local = perm - s
                arrays[f"k{i}v"] = np.asarray(v)[s:e][local]
                arrays[f"k{i}m"] = np.asarray(m)[s:e][local]
            with _span("spill_run", rows=e - s):
                slot = ctx.store.put(arrays, e - s)
            ctx.spilled(slot.nbytes)  # runs count as spilled partitions
            runs.append(slot)
        if not runs:
            return np.empty(0, dtype=np.int64)
        return _merge_runs(ctx, runs, descs, nk, n_rows)
    finally:
        for slot in runs:
            ctx.store.free(slot)


class _RunChain:
    """A logical sorted run: an ordered chain of spilled chunk slots
    (one slot for an original run; several for a merge pass's output)."""

    __slots__ = ("slots", "rows")

    def __init__(self, slots: List[SpillSlot]):
        self.slots = [s for s in slots if s.rows]
        self.rows = sum(s.rows for s in self.slots)


class _ChainCursor:
    """Block reader over a run chain: memmaps one slot at a time and
    hands out materialized blocks of bounded rows."""

    __slots__ = ("_ctx", "_slots", "_si", "_off", "_arrs")

    def __init__(self, ctx: SpillContext, chain: _RunChain):
        self._ctx = ctx
        self._slots = chain.slots
        self._si = 0
        self._off = 0
        self._arrs: Optional[Dict[str, np.ndarray]] = None

    def exhausted(self) -> bool:
        return self._si >= len(self._slots)

    def next_block(self, rows: int,
                   names: List[str]) -> Optional[Dict[str, np.ndarray]]:
        chunks = []
        while rows > 0 and self._si < len(self._slots):
            slot = self._slots[self._si]
            if self._arrs is None:
                with _span("spill_reload", bytes=slot.nbytes):
                    self._arrs = self._ctx.store.load(slot, mmap=True)
                self._ctx.reloaded(slot.nbytes)
            s = self._off
            e = min(s + rows, slot.rows)
            chunks.append({k: np.asarray(self._arrs[k][s:e])
                           for k in names})
            rows -= e - s
            self._off = e
            if e >= slot.rows:
                self._arrs = None
                self._si += 1
                self._off = 0
        if not chunks:
            return None
        if len(chunks) == 1:
            return chunks[0]
        return {k: np.concatenate([c[k] for c in chunks])
                for k in names}


def _merge_group(ctx: SpillContext, chains: List[_RunChain], descs,
                 nk: int, block_rows: int, emit) -> None:
    """Vectorized k-way merge of sorted run chains with bounded
    residency.  Loop invariant: every non-exhausted chain has rows in
    the buffer.  Each round sorts the buffer through the UNCHANGED
    ``kernels._np_lexsort_perm`` with the original row id appended as
    the least-significant ascending key — exactly the stable global
    lexsort's implicit tie-break, so emitted order is bit-identical to
    the full sort — then emits the prefix up to the smallest
    still-feeding chain's largest buffered element (everything unseen
    from any chain is strictly greater, keys being unique by row id)
    and refills only the chains that drained."""
    from . import kernels
    names = ["perm"] + [f"k{i}{t}" for i in range(nk) for t in "vm"]
    cursors = [_ChainCursor(ctx, c) for c in chains]
    buf: Optional[Dict[str, np.ndarray]] = None
    need = list(range(len(cursors)))
    while True:
        interrupt.check()
        for r in need:
            blk = cursors[r].next_block(block_rows, names)
            if blk is None:
                continue
            blk["src"] = np.full(len(blk["perm"]), r, dtype=np.int64)
            buf = blk if buf is None else \
                {k: np.concatenate([buf[k], blk[k]]) for k in buf}
        if buf is None or not len(buf["perm"]):
            return
        keys = [(buf[f"k{i}v"], buf[f"k{i}m"]) for i in range(nk)]
        keys.append((buf["perm"],
                     np.zeros(len(buf["perm"]), dtype=bool)))
        order = kernels._np_lexsort_perm(keys, list(descs) + [False])
        buf = {k: v[order] for k, v in buf.items()}
        src = buf["src"]
        cut = len(src)
        for r, cur in enumerate(cursors):
            if not cur.exhausted():
                pos = np.nonzero(src == r)[0]
                cut = min(cut, int(pos[-1]) + 1)
        emit({k: v[:cut] for k, v in buf.items() if k != "src"})
        buf = None if cut >= len(src) \
            else {k: v[cut:] for k, v in buf.items()}
        need = [r for r, cur in enumerate(cursors)
                if not cur.exhausted()
                and (buf is None or not (buf["src"] == r).any())]
        if not need and buf is None:
            return


def _merge_runs(ctx: SpillContext, runs, descs, nk: int,
                n_rows: int) -> np.ndarray:
    """External merge of the sorted run files, vectorized end to end
    (no per-row Python): runs merge in budget-bounded fan-in groups —
    more runs than the fan-in holds cascade through intermediate merge
    passes whose output chunks go back THROUGH the store as chained
    run files — and the final pass streams the global permutation out
    block by block."""
    row_b = max(sum(s.nbytes for s in runs) // max(n_rows, 1), 1)
    cap_rows = max(int(ctx.budget // row_b), 512)
    fan = int(min(len(runs), max(cap_rows // 256, 2)))
    block = max(cap_rows // fan, 256)
    chains = [_RunChain([s]) for s in runs]
    owned: List[SpillSlot] = []
    try:
        while len(chains) > fan:
            interrupt.check()
            nxt: List[_RunChain] = []
            for g in range(0, len(chains), fan):
                group = chains[g:g + fan]
                if len(group) == 1:
                    nxt.append(group[0])
                    continue
                merged: List[SpillSlot] = []

                def emit_slot(chunk, _m=merged):
                    slot = ctx.store.put(chunk, len(chunk["perm"]))
                    ctx.spilled(slot.nbytes)
                    owned.append(slot)
                    _m.append(slot)

                with _span("spill_merge_pass", runs=len(group)):
                    _merge_group(ctx, group, descs, nk, block, emit_slot)
                for c in group:          # inputs consumed: free early
                    for s in c.slots:
                        ctx.store.free(s)
                nxt.append(_RunChain(merged))
            chains = nxt
        out = np.empty(n_rows, dtype=np.int64)
        w = 0

        def emit_out(chunk):
            nonlocal w
            n = len(chunk["perm"])
            out[w:w + n] = chunk["perm"]
            w += n

        _merge_group(ctx, chains, descs, nk, block, emit_out)
        return out[:w]
    finally:
        for s in owned:
            ctx.store.free(s)   # idempotent; covers the error path


# ---- external top-k --------------------------------------------------------

def external_topk(ctx: SpillContext, key_cols, descs, n_rows: int,
                  k: int, run_rows: int) -> np.ndarray:
    """Blockwise top-k with the candidate carry held IN THE STORE: each
    run contributes its local top-k, the carried candidate set (≤ k
    rows, spilled between runs) merges with each run's winners exactly
    like the in-memory blockwise TopN — same kernels, same tie
    semantics, bounded residency."""
    ctx.mark_used()
    from . import kernels
    cand = np.empty(0, dtype=np.int64)
    slot: Optional[SpillSlot] = None
    try:
        for s in range(0, n_rows, run_rows):
            interrupt.check()
            e = min(s + run_rows, n_rows)
            bkeys = [(np.asarray(v)[s:e], np.asarray(m)[s:e])
                     for v, m in key_cols]
            ids = np.asarray(kernels.top_k(bkeys, descs, e - s, k)) + s
            if slot is not None:
                arrays = ctx.store.load(slot)
                ctx.reloaded(slot.nbytes)
                cand = arrays["cand"]
                ctx.store.free(slot)
                slot = None
            pool = np.concatenate([cand, ids])
            pkeys = [(np.asarray(v)[pool], np.asarray(m)[pool])
                     for v, m in key_cols]
            order = np.asarray(kernels.top_k(pkeys, descs, len(pool), k))
            cand = pool[order]
            if e < n_rows:
                with _span("spill_run", rows=len(cand)):
                    slot = ctx.store.put({"cand": cand}, len(cand))
                ctx.spilled(slot.nbytes)
                cand = np.empty(0, dtype=np.int64)
        return cand
    finally:
        if slot is not None:
            ctx.store.free(slot)
