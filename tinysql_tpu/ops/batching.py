"""Cross-query micro-batching: the same-digest coalescer core.

PR 6's literal parameterization made every query in a normalized-SQL
digest family share ONE compiled program with its constants as runtime
operands (exprjit.ParamTable).  This module supplies the other half of
the serving win: when several concurrently-admitted statements belong
to the same WARM family, the statement pool (server/pool.py) executes
them as one *batch round* — N ParamTables through one compiled program
in a single back-to-back device round — instead of N independent,
interleaved dispatches.

Protocol (driven by the pool's worker thread; all members run
sequentially on it):

1. **collect** — each member statement executes normally under the
   round's collect scope.  When its fused aggregate reaches the device
   dispatch boundary with a params-compiled dev mask AND the program
   already warm (ops/kernels.py fused entries, ``batchable=True`` call
   sites), it *parks*: the round captures ``(program key, cached
   program, non-param args, this member's params)`` and the statement
   aborts with :class:`Parked` (invisible to observability — the
   session skips the obs fan-out for parked attempts).  Members whose
   statements never reach a batchable dispatch (host paths, cold
   programs, non-SELECTs) simply COMPLETE during collect: transparent
   solo fallback.
2. **dispatch** — the round groups parked members by (program key,
   staged-array identity) and, when ``tidb_batch_stack_max`` allows it
   and >= 2 members' ParamTables share a slot layout, STACKS them on a
   leading batch axis (exprjit.ParamTable.stack) and runs ONE
   ``jax.vmap``-batched program variant (kernels.stacked_variant,
   registered under the base key extended with a power-of-two occupancy
   bucket B — occupancy 3 rides the B=4 program with an inert padding
   row): the whole group costs one XLA dispatch, and packed outputs
   download in one transfer.  Groups that cannot stack (stacking off,
   layout mismatch, no stacking recipe on the program, singleton
   leftovers) run the legacy back-to-back leg — one ParamTable replay
   per member (zero compiles either way: park only happens on warm
   programs).  Each dispatch leg runs inside a CAPTURE observability
   scope; its device counters (dispatches, device_s, transfer bytes)
   are split across the members it served — occupancy-weighted for a
   stacked group, exact for a solo replay — so statements_summary and
   EXPLAIN ANALYZE stay truthful and member shares sum to the global
   counters.
3. **replay** — each parked member re-executes; at the same boundary it
   *consumes* its precomputed device output (matched by program key +
   the identity of the staged device arrays + its own param bytes) and
   the rest of the statement — unpack, d2h, result assembly,
   observability — runs normally in the member's own scope.  A consume
   miss (the replica rotated between phases, plan re-placed, ...)
   falls through to a plain solo dispatch: batching is an optimization,
   never a correctness dependency.

Family eligibility is learned, not declared: the session's statement
close hook calls :func:`note_family` for statements that executed a
batchable fused dispatch (the ``batchable`` obs marker), and the pool
only forms rounds for digests seen here.

Counter-write discipline: ``STATS`` is written only through this
module's accessors (qlint OB401/OB402 — batching.py is an owning
module).
"""
from __future__ import annotations

import contextlib
import contextvars
import threading
from typing import Dict, List, Optional

from ..obs import context as _obs

#: process-total coalescing counters (exported to /metrics and the
#: serve bench): batches = rounds that dispatched >= 1 parked member,
#: batched_statements = members served from a round dispatch,
#: occupancy_sum / batches = average batch occupancy, parks / replays
#: the protocol legs, fallbacks = replay consume misses (solo re-dispatch)
#: dispatch_s_sum accumulates wall seconds inside round dispatch legs
#: (exported as tinysql_batch_dispatch_seconds_total: the device-side
#: half of a batched member's wait attribution).  The stacked leg:
#: stacked_rounds = groups served by ONE vmap-batched dispatch,
#: stacked_statements = members inside them, stacked_occupancy_sum /
#: stacked_rounds = average stacked occupancy, stack_fallbacks = groups
#: that fell back to the legacy back-to-back leg (layout mismatch, no
#: stacking recipe, stacked dispatch error)
STATS = {"batches": 0, "batched_statements": 0, "occupancy_sum": 0,
         "parks": 0, "replays": 0, "fallbacks": 0, "dispatch_s_sum": 0.0,
         "stacked_rounds": 0, "stacked_statements": 0,
         "stacked_occupancy_sum": 0, "stack_fallbacks": 0}
_stats_mu = threading.Lock()


def _stat_add(key: str, n: int = 1) -> None:
    with _stats_mu:
        STATS[key] = STATS.get(key, 0) + n


def stats_snapshot() -> Dict[str, int]:
    with _stats_mu:
        return dict(STATS)


def reset_stats() -> None:
    """Tests only."""
    with _stats_mu:
        for k in STATS:
            STATS[k] = 0


class Parked(Exception):
    """Control-flow signal of the collect leg: the statement reached a
    batchable warm dispatch and its params were captured.  Never
    surfaces to clients — only the pool's batch driver catches it, and
    the session skips the observability fan-out for parked attempts."""


class _ParkedDispatch:
    __slots__ = ("key", "fn", "args", "arg_ids", "params_key", "params",
                 "out", "share")

    def __init__(self, key, fn, args, params):
        self.key = key
        self.fn = fn
        self.args = args            # positional device args WITHOUT params
        self.arg_ids = _leaf_ids(args)
        self.params = params        # the member's (pi, pf) host vectors
        self.params_key = _params_key(params)
        self.out = None             # ("dev"|"host", payload) once served
        self.share = None           # this member's device-counter share


def _params_key(params) -> bytes:
    pi, pf = params
    return bytes(memoryview(pi).cast("B")) + b"|" + \
        bytes(memoryview(pf).cast("B"))


def _leaf_ids(x) -> tuple:
    """Structural identity of a dispatch's non-param arguments: the
    executor rebuilds its ``dev_cols`` list (and the (values, null)
    tuples in it) per execution, but the LEAF device arrays are
    replica-memoized — the same objects across a family's queries until
    a write invalidates the replica.  Matching on leaf ids is exactly
    the guard batching needs: a replica rotation between the collect and
    replay legs changes the leaves, the consume misses, and the member
    falls back to a solo dispatch over the fresh data."""
    if x is None:
        return ("~",)
    if isinstance(x, (list, tuple)):
        out = ["("]
        for v in x:
            out.extend(_leaf_ids(v))
        out.append(")")
        return tuple(out)
    return (id(x),)


@contextlib.contextmanager
def _capture_scope():
    """A throwaway QueryObs installed around one round dispatch leg:
    counted_jit / d2h / h2d report into it like into any statement
    scope, and the collected totals become the served members'
    attribution shares (the replay-side consume records them into each
    member's own scope).  Without it the whole round's device_s and
    transfer bytes would land on no statement at all — the pool worker
    drives the dispatch leg outside every member context."""
    cap = _obs.QueryObs()
    tok = _obs.activate(cap)
    try:
        yield cap
    finally:
        _obs.deactivate(tok)


class BatchRound:
    """One coalesced group's shared state across collect/dispatch/replay.
    Used from the single pool worker thread driving the group (members
    run sequentially), so no internal locking is needed beyond the
    global counters.  ``stack_max`` is the live ``tidb_batch_stack_max``
    value (0/1 = legacy back-to-back only; >= 2 caps how many members
    one stacked dispatch may carry)."""

    def __init__(self, stack_max: int = 0):
        self.collecting = False
        self.replaying = False
        self.stack_max = max(int(stack_max), 0)
        self._parked: List[_ParkedDispatch] = []
        #: (key, arg_ids, params_key) -> [(out, share)]: a LIST because
        #: concurrent clients legitimately submit IDENTICAL statements —
        #: each member consumes one stored output
        self._results: Dict[tuple, list] = {}

    # ---- collect ---------------------------------------------------------
    def park(self, key, fn, args, params) -> None:
        """Capture one member's dispatch and abort its collect execution
        (raises :class:`Parked`)."""
        self._parked.append(_ParkedDispatch(key, fn, args, params))
        _stat_add("parks")
        raise Parked()

    @property
    def parked_count(self) -> int:
        return len(self._parked)

    # ---- dispatch --------------------------------------------------------
    def dispatch(self) -> int:
        """Serve every parked member: same-program/same-data groups of
        >= 2 layout-compatible members go through ONE stacked-params
        vmap dispatch (``stack_max`` permitting), everything else
        replays back-to-back through the captured solo program.
        Returns the round's occupancy (members served).  Zero compiles
        by construction on warm paths — park only happens on
        progcache-warm programs, and the stacked variants are
        prewarmable (kernels.prewarm_stacked).  A member whose dispatch
        raises (device loss, injected fault) simply has no stored
        result: its replay consume misses and the solo re-dispatch
        surfaces the error through the statement's own degradation
        path."""
        import time as _time
        t0 = _time.perf_counter()
        groups: Dict[tuple, list] = {}
        order: List[tuple] = []
        for p in self._parked:
            k = (p.key, p.arg_ids)
            if k not in groups:
                groups[k] = []
                order.append(k)
            groups[k].append(p)
        occ = 0
        for k in order:
            members = groups[k]
            while members:
                chunk = members[: max(self.stack_max, 1)]
                members = members[len(chunk):]
                if len(chunk) >= 2 and self._dispatch_stacked(chunk):
                    occ += len(chunk)
                    continue
                for p in chunk:
                    occ += self._dispatch_solo(p)
        if occ:
            _stat_add("batches")
            _stat_add("batched_statements", occ)
            _stat_add("occupancy_sum", occ)
            _stat_add("dispatch_s_sum", _time.perf_counter() - t0)
        return occ

    def _store(self, p: _ParkedDispatch, out, share: dict) -> None:
        p.out = out
        p.share = share
        self._results.setdefault(
            (p.key, p.arg_ids, p.params_key), []).append((out, share))

    def _dispatch_solo(self, p: _ParkedDispatch) -> int:
        """Legacy back-to-back leg: one ParamTable replay through the
        member's captured solo program.  The capture scope's totals are
        this member's EXACT attribution (the whole dispatch served only
        it) — including any sampled device_s, so the profiler's measured
        time lands on the member that caused it, not on whoever
        dispatched the round."""
        from . import kernels
        try:
            with _capture_scope() as cap:
                out = p.fn(*p.args, kernels._params_dev(p.params))
        except Exception:
            return 0
        self._store(p, ("dev", out), cap.device_totals())
        return 1

    def _dispatch_stacked(self, chunk: List[_ParkedDispatch]) -> bool:
        """ONE dispatch for the whole chunk: stack the members'
        ParamTables on a leading batch axis padded to the occupancy
        bucket, run the B-stacked program variant, and split the output
        per member — packed outputs download as one [B, L] transfer
        here (host rows, no further d2h at replay), tree outputs slice
        off axis 0 on device.  The capture scope's totals are divided
        by the chunk's occupancy: each member's share of the one
        dispatch.  Any failure (layout mismatch, no stacking recipe,
        dispatch error) returns False and the chunk falls back to the
        legacy leg — stacking is an optimization, never a correctness
        dependency."""
        from . import kernels
        from .exprjit import ParamTable
        p0 = chunk[0]
        n = len(chunk)
        try:
            ent = kernels.stacked_variant(
                p0.key, p0.fn, kernels.occupancy_bucket(n))
            if ent is None:
                _stat_add("stack_fallbacks")
                return False
            vfn, kind, schema = ent
            stacked = ParamTable.stack(
                [p.params for p in chunk], kernels.occupancy_bucket(n))
        except Exception:
            _stat_add("stack_fallbacks")
            return False
        try:
            with _capture_scope() as cap:
                res = vfn(*p0.args, kernels._params_dev(stacked))
                if kind == "packed":
                    rows = kernels.d2h_many(list(res))
        except Exception:
            _stat_add("stack_fallbacks")
            return False
        totals = cap.device_totals()
        # exact occupancy split (shardops.split_exact): members' shares
        # sum to the round's totals to the last ulp, so per-member (and,
        # for sharded programs, per-shard) attribution reconciles with
        # the global counters EXACTLY, not just approximately
        from . import shardops
        shares = shardops.split_exact(totals, n)
        if shardops.shards_of_key(p0.key) > 1:
            shardops.note_stacked_round()
        tree_map = kernels.jax().tree_util.tree_map
        for i, p in enumerate(chunk):
            if kind == "packed":
                out = ("host", (rows[0][i], rows[1][i]))
            else:
                out = ("dev", tree_map(lambda x, i=i: x[i], res))
            self._store(p, out, shares[i])
        _stat_add("stacked_rounds")
        _stat_add("stacked_statements", n)
        _stat_add("stacked_occupancy_sum", n)
        return True

    # ---- replay ----------------------------------------------------------
    def consume(self, key, args, params):
        """The replay-side lookup: this member's precomputed
        ``(tag, output)``, or None when the capture no longer matches
        (fall back to a solo dispatch).  A hit records the member's
        attribution share — its occupancy-weighted slice of the round
        dispatch's device counters — into the member's own live scope,
        so summing statements_summary across members reconciles with
        the global counters."""
        outs = self._results.get(
            (key, _leaf_ids(args), _params_key(params)))
        if outs:
            _stat_add("replays")
            out, share = outs.pop()
            for k, v in share.items():
                _obs.record(k, v)
            _obs.record("coalesced", 1)
            return out
        _stat_add("fallbacks")
        return None


_ROUND: contextvars.ContextVar = contextvars.ContextVar(
    "tinysql_batch_round", default=None)


def activate(rnd: Optional[BatchRound]):
    return _ROUND.set(rnd)


def deactivate(token) -> None:
    _ROUND.reset(token)


def current() -> Optional[BatchRound]:
    return _ROUND.get()


def active() -> bool:
    """True while a batch round's collect or replay leg drives THIS
    context — executors use it to prefer the batchable fused paths over
    per-member-only variants (device passthrough) so a round's members
    park and consume along the same route."""
    rnd = _ROUND.get()
    return rnd is not None and (rnd.collecting or rnd.replaying)


# ---- family registry (learned batch eligibility) --------------------------

#: normalized-SQL digests whose statements executed a batchable fused
#: dispatch (dev-mask + params, single-shot path).  Bounded: serving
#: works with O(active digest families).
_FAM_MAX = 512
_fam_mu = threading.Lock()
_FAMILIES: Dict[str, int] = {}


def note_family(sql_digest: str) -> None:
    """Mark a digest family batchable (called from the session statement
    close hook for statements that recorded the ``batchable`` marker)."""
    if not sql_digest:
        return
    with _fam_mu:
        if len(_FAMILIES) >= _FAM_MAX and sql_digest not in _FAMILIES:
            _FAMILIES.pop(next(iter(_FAMILIES)))
        _FAMILIES[sql_digest] = _FAMILIES.get(sql_digest, 0) + 1


def family_batchable(sql_digest: str) -> bool:
    with _fam_mu:
        return sql_digest in _FAMILIES


def have_families() -> bool:
    """Cheap pre-check so the pool skips per-statement SQL
    normalization until at least one batchable family exists."""
    with _fam_mu:
        return bool(_FAMILIES)


def reset_families() -> None:
    """Tests only."""
    with _fam_mu:
        _FAMILIES.clear()
