"""Cross-query micro-batching: the same-digest coalescer core.

PR 6's literal parameterization made every query in a normalized-SQL
digest family share ONE compiled program with its constants as runtime
operands (exprjit.ParamTable).  This module supplies the other half of
the serving win: when several concurrently-admitted statements belong
to the same WARM family, the statement pool (server/pool.py) executes
them as one *batch round* — N ParamTables through one compiled program
in a single back-to-back device round — instead of N independent,
interleaved dispatches.

Protocol (driven by the pool's worker thread; all members run
sequentially on it):

1. **collect** — each member statement executes normally under the
   round's collect scope.  When its fused aggregate reaches the device
   dispatch boundary with a params-compiled dev mask AND the program
   already warm (ops/kernels.py fused entries, ``batchable=True`` call
   sites), it *parks*: the round captures ``(program key, cached
   program, non-param args, this member's params)`` and the statement
   aborts with :class:`Parked` (invisible to observability — the
   session skips the obs fan-out for parked attempts).  Members whose
   statements never reach a batchable dispatch (host paths, cold
   programs, non-SELECTs) simply COMPLETE during collect: transparent
   solo fallback.
2. **dispatch** — the round pushes every parked member's ParamTable
   through the captured compiled program back-to-back (one device
   round, zero host work in between, zero compiles by construction).
3. **replay** — each parked member re-executes; at the same boundary it
   *consumes* its precomputed device output (matched by program key +
   the identity of the staged device arrays + its own param bytes) and
   the rest of the statement — unpack, d2h, result assembly,
   observability — runs normally in the member's own scope.  A consume
   miss (the replica rotated between phases, plan re-placed, ...)
   falls through to a plain solo dispatch: batching is an optimization,
   never a correctness dependency.

Family eligibility is learned, not declared: the session's statement
close hook calls :func:`note_family` for statements that executed a
batchable fused dispatch (the ``batchable`` obs marker), and the pool
only forms rounds for digests seen here.

Counter-write discipline: ``STATS`` is written only through this
module's accessors (qlint OB401/OB402 — batching.py is an owning
module).
"""
from __future__ import annotations

import contextvars
import threading
from typing import Dict, List, Optional

#: process-total coalescing counters (exported to /metrics and the
#: serve bench): batches = rounds that dispatched >= 1 parked member,
#: batched_statements = members served from a round dispatch,
#: occupancy_sum / batches = average batch occupancy, parks / replays
#: the protocol legs, fallbacks = replay consume misses (solo re-dispatch)
#: dispatch_s_sum accumulates wall seconds inside round dispatch legs
#: (exported as tinysql_batch_dispatch_seconds_total: the device-side
#: half of a batched member's wait attribution)
STATS = {"batches": 0, "batched_statements": 0, "occupancy_sum": 0,
         "parks": 0, "replays": 0, "fallbacks": 0, "dispatch_s_sum": 0.0}
_stats_mu = threading.Lock()


def _stat_add(key: str, n: int = 1) -> None:
    with _stats_mu:
        STATS[key] = STATS.get(key, 0) + n


def stats_snapshot() -> Dict[str, int]:
    with _stats_mu:
        return dict(STATS)


def reset_stats() -> None:
    """Tests only."""
    with _stats_mu:
        for k in STATS:
            STATS[k] = 0


class Parked(Exception):
    """Control-flow signal of the collect leg: the statement reached a
    batchable warm dispatch and its params were captured.  Never
    surfaces to clients — only the pool's batch driver catches it, and
    the session skips the observability fan-out for parked attempts."""


class _ParkedDispatch:
    __slots__ = ("key", "fn", "args", "arg_ids", "params_key", "params",
                 "out")

    def __init__(self, key, fn, args, params):
        self.key = key
        self.fn = fn
        self.args = args            # positional device args WITHOUT params
        self.arg_ids = _leaf_ids(args)
        self.params = params        # the member's (pi, pf) host vectors
        self.params_key = _params_key(params)
        self.out = None


def _params_key(params) -> bytes:
    pi, pf = params
    return bytes(memoryview(pi).cast("B")) + b"|" + \
        bytes(memoryview(pf).cast("B"))


def _leaf_ids(x) -> tuple:
    """Structural identity of a dispatch's non-param arguments: the
    executor rebuilds its ``dev_cols`` list (and the (values, null)
    tuples in it) per execution, but the LEAF device arrays are
    replica-memoized — the same objects across a family's queries until
    a write invalidates the replica.  Matching on leaf ids is exactly
    the guard batching needs: a replica rotation between the collect and
    replay legs changes the leaves, the consume misses, and the member
    falls back to a solo dispatch over the fresh data."""
    if x is None:
        return ("~",)
    if isinstance(x, (list, tuple)):
        out = ["("]
        for v in x:
            out.extend(_leaf_ids(v))
        out.append(")")
        return tuple(out)
    return (id(x),)


class BatchRound:
    """One coalesced group's shared state across collect/dispatch/replay.
    Used from the single pool worker thread driving the group (members
    run sequentially), so no internal locking is needed beyond the
    global counters."""

    def __init__(self):
        self.collecting = False
        self.replaying = False
        self._parked: List[_ParkedDispatch] = []
        #: (key, arg_ids, params_key) -> [device outputs]: a LIST because
        #: concurrent clients legitimately submit IDENTICAL statements —
        #: each member consumes one stored output
        self._results: Dict[tuple, list] = {}

    # ---- collect ---------------------------------------------------------
    def park(self, key, fn, args, params) -> None:
        """Capture one member's dispatch and abort its collect execution
        (raises :class:`Parked`)."""
        self._parked.append(_ParkedDispatch(key, fn, args, params))
        _stat_add("parks")
        raise Parked()

    @property
    def parked_count(self) -> int:
        return len(self._parked)

    # ---- dispatch --------------------------------------------------------
    def dispatch(self) -> int:
        """Run every parked ParamTable through its captured compiled
        program back-to-back; returns the round's occupancy (parked
        member count).  Zero compiles by construction — park only
        happens on progcache-warm programs.  A member whose dispatch
        raises (device loss, injected fault) simply has no stored
        result: its replay consume misses and the solo re-dispatch
        surfaces the error through the statement's own degradation
        path."""
        import time as _time
        from . import kernels
        t0 = _time.perf_counter()
        occ = 0
        for p in self._parked:
            try:
                p.out = p.fn(*p.args, kernels._params_dev(p.params))
            except Exception:
                continue
            self._results.setdefault(
                (p.key, p.arg_ids, p.params_key), []).append(p.out)
            occ += 1
        if occ:
            _stat_add("batches")
            _stat_add("batched_statements", occ)
            _stat_add("occupancy_sum", occ)
            _stat_add("dispatch_s_sum", _time.perf_counter() - t0)
        return occ

    # ---- replay ----------------------------------------------------------
    def consume(self, key, args, params):
        """The replay-side lookup: this member's precomputed device
        output, or None when the capture no longer matches (fall back to
        a solo dispatch)."""
        outs = self._results.get(
            (key, _leaf_ids(args), _params_key(params)))
        if outs:
            _stat_add("replays")
            return outs.pop()
        _stat_add("fallbacks")
        return None


_ROUND: contextvars.ContextVar = contextvars.ContextVar(
    "tinysql_batch_round", default=None)


def activate(rnd: Optional[BatchRound]):
    return _ROUND.set(rnd)


def deactivate(token) -> None:
    _ROUND.reset(token)


def current() -> Optional[BatchRound]:
    return _ROUND.get()


# ---- family registry (learned batch eligibility) --------------------------

#: normalized-SQL digests whose statements executed a batchable fused
#: dispatch (dev-mask + params, single-shot path).  Bounded: serving
#: works with O(active digest families).
_FAM_MAX = 512
_fam_mu = threading.Lock()
_FAMILIES: Dict[str, int] = {}


def note_family(sql_digest: str) -> None:
    """Mark a digest family batchable (called from the session statement
    close hook for statements that recorded the ``batchable`` marker)."""
    if not sql_digest:
        return
    with _fam_mu:
        if len(_FAMILIES) >= _FAM_MAX and sql_digest not in _FAMILIES:
            _FAMILIES.pop(next(iter(_FAMILIES)))
        _FAMILIES[sql_digest] = _FAMILIES.get(sql_digest, 0) + 1


def family_batchable(sql_digest: str) -> bool:
    with _fam_mu:
        return sql_digest in _FAMILIES


def have_families() -> bool:
    """Cheap pre-check so the pool skips per-statement SQL
    normalization until at least one batchable family exists."""
    with _fam_mu:
        return bool(_FAMILIES)


def reset_families() -> None:
    """Tests only."""
    with _fam_mu:
        _FAMILIES.clear()
