"""Expression tree -> JAX: the device-side vectorized evaluator.

The TPU counterpart of the reference's VecEval* builtins
(expression/builtin_*_vec.go): each numeric expression tree lowers to a
jittable function over (values, null-mask) device-array pairs with MySQL
3-valued null semantics.  XLA fuses the whole tree into a handful of
elementwise kernels — the TPU-first replacement for the reference's
per-builtin Go loops (SURVEY §2.5 note).

Only INT/REAL expressions lower; the planner's device enforcer
(planner/device.py) keeps strings on the CPU tier.
"""
from __future__ import annotations

from functools import partial
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from ..expression import Column, Constant, Expression, ScalarFunction
from ..mytypes import EvalType

# lazy jax import so CPU-only paths never pay for it
_jnp = None


def jnp():
    global _jnp
    if _jnp is None:
        from . import kernels
        _jnp = kernels.jnp()  # shares x64 + backend-liveness handling
    return _jnp


JITTABLE_FUNCS = {
    "+", "-", "*", "/", "div", "%", "unaryminus", "abs",
    "=", "!=", "<", "<=", ">", ">=", "<=>",
    "and", "or", "xor", "not", "isnull", "istrue", "isfalse",
    "if", "ifnull", "case", "in", "cast_int", "cast_real",
}


def is_jittable(e: Expression) -> bool:
    """Can this tree run on device?  (numeric-only, known functions)"""
    if e.eval_type is EvalType.STRING:
        return False
    if isinstance(e, Column):
        return e.eval_type is not EvalType.STRING
    if isinstance(e, Constant):
        return not isinstance(e.value, str)
    if isinstance(e, ScalarFunction):
        if e.name not in JITTABLE_FUNCS:
            return False
        if (e.name in ("div", "%") and len(e.args) == 2
                and all(a.eval_type is EvalType.INT for a in e.args)):
            u = [getattr(a.ret_type, "is_unsigned", False) for a in e.args]
            if u[0] != u[1]:  # mixed-signedness int div/mod: CPU tier only
                return False
        return all(is_jittable(a) for a in e.args)
    return False


VV = Tuple[object, object]  # (jnp values, jnp bool null-mask)


def _truthy(a: VV):
    v, nl = a
    return v != 0, nl


def compile_expr(e: Expression) -> Callable[[Sequence[VV]], VV]:
    """Build a python closure evaluating `e` over device columns; the result
    is jit-traceable (call it inside jax.jit)."""
    j = jnp()
    if isinstance(e, Column):
        idx = e.index

        def col_fn(cols):
            return cols[idx]
        return col_fn
    if isinstance(e, Constant):
        val = e.value
        is_null = val is None
        if e.eval_type is EvalType.INT:
            from ..mytypes import wrap_i64
            cval = wrap_i64(int(val)) if val is not None else 0
            dt = j.int64
        else:
            cval = float(val) if val is not None else 0.0
            dt = j.float64

        def const_fn(cols):
            # broadcast length: first populated slot (sparse device-column
            # lists hold None for untouched columns; string slots may carry
            # only their null mask)
            n = _broadcast_len(cols)
            return (j.full((n,), cval, dtype=dt),  # qlint: disable=TS107 -- compile_expr IS the legacy literal-baked lowering; cached_compile_expr keys it by constant VALUE (stable_key), so the bake is correct here.  New fused/executor paths use compile_expr_params.
                    j.full((n,), is_null, dtype=bool))  # qlint: disable=TS107 -- NULL-ness is structural even in the params path; see compile_expr_params
        return const_fn
    assert isinstance(e, ScalarFunction), e
    args = [compile_expr(a) for a in e.args]
    arg_types = [a.eval_type for a in e.args]
    arg_uns = [a.eval_type is EvalType.INT
               and getattr(a.ret_type, "is_unsigned", False) for a in e.args]
    name = e.name
    ret_int = e.eval_type is EvalType.INT

    def fn(cols):
        vals = [a(cols) for a in args]
        return _apply(name, vals, arg_types, ret_int, arg_uns)
    return fn


def _to_real_u(v, unsigned: bool):
    """int64 -> float64 honoring the wrapped-uint64 representation."""
    j = jnp()
    r = v.astype(j.float64)
    if unsigned and v.dtype == j.int64:
        r = j.where(v < 0, r + 2.0**64, r)
    return r


def _int_div_j(a, safe_b, uns):
    """Truncating int64 div/mod on device.  Both-unsigned runs in uint64
    via bitcast; mixed signedness is rejected by is_jittable (CPU tier)."""
    j = jnp()
    from jax import lax
    if uns[0] and uns[1]:
        ua = lax.bitcast_convert_type(a, j.uint64)
        ub = lax.bitcast_convert_type(safe_b, j.uint64)
        q = ua // ub
        r = ua - ub * q
        return (lax.bitcast_convert_type(q, j.int64),
                lax.bitcast_convert_type(r, j.int64))
    q = j.abs(a) // j.abs(safe_b)
    q = j.where((a < 0) != (safe_b < 0), -q, q)
    return q, a - safe_b * q


def _int_lt_eq_j(a, ua: bool, b, ub: bool):
    """(lt, eq) for int64 device arrays with per-side unsignedness —
    mirrors expression/builtins._int_lt_eq."""
    j = jnp()
    if ua == ub:
        if ua:
            a = a ^ j.int64(-2**63)
            b = b ^ j.int64(-2**63)
        return a < b, a == b
    if ua:
        ok = (a >= 0) & (b >= 0)
        return ok & (a < b), ok & (a == b)
    ok = (a >= 0) & (b >= 0)
    return (a < 0) | (b < 0) | (a < b), ok & (a == b)


def _apply(name: str, vals: List[VV], arg_types, ret_int: bool,
           arg_uns=None) -> VV:
    j = jnp()
    arg_uns = arg_uns or [False] * len(vals)
    if name in ("+", "-", "*", "/", "div", "%"):
        (a, na), (b, nb) = vals
        null = na | nb
        int_math = (arg_types[0] is EvalType.INT
                    and arg_types[1] is EvalType.INT and name != "/")
        if not int_math:
            a = _to_real_u(a, arg_uns[0])
            b = _to_real_u(b, arg_uns[1])
        if name == "+":
            return a + b, null  # int: wrap-correct mod 2^64 any signedness
        if name == "-":
            return a - b, null
        if name == "*":
            return a * b, null
        safe_b = j.where(b == 0, 1, b)
        null = null | (b == 0)
        if name == "/":
            return a / safe_b, null
        if name == "div":
            if int_math:
                q = _int_div_j(a, safe_b, arg_uns)[0]
            else:
                q = j.trunc(a / safe_b).astype(j.int64)
            return q, null
        # %
        if int_math:
            return _int_div_j(a, safe_b, arg_uns)[1], null
        return j.where(b == 0, 0.0, j.where(
            j.sign(a) >= 0, j.abs(a) % j.abs(safe_b),
            -(j.abs(a) % j.abs(safe_b)))), null
    if name == "unaryminus":
        v, nl = vals[0]
        return -v, nl
    if name == "abs":
        v, nl = vals[0]
        return j.abs(v), nl
    if name in ("=", "!=", "<", "<=", ">", ">=", "<=>"):
        (a, na), (b, nb) = vals
        if arg_types[0] is not arg_types[1]:
            a = _to_real_u(a, arg_uns[0])
            b = _to_real_u(b, arg_uns[1])
            r = {"=": a == b, "<=>": a == b, "!=": a != b, "<": a < b,
                 "<=": a <= b, ">": a > b, ">=": a >= b}[name]
        elif (arg_types[0] is EvalType.INT
              and (arg_uns[0] or arg_uns[1])):
            lt, eq = _int_lt_eq_j(a, arg_uns[0], b, arg_uns[1])
            base = "=" if name == "<=>" else name
            r = {"=": eq, "!=": ~eq, "<": lt, "<=": lt | eq,
                 ">": ~(lt | eq), ">=": ~lt}[base]
        else:
            r = {"=": a == b, "<=>": a == b, "!=": a != b, "<": a < b,
                 "<=": a <= b, ">": a > b, ">=": a >= b}[name]
        if name == "<=>":
            v = j.where(na | nb, na & nb, r)
            return v.astype(j.int64), j.zeros_like(na)
        return r.astype(j.int64), na | nb
    if name == "and":
        (a, na), (b, nb) = (_truthy(v) for v in vals)
        fa, fb = (~a) & ~na, (~b) & ~nb
        v = (a & b) & ~(na | nb)
        null = (na | nb) & ~(fa | fb)
        return v.astype(j.int64), null
    if name == "or":
        (a, na), (b, nb) = (_truthy(v) for v in vals)
        ta, tb = a & ~na, b & ~nb
        v = ta | tb
        null = (na | nb) & ~v
        return v.astype(j.int64), null
    if name == "xor":
        (a, na), (b, nb) = (_truthy(v) for v in vals)
        return (a != b).astype(j.int64), na | nb
    if name == "not":
        a, na = _truthy(vals[0])
        return (~a).astype(j.int64), na
    if name == "isnull":
        v, nl = vals[0]
        return nl.astype(j.int64), j.zeros_like(nl)
    if name in ("istrue", "isfalse"):
        a, na = _truthy(vals[0])
        want = name == "istrue"
        v = j.where(na, False, a == want)
        return v.astype(j.int64), j.zeros_like(na)
    if name == "if":
        c, nc = _truthy(vals[0])
        take = c & ~nc
        (a, na), (b, nb) = vals[1], vals[2]
        return j.where(take, a, b), j.where(take, na, nb)
    if name == "ifnull":
        (a, na), (b, nb) = vals
        return j.where(na, b, a), na & nb
    if name == "case":
        has_else = len(vals) % 2 == 1
        pairs = len(vals) // 2
        proto = vals[1][0]
        v = j.zeros_like(proto)
        null = j.ones(proto.shape, dtype=bool)
        decided = j.zeros(proto.shape, dtype=bool)
        for p in range(pairs):
            c, nc = _truthy(vals[2 * p])
            take = c & ~nc & ~decided
            rv, rn = vals[2 * p + 1]
            v = j.where(take, rv, v)
            null = j.where(take, rn, null)
            decided = decided | take
        if has_else:
            rv, rn = vals[-1]
            v = j.where(decided, v, rv)
            null = j.where(decided, null, rn)
        return v, null
    if name == "in":
        x, xn = vals[0]
        hit = j.zeros(x.shape, dtype=bool)
        saw_null = j.zeros(x.shape, dtype=bool)
        for k, (item, inull) in enumerate(vals[1:], start=1):
            if x.dtype != item.dtype:
                xi = _to_real_u(x, arg_uns[0])
                it = _to_real_u(item, arg_uns[k])
                eq = xi == it
            elif x.dtype == j.int64 and (arg_uns[0] or arg_uns[k]):
                eq = _int_lt_eq_j(x, arg_uns[0], item, arg_uns[k])[1]
            else:
                eq = x == item
            hit = hit | (eq & ~inull & ~xn)
            saw_null = saw_null | inull
        return hit.astype(j.int64), ~hit & (saw_null | xn)
    if name == "cast_int":
        v, nl = vals[0]
        if v.dtype == j.int64:
            return v, nl
        r = j.where(v >= 0, j.floor(v + 0.5), -j.floor(-v + 0.5))
        r = j.clip(r, -2.0**63, 2.0**63 - 1)
        return r.astype(j.int64), nl
    if name == "cast_real":
        v, nl = vals[0]
        return _to_real_u(v, arg_uns[0]), nl
    raise ValueError(f"not jittable: {name}")


#: live ParamTables, weakly held — the HBM census claims any device
#: buffers a parameter staging path pins (today's slots are host python
#: lists and uploads are per-dispatch transients: the category reads 0)
import weakref  # noqa: E402
_LIVE_PARAM_TABLES: "weakref.WeakSet[ParamTable]" = weakref.WeakSet()


def _census_param_tables():
    for pt in list(_LIVE_PARAM_TABLES):
        yield [pt.i64, pt.f64]


from ..obs import memprof as _memprof  # noqa: E402  (cycle-free: memprof
#                                        imports no ops module at top level)
_memprof.register_census_walker("paramtable", _census_param_tables)


class ParamTable:
    """Per-query runtime parameters for compiled device programs.
    Constants lower to slot reads instead of baked literals, so a query
    that differs only in its constants (date bounds, LIMIT thresholds)
    reuses the SAME compiled XLA program.  compile_expr_params assigns
    slots in deterministic traversal order and fills the values as it
    walks; per query the caller re-runs it on the identically-shaped
    expression (closure rebuild is cheap; the jit program is cached by
    the shape key)."""

    def __init__(self):
        self.i64: list = []
        self.f64: list = []
        _LIVE_PARAM_TABLES.add(self)

    def add_int(self, v) -> int:
        from ..mytypes import wrap_i64
        self.i64.append(0 if v is None else wrap_i64(int(v)))
        return len(self.i64) - 1

    def add_real(self, v) -> int:
        self.f64.append(0.0 if v is None else float(v))
        return len(self.f64) - 1

    def arrays(self):
        return (np.asarray(self.i64, dtype=np.int64),
                np.asarray(self.f64, dtype=np.float64))

    @staticmethod
    def stack(tables, b: Optional[int] = None):
        """Stack N members' runtime-constant vectors on a LEADING batch
        axis: ``[(int64[Ni], float64[Nf]), ...] -> (int64[B, Ni],
        float64[B, Nf])`` — the params operand of a ``jax.vmap``-batched
        fused kernel (ops/kernels.stacked_variant), where the data
        columns stay shared and only the per-member constants carry the
        batch dimension.  ``tables`` holds ParamTables or their
        ``arrays()`` pairs; ``b`` pads the batch axis up to an occupancy
        bucket (rows past the member count repeat member 0 — inert: the
        dispatcher slices only real member rows off axis 0).  Raises
        ``ValueError`` on a slot-layout mismatch (members compiled from
        different expression shapes) — the stacked dispatch falls back
        to the legacy back-to-back leg on it."""
        pairs = [t.arrays() if isinstance(t, ParamTable) else t
                 for t in tables]
        if not pairs:
            raise ValueError("ParamTable.stack: no members")
        ni, nf = len(pairs[0][0]), len(pairs[0][1])
        for pi, pf in pairs[1:]:
            if len(pi) != ni or len(pf) != nf:
                raise ValueError(
                    f"ParamTable.stack: slot-layout mismatch "
                    f"({len(pi)}i/{len(pf)}f vs {ni}i/{nf}f)")
        b = len(pairs) if b is None else int(b)
        if b < len(pairs):
            raise ValueError(
                f"ParamTable.stack: bucket {b} < occupancy {len(pairs)}")
        idx = list(range(len(pairs))) + [0] * (b - len(pairs))
        return (np.stack([np.asarray(pairs[i][0], dtype=np.int64)
                          for i in idx]),
                np.stack([np.asarray(pairs[i][1], dtype=np.float64)
                          for i in idx]))


def compile_expr_params(e: Expression, pt: ParamTable) \
        -> Callable[[Sequence[VV], tuple], VV]:
    """Like compile_expr, but closures take (cols, (params_i64,
    params_f64)) and Constants read their value from a param slot.
    NULL-ness of a constant stays structural (baked)."""
    j = jnp()
    if isinstance(e, Column):
        idx = e.index

        def col_fn(cols, params):
            return cols[idx]
        return col_fn
    if isinstance(e, Constant):
        is_null = e.value is None
        if e.eval_type is EvalType.INT:
            slot = pt.add_int(e.value)

            def const_fn(cols, params, slot=slot, is_null=is_null):
                n = _broadcast_len(cols)
                v = j.full((n,), 1, dtype=j.int64) * params[0][slot]
                return v, j.full((n,), is_null, dtype=bool)
        else:
            slot = pt.add_real(e.value)

            def const_fn(cols, params, slot=slot, is_null=is_null):
                n = _broadcast_len(cols)
                v = j.full((n,), 1.0, dtype=j.float64) * params[1][slot]
                return v, j.full((n,), is_null, dtype=bool)
        return const_fn
    assert isinstance(e, ScalarFunction), e
    args = [compile_expr_params(a, pt) for a in e.args]
    arg_types = [a.eval_type for a in e.args]
    arg_uns = [a.eval_type is EvalType.INT
               and getattr(a.ret_type, "is_unsigned", False) for a in e.args]
    name = e.name
    ret_int = e.eval_type is EvalType.INT

    def fn(cols, params):
        vals = [a(cols, params) for a in args]
        return _apply(name, vals, arg_types, ret_int, arg_uns)
    return fn


def _broadcast_len(cols) -> int:
    for c in cols:
        if c is None:
            continue
        arr = c[0] if c[0] is not None else c[1]
        if arr is not None:
            return arr.shape[0]
    return 1


def stable_shape_key(e: Expression) -> str:
    """stable_key with constant VALUES erased — the program-cache key for
    the params-compiled variant (same shape + types = same program)."""
    if isinstance(e, Column):
        return f"@{e.index}:{e.ret_type.tp}:{e.ret_type.flag & 32}"
    if isinstance(e, Constant):
        return f"c?({'N' if e.value is None else 'v'}:{e.ret_type.tp})"
    if isinstance(e, ScalarFunction):
        return f"{e.name}({','.join(stable_shape_key(a) for a in e.args)})"
    return repr(e)


def stable_key(e: Expression) -> str:
    """Cache key independent of per-query Column unique ids: identifies an
    expression by schema OFFSETS + types, so the same query shape reuses
    one compiled program across sessions."""
    if isinstance(e, Column):
        return f"@{e.index}:{e.ret_type.tp}:{e.ret_type.flag & 32}"
    if isinstance(e, Constant):
        return f"c({e.value!r}:{e.ret_type.tp})"
    if isinstance(e, ScalarFunction):
        return f"{e.name}({','.join(stable_key(a) for a in e.args)})"
    return repr(e)


def cached_compile_expr(e: Expression) -> Callable[[Sequence[VV]], VV]:
    """compile_expr memoized through the shared program registry
    (ops/progcache): the closure build is pure over the expression SHAPE
    — stable_key pins schema offsets, types, the unsigned flag, and
    constant values — so identical trees across queries share ONE
    closure, and the kernels that embed it key their jit programs off
    the same identity."""
    from . import progcache
    key = ("exprfn", stable_key(e), str(e.eval_type))
    return progcache.get(key, lambda: compile_expr(e))


