"""Failpoint fault-injection registry (reference: pingcap/failpoint —
`fail.Enable("github.com/pingcap/tidb/store/tikv/rpcServerBusy", ...)`,
SURVEY §5.3).

Every resilience seam in the engine declares a NAMED failpoint (the
catalogue lives in ``fail/points.py``; qlint FP502 rejects inject sites
whose name is not registered there).  Disarmed failpoints are zero-cost:
``inject``/``eval`` check one module-level dict for emptiness and
return — no lock, no allocation — so production paths pay a dict
truthiness test per seam.

Arming, three ways:

- programmatic (tests): ``with fail.armed("commitError", exc=IOError()):``
- environment: ``TINYSQL_FAILPOINTS="copTaskError=2*error(boom);
  devpipeStageError=sleep(0.01)"`` parsed on first use;
- sysvar: ``SET tidb_failpoints = 'kernelDispatchError=error(lost)'``
  (session layer calls :func:`configure`; empty string disarms all).

Actions (the pingcap/failpoint verbs): ``error(msg)`` raises
:class:`Injected`, ``sleep(seconds)`` delays, ``panic`` raises the
:class:`Panic` BaseException (models a process crash — ordinary
``except Exception`` recovery must NOT swallow it), ``return(value)``
makes ``eval`` yield the value.  An optional ``N*`` prefix fires the
action N times then disarms.  Programmatic arming can attach an
arbitrary exception instance instead (``exc=RegionError(...)``) so kv
retry ladders see their own typed errors.

Every fire bumps a per-name hit counter, exported to /metrics as
``tinysql_failpoint_hits_total{name=...}`` and fanned into the active
per-query observability scope (obs/context.py) as ``failpoint_hits``.
"""
from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Any, Dict, Optional

__all__ = [
    "Injected", "Panic", "register", "catalogue", "arm", "disarm",
    "disarm_all", "armed", "inject", "eval_point", "is_armed", "hits",
    "reset_hits", "configure", "parse_spec",
]


class Injected(RuntimeError):
    """The generic typed error an ``error(...)`` action raises."""

    def __init__(self, name: str, msg: str = ""):
        super().__init__(f"failpoint {name} injected" + (f": {msg}" if msg
                                                         else ""))
        self.failpoint = name


class Panic(BaseException):
    """Models a process crash (pingcap/failpoint's panic action): rides
    BaseException so recovery paths that catch ``Exception`` do not
    accidentally 'survive' a crash they are supposed to be killed by."""

    def __init__(self, name: str):
        super().__init__(f"failpoint {name} panic")
        self.failpoint = name


class _Action:
    __slots__ = ("kind", "value", "exc", "times")

    def __init__(self, kind: str, value: Any = True,
                 exc: Optional[BaseException] = None, times: int = -1):
        self.kind = kind          # error | sleep | panic | return
        self.value = value
        self.exc = exc
        self.times = times        # remaining fires; -1 = unlimited


_mu = threading.Lock()
#: name -> registered description (THE catalogue; points.py populates it)
_CATALOG: Dict[str, str] = {}
#: armed points only — emptiness is the disarmed fast path
_ACTIVE: Dict[str, _Action] = {}
#: name -> total fires since process start (or reset_hits)
_HITS: Dict[str, int] = {}
_ENV_LOADED = False


def register(name: str, description: str = "") -> str:
    """Declare a failpoint.  Arming an unregistered name is an error —
    the catalogue is what the chaos suite enumerates to prove every
    seam degrades cleanly."""
    with _mu:
        _CATALOG[name] = description
    return name


def catalogue() -> Dict[str, str]:
    _load_env_once()
    with _mu:
        return dict(_CATALOG)


def hits() -> Dict[str, int]:
    with _mu:
        return dict(_HITS)


def reset_hits() -> None:
    with _mu:
        _HITS.clear()


def arm(name: str, value: Any = True, exc: Optional[BaseException] = None,
        sleep: Optional[float] = None, panic: bool = False,
        times: int = -1) -> None:
    """Arm ``name``.  Precedence: exc > panic > sleep > return-value."""
    if name not in _CATALOG:
        raise ValueError(f"unregistered failpoint {name!r} — declare it in "
                         "tinysql_tpu/fail/points.py")
    if exc is not None:
        act = _Action("error", exc=exc, times=times)
    elif panic:
        act = _Action("panic", times=times)
    elif sleep is not None:
        act = _Action("sleep", value=float(sleep), times=times)
    else:
        act = _Action("return", value=value, times=times)
    with _mu:
        _ACTIVE[name] = act


def disarm(name: str) -> None:
    with _mu:
        _ACTIVE.pop(name, None)


def disarm_all() -> None:
    with _mu:
        _ACTIVE.clear()


@contextlib.contextmanager
def armed(name: str, value: Any = True,
          exc: Optional[BaseException] = None,
          sleep: Optional[float] = None, panic: bool = False,
          times: int = -1):
    """Scoped arming.  A previously armed action for the same name
    (env/sysvar arming, an outer ``armed`` block) is RESTORED on exit,
    not clobbered — the with-block is an override, not a disarm."""
    with _mu:
        prev = _ACTIVE.get(name)
    arm(name, value=value, exc=exc, sleep=sleep, panic=panic, times=times)
    try:
        yield
    finally:
        with _mu:
            if prev is not None:
                _ACTIVE[name] = prev
            else:
                _ACTIVE.pop(name, None)


def _consume(name: str) -> Optional[_Action]:
    with _mu:
        act = _ACTIVE.get(name)
        if act is None:
            return None
        if act.times == 0:
            _ACTIVE.pop(name, None)
            return None
        if act.times > 0:
            act.times -= 1
            if act.times == 0:
                _ACTIVE.pop(name, None)
        _HITS[name] = _HITS.get(name, 0) + 1
    # per-query attribution (no-op without an active statement scope)
    try:
        from ..obs import context as _obs
        _obs.record("failpoint_hits", 1)
    except Exception:
        pass
    return act


def eval_point(name: str) -> Any:
    """Fire ``name`` if armed: raises for error/panic actions, sleeps for
    sleep actions, returns the armed value for return actions; None when
    disarmed (the zero-cost path)."""
    if not _ACTIVE and _ENV_LOADED:
        return None
    _load_env_once()
    if not _ACTIVE:
        return None
    act = _consume(name)
    if act is None:
        return None
    if act.kind == "error":
        if act.exc is None:
            raise Injected(name)
        # fresh instance per fire: re-raising the ONE stored exception
        # would grow its shared __traceback__ on every retry (pinning
        # each frame's locals) and let concurrent pool workers mutate
        # it under each other
        raise _fresh_exc(act.exc)
    if act.kind == "panic":
        raise Panic(name)
    if act.kind == "sleep":
        time.sleep(act.value)  # qlint: disable=FP501 -- the sleep ACTION is the injected fault itself, not a retry path
        return True
    return act.value


def is_armed(name: str) -> bool:
    """Side-effect-free probe: is ``name`` currently armed with fires
    remaining?  Unlike :func:`eval_point` it consumes nothing from a
    counted (``N*``) arming, bumps no hit counters, and never
    raises/sleeps — for decision probes that must not perturb the
    arming they observe."""
    if not _ACTIVE and _ENV_LOADED:
        return False
    _load_env_once()
    with _mu:
        act = _ACTIVE.get(name)
        return act is not None and act.times != 0


def _fresh_exc(exc: BaseException) -> BaseException:
    """A per-fire copy of an armed exception (attributes preserved,
    traceback cleared); falls back to the original when uncopyable."""
    import copy
    try:
        new = copy.copy(exc)
        new.__traceback__ = None
        return new
    except Exception:
        return exc


def inject(name: str) -> None:
    """Statement-position form of :func:`eval_point` (discards the
    return value)."""
    eval_point(name)


# ---- spec strings (env var / sysvar) --------------------------------------

def parse_spec(spec: str) -> Dict[str, _Action]:
    """``name=action`` terms separated by ``;``.  Actions:
    ``error(msg)`` | ``sleep(seconds)`` | ``panic`` | ``return(value)``,
    optionally prefixed ``N*`` to fire N times.  Values for return() are
    parsed as int, then float, else kept as string."""
    out: Dict[str, _Action] = {}
    for term in spec.split(";"):
        term = term.strip()
        if not term:
            continue
        name, _, action = term.partition("=")
        name = name.strip()
        action = action.strip()
        if not name or not action:
            raise ValueError(f"bad failpoint term {term!r} "
                             "(want name=action)")
        if name not in _CATALOG:
            raise ValueError(f"unregistered failpoint {name!r}")
        times = -1
        if "*" in action.split("(")[0]:
            n, _, action = action.partition("*")
            times = int(n.strip())
            action = action.strip()
        verb, _, rest = action.partition("(")
        arg = rest[:-1] if rest.endswith(")") else rest
        verb = verb.strip().lower()
        if verb == "error":
            out[name] = _Action("error", exc=Injected(name, arg),
                                times=times)
        elif verb == "sleep":
            out[name] = _Action("sleep", value=float(arg), times=times)
        elif verb == "panic":
            out[name] = _Action("panic", times=times)
        elif verb == "return":
            val: Any = True
            if arg:
                for conv in (int, float):
                    try:
                        val = conv(arg)
                        break
                    except ValueError:
                        val = arg
            out[name] = _Action("return", value=val, times=times)
        else:
            raise ValueError(f"unknown failpoint action {verb!r}")
    return out


def configure(spec: str) -> None:
    """Replace ALL armed points with the parsed ``spec`` (the sysvar
    entry point: ``SET tidb_failpoints = '...'``; empty disarms all).
    The env spec is consumed FIRST so a later lazy load cannot silently
    resurrect points this call disarmed (or merge on top of it)."""
    global _ENV_LOADED
    _load_env_once()
    _ENV_LOADED = True
    acts = parse_spec(spec or "")
    with _mu:
        _ACTIVE.clear()
        _ACTIVE.update(acts)


def _load_env_once() -> None:
    """TINYSQL_FAILPOINTS env activation, applied once per process on the
    first catalogue/eval touch (after points.py registered the names)."""
    global _ENV_LOADED
    if _ENV_LOADED:
        return
    _ENV_LOADED = True
    spec = os.environ.get("TINYSQL_FAILPOINTS", "")
    if not spec:
        return
    try:
        acts = parse_spec(spec)
    except ValueError:
        import logging
        logging.getLogger("tinysql_tpu").warning(
            "ignoring malformed TINYSQL_FAILPOINTS=%r", spec, exc_info=True)
        return
    with _mu:
        for k, v in acts.items():
            _ACTIVE.setdefault(k, v)


# the catalogue must exist before any inject site fires
from . import points  # noqa: E402,F401  (registration side effects)
