"""THE failpoint catalogue: every named inject site in the engine.

qlint FP502 statically checks that each ``failpoint.inject("...")`` /
``eval`` site names a point registered here, and the chaos suite
(tests/test_chaos.py) asserts it has a driver for EVERY name below — so
a new failpoint cannot be added without both a registration and a chaos
proof that arming it degrades cleanly.
"""
from __future__ import annotations

from . import register

# ---- kv / 2PC (store/tikv lineage) ----------------------------------------
RPC_SERVER_BUSY = register(
    "rpcServerBusy",
    "RPC region check raises RegionError(server_busy) — drives the "
    "BO_REGION_MISS retry ladder (kv/rpc.py)")
PREWRITE_ERROR = register(
    "prewriteError",
    "kv_prewrite raises before touching MVCC — 2PC must clean up, no "
    "locks left (kv/rpc.py)")
COMMIT_ERROR = register(
    "commitError",
    "kv_commit raises for every batch (kv/rpc.py)")
COMMIT_PRIMARY_ERROR = register(
    "commitPrimaryError",
    "commit RPC on the PRIMARY batch fails — outcome undetermined, "
    "UndeterminedError must surface (kv/txn.py)")
COMMIT_SECONDARY_ERROR = register(
    "commitSecondaryError",
    "commit RPC on a secondary batch fails — txn stays durable, later "
    "readers resolve the leftover locks (kv/txn.py)")
BEFORE_COMMIT = register(
    "beforeCommit",
    "between prewrite and commit_keys — a panic here models the classic "
    "Percolator crashed-committer window (kv/txn.py)")

# ---- durability: WAL + checkpoint (kv/wal.py) ------------------------------
WAL_APPEND_ERROR = register(
    "walAppendError",
    "WAL record append fails BEFORE any bytes are written — the "
    "journaled mutation is not applied, a typed WalError surfaces, the "
    "store never diverges ahead of its log (kv/wal.py append)")
WAL_FSYNC_ERROR = register(
    "walFsyncError",
    "the wal fsync syscall fails — under strict policy the ack-bearing "
    "commit surfaces a typed error (the bytes may still be in the page "
    "cache: outcome undetermined, exactly the primary-commit contract); "
    "counted as fsync_errors (kv/wal.py _fsync_locked)")
WAL_TORN_TAIL = register(
    "walTornTail",
    "the next record is deliberately half-written — the crash-boundary "
    "lever: recovery must truncate at the first bad checksum and the "
    "live log poisons itself (further appends raise WalError) "
    "(kv/wal.py append)")
CHECKPOINT_ERROR = register(
    "checkpointError",
    "a checkpoint attempt fails (or stalls, with sleep=) before the "
    "atomic rename — counted, never fatal: the previous checkpoint + "
    "unrotated log remain the recovery source; armed during recovery it "
    "is the crash-during-recovery lever (kv/wal.py checkpoint)")

# ---- distsql coprocessor ---------------------------------------------------
COP_TASK_ERROR = register(
    "copTaskError",
    "start of every region task attempt in the scatter-gather pool — "
    "RegionError retries through re-split, generic errors surface typed "
    "(distsql/client.py)")

# ---- device tier -----------------------------------------------------------
DEVPIPE_STAGE_ERROR = register(
    "devpipeStageError",
    "block-staging function of the async pipeline — the producer's "
    "error contract must deliver it to the consumer in order "
    "(executor/devpipe.py BlockPipeline)")
KERNEL_DISPATCH_ERROR = register(
    "kernelDispatchError",
    "every compiled-program dispatch (ops/kernels.py counted_jit) — "
    "armed with degrade.DeviceLost it models a TPU dying mid-statement")
KERNEL_D2H_ERROR = register(
    "kernelD2HError",
    "every device->host materialization (ops/kernels.py d2h/d2h_many)")
BACKEND_PROBE_FAIL = register(
    "backendProbeFail",
    "backend liveness probe reports the device backend unreachable — "
    "engine must pin jax_platforms=cpu instead of hanging "
    "(ops/kernels.py ensure_live_backend)")

# ---- DDL -------------------------------------------------------------------
DDL_STEP_ERROR = register(
    "ddlStepError",
    "one DDL worker state-machine step fails — the job retries/rolls "
    "back, the queue never wedges (ddl/worker.py)")
REORG_BATCH_ERROR = register(
    "reorgBatchError",
    "one index-backfill batch fails — reorg resumes from the checkpoint "
    "handle (ddl/worker.py)")

# ---- auto-prewarm ----------------------------------------------------------
PREWARM_COMPILE_ERROR = register(
    "prewarmCompileError",
    "start of one family's warm attempt in the auto-prewarm worker — "
    "the worker must count the error, start the family's cooldown, and "
    "keep serving later candidates and cycles (session/prewarm.py)")

# ---- serving / admission ---------------------------------------------------
ADMISSION_QUEUE_FULL = register(
    "admissionQueueFull",
    "admission gate reports the statement queue full — every pooled "
    "statement sheds with typed MySQL 1041 + retry hint; control "
    "statements and KILL keep working (server/admission.py)")
ADMISSION_DELAY = register(
    "admissionDelay",
    "statement-pool worker stalls (sleep) or fails (error) with an "
    "entry claimed — the queue builds behind it, queued statements stay "
    "KILLable, the accept loop never hangs (server/pool.py)")

# ---- sharded operator tier (ops/shardops.py) -------------------------------
SHARD_EXCHANGE_STALL = register(
    "shardExchangeStall",
    "entry of a partitioned join/semijoin shard exchange (ops/shardops.py)"
    " — armed with sleep= it holds the statement mid-exchange so KILL "
    "must land at the next drain-block boundary with the session healthy "
    "after; armed with exc= the sharded attempt surfaces the error")

# ---- memory-adaptive spilling (ops/spill.py) -------------------------------
SPILL_PARTITION_ERROR = register(
    "spillPartitionError",
    "spill-store partition write fails — the statement surfaces a typed "
    "error, no partition files or resident bytes leak (ops/spill.py "
    "SpillStore.put)")
SPILL_RELOAD_ERROR = register(
    "spillReloadError",
    "spilled-partition reload fails mid-probe/merge — typed error, all "
    "remaining partitions dropped cleanly (ops/spill.py SpillStore.load)")
SPILL_FORCE_ALL = register(
    "spillForceAll",
    "armed with return(1): every spill-capable operator (hash join, "
    "hash agg, sort, topn) runs its partitioned spill path regardless "
    "of tidb_mem_quota_query — the spill==no-spill equivalence and CI "
    "smoke lever (ops/spill.py maybe_context)")

# ---- executor --------------------------------------------------------------
EXEC_SLOW_NEXT = register(
    "execSlowNext",
    "fires once per root drain block — a sleep action makes any "
    "statement controllably long-running (KILL / max_execution_time "
    "tests; executor/executors.py Executor.drain)")

# ---- continuous heap profiler (obs/memprof.py) -----------------------------
MEMPROF_SAMPLE_ERROR = register(
    "memprofSampleError",
    "one heap-profiler sampling tick fails at snapshot time "
    "(obs/memprof.py HeapProfiler.sample_once) — the background sampler "
    "counts the error and keeps ticking, the fold/attribution store "
    "stays consistent, no statement or surface is affected")
